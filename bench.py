"""Headline benchmark: the BASELINE.md north-star config.

Filter a 100k-pod list response against a 10M-relationship graph on one
chip — the reference's prefilter/list hot path (SURVEY.md §3.3:
runLookupResources + filterList) executed as one slot-space reachability
query (`Engine.lookup_resources_mask`). Also reports bulk-check throughput
(reference CheckBulkPermissions path, SURVEY.md §3.2) on stderr.

Prints ONE JSON line on stdout:
    {"metric": ..., "value": p50_ms, "unit": "ms", "vs_baseline": ...}
vs_baseline is the 50 ms BASELINE.json target divided by the measured p50
(>1.0 means the target is beaten).

Usage: python bench.py [--quick]   (--quick: small graph, CPU-friendly)
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Optional

import numpy as np

# BASELINE.md north-star target: <50ms p50 list filter on one v5e chip
BASELINE_TARGET_MS = 50.0

BENCH_SCHEMA = """
use expiration

definition user {}
definition group {
  relation member: user
}
definition namespace {
  relation creator: user
  relation viewer: user | group#member
  permission admin = creator
  permission view = viewer + creator
}
definition pod {
  relation namespace: namespace
  relation creator: user
  relation viewer: user
  permission edit = creator
  permission view = viewer + creator + namespace->view
}
"""


# BENCH_SCHEMA plus conditional grants: the mesh phase's caveated mix
# (ISSUE 15) — a share of the flat pod#viewer grants carry an
# IP-allowlist caveat, evaluated ON the mesh.
MESH_SCHEMA = """
use expiration

caveat ip_allowlist(ip ipaddress, allowed list<ipaddress>) {
  ip in allowed
}

definition user {}
definition group {
  relation member: user | group#member
}
definition namespace {
  relation creator: user
  relation viewer: user | group#member
  permission admin = creator
  permission view = viewer + creator
}
definition pod {
  relation namespace: namespace
  relation creator: user
  relation viewer: user | user with ip_allowlist
  permission edit = creator
  permission view = viewer + creator + namespace->view
}
"""

# the two stored contexts the caveated mix interleaves (two distinct
# (caveat, ctx) instances => an 8-row padded bucket with spare rows for
# incremental instance appends)
MESH_CTXS = ('{"allowed":["10.0.0.0/8","192.168.0.0/16"]}',
             '{"allowed":["10.0.0.0/8"]}')


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def build_engine(n_pods: int, n_users: int, n_ns: int, n_groups: int,
                 n_rels: int, seed: int = 0, cav_share: float = 0.0,
                 schema: str = BENCH_SCHEMA):
    """Synthesize the graph columnar-side (no per-row Python objects).
    ``cav_share`` > 0 marks that fraction of the flat pod#viewer grants
    with the ``ip_allowlist`` caveat (``schema`` must declare it —
    MESH_SCHEMA), alternating the two MESH_CTXS stored contexts."""
    from spicedb_kubeapi_proxy_tpu.engine import Engine
    from spicedb_kubeapi_proxy_tpu.models import parse_schema

    rng = np.random.default_rng(seed)
    pods = np.char.add("ns/p", np.arange(n_pods).astype(str))
    users = np.char.add("u", np.arange(n_users).astype(str))
    groups = np.char.add("g", np.arange(n_groups).astype(str))
    nss = np.char.add("ns", np.arange(n_ns).astype(str))

    keys = ["resource_type", "resource_id", "relation",
            "subject_type", "subject_id", "subject_relation"]
    if cav_share > 0:
        keys += ["caveat", "caveat_context"]
    cols = {k: [] for k in keys}

    def add(rt, rid, rl, st, sid, srl=None, cav=None, ctx=None):
        n = len(rid)
        cols["resource_type"].append(np.full(n, rt))
        cols["resource_id"].append(rid)
        cols["relation"].append(np.full(n, rl))
        cols["subject_type"].append(np.full(n, st))
        cols["subject_id"].append(sid)
        cols["subject_relation"].append(
            np.full(n, srl if srl is not None else ""))
        if cav_share > 0:
            cols["caveat"].append(
                cav if cav is not None else np.full(n, ""))
            cols["caveat_context"].append(
                ctx if ctx is not None else np.full(n, ""))

    # group membership: ~20 users per group
    gm = min(20 * n_groups, n_rels // 20)
    add("group", groups[rng.integers(n_groups, size=gm)], "member",
        "user", users[rng.integers(n_users, size=gm)])
    if cav_share > 0:
        # the mesh mix adds a SHORT nested-group chain (g1 ⊂ g0, ...):
        # a genuinely cyclic-core range too sparse for the dense-closure
        # peel, so the fixpoint iterates a few hops and the K-step
        # convergence fuse has collectives to save — the shallow
        # headline graph stratifies to a zero-iteration core, which
        # would make the reduction unmeasurable
        chain = int(min(6, n_groups - 1))
        if chain > 0:
            add("group", groups[np.arange(chain)], "member",
                "group", groups[np.arange(1, chain + 1)], "member")
    # namespace viewer grants via groups (2 per ns) — exercises the
    # group#member userset + namespace->view arrow rewrite chain
    nv = 2 * n_ns
    add("namespace", nss[rng.integers(n_ns, size=nv)], "viewer",
        "group", groups[rng.integers(n_groups, size=nv)], "member")
    # every pod lives in a namespace
    pod_ns = np.char.add("ns", rng.integers(n_ns, size=n_pods).astype(str))
    add("pod", pods, "namespace", "namespace", pod_ns)
    # the rest: flat pod#viewer@user direct grants, deduplicated
    n_flat = n_rels - gm - nv - n_pods
    pair = rng.integers(0, n_pods * n_users, size=int(n_flat * 1.01),
                        dtype=np.int64)
    pair = np.unique(pair)[:n_flat]
    rng.shuffle(pair)
    cav_col = ctx_col = None
    if cav_share > 0:
        idx = np.arange(len(pair))
        is_cav = idx < int(len(pair) * cav_share)
        cav_col = np.where(is_cav, "ip_allowlist", "")
        ctx_col = np.where(is_cav,
                           np.asarray(MESH_CTXS)[idx % len(MESH_CTXS)], "")
    add("pod", pods[pair // n_users], "viewer", "user",
        users[pair % n_users], cav=cav_col, ctx=ctx_col)

    rels_cols = {k: np.concatenate(v) for k, v in cols.items()}
    total = len(rels_cols["resource_id"])
    log(f"built columns: {total} relationships"
        + (f" ({cav_share:.0%} of flat grants caveated)"
           if cav_share > 0 else ""))

    e = Engine(schema=parse_schema(schema))
    t0 = time.perf_counter()
    e.bulk_load(rels_cols)
    log(f"bulk_load: {time.perf_counter() - t0:.1f}s")
    return e, total


# Per-stage attribution (ISSUE 6): each stage maps to the histogram(s)
# its code path observes. Phases snapshot before/after and report the
# delta's p50/p99, so BENCH_*.json rows carry stage breakdowns instead of
# only end-to-end percentiles.
_STAGE_HISTOGRAMS = {
    "admission_wait": ("admission_queue_seconds",),
    "device": ("engine_check_seconds", "engine_lookup_seconds"),
    "upstream": ("proxy_upstream_seconds",),
}


def _stage_snapshot() -> dict:
    from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

    out = {}
    for stage, names in _STAGE_HISTOGRAMS.items():
        out[stage] = {n: metrics.hist_snapshot(n) for n in names}
    return out


def _record_stage_breakdown(result: dict, key: str, before: dict) -> None:
    """p50/p99/p99.9 (ms) + sample count per stage for the window since
    ``before`` (a ``_stage_snapshot()``), merged across each stage's
    histograms. Stages whose window saw NO samples are omitted entirely
    (a zero-count row with null percentiles reads like a measurement);
    recorded percentiles are always finite — never Infinity, never a
    crash (the JSON contract)."""
    from spicedb_kubeapi_proxy_tpu.utils.metrics import (
        snapshot_delta_quantile,
    )

    after = _stage_snapshot()
    stages = {}
    for stage, names in _STAGE_HISTOGRAMS.items():
        n = 0
        p50 = p99 = p999 = None
        for name in names:
            b, a = before[stage][name], after[stage][name]
            if a is None:
                continue
            dn = a["n"] - (b["n"] if b else 0)
            if dn <= 0:
                continue
            n += dn
            q50 = snapshot_delta_quantile(b, a, 0.5)
            q99 = snapshot_delta_quantile(b, a, 0.99)
            q999 = snapshot_delta_quantile(b, a, 0.999)
            # multiple histograms per stage: keep the slower series'
            # percentile (an upper bound; exact merging would need raw
            # samples the registry deliberately doesn't retain)
            p50 = q50 * 1e3 if p50 is None else max(p50, q50 * 1e3)
            p99 = q99 * 1e3 if p99 is None else max(p99, q99 * 1e3)
            p999 = q999 * 1e3 if p999 is None else max(p999, q999 * 1e3)
        if n == 0:
            continue
        stages[stage] = {
            "n": n,
            "p50_ms": None if p50 is None else round(p50, 3),
            "p99_ms": None if p99 is None else round(p99, 3),
            "p999_ms": None if p999 is None else round(p999, 3),
        }
    result[key] = stages


def _dispatch_floor_ms(trials: int = 12) -> float:
    """Wall p50 of a no-op jitted dispatch+readback — the transport floor
    below which no synchronous device query can go (one tunnel RTT on
    remotely-attached chips, sub-ms on host-local ones)."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((), jnp.int32)
    np.asarray(f(x))  # compile + warm
    lat = []
    for _ in range(trials):
        t0 = time.perf_counter()
        np.asarray(f(x))
        lat.append((time.perf_counter() - t0) * 1e3)
    return float(np.percentile(lat, 50))


def _chained_device_estimate(e, subjects, trials: int, k: int = 8):
    """Per-query device time for the list-filter query, via the slope of
    chained dispatches: lax.scan runs K fixpoints back-to-back on device
    (the carry makes query i+1 depend on query i's result, so they cannot
    overlap), and (wall_K - wall_1)/(K-1) cancels every fixed
    per-dispatch cost. Returns (ms_per_query, wall1_ms, wallK_ms, k)."""
    import jax
    import jax.numpy as jnp

    from spicedb_kubeapi_proxy_tpu.ops.reachability import (
        DEFAULT_MAX_ITERS,
        _next_bucket,
        _run,
    )

    cg = e.compiled()
    objs = e._objects_by_name()
    d = cg._dev()
    off = cg.offset_of("pod", "view")
    n = cg.type_sizes["pod"]
    q_pad = _next_bucket(n, 8)
    qs = np.full(q_pad, cg.M, dtype=np.int32)
    qs[:n] = off + np.arange(n, dtype=np.int32)
    qb = np.zeros(q_pad, dtype=np.int32)
    now_rel = np.float32(time.time() - cg.base_time)
    uniq = list(dict.fromkeys(subjects))
    picks = [uniq[i % len(uniq)] for i in range(k)]
    seed_stack = np.asarray(
        [[cg.encode_subject("user", u, None, objs)] for u in picks],
        dtype=np.int32,
    )  # [k, 1, 2]

    def chained(blocks, blocks_bits, src, dst, exp, cav,
                dsrc, ddst, dexp, dcav, cav_static,
                seed_stack, qs, qb, now_rel):
        def body(dep, seeds):
            # optimization_barrier ties each query's input to the previous
            # result in a way XLA cannot fold away (an arithmetic no-op
            # like `+ dep * 0` would be simplified out); together with
            # scan's sequential While lowering this guarantees the K
            # queries execute back-to-back, never overlapped
            seeds, _ = jax.lax.optimization_barrier((seeds, dep))
            out, _, _, _, _ = _run(cg.run_meta(), blocks, blocks_bits,
                                   src, dst, exp, cav,
                                   dsrc, ddst, dexp, dcav, cav_static, (),
                                   seeds, qs, qb, now_rel,
                                   jnp.float32(1.0),
                                   max_iters=DEFAULT_MAX_ITERS)
            return out.astype(jnp.int32).sum(), out[:1]
        dep, _ = jax.lax.scan(body, jnp.int32(0), seed_stack)
        return dep

    fn = jax.jit(chained)
    a = (d["blocks"], d["blocks_bits"], d["src"], d["dst"], d["exp"],
         d["cav"], d["dsrc"], d["ddst"], d["dexp"], d["dcav"],
         d["cav_static"])
    jqs, jqb = jnp.asarray(qs), jnp.asarray(qb)
    s1 = jnp.asarray(seed_stack[:1])
    sk = jnp.asarray(seed_stack)
    np.asarray(fn(*a, s1, jqs, jqb, now_rel))  # compile both shapes
    np.asarray(fn(*a, sk, jqs, jqb, now_rel))
    w1, wk = [], []
    for _ in range(trials):
        t0 = time.perf_counter()
        np.asarray(fn(*a, s1, jqs, jqb, now_rel))
        w1.append((time.perf_counter() - t0) * 1e3)
        t0 = time.perf_counter()
        np.asarray(fn(*a, sk, jqs, jqb, now_rel))
        wk.append((time.perf_counter() - t0) * 1e3)
    p1 = float(np.percentile(w1, 50))
    pk = float(np.percentile(wk, 50))
    return max((pk - p1) / (k - 1), 0.0), p1, pk, k


def run_suite(quick: bool, result: Optional[dict] = None) -> None:
    """BASELINE.md eval configs 3-5 (the headline run is config 2; config 1
    is the trivial ~10-relationship check, covered by every unit test).
    Results go to stderr AND, when ``result`` is given, into the emitted
    JSON as config3_*/config4_*/config5_* fields so a suite artifact is
    self-contained."""
    if result is None:
        result = {}
    from spicedb_kubeapi_proxy_tpu.engine import CheckItem, Engine
    from spicedb_kubeapi_proxy_tpu.models import parse_schema

    rng = np.random.default_rng(3)
    scale = 10 if quick else 1

    # -- config 3: nested-group userset rewrites, ~1M rels ------------------
    n_users, n_g2, n_g1, n_g0, n_ns = (np.array(
        [100_000, 20_000, 2_000, 200, 200_000]) // scale).tolist()
    schema = parse_schema("""
definition user {}
definition group { relation member: user | group#member }
definition namespace {
  relation viewer: group#member
  permission view = viewer
}
""")
    cols = {k: [] for k in ("resource_type", "resource_id", "relation",
                            "subject_type", "subject_id", "subject_relation")}

    def add(rt, rid, rl, st, sid, srl):
        m = len(rid)
        cols["resource_type"].append(np.full(m, rt))
        cols["resource_id"].append(rid)
        cols["relation"].append(np.full(m, rl))
        cols["subject_type"].append(np.full(m, st))
        cols["subject_id"].append(sid)
        cols["subject_relation"].append(np.full(m, srl))

    users = np.char.add("u", np.arange(n_users).astype(str))
    g2 = np.char.add("g2-", np.arange(n_g2).astype(str))
    g1 = np.char.add("g1-", np.arange(n_g1).astype(str))
    g0 = np.char.add("g0-", np.arange(n_g0).astype(str))
    nss = np.char.add("ns", np.arange(n_ns).astype(str))
    # leaf membership: ~40 users per g2; g2 in g1; g1 in g0; ns viewer g0
    # (totals ~1M relationships at full scale, BASELINE config 3)
    m = 40 * n_g2
    add("group", g2[rng.integers(n_g2, size=m)], "member",
        "user", users[rng.integers(n_users, size=m)], "")
    add("group", g1[rng.integers(n_g1, size=n_g2)], "member",
        "group", g2, "member")
    add("group", g0[rng.integers(n_g0, size=n_g1)], "member",
        "group", g1, "member")
    add("namespace", nss, "viewer", "group",
        g0[rng.integers(n_g0, size=n_ns)], "member")
    e3 = Engine(schema=schema)
    merged = {k: np.concatenate(v) for k, v in cols.items()}
    total = len(merged["resource_id"])
    e3.bulk_load(merged)
    # a user that is definitely a leaf member, so visibility is non-trivial
    member = str(merged["subject_id"][0])
    t0 = time.perf_counter()
    mask, _ = e3.lookup_resources_mask("namespace", "view", "user", member)
    warm = time.perf_counter() - t0
    vis_member = int(mask.sum())
    lat = []
    iters = 0
    for u in rng.integers(n_users, size=11):
        t0 = time.perf_counter()
        fut = e3.lookup_resources_mask_async("namespace", "view", "user",
                                             f"u{u}")
        fut.result()
        lat.append((time.perf_counter() - t0) * 1e3)
        iters = max(iters, fut.iterations())
    # fixpoint_iters makes the closured-self-block win auditable in ANY
    # run (VERDICT r3 weak #2: pre-closure this config took 4 iterations;
    # the closure collapses the recursive-group chain to 1)
    log(f"[config 3] nested-group LookupResources @ {total} rels: "
        f"p50_wall={np.percentile(lat, 50):.1f}ms "
        f"fixpoint_iters={iters} (warmup {warm:.1f}s, "
        f"member {member} sees {vis_member}/{n_ns})")
    result["config3_rels"] = total
    result["config3_p50_wall_ms"] = round(float(np.percentile(lat, 50)), 3)
    result["config3_fixpoint_iters"] = iters

    # -- config 4: 10-hop tupleset-to-userset chains ------------------------
    n_chains = 2_000 // scale
    cols = {k: [] for k in cols}
    hops = []
    for h in range(10):
        a = np.char.add(f"t{h}-", np.arange(n_chains).astype(str))
        b = np.char.add(f"t{h + 1}-", np.arange(n_chains).astype(str))
        hops.append((a, b))
    for h, (a, b) in enumerate(hops):
        add("group", a, "member", "group", b, "member")
    leaf = np.char.add("t10-", np.arange(n_chains).astype(str))
    add("group", leaf, "member", "user",
        np.char.add("u", np.arange(n_chains).astype(str)), "")
    add("namespace", np.char.add("ns", np.arange(n_chains).astype(str)),
        "viewer", "group",
        np.char.add("t0-", np.arange(n_chains).astype(str)), "member")
    e4 = Engine(schema=schema)
    merged = {k: np.concatenate(v) for k, v in cols.items()}
    total = len(merged["resource_id"])
    e4.bulk_load(merged)
    items = [CheckItem("namespace", f"ns{i}", "view", "user", f"u{i}")
             for i in rng.integers(n_chains, size=512).tolist()]
    e4.check_bulk(items)  # warm
    t0 = time.perf_counter()
    got = e4.check_bulk(items)
    dt = (time.perf_counter() - t0) * 1e3
    log(f"[config 4] 10-hop chains @ {total} rels: 512 checks in "
        f"{dt:.1f}ms ({all(got) and 'all allowed' or 'DENIALS!'})")
    result["config4_rels"] = total
    result["config4_512checks_ms"] = round(dt, 3)

    # -- config 5: multi-tenant concurrent lists ----------------------------
    n_ns, n_users, conc = (np.array([100_000, 10_000, 256]) // scale).tolist()
    schema5 = parse_schema("""
definition user {}
definition namespace {
  relation viewer: user
  permission view = viewer
}
""")
    cols = {k: [] for k in cols}
    nss = np.char.add("ns", np.arange(n_ns).astype(str))
    # ~20 viewers per namespace
    m = 20 * n_ns
    add("namespace", nss[rng.integers(n_ns, size=m)], "viewer",
        "user", np.char.add("u", rng.integers(n_users, size=m).astype(str)),
        "")
    e5 = Engine(schema=schema5)
    merged = {k: np.concatenate(v) for k, v in cols.items()}
    total = len(merged["resource_id"])
    e5.bulk_load(merged)
    e5.lookup_resources_mask("namespace", "view", "user", "u0")  # warm
    subs = [f"u{u}" for u in rng.integers(n_users, size=conc)]

    def run_conc():
        t0 = time.perf_counter()
        futs = [e5.lookup_resources_mask_async(
            "namespace", "view", "user", u) for u in subs]
        for f in futs:
            f.result()
        return time.perf_counter() - t0

    dt = run_conc()
    log(f"[config 5] {conc} concurrent ns-list queries @ {total} rels "
        f"x {n_ns} ns: {dt * 1e3:.0f}ms total = {conc / dt:.0f} "
        f"list-queries/s/chip ({dt * 1e3 / conc:.2f}ms/query amortized)")
    # same workload with cross-request dispatch fusion (the deployment
    # shape: a fleet of same-type list requests) — up to 8 subjects share
    # one fixpoint whose grid extraction is a single dynamic_slice
    e5.enable_lookup_batching()
    run_conc()  # warm the fused-grid trace (B=8 compile)
    dt_b = run_conc()
    log(f"[config 5+batcher] same workload, fused dispatches: "
        f"{dt_b * 1e3:.0f}ms total = {conc / dt_b:.0f} list-queries/s/chip "
        f"({dt_b * 1e3 / conc:.2f}ms/query amortized, "
        f"{dt / dt_b:.1f}x the unbatched run)")
    result["config5_conc"] = conc
    result["config5_ms_per_query"] = round(dt * 1e3 / conc, 3)
    result["config5_batched_ms_per_query"] = round(dt_b * 1e3 / conc, 3)


# ---------------------------------------------------------------------------
# Backend init. Two failure modes observed on the driver (BENCH_r01/r02):
# a fast UNAVAILABLE from the axon TPU plugin, and a ~25-minute hang inside a
# single jax.devices() call. The parent process therefore NEVER touches the
# TPU plugin until a *subprocess* probe (hard per-attempt timeout) has proven
# it alive; on probe failure the parent pins jax_platforms=cpu — the exact
# move tests/conftest.py uses to keep unit tests off the chip — and runs
# degraded. A watchdog THREAD (not a signal: a hang inside a C extension
# never returns to the bytecode loop, so a Python signal handler would wait
# forever) enforces an overall deadline and emits the partial JSON.
# ---------------------------------------------------------------------------

_EMIT_LOCK = threading.Lock()
_EMITTED = False

# accepted non-degraded platform names: the axon plugin registers the chip
# as platform "axon" (sometimes surfacing as "tpu")
_TPU_PLATFORMS = ("tpu", "axon")


def emit(result: dict, code: int = 0, os_exit: bool = False) -> None:
    """Print the one JSON contract line exactly once, whoever gets there
    first (main path, signal handler, or watchdog thread)."""
    global _EMITTED
    with _EMIT_LOCK:
        if not _EMITTED:
            _EMITTED = True
            sys.stdout.write(json.dumps(result) + "\n")
            sys.stdout.flush()
    if os_exit:
        os._exit(code)


# prints "<default_backend> <device platform>"; success requires rc 0 AND a
# recognizably-TPU token, so a silent CPU fallback inside the probe still
# counts as degraded (jax only warns when the plugin fails non-fatally)
_PROBE_CODE = (
    "import jax; d = jax.devices(); "
    "print(jax.default_backend(), d[0].platform)"
)


def probe_backend(args) -> tuple[bool, Optional[str]]:
    """Probe TPU availability in a subprocess. Returns (degraded, error).

    The subprocess is the crash barrier: if the plugin hangs, only the
    child is killed at ``--probe-timeout``; the parent's jax stays
    uninitialized and can still pin CPU. ``BENCH_PROBE_CMD`` overrides the
    probe command so tests can simulate a hung plugin with ``sleep``.
    """
    override = os.environ.get("BENCH_PROBE_CMD")
    cmd = (["sh", "-c", override] if override
           else [sys.executable, "-c", _PROBE_CODE])
    last: Optional[str] = None
    for attempt in range(1, args.retries + 1):
        t0 = time.monotonic()
        # logged BEFORE the (possibly hanging) probe: signal handlers are
        # already installed, so once this line is visible a SIGTERM test
        # can kill deterministically instead of sleeping and hoping
        log(f"probing TPU (attempt {attempt}/{args.retries}, "
            f"timeout {args.probe_timeout:.0f}s)")
        try:
            p = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=args.probe_timeout)
            words = (p.stdout or "").strip().split()
            if p.returncode == 0 and any(
                    w in _TPU_PLATFORMS for w in words):
                log(f"probe attempt {attempt}: TPU alive "
                    f"({' '.join(words)}, {time.monotonic() - t0:.0f}s)")
                return False, None
            tail = (p.stderr or "").strip().splitlines()
            last = (f"probe rc={p.returncode} backend="
                    f"{' '.join(words) or '?'}"
                    + (f": {tail[-1][:200]}" if tail else ""))
        except subprocess.TimeoutExpired:
            last = f"probe timed out after {args.probe_timeout}s (hung plugin)"
        except OSError as e:
            last = f"probe failed to launch: {e}"
        log(f"probe attempt {attempt}/{args.retries} failed: {last}")
        if attempt < args.retries:
            time.sleep(args.retry_delay)
    log("TPU unavailable; pinning jax to CPU (degraded run)")
    return True, last


def reprobe_backend(result: dict, label: str, timeout: float = 60.0,
                    retries: int = 2) -> bool:
    """Between-phase backend liveness check (VERDICT Weak #1: five rounds
    of driver captures went [DEGRADED: cpu] off a single mid-run hang).
    The probe is a SUBPROCESS with its own per-attempt deadline plus one
    retry, so a tunnel that died after the headline costs at most
    ~2*timeout and a skipped phase — never a 120s in-process hang that
    runs the watchdog out and relabels already-measured real-chip data.
    Returns True when the next device-touching phase may proceed."""
    if result.get("degraded"):
        return True  # already on CPU: nothing left to lose mid-run
    override = os.environ.get("BENCH_PROBE_CMD")
    cmd = (["sh", "-c", override] if override
           else [sys.executable, "-c", _PROBE_CODE])
    for attempt in range(1, retries + 1):
        try:
            p = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=timeout)
            words = (p.stdout or "").strip().split()
            if p.returncode == 0 and any(
                    w in _TPU_PLATFORMS for w in words):
                return True
        except subprocess.TimeoutExpired:
            pass
        except OSError:
            break
        log(f"re-probe before {label} failed (attempt {attempt}/{retries})")
    # record, don't relabel: phases measured BEFORE the loss keep their
    # provenance; phases after it are skipped instead of hanging
    result.setdefault("backend_lost_midrun", []).append(label)
    log(f"backend unresponsive before {label}: skipping the phase "
        "(earlier numbers keep their provenance)")
    return False


def _measure(args, result: dict) -> None:
    """The benchmark body; fills ``result`` in place so the caller can emit
    whatever was measured even if a later stage dies."""
    degraded, err = probe_backend(args)
    result["degraded"] = degraded
    if err:
        result["backend_error"] = err
    import jax

    if degraded:
        # same platform pinning as tests/conftest.py: backends initialize
        # lazily, so forcing cpu before first use never touches the plugin
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
    devs = jax.devices()
    backend = jax.default_backend()
    log(f"jax {jax.__version__} backend={backend} devices={devs}")
    if not degraded and backend not in _TPU_PLATFORMS:
        # probe saw a TPU but the parent silently fell back to CPU: still a
        # degraded run — shrink the config and label the metric honestly
        log(f"parent backend is {backend!r}, not TPU: degraded run")
        degraded = True
        result["degraded"] = True
        result["backend_error"] = f"parent fell back to {backend}"
    result["backend"] = backend
    quick = args.quick or args.tiny or (degraded and not args.force_full)
    if quick and not args.quick:
        log("degraded backend: shrinking to --quick config")
    if args.macro_only:
        # the CI smoke path (make bench-macro): only the open-loop
        # macrobench, headline metric = the sweep's knee estimate
        _macro_phase(result, quick, args.tiny)
        macro = result["macro"]
        result["metric"] = (
            "open-loop macrobench goodput knee (offered op/s)"
            + (" [DEGRADED: cpu]" if degraded else ""))
        result["value"] = macro.get("knee_rps")
        result["unit"] = "op/s"
        result["vs_baseline"] = None
        return
    if args.tiny:
        n_pods, n_users, n_ns, n_groups, n_rels = 200, 100, 10, 10, 3_000
        args.trials = min(args.trials, 5)
    elif quick:
        n_pods, n_users, n_ns, n_groups, n_rels = 2_000, 500, 50, 50, 50_000
    else:
        n_pods, n_users, n_ns, n_groups, n_rels = (
            100_000, 10_000, 1_000, 1_000, 10_000_000)

    e, total = build_engine(n_pods, n_users, n_ns, n_groups, n_rels)
    result["n_pods"], result["n_rels"] = n_pods, total

    t0 = time.perf_counter()
    cg = e.compiled()
    compile_s = time.perf_counter() - t0
    log(f"compile_graph: {compile_s:.1f}s (M={cg.M} slots, "
        f"E={cg.n_edges} edges)")
    result["graph_compile_s"] = round(compile_s, 2)

    # -- p50 list-filter latency: one user's visibility mask over all pods --
    rng = np.random.default_rng(1)
    subjects = [f"u{rng.integers(n_users)}" for _ in range(args.trials)]
    t0 = time.perf_counter()
    mask, _ = e.lookup_resources_mask("pod", "view", "user", subjects[0])
    log(f"warmup (jit compile + run): {time.perf_counter() - t0:.1f}s; "
        f"visible={int(mask.sum())}/{n_pods}")
    # per-stage attribution window: everything from here through the
    # repeat-traffic section lands in result["stages"] (p50/p99 per
    # stage from the span-backed histograms, warmup excluded)
    stage0 = _stage_snapshot()
    profiling = False
    if args.profile_dir:
        # device timeline for the measured queries (the fixpoint dispatch
        # is annotated "sdbkp:fixpoint", ops/reachability.py); view with
        # tensorboard or xprof
        import jax

        try:
            jax.profiler.start_trace(args.profile_dir)
            profiling = True
            log(f"jax profiler trace -> {args.profile_dir}")
        except Exception as ex:  # noqa: BLE001 - profiling is best-effort
            log(f"profiler start failed (non-fatal): {ex}")
    # p99-tail diagnosis (VERDICT r3 weak #2: an unexplained 1.7x tail):
    # per-trial latencies plus the HOST-side suspects sampled around the
    # loop — full GEN-2 GC collections (gen-0/1 fire constantly and cost
    # microseconds; only gen-2 pauses reach milliseconds) and graph
    # recompiles/incremental updates. Device-side suspects (XLA
    # respecialization, tunnel jitter) are not observable host-side: the
    # --profile-dir trace is the tool for those.
    import gc

    from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

    def _gen2():
        return gc.get_stats()[2]["collections"]

    gc2_before = _gen2()
    compiles_before = metrics.counter("engine_graph_compiles_total").value
    incr_before = metrics.counter(
        "engine_graph_incremental_updates_total").value
    lat = []
    gc_flagged = 0
    for u in subjects:
        g0 = _gen2()
        t0 = time.perf_counter()
        mask, _ = e.lookup_resources_mask("pod", "view", "user", u)
        lat.append((time.perf_counter() - t0) * 1e3)
        if _gen2() != g0:
            gc_flagged += 1
    if profiling:
        import jax

        jax.profiler.stop_trace()
    p50_wall = float(np.percentile(lat, 50))
    p99_wall = float(np.percentile(lat, 99))
    log(f"list-filter latency over {len(lat)} trials: "
        f"p50_wall={p50_wall:.2f}ms p99_wall={p99_wall:.2f}ms")
    slowest = sorted(range(len(lat)), key=lambda i: -lat[i])[:3]
    log(f"tail diagnosis: slowest trials "
        f"{[(i, round(lat[i], 1)) for i in slowest]} (ms); "
        f"{gc_flagged}/{len(lat)} trials saw a gen-2 GC collection "
        f"({_gen2() - gc2_before} total); graph recompiles = "
        f"{int(metrics.counter('engine_graph_compiles_total').value - compiles_before)}, "
        f"incremental updates = "
        f"{int(metrics.counter('engine_graph_incremental_updates_total').value - incr_before)} "
        f"during the loop (device-side suspects: see --profile-dir)")
    result["lat_ms_trials"] = [round(x, 2) for x in lat]
    result["tail_gc_flagged_trials"] = gc_flagged

    # Dispatch floor: wall p50 of a no-op jitted scalar round trip. On a
    # remotely-attached chip (the axon tunnel) this is pure transport —
    # ~70ms here vs <1ms host-local — and bounds EVERY synchronous device
    # query from below, ours or anyone's. Reported so the wall headline is
    # legible: p50_wall_minus_floor_ms is what the framework itself adds,
    # i.e. the wall latency a host-local chip would see (plus ~floor).
    floor = _dispatch_floor_ms()
    minus_floor = max(p50_wall - floor, 0.0)
    result["dispatch_floor_ms"] = round(floor, 3)
    result["p50_wall_minus_floor_ms"] = round(minus_floor, 3)
    # the 50ms BASELINE target describes chip+framework latency; through a
    # remote tunnel the raw vs_baseline mostly measures the tunnel, so the
    # transport-excluded ratio is reported alongside (never as `value`).
    # Residuals below measurement jitter would publish noise as a huge
    # ratio, so they report nothing instead.
    if minus_floor >= 0.25:
        result["vs_baseline_excl_transport"] = round(
            BASELINE_TARGET_MS / minus_floor, 2)
    log(f"dispatch floor (no-op jit round trip): {floor:.2f}ms; "
        f"p50 minus floor = {minus_floor:.2f}ms")

    # The headline value is the MEASURED wall p50 (vs_baseline divides the
    # 50ms BASELINE target by it). The chained-dispatch slope — per-query
    # device compute with fixed dispatch overhead cancelled — is reported
    # as a separate field, never as the headline.
    result["metric"] = (
        f"p50 list-filter latency (wall), {n_pods} pods @ {total} rels, "
        f"1 chip" + (" [DEGRADED: cpu]" if degraded else ""))
    result["value"] = round(p50_wall, 3)
    result["unit"] = "ms"
    result["vs_baseline"] = round(BASELINE_TARGET_MS / p50_wall, 2)
    result["p50_wall_ms"] = round(p50_wall, 3)
    result["p99_wall_ms"] = round(p99_wall, 3)

    # fixpoint depth for this query shape (dispatch-depth analog)
    objs = e._objects_by_name()
    seeds = np.asarray(
        [cg.encode_subject("user", subjects[0], None, objs)], dtype=np.int32)
    off = cg.offset_of("pod", "view")
    n = cg.type_sizes["pod"]
    qf = cg.query_async(seeds, off + np.arange(n, dtype=np.int32),
                        np.zeros(n, dtype=np.int32))
    qf.result()
    iters = qf.iterations()
    result["fixpoint_iters"] = iters

    # -- fused-concurrency amortization on the HEADLINE shape --
    # The 50ms target describes a serving fleet, not a lone caller: with
    # cross-request batching on (proxy --lookup-batch-window), concurrent
    # same-type list prefilters fuse up to 8 subjects per fixpoint whose
    # grid extraction is one dynamic_slice. Measured here on the same 10M
    # graph so the driver-captured JSON carries the deployment number.
    try:
        conc_n = 16 if quick else 32
        e.enable_lookup_batching()
        conc_subs = [subjects[i % len(subjects)] for i in range(conc_n)]

        def run_conc_headline() -> float:
            t0 = time.perf_counter()
            futs = [e.lookup_resources_mask_async("pod", "view", "user", u)
                    for u in conc_subs]
            for f in futs:
                f.result()
            return (time.perf_counter() - t0) * 1e3

        run_conc_headline()  # warm the fused-grid (B=8) trace
        conc_ms = sorted(run_conc_headline() for _ in range(3))[1]
        amort = conc_ms / conc_n
        log(f"fused concurrency: {conc_n} concurrent pod-list queries "
            f"(batch window 2ms) in {conc_ms:.1f}ms = {amort:.2f}ms/query "
            f"amortized")
        result["concurrent_queries"] = conc_n
        result["concurrent_amortized_ms_per_query"] = round(amort, 3)
        result["vs_baseline_concurrent"] = round(BASELINE_TARGET_MS / amort, 2)
    except Exception as ex:  # noqa: BLE001 - aux measurement only
        log(f"fused-concurrency section failed (non-fatal): {ex}")
    finally:
        e.disable_lookup_batching()

    try:
        if not reprobe_backend(result, "chained-estimate",
                               timeout=min(args.probe_timeout, 60.0)):
            raise RuntimeError("backend lost mid-run")
        chain_est, p50_w1, p50_wk, k = _chained_device_estimate(
            e, subjects, trials=max(args.trials // 2, 5))
        log(f"chained-dispatch slope: wall(1)={p50_w1:.2f}ms "
            f"wall({k})={p50_wk:.2f}ms -> {chain_est:.2f}ms/query "
            f"device time")
        result["device_ms_estimate"] = round(chain_est, 3)
        # roofline: bytes touched per hop x hops / device time
        hb = cg.hop_bytes(batch=1)
        if chain_est > 0:
            tail = hb.get("tail_once", 0)
            streamed = hb["total"] * iters + tail
            eff_gbps = streamed / (chain_est * 1e-3) / 1e9
            # v5e HBM ~819 GB/s; v4 ~1228; CPU n/a — report raw GB/s and
            # let the reader place it on the roofline for the actual chip
            log(f"roofline: {hb['total'] / 1e6:.1f} MB/core-hop x {iters} "
                f"iters + {tail / 1e6:.0f} MB acyclic tail (once) = "
                f"{streamed / 1e6:.0f} MB streamed -> "
                f"{eff_gbps:.0f} GB/s effective "
                f"(core residual {hb['residual'] / 1e6:.1f} MB, core "
                f"blocks {hb['blocks'] / 1e6:.1f} MB per iter)")
            result["core_hop_mb"] = round(hb["total"] / 1e6, 1)
            result["tail_once_mb"] = round(tail / 1e6, 1)
            result["effective_gbps"] = round(eff_gbps, 1)
    except Exception as ex:  # noqa: BLE001 - aux measurement only
        log(f"chained-dispatch estimate failed (non-fatal): {ex}")

    # -- bulk-check throughput --
    from spicedb_kubeapi_proxy_tpu.engine import CheckItem

    B, per = (8, 64) if quick else (64, 1024)
    items = [
        CheckItem("pod", f"ns/p{rng.integers(n_pods)}", "view",
                  "user", f"u{b}")
        for b in rng.integers(n_users, size=B)
        for _ in range(per)
    ]
    e.check_bulk(items[: B * per])  # warmup shape
    # p50 over several trials: a single trial spans 2-3x on this host
    # (bench_results/bulkcheck_regression_r5.md — the r3->r4 "regression"
    # was one slow trial), so one sample is not a measurement.
    bulk_trials = 5 if quick else 7
    bulk_rates = []
    for _ in range(bulk_trials):
        t0 = time.perf_counter()
        e.check_bulk(items)
        dt = time.perf_counter() - t0
        bulk_rates.append(len(items) / dt)
    bulk_rates.sort()
    checks_per_s = bulk_rates[len(bulk_rates) // 2]
    log(f"bulk check: {len(items)} checks, p50 over {bulk_trials} trials "
        f"= {checks_per_s:,.0f} checks/s/chip "
        f"(min {bulk_rates[0]:,.0f}, max {bulk_rates[-1]:,.0f})")
    result["checks_per_s_per_chip"] = round(checks_per_s)
    result["checks_per_s_min"] = round(bulk_rates[0])

    # -- interleaved write -> fully-consistent read (delta overlay) --
    from spicedb_kubeapi_proxy_tpu.engine.store import WriteOp
    from spicedb_kubeapi_proxy_tpu.models.tuples import Relationship
    from spicedb_kubeapi_proxy_tpu.utils.metrics import (
        snapshot_delta_quantile,
    )

    wr = min(args.trials, 11)
    # the first write after bulk_load pays the store-index build
    # (vectorized hash + native radix sort, engine/store.py), and its
    # read pays the ONE unavoidable full recompile (bulk-loaded history
    # isn't in the watch log, so the overlay can't absorb it). Both are
    # reported separately; the measured loop below is the STEADY-STATE
    # write-churn path, which must run recompile-free on the overlay.
    t0 = time.perf_counter()
    e.write_relationships([WriteOp("touch", Relationship(
        "pod", f"ns/p{int(rng.integers(n_pods))}", "viewer",
        "user", f"u{int(rng.integers(n_users))}"))])
    t_first_write = time.perf_counter() - t0
    e.lookup_resources_mask("pod", "view", "user", subjects[0])
    # one warm overlay append outside the measurement: the first append
    # against a fresh base jit-compiles the O(write) device scatters
    # (dynamic_update_slice shapes), a once-per-process cost that is not
    # part of the steady state being claimed
    e.write_relationships([WriteOp("touch", Relationship(
        "pod", f"ns/p{int(rng.integers(n_pods))}", "viewer",
        "user", f"u{int(rng.integers(n_users))}"))])
    e.lookup_resources_mask("pod", "view", "user", subjects[0])
    # tail diagnosis for THIS phase (the read-only list-filter loop above
    # trivially reports 0 for both counters — the write path is where
    # they move): recompile / overlay-append counts plus the per-write
    # stage split (journal = store mutation + WAL, overlay-append = the
    # O(write) incremental graph fold, dispatch = the fully-consistent
    # read's device round trip)
    compiles_b = metrics.counter("engine_graph_compiles_total").value
    incr_b = metrics.counter("engine_graph_incremental_updates_total").value
    journal_b = metrics.hist_snapshot("store_write_seconds")
    overlay_b = metrics.hist_snapshot("engine_graph_incremental_seconds")
    wlat = []
    write_ms = []
    for i in range(wr):
        t0 = time.perf_counter()
        e.write_relationships([WriteOp("touch", Relationship(
            "pod", f"ns/p{int(rng.integers(n_pods))}", "viewer",
            "user", f"u{int(rng.integers(n_users))}"))])
        write_ms.append((time.perf_counter() - t0) * 1e3)
        t0 = time.perf_counter()
        e.lookup_resources_mask("pod", "view", "user",
                                subjects[i % len(subjects)])
        wlat.append((time.perf_counter() - t0) * 1e3)
    p50_aw = float(np.percentile(wlat, 50))
    raw_recompiles = int(
        metrics.counter("engine_graph_compiles_total").value - compiles_b)
    raw_incr = int(metrics.counter(
        "engine_graph_incremental_updates_total").value - incr_b)
    journal_a = metrics.hist_snapshot("store_write_seconds")
    overlay_a = metrics.hist_snapshot("engine_graph_incremental_seconds")
    breakdown = {"write_p50_ms": round(float(np.percentile(write_ms, 50)),
                                       3),
                 "dispatch_p50_ms": round(p50_aw, 3)}
    for k, b, a in (("journal", journal_b, journal_a),
                    ("overlay_append", overlay_b, overlay_a)):
        dn = (a["n"] if a else 0) - (b["n"] if b else 0)
        if dn > 0:
            q = snapshot_delta_quantile(b, a, 0.5)
            if q is not None:
                breakdown[f"{k}_p50_ms"] = round(q * 1e3, 3)
            breakdown[f"{k}_n"] = dn
    log(f"fully-consistent read after write: p50={p50_aw:.2f}ms "
        f"over {wr} write->read pairs; first write (index build) = "
        f"{t_first_write * 1e3:.0f}ms")
    log(f"tail diagnosis (read-after-write): graph recompiles = "
        f"{raw_recompiles}, incremental overlay updates = {raw_incr} "
        f"across {wr} writes; per-write breakdown "
        f"journal={breakdown.get('journal_p50_ms', '?')}ms "
        f"overlay-append={breakdown.get('overlay_append_p50_ms', '?')}ms "
        f"dispatch={breakdown['dispatch_p50_ms']}ms (p50)")
    result["p50_read_after_write_ms"] = round(p50_aw, 3)
    result["first_write_after_bulk_ms"] = round(t_first_write * 1e3, 1)
    result["read_after_write"] = {
        "recompiles": raw_recompiles,
        "incremental_updates": raw_incr,
        "write_breakdown": breakdown,
    }

    # -- repeat-traffic: decision-cache cold vs warm p50 + hit rate --
    # The serving-curve claim (ISSUE 2): repeat-heavy traffic (watch
    # fan-out, dashboard polling, fleet lists by one service account)
    # costs O(distinct queries per revision) dispatches, not O(requests).
    # Cold = first touch of each subject at this revision (full dispatch
    # through the cache's miss path); warm = the same subjects again.
    try:
        from spicedb_kubeapi_proxy_tpu.utils.metrics import (
            metrics as _metrics,
        )

        e.enable_decision_cache()
        rep_subs = list(dict.fromkeys(subjects))[:8]
        cold = []
        for u in rep_subs:
            t0 = time.perf_counter()
            e.lookup_resources_mask("pod", "view", "user", u)
            cold.append((time.perf_counter() - t0) * 1e3)
        hits0 = _metrics.counter("engine_decision_cache_hits_total",
                                 kind="lookup").value
        warm = []
        rounds = 3
        for _ in range(rounds):
            for u in rep_subs:
                t0 = time.perf_counter()
                e.lookup_resources_mask("pod", "view", "user", u)
                warm.append((time.perf_counter() - t0) * 1e3)
        hits = _metrics.counter("engine_decision_cache_hits_total",
                                kind="lookup").value - hits0
        hit_rate = hits / len(warm) if warm else 0.0
        cold_p50 = float(np.percentile(cold, 50))
        warm_p50 = float(np.percentile(warm, 50))
        log(f"repeat-traffic (decision cache): cold p50={cold_p50:.2f}ms, "
            f"warm (cached) p50={warm_p50:.3f}ms, hit rate="
            f"{hit_rate:.2f} over {len(warm)} repeats of "
            f"{len(rep_subs)} queries")
        result["repeat_cold_p50_ms"] = round(cold_p50, 3)
        result["repeat_warm_p50_ms"] = round(warm_p50, 4)
        result["repeat_hit_rate"] = round(hit_rate, 3)
    except Exception as ex:  # noqa: BLE001 - aux measurement only
        log(f"repeat-traffic section failed (non-fatal): {ex}")
    finally:
        e.disable_decision_cache()

    _record_stage_breakdown(result, "stages", stage0)

    # -- restart recovery: WAL replay throughput + time-to-ready --
    # Simulated crash (the --data-dir durability story, persistence/):
    # journal a write workload, abandon the process state WITHOUT a
    # checkpoint, and measure a cold store recovering from the WAL tail —
    # records/sec of replay and wall time until the store serves again.
    try:
        import shutil
        import tempfile

        from spicedb_kubeapi_proxy_tpu.engine.store import Store
        from spicedb_kubeapi_proxy_tpu.persistence import (
            Persistence,
            recover,
        )

        data_dir = tempfile.mkdtemp(prefix="bench-recovery-")
        try:
            src = Store()
            pers = Persistence.open(src, data_dir, wal_fsync="off",
                                    auto_checkpoint=False)
            n_recs = 2_000 if quick else 20_000
            for i in range(n_recs):
                src.write([WriteOp("touch", Relationship(
                    "pod", f"ns/p{i % max(n_pods, 1)}", "viewer",
                    "user", f"u{i % 997}"))])
            pers.wal.sync()  # the crash point: fsynced log, no checkpoint
            pers.close(final_checkpoint=False)
            t0 = time.perf_counter()
            cold = Store()
            res = recover(cold, data_dir)
            ready_s = time.perf_counter() - t0
            assert res.replayed_records == n_recs and len(cold) > 0
            rate = n_recs / max(ready_s, 1e-9)
            log(f"restart recovery: replayed {n_recs} WAL records in "
                f"{ready_s * 1e3:.0f}ms ({rate:.0f} records/s "
                "time-to-ready, no snapshot)")
            result["recovery_replayed_records"] = n_recs
            result["recovery_records_per_s"] = round(rate)
            result["recovery_time_to_ready_s"] = round(ready_s, 3)
        finally:
            shutil.rmtree(data_dir, ignore_errors=True)
    except Exception as ex:  # noqa: BLE001 - aux measurement only
        log(f"restart-recovery section failed (non-fatal): {ex}")

    # -- leader failover: SIGKILL the leader under write load --
    # The robustness headline (ISSUE 4): a replicated engine set
    # (--peers, parallel/failover.py) loses its leader mid-traffic; the
    # follower promotes with a fenced term and the client fails over.
    # Reported: wall time from the kill to the first post-failover ack,
    # plus how the window's requests split between fail-closed errors
    # (the proxy's 503 family) and successes. Skipped on --tiny (the
    # contract-test smoke must not pay two engine-host boots).
    if not args.tiny:
        try:
            _failover_phase(result, quick)
        except Exception as ex:  # noqa: BLE001 - aux measurement only
            log(f"failover section failed (non-fatal): {ex}")

    # -- admission control: overload behavior at 2x offered load --
    # (ISSUE 5 acceptance: goodput, per-class p99, per-tenant fairness,
    # shed accounting.) Skipped on --tiny like the failover phase.
    if not args.tiny:
        try:
            _admission_phase(result, quick)
        except Exception as ex:  # noqa: BLE001 - aux measurement only
            log(f"admission section failed (non-fatal): {ex}")

    # -- device-side caveat evaluation (ISSUE 9): caveated-mix cold/warm
    # check p50 with and without request context. Runs at EVERY scale
    # including --tiny (the result schema is contract-test-pinned).
    try:
        _caveat_phase(result, quick)
    except Exception as ex:  # noqa: BLE001 - aux measurement only
        log(f"caveat section failed (non-fatal): {ex}")

    # -- mesh-native hot path (ISSUE 15): caveats on-mesh + K-step fused
    # fixpoint at 1 vs 2 vs 8 devices over a caveated mix. Runs at EVERY
    # scale including --tiny (contract-pinned); CPU-only hosts measure
    # whatever device counts exist (no TPU re-probe — the run-level
    # degraded label already carries the provenance) and the full run
    # records the 100k-pod/10M-rel mesh point.
    try:
        _mesh_phase(result, quick, args.tiny)
    except Exception as ex:  # noqa: BLE001 - aux measurement only
        import traceback

        traceback.print_exc(file=sys.stderr)
        log(f"mesh section failed (non-fatal): {ex}")

    # -- masked-semiring SpMM core (ISSUE 17): forced pull vs push vs
    # auto over the caveated mix at EVERY scale (contract-pinned) —
    # the same-revision dense-phase baseline comes from the force-mode
    # knob, not a separate checkout
    try:
        _semiring_phase(result, quick, args.tiny)
    except Exception as ex:  # noqa: BLE001 - aux measurement only
        import traceback

        traceback.print_exc(file=sys.stderr)
        log(f"semiring section failed (non-fatal): {ex}")

    # -- tiered graph storage (ISSUE 18): all-resident vs 50%-budget
    # hot-working-set p50 (gate: tools/tiered_gate.py), plus a
    # beyond-budget point with cold-start parity and miss stalls. Runs
    # at EVERY scale (contract-pinned); full runs add the
    # 100M-relationship beyond-memory point.
    try:
        _tiered_phase(result, quick, args.tiny)
    except Exception as ex:  # noqa: BLE001 - aux measurement only
        import traceback

        traceback.print_exc(file=sys.stderr)
        log(f"tiered section failed (non-fatal): {ex}")

    # -- scale-out shard scaling (ROADMAP item 4 / ISSUE 11): the same
    # tuples behind 1 vs 2 vs 4 engine groups on loopback — single-shard
    # check p50 (counter-verified no-scatter), scatter-lookup p50, mixed
    # goodput. Runs at EVERY scale including --tiny (contract-pinned).
    try:
        _shard_phase(result, quick, args.tiny)
    except Exception as ex:  # noqa: BLE001 - aux measurement only
        import traceback

        traceback.print_exc(file=sys.stderr)
        log(f"shard section failed (non-fatal): {ex}")

    # -- online shard rebalancing (ISSUE 14): goodput on non-moving
    # slices during a live 3->4 group move, paused-vs-running mover
    # windows interleaved. Runs at EVERY scale (contract-pinned).
    try:
        _rebalance_phase(result, quick, args.tiny)
    except Exception as ex:  # noqa: BLE001 - aux measurement only
        import traceback

        traceback.print_exc(file=sys.stderr)
        log(f"rebalance section failed (non-fatal): {ex}")

    # -- elastic scale-out (ISSUE 20): frontier-exchange parity on a
    # cross-namespace reference schema WITHOUT replication (boundary
    # wire bytes + rounds recorded), then an autoscaler-applied 3->2
    # shrink under load with paused-vs-running goodput windows. Runs at
    # EVERY scale including --tiny (contract-pinned).
    try:
        _autoscale_phase(result, quick, args.tiny)
    except Exception as ex:  # noqa: BLE001 - aux measurement only
        import traceback

        traceback.print_exc(file=sys.stderr)
        log(f"autoscale section failed (non-fatal): {ex}")

    # -- live schema migration (ISSUE 19): additive + rewriting targets
    # applied under a sustained check/write mix — time-to-cut, cut
    # freeze, backfill volume, and check p50 during-vs-before. Runs at
    # EVERY scale including --tiny (contract-pinned).
    try:
        _migration_phase(result, quick, args.tiny)
    except Exception as ex:  # noqa: BLE001 - aux measurement only
        import traceback

        traceback.print_exc(file=sys.stderr)
        log(f"migration section failed (non-fatal): {ex}")

    # -- open-loop trace-shaped macrobench (ROADMAP item 5) --
    # Runs at EVERY scale including --tiny: the macro result schema is
    # contract-test-pinned, and the sweep is the harness later
    # engine-scaling PRs are judged against.
    try:
        _macro_phase(result, quick, args.tiny)
    except Exception as ex:  # noqa: BLE001 - aux measurement only
        import traceback

        traceback.print_exc(file=sys.stderr)
        log(f"macro section failed (non-fatal): {ex}")

    # -- macro with a live schema migration (ISSUE 19): the SAME-SEED
    # sweep re-run with a rewriting migration (caveat attached to
    # namespace#viewer) held open across every measured point, cut at
    # the end, folded into macro.migration.knee_ratio vs the baseline
    # just recorded. Runs at EVERY scale (contract-pinned).
    try:
        if "macro" in result:
            _macro_phase(result, quick, args.tiny,
                         result_key="_macro_migration",
                         migrate_live=True)
            _fold_macro_migration(result)
    except Exception as ex:  # noqa: BLE001 - aux measurement only
        import traceback

        traceback.print_exc(file=sys.stderr)
        log(f"macro migration sub-run failed (non-fatal): {ex}")
    if not quick:
        # second scale point (full runs only): the same trace at 10k
        # namespaces, so the overlay-on/off goodput delta is recorded at
        # 2k AND 10k scale (BENCH captures whether the write-path win
        # survives a 5x larger graph)
        try:
            _macro_phase(result, quick, args.tiny,
                         result_key="macro_10k", n_ns_override=10_000)
        except Exception as ex:  # noqa: BLE001 - aux measurement only
            log(f"macro 10k scale point failed (non-fatal): {ex}")

    if args.remote_compare and not reprobe_backend(
            result, "remote-compare",
            timeout=min(args.probe_timeout, 60.0)):
        args.remote_compare = False
    if args.remote_compare:
        # remote (tcp:// packed-bitmask wire) vs in-process list filter:
        # the directive-3 acceptance measurement — the remote hot path
        # should cost ~1 loopback RTT + one constant-size bitmask frame
        # (~16KB at a bucket-padded 100k-object space) over in-process,
        # NOT a multi-MB JSON id list
        import asyncio

        from spicedb_kubeapi_proxy_tpu.engine.remote import (
            EngineServer,
            RemoteEngine,
        )

        def remote_ids(remote, u):
            # pin the MASK wire: lookup_resources_mask raises instead of
            # silently falling back to the legacy JSON id-list op, so a
            # broken mask path can never masquerade as a measurement of it
            mask, interner = remote.lookup_resources_mask(
                "pod", "view", "user", u)
            if mask is None:
                return []
            return [interner.string(i)
                    for i in np.flatnonzero(mask).tolist()
                    if i < len(interner)]

        async def remote_compare():
            srv = EngineServer(e)
            port = await srv.start()
            remote = RemoteEngine("127.0.0.1", port)
            try:
                # warm: jit + id-table sync (the one-time transfer the
                # per-request path no longer pays)
                t0 = time.perf_counter()
                ids = await asyncio.to_thread(remote_ids, remote,
                                              subjects[0])
                warm_s = time.perf_counter() - t0
                # the ACTUAL wire frame for this lookup (meta + payload)
                meta, payload = await asyncio.to_thread(
                    remote._call_any, "lookup_mask", resource_type="pod",
                    permission="view", subject_type="user",
                    subject_id=subjects[0], subject_relation=None,
                    now=None)
                frame_b = 9 + len(json.dumps(meta)) + len(payload)
                lat_r, lat_l = [], []
                for u in subjects:
                    t0 = time.perf_counter()
                    await asyncio.to_thread(remote_ids, remote, u)
                    lat_r.append((time.perf_counter() - t0) * 1e3)
                for u in subjects:
                    t0 = time.perf_counter()
                    e.lookup_resources("pod", "view", "user", u)
                    lat_l.append((time.perf_counter() - t0) * 1e3)
                return len(ids), warm_s, frame_b, lat_r, lat_l
            finally:
                remote.close()
                await srv.stop()

        try:
            n_ids, warm_s, frame_b, lat_r, lat_l = \
                asyncio.run(remote_compare())
            r50 = float(np.percentile(lat_r, 50))
            l50 = float(np.percentile(lat_l, 50))
            log(f"remote-compare: in-process p50={l50:.2f}ms, "
                f"tcp:// p50={r50:.2f}ms (delta {r50 - l50:+.2f}ms; "
                f"measured mask frame {frame_b / 1024:.1f}KB, "
                f"{n_ids} allowed ids, warm sync {warm_s * 1e3:.0f}ms)")
            result["remote_list_filter_p50_ms"] = round(r50, 3)
            result["inproc_list_filter_p50_ms"] = round(l50, 3)
            result["remote_mask_frame_kb"] = round(frame_b / 1024, 1)
        except Exception as ex:  # noqa: BLE001 - aux measurement only
            log(f"remote-compare failed (non-fatal): {ex}")

    if args.suite:
        if reprobe_backend(result, "suite",
                           timeout=min(args.probe_timeout, 60.0)):
            run_suite(quick, result)
        else:
            log("skipping suite: backend lost mid-run")


_FAILOVER_WORKER = r"""
import os, sys
peer_id, port0, port1, data_dir, repo = sys.argv[1:6]
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, repo)
import jax
jax.config.update("jax_platforms", "cpu")
from spicedb_kubeapi_proxy_tpu.engine.remote import main
sys.exit(main([
    "--peers", "127.0.0.1:%s,127.0.0.1:%s" % (port0, port1),
    "--peer-id", peer_id,
    "--bind-port", port0 if peer_id == "0" else port1,
    "--token", "bench-fo", "--engine-insecure",
    "--data-dir", data_dir, "--wal-fsync", "always",
    "--mirror-heartbeat-seconds", "0.25",
    "--failover-boot-grace", "30",
]))
"""


def _failover_phase(result: dict, quick: bool) -> None:
    """Kill-the-leader under load: two CPU engine-host subprocesses in a
    --peers replication set, a FailoverEngine client writing at a fixed
    cadence, SIGKILL on the leader, and the wall-clock until writes ack
    again. Always CPU subprocesses — the phase measures failover
    machinery, and must not contend for the chip the headline owns."""
    import shutil
    import socket as _socket
    import tempfile
    import threading as _threading

    from spicedb_kubeapi_proxy_tpu.engine import WriteOp
    from spicedb_kubeapi_proxy_tpu.engine.remote import (
        FailoverEngine,
        RemoteEngine,
    )
    from spicedb_kubeapi_proxy_tpu.models.tuples import Relationship
    from spicedb_kubeapi_proxy_tpu.utils.resilience import (
        DependencyUnavailable,
    )

    def free_port():
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    tmp = tempfile.mkdtemp(prefix="bench-failover-")
    script = os.path.join(tmp, "worker.py")
    with open(script, "w") as f:
        f.write(_FAILOVER_WORKER)
    port0, port1 = free_port(), free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(os.path.abspath(__file__))

    def boot(pid):
        return subprocess.Popen(
            [sys.executable, script, str(pid), str(port0), str(port1),
             os.path.join(tmp, f"data{pid}"), repo],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            env=env, cwd=repo)

    def leader_port(budget=90.0):
        deadline = time.monotonic() + budget
        while time.monotonic() < deadline:
            for port in (port0, port1):
                probe = RemoteEngine("127.0.0.1", port, token="bench-fo",
                                     timeout=2.0, connect_timeout=2.0,
                                     retries=0)
                try:
                    if probe.failover_state()["role"] == "leader":
                        return port
                except Exception:  # noqa: BLE001 - still booting
                    pass
                finally:
                    probe.close()
            time.sleep(0.3)
        raise RuntimeError("failover bench: no leader elected")

    procs = {0: boot(0), 1: boot(1)}
    client = None
    try:
        lport = leader_port()
        client = FailoverEngine(
            [("127.0.0.1", port0), ("127.0.0.1", port1)],
            token="bench-fo", connect_timeout=2.0, timeout=20.0,
            retries=0, probe_timeout=2.0, resolve_deadline=45.0)
        acked, failed_closed = [], [0]
        stop = _threading.Event()

        def writer():
            i = 0
            while not stop.is_set():
                try:
                    client.write_relationships([WriteOp(
                        "touch", Relationship(
                            "namespace", f"fo{i}", "creator", "user",
                            "bench", None, None))])
                    acked.append(time.monotonic())
                except (DependencyUnavailable, OSError):
                    failed_closed[0] += 1  # the proxy's 503 family
                i += 1
                time.sleep(0.02)

        t = _threading.Thread(target=writer, daemon=True)
        t.start()
        warm = 2.0 if quick else 5.0
        time.sleep(warm)
        if not acked:
            raise RuntimeError("failover bench: no writes acked pre-kill")
        pre_kill_acked = len(acked)
        victim = 0 if lport == port0 else 1
        t_kill = time.monotonic()
        procs[victim].kill()
        deadline = time.monotonic() + 60
        while (not acked or acked[-1] <= t_kill) \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        stop.set()
        t.join(30)
        post = [a for a in acked if a > t_kill]
        if not post:
            raise RuntimeError("failover bench: writes never resumed")
        ready_s = post[0] - t_kill
        log(f"leader failover: time-to-ready {ready_s * 1e3:.0f}ms after "
            f"SIGKILL ({pre_kill_acked} acks pre-kill, {len(post)} post, "
            f"{failed_closed[0]} requests failed closed in the window, "
            "0 dropped silently)")
        result["failover_time_to_ready_s"] = round(ready_s, 3)
        result["failover_requests_failed_closed"] = failed_closed[0]
        result["failover_requests_acked_post"] = len(post)
    finally:
        if client is not None:
            client.close()
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
        shutil.rmtree(tmp, ignore_errors=True)


def _admission_phase(result: dict, quick: bool) -> None:
    """Overload behavior at 2x offered load, admission ON vs OFF
    (ISSUE 5 acceptance): one storm tenant offers 10x each normal
    tenant's load; admission ON must deliver higher within-SLO goodput,
    a bounded check p99, a per-tenant fairness ratio >= 0.5, and every
    rejection accounted in admission_shed_total{class=...} with a
    Retry-After and a bounded wait (never a hang)."""
    import threading as _th

    from spicedb_kubeapi_proxy_tpu.admission import (
        BULK_CHECK,
        CHECK,
        LOOKUP_PREFILTER,
        AdmissionController,
        AdmissionRejected,
    )
    from spicedb_kubeapi_proxy_tpu.engine import CheckItem, Engine
    from spicedb_kubeapi_proxy_tpu.models import parse_schema
    from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics as _m

    rng = np.random.default_rng(7)
    n_ns, n_users = (400, 100) if quick else (2000, 400)
    schema = parse_schema("""
definition user {}
definition namespace {
  relation viewer: user
  permission view = viewer
}
""")
    cols = {k: [] for k in ("resource_type", "resource_id", "relation",
                            "subject_type", "subject_id", "subject_relation")}
    nss = np.char.add("ns", np.arange(n_ns).astype(str))
    m = 8 * n_ns
    cols["resource_type"].append(np.full(m, "namespace"))
    cols["resource_id"].append(nss[rng.integers(n_ns, size=m)])
    cols["relation"].append(np.full(m, "viewer"))
    cols["subject_type"].append(np.full(m, "user"))
    cols["subject_id"].append(
        np.char.add("u", rng.integers(n_users, size=m).astype(str)))
    cols["subject_relation"].append(np.full(m, ""))
    e = Engine(schema=schema)
    e.bulk_load({k: np.concatenate(v) for k, v in cols.items()})

    def op_check(i):
        e.check_bulk([CheckItem("namespace", f"ns{i % n_ns}", "view",
                                "user", f"u{i % n_users}")])

    def op_bulk(i):
        e.check_bulk([CheckItem("namespace", f"ns{(i + j) % n_ns}", "view",
                                "user", f"u{i % n_users}")
                      for j in range(32)])

    def op_lookup(i):
        e.lookup_resources_mask("namespace", "view", "user",
                                f"u{i % n_users}")

    # 70% checks / 15% bulk checks / 15% list lookups
    ops = ([(CHECK, op_check)] * 14 + [(BULK_CHECK, op_bulk)] * 3
           + [(LOOKUP_PREFILTER, op_lookup)] * 3)
    op_check(0), op_bulk(0), op_lookup(0)  # warm all three jit shapes

    # -- capacity probe: closed loop, then offer 2x of it --------------------
    def closed_loop(dur: float, nthreads: int = 8):
        stop = time.perf_counter() + dur
        lat: list = []
        lock = _th.Lock()

        def worker(w):
            i = w
            while time.perf_counter() < stop:
                cls, op = ops[i % len(ops)]
                t0 = time.perf_counter()
                op(i)
                with lock:
                    lat.append((cls.name, time.perf_counter() - t0))
                i += nthreads

        ts = [_th.Thread(target=worker, args=(w,)) for w in range(nthreads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        return len(lat) / dur, lat

    # The load is CLOSED-LOOP per tenant (each thread issues its next
    # request as soon as the previous completes): the overload factor is
    # then structural — 28 worker threads against a knee measured at 8 —
    # instead of riding a rate estimate that a noisy shared host skews
    # several-fold between windows. The storm tenant runs 20 threads vs
    # 2 per normal tenant: 10x the offered load of each of the rest.
    closed_loop(0.4)  # settle: background index build, jit caches
    cap_rps, base_lat = closed_loop(1.0 if quick else 1.5)
    checks = sorted(dt for c, dt in base_lat if c == "check") or [0.005]
    base_p50 = checks[len(checks) // 2]
    slo = max(0.05, 4 * base_p50)
    n_normal = 4
    tenants = [(f"tenant{i}", 2) for i in range(n_normal)]
    tenants.append(("storm", 20))
    n_threads = sum(k for _, k in tenants)
    log(f"[admission] capacity ~{cap_rps:.0f} req/s at 8 threads, SLO "
        f"{slo * 1e3:.0f}ms; overload = {n_threads} closed-loop threads "
        "(storm tenant at 10x the rest)")

    avg_weight = sum(c.weight for c, _ in ops) / len(ops)
    unit_cap = cap_rps * avg_weight
    fair_share = unit_cap / len(tenants)  # cost units/s per tenant

    def run(ctrl, dur: float):
        start = time.perf_counter()
        stop_at = start + dur
        lock = _th.Lock()
        stats = {name: {"good": 0, "done": 0, "shed": 0}
                 for name, _ in tenants}
        lat_by_class: dict = {}
        shed_waits: list = []
        retry_after_missing = [0]

        def tenant_worker(name, seed):
            n = seed
            while time.perf_counter() < stop_at:
                cls, op = ops[n % len(ops)]
                n += 1
                t0 = time.perf_counter()
                try:
                    ticket = ctrl.acquire(name, cls) if ctrl else None
                    try:
                        op(n)
                    finally:
                        if ticket is not None:
                            ticket.release()
                    dt = time.perf_counter() - t0
                    with lock:
                        stats[name]["done"] += 1
                        if dt <= slo:
                            stats[name]["good"] += 1
                        lat_by_class.setdefault(cls.name, []).append(dt)
                except AdmissionRejected as ex:
                    wait = time.perf_counter() - t0
                    with lock:
                        stats[name]["shed"] += 1
                        shed_waits.append(wait)
                        if not ex.retry_after or ex.retry_after <= 0:
                            retry_after_missing[0] += 1

        threads = [_th.Thread(target=tenant_worker, args=(name, w * 37))
                   for name, k in tenants for w in range(k)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - start
        return stats, lat_by_class, shed_waits, retry_after_missing[0], wall

    def summarize(label, stats, lat_by_class, wall):
        good = sum(s["good"] for s in stats.values())
        per_tenant = [s["good"] for s in stats.values()]
        # fairness ratio: min/max of per-tenant COMPLETED service (the
        # share of engine time each tenant received). Every tenant is
        # backlogged (closed loop), so a fair scheduler serves them
        # near-equally (ratio -> 1) while an unguarded dispatch pool
        # serves them by thread count (ratio -> 2/20). Within-SLO
        # attainment is the goodput/p99 story, reported separately — a
        # storm whose requests wait longer is the scheduler WORKING
        per_done = [s["done"] for s in stats.values()]
        fairness = (min(per_done) / max(per_done)) \
            if max(per_done) else 0.0
        cl = sorted(lat_by_class.get("check", [0.0]))
        p99 = cl[min(len(cl) - 1, int(len(cl) * 0.99))] * 1e3
        shed = sum(s["shed"] for s in stats.values())
        offered = sum(s["done"] + s["shed"] for s in stats.values()) / wall
        log(f"[admission {label}] goodput={good / wall:.0f}/s of "
            f"{offered:.0f}/s offered (SLO {slo * 1e3:.0f}ms), "
            f"check p99={p99:.1f}ms, fairness={fairness:.2f} "
            f"(per-tenant good {per_tenant}, done "
            f"{[s['done'] for s in stats.values()]}), shed={shed}")
        return good / wall, p99, fairness, shed, offered

    dur = 2.5 if quick else 5.0
    shed_before = sum(
        _m.counter("admission_shed_total", **{"class": c}).value
        for c in ("check", "bulk-check", "lookup-prefilter",
                  "watch-recompute", "write-dtx"))
    # the limit stays CLAMPED near the closed-loop knee (the capacity
    # probe ran 8 threads, so ~8 ops of average weight saturate the
    # engine): under 2x offered load the queue is then never empty, every
    # grant goes through the fair scheduler, and admitted ops run near
    # baseline latency instead of contending 24-wide
    # decay SLOWER than the fair share and cap high: the capacity
    # estimate is noisy on a shared CPU host, and a too-generous refill
    # would zero every tenant's debt (collapsing the fair order to FIFO,
    # which the storm wins by volume). Low decay only lengthens the
    # storm's memory — ordering is work-conserving, so it never idles
    # capacity
    ctrl = AdmissionController(
        initial_concurrency=16.0, min_concurrency=8.0,
        max_concurrency=48.0,
        tenant_rate=fair_share / 4, tenant_burst=unit_cap * 2,
        tenant_depth=32, global_depth=128,
        queue_timeout=max(0.05, slo * 0.5))
    stage0 = _stage_snapshot()
    stats_on, lat_on, shed_waits, ra_missing, wall_on = run(ctrl, dur)
    _record_stage_breakdown(result, "admission_stages", stage0)
    good_on, p99_on, fair_on, shed_on, offered_on = summarize(
        "ON", stats_on, lat_on, wall_on)
    shed_after = sum(
        _m.counter("admission_shed_total", **{"class": c}).value
        for c in ("check", "bulk-check", "lookup-prefilter",
                  "watch-recompute", "write-dtx"))

    stats_off, lat_off, _, _, wall_off = run(None, dur)
    good_off, p99_off, fair_off, _, _ = summarize(
        "OFF", stats_off, lat_off, wall_off)

    max_wait = max(shed_waits) * 1e3 if shed_waits else 0.0
    accounted = int(shed_after - shed_before) == shed_on
    log(f"[admission] shed accounting: metric delta "
        f"{int(shed_after - shed_before)} vs {shed_on} client rejections "
        f"({'OK' if accounted else 'MISMATCH'}); max shed wait "
        f"{max_wait:.0f}ms; {ra_missing} rejections lacked Retry-After")
    result["admission_capacity_rps"] = round(cap_rps)
    result["admission_offered_rps"] = round(offered_on)
    result["admission_slo_ms"] = round(slo * 1e3, 1)
    result["admission_goodput_on"] = round(good_on, 1)
    result["admission_goodput_off"] = round(good_off, 1)
    result["admission_check_p99_ms_on"] = round(p99_on, 2)
    result["admission_check_p99_ms_off"] = round(p99_off, 2)
    result["admission_fairness_on"] = round(fair_on, 3)
    result["admission_fairness_off"] = round(fair_off, 3)
    result["admission_shed"] = shed_on
    result["admission_shed_accounted"] = accounted
    result["admission_max_shed_wait_ms"] = round(max_wait, 1)


_MACRO_SCHEMA = """
definition user {}
definition group {
  relation member: user
}
definition namespace {
  relation viewer: user | user:* | group#member
  permission view = viewer
}
"""

# The macro migration target (ISSUE 19): _MACRO_SCHEMA with a caveat
# attached to the live namespace#viewer relation — a REWRITING change
# whose affected closure is every stored viewer grant, so the in-sweep
# backfill and dual window carry real volume.
_MACRO_MIG_SCHEMA = _MACRO_SCHEMA.replace(
    "definition user {}",
    "caveat macro_probation(level int) {\n"
    "  level < 3\n"
    "}\n\n"
    "definition user {}").replace(
    "  relation viewer: user | user:* | group#member\n",
    "  relation viewer: user | user:* | group#member"
    " | user with macro_probation\n")

_MACRO_RULES = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata:
  name: macro-ns-list-watch
match:
  - apiVersion: v1
    resource: namespaces
    verbs: [list, watch]
prefilter:
  - fromObjectIDNameExpr: "{{resourceId}}"
    lookupMatchingResources:
      tpl: "namespace:$#view@user:{{user.name}}"
"""


class _WatchStreamHarness:
    """Concurrent watch streams through the fused watch hub, drivable
    from loadgen worker THREADS: the hub and its watchers live on a
    dedicated asyncio loop thread (the serving shape — the proxy's hub
    runs on its event loop while engine work happens on executors).
    ``open()`` registers one more stream; beyond ``max_streams`` the
    oldest is recycled so a storm holds a bounded high-water population
    instead of leaking forever."""

    def __init__(self, engine, max_streams: int):
        import asyncio

        from spicedb_kubeapi_proxy_tpu.authz.watchhub import WatchHub
        from spicedb_kubeapi_proxy_tpu.proxy.requestinfo import (
            parse_request_info,
        )
        from spicedb_kubeapi_proxy_tpu.rules.input import (
            ResolveInput,
            UserInfo,
        )
        from spicedb_kubeapi_proxy_tpu.rules.matcher import (
            MapMatcher,
            RequestMeta,
        )

        self.max_streams = max_streams
        self.opened = 0
        self._handles: list = []
        self._info = parse_request_info("GET", "/api/v1/namespaces",
                                        {"watch": ["true"]})
        matcher = MapMatcher.from_yaml(_MACRO_RULES)
        rules = matcher.match(RequestMeta.from_request(self._info))
        self._pf = next(p for r in rules for p in r.pre_filters)
        self._ResolveInput, self._UserInfo = ResolveInput, UserInfo
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, name="macro-watch-loop",
            daemon=True)
        self._thread.start()
        self.hub = WatchHub(engine, poll_interval=0.02)

    def open(self, user: str, timeout: float = 10.0) -> None:
        import asyncio

        fut = asyncio.run_coroutine_threadsafe(self._open(user),
                                               self._loop)
        fut.result(timeout=timeout)
        self.opened += 1

    async def _open(self, user: str) -> None:
        input = self._ResolveInput.create(
            self._info, self._UserInfo(name=user))
        handle = await self.hub.register(self._pf, input)
        self._handles.append(handle)
        if len(self._handles) > self.max_streams:
            await self.hub.unregister(self._handles.pop(0))

    @property
    def live_streams(self) -> int:
        return len(self._handles)

    def close(self) -> None:
        import asyncio

        async def teardown():
            for h in self._handles:
                try:
                    await self.hub.unregister(h)
                except Exception:  # noqa: BLE001 - teardown best-effort
                    pass
            self._handles.clear()

        try:
            asyncio.run_coroutine_threadsafe(
                teardown(), self._loop).result(timeout=15)
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass

        async def cancel_stragglers():
            # the hub's pump/source tasks wind down via unregister, but
            # an in-flight wait may still be parked: cancel whatever is
            # left so stopping the loop doesn't warn about pending tasks
            me = asyncio.current_task()
            rest = [t for t in asyncio.all_tasks() if t is not me]
            for t in rest:
                t.cancel()
            await asyncio.gather(*rest, return_exceptions=True)

        try:
            asyncio.run_coroutine_threadsafe(
                cancel_stragglers(), self._loop).result(timeout=5)
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)
        if not self._thread.is_alive():
            self._loop.close()  # release the selector/self-pipe fds


def _caveat_phase(result: dict, quick: bool) -> None:
    """Conditional grants (ISSUE 9): a caveated-mix graph — 30% of the
    viewer tuples carry an IP-allowlist caveat — measured for cold and
    warm (decision-cached) bulk-check p50 WITH a satisfying request
    context, WITHOUT context (missing-context fail-closed denies), and
    against the uncaveated baseline. The acceptance bar is the
    caveated/uncaveated cold ratio (the caveat VM rides the same
    dispatch as the fixpoint, so it should be well under 1.5x)."""
    from spicedb_kubeapi_proxy_tpu.engine import CheckItem, Engine
    from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

    n_docs = 256 if quick else 2048
    share = 0.3
    n_cav = int(n_docs * share)
    e = Engine(bootstrap="""
schema: |-
  caveat ip_allowlist(ip ipaddress, allowed list<ipaddress>) {
    ip in allowed
  }
  definition user {}
  definition doc {
    relation viewer: user | user with ip_allowlist
    permission view = viewer
  }
relationships: ""
""")
    names = np.char.add("d", np.arange(n_docs).astype(str))
    ctx_json = '{"allowed":["10.0.0.0/8","192.168.0.0/16"]}'
    e.bulk_load({
        "resource_type": np.full(n_docs, "doc"),
        "resource_id": names,
        "relation": np.full(n_docs, "viewer"),
        "subject_type": np.full(n_docs, "user"),
        "subject_id": np.full(n_docs, "alice"),
        "caveat": np.where(np.arange(n_docs) < n_cav,
                           "ip_allowlist", ""),
        "caveat_context": np.where(np.arange(n_docs) < n_cav,
                                   ctx_json, ""),
    })
    items_cav = [CheckItem("doc", f"d{i}", "view", "user", "alice")
                 for i in range(n_cav)]
    items_unc = [CheckItem("doc", f"d{i}", "view", "user", "alice")
                 for i in range(n_cav, 2 * n_cav)]
    req_ctx = {"ip": "10.1.2.3"}
    # correctness spot check + jit warmup (compiles happen HERE, not in
    # the timed loops)
    assert all(e.check_bulk(items_cav, context=req_ctx))
    assert all(e.check_bulk(items_unc))
    miss0 = metrics.counter(
        "engine_caveat_denied_missing_context_total").value
    assert not any(e.check_bulk(items_cav))  # missing ctx: fail closed
    denied_missing = metrics.counter(
        "engine_caveat_denied_missing_context_total").value - miss0

    def p50(fn, trials=9):
        lat = []
        for _ in range(trials):
            t0 = time.perf_counter()
            fn()
            lat.append((time.perf_counter() - t0) * 1e3)
        return float(np.percentile(lat, 50))

    cold_unc = p50(lambda: e.check_bulk(items_unc))
    cold_ctx = p50(lambda: e.check_bulk(items_cav, context=req_ctx))
    cold_noctx = p50(lambda: e.check_bulk(items_cav))
    e.enable_decision_cache()
    e.check_bulk(items_cav, context=req_ctx)  # prime
    e.check_bulk(items_unc)
    warm_ctx = p50(lambda: e.check_bulk(items_cav, context=req_ctx))
    warm_unc = p50(lambda: e.check_bulk(items_unc))
    e.disable_decision_cache()
    ratio = cold_ctx / max(cold_unc, 1e-9)
    result["caveats"] = {
        "n_tuples": int(n_docs),
        "caveated_share": share,
        "check_p50_uncaveated_ms": round(cold_unc, 3),
        "check_p50_caveated_ctx_ms": round(cold_ctx, 3),
        "check_p50_caveated_noctx_ms": round(cold_noctx, 3),
        "warm_p50_caveated_ctx_ms": round(warm_ctx, 4),
        "warm_p50_uncaveated_ms": round(warm_unc, 4),
        "caveated_over_uncaveated": round(ratio, 3),
        "missing_context_denials": int(denied_missing),
    }
    log(f"caveat mix: {n_docs} tuples ({share:.0%} caveated) "
        f"cold ctx p50 {cold_ctx:.2f}ms vs uncaveated {cold_unc:.2f}ms "
        f"(ratio {ratio:.2f}x), warm ctx {warm_ctx:.3f}ms")


def _mesh_phase(result: dict, quick: bool, tiny: bool) -> None:
    """Mesh-native hot path (ISSUE 15): the caveated-mix graph served
    through ``Engine(mesh=...)`` at 1 vs 2 vs 8 devices (whatever the
    host actually has — CPU CI forces 8 virtual devices via
    ``XLA_FLAGS=--xla_force_host_platform_device_count=8``; a bare
    CPU-only host measures its single device and labels the topology,
    riding the run-level ``[DEGRADED: cpu]`` convention instead of
    re-probing hardware). Per device count: list-filter p50 WITH request
    context, the K-step fused fixpoint's convergence-collective count
    (vs the single-device iteration count = the pre-fuse per-hop
    collectives), and a steady-churn window (caveated + plain touches,
    reused contexts) that must stay recompile-free on the resident
    shards. ``engine_caveat_mesh_fallback_total`` must not move: the
    caveat VM runs INSIDE the shard_map body now."""
    import jax

    from spicedb_kubeapi_proxy_tpu.engine.store import WriteOp
    from spicedb_kubeapi_proxy_tpu.models.tuples import Relationship
    from spicedb_kubeapi_proxy_tpu.parallel import make_mesh
    from spicedb_kubeapi_proxy_tpu.parallel.mesh import mesh_topology
    from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

    devs = jax.devices()
    counts = [c for c in (1, 2, 8) if c <= len(devs)]
    if tiny:
        n_pods, n_users, n_ns, n_groups, n_rels = 200, 100, 10, 10, 3_000
        trials, churn = 3, 3
    elif quick:
        n_pods, n_users, n_ns, n_groups, n_rels = (
            2_000, 500, 50, 50, 50_000)
        trials, churn = 5, 4
    else:
        # ROADMAP item 1's scale point: the headline 100k-pod / 10M-rel
        # build itself, with the caveated mix — measured, not claimed
        n_pods, n_users, n_ns, n_groups, n_rels = (
            100_000, 10_000, 1_000, 1_000, 10_000_000)
        trials, churn = 9, 6
    share = 0.3
    e, total = build_engine(n_pods, n_users, n_ns, n_groups, n_rels,
                            seed=2, cav_share=share, schema=MESH_SCHEMA)
    rng = np.random.default_rng(5)
    req_ctx = {"ip": "10.1.2.3"}
    cg = e.compiled()
    assert cg.caveats is not None and cg.caveats.metas, \
        "mesh phase needs a caveated graph"
    objs = e._objects_by_name()
    u0 = f"u{int(rng.integers(n_users))}"
    off = cg.offset_of("pod", "view")
    nq = cg.type_sizes["pod"]
    seeds = np.asarray([cg.encode_subject("user", u0, None, objs)],
                       dtype=np.int32)
    qs = off + np.arange(nq, dtype=np.int32)
    qb = np.zeros(nq, dtype=np.int32)
    fut = cg.query_async(seeds, qs, qb, context=req_ctx)
    fut.result()
    # the pre-fuse baseline at build (informational; each device-count
    # point re-measures against ITS revision — churn can add hops)
    iters_single = fut.iterations()

    fb0 = metrics.counter("engine_caveat_mesh_fallback_total").value
    points = {}
    for c in counts:
        mesh = make_mesh(c, devices=devs[:c])
        topo = mesh_topology(mesh)
        e.mesh = mesh
        e._sharded = None
        # warm: sharded build + shard_map jit compile + grid cache
        e.lookup_resources_mask("pod", "view", "user", u0,
                                context=req_ctx)
        # one write->read pair OUTSIDE the churn window: the first write
        # after bulk_load pays the store-index build and its read the
        # one unavoidable full recompile (bulk-loaded history isn't in
        # the watch log), plus the first overlay-append scatter compile
        e.write_relationships([WriteOp("touch", Relationship(
            "pod", f"ns/p{int(rng.integers(n_pods))}", "viewer",
            "user", f"u{int(rng.integers(n_users))}", None, None,
            "ip_allowlist", MESH_CTXS[0]))])
        e.lookup_resources_mask("pod", "view", "user", u0,
                                context=req_ctx)
        lat = []
        for _ in range(trials):
            u = f"u{int(rng.integers(n_users))}"
            t0 = time.perf_counter()
            e.lookup_resources_mask("pod", "view", "user", u,
                                    context=req_ctx)
            lat.append((time.perf_counter() - t0) * 1e3)
        p50 = float(np.percentile(lat, 50))
        # the one-per-hop baseline is re-measured at the SAME revision
        # the mesh conv-check query reads: the warm writes above may
        # have advanced the graph (a touch can extend the group chain
        # by a hop), and a stale pre-write baseline would undercount —
        # flaking the relative pin instead of measuring the reduction
        cg_now = e.compiled()
        objs_now = e._objects_by_name()
        seeds_now = np.asarray(
            [cg_now.encode_subject("user", u0, None, objs_now)],
            dtype=np.int32)
        off_now = cg_now.offset_of("pod", "view")
        nq_now = cg_now.type_sizes["pod"]
        qs_now = off_now + np.arange(nq_now, dtype=np.int32)
        qb_now = np.zeros(nq_now, dtype=np.int32)
        sfut = cg_now.query_async(seeds_now, qs_now, qb_now,
                                  context=req_ctx)
        sfut.result()
        iters_pt = sfut.iterations()
        sg = e._backend(cg_now)
        qf = sg.query_async(seeds_now, qs_now, qb_now, context=req_ctx)
        qf.result()
        checks = qf.conv_checks()
        # steady churn: caveated (reused stored contexts) + plain
        # touches with a fully-consistent mesh read after each — the
        # resident shards absorb everything (zero graph recompiles)
        compiles0 = metrics.counter("engine_graph_compiles_total").value
        upd0 = metrics.counter("engine_sharded_updates_total").value
        for i in range(churn):
            cav = i % 2 == 0
            e.write_relationships([WriteOp("touch", Relationship(
                "pod", f"ns/p{int(rng.integers(n_pods))}", "viewer",
                "user", f"u{int(rng.integers(n_users))}", None, None,
                "ip_allowlist" if cav else None,
                MESH_CTXS[i % len(MESH_CTXS)] if cav else None))])
            e.lookup_resources_mask("pod", "view", "user", u0,
                                    context=req_ctx)
        recompiles = int(metrics.counter(
            "engine_graph_compiles_total").value - compiles0)
        updates = int(metrics.counter(
            "engine_sharded_updates_total").value - upd0)
        points[str(c)] = {
            "devices": topo["devices"],
            "data": topo["data"],
            "graph": topo["graph"],
            "platform": topo["platform"],
            "list_p50_ms": round(p50, 3),
            "k_steps": int(sg.k_steps),
            "conv_checks": int(checks),
            "conv_checks_before": int(iters_pt),
            "churn_recompiles": recompiles,
            "churn_sharded_updates": updates,
        }
        log(f"mesh {c}d (data={mesh.shape['data']},"
            f"graph={mesh.shape['graph']}): list p50 {p50:.2f}ms, "
            f"conv collectives {checks} (K={sg.k_steps}; one-per-hop "
            f"baseline {iters_pt}), churn recompiles {recompiles}, "
            f"sharded updates {updates}")
    e.mesh = None
    e._sharded = None
    fallbacks = int(metrics.counter(
        "engine_caveat_mesh_fallback_total").value - fb0)
    result["mesh"] = {
        "backend": result.get("backend"),
        "devices_available": len(devs),
        "device_counts": counts,
        "n_pods": n_pods,
        "n_rels": total,
        "caveated_share": share,
        "fixpoint_iters_single": int(iters_single),
        "caveat_mesh_fallbacks": fallbacks,
        "points": points,
    }
    log(f"mesh phase: {total} rels ({share:.0%} caveated), device axis "
        f"{counts}, caveat mesh fallbacks {fallbacks}")


def _semiring_phase(result: dict, quick: bool, tiny: bool) -> None:
    """Masked-semiring SpMM core (ISSUE 17): the caveated-mix graph's
    dense phase measured under every mode of the one propagation
    primitive — forced ``pull`` (the pre-semiring dense baseline, SAME
    revision via the force-mode knob), forced ``push`` (bit-packed
    contraction), and ``auto`` (the occupancy-switched ``lax.cond``).
    Per mode: bulk-check p50, list-filter p50, and the per-iteration
    push-vs-pull choices the fixpoint actually made (``push_steps`` out
    of ``iterations``). A second section pins the Pallas-vs-lax delta
    on the forced-pull dense path by flipping the ``SemiringDenseKernel``
    gate between freshly-traced dispatches; on a CPU host the MXU kernel
    never engages (both sides are the lax fallback), so the point is
    recorded with the run-level ``[DEGRADED: cpu]`` provenance instead
    of a fabricated speedup."""
    import jax

    from spicedb_kubeapi_proxy_tpu.engine import CheckItem
    from spicedb_kubeapi_proxy_tpu.ops import bitprop, semiring
    from spicedb_kubeapi_proxy_tpu.utils.features import features
    from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

    if tiny:
        n_pods, n_users, n_ns, n_groups, n_rels = 200, 100, 10, 10, 3_000
        trials, n_checks = 3, 64
    elif quick:
        n_pods, n_users, n_ns, n_groups, n_rels = (
            2_000, 500, 50, 50, 50_000)
        trials, n_checks = 5, 512
    else:
        n_pods, n_users, n_ns, n_groups, n_rels = (
            100_000, 10_000, 1_000, 1_000, 10_000_000)
        trials, n_checks = 9, 2048
    share = 0.3
    e, total = build_engine(n_pods, n_users, n_ns, n_groups, n_rels,
                            seed=3, cav_share=share, schema=MESH_SCHEMA)
    rng = np.random.default_rng(7)
    req_ctx = {"ip": "10.1.2.3"}
    items = [CheckItem("pod", f"ns/p{int(p)}", "view", "user", f"u{int(u)}")
             for p, u in zip(rng.integers(n_pods, size=n_checks),
                             rng.integers(n_users, size=n_checks))]
    u0 = f"u{int(rng.integers(n_users))}"
    cg = e.compiled()
    objs = e._objects_by_name()
    seeds = np.asarray([cg.encode_subject("user", u0, None, objs)],
                       dtype=np.int32)
    off = cg.offset_of("pod", "view")
    nq = cg.type_sizes["pod"]
    qs = off + np.arange(nq, dtype=np.int32)
    qb = np.zeros(nq, dtype=np.int32)

    def p50(fn, n=trials):
        lat = []
        for _ in range(n):
            t0 = time.perf_counter()
            fn()
            lat.append((time.perf_counter() - t0) * 1e3)
        return float(np.percentile(lat, 50))

    def list_once():
        e.lookup_resources_mask("pod", "view", "user", u0,
                                context=req_ctx)

    modes = {}
    for mode in ("pull", "push", "auto"):
        with semiring.force_mode(mode):
            # warm: the per-mode jitted run entry compiles HERE
            e.check_bulk(items, context=req_ctx)
            list_once()
            check_p50 = p50(lambda: e.check_bulk(items, context=req_ctx))
            list_p50 = p50(list_once)
            # a direct dispatch exposes the per-iteration mode choices
            fut = cg.query_async(seeds, qs, qb, context=req_ctx)
            fut.result()
            iters = int(fut.iterations())
            push = int(fut.push_steps())
            modes[mode] = {
                "check_p50_ms": round(check_p50, 3),
                "list_p50_ms": round(list_p50, 3),
                "iterations": iters,
                "push_steps": push,
                "pull_steps": int(max(iters - push, 0)),
            }
            log(f"semiring {mode}: check p50 {check_p50:.2f}ms, "
                f"list p50 {list_p50:.2f}ms, "
                f"steps push={push}/pull={max(iters - push, 0)} "
                f"of {iters}")
    degraded = jax.default_backend() not in _TPU_PLATFORMS
    # mode correctness spot check rides the bench too: the three forced
    # modes must answer bulk-check identically on this revision
    with semiring.force_mode("pull"):
        want = e.check_bulk(items, context=req_ctx)
    for m in ("push", "auto"):
        with semiring.force_mode(m):
            assert e.check_bulk(items, context=req_ctx) == want, m
    # Pallas-vs-lax on the forced-pull dense path: drop the cached
    # per-mode run entry so each side re-traces under its gate state
    d = cg._dev()

    def fresh_pull_p50():
        d.pop(("run", "pull"), None)
        with semiring.force_mode("pull"):
            list_once()  # compile
            return p50(list_once)

    pallas_engaged = bool(bitprop.dense_kernel_enabled())
    lat_kernel = fresh_pull_p50()
    features.set("SemiringDenseKernel", False)
    try:
        lat_lax = fresh_pull_p50()
    finally:
        features.reset()
        d.pop(("run", "pull"), None)
    pallas_delta = lat_lax / max(lat_kernel, 1e-9)
    base = modes["pull"]
    speedup_push = base["check_p50_ms"] / max(modes["push"]["check_p50_ms"],
                                              1e-9)
    speedup_auto = base["check_p50_ms"] / max(modes["auto"]["check_p50_ms"],
                                              1e-9)
    result["semiring"] = {
        "backend": result.get("backend"),
        "n_pods": n_pods,
        "n_rels": total,
        "caveated_share": share,
        "bulk_checks": n_checks,
        "crossover": float(getattr(cg, "spmm_crossover", 1.0)),
        # registry view of the same dispatch telemetry: the published
        # crossover gauge plus the cumulative per-dispatch mode choices
        # (engine._note_fixpoint_telemetry feeds these counters)
        "crossover_gauge": float(
            metrics.gauge("engine_semiring_crossover").value),
        "push_steps_total": int(metrics.counter(
            "engine_semiring_push_steps_total").value),
        "pull_steps_total": int(metrics.counter(
            "engine_semiring_pull_steps_total").value),
        "modes": modes,
        "dense_speedup_push_vs_pull": round(speedup_push, 3),
        "dense_speedup_auto_vs_pull": round(speedup_auto, 3),
        "pallas_engaged": pallas_engaged,
        "pallas_list_p50_ms": round(lat_kernel, 3),
        "lax_list_p50_ms": round(lat_lax, 3),
        "pallas_over_lax": round(pallas_delta, 3),
        "provenance": "[DEGRADED: cpu]" if degraded else "tpu",
    }
    log(f"semiring phase: {total} rels, dense-phase speedup "
        f"push {speedup_push:.2f}x / auto {speedup_auto:.2f}x vs forced "
        f"pull, pallas/lax {pallas_delta:.2f}x "
        f"(kernel {'on' if pallas_engaged else 'off — lax both sides'})"
        + (" [DEGRADED: cpu]" if degraded else ""))


def _tiered_phase(result: dict, quick: bool, tiny: bool) -> None:
    """Tiered graph storage (ISSUE 18): the same graph measured
    all-resident and then under a device budget of ~50% of its dense
    block bytes (storage/tiers.py). The hot working set — repeated
    pod.view traffic — streams in on first demand, gets admitted, and
    steady-state p50 is pinned against the all-resident baseline
    (tools/tiered_gate.py enforces the <= 1.3x ratio in bench-smoke).
    A second, beyond-budget point shrinks the budget far below the
    working set so every dispatch pays the miss-stall path: cold-start
    latency, oracle parity, and a non-empty
    ``engine_tier_miss_stall_seconds`` histogram are recorded. Full
    runs add the 100M-relationship point — a graph whose dense blocks
    exceed any realistic single-device budget — at the same schema.
    On a CPU host the 'device' tier is host RAM too, so the point is
    recorded with the run-level ``[DEGRADED: cpu]`` provenance."""
    import jax

    import spicedb_kubeapi_proxy_tpu.ops.reachability as reach
    from spicedb_kubeapi_proxy_tpu.engine import CheckItem
    from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

    if tiny:
        n_pods, n_users, n_ns, n_groups, n_rels = 200, 100, 10, 10, 3_000
        trials, n_checks = 3, 64
    elif quick:
        n_pods, n_users, n_ns, n_groups, n_rels = (
            2_000, 500, 50, 50, 50_000)
        trials, n_checks = 5, 256
    else:
        n_pods, n_users, n_ns, n_groups, n_rels = (
            100_000, 10_000, 1_000, 1_000, 10_000_000)
        trials, n_checks = 9, 1024
    e, total = build_engine(n_pods, n_users, n_ns, n_groups, n_rels,
                            seed=5)
    rng = np.random.default_rng(13)
    # the HOT working set: repeated pod.view checks over a confined pod
    # slice — demand closure activates only the blocks this traffic can
    # reach, so the rest of the graph never earns device bytes
    hot_pods = rng.integers(max(n_pods // 4, 1), size=n_checks)
    hot_users = rng.integers(n_users, size=n_checks)
    items = [CheckItem("pod", f"ns/p{int(p)}", "view", "user", f"u{int(u)}")
             for p, u in zip(hot_pods, hot_users)]

    def p50(fn, n=trials):
        lat = []
        for _ in range(n):
            t0 = time.perf_counter()
            fn()
            lat.append((time.perf_counter() - t0) * 1e3)
        return float(np.percentile(lat, 50))

    # all-resident baseline — SAME revision, classic placement
    want = e.check_bulk(items)  # warm + oracle answers
    resident_p50 = p50(lambda: e.check_bulk(items))
    cg = e.compiled()

    def stall_count():
        snap = metrics.hist_snapshot("engine_tier_miss_stall_seconds")
        return int(sum(snap["counts"])) if snap else 0

    # size the budget off the real per-block footprint: enable with an
    # unbounded budget once to take the census AND measure the hot
    # working set (one warm pass admits exactly the demanded blocks),
    # then re-enable at ~50% of the graph — floored at the working set
    # so the hot slice genuinely fits (block granularity can make one
    # block the whole graph at small scales)
    census = cg.enable_tiering(budget_bytes=1 << 62)
    graph_bytes = census.total_bytes()
    e.check_bulk(items)
    demand_bytes = census.hot_bytes()
    from spicedb_kubeapi_proxy_tpu.storage.tiers import HEADROOM
    budget = max(graph_bytes // 2, int(demand_bytes / HEADROOM) + 1)
    tier = cg.enable_tiering(budget_bytes=budget)
    stalls0 = stall_count()
    t0 = time.perf_counter()
    got = e.check_bulk(items)  # cold start: demand-misses stream in
    cold_ms = (time.perf_counter() - t0) * 1e3
    parity_ok = bool(got == want)
    e.check_bulk(items)  # steady state from here: hot set admitted
    builds0 = reach._TRACE_BUILDS
    tiered_p50 = p50(lambda: e.check_bulk(items))
    zero_recompiles = bool(reach._TRACE_BUILDS == builds0)
    ratio = tiered_p50 / max(resident_p50, 1e-9)
    st = tier.stats()
    tier.publish_gauges()
    log(f"tiered: graph {graph_bytes}B, budget {budget}B, resident p50 "
        f"{resident_p50:.2f}ms, tiered p50 {tiered_p50:.2f}ms "
        f"({ratio:.2f}x), cold start {cold_ms:.1f}ms, "
        f"hot {st['hot_blocks']}/{st['blocks']} blocks, "
        f"recompiles={'none' if zero_recompiles else 'SOME'}")

    def beyond_point(engine, bb_items, bb_budget, bb_rels):
        """One beyond-budget sample: budget far under the working set,
        so the cold start AND steady traffic pay miss stalls."""
        bb_want = engine.check_bulk(bb_items)  # oracle before tiering
        cgx = engine.compiled()
        cgx.enable_tiering(budget_bytes=bb_budget)
        s0 = stall_count()
        tb = time.perf_counter()
        bb_got = engine.check_bulk(bb_items)
        bb_cold = (time.perf_counter() - tb) * 1e3
        engine.check_bulk(bb_items)  # steady point still streams
        return {
            "budget_bytes": int(bb_budget),
            "n_rels": int(bb_rels),
            "cold_start_ms": round(bb_cold, 3),
            "parity_ok": bool(bb_got == bb_want),
            "miss_stalls": stall_count() - s0,
        }

    if tiny or quick:
        beyond = beyond_point(e, items, max(graph_bytes // 100, 1), total)
    else:
        # the 100M-relationship point: dense blocks beyond any single
        # device's budget — a fresh engine so the headline numbers above
        # stay uncontaminated by its footprint
        be, btotal = build_engine(1_000_000, 100_000, 10_000, 10_000,
                                  100_000_000, seed=6)
        bb_items = [CheckItem("pod", f"ns/p{int(p)}", "view", "user",
                              f"u{int(u)}")
                    for p, u in zip(rng.integers(1_000_000, size=n_checks),
                                    rng.integers(100_000, size=n_checks))]
        be.check_bulk(bb_items)  # compile before the census
        bcg = be.compiled()
        bcg.enable_tiering(budget_bytes=1 << 62)
        bgb = bcg.tier.total_bytes()
        beyond = beyond_point(be, bb_items, max(bgb // 100, 1), btotal)
    log(f"tiered beyond-budget: cold start {beyond['cold_start_ms']:.1f}ms"
        f" over {beyond['n_rels']} rels, {beyond['miss_stalls']} miss "
        f"stalls, parity {'ok' if beyond['parity_ok'] else 'BROKEN'}")

    degraded = jax.default_backend() not in _TPU_PLATFORMS
    result["tiered"] = {
        "backend": result.get("backend"),
        "n_pods": n_pods,
        "n_rels": total,
        "graph_bytes": int(graph_bytes),
        "budget_bytes": int(budget),
        "resident_check_p50_ms": round(resident_p50, 3),
        "tiered_check_p50_ms": round(tiered_p50, 3),
        "tiered_over_resident": round(ratio, 3),
        "cold_start_ms": round(cold_ms, 3),
        "parity_ok": parity_ok,
        "zero_recompiles": zero_recompiles,
        "miss_stalls": stall_count() - stalls0,
        "hot_blocks": int(st["hot_blocks"]),
        "cold_blocks": int(st["cold_blocks"]),
        "hot_bytes": int(st["hot_bytes"]),
        "cold_bytes": int(st["cold_bytes"]),
        "beyond_budget": beyond,
        "provenance": "[DEGRADED: cpu]" if degraded else "tpu",
    }


_SHARD_SCHEMA = """
use expiration

definition user {}

definition group {
  relation member: user
}

definition namespace {
  relation viewer: user | group#member
  permission view = viewer
}

definition pod {
  relation namespace: namespace
  relation viewer: user
  permission view = viewer + namespace->view
}
"""


def _shard_phase(result: dict, quick: bool, tiny: bool) -> None:
    """Scale-out scaling curve (ROADMAP item 4 / ISSUE 11): the SAME
    tuple set served by 1 vs 2 vs 4 engine groups over loopback TCP
    (one EngineServer per group, the scatter-gather planner in front).
    Reported per group count: single-shard check p50 (must route with
    NO scatter — per-shard op counters prove it), scatter-gathered
    lookup p50, and closed-loop mixed goodput. In-process asyncio
    servers: the phase measures planner + wire overhead and the scaling
    shape, not process boot. Full (non-quick) runs add a 10x scale
    point (~20k namespaces / ~500k relationships) so shard scaling is
    measured, not claimed."""
    if tiny:
        base = (12, 2, 8, 24, 6, 0.8)
    elif quick:
        base = (48, 4, 24, 80, 16, 1.5)
    else:
        base = (200, 8, 64, 200, 40, 3.0)

    result["shard"] = _shard_phase_at_scale(*base)
    if not quick and not tiny:
        # ROADMAP item 1's scale-point demand: shard scaling MEASURED
        # at a 10x point (~20k namespaces / ~500k relationships, 1 vs
        # 2 vs 4 groups), not extrapolated from the small curve. Full
        # runs only — the bulk loads dominate the phase's wall clock.
        try:
            result["shard"]["scale10x"] = _shard_phase_at_scale(
                n_ns=20_000, pods_per_ns=12, n_users=512,
                n_checks=120, n_lookups=8, good_s=3.0)
        except Exception as ex:  # noqa: BLE001 - aux measurement only
            log(f"shard 10x scale point failed (non-fatal): {ex}")



def _shard_phase_at_scale(n_ns: int, pods_per_ns: int, n_users: int,
                          n_checks: int, n_lookups: int,
                          good_s: float) -> dict:
    """One shard scaling point at an arbitrary size; returns the
    per-group-count schema ({1,2,4} groups) plus its sizes."""
    import asyncio
    import threading as _threading

    from spicedb_kubeapi_proxy_tpu.engine import Engine
    from spicedb_kubeapi_proxy_tpu.engine.engine import CheckItem
    from spicedb_kubeapi_proxy_tpu.engine.remote import (
        EngineServer,
        RemoteEngine,
    )
    from spicedb_kubeapi_proxy_tpu.models import parse_schema
    from spicedb_kubeapi_proxy_tpu.scaleout import (
        ShardMap,
        ShardedEngine,
    )
    from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

    rng = np.random.default_rng(7)
    # one canonical tuple set, partitioned per map below
    ns_viewer = [(f"ns{i}", f"u{int(rng.integers(n_users))}")
                 for i in range(n_ns)]
    pod_rows = []
    for i in range(n_ns):
        for p in range(pods_per_ns):
            pod_rows.append((f"ns{i}/p{p}",
                             f"ns{i}",
                             f"u{int(rng.integers(n_users))}"))
    total_rels = len(ns_viewer) + 2 * len(pod_rows)

    def cols_for(smap, gi):
        cols = {k: [] for k in ("resource_type", "resource_id",
                                "relation", "subject_type",
                                "subject_id", "subject_relation")}

        def add(rt, rid, rl, st, sid):
            cols["resource_type"].append(rt)
            cols["resource_id"].append(rid)
            cols["relation"].append(rl)
            cols["subject_type"].append(st)
            cols["subject_id"].append(sid)
            cols["subject_relation"].append("")

        for ns, u in ns_viewer:  # global: replicated to every group
            add("namespace", ns, "viewer", "user", u)
        for pid, ns, u in pod_rows:
            if smap.shard_of("pod", pid) == gi:
                add("pod", pid, "namespace", "namespace", ns)
                add("pod", pid, "viewer", "user", u)
        return {k: np.asarray(v) for k, v in cols.items()}

    loop = asyncio.new_event_loop()
    loop_thread = _threading.Thread(target=loop.run_forever,
                                    daemon=True)
    loop_thread.start()

    def run_in_loop(coro, timeout=60.0):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(
            timeout)

    def scatter_count():
        tot = 0
        for gi in range(4):
            for op in ("check_bulk",):
                tot += metrics.counter(
                    "scaleout_ops_total", group=str(gi), op=op,
                    mode="scatter").value
        return tot

    groups_out = {}
    single_only = True

    def run_points():
        nonlocal single_only
        for k in (1, 2, 4):
            smap = ShardMap(version=1, groups=tuple(
                (("127.0.0.1", 0),) for _ in range(k)))
            servers, clients = [], []
            planner = None
            try:
                for gi in range(k):
                    eng = Engine(schema=parse_schema(_SHARD_SCHEMA))
                    eng.bulk_load(cols_for(smap, gi))
                    srv = EngineServer(eng)
                    port = run_in_loop(srv.start())
                    servers.append(srv)
                    clients.append(RemoteEngine("127.0.0.1", port))
                planner = ShardedEngine(smap, clients, journal=None)
                # warm every jit shape (per group) outside the timed loops
                planner.check(CheckItem("pod", "ns0/p0", "view",
                                        "user", "u0"))
                planner.lookup_resources("pod", "view", "user", "u0")

                sc0 = scatter_count()
                lat = []
                for i in range(n_checks):
                    pid, ns, u = pod_rows[i % len(pod_rows)]
                    t0 = time.perf_counter()
                    planner.check(CheckItem("pod", pid, "view", "user", u))
                    lat.append((time.perf_counter() - t0) * 1e3)
                check_p50 = float(np.percentile(lat, 50))
                no_scatter = scatter_count() == sc0
                single_only = single_only and no_scatter

                lat = []
                for i in range(n_lookups):
                    t0 = time.perf_counter()
                    planner.lookup_resources("pod", "view", "user",
                                             f"u{i % n_users}")
                    lat.append((time.perf_counter() - t0) * 1e3)
                lookup_p50 = float(np.percentile(lat, 50))

                # closed-loop mixed goodput: 8 threads, ~85% single-shard
                # checks / 15% scatter lookups
                done = [0] * 8
                stop = _threading.Event()

                def worker(wi):
                    j = wi
                    while not stop.is_set():
                        if j % 7 == 0:
                            planner.lookup_resources(
                                "pod", "view", "user", f"u{j % n_users}")
                        else:
                            pid, ns, u = pod_rows[j % len(pod_rows)]
                            planner.check(CheckItem("pod", pid, "view",
                                                    "user", u))
                        done[wi] += 1
                        j += 8

                threads = [_threading.Thread(target=worker, args=(wi,),
                                             daemon=True)
                           for wi in range(8)]
                t0 = time.perf_counter()
                for t in threads:
                    t.start()
                stop.wait(good_s)
                stop.set()
                for t in threads:
                    t.join(10)
                span = time.perf_counter() - t0
                goodput = sum(done) / max(span, 1e-9)
                groups_out[str(k)] = {
                    "check_p50_ms": round(check_p50, 3),
                    "scatter_lookup_p50_ms": round(lookup_p50, 3),
                    "goodput_ops_s": round(goodput, 1),
                    "single_shard_no_scatter": bool(no_scatter),
                }
                log(f"shard {k}g: check p50 {check_p50:.2f}ms "
                    f"(no_scatter={no_scatter}), scatter lookup p50 "
                    f"{lookup_p50:.2f}ms, goodput {goodput:.0f} op/s")
            finally:
                # close the planner (scatter pool + client sockets) and
                # stop the servers even when a measurement throws — a
                # leaked loop thread would keep spinning under every later
                # phase's latency numbers
                if planner is not None:
                    try:
                        planner.close()
                    except Exception:  # noqa: BLE001 - teardown best effort
                        pass
                for srv in servers:
                    try:
                        run_in_loop(srv.stop(), timeout=15.0)
                    except Exception:  # noqa: BLE001 - teardown best effort
                        pass
    try:
        run_points()
    finally:
        # the loop thread must die even when a point raises — a leaked
        # daemon loop would keep spinning under every later phase's
        # latency numbers
        loop.call_soon_threadsafe(loop.stop)
        loop_thread.join(10)
    return {
        "n_ns": n_ns,
        "n_rels": total_rels,
        "single_shard_no_scatter": bool(single_only),
        "groups": groups_out,
    }


def _rebalance_phase(result: dict, quick: bool, tiny: bool) -> None:
    """Online shard rebalancing (ISSUE 14): a live 3 -> 4 group GROW
    move over loopback TCP engine servers under sustained check load
    on NON-moving slices. Goodput is compared between interleaved
    PAUSED-mover and RUNNING-mover windows (coordinator pause/resume),
    so the ratio isolates the mover's interference from wall-clock
    noise; the phase also records rows moved, slice count, move
    duration, zero-acked-write-loss and the fail-open probe count."""
    import asyncio
    import statistics
    import threading as _threading

    from spicedb_kubeapi_proxy_tpu.engine import Engine
    from spicedb_kubeapi_proxy_tpu.engine.engine import CheckItem
    from spicedb_kubeapi_proxy_tpu.engine.remote import (
        EngineServer,
        RemoteEngine,
    )
    from spicedb_kubeapi_proxy_tpu.engine.store import (
        RelationshipFilter,
        WriteOp,
    )
    from spicedb_kubeapi_proxy_tpu.models import parse_schema
    from spicedb_kubeapi_proxy_tpu.models.tuples import Relationship
    from spicedb_kubeapi_proxy_tpu.scaleout import (
        MapTransition,
        ShardMap,
        ShardedEngine,
        plan_moves,
    )

    if tiny:
        n_ns, win_s, n_windows = 24, 0.4, 2
    elif quick:
        n_ns, win_s, n_windows = 48, 0.5, 3
    else:
        n_ns, win_s, n_windows = 200, 0.7, 3

    old = ShardMap(version=1, groups=tuple(
        (("127.0.0.1", 0),) for _ in range(3)))
    new = ShardMap(version=2, groups=tuple(
        (("127.0.0.1", 0),) for _ in range(4)))

    loop = asyncio.new_event_loop()
    loop_thread = _threading.Thread(target=loop.run_forever,
                                    daemon=True)
    loop_thread.start()

    def run_in_loop(coro, timeout=60.0):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(
            timeout)

    servers, clients = [], []
    planner = None
    stop = _threading.Event()
    try:
        for _ in range(4):
            srv = EngineServer(Engine(schema=parse_schema(
                _SHARD_SCHEMA)))
            port = run_in_loop(srv.start())
            servers.append(srv)
            clients.append(RemoteEngine("127.0.0.1", port))
        planner = ShardedEngine(old, clients[:3], journal=None)
        writes = []
        for i in range(n_ns):
            writes.append(WriteOp("create", Relationship(
                "namespace", f"ns{i}", "viewer", "user",
                f"u{i % 8}")))
            writes.append(WriteOp("create", Relationship(
                "pod", f"ns{i}/p0", "namespace", "namespace",
                f"ns{i}")))
            writes.append(WriteOp("create", Relationship(
                "pod", f"ns{i}/p0", "viewer", "user", f"u{i % 8}")))
        planner.write_relationships(writes)
        t = MapTransition(old, new, plan_moves(old, new))
        moving = [f"ns{i}" for i in range(n_ns)
                  if t.slice_for_key(f"ns{i}", "pod") is not None]
        staying = [f"ns{i}" for i in range(n_ns)
                   if t.slice_for_key(f"ns{i}", "pod") is None]
        probes = staying[:8] or staying

        goodput = {"n": 0}
        fail_open = {"n": 0}

        def load_worker(wi):
            j = wi
            while not stop.is_set():
                ns = probes[j % len(probes)]
                try:
                    planner.check(CheckItem("pod", f"{ns}/p0", "view",
                                            "user", f"u{j % 8}"))
                    if planner.check(CheckItem(
                            "pod", f"{ns}/p0", "view", "user",
                            "intruder")):
                        fail_open["n"] += 1
                    goodput["n"] += 2
                except Exception:  # noqa: BLE001 - keep probing
                    # a transient error is a non-completion (it costs
                    # goodput, which is the point of the measurement) —
                    # it must NOT silently kill the probe thread, or the
                    # fail-open pin would pass vacuously
                    pass
                j += 4

        def writer():
            i = 0
            while not stop.is_set():
                ns = moving[i % len(moving)]
                try:
                    planner.write_relationships([WriteOp(
                        "touch", Relationship(
                            "pod", f"{ns}/p0", "viewer", "user",
                            f"mv{i}"))])
                except Exception:  # noqa: BLE001 - unacked: no claim
                    pass
                i += 1
                time.sleep(0.1)

        workers = [_threading.Thread(target=load_worker, args=(wi,),
                                     daemon=True) for wi in range(3)]
        wt = _threading.Thread(target=writer, daemon=True)
        for w in workers:
            w.start()
        wt.start()
        # warm jit shapes + caches before sampling
        time.sleep(0.6)

        t0 = time.perf_counter()
        coord = planner.begin_rebalance(
            new, new_clients={3: clients[3]},
            pace_seconds=0.2, batch_rows=16, poll_seconds=0.25)

        def window():
            goodput["n"] = 0
            w0 = time.monotonic()
            time.sleep(win_s)
            return goodput["n"] / (time.monotonic() - w0)

        time.sleep(0.3)
        paused_w, running_w = [], []
        for _ in range(n_windows):
            if coord._done.is_set():
                break
            coord.pause()
            time.sleep(0.05)
            paused_w.append(window())
            coord.resume()
            time.sleep(0.05)
            if coord._done.is_set():
                break
            running_w.append(window())
        coord.resume()
        ok = coord.wait(120.0)
        move_s = time.perf_counter() - t0
        stop.set()
        wt.join(5)
        for w in workers:
            w.join(5)
        if not ok or coord.error is not None:
            raise RuntimeError(f"mover failed: {coord.error}")

        # zero acked writes lost: every seeded tuple answers at V+1
        lost = 0
        for i in range(n_ns):
            if not planner.check(CheckItem(
                    "pod", f"ns{i}/p0", "view", "user", f"u{i % 8}")):
                lost += 1
        moved_rows = sum(
            1 for i in range(n_ns)
            if new.shard_for(f"ns{i}", "pod") == 3) * 2
        paused = (statistics.median(paused_w) if paused_w else None)
        running = (statistics.median(running_w) if running_w
                   else None)
        ratio = (round(running / paused, 3)
                 if paused and running else None)
        result["rebalance"] = {
            "n_ns": n_ns,
            "slices": len(t.slices),
            "rows_moved": int(moved_rows),
            "move_seconds": round(move_s, 3),
            "goodput_paused_ops_s": (round(paused, 1)
                                     if paused else None),
            "goodput_moving_ops_s": (round(running, 1)
                                     if running else None),
            "goodput_ratio_moving_over_paused": ratio,
            "zero_acked_write_loss": lost == 0,
            "fail_open_probes": int(fail_open["n"]),
        }
        log(f"rebalance: {moved_rows} rows / {len(t.slices)} slices "
            f"in {move_s:.2f}s, goodput paused "
            f"{paused or 0:.0f} vs moving {running or 0:.0f} op/s "
            f"(ratio {ratio}), lost={lost} "
            f"fail_open={fail_open['n']}")
    finally:
        stop.set()
        if planner is not None:
            try:
                planner.close()
            except Exception:  # noqa: BLE001 - teardown best effort
                pass
        for srv in servers:
            try:
                run_in_loop(srv.stop(), timeout=15.0)
            except Exception:  # noqa: BLE001 - teardown best effort
                pass
        loop.call_soon_threadsafe(loop.stop)
        loop_thread.join(10)


# ISSUE 20's cross-namespace reference schema: `team` is NAMESPACED
# (sharded — one copy, on its owner group) yet referenced as a userset
# subject by `doc` rows living in OTHER namespaces, i.e. usually on
# OTHER shards. Under the PR-11 contract this schema required `team`
# to be cluster-scoped (replicated everywhere); the frontier exchange
# resolves it with only boundary descriptors on the wire.
_FRONTIER_SCHEMA = """
definition user {}

definition team {
  relation member: user
}

definition doc {
  relation owner: team#member
  relation viewer: user
  permission view = viewer + owner
}
"""


def _autoscale_phase(result: dict, quick: bool, tiny: bool) -> None:
    """Elastic scale-out (ISSUE 20): a cross-namespace reference schema
    served WITHOUT replication — frontier-exchange checks/lookups
    verified against an unsharded oracle, per-round boundary wire
    bytes and round counts recorded straight from the planner's
    counters — then an SLO-driven SHRINK (3 -> 2 groups) proposed and
    applied by the real AutoscaleController under sustained load, with
    paused-vs-running goodput windows, zero acked-write loss, and the
    fail-open probe count."""
    import asyncio
    import statistics
    import threading as _threading

    from spicedb_kubeapi_proxy_tpu.autoscale import (
        AutoscaleController,
        AutoscalePolicy,
        PolicyConfig,
        Signals,
    )
    from spicedb_kubeapi_proxy_tpu.engine import Engine
    from spicedb_kubeapi_proxy_tpu.engine.engine import CheckItem
    from spicedb_kubeapi_proxy_tpu.engine.remote import (
        EngineServer,
        RemoteEngine,
    )
    from spicedb_kubeapi_proxy_tpu.engine.store import (
        RelationshipFilter,
        WriteOp,
    )
    from spicedb_kubeapi_proxy_tpu.models import parse_schema
    from spicedb_kubeapi_proxy_tpu.models.tuples import Relationship
    from spicedb_kubeapi_proxy_tpu.scaleout import (
        FrontierConfig,
        ShardMap,
        ShardedEngine,
    )
    from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

    if tiny:
        n_pairs, win_s, n_windows = 16, 0.4, 2
    elif quick:
        n_pairs, win_s, n_windows = 48, 0.5, 3
    else:
        n_pairs, win_s, n_windows = 160, 0.7, 3

    smap = ShardMap(version=1, groups=tuple(
        (("127.0.0.1", 0),) for _ in range(3)))

    loop = asyncio.new_event_loop()
    loop_thread = _threading.Thread(target=loop.run_forever,
                                    daemon=True)
    loop_thread.start()

    def run_in_loop(coro, timeout=60.0):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(
            timeout)

    def wire(direction):
        return metrics.counter("scaleout_frontier_wire_bytes_total",
                               direction=direction).value

    servers, clients = [], []
    planner = None
    oracle = Engine(schema=parse_schema(_FRONTIER_SCHEMA))
    stop = _threading.Event()
    try:
        for _ in range(3):
            srv = EngineServer(Engine(schema=parse_schema(
                _FRONTIER_SCHEMA)))
            port = run_in_loop(srv.start())
            servers.append(srv)
            clients.append(RemoteEngine("127.0.0.1", port))
        planner = ShardedEngine(smap, clients, journal=None,
                                frontier=FrontierConfig())
        # teams live in the a* namespaces, docs in b* — the owner
        # edge crosses namespaces (and so, usually, shards)
        writes = []
        for i in range(n_pairs):
            writes.append(WriteOp("create", Relationship(
                "team", f"a{i}/t", "member", "user", f"u{i % 8}",
                None)))
            writes.append(WriteOp("create", Relationship(
                "doc", f"b{i}/d", "owner", "team", f"a{i}/t",
                "member")))
            writes.append(WriteOp("create", Relationship(
                "doc", f"b{i}/d", "viewer", "user", f"v{i % 8}",
                None)))
        planner.write_relationships(writes)
        oracle.write_relationships(writes)

        # -- frontier parity vs the unsharded oracle, wire-accounted --
        scatter0, gather0 = wire("scatter"), wire("gather")
        rounds0 = (metrics.hist_snapshot("scaleout_frontier_rounds")
                   or {"n": 0, "max": 0})
        boundary0 = metrics.counter(
            "scaleout_frontier_boundary_tuples_total").value
        parity = 0
        mismatches = 0
        for i in range(min(n_pairs, 32)):
            for subj in (f"u{i % 8}", "intruder"):
                item = CheckItem("doc", f"b{i}/d", "view", "user",
                                 subj)
                if bool(planner.check(item)) == bool(
                        oracle.check(item)):
                    parity += 1
                else:
                    mismatches += 1
        lookup_ok = (sorted(planner.lookup_resources(
            "doc", "view", "user", "u0"))
            == sorted(oracle.lookup_resources(
                "doc", "view", "user", "u0")))
        rounds1 = (metrics.hist_snapshot("scaleout_frontier_rounds")
                   or {"n": 0, "max": 0})
        scatter_bytes = wire("scatter") - scatter0
        gather_bytes = wire("gather") - gather0
        boundary_tuples = metrics.counter(
            "scaleout_frontier_boundary_tuples_total").value - boundary0
        # the no-replication proof: every team tuple has exactly ONE
        # copy fleet-wide (its owner group) — the closure crossed
        # shards via the exchange, not via replicated reference data
        per_group_teams = [
            len(list(c.read_relationships(RelationshipFilter(
                resource_type="team")))) for c in clients]
        single_copy = sum(per_group_teams) == n_pairs

        # -- SLO-driven shrink applied by the real controller ---------
        staying = []
        for i in range(n_pairs):
            if smap.shard_for(f"b{i}", "doc") != 2:
                staying.append(i)
        probes = staying[:8] or list(range(n_pairs))
        goodput = {"n": 0}
        fail_open = {"n": 0}

        def load_worker(wi):
            j = wi
            while not stop.is_set():
                i = probes[j % len(probes)]
                try:
                    planner.check(CheckItem(
                        "doc", f"b{i}/d", "view", "user",
                        f"v{i % 8}"))
                    if planner.check(CheckItem(
                            "doc", f"b{i}/d", "view", "user",
                            "intruder")):
                        fail_open["n"] += 1
                    goodput["n"] += 2
                except Exception:  # noqa: BLE001 - keep probing
                    pass
                j += 3

        workers = [_threading.Thread(target=load_worker, args=(wi,),
                                     daemon=True) for wi in range(3)]
        for w in workers:
            w.start()
        time.sleep(0.4)

        controller = AutoscaleController(
            planner,
            AutoscalePolicy(PolicyConfig(
                min_groups=2, max_groups=4, hysteresis_ticks=2,
                cooldown_seconds=0.0)),
            mode="apply",
            signal_fn=lambda: Signals(
                n_groups=len(planner.groups), occupancy=0.05,
                burn_rate=0.0,
                rebalance_active=(planner.rebalance_status()
                                  is not None),
                gc_pending=any(
                    not t.gc_complete
                    for t in planner._archived_transitions)),
            coordinator_cfg={"pace_seconds": 0.2, "batch_rows": 16,
                             "poll_seconds": 0.25})
        t0 = time.perf_counter()
        ticks = 0
        proposal = None
        while proposal is None and ticks < 10:
            proposal = controller.tick(now=float(ticks))
            ticks += 1
        if proposal is None:
            raise RuntimeError("autoscaler never proposed the shrink")
        coord = planner._coordinator

        def window():
            goodput["n"] = 0
            w0 = time.monotonic()
            time.sleep(win_s)
            return goodput["n"] / (time.monotonic() - w0)

        paused_w, running_w = [], []
        for _ in range(n_windows):
            if coord is None or coord._done.is_set():
                break
            coord.pause()
            time.sleep(0.05)
            paused_w.append(window())
            coord.resume()
            time.sleep(0.05)
            if coord._done.is_set():
                break
            running_w.append(window())
        if coord is not None:
            coord.resume()
            ok = coord.wait(120.0)
            if not ok or coord.error is not None:
                raise RuntimeError(f"shrink mover failed: "
                                   f"{coord.error}")
        move_s = time.perf_counter() - t0
        stop.set()
        for w in workers:
            w.join(5)

        # zero acked writes lost across the shrink: every seeded doc
        # still answers — the DIRECT viewer and the CROSS-SHARD
        # frontier path both
        lost = 0
        for i in range(n_pairs):
            if not planner.check(CheckItem(
                    "doc", f"b{i}/d", "view", "user", f"v{i % 8}")):
                lost += 1
            if not planner.check(CheckItem(
                    "doc", f"b{i}/d", "view", "user", f"u{i % 8}")):
                lost += 1
        paused = (statistics.median(paused_w) if paused_w else None)
        running = (statistics.median(running_w) if running_w
                   else None)
        ratio = (round(running / paused, 3)
                 if paused and running else None)
        result["autoscale"] = {
            "n_teams": n_pairs,
            "n_docs": n_pairs,
            "frontier": {
                "parity_checks": parity,
                "parity_ok": mismatches == 0,
                "lookup_parity_ok": bool(lookup_ok),
                "exchanges": int(rounds1["n"] - rounds0["n"]),
                "rounds_max": int(rounds1["max"] or 0),
                "scatter_bytes": int(scatter_bytes),
                "gather_bytes": int(gather_bytes),
                "boundary_tuples": int(boundary_tuples),
                "reference_single_copy": bool(single_copy),
            },
            "shrink": {
                "proposal_action": proposal.action,
                "ticks_to_fire": ticks,
                "groups_after": len(planner.groups),
                "move_seconds": round(move_s, 3),
                "goodput_paused_ops_s": (round(paused, 1)
                                         if paused else None),
                "goodput_moving_ops_s": (round(running, 1)
                                         if running else None),
                "goodput_ratio_moving_over_paused": ratio,
                "zero_acked_write_loss": lost == 0,
                "fail_open_probes": int(fail_open["n"]),
            },
        }
        fr = result["autoscale"]["frontier"]
        log(f"autoscale: frontier parity {parity} checks "
            f"({mismatches} mismatches), {fr['exchanges']} exchanges "
            f"<= {fr['rounds_max']} rounds, "
            f"{fr['scatter_bytes']}+{fr['gather_bytes']}B boundary "
            f"wire; shrink {proposal.action} after {ticks} ticks in "
            f"{move_s:.2f}s, goodput ratio {ratio}, lost={lost} "
            f"fail_open={fail_open['n']}")
    finally:
        stop.set()
        if planner is not None:
            try:
                planner.close()
            except Exception:  # noqa: BLE001 - teardown best effort
                pass
        for srv in servers:
            try:
                run_in_loop(srv.stop(), timeout=15.0)
            except Exception:  # noqa: BLE001 - teardown best effort
                pass
        loop.call_soon_threadsafe(loop.stop)
        loop_thread.join(10)


# The two migration targets the phase applies in sequence.  Both are
# BENCH_SCHEMA derivatives built by string surgery so the bench schema
# stays the single source of truth: the ADDITIVE step grows pod with an
# auditor relation + audit permission (no existing relation changes →
# swap-at-a-revision, zero backfill), and the REWRITING step — layered
# on the additive result, since migrations are sequential — attaches a
# caveat to the live pod#viewer relation (allowed-set change on stored
# tuples → journaled backfill of the affected closure).
_MIG_ADDITIVE_SCHEMA = BENCH_SCHEMA.replace(
    "  permission edit = creator\n",
    "  relation auditor: user\n"
    "  permission audit = auditor\n"
    "  permission edit = creator\n")
_MIG_REWRITING_SCHEMA = _MIG_ADDITIVE_SCHEMA.replace(
    "definition user {}",
    "caveat bench_probation(level int) {\n"
    "  level < 3\n"
    "}\n\n"
    "definition user {}").replace(
    "  relation viewer: user\n",
    "  relation viewer: user | user with bench_probation\n")


def _migration_phase(result: dict, quick: bool, tiny: bool) -> None:
    """Live schema migration (ISSUE 19): an additive and then a
    rewriting migration applied to a serving engine under a sustained
    check/write mix at every scale. For each migration the phase records
    end-to-end time-to-cut, the cut freeze, backfilled row count, and
    check p50 DURING the migration window (compile + backfill + dual)
    against the same engine's p50 before any migration — the
    during-vs-before ratio is the number the no-downtime claim rides
    on. The migration holds at dual only long enough to collect the
    during-window samples, then cuts."""
    import jax

    from spicedb_kubeapi_proxy_tpu.engine import CheckItem
    from spicedb_kubeapi_proxy_tpu.engine.store import WriteOp
    from spicedb_kubeapi_proxy_tpu.models.tuples import Relationship

    if tiny:
        n_pods, n_users, n_ns, n_groups, n_rels = 200, 100, 10, 10, 3_000
        n_checks, n_during = 64, 24
    elif quick:
        n_pods, n_users, n_ns, n_groups, n_rels = (
            2_000, 500, 50, 50, 50_000)
        n_checks, n_during = 256, 64
    else:
        n_pods, n_users, n_ns, n_groups, n_rels = (
            20_000, 4_000, 400, 400, 2_000_000)
        n_checks, n_during = 512, 128
    e, total = build_engine(n_pods, n_users, n_ns, n_groups, n_rels,
                            seed=11)
    rng = np.random.default_rng(29)
    items = [CheckItem("pod", f"ns/p{int(p)}", "view",
                       "user", f"u{int(u)}")
             for p, u in zip(rng.integers(n_pods, size=n_checks),
                             rng.integers(n_users, size=n_checks))]
    e.check_bulk(items)  # warm the compiled graph

    def one_check(i: int) -> float:
        it = items[i % len(items)]
        t0 = time.perf_counter()
        e.check(it)
        return (time.perf_counter() - t0) * 1e3

    before = [one_check(i) for i in range(n_checks)]
    p50_before = float(np.percentile(before, 50))

    # live write churn for the whole phase: touches pod#viewer rows so
    # the rewriting window has dual-applied writes racing its backfill
    stop = threading.Event()
    writes = {"n": 0}

    def writer():
        i = 0
        while not stop.is_set():
            e.write_relationships([WriteOp("touch", Relationship(
                "pod", f"ns/p{i % n_pods}", "viewer",
                "user", f"u{(i * 7) % n_users}"))])
            writes["n"] += 1
            i += 1
            time.sleep(0.002)

    wt = threading.Thread(target=writer, daemon=True,
                          name="mig-bench-writer")
    wt.start()

    def migrate(schema_text: str, pause: float) -> dict:
        """Run one migration under the live mix: hold at dual until the
        during-window sample budget is met, then cut. Returns the
        per-migration result row."""
        e.begin_schema_migration(schema_text, hold_at_dual=True,
                                 backfill_pause=pause)
        during: list[float] = []
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            st = e.migration_status()
            phase = st["phase"] if st else None
            if phase in ("done", "failed", "aborted"):
                break
            if phase == "dual" and len(during) >= n_during:
                break
            during.append(one_check(len(during)))
        st = e.cut_schema_migration(wait=True)
        row = {
            "classification": st.get("classification"),
            "phase": st.get("phase"),
            "time_to_cut_ms": float(st.get("time_to_cut_ms") or 0.0),
            "freeze_ms": float(st.get("freeze_ms") or 0.0),
            "backfilled": int(st.get("backfilled") or 0),
            "affected": int(st.get("affected") or 0),
            "p50_during_ms": float(np.percentile(during, 50))
            if during else p50_before,
            "during_samples": len(during),
        }
        log(f"migration [{row['classification']}]: phase={row['phase']} "
            f"time_to_cut={row['time_to_cut_ms']:.1f}ms "
            f"freeze={row['freeze_ms']:.2f}ms "
            f"backfilled={row['backfilled']} "
            f"p50 during {row['p50_during_ms']:.3f}ms "
            f"vs before {p50_before:.3f}ms")
        return row

    try:
        additive = migrate(_MIG_ADDITIVE_SCHEMA, pause=0.0)
        # pace the rewriting backfill a little so the during window is a
        # genuine mid-backfill measurement, not an instant flip
        rewriting = migrate(_MIG_REWRITING_SCHEMA,
                            pause=0.005 if tiny else 0.002)
    finally:
        stop.set()
        wt.join(5)

    worst_during = max(additive["p50_during_ms"],
                       rewriting["p50_during_ms"])
    result["migration"] = {
        "n_rels": int(total),
        "writes": int(writes["n"]),
        "p50_before_ms": p50_before,
        "additive": additive,
        "rewriting": rewriting,
        "during_over_before_p50": (worst_during / p50_before
                                   if p50_before > 0 else 1.0),
        "provenance": ("[DEGRADED: cpu]"
                       if jax.default_backend() not in _TPU_PLATFORMS
                       else "tpu"),
    }


def _macro_phase(result: dict, quick: bool, tiny: bool,
                 result_key: str = "macro",
                 n_ns_override: Optional[int] = None,
                 migrate_live: bool = False) -> None:
    """The open-loop, trace-shaped macrobench (ROADMAP item 5): a mixed-
    op workload (checks, bulk checks, list prefilters, Table filtering,
    LookupSubjects, wildcard grants, write churn, watch streams through
    the fused hub) fired on a Poisson-plus-bursts arrival schedule with
    Zipf tenant skew, swept across offered-load multipliers of a probed
    closed-loop capacity. Emits the goodput-vs-offered-load curve, a
    knee estimate, per-class burst p99/p99.9, per-stage tail attribution
    from the trace ring, and per-class SLO attainment into the result
    JSON — the harness every engine-scaling PR after this one is judged
    against.

    ``migrate_live`` (ISSUE 19) re-runs the same-seed sweep with a
    REWRITING schema migration (caveat attached to the live
    namespace#viewer relation) held open across every measured point —
    backfill races the write churn, the dual window replays it — and
    cut after the sweep. The overlay-on/off comparison is skipped in
    this mode (one variable at a time); the caller folds the resulting
    knee into the baseline's ``migration.knee_ratio``."""
    import hashlib

    from spicedb_kubeapi_proxy_tpu.admission import (
        BULK_CHECK,
        CHECK,
        LOOKUP_PREFILTER,
        WATCH_RECOMPUTE,
        WRITE_DTX,
        AdmissionController,
    )
    from spicedb_kubeapi_proxy_tpu.authz.filterer import filter_body
    from spicedb_kubeapi_proxy_tpu.authz.lookups import AllowedSet
    from spicedb_kubeapi_proxy_tpu.engine import CheckItem, Engine
    from spicedb_kubeapi_proxy_tpu.engine.store import WriteOp
    from spicedb_kubeapi_proxy_tpu.loadgen import run_sweep
    from spicedb_kubeapi_proxy_tpu.loadgen.schedule import (
        OP_BULK_CHECK,
        OP_CHECK,
        OP_LIST_PREFILTER,
        OP_LOOKUP_SUBJECTS,
        OP_TABLE,
        OP_WATCH_OPEN,
        OP_WILDCARD,
        OP_WRITE,
        trace_shaped_config,
    )
    from spicedb_kubeapi_proxy_tpu.models import parse_schema
    from spicedb_kubeapi_proxy_tpu.models.tuples import Relationship
    from spicedb_kubeapi_proxy_tpu.obs.slo import (
        SLOMonitor,
        default_objectives,
    )
    from spicedb_kubeapi_proxy_tpu.obs.trace import tracer
    from spicedb_kubeapi_proxy_tpu.rules.input import ResolveInput, UserInfo
    from spicedb_kubeapi_proxy_tpu.proxy.requestinfo import (
        parse_request_info,
    )

    if tiny:
        n_ns, n_users, n_groups = 120, 80, 8
        table_rows, max_streams, dur, workers = 300, 64, 2.0, 16
    elif quick:
        n_ns, n_users, n_groups = 600, 300, 24
        table_rows, max_streams, dur, workers = 1_200, 256, 3.0, 32
    else:
        n_ns, n_users, n_groups = 2_000, 800, 64
        table_rows, max_streams, dur, workers = 5_000, 2_048, 5.0, 64
    if n_ns_override:
        # extra scale point (the full bench runs 2k AND 10k): resource
        # population scales, run shape (duration/workers) stays fixed so
        # the two points differ only in graph scale
        scale = n_ns_override / n_ns
        n_users = int(n_users * scale)
        n_groups = int(n_groups * scale)
        n_ns = n_ns_override
    # workers sized to the host: on a 2-core CI box, 16+ jax-busy
    # threads starve the dispatcher thread and every point reads late
    # (generator noise, not server signal)
    workers = max(4, min(workers, 4 * (os.cpu_count() or 4)))

    rng = np.random.default_rng(11)
    schema = parse_schema(_MACRO_SCHEMA)
    cols = {k: [] for k in ("resource_type", "resource_id", "relation",
                            "subject_type", "subject_id",
                            "subject_relation")}

    def add(rt, rid, rl, st, sid, srl=""):
        m = len(rid)
        cols["resource_type"].append(np.full(m, rt))
        cols["resource_id"].append(np.asarray(rid))
        cols["relation"].append(np.full(m, rl))
        cols["subject_type"].append(np.full(m, st))
        cols["subject_id"].append(np.asarray(sid))
        cols["subject_relation"].append(np.full(m, srl))

    nss = np.char.add("ns", np.arange(n_ns).astype(str))
    users = np.char.add("u", np.arange(n_users).astype(str))
    groups = np.char.add("g", np.arange(n_groups).astype(str))
    m = 8 * n_ns
    add("namespace", nss[rng.integers(n_ns, size=m)], "viewer",
        "user", users[rng.integers(n_users, size=m)])
    gm = 10 * n_groups
    add("group", groups[rng.integers(n_groups, size=gm)], "member",
        "user", users[rng.integers(n_users, size=gm)])
    add("namespace", nss[rng.integers(n_ns, size=n_groups)], "viewer",
        "group", groups, "member")
    # the wildcard slice: ~2% of namespaces are public (user:*) — the
    # still-unexercised grant form the mixed workload drives
    n_wild = max(2, n_ns // 50)
    wild_ns = nss[:n_wild]
    add("namespace", wild_ns, "viewer", "user", np.full(n_wild, "*"))
    e = Engine(schema=schema)
    e.bulk_load({k: np.concatenate(v) for k, v in cols.items()})

    # a Table response body at scale (rows named like the namespaces, so
    # the allowed-set filter drops real rows), built once per run
    table_body = json.dumps({
        "kind": "Table", "apiVersion": "meta.k8s.io/v1",
        "columnDefinitions": [{"name": "Name", "type": "string"}],
        "rows": [{"cells": [f"ns{i}"],
                  "object": {"metadata": {"name": f"ns{i}"}}}
                 for i in range(table_rows)],
    }).encode()
    table_info = parse_request_info("GET", "/api/v1/namespaces", {})
    table_input = ResolveInput.create(table_info, UserInfo(name="macro"))

    # -- op table (the mixed workload) ---------------------------------------
    def op_check(a):
        e.check_bulk([CheckItem("namespace", f"ns{a.ns_key % n_ns}", "view",
                                "user", f"u{a.key % n_users}")])

    def op_bulk(a):
        e.check_bulk([CheckItem("namespace", f"ns{(a.ns_key + j) % n_ns}",
                                "view", "user", f"u{a.key % n_users}")
                      for j in range(32)])

    def op_list(a):
        e.lookup_resources_mask("namespace", "view", "user",
                                f"u{a.key % n_users}")

    def op_table(a):
        ids = e.lookup_resources("namespace", "view", "user",
                                 f"u{a.key % n_users}")
        allowed = AllowedSet()
        for i in ids:
            allowed.add("", i)
        status, _body = filter_body(table_body, allowed, table_input)
        assert status == 200

    def op_lookup_subjects(a):
        e.lookup_subjects("namespace", f"ns{a.ns_key % n_ns}", "view",
                          "user")

    def op_wildcard(a):
        # a public (user:*) namespace must admit ANY subject, including
        # ones holding no direct tuples at all
        ok = e.check_bulk([CheckItem(
            "namespace", str(wild_ns[a.key % n_wild]), "view",
            "user", f"ghost{a.key}")])[0]
        assert ok, "wildcard grant failed"

    def op_write(a):
        e.write_relationships([WriteOp("touch", Relationship(
            "namespace", f"ns{a.ns_key % n_ns}", "viewer",
            "user", f"u{(a.key * 7) % n_users}"))])

    # the watch harness is ROTATED per sweep point (make_config below):
    # streams opened at 0.5x must not ride along as recompute background
    # load for the 3.5x point — each point's stream population is the
    # one its own offered load built
    harness_box = [_WatchStreamHarness(e, max_streams=max_streams)]
    watch_opened = [0]

    def op_watch(a):
        harness_box[0].open(f"u{a.key % n_users}")
        watch_opened[0] += 1

    for op in (op_check, op_bulk, op_list, op_table, op_lookup_subjects,
               op_wildcard, op_write):
        op(type("A", (), {"key": 0, "ns_key": 0})())  # warm every jit shape
    ops_raw = {
        OP_CHECK: op_check, OP_BULK_CHECK: op_bulk,
        OP_LIST_PREFILTER: op_list, OP_TABLE: op_table,
        OP_LOOKUP_SUBJECTS: op_lookup_subjects, OP_WILDCARD: op_wildcard,
        OP_WRITE: op_write, OP_WATCH_OPEN: op_watch,
    }

    # -- capacity probe (closed loop) anchors the offered-load axis ----------
    # The probe runs the REAL op mix (minus watch-open, which mutates
    # the stream population): anchoring to a checks-only rate would put
    # even the 0.5x sweep point past the knee of the heavier mixed
    # workload, and the curve would have no healthy region at all.
    import threading as _th

    from spicedb_kubeapi_proxy_tpu.loadgen.schedule import DEFAULT_MIX

    probe_ops = []
    for name, w in DEFAULT_MIX.items():
        fn = ops_raw[OP_CHECK if name == OP_WATCH_OPEN else name]
        probe_ops.extend([fn] * max(1, round(w * 100)))

    def closed_probe(dur_s: float, nthreads: int = 8) -> float:
        stop = time.perf_counter() + dur_s
        done = [0] * nthreads

        def w(i):
            k = i

            class A:  # minimal arrival stand-in for the op table
                key = 0
                ns_key = 0  # ops route on BOTH (the warmup above does
                # too); without it every probe thread died at its first
                # namespace-keyed op and the capacity anchor was garbage

            while time.perf_counter() < stop:
                A.key = k
                A.ns_key = k
                probe_ops[(k * 131) % len(probe_ops)](A)
                done[i] += 1
                k += nthreads

        ts = [_th.Thread(target=w, args=(i,)) for i in range(nthreads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        return sum(done) / dur_s

    closed_probe(0.3)  # settle jit + index
    cap_rps = closed_probe(0.8 if tiny else 1.5)
    # base = 0.25x the probed mix capacity. The trace shape roughly
    # 1.5x-es the average rate over the baseline (bursts), so the
    # (0.5, 1, 2, 3.5) sweep spans ~0.2x..1.4x capacity on average with
    # bursts transiently far past it — healthy points below the knee,
    # genuine overload above, exactly the curve shape the knee estimator
    # needs
    base_rate = max(5.0, cap_rps * 0.25)
    log(f"[macro] closed-loop mixed capacity ~{cap_rps:.0f} op/s at 8 "
        f"threads; base offered rate {base_rate:.0f}/s")

    # SLOs: anchored to the probed baseline, floored so CI jitter does
    # not reclassify a healthy run (values recorded in the result)
    slo_s = {
        OP_CHECK: 0.05, OP_WILDCARD: 0.05, OP_BULK_CHECK: 0.15,
        OP_LIST_PREFILTER: 0.15, OP_TABLE: 0.25,
        OP_LOOKUP_SUBJECTS: 0.5, OP_WRITE: 0.25, OP_WATCH_OPEN: 0.5,
    }

    # one admission controller PER SWEEP POINT (rotated by make_config
    # below): the AIMD limit a 1.4x-overload run ratchets down to must
    # not leak into the next point's healthy-load measurement
    ctrl_box = [None]

    def fresh_ctrl():
        ctrl_box[0] = AdmissionController(
            initial_concurrency=16.0, min_concurrency=8.0,
            max_concurrency=64.0, tenant_rate=cap_rps / 4,
            tenant_burst=cap_rps * 2, tenant_depth=32, global_depth=256,
            queue_timeout=0.25)

    fresh_ctrl()
    op_cls = {
        OP_CHECK: CHECK, OP_WILDCARD: CHECK,
        OP_BULK_CHECK: BULK_CHECK, OP_LOOKUP_SUBJECTS: BULK_CHECK,
        OP_LIST_PREFILTER: LOOKUP_PREFILTER, OP_TABLE: LOOKUP_PREFILTER,
        OP_WRITE: WRITE_DTX, OP_WATCH_OPEN: WATCH_RECOMPUTE,
    }

    from spicedb_kubeapi_proxy_tpu.obs.trace import tracer as _tracer

    def admitted(name, fn):
        # only the homogeneous single-check class feeds the AIMD
        # limiter's latency probe (the engine-host round-6 rule): a
        # mixed feed of 32-item bulks, full-mask lookups, and watch
        # registrations reads op VARIETY as congestion and ratchets the
        # limit to the floor under healthy load
        observe = op_cls[name] is CHECK

        def run(a):
            with _tracer.span("admission_wait"):
                ticket = ctrl_box[0].acquire(a.tenant, op_cls[name])
            try:
                fn(a)
            finally:
                ticket.release(observe=observe)
        return run

    ops = {name: admitted(name, fn) for name, fn in ops_raw.items()}

    seed = 42
    multipliers = (0.5, 1.0, 2.0, 3.5)
    tenants = 6
    peak_streams = [0]

    def make_config(m):
        fresh_ctrl()  # each point starts with an unratcheted limiter
        peak_streams[0] = max(peak_streams[0],
                              harness_box[0].live_streams)
        harness_box[0].close()  # each point's own watch-stream population
        harness_box[0] = _WatchStreamHarness(e, max_streams=max_streams)
        return trace_shaped_config(dur, base_rate * m, tenants=tenants,
                                   seed=seed, burst_multiplier=3.0)

    from spicedb_kubeapi_proxy_tpu.loadgen import OpenLoopDriver
    from spicedb_kubeapi_proxy_tpu.loadgen.schedule import build_schedule

    # everything from the tracer reconfiguration on runs under ONE
    # try/finally: _measure treats a macro failure as non-fatal, so a
    # mid-phase exception must not leave the process-global tracer at
    # sweep settings or leak the watch loop thread into later phases
    prev = (tracer.sample, tracer.slow_s * 1e3,
            tracer._shards[0][1].maxlen * tracer.RING_SHARDS)
    monitor = None
    try:
        # tracing: tail-sampled ring sized for the sweep; slow/shed
        # macro ops are always kept (the attribution evidence)
        tracer.configure(sample=0.01, slow_ms=1e3 * min(slo_s.values()),
                         ring=1024)

        # warmup pass (discarded): every jit shape the mixed schedule
        # can draw compiles here, not inside the first measured point
        warm_cfg = trace_shaped_config(dur * 0.5, base_rate * 0.5,
                                       tenants=tenants, seed=7,
                                       burst_multiplier=3.0)
        OpenLoopDriver(ops, max_workers=workers, slo_s=slo_s,
                       trace_ops=False,
                       drain_timeout=10.0).run(build_schedule(warm_cfg),
                                               duration=warm_cfg.duration)

        # the warmup also drove op_watch: reset the counter AND rotate
        # the harness so the recorded stats (opened, live peak) cover
        # only the measured sweep
        watch_opened[0] = 0
        peak_streams[0] = 0
        harness_box[0].close()
        harness_box[0] = _WatchStreamHarness(e, max_streams=max_streams)

        if migrate_live:
            # the live rewriting migration spans the WHOLE measured
            # sweep: begin after warmup (its jit compiles must not hide
            # inside the migration window), hold at dual so every point
            # runs with dual-applied writes + catch-up replay, cut after
            e.begin_schema_migration(_MACRO_MIG_SCHEMA,
                                     hold_at_dual=True,
                                     backfill_pause=0.005)

        monitor = SLOMonitor(default_objectives(), windows=(30.0, 120.0),
                             tick_seconds=0.5)
        monitor.start()
        sweep = run_sweep(
            make_config,
            ops, multipliers, slo_s, max_workers=workers,
            trace_ops=True, drain_timeout=(8.0 if tiny else 15.0),
            on_point=lambda p: log(
                f"[macro x{p.multiplier}] offered={p.offered_rps:.0f}/s "
                f"completed={p.completed_rps:.0f}/s "
                f"goodput={p.goodput_rps:.0f}/s shed={p.shed_n} "
                f"err={p.error_n} late={p.late_n}"))

        # capture the overlay-ON system's numbers BEFORE the off sweep
        # runs: the deliberately-degraded comparison below must not bleed
        # into the recorded SLO attainment / watch-stream stats
        monitor_objectives = monitor.status()["objectives"]
        watch_opened_on = watch_opened[0]
        peak_streams_on = max(peak_streams[0],
                              harness_box[0].live_streams)

        # -- overlay on/off delta (ISSUE 8) -------------------------------
        # The same trace re-swept with IncrementalGraphUpdates off:
        # every write in the (write-heavy) reconcile burst then forces a
        # full graph re-encode before the next fully-consistent dispatch,
        # so the goodput gap between the two curves is exactly what the
        # device-resident delta overlay buys under sustained churn.
        # Reduced multiplier set — the comparison needs the healthy point
        # and the knee neighborhood, not the whole curve.
        from spicedb_kubeapi_proxy_tpu.utils.features import features

        off_mults = (1.0, 2.0)
        sweep_off = None
        mig_status = None
        if migrate_live:
            # cut INSIDE the measured configuration (tracer still wide
            # open) so the freeze histogram covers the real serving
            # shape, then skip the overlay-off comparison — this run
            # varies exactly one thing vs the baseline sweep
            mig_status = e.cut_schema_migration(wait=True)
        else:
            try:
                features.set("IncrementalGraphUpdates", False)
                # trace_ops matches the main sweep: the two curves must
                # be measured under identical instrumentation, or the
                # ratio reports tracing overhead as an overlay effect.
                # (At --tiny scale on a small CPU box the ratio is
                # smoke, not signal — a 120-namespace re-encode is ~ms;
                # the delta grows with graph scale.)
                sweep_off = run_sweep(
                    make_config, ops, off_mults, slo_s,
                    max_workers=workers, trace_ops=True,
                    drain_timeout=(8.0 if tiny else 15.0),
                    on_point=lambda p: log(
                        f"[macro overlay-off x{p.multiplier}] "
                        f"offered={p.offered_rps:.0f}/s "
                        f"goodput={p.goodput_rps:.0f}/s shed={p.shed_n} "
                        f"err={p.error_n} late={p.late_n}"))
            finally:
                features.set("IncrementalGraphUpdates", True)
    finally:
        if migrate_live:
            # don't leak a held-at-dual migration thread when a sweep
            # point raises — the happy path already cut above
            try:
                _st = e.migration_status()
                if _st and _st.get("phase") not in ("done", "aborted",
                                                    "failed"):
                    e.abort_schema_migration()
            except Exception:  # noqa: BLE001 - teardown best effort
                pass
        if monitor is not None:
            monitor.stop()
        peak_streams[0] = max(peak_streams[0],
                              harness_box[0].live_streams)
        harness_box[0].close()
        tracer.configure(sample=prev[0], slow_ms=prev[1], ring=prev[2])

    top_cfg = trace_shaped_config(dur, base_rate * multipliers[-1],
                                  tenants=tenants, seed=seed,
                                  burst_multiplier=3.0)
    digest = hashlib.sha256(repr([
        (round(a.t, 9), a.op, a.tenant, a.key, a.phase)
        for a in build_schedule(top_cfg)]).encode()).hexdigest()[:16]

    macro = sweep.to_dict()
    macro["seed"] = seed
    macro["schedule_digest"] = digest
    macro["capacity_rps"] = round(cap_rps, 1)
    macro["base_rate_rps"] = round(base_rate, 1)
    macro["scale"] = {"n_ns": n_ns, "n_users": n_users,
                      "n_groups": n_groups}
    if sweep_off is not None:
        off = sweep_off.to_dict()
        on_by_mult = {p["multiplier"]: p for p in macro["curve"]}
        macro["overlay_off"] = {
            "curve": off["curve"],
            "knee_rps": off.get("knee_rps"),
            "goodput_ratio_on_over_off": {
                str(m): round(
                    on_by_mult[m]["goodput_rps"]
                    / max(p_off["goodput_rps"], 1e-9), 2)
                for m in off_mults
                for p_off in [next(p for p in off["curve"]
                                   if p["multiplier"] == m)]
                if m in on_by_mult
            },
        }
        for m, ratio in macro["overlay_off"][
                "goodput_ratio_on_over_off"].items():
            log(f"[macro] overlay on/off goodput at x{m}: {ratio}x "
                f"(delta overlay vs per-write re-encode)")
    if mig_status is not None:
        macro["migration_live"] = {
            "classification": mig_status.get("classification"),
            "phase": mig_status.get("phase"),
            "time_to_cut_ms": float(
                mig_status.get("time_to_cut_ms") or 0.0),
            "freeze_ms": float(mig_status.get("freeze_ms") or 0.0),
            "backfilled": int(mig_status.get("backfilled") or 0),
        }
    macro["slo_ms"] = {k: round(v * 1e3, 1) for k, v in slo_s.items()}
    macro["watch_streams_opened"] = watch_opened_on
    macro["watch_streams_peak"] = peak_streams_on
    macro["slo_monitor"] = {
        o["name"]: {
            "burn_rate": o["windows"]["30s"]["burn_rate"],
            "attainment": o["windows"]["30s"]["attainment"],
        }
        for o in monitor_objectives
    }
    result[result_key] = macro
    knee_txt = ("~" if sweep.knee_saturated else ">= ") + (
        f"{sweep.knee_rps:.0f}" if sweep.knee_rps is not None else "?")
    log(f"[macro] knee {knee_txt} op/s offered"
        f"{'' if sweep.knee_saturated else ' (never reached)'}; "
        f"attainment {sweep.slo_attainment}; "
        f"{watch_opened_on} watch streams opened "
        f"(tail attribution: {sweep.tail_attribution.get('burst')} "
        f"burst, {sweep.tail_attribution.get('traces', 0)} traces)")


def _fold_macro_migration(result: dict) -> None:
    """Fold the migrate-live macro sub-run into the baseline macro dict
    as ``macro.migration`` — the same-seed knee ratio the ISSUE 19
    acceptance gate reads (>= 0.9x means a live rewriting migration
    costs the serving engine at most 10% of its knee)."""
    mig = result.pop("_macro_migration", None)
    base = result.get("macro")
    if not mig or not base:
        return
    base_knee = base.get("knee_rps")
    mig_knee = mig.get("knee_rps")
    if base_knee and mig_knee:
        knee_ratio = mig_knee / base_knee
        basis = "knee"
    else:
        # the sweep never saturated at this scale (small boxes often
        # don't) — fall back to goodput at the highest common offered-
        # load multiplier, same-seed schedules on both sides
        on = {p["multiplier"]: p["goodput_rps"] for p in base["curve"]}
        off = {p["multiplier"]: p["goodput_rps"] for p in mig["curve"]}
        common = sorted(set(on) & set(off))
        if not common:
            return
        m = common[-1]
        knee_ratio = off[m] / max(on[m], 1e-9)
        basis = f"goodput@x{m}"
    base["migration"] = {
        "knee_ratio": round(float(knee_ratio), 3),
        "basis": basis,
        "knee_rps": mig.get("knee_rps"),
        "curve": mig.get("curve"),
        **(mig.get("migration_live") or {}),
    }
    log(f"[macro] live-migration knee ratio {knee_ratio:.2f}x "
        f"({basis}) — rewriting migration held across the sweep, "
        f"backfilled={base['migration'].get('backfilled')} "
        f"freeze={base['migration'].get('freeze_ms')}ms")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="small graph (CI / CPU smoke)")
    ap.add_argument("--tiny", action="store_true",
                    help="minimal graph (contract-test smoke, seconds)")
    ap.add_argument("--force-full", action="store_true",
                    help="run the full 10M config even on a degraded "
                         "(CPU) backend")
    ap.add_argument("--suite", action="store_true",
                    help="also run BASELINE eval configs 3-5")
    ap.add_argument("--macro-only", action="store_true",
                    help="run ONLY the open-loop macrobench sweep "
                         "(make bench-macro smoke; headline = knee)")
    ap.add_argument("--trials", type=int, default=21)
    ap.add_argument("--retries", type=int, default=2,
                    help="TPU probe attempts before CPU fallback")
    ap.add_argument("--retry-delay", type=float, default=10.0)
    ap.add_argument("--probe-timeout", type=float, default=120.0,
                    help="hard per-attempt timeout for the subprocess "
                         "TPU probe")
    ap.add_argument("--profile-dir",
                    help="write a jax profiler trace of the latency loop "
                         "here (tensorboard/xprof format)")
    ap.add_argument("--remote-compare", action="store_true",
                    help="also serve the engine over loopback TCP and "
                         "measure the remote list-filter (packed-bitmask "
                         "wire) against the in-process path")
    ap.add_argument("--deadline", type=float, default=None,
                    help="overall wall-clock budget (default 1200s, or "
                         "2400s with --suite; BENCH_DEADLINE overrides); "
                         "the watchdog emits whatever was measured and "
                         "exits when it expires")
    args = ap.parse_args()
    if args.deadline is None:
        env = os.environ.get("BENCH_DEADLINE")
        # the default budget covers the headline run; the suite's three
        # extra graph builds need their own allowance on top
        args.deadline = float(env) if env else (2400 if args.suite else 1200)

    # The contract: this process ALWAYS prints exactly one JSON line on
    # stdout, whatever happens (r01 crashed before printing; r02 was
    # SIGTERMed outside any try block). Partial results beat no results.
    result: dict = {
        "metric": "p50 list-filter latency (wall), not measured",
        "value": None, "unit": "ms", "vs_baseline": None,
    }

    def on_signal(signum, frame):  # noqa: ARG001
        result.setdefault("error", f"killed by signal {signum}")
        result["degraded"] = True
        emit(result, 128 + signum, os_exit=True)

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)

    def watchdog():
        time.sleep(args.deadline)
        result.setdefault(
            "error", f"deadline {args.deadline:.0f}s exceeded; "
            "emitting partial result")
        # a deadline partial is not a backend downgrade: numbers captured
        # before the cutoff keep their provenance (window #1 measured the
        # whole headline on a real chip, then the tunnel hung mid-suite —
        # marking that run "degraded" would misfile real-chip data)
        result["deadline_exceeded"] = True
        if result.get("backend") != "tpu":
            result["degraded"] = True
        log(f"WATCHDOG: deadline {args.deadline:.0f}s exceeded")
        emit(result, 2, os_exit=True)

    threading.Thread(target=watchdog, daemon=True).start()

    code = 0
    try:
        _measure(args, result)
    except BaseException as e:  # noqa: BLE001 - emit, then re-signal
        import traceback

        traceback.print_exc(file=sys.stderr)
        result["error"] = f"{type(e).__name__}: {e}"[:500]
        result["degraded"] = True
        code = 1
    emit(result, code)
    sys.exit(code)


if __name__ == "__main__":
    main()
