"""Headline benchmark: the BASELINE.md north-star config.

Filter a 100k-pod list response against a 10M-relationship graph on one
chip — the reference's prefilter/list hot path (SURVEY.md §3.3:
runLookupResources + filterList) executed as one slot-space reachability
query (`Engine.lookup_resources_mask`). Also reports bulk-check throughput
(reference CheckBulkPermissions path, SURVEY.md §3.2) on stderr.

Prints ONE JSON line on stdout:
    {"metric": ..., "value": p50_ms, "unit": "ms", "vs_baseline": ...}
vs_baseline is the 50 ms BASELINE.json target divided by the measured p50
(>1.0 means the target is beaten).

Usage: python bench.py [--quick]   (--quick: small graph, CPU-friendly)
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

BENCH_SCHEMA = """
use expiration

definition user {}
definition group {
  relation member: user
}
definition namespace {
  relation creator: user
  relation viewer: user | group#member
  permission admin = creator
  permission view = viewer + creator
}
definition pod {
  relation namespace: namespace
  relation creator: user
  relation viewer: user
  permission edit = creator
  permission view = viewer + creator + namespace->view
}
"""


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def build_engine(n_pods: int, n_users: int, n_ns: int, n_groups: int,
                 n_rels: int, seed: int = 0):
    """Synthesize the graph columnar-side (no per-row Python objects)."""
    from spicedb_kubeapi_proxy_tpu.engine import Engine
    from spicedb_kubeapi_proxy_tpu.models import parse_schema

    rng = np.random.default_rng(seed)
    pods = np.char.add("ns/p", np.arange(n_pods).astype(str))
    users = np.char.add("u", np.arange(n_users).astype(str))
    groups = np.char.add("g", np.arange(n_groups).astype(str))
    nss = np.char.add("ns", np.arange(n_ns).astype(str))

    cols = {k: [] for k in ("resource_type", "resource_id", "relation",
                            "subject_type", "subject_id", "subject_relation")}

    def add(rt, rid, rl, st, sid, srl=None):
        n = len(rid)
        cols["resource_type"].append(np.full(n, rt))
        cols["resource_id"].append(rid)
        cols["relation"].append(np.full(n, rl))
        cols["subject_type"].append(np.full(n, st))
        cols["subject_id"].append(sid)
        cols["subject_relation"].append(
            np.full(n, srl if srl is not None else ""))

    # group membership: ~20 users per group
    gm = min(20 * n_groups, n_rels // 20)
    add("group", groups[rng.integers(n_groups, size=gm)], "member",
        "user", users[rng.integers(n_users, size=gm)])
    # namespace viewer grants via groups (2 per ns) — exercises the
    # group#member userset + namespace->view arrow rewrite chain
    nv = 2 * n_ns
    add("namespace", nss[rng.integers(n_ns, size=nv)], "viewer",
        "group", groups[rng.integers(n_groups, size=nv)], "member")
    # every pod lives in a namespace
    pod_ns = np.char.add("ns", rng.integers(n_ns, size=n_pods).astype(str))
    add("pod", pods, "namespace", "namespace", pod_ns)
    # the rest: flat pod#viewer@user direct grants, deduplicated
    n_flat = n_rels - gm - nv - n_pods
    pair = rng.integers(0, n_pods * n_users, size=int(n_flat * 1.01),
                        dtype=np.int64)
    pair = np.unique(pair)[:n_flat]
    rng.shuffle(pair)
    add("pod", pods[pair // n_users], "viewer", "user", users[pair % n_users])

    rels_cols = {k: np.concatenate(v) for k, v in cols.items()}
    total = len(rels_cols["resource_id"])
    log(f"built columns: {total} relationships")

    e = Engine(schema=parse_schema(BENCH_SCHEMA))
    t0 = time.perf_counter()
    e.bulk_load(rels_cols)
    log(f"bulk_load: {time.perf_counter() - t0:.1f}s")
    return e, total


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="small graph (CI / CPU smoke)")
    ap.add_argument("--trials", type=int, default=21)
    args = ap.parse_args()

    if args.quick:
        n_pods, n_users, n_ns, n_groups, n_rels = 2_000, 500, 50, 50, 50_000
    else:
        n_pods, n_users, n_ns, n_groups, n_rels = (
            100_000, 10_000, 1_000, 1_000, 10_000_000)

    import jax

    log(f"jax {jax.__version__} devices={jax.devices()}")
    e, total = build_engine(n_pods, n_users, n_ns, n_groups, n_rels)

    t0 = time.perf_counter()
    cg = e.compiled()
    log(f"compile_graph: {time.perf_counter() - t0:.1f}s "
        f"(M={cg.M} slots, E={cg.n_edges} edges)")

    # -- p50 list-filter latency: one user's visibility mask over all pods --
    rng = np.random.default_rng(1)
    subjects = [f"u{rng.integers(n_users)}" for _ in range(args.trials)]
    t0 = time.perf_counter()
    mask, _ = e.lookup_resources_mask("pod", "view", "user", subjects[0])
    log(f"warmup (jit compile + run): {time.perf_counter() - t0:.1f}s; "
        f"visible={int(mask.sum())}/{n_pods}")
    lat = []
    for u in subjects:
        t0 = time.perf_counter()
        mask, _ = e.lookup_resources_mask("pod", "view", "user", u)
        lat.append((time.perf_counter() - t0) * 1e3)
    p50_wall = float(np.percentile(lat, 50))
    p99_wall = float(np.percentile(lat, 99))

    # Transport floor: this environment reaches the chip through a network
    # tunnel, so every dispatch+readback pays a fixed RTT (~65ms measured
    # via a trivial jitted op) that a locally-attached v5e does not. The
    # floor is measured with an identically-shaped null dispatch and
    # subtracted; both raw wall and floor are logged for transparency.
    import jax.numpy as jnp

    q = jnp.zeros(len(mask), dtype=jnp.int32)
    null_fn = jax.jit(lambda q: (q > 0, jnp.bool_(True)))
    np.asarray(null_fn(q)[0])  # compile
    floor = []
    for _ in range(len(subjects)):
        t0 = time.perf_counter()
        out, _ = null_fn(q)
        np.asarray(out)
        floor.append((time.perf_counter() - t0) * 1e3)
    p50_floor = float(np.percentile(floor, 50))
    device_est = p50_wall - p50_floor
    if device_est >= 1.0:
        p50, note = device_est, f"device; tunnel RTT {p50_floor:.0f}ms excluded"
    else:
        # floor subtraction is unreliable below measurement noise (or the
        # query fully overlaps the RTT) — fall back to raw wall clock
        p50, note = p50_wall, "wall clock incl tunnel RTT"
    log(f"list-filter latency over {len(lat)} trials: "
        f"p50_wall={p50_wall:.2f}ms p99_wall={p99_wall:.2f}ms; "
        f"transport floor p50={p50_floor:.2f}ms -> reported p50={p50:.2f}ms "
        f"({note})")

    # -- bulk-check throughput (stderr only) --
    from spicedb_kubeapi_proxy_tpu.engine import CheckItem

    B, per = (8, 64) if args.quick else (64, 1024)
    items = [
        CheckItem("pod", f"ns/p{rng.integers(n_pods)}", "view",
                  "user", f"u{b}")
        for b in rng.integers(n_users, size=B)
        for _ in range(per)
    ]
    e.check_bulk(items[: B * per])  # warmup shape
    t0 = time.perf_counter()
    e.check_bulk(items)
    dt = time.perf_counter() - t0
    checks_per_s = len(items) / dt
    log(f"bulk check: {len(items)} checks in {dt * 1e3:.1f}ms "
        f"= {checks_per_s:,.0f} checks/s/chip")

    print(json.dumps({
        "metric": (
            f"p50 list-filter latency ({note}), {n_pods} pods @ {total} "
            f"rels, 1 chip"),
        "value": round(p50, 3),
        "unit": "ms",
        "vs_baseline": round(50.0 / p50, 2),
    }), flush=True)


if __name__ == "__main__":
    main()
