"""TLS serving + client-certificate authentication end-to-end.

Mirrors the reference's e2e harness, which stamps per-user client certs
(CommonName = username, Organization = groups) from a self-made CA and
talks to the proxy over TLS (/root/reference/e2e/e2e_test.go:215-318;
client-cert authn mode authn.go:40-47)."""

import asyncio
import datetime
import ipaddress
import json
import ssl

import pytest

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import NameOID

from spicedb_kubeapi_proxy_tpu.proxy.options import Options, OptionsError

from fake_kube import FakeKube

RULES = open(__import__("os").path.join(
    __import__("os").path.dirname(__file__), "..", "deploy",
    "rules.yaml")).read()
BOOT = open(__import__("os").path.join(
    __import__("os").path.dirname(__file__), "..", "deploy",
    "bootstrap.yaml")).read()


def _key():
    return ec.generate_private_key(ec.SECP256R1())


def _name(cn, orgs=()):
    rdns = [x509.NameAttribute(NameOID.COMMON_NAME, cn)]
    rdns += [x509.NameAttribute(NameOID.ORGANIZATION_NAME, o) for o in orgs]
    return x509.Name(rdns)


def _cert(subject, issuer, pub, signer, *, ca=False, san=None):
    now = datetime.datetime.now(datetime.timezone.utc)
    b = (x509.CertificateBuilder()
         .subject_name(subject)
         .issuer_name(issuer)
         .public_key(pub)
         .serial_number(x509.random_serial_number())
         .not_valid_before(now - datetime.timedelta(minutes=5))
         .not_valid_after(now + datetime.timedelta(days=1))
         .add_extension(x509.BasicConstraints(ca=ca, path_length=None),
                        critical=True))
    if san:
        b = b.add_extension(x509.SubjectAlternativeName(san), critical=False)
    return b.sign(signer, hashes.SHA256())


@pytest.fixture(scope="module")
def pki(tmp_path_factory):
    """CA + server cert + per-user client certs, PEM files on disk."""
    d = tmp_path_factory.mktemp("pki")
    ca_key = _key()
    ca_name = _name("test-ca")
    ca_cert = _cert(ca_name, ca_name, ca_key.public_key(), ca_key, ca=True)

    def write(path, *objs):
        data = b"".join(
            o.private_bytes(serialization.Encoding.PEM,
                            serialization.PrivateFormat.PKCS8,
                            serialization.NoEncryption())
            if hasattr(o, "private_bytes")
            else o.public_bytes(serialization.Encoding.PEM)
            for o in objs)
        p = d / path
        p.write_bytes(data)
        return str(p)

    files = {"ca": write("ca.pem", ca_cert)}
    srv_key = _key()
    srv_cert = _cert(
        _name("proxy"), ca_name, srv_key.public_key(), ca_key,
        san=[x509.DNSName("localhost"),
             x509.IPAddress(ipaddress.ip_address("127.0.0.1"))])
    files["server_cert"] = write("server.pem", srv_cert)
    files["server_key"] = write("server-key.pem", srv_key)
    for user, orgs in (("alice", ["team-alpha"]), ("bob", []),
                       ("front-proxy", [])):
        k = _key()
        c = _cert(_name(user, orgs), ca_name, k.public_key(), ca_key)
        files[user] = write(f"{user}.pem", c, k)
    return files


class TlsClient:
    """Minimal HTTP/1.1 client over TLS with an optional client cert."""

    def __init__(self, port, ca, cert=None):
        self.port = port
        self.ctx = ssl.create_default_context(cafile=ca)
        if cert:
            self.ctx.load_cert_chain(cert)

    async def request(self, method, target, body=None, headers=()):
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", self.port, ssl=self.ctx,
            server_hostname="localhost")
        data = json.dumps(body).encode() if body is not None else b""
        lines = [f"{method} {target} HTTP/1.1", "Host: localhost",
                 f"Content-Length: {len(data)}",
                 "Content-Type: application/json", "Connection: close"]
        lines += list(headers)
        writer.write(("\r\n".join(lines) + "\r\n\r\n").encode() + data)
        await writer.drain()
        status = int((await reader.readline()).split(b" ")[1])
        hdrs = {}
        while True:
            line = await reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            k, _, v = line.decode().partition(":")
            hdrs[k.strip().lower()] = v.strip()
        if "chunked" in hdrs.get("transfer-encoding", ""):
            chunks = []
            while True:
                size = int((await reader.readline()).strip() or b"0", 16)
                if size == 0:
                    break
                chunks.append(await reader.readexactly(size))
                await reader.readline()
            out = b"".join(chunks)
        else:
            n = int(hdrs.get("content-length", 0))
            out = await reader.readexactly(n) if n else b""
        writer.close()
        return status, out


def test_tls_client_cert_end_to_end(pki, tmp_path):
    """Two cert-authenticated users see disjoint lists over TLS; identity
    headers are ignored in favor of (and only trusted with) certs."""
    async def go():
        cfg = Options(
            rule_content=RULES,
            bootstrap_content=BOOT,
            upstream=FakeKube(),
            workflow_database_path=str(tmp_path / "dtx.sqlite"),
            bind_port=0,
            tls_cert_file=pki["server_cert"],
            tls_key_file=pki["server_key"],
            tls_client_ca_file=pki["ca"],
        ).complete()
        await cfg.run()
        port = cfg.server.port
        alice = TlsClient(port, pki["ca"], pki["alice"])
        bob = TlsClient(port, pki["ca"], pki["bob"])
        nocert = TlsClient(port, pki["ca"])

        # health over TLS needs no identity
        status, body = await nocert.request("GET", "/readyz")
        assert (status, body) == (200, b"ok")

        # dual-write create as the cert identity
        status, body = await alice.request(
            "POST", "/api/v1/namespaces",
            body={"apiVersion": "v1", "kind": "Namespace",
                  "metadata": {"name": "team-a"}})
        assert status == 201, body

        # list isolation between the two cert users
        status, body = await alice.request("GET", "/api/v1/namespaces")
        assert [o["metadata"]["name"]
                for o in json.loads(body)["items"]] == ["team-a"]
        status, body = await bob.request("GET", "/api/v1/namespaces")
        assert json.loads(body)["items"] == []

        # single-object isolation
        status, _ = await alice.request("GET", "/api/v1/namespaces/team-a")
        assert status == 200
        status, _ = await bob.request("GET", "/api/v1/namespaces/team-a")
        assert status == 403

        # with a client CA configured, X-Remote-* headers from a CERT-LESS
        # connection are stripped, not trusted: spoofing alice fails
        status, _ = await nocert.request(
            "GET", "/api/v1/namespaces", headers=["X-Remote-User: alice"])
        assert status == 401

        # ...and a cert-bearing peer's headers cannot override the cert
        status, body = await bob.request(
            "GET", "/api/v1/namespaces", headers=["X-Remote-User: alice"])
        assert json.loads(body)["items"] == []

        await cfg.server.stop()
        await cfg.workflow.shutdown()
    asyncio.run(go())


def test_tls_front_proxy_allowed_names(pki, tmp_path):
    """A cert whose CN is in --tls-requestheader-allowed-name is a trusted
    front proxy: its X-Remote-* headers carry the end-user identity
    (kube's requestheader contract). Other cert users' headers do not."""
    async def go():
        cfg = Options(
            rule_content=RULES,
            bootstrap_content=BOOT,
            upstream=FakeKube(),
            workflow_database_path=str(tmp_path / "dtx.sqlite"),
            bind_port=0,
            tls_cert_file=pki["server_cert"],
            tls_key_file=pki["server_key"],
            tls_client_ca_file=pki["ca"],
            tls_requestheader_allowed_names=["front-proxy"],
        ).complete()
        await cfg.run()
        port = cfg.server.port
        front = TlsClient(port, pki["ca"], pki["front-proxy"])
        bob = TlsClient(port, pki["ca"], pki["bob"])

        # the front proxy creates as carol via headers
        status, body = await front.request(
            "POST", "/api/v1/namespaces",
            body={"apiVersion": "v1", "kind": "Namespace",
                  "metadata": {"name": "carol-ns"}},
            headers=["X-Remote-User: carol"])
        assert status == 201, body
        status, body = await front.request(
            "GET", "/api/v1/namespaces", headers=["X-Remote-User: carol"])
        assert [o["metadata"]["name"]
                for o in json.loads(body)["items"]] == ["carol-ns"]
        # without identity headers the front proxy has NO identity at all
        # (it authenticates users, it isn't one): 401
        status, body = await front.request("GET", "/api/v1/namespaces")
        assert status == 401
        # an ordinary cert user still cannot assert headers
        status, body = await bob.request(
            "GET", "/api/v1/namespaces", headers=["X-Remote-User: carol"])
        assert json.loads(body)["items"] == []
        await cfg.server.stop()
        await cfg.workflow.shutdown()
    asyncio.run(go())


def test_tls_without_client_ca_keeps_header_authn(pki, tmp_path):
    """TLS-only mode (no client CA): headers still authenticate — the
    embedded/front-proxy deployment shape, now encrypted."""
    async def go():
        cfg = Options(
            rule_content=RULES,
            bootstrap_content=BOOT,
            upstream=FakeKube(),
            workflow_database_path=str(tmp_path / "dtx.sqlite"),
            bind_port=0,
            tls_cert_file=pki["server_cert"],
            tls_key_file=pki["server_key"],
        ).complete()
        await cfg.run()
        c = TlsClient(cfg.server.port, pki["ca"])
        status, body = await c.request(
            "POST", "/api/v1/namespaces",
            body={"apiVersion": "v1", "kind": "Namespace",
                  "metadata": {"name": "hdr-ns"}},
            headers=["X-Remote-User: carol"])
        assert status == 201, body
        status, body = await c.request(
            "GET", "/api/v1/namespaces", headers=["X-Remote-User: carol"])
        assert [o["metadata"]["name"]
                for o in json.loads(body)["items"]] == ["hdr-ns"]
        await cfg.server.stop()
        await cfg.workflow.shutdown()
    asyncio.run(go())


def test_tls_option_validation():
    base = dict(rule_content="x", upstream_url="http://u")
    with pytest.raises(OptionsError, match="set together"):
        Options(tls_cert_file="c.pem", **base).validate()
    with pytest.raises(OptionsError, match="requires"):
        Options(tls_client_ca_file="ca.pem", **base).validate()
    with pytest.raises(OptionsError, match="requires"):
        Options(tls_requestheader_allowed_names=["fp"], **base).validate()
