"""Kube protobuf content negotiation: envelope decode, list wire surgery,
single-object passthrough (reference responsefilterer.go:242-313)."""

import json

import pytest

from spicedb_kubeapi_proxy_tpu.authz.filterer import (
    FilterError,
    apply_filter,
    filter_body_proto,
)
from spicedb_kubeapi_proxy_tpu.authz.lookups import AllowedSet
from spicedb_kubeapi_proxy_tpu.proxy import kubeproto
from spicedb_kubeapi_proxy_tpu.proxy.types import ProxyResponse
from spicedb_kubeapi_proxy_tpu.rules.input import ResolveInput, UserInfo
from spicedb_kubeapi_proxy_tpu.proxy.requestinfo import parse_request_info


# -- hand-rolled encoders (tests only; product code never builds these) ----


def ld(field_no: int, payload: bytes) -> bytes:
    return kubeproto._ld_field(field_no, payload)


def s(field_no: int, text: str) -> bytes:
    return ld(field_no, text.encode())


def object_meta(name: str, namespace: str = "") -> bytes:
    out = s(1, name)
    if namespace:
        out += s(3, namespace)
    return out


def item(name: str, namespace: str = "", extra: bytes = b"") -> bytes:
    # e.g. a Pod: metadata=1 (+ arbitrary other fields the surgery must
    # preserve byte-identically)
    return ld(1, object_meta(name, namespace)) + extra


def klist(items: list[bytes], list_meta: bytes = b"") -> bytes:
    out = ld(1, list_meta or s(2, "rv123"))  # ListMeta (opaque here)
    for it in items:
        out += ld(2, it)
    return out


def unknown(kind: str, raw: bytes, api_version: str = "v1") -> bytes:
    tm = s(1, api_version) + s(2, kind)
    return kubeproto.MAGIC + ld(1, tm) + ld(2, raw) \
        + s(4, kubeproto.CONTENT_TYPE)


def allowed_set(pairs) -> AllowedSet:
    a = AllowedSet()
    for ns, name in pairs:
        a.add(ns, name)
    return a


def make_input(verb="list", path="/api/v1/pods"):
    info = parse_request_info("GET", path, {})
    return ResolveInput.create(
        info, UserInfo(name="alice", groups=[], extra={}))


def test_envelope_round_trip():
    raw = klist([item("a", "ns1"), item("b", "ns2")])
    body = unknown("PodList", raw)
    api, kind, got_raw = kubeproto.decode_unknown(body)
    assert (api, kind) == ("v1", "PodList")
    assert got_raw == raw
    # replacing raw with itself reproduces the body byte-identically
    assert kubeproto.replace_unknown_raw(body, raw) == body


def test_list_filtering_preserves_kept_bytes():
    extra = ld(2, b"\x08\x01")  # fake spec field on the item
    items = [item("a", "ns1", extra), item("b", "ns2"), item("c", "ns1")]
    raw = klist(items)
    kept = kubeproto.filter_list_raw(
        raw, lambda ns, name: (ns, name) != ("ns2", "b"))
    assert kept == klist([items[0], items[2]])
    # item bytes (incl. unknown fields) are untouched
    assert ld(2, items[0]) in kept and ld(2, items[2]) in kept


def test_filter_body_proto_list():
    raw = klist([item("a", "ns1"), item("b", "ns2")])
    body = unknown("PodList", raw)
    status, out = filter_body_proto(
        body, allowed_set([("ns1", "a")]), make_input())
    assert status == 200
    _, _, new_raw = kubeproto.decode_unknown(out)
    names = [kubeproto.item_meta(p)
             for f, w, _, p in kubeproto.fields(new_raw) if f == 2]
    assert names == [("ns1", "a")]


def test_filter_body_proto_single_object_passthrough():
    body = unknown("Namespace", ld(1, object_meta("team-a")))
    inp = make_input(verb="get", path="/api/v1/namespaces/team-a")
    status, out = filter_body_proto(
        body, allowed_set([("", "team-a")]), inp)
    assert (status, out) == (200, body)  # byte-identical
    status, out = filter_body_proto(body, allowed_set([]), inp)
    assert status == 404


def raw_extension(obj: bytes) -> bytes:
    return ld(1, obj)  # runtime.RawExtension: raw=1


def table_row(name: str, namespace: str = "", wrap_unknown: bool = True,
              cells: bytes = b"", with_object: bool = True) -> bytes:
    # TableRow: cells=1, conditions=2, object(RawExtension)=3
    pom = ld(1, object_meta(name, namespace))  # PartialObjectMetadata
    obj = unknown("PartialObjectMetadata", pom,
                  api_version="meta.k8s.io/v1") if wrap_unknown else pom
    out = cells or ld(1, raw_extension(b'"c1"'))
    if with_object:
        out += ld(3, raw_extension(obj))
    return out


def table(rows: list[bytes]) -> bytes:
    # Table: metadata=1, columnDefinitions=2, rows=3
    out = ld(1, s(2, "rv9")) + ld(2, s(1, "Name"))
    for r in rows:
        out += ld(3, r)
    return out


def test_proto_table_row_filtering_both_object_encodings():
    """Proto Table rows filter at the wire level; the row object may be a
    nested magic-prefixed runtime.Unknown (kube's proto RawExtension
    encoding) or bare PartialObjectMetadata — kept rows byte-identical
    (reference responsefilterer.go:349-374)."""
    for wrap in (True, False):
        rows = [table_row("a", "ns1", wrap_unknown=wrap),
                table_row("b", "ns2", wrap_unknown=wrap),
                table_row("c", "", wrap_unknown=wrap)]
        raw = table(rows)
        body = unknown("Table", raw, api_version="meta.k8s.io/v1")
        status, out = filter_body_proto(
            body, allowed_set([("ns1", "a"), ("", "c")]), make_input())
        assert status == 200, wrap
        _, kind, new_raw = kubeproto.decode_unknown(out)
        assert kind == "Table"
        assert new_raw == table([rows[0], rows[2]]), wrap
        # non-row fields (metadata, columnDefinitions) byte-identical
        assert ld(1, s(2, "rv9")) in new_raw
        assert ld(2, s(1, "Name")) in new_raw


def test_proto_table_without_row_objects_clean_4xx():
    """includeObject=None rows carry nothing to authorize against: the
    filter must yield a clean 401 (FilterError), never a 500 (VERDICT r3
    weak #7)."""
    raw = table([table_row("a", "ns1", with_object=False)])
    body = unknown("Table", raw, api_version="meta.k8s.io/v1")
    with pytest.raises(FilterError, match="row"):
        filter_body_proto(body, allowed_set([("ns1", "a")]), make_input())
    # through apply_filter: a clean 401 response
    resp = ProxyResponse(
        status=200, headers={"Content-Type": kubeproto.CONTENT_TYPE},
        body=body)
    out = apply_filter(resp, allowed_set([("ns1", "a")]), make_input())
    assert out.status == 401


def test_apply_filter_negotiates_proto():
    raw = klist([item("x", "nsA"), item("y", "nsB")])
    resp = ProxyResponse(
        status=200,
        headers={"Content-Type": kubeproto.CONTENT_TYPE},
        body=unknown("PodList", raw))
    out = apply_filter(resp, allowed_set([("nsB", "y")]), make_input())
    assert out.status == 200
    assert out.headers["Content-Type"] == kubeproto.CONTENT_TYPE
    _, _, new_raw = kubeproto.decode_unknown(out.body)
    assert [kubeproto.item_meta(p)
            for f, w, _, p in kubeproto.fields(new_raw)
            if f == 2] == [("nsB", "y")]
    # malformed proto -> 401, not a crash
    bad = ProxyResponse(
        status=200, headers={"Content-Type": kubeproto.CONTENT_TYPE},
        body=b"not-protobuf")
    out = apply_filter(bad, allowed_set([]), make_input())
    assert out.status == 401


def test_upstream_accept_negotiation():
    from spicedb_kubeapi_proxy_tpu.proxy.upstream import rewrite_accept

    # client-go protobuf default: proto range now forwarded
    assert rewrite_accept(
        "application/vnd.kubernetes.protobuf,application/json", False
    ) == "application/vnd.kubernetes.protobuf,application/json"
    # protobuf Tables are filterable now: the range passes through
    assert rewrite_accept(
        "application/vnd.kubernetes.protobuf;as=Table;v=v1;g=meta.k8s.io,"
        "application/json", False
    ) == ("application/vnd.kubernetes.protobuf;as=Table;v=v1;g=meta.k8s.io,"
          "application/json")
    # JSON Tables pass through untouched
    assert rewrite_accept(
        "application/json;as=Table;v=v1;g=meta.k8s.io,application/json",
        False
    ) == "application/json;as=Table;v=v1;g=meta.k8s.io,application/json"
    # watch requests negotiate protobuf too now (ProtobufWatch, default on)
    assert rewrite_accept(
        "application/vnd.kubernetes.protobuf,application/json", True
    ) == "application/vnd.kubernetes.protobuf,application/json"
    # json_only (the postfilter path) strips protobuf unconditionally
    assert rewrite_accept(
        "application/vnd.kubernetes.protobuf,application/json", False,
        json_only=True) == "application/json"


def test_watch_downgrade_gate_and_metric():
    """ProtobufWatch=false restores the JSON downgrade — and counts each
    downgraded watch request in /metrics (VERDICT r4 Weak #5: silent
    re-encoding of a proto watch fleet must be visible)."""
    from spicedb_kubeapi_proxy_tpu.proxy.upstream import rewrite_accept
    from spicedb_kubeapi_proxy_tpu.utils.features import features
    from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

    counter = metrics.counter("proxy_proto_watch_downgrades_total")
    features.set("ProtobufWatch", False)
    try:
        before = counter.value
        assert rewrite_accept(
            "application/vnd.kubernetes.protobuf,application/json", True
        ) == "application/json"
        # pure-proto accept on a watch falls back to JSON rather than empty
        assert rewrite_accept(
            "application/vnd.kubernetes.protobuf", True
        ) == "application/json"
        assert counter.value == before + 2  # one per downgraded request
        # a JSON-only watch is not a downgrade
        assert rewrite_accept("application/json", True) \
            == "application/json"
        # nor is a non-watch proto request
        assert rewrite_accept(
            "application/vnd.kubernetes.protobuf,application/json", False
        ) == "application/vnd.kubernetes.protobuf,application/json"
        assert counter.value == before + 2
    finally:
        features.reset()


# -- protobuf watch frames ---------------------------------------------------


def test_watch_frame_encode_decode_round_trip():
    env = unknown("Namespace", item("ns-a"))
    frame = kubeproto.encode_watch_frame("ADDED", env)
    # length prefix covers exactly the body
    assert int.from_bytes(frame[:4], "big") == len(frame) - 4
    typ, raw = kubeproto.decode_watch_event(frame[4:])
    assert typ == "ADDED" and raw == env
    assert kubeproto.watch_frame_key(frame) == ("", "ns-a")


def test_watch_frame_key_shapes():
    # namespaced object
    env = unknown("Pod", item("api", "prod"))
    assert kubeproto.watch_frame_key(
        kubeproto.encode_watch_frame("MODIFIED", env)) == ("prod", "api")
    # BOOKMARK: progress marker, no key (passes through for everyone)
    assert kubeproto.watch_frame_key(
        kubeproto.encode_watch_frame("BOOKMARK", env)) is None
    # terminal Status (watch expiry): no object to judge
    st = unknown("Status", b"")
    assert kubeproto.watch_frame_key(
        kubeproto.encode_watch_frame("ERROR", st)) is None
    # Table-wrapped event keys on its first row
    tbl = unknown("Table", table([table_row("rowed", "nsX")]))
    assert kubeproto.watch_frame_key(
        kubeproto.encode_watch_frame("ADDED", tbl)) == ("nsX", "rowed")
    # an event with no keyable object raises (the join ends the stream
    # rather than leaking it)
    import pytest as _pytest

    with _pytest.raises(kubeproto.ProtoError):
        kubeproto.watch_frame_key(
            kubeproto.encode_watch_frame("ADDED", unknown("Pod", b"")))


def test_http_upstream_streams_proto_frames_whole():
    """_stream_body reframes proto watch bodies on the 4-byte length
    prefix (not newlines): frames arrive whole and byte-identical even
    when their bytes contain 0x0A."""
    import asyncio

    from fake_kube import FakeKube, serve_upstream
    from spicedb_kubeapi_proxy_tpu.proxy.types import ProxyRequest
    from spicedb_kubeapi_proxy_tpu.proxy.upstream import HttpUpstream

    async def go():
        fake = FakeKube()
        # a name containing a raw newline byte once encoded would split a
        # naive newline framer; prove the length framer keeps it whole
        fake.objects[("namespaces", "", "nl\nname")] = {
            "kind": "Namespace",
            "metadata": {"name": "nl\nname"}}
        fake.objects[("namespaces", "", "plain")] = {
            "kind": "Namespace", "metadata": {"name": "plain"}}
        server, port = await serve_upstream(fake)
        upstream = HttpUpstream(f"http://127.0.0.1:{port}")
        req = ProxyRequest(
            method="GET", path="/api/v1/namespaces",
            query={"watch": ["true"]},
            headers={"Accept": kubeproto.CONTENT_TYPE},
            body=b"")
        resp = await upstream(req)
        assert resp.status == 200 and resp.stream is not None
        assert "protobuf" in resp.headers.get("Content-Type", "")
        frames = []
        async for f in resp.stream:
            frames.append(f)
            if len(frames) == 2:
                break
        keys = [kubeproto.watch_frame_key(f) for f in frames]
        assert ("", "nl\nname") in keys and ("", "plain") in keys
        for f in frames:
            assert int.from_bytes(f[:4], "big") == len(f) - 4
        fake.stop_watches()
        server.close()

    asyncio.run(go())


def test_json_path_unchanged():
    doc = {"kind": "PodList", "items": [
        {"metadata": {"name": "a", "namespace": "ns1"}},
        {"metadata": {"name": "b", "namespace": "ns2"}}]}
    resp = ProxyResponse(status=200,
                         headers={"Content-Type": "application/json"},
                         body=json.dumps(doc).encode())
    out = apply_filter(resp, allowed_set([("ns1", "a")]), make_input())
    assert [o["metadata"]["name"]
            for o in json.loads(out.body)["items"]] == ["a"]
