"""Schema DSL parser + tuple parsing tests (models/)."""

import pytest

from spicedb_kubeapi_proxy_tpu.models import (
    Arrow,
    Exclude,
    Intersect,
    Nil,
    RelationRef,
    SchemaError,
    Union,
    parse_bootstrap,
    parse_schema,
)
from spicedb_kubeapi_proxy_tpu.models.bootstrap import DEFAULT_BOOTSTRAP
from spicedb_kubeapi_proxy_tpu.models.tuples import (
    Relationship,
    TupleError,
    parse_rel_fields,
    parse_relationship,
)

REFERENCE_SCHEMA = """
use expiration

definition cluster {}
definition user {}
definition namespace {
  relation cluster: cluster
  relation creator: user
  relation viewer: user

  permission admin = creator
  permission edit = creator
  permission view = viewer + creator
  permission no_one_at_all = nil
}
definition pod {
  relation namespace: namespace
  relation creator: user
  relation viewer: user
  permission edit = creator
  permission view = viewer + creator
}
definition lock {
  relation workflow: workflow
}

definition workflow {
  relation idempotency_key: activity with expiration
}

definition activity{}
"""


def test_parse_reference_bootstrap_schema():
    s = parse_schema(REFERENCE_SCHEMA)
    assert s.use_expiration
    assert set(s.definitions) == {
        "cluster", "user", "namespace", "pod", "lock", "workflow", "activity",
    }
    ns = s.definitions["namespace"]
    assert set(ns.relations) == {"cluster", "creator", "viewer"}
    assert set(ns.permissions) == {"admin", "edit", "view", "no_one_at_all"}
    view = ns.permissions["view"].expr
    assert view == Union((RelationRef("viewer"), RelationRef("creator")))
    assert ns.permissions["no_one_at_all"].expr == Nil()
    wf = s.definitions["workflow"]
    assert wf.relations["idempotency_key"].allowed[0].expiration


def test_userset_wildcard_and_arrow():
    s = parse_schema("""
    definition user {}
    definition group {
      relation member: user | group#member
    }
    definition folder {
      relation parent: folder
      relation viewer: user | user:* | group#member
      permission view = viewer + parent->view
    }
    """)
    g = s.definitions["group"].relations["member"]
    assert g.allowed[1].relation == "member"
    f = s.definitions["folder"]
    viewer = f.relations["viewer"]
    assert viewer.allowed[1].wildcard
    expr = f.permissions["view"].expr
    assert expr == Union((RelationRef("viewer"), Arrow("parent", "view")))


def test_intersection_exclusion_parens():
    s = parse_schema("""
    definition user {}
    definition doc {
      relation a: user
      relation b: user
      relation c: user
      permission p = (a & b) - c
      permission q = a - (b + c)
    }
    """)
    d = s.definitions["doc"]
    p = d.permissions["p"].expr
    assert p == Exclude(Intersect((RelationRef("a"), RelationRef("b"))), RelationRef("c"))
    q = d.permissions["q"].expr
    assert q == Exclude(RelationRef("a"), Union((RelationRef("b"), RelationRef("c"))))


def test_comments_and_caveats_tolerated():
    s = parse_schema("""
    // line comment
    /* block
       comment */
    caveat only_on_tuesday(day string) {
      day == "tuesday"
    }
    definition user {}
    """)
    assert "user" in s.definitions


@pytest.mark.parametrize(
    "bad,msg",
    [
        ("definition a { relation r: nosuch }", "unknown subject type"),
        ("definition a { permission p = nope }", "unknown relation"),
        ("definition user {} definition a { relation r: user } definition a {}", "duplicate definition"),
        ("definition a { relation r: a relation r: a }", "duplicate"),
        ("definition user {} definition g { relation m: user } definition a { relation r: g#nosuch }", "unknown subject relation"),
        ("definition user {} definition a { relation t: user permission p = t->nothing }", "arrow target"),
        ("definition user {} definition a { permission p = p2->x }", "tupleset"),
    ],
)
def test_validation_errors(bad, msg):
    with pytest.raises(SchemaError, match=msg):
        parse_schema(bad)


def test_parse_relationship_roundtrip():
    r = parse_relationship("namespace:spicedb-kubeapi-proxy#viewer@user:rakis")
    assert r == Relationship("namespace", "spicedb-kubeapi-proxy", "viewer", "user", "rakis")
    assert str(r) == "namespace:spicedb-kubeapi-proxy#viewer@user:rakis"

    r2 = parse_relationship("pod:default/nginx#viewer@group:eng#member")
    assert r2.resource_id == "default/nginx"
    assert r2.subject_relation == "member"

    r3 = parse_relationship(
        "workflow:abc#idempotency_key@activity:xyz[expiration:2030-01-01T00:00:00Z]"
    )
    assert r3.expiration is not None and r3.expiration > 1.8e9
    assert "expiration:2030-01-01T00:00:00Z" in str(r3)

    # '...' subject relation normalizes to None
    r4 = parse_relationship("a:b#c@d:e#...")
    assert r4.subject_relation is None

    # email-shaped subject ids: '@' inside an id field is data, not the
    # structural separator (which always follows '#relation')
    r5 = parse_relationship("namespace:x#viewer@user:alice@example.com")
    assert r5.subject_id == "alice@example.com"
    assert str(r5) == "namespace:x#viewer@user:alice@example.com"
    r6 = parse_relationship("ns:x#viewer@group:eng@corp#member")
    assert (r6.subject_id, r6.subject_relation) == ("eng@corp", "member")


def test_parse_relationship_errors():
    for bad in ["nope", "a:b@c:d", "a:b#c@d", ":x#y@z:w"]:
        with pytest.raises(TupleError):
            parse_relationship(bad)


def test_parse_rel_fields_templates():
    f = parse_rel_fields("pod:{{namespacedName}}#creator@user:{{user.name}}")
    assert f["resource_type"] == "pod"
    assert f["resource_id"] == "{{namespacedName}}"
    assert f["subject_id"] == "{{user.name}}"
    assert f["subject_relation"] is None
    f2 = parse_rel_fields("namespace:$#view@user:{{user.name}}")
    assert f2["resource_id"] == "$"
    # literal template ids may carry '@' (user:alice@example.com in a rule
    # template must compile, not fail at boot)
    f3 = parse_rel_fields("namespace:x#viewer@user:alice@example.com")
    assert f3["subject_id"] == "alice@example.com"


def test_parse_bootstrap_default():
    b = parse_bootstrap(DEFAULT_BOOTSTRAP)
    assert "namespace" in b.schema.definitions
    assert b.relationships == []


def test_parse_bootstrap_multi_doc():
    b = parse_bootstrap("""
schema: |-
  definition user {}
  definition ns {
    relation viewer: user
  }
relationships: |
  ns:a#viewer@user:alice
  ns:b#viewer@user:bob
""")
    assert len(b.relationships) == 2
    assert b.relationships[0].resource_id == "a"


def test_review_findings_regressions():
    # Trailing garbage / malformed expiration traits are rejected, not absorbed.
    for bad in [
        "a:b#c@d:e[expiration:2030-01-01T00:00:00Z]x",
        "a:b#c@d:e[expiration:notclosed",
        "a:b#c@d:e]junk",
    ]:
        with pytest.raises(TupleError):
            parse_relationship(bad)

    # Keywords are reserved as relation/permission names.
    with pytest.raises(SchemaError, match="reserved keyword"):
        parse_schema("definition user {} definition a { relation nil: user }")

    # Arrows over wildcard-able tuplesets are rejected.
    with pytest.raises(SchemaError, match="wildcard"):
        parse_schema("""
        definition user {}
        definition folder {
          relation parent: folder:*
          relation viewer: user
          permission view = viewer + parent->view
        }
        """)

    # Caller bootstraps missing lock/workflow/activity get them appended.
    b = parse_bootstrap("schema: |\n  definition user {}\n")
    assert {"lock", "workflow", "activity"} <= set(b.schema.definitions)
    # ...without clobbering caller-provided ones.
    b2 = parse_bootstrap(
        "schema: |\n  definition user {}\n  definition activity {}\n  definition lock { relation workflow: workflow }\n"
    )
    assert "workflow" in b2.schema.definitions

    # Wildcard subject ids still parse as concrete tuples.
    r = parse_relationship("pod:x#viewer@user:*")
    assert r.subject_id == "*"


def test_relevant_resource_types():
    """The schema walk that gates watch recomputes: exactly the types
    whose writes can affect a permission, through relations, usersets,
    arrows, and recursive groups; unrelated types excluded."""
    from spicedb_kubeapi_proxy_tpu.models.schema import (
        relevant_resource_types,
    )

    s = parse_schema("""
definition user {}
definition team { relation member: user | group#member }
definition group { relation member: user | group#member }
definition namespace {
  relation creator: user
  relation viewer: group#member
  permission view = viewer + creator
}
definition pod {
  relation namespace: namespace
  relation viewer: user
  permission view = viewer + namespace->view
}
definition unrelated { relation owner: user }
""")
    assert relevant_resource_types(s, "pod", "view") == {
        "pod", "namespace", "group"}
    assert relevant_resource_types(s, "namespace", "view") == {
        "namespace", "group"}
    # recursive groups terminate; team is NOT pulled in by pod#view
    assert relevant_resource_types(s, "group", "member") == {"group"}
    assert "unrelated" not in relevant_resource_types(s, "pod", "view")
    # a relation (not permission) target works too
    assert relevant_resource_types(s, "pod", "viewer") == {"pod"}


def test_watch_relevance_scopes_expiration_to_watched_permission():
    """`with expiration` anywhere in the schema must NOT make every
    watcher tick (advisor r3): the flag is true only when a relation the
    watched permission can reach allows expiring tuples."""
    from spicedb_kubeapi_proxy_tpu.models.schema import watch_relevance

    s = parse_schema("""
use expiration
definition user {}
definition group { relation member: user | group#member }
definition badge { relation holder: user with expiration }
definition namespace {
  relation creator: user
  relation viewer: group#member
  permission view = viewer + creator
}
definition door {
  relation badge: badge
  permission open = badge->holder
}
""")
    assert s.use_expiration  # the schema-wide flag is set...
    # ...but namespace#view cannot reach badge#holder: no expiry tick
    types, expires = watch_relevance(s, "namespace", "view")
    assert types == {"namespace", "group"}
    assert expires is False
    # door#open walks badge->holder, which expires
    types, expires = watch_relevance(s, "door", "open")
    assert "badge" in types
    assert expires is True
    # watching the expiring relation itself
    _, expires = watch_relevance(s, "badge", "holder")
    assert expires is True
    # userset-reached expiring relation: group#member with expiration
    s2 = parse_schema("""
use expiration
definition user {}
definition group { relation member: user with expiration }
definition ns {
  relation viewer: group#member
  permission view = viewer
}
""")
    _, expires = watch_relevance(s2, "ns", "view")
    assert expires is True


# ---------------------------------------------------------------------------
# parser DX: errors name the enclosing definition/relation (ISSUE 19)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "bad,where",
    [
        ("definition user {}\ndefinition pod {\n"
         "  relation viewer user\n}",
         "in definition 'pod', relation 'viewer'"),
        ("definition user {}\ndefinition pod {\n"
         "  relation viewer: user |\n}",
         "in definition 'pod', relation 'viewer'"),
        ("definition user {}\ndefinition pod {\n"
         "  permission view = viewer +\n}",
         "in definition 'pod', permission 'view'"),
        ("definition user {}\ndefinition pod {\n"
         "  relation viewer: user with\n}",
         "in definition 'pod', relation 'viewer'"),
    ],
)
def test_parse_errors_name_enclosing_scope(bad, where):
    """An operator editing a 500-line schema needs 'in definition
    <d>, relation <r>', not a bare line number."""
    with pytest.raises(SchemaError) as ei:
        parse_schema(bad)
    msg = str(ei.value)
    assert where in msg
    assert "schema line" in msg  # the line number survives too


# ---------------------------------------------------------------------------
# migration diff stability under definition reordering (ISSUE 19)
# ---------------------------------------------------------------------------

_DIFF_BASE = """
definition user {}
definition group {
  relation member: user | group#member
}
definition namespace {
  relation viewer: user | group#member
  permission view = viewer
}
definition pod {
  relation namespace: namespace
  relation viewer: user
  permission view = viewer + namespace->view
}
"""

_DIFF_TARGET = """
caveat probation(level int) {
  level < 3
}

definition user {}
definition group {
  relation member: user | group#member
}
definition namespace {
  relation viewer: user | group#member
  permission view = viewer
}
definition pod {
  relation namespace: namespace
  relation viewer: user | user with probation
  permission view = viewer + namespace->view
}
"""


def _shuffled(text: str, rng) -> str:
    """Permute top-level blocks (definitions + caveats) of a schema
    text — same IR, different declaration order."""
    import re

    blocks = re.split(r"(?m)^(?=definition |caveat )", text)
    head, body = blocks[0], blocks[1:]
    rng.shuffle(body)
    return head + "".join(body)


def test_diff_classification_stable_under_reordering():
    """SchemaDiff is frozenset-based by construction: permuting either
    side's definitions yields an EQUAL diff and an identical ir_digest
    — the migration layer's identity test must not depend on the order
    an operator happened to write the file in."""
    import random

    from spicedb_kubeapi_proxy_tpu.models.schema import (
        diff_schemas,
        ir_digest,
    )

    base = parse_schema(_DIFF_BASE)
    target = parse_schema(_DIFF_TARGET)
    ref = diff_schemas(base, target)
    assert ref.classification == "rewriting"
    rng = random.Random(0x5EED)
    for _ in range(25):
        base2 = parse_schema(_shuffled(_DIFF_BASE, rng))
        target2 = parse_schema(_shuffled(_DIFF_TARGET, rng))
        assert ir_digest(base2) == ir_digest(base)
        assert ir_digest(target2) == ir_digest(target)
        got = diff_schemas(base2, target2)
        assert got == ref  # frozen dataclass: full structural equality
