"""OIDC bearer authentication: JWS primitives, claim validation, JWKS
fetch/rotation, and the proxy-level bearer path.

Mirrors what kube's OIDC authenticator gives the reference for free
(/root/reference/pkg/proxy/authn.go:40-47): locally-signed JWTs against a
JWKS fixture; bad-issuer / expired / wrong-audience / forged tokens are
rejected."""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from spicedb_kubeapi_proxy_tpu.proxy import jose
from spicedb_kubeapi_proxy_tpu.proxy.oidc import (
    ChainTokenAuthenticator,
    OIDCAuthenticator,
    OIDCError,
)

# fixed 1024-bit RSA test key (test fixture only — never a real identity)
RSA_N = int(
    "ce40bb0ca6889fb84e84f99e498056fdfde2860b02b1e0d95cb54080a79bed8c"
    "dc093c8acaece1d5468ac9c273a3f44c914f4f06d1e552c087ae96cc1574606e"
    "80c45c91db07c2becd804629d22b71f4661aea5c4aae6ce4953603af153715cf"
    "cf7b4cc24704633a45bde58ea2a8f90134c08644e73e4c76b7ba3b1e8348aa09", 16)
RSA_E = 65537
RSA_D = int(
    "a0f66f83fdeb9e0aae6ca48a5d7e6565af5fbb909837cdec94a77781704d0664"
    "e9cbe38dc5b47cc27f5d0cfc4e5763eee57069923ef8a34e521574e62cd037f8"
    "5cd9770ae5fe14adde3677eb8ef0bf3338e6681fc1eb8aad2c86418de4e5643b"
    "c40873019ffee7d5bfb543f4dc2644db86753da77fb49aeef9b55dcb63e05c21", 16)

# fixed P-256 test key
EC_D = int("84db091bf646b1f4775321d32e14b9c44bf8c481aa803c34f0823d06f9a149f1",
           16)
EC_X = int("0e4d38f438926f38c39d985213ef119375c65900cad1bffe8e16eb0253fd2c13",
           16)
EC_Y = int("a28242f69cd3c963e8f1e907565573c3f0c5ab1bbfd6bcad0030230dddea9bfb",
           16)

ISSUER = "https://idp.test"
CLIENT_ID = "kube-proxy"


def _int_b64(i: int, size: int = 0) -> str:
    b = i.to_bytes(max(size, (i.bit_length() + 7) // 8), "big")
    return jose.b64url_encode(b)


def rsa_jwk(kid: str = "rsa-1") -> dict:
    return {"kty": "RSA", "kid": kid, "alg": "RS256", "use": "sig",
            "n": _int_b64(RSA_N), "e": _int_b64(RSA_E)}


def ec_jwk(kid: str = "ec-1") -> dict:
    return {"kty": "EC", "kid": kid, "crv": "P-256", "use": "sig",
            "x": _int_b64(EC_X, 32), "y": _int_b64(EC_Y, 32)}


def sign_jwt(claims: dict, alg: str = "RS256", kid: str = "rsa-1",
             header_extra: dict = ()) -> str:
    header = {"alg": alg, "typ": "JWT", **({"kid": kid} if kid else {}),
              **dict(header_extra)}
    si = (jose.b64url_encode(json.dumps(header).encode()) + "." +
          jose.b64url_encode(json.dumps(claims).encode()))
    if alg.startswith("RS"):
        sig = jose.rsa_pkcs1v15_sign(RSA_N, RSA_D, si.encode(),
                                     jose._HASHES[alg])
    elif alg == "ES256":
        import secrets

        sig = jose.ecdsa_sign(jose.P256, EC_D, si.encode(),
                              2 + secrets.randbelow(jose.P256.n - 3),
                              "sha256")
    else:
        raise AssertionError(alg)
    return si + "." + jose.b64url_encode(sig)


def std_claims(**over) -> dict:
    # wide margins: parametrize lists evaluate these at IMPORT time, and
    # a full-suite run can put hours between collection and execution
    c = {"iss": ISSUER, "aud": CLIENT_ID, "sub": "alice",
         "exp": time.time() + 6 * 3600}
    c.update(over)
    return c


def make_auth(**over) -> OIDCAuthenticator:
    kw = dict(issuer_url=ISSUER, client_id=CLIENT_ID,
              jwks_uri="jwks", fetch=lambda url: json.dumps(
                  {"keys": [rsa_jwk(), ec_jwk()]}).encode(),
              signing_algs=("RS256", "ES256"))
    kw.update(over)
    return OIDCAuthenticator(**kw)


# -- jose primitives ---------------------------------------------------------


def test_rsa_sign_verify_roundtrip_and_tamper():
    msg = b"covered bytes"
    sig = jose.rsa_pkcs1v15_sign(RSA_N, RSA_D, msg, "sha256")
    assert jose.rsa_pkcs1v15_verify(RSA_N, RSA_E, msg, sig, "sha256")
    assert not jose.rsa_pkcs1v15_verify(RSA_N, RSA_E, b"other", sig,
                                        "sha256")
    bad = bytearray(sig)
    bad[-1] ^= 1
    assert not jose.rsa_pkcs1v15_verify(RSA_N, RSA_E, msg, bytes(bad),
                                        "sha256")
    # wrong length / s >= n rejected outright
    assert not jose.rsa_pkcs1v15_verify(RSA_N, RSA_E, msg, sig[:-1],
                                        "sha256")


def test_ecdsa_sign_verify_roundtrip_and_tamper():
    msg = b"covered bytes"
    sig = jose.ecdsa_sign(jose.P256, EC_D, msg, k=12345, hash_name="sha256")
    assert jose.ecdsa_verify(jose.P256, EC_X, EC_Y, msg, sig, "sha256")
    assert not jose.ecdsa_verify(jose.P256, EC_X, EC_Y, b"other", sig,
                                 "sha256")
    bad = bytearray(sig)
    bad[7] ^= 1
    assert not jose.ecdsa_verify(jose.P256, EC_X, EC_Y, msg, bytes(bad),
                                 "sha256")
    # r or s out of range
    zero = b"\x00" * 32 + sig[32:]
    assert not jose.ecdsa_verify(jose.P256, EC_X, EC_Y, msg, zero, "sha256")
    # a public point off the curve must not verify anything
    assert not jose.ecdsa_verify(jose.P256, EC_X, EC_Y + 1, msg, sig,
                                 "sha256")


def test_jws_key_type_confusion_rejected():
    """An RS-alg token must not verify against an EC key or vice versa,
    and HS* (symmetric) algs are structurally unsupported — the classic
    JWKS-as-HMAC-secret downgrade cannot exist."""
    tok = sign_jwt(std_claims())
    header, _, si, sig = jose.parse_compact(tok)
    with pytest.raises(jose.JoseError):
        jose.verify_jws(header, si, sig, ec_jwk())
    with pytest.raises(jose.JoseError):
        jose.verify_jws({"alg": "HS256"}, si, sig, rsa_jwk())
    with pytest.raises(jose.JoseError):
        jose.verify_jws({"alg": "none"}, si, b"", rsa_jwk())


# -- authenticator claim validation ------------------------------------------


def test_valid_token_maps_identity_with_default_prefix():
    a = make_auth()
    user = a.authenticate_token(sign_jwt(std_claims()))
    assert user is not None
    # kube default: non-email username claims get the issuer# prefix
    assert user.name == f"{ISSUER}#alice"
    assert user.groups == []


def test_username_prefix_dash_and_custom():
    a = make_auth(username_prefix="-")
    assert a.authenticate_token(sign_jwt(std_claims())).name == "alice"
    a = make_auth(username_prefix="oidc:")
    assert a.authenticate_token(sign_jwt(std_claims())).name == "oidc:alice"


def test_groups_claim_string_and_list_with_prefix():
    a = make_auth(groups_claim="roles", groups_prefix="oidc:")
    u = a.authenticate_token(sign_jwt(std_claims(roles=["dev", "ops"])))
    assert u.groups == ["oidc:dev", "oidc:ops"]
    u = a.authenticate_token(sign_jwt(std_claims(roles="dev")))
    assert u.groups == ["oidc:dev"]
    # non-string group entries reject the token
    assert a.authenticate_token(
        sign_jwt(std_claims(roles=["dev", 7]))) is None


def test_email_claim_requires_verified():
    a = make_auth(username_claim="email")
    ok = std_claims(email="a@b.test", email_verified=True)
    assert a.authenticate_token(sign_jwt(ok)).name == "a@b.test"
    # absent email_verified is accepted (kube semantics)...
    del ok["email_verified"]
    assert a.authenticate_token(sign_jwt(ok)) is not None
    # ...but present-and-false rejects
    bad = std_claims(email="a@b.test", email_verified=False)
    assert a.authenticate_token(sign_jwt(bad)) is None


@pytest.mark.parametrize("claims,why", [
    (std_claims(iss="https://evil.test"), "bad issuer"),
    (std_claims(exp=time.time() - 120), "expired"),
    (std_claims(aud="other-client"), "wrong audience"),
    (std_claims(aud=["a", "b"]), "aud list without client id"),
    (std_claims(nbf=time.time() + 6 * 3600), "not yet valid"),
    ({k: v for k, v in std_claims().items() if k != "exp"}, "no exp"),
    ({k: v for k, v in std_claims().items() if k != "sub"}, "no username"),
])
def test_invalid_claims_rejected(claims, why):
    assert make_auth().authenticate_token(sign_jwt(claims)) is None, why


def test_aud_list_containing_client_id_accepted():
    a = make_auth()
    tok = sign_jwt(std_claims(aud=["other", CLIENT_ID]))
    assert a.authenticate_token(tok) is not None


def test_forged_signature_and_alg_confusion_rejected():
    a = make_auth()
    tok = sign_jwt(std_claims())
    h, p, s = tok.split(".")
    # flip a payload byte: signature no longer covers it
    p2 = jose.b64url_encode(
        json.dumps(std_claims(sub="mallory")).encode())
    assert a.authenticate_token(f"{h}.{p2}.{s}") is None
    # alg not in the accepted set
    rs384 = sign_jwt(std_claims(), alg="RS384")
    assert a.authenticate_token(rs384) is None
    # structurally not a JWT
    assert a.authenticate_token("not-a-jwt") is None
    assert a.authenticate_token("") is None


def test_es256_token_verifies():
    a = make_auth()
    tok = sign_jwt(std_claims(), alg="ES256", kid="ec-1")
    assert a.authenticate_token(tok) is not None


def test_unknown_kid_triggers_rate_limited_refresh(monkeypatch):
    """Key rotation: an unknown kid refetches the JWKS once; repeated
    unknown kids within the cooldown do NOT hammer the IDP."""
    calls = []
    keys = {"keys": [rsa_jwk("old")]}

    def fetch(url):
        calls.append(url)
        return json.dumps(keys).encode()

    a = make_auth(fetch=fetch)
    tok_old = sign_jwt(std_claims(), kid="old")
    assert a.authenticate_token(tok_old) is not None
    assert len(calls) == 1
    # rotate: the server now serves kid=new
    keys = {"keys": [rsa_jwk("new")]}
    monkeypatch.setattr(
        "spicedb_kubeapi_proxy_tpu.proxy.oidc.REFRESH_COOLDOWN", 0.0)
    tok_new = sign_jwt(std_claims(), kid="new")
    assert a.authenticate_token(tok_new) is not None
    assert len(calls) == 2
    # cooldown: a storm of unknown kids must not hammer the IDP — the
    # refresh just happened, so ghost kids trigger ZERO further fetches
    monkeypatch.setattr(
        "spicedb_kubeapi_proxy_tpu.proxy.oidc.REFRESH_COOLDOWN", 600.0)
    for _ in range(5):
        assert a.authenticate_token(
            sign_jwt(std_claims(), kid="ghost")) is None
    assert len(calls) == 2


def test_jwks_fetch_failure_fails_closed_and_cools_down():
    calls = []

    def fetch(url):
        calls.append(url)
        raise OSError("idp down")

    a = make_auth(fetch=fetch)
    # fails closed, and a token storm against a down IDP costs ONE fetch
    # per cooldown window, not one per token (review finding)
    for _ in range(5):
        assert a.authenticate_token(sign_jwt(std_claims())) is None
    assert len(calls) == 1


def test_issuer_comparison_is_exact_including_trailing_slash():
    """kube's OIDC authenticator compares iss to the configured issuer
    exactly — a trailing-slash-only difference rejects (advisor finding:
    normalizing both sides accepted tokens kube would refuse)."""
    a = make_auth()  # configured issuer has no trailing slash
    assert a.authenticate_token(
        sign_jwt(std_claims(iss=ISSUER + "/"))) is None
    # and the reverse: configured WITH slash only accepts iss with slash
    b = make_auth(issuer_url=ISSUER + "/")
    assert b.authenticate_token(
        sign_jwt(std_claims(iss=ISSUER + "/"))) is not None
    assert b.authenticate_token(sign_jwt(std_claims())) is None


def test_hung_jwks_fetch_blocks_only_the_triggering_request(monkeypatch):
    """Stale-while-revalidate: with the key map cached, a token bearing an
    unknown kid may stall on a hung IDP fetch, but concurrent validations
    whose kid IS cached must complete without waiting on that socket
    (VERDICT r4 Weak #6 / directive #6)."""
    release = threading.Event()
    fetched_once = threading.Event()

    def fetch(url):
        if fetched_once.is_set():
            # second fetch = the rotation refetch: hang until released
            assert release.wait(30), "test released too late"
            raise OSError("idp gone")
        fetched_once.set()
        return json.dumps({"keys": [rsa_jwk()]}).encode()

    a = make_auth(fetch=fetch)
    assert a.authenticate_token(sign_jwt(std_claims())) is not None  # prime
    monkeypatch.setattr(
        "spicedb_kubeapi_proxy_tpu.proxy.oidc.REFRESH_COOLDOWN", 0.0)

    hung_done = threading.Event()

    def hung_request():
        a.authenticate_token(sign_jwt(std_claims(), kid="rotated"))
        hung_done.set()

    t = threading.Thread(target=hung_request, daemon=True)
    t.start()
    # wait until the refresher actually owns the refresh lock
    deadline = time.monotonic() + 5
    while not a._refresh_lock.locked() and time.monotonic() < deadline:
        time.sleep(0.005)
    assert a._refresh_lock.locked(), "refresh never started"
    # cached-kid validations proceed while the fetch hangs
    t0 = time.monotonic()
    assert a.authenticate_token(sign_jwt(std_claims())) is not None
    assert time.monotonic() - t0 < 1.0, "cached-kid auth waited on fetch"
    # a SECOND unknown-kid token queues behind the in-flight fetch, but
    # the wait is BOUNDED by the fetch timeout, not the hang duration
    second_done = threading.Event()

    def second_request():
        assert a.authenticate_token(
            sign_jwt(std_claims(), kid="rotated2")) is None
        second_done.set()

    t2 = threading.Thread(target=second_request, daemon=True)
    t2.start()
    time.sleep(0.3)
    assert not second_done.is_set(), "waiter should block on the fetch"
    assert not hung_done.is_set()
    release.set()
    t.join(10)
    t2.join(10)
    assert hung_done.is_set() and second_done.is_set()


def test_initial_jwks_fetch_is_single_flight():
    """Before any keys are cached, exactly one request performs the fetch;
    concurrent first requests WAIT for it (bounded by the fetch timeout)
    and then validate against the fresh cache — a restart under a
    reconnect storm must not turn one fetch's latency into spurious
    401s."""
    release = threading.Event()
    calls = []

    def fetch(url):
        calls.append(url)
        assert release.wait(30)
        return json.dumps({"keys": [rsa_jwk()]}).encode()

    a = make_auth(fetch=fetch)
    results = {}

    def auth(slot):
        results[slot] = a.authenticate_token(sign_jwt(std_claims()))

    t = threading.Thread(target=auth, args=("first",), daemon=True)
    t.start()
    deadline = time.monotonic() + 5
    while not a._refresh_lock.locked() and time.monotonic() < deadline:
        time.sleep(0.005)
    # a concurrent request queues on the single-flight lock (it must not
    # issue its own fetch) ...
    t2 = threading.Thread(target=auth, args=("second",), daemon=True)
    t2.start()
    time.sleep(0.3)
    assert "second" not in results, "waiter should block on the fetch"
    assert len(calls) == 1
    release.set()
    t.join(10)
    t2.join(10)
    # ... and BOTH succeed from the one fetch once the IDP answers
    assert results["first"] is not None
    assert results["second"] is not None
    assert len(calls) == 1


def test_kidless_token_tries_all_candidate_keys():
    """A mixed-kty JWKS with kid-less keys: the EC key raising a
    key-type mismatch must not abort the scan before the RSA key verifies
    (review finding)."""
    jwks = {"keys": [
        {k: v for k, v in ec_jwk().items() if k != "kid"},
        {k: v for k, v in rsa_jwk().items() if k != "kid"},
    ]}
    a = make_auth(fetch=lambda url: json.dumps(jwks).encode())
    tok = sign_jwt(std_claims(), kid=None)
    assert a.authenticate_token(tok) is not None


def test_email_verified_string_forms_accepted():
    a = make_auth(username_claim="email")
    ok = std_claims(email="a@b.test", email_verified="true")
    assert a.authenticate_token(sign_jwt(ok)) is not None
    bad = std_claims(email="a@b.test", email_verified="false")
    assert a.authenticate_token(sign_jwt(bad)) is None


def test_config_errors():
    with pytest.raises(OIDCError):
        OIDCAuthenticator(issuer_url="", client_id="x")
    with pytest.raises(OIDCError):
        OIDCAuthenticator(issuer_url=ISSUER, client_id="x",
                          signing_algs=("HS256",))


# -- discovery over real HTTP ------------------------------------------------


def test_discovery_document_fetch_over_http():
    """End-to-end JWKS resolution: issuer discovery document → jwks_uri →
    keys, over a real local HTTP server."""
    state = {}

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path == "/.well-known/openid-configuration":
                body = json.dumps({
                    "issuer": state["issuer"],
                    "jwks_uri": state["base"] + "/keys"}).encode()
            elif self.path == "/keys":
                body = json.dumps({"keys": [rsa_jwk()]}).encode()
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), Handler)
    state["base"] = f"http://127.0.0.1:{srv.server_port}"
    state["issuer"] = state["base"]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        a = OIDCAuthenticator(issuer_url=state["base"], client_id=CLIENT_ID)
        claims = std_claims(iss=state["base"])
        user = a.authenticate_token(sign_jwt(claims))
        assert user is not None and user.name.endswith("#alice")
        # a discovery document for a DIFFERENT issuer is rejected
        state["issuer"] = "https://evil.test"
        b = OIDCAuthenticator(issuer_url=state["base"], client_id=CLIENT_ID)
        assert b.authenticate_token(sign_jwt(claims)) is None
    finally:
        srv.shutdown()
        srv.server_close()


# -- proxy-level bearer path -------------------------------------------------


def test_chain_token_authenticator_order_and_401():
    from spicedb_kubeapi_proxy_tpu.proxy.authn import TokenFileAuthenticator

    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".csv",
                                     delete=False) as f:
        f.write("static-tok,carol,uid-1\n")
        path = f.name
    chain = ChainTokenAuthenticator(
        [TokenFileAuthenticator(path), make_auth()])
    assert chain.authenticate_token("static-tok").name == "carol"
    oidc_user = chain.authenticate_token(sign_jwt(std_claims()))
    assert oidc_user is not None and oidc_user.name.endswith("#alice")
    assert chain.authenticate_token("bogus") is None


def test_proxy_server_oidc_bearer_end_to_end():
    """A bearer JWT authenticates a real proxied request; a forged one
    gets 401 (not a fall-through to header identity)."""
    import asyncio

    from spicedb_kubeapi_proxy_tpu.authz import AuthzDeps
    from spicedb_kubeapi_proxy_tpu.engine import Engine, WriteOp
    from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship
    from spicedb_kubeapi_proxy_tpu.proxy.server import Server
    from spicedb_kubeapi_proxy_tpu.proxy.types import ProxyRequest
    from spicedb_kubeapi_proxy_tpu.rules.matcher import MapMatcher

    rules = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
lock: Pessimistic
match:
- apiVersion: v1
  resource: namespaces
  verbs: ["get"]
check:
- tpl: "namespace:{{name}}#view@user:{{user.name}}"
"""
    engine = Engine()
    engine.write_relationships([WriteOp("touch", parse_relationship(
        "namespace:ns1#creator@user:oidc-alice"))])

    async def upstream(req):
        from spicedb_kubeapi_proxy_tpu.proxy.types import ProxyResponse

        return ProxyResponse(status=200, body=b'{"kind":"Namespace"}')

    deps = AuthzDeps(matcher=MapMatcher.from_yaml(rules), engine=engine,
                     upstream=upstream)
    server = Server(deps, token_authenticator=make_auth(
        username_prefix="oidc-"))

    async def go():
        tok = sign_jwt(std_claims())
        req = ProxyRequest(
            method="GET", path="/api/v1/namespaces/ns1",
            headers={"Authorization": f"Bearer {tok}"})
        resp = await server.handle(req)
        assert resp.status == 200
        # a token for a user without the grant: authn ok, authz 403
        req = ProxyRequest(
            method="GET", path="/api/v1/namespaces/ns1",
            headers={"Authorization":
                     f"Bearer {sign_jwt(std_claims(sub='bob'))}"})
        assert (await server.handle(req)).status == 403
        # forged token: 401, never falls through to header identity
        req = ProxyRequest(
            method="GET", path="/api/v1/namespaces/ns1",
            headers={"Authorization": "Bearer forged.token.here",
                     "X-Remote-User": "oidc-alice"})
        assert (await server.handle(req)).status == 401

    asyncio.run(go())


def test_options_wiring_and_validation():
    from spicedb_kubeapi_proxy_tpu.proxy.options import (
        Options,
        OptionsError,
    )

    base = dict(rule_content="""
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
lock: Pessimistic
match:
- apiVersion: v1
  resource: namespaces
  verbs: ["get"]
check:
- tpl: "namespace:{{name}}#view@user:{{user.name}}"
""", upstream=object())
    with pytest.raises(OptionsError, match="oidc-client-id"):
        Options(oidc_issuer_url=ISSUER, **base).validate()
    with pytest.raises(OptionsError, match="require oidc-issuer-url"):
        Options(oidc_client_id="x", **base).validate()
    with pytest.raises(OptionsError, match="require oidc-issuer-url"):
        Options(oidc_username_prefix="corp:", **base).validate()
    with pytest.raises(OptionsError, match="signing-algs"):
        Options(oidc_issuer_url=ISSUER, oidc_client_id="x",
                oidc_signing_algs="HS256", **base).validate()
    Options(oidc_issuer_url=ISSUER, oidc_client_id="x", **base).validate()


def test_required_claims():
    """kube --oidc-required-claim semantics: every configured key=value
    must appear verbatim in the token."""
    a = make_auth(required_claims={"tenant": "acme"})
    assert a.authenticate_token(
        sign_jwt(std_claims(tenant="acme"))) is not None
    assert a.authenticate_token(
        sign_jwt(std_claims(tenant="evil"))) is None
    assert a.authenticate_token(sign_jwt(std_claims())) is None
    from spicedb_kubeapi_proxy_tpu.proxy.options import (
        Options,
        OptionsError,
    )

    base = dict(rule_content="""
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
lock: Pessimistic
match:
- apiVersion: v1
  resource: namespaces
  verbs: ["get"]
check:
- tpl: "namespace:{{name}}#view@user:{{user.name}}"
""", upstream=object())
    with pytest.raises(OptionsError, match="key=value"):
        Options(oidc_issuer_url=ISSUER, oidc_client_id="x",
                oidc_required_claims=["noequals"], **base).validate()
    with pytest.raises(OptionsError, match="require oidc-issuer-url"):
        Options(oidc_required_claims=["a=b"], **base).validate()
    Options(oidc_issuer_url=ISSUER, oidc_client_id="x",
            oidc_required_claims=["tenant=acme"], **base).validate()
