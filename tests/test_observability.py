"""Observability subsystem (ISSUE 6): end-to-end request tracing, the
decision audit log, engine profiling hooks, and the Prometheus exposition
contract.

The e2e pins: a live proxy + tcp engine-host request produces ONE trace
holding both proxy-side and engine-host-side spans (stitched via the wire
frame field); denies always land in the audit log with the matched rule
and trace_id; the failure paths (admission shed, breaker-open
fail-closed, failover re-aim) keep their traces and carry the trace id to
the client.
"""

import asyncio
import json
import os
import re
import time

import pytest

from spicedb_kubeapi_proxy_tpu.obs.audit import AuditLog
from spicedb_kubeapi_proxy_tpu.obs.trace import (
    Tracer,
    format_traceparent,
    parse_traceparent,
    tracer,
)
from spicedb_kubeapi_proxy_tpu.utils.metrics import (
    Histogram,
    Registry,
    metrics,
    snapshot_delta_quantile,
)

RULES = open(os.path.join(os.path.dirname(__file__), "..", "deploy",
                          "rules.yaml")).read()


@pytest.fixture(autouse=True)
def _tracing_on():
    """Every test starts from a clean, keep-everything tracer and leaves
    the module-global in its default state."""
    tracer.configure(sample=1.0, slow_ms=250.0, ring=256)
    tracer.reset()
    yield
    tracer.configure(sample=0.1, slow_ms=250.0, ring=256)
    tracer.reset()


# -- traceparent --------------------------------------------------------------


def test_traceparent_roundtrip():
    tp = format_traceparent("0af7651916cd43dd8448eb211c80319c",
                            "b7ad6b7169203331")
    assert tp == ("00-0af7651916cd43dd8448eb211c80319c-"
                  "b7ad6b7169203331-01")
    trace_id, span_id, flags = parse_traceparent(tp)
    assert trace_id == "0af7651916cd43dd8448eb211c80319c"
    assert span_id == "b7ad6b7169203331"
    assert flags == 1


def test_traceparent_malformed_is_none():
    for bad in (None, "", "garbage", "00-short-short-01",
                "00-" + "0" * 32 + "-" + "1" * 16 + "-01",  # zero trace
                "00-" + "1" * 32 + "-" + "0" * 16 + "-01",  # zero span
                "00-" + "z" * 32 + "-" + "1" * 16 + "-01",  # non-hex
                "00-" + "1" * 32 + "-" + "1" * 16,  # missing flags
                42):
        assert parse_traceparent(bad) is None, bad


def test_concurrent_same_traceparent_requests_stay_separate():
    """A client retry reusing its traceparent while the original is
    still in flight must NOT share a live trace (engine-host spans and
    stage timings would cross-stitch between unrelated requests): the
    second request gets a fresh trace_id, keeping the requested one as
    an attribute."""
    tp = format_traceparent("e" * 32, "f" * 16)
    with tracer.start("request", traceparent=tp) as first:
        with tracer.start("request", traceparent=tp) as second:
            assert second.trace_id != first.trace_id
            assert second.attrs["requested_trace_id"] == "e" * 32
            # adopt() while both live stitches to the ORIGINAL holder
            with tracer.adopt(tp, "engine_host.op") as sp:
                assert sp.trace_id == first.trace_id
        # the inner root's finish must not evict the original live entry
        with tracer.adopt(tp, "engine_host.op2") as sp:
            assert sp.trace_id == first.trace_id
    kept = {t["trace_id"] for t in tracer.recent()}
    assert {first.trace_id, second.trace_id} <= kept


def test_ingress_adopts_incoming_traceparent():
    with tracer.start("request", traceparent=format_traceparent(
            "c" * 32, "d" * 16)) as root:
        assert root.trace_id == "c" * 32
    kept = tracer.recent(1)
    assert kept and kept[0]["trace_id"] == "c" * 32
    # the root's parent is the incoming span id
    root_span = [s for s in kept[0]["spans"] if s["name"] == "request"][0]
    assert root_span["parent_id"] == "d" * 16


# -- tail sampling ------------------------------------------------------------


def test_tail_sampling_keeps_errors_sheds_and_slow_only():
    t = Tracer(sample=0.5, slow_ms=10_000.0, ring=64)
    t.configure(_rand=lambda: 0.99)  # above sample: ordinary drops
    with t.start("request"):
        pass
    assert t.recent() == []
    with t.start("request"):
        t.flag("error", "boom")
    with t.start("request"):
        t.flag("shed")
    assert len(t.recent()) == 2
    t.configure(slow_ms=0.0)  # everything is "slow" now
    with t.start("request"):
        pass
    assert len(t.recent()) == 3
    # sample=0 disables recording entirely
    t.configure(sample=0.0, slow_ms=0.0)
    with t.start("request") as root:
        assert root.trace_id is None
    assert len(t.recent()) == 3


def test_span_exception_flags_trace_error():
    t = Tracer(sample=0.0001, slow_ms=10_000.0, ring=64)
    t.configure(_rand=lambda: 0.99)
    with pytest.raises(RuntimeError):
        with t.start("request"):
            with t.span("engine_dispatch"):
                raise RuntimeError("device fell over")
    kept = t.recent()
    assert len(kept) == 1 and kept[0]["flags"].get("error")
    sp = [s for s in kept[0]["spans"] if s["name"] == "engine_dispatch"][0]
    assert "device fell over" in sp["attrs"]["error"]


def test_spans_cross_executor_hops_via_capture_activate():
    import concurrent.futures

    with tracer.start("request") as root:
        cap = tracer.capture()

        def worker():
            with tracer.activate(cap), tracer.span("engine_device"):
                return tracer.current_trace_id()

        with concurrent.futures.ThreadPoolExecutor(1) as pool:
            tid = pool.submit(worker).result()
        assert tid == root.trace_id
    kept = tracer.recent(1)[0]
    assert {"engine_device", "request"} <= {s["name"]
                                            for s in kept["spans"]}


# -- histogram quantile + exposition ------------------------------------------


def test_histogram_quantile_overflow_clamps_to_max():
    h = Histogram(buckets=(0.001, 0.01))
    h.observe(42.5)
    h.observe(97.25)
    # both observations overflow the last bucket: p50/p99 must be the
    # largest observed value, never float("inf") (BENCH_*.json fields)
    assert h.quantile(0.5) == 97.25
    assert h.quantile(0.99) == 97.25
    assert h.quantile(0.99) != float("inf")
    h.observe(0.0005)
    assert h.quantile(0.01) == 0.001  # in-range targets keep bucket UB


def test_snapshot_delta_quantile_windows():
    h = Histogram(buckets=(0.001, 0.01, 0.1))
    h.observe(0.05)
    before = h.snapshot()
    assert snapshot_delta_quantile(before, h.snapshot(), 0.5) is None
    for _ in range(9):
        h.observe(0.005)
    h.observe(7.0)
    after = h.snapshot()
    assert snapshot_delta_quantile(before, after, 0.5) == 0.01
    assert snapshot_delta_quantile(before, after, 0.999) == 7.0


def test_histogram_renders_cumulative_buckets_and_types():
    r = Registry()
    r.counter("demo_total").inc()
    r.gauge("demo_gauge").set(3)
    h = r.histogram("demo_seconds", dependency="x")
    for v in (0.0001, 0.004, 50.0):
        h.observe(v)
    text = r.render()
    assert "# TYPE demo_total counter" in text
    assert "# TYPE demo_gauge gauge" in text
    assert "# TYPE demo_seconds histogram" in text
    # cumulative bucket series, closed by +Inf == _count
    assert 'demo_seconds_bucket{dependency="x",le="0.005"} 2' in text
    assert 'demo_seconds_bucket{dependency="x",le="+Inf"} 3' in text
    # the historical lines are unchanged (backward compatibility)
    assert 'demo_seconds_count{dependency="x"} 3' in text
    assert 'demo_seconds_sum{dependency="x"}' in text
    # buckets are monotonically non-decreasing
    counts = [int(m.group(1)) for m in re.finditer(
        r'demo_seconds_bucket\{[^}]*\} (\d+)', text)]
    assert counts == sorted(counts)


_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? (.+)$")


def test_metrics_exposition_lints():
    """The scrape-format contract (CI-pinned): every registered metric
    name matches Prometheus naming rules, no duplicate name+label-set
    sample, and every histogram renders a bucket series closed by +Inf.
    Exercises a representative slice of the real instrumentation first so
    the lint sees the names production registers."""
    async def exercise():
        from fake_kube import FakeKube
        from spicedb_kubeapi_proxy_tpu.proxy.inmemory import InMemoryClient
        from spicedb_kubeapi_proxy_tpu.proxy.options import Options

        import tempfile

        cfg = Options(
            rule_content=RULES, upstream=FakeKube(), bind_port=0,
            workflow_database_path=os.path.join(
                tempfile.mkdtemp(prefix="obslint-"), "dtx.sqlite"),
            admission=True,
        ).complete()
        alice = InMemoryClient(cfg.server.handle, user="alice")
        assert (await alice.post(
            "/api/v1/namespaces",
            {"metadata": {"name": "lint"}})).status == 201
        assert (await alice.get("/api/v1/namespaces")).status == 200
        assert (await alice.get("/api/v1/namespaces/lint")).status == 200
        bob = InMemoryClient(cfg.server.handle, user="bob")
        assert (await bob.get("/api/v1/namespaces/lint")).status == 403
        await cfg.workflow.shutdown()

    asyncio.run(exercise())
    text = metrics.render()
    assert text.strip(), "registry rendered empty after real traffic"
    seen: set = set()
    hist_names: set = set()
    for line in text.strip().splitlines():
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ")
            assert _NAME_RE.match(name), f"bad metric name {name!r}"
            if kind == "histogram":
                hist_names.add(name)
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable exposition line {line!r}"
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        assert _NAME_RE.match(name), f"bad metric name {name!r}"
        for lk in re.findall(r'([a-zA-Z0-9_]+)="', labels):
            assert _NAME_RE.match(lk), f"bad label name {lk!r} in {line!r}"
        float(value)  # every sample value parses as a number
        assert (name, labels) not in seen, f"duplicate sample {line!r}"
        seen.add((name, labels))
    assert hist_names, "no histograms registered by real traffic"
    for name in hist_names:
        assert f'{name}_bucket' in text, f"{name} renders no buckets"
        assert re.search(rf'{name}_bucket{{[^}}]*le="\+Inf"}}', text), \
            f"{name} bucket series not closed by +Inf"


# -- audit log ----------------------------------------------------------------


def test_audit_denies_always_allows_rate_capped(tmp_path):
    path = str(tmp_path / "audit.jsonl")
    clock = [0.0]
    a = AuditLog(path, allow_rps=2.0, clock=lambda: clock[0])
    for _ in range(10):
        a.decision(allow=True, verb="list", subject="alice",
                   rule="namespace-list-watch")
    for _ in range(5):
        a.decision(allow=False, verb="get", subject="bob",
                   rule="namespace-get", reason="check denied")
    a.close()
    lines = [json.loads(ln) for ln in open(path)]
    allows = [r for r in lines if r["decision"] == "allow"]
    denies = [r for r in lines if r["decision"] == "deny"]
    assert len(allows) == 2  # burst = allow_rps, clock frozen
    assert len(denies) == 5  # never capped
    assert denies[0]["rule"] == "namespace-get"
    # budget refills with time
    clock[0] += 1.0
    a2 = AuditLog(path, allow_rps=2.0, clock=lambda: clock[0])
    a2.decision(allow=True, verb="list", subject="alice")
    a2.close()
    assert sum(1 for ln in open(path)
               if json.loads(ln)["decision"] == "allow") == 3


# -- e2e: live proxy + tcp engine host ----------------------------------------


def _free_client(handle, user):
    from spicedb_kubeapi_proxy_tpu.proxy.inmemory import InMemoryClient

    return InMemoryClient(handle, user=user)


def test_trace_end_to_end_proxy_tcp_engine(tmp_path):
    """THE acceptance pin: one request through a live proxy + tcp engine
    host yields ONE trace containing proxy-side spans (admission wait,
    engine rpc, upstream) AND engine-host spans (queue wait, device
    dispatch) stitched via the wire frame field; denies always appear in
    the audit log with the matched rule and trace_id."""
    from fake_kube import FakeKube
    from spicedb_kubeapi_proxy_tpu.admission import AdmissionController
    from spicedb_kubeapi_proxy_tpu.engine import Engine
    from spicedb_kubeapi_proxy_tpu.engine.remote import EngineServer
    from spicedb_kubeapi_proxy_tpu.proxy.options import Options

    audit_path = str(tmp_path / "audit.jsonl")

    async def go():
        e = Engine()
        srv = EngineServer(
            e, admission=AdmissionController(
                dependency="engine-admission"))
        port = await srv.start()
        cfg = Options(
            engine_endpoint=f"tcp://127.0.0.1:{port}",
            engine_insecure=True,
            rule_content=RULES,
            upstream=FakeKube(),
            workflow_database_path=str(tmp_path / "dtx.sqlite"),
            admission=True,
            trace_sample=1.0,
            enable_debug_traces=True,
            audit_log=audit_path,
        ).complete()
        await cfg.workflow.resume_pending()
        alice = _free_client(cfg.server.handle, "alice")
        bob = _free_client(cfg.server.handle, "bob")

        resp = await alice.post("/api/v1/namespaces",
                                {"metadata": {"name": "team-a"}})
        assert resp.status == 201, resp.body
        resp = await alice.get("/api/v1/namespaces/team-a")
        assert resp.status == 200
        allow_trace = resp.headers["X-Trace-Id"]
        resp = await bob.get("/api/v1/namespaces/team-a")
        assert resp.status == 403
        deny_trace = resp.headers["X-Trace-Id"]

        # /debug/traces serves the ring; find the allowed get's trace
        resp = await alice.get("/debug/traces")
        assert resp.status == 200
        traces = {t["trace_id"]: t
                  for t in json.loads(resp.body)["traces"]}
        t = traces[allow_trace]
        names = {s["name"] for s in t["spans"]}
        # proxy-side stages
        assert {"request", "rule_match", "admission_wait", "cache_probe",
                "engine_dispatch", "engine_rpc", "upstream"} <= names, \
            names
        # engine-host-side stages, stitched into the SAME trace via the
        # wire frame field
        assert {"engine_host.check_bulk", "engine_queue_wait",
                "engine_device"} <= names, names
        # admission-wait, device-dispatch, and upstream individually
        # timed (finished spans with a recorded duration)
        by_name = {s["name"]: s for s in t["spans"]}
        for stage in ("admission_wait", "engine_device", "upstream"):
            assert by_name[stage]["duration_us"] >= 0
        # the engine-host span names the endpoint it served on
        assert by_name["engine_host.check_bulk"]["attrs"][
            "endpoint"].endswith(str(port))
        # deny trace was kept too (tail sampling at 1.0 keeps all)
        assert deny_trace in traces

        await cfg.workflow.shutdown()
        cfg.engine.close()
        await srv.stop()

        # audit: the deny line carries the matched rule and trace_id
        # (writes drain through the audit writer thread: flush first)
        cfg.deps.audit.flush()
        lines = [json.loads(ln) for ln in open(audit_path)]
        denies = [r for r in lines if r["decision"] == "deny"]
        assert denies, lines
        d = denies[-1]
        assert d["subject"] == "bob"
        assert d["rule"] == "namespace-get"
        assert d["trace_id"] == deny_trace
        assert d["verb"] == "get" and d["name"] == "team-a"
        # per-stage micros recorded up to the decision point
        assert "engine_dispatch" in d["stages_us"] \
            or "cache_probe" in d["stages_us"]
        allows = [r for r in lines if r["decision"] == "allow"]
        assert any(r["trace_id"] == allow_trace for r in allows)

    asyncio.run(go())


def test_admission_shed_503_carries_trace_id_and_shed_flag(tmp_path):
    """Failure path 1: an admission shed's 503 carries the trace id and
    the trace is flagged shed (always kept by tail sampling)."""
    from fake_kube import FakeKube
    from spicedb_kubeapi_proxy_tpu.admission import AdmissionRejected
    from spicedb_kubeapi_proxy_tpu.proxy.options import Options

    class AlwaysShed:
        async def acquire_async(self, tenant, cls):
            raise AdmissionRejected(cls.name, "queue full",
                                    retry_after=2.0)

        def status(self):
            return {"limit": 0, "inflight": 0, "queued": 0,
                    "shed_total": 1}

    async def go():
        cfg = Options(
            rule_content=RULES, upstream=FakeKube(), bind_port=0,
            workflow_database_path=str(tmp_path / "dtx.sqlite"),
            trace_sample=1.0,
        ).complete()
        cfg.deps.admission = AlwaysShed()
        tracer.configure(_rand=lambda: 0.99)  # only flags keep traces
        tracer.configure(sample=0.0001)
        alice = _free_client(cfg.server.handle, "alice")
        resp = await alice.get("/api/v1/namespaces")
        assert resp.status == 503
        assert resp.headers["Retry-After"] == "2"
        trace_id = resp.headers["X-Trace-Id"]
        kept = {t["trace_id"]: t for t in tracer.recent()}
        assert trace_id in kept, "shed trace must survive tail sampling"
        assert kept[trace_id]["flags"].get("shed") is True
        # a shed is the admission design WORKING: it must not pollute an
        # operator's error-trace filter
        assert not kept[trace_id]["flags"].get("error")
        await cfg.workflow.shutdown()

    asyncio.run(go())


def test_breaker_open_fail_closed_trace_kept_with_error(tmp_path):
    """Failure path 2: breaker-open fail-closed 503s keep their trace
    (error-flagged) and carry the trace id to the client."""
    import socket

    from fake_kube import FakeKube
    from spicedb_kubeapi_proxy_tpu.proxy.options import Options

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        dead = s.getsockname()[1]  # bound-then-closed: nothing listens

    async def go():
        cfg = Options(
            engine_endpoint=f"tcp://127.0.0.1:{dead}",
            engine_insecure=True,
            rule_content=RULES, upstream=FakeKube(), bind_port=0,
            workflow_database_path=str(tmp_path / "dtx.sqlite"),
            engine_retries=0, engine_connect_timeout=0.5,
            breaker_failure_threshold=1, breaker_reset_seconds=60.0,
            trace_sample=1.0,
        ).complete()
        tracer.configure(_rand=lambda: 0.99)
        tracer.configure(sample=0.0001)
        alice = _free_client(cfg.server.handle, "alice")
        resp = await alice.get("/api/v1/namespaces")  # trips the breaker
        assert resp.status >= 500
        resp = await alice.get("/api/v1/namespaces")  # breaker-open 503
        assert resp.status == 503
        trace_id = resp.headers["X-Trace-Id"]
        kept = {t["trace_id"]: t for t in tracer.recent()}
        assert trace_id in kept
        assert kept[trace_id]["flags"].get("error")
        await cfg.workflow.shutdown()

    asyncio.run(go())


def test_cross_process_fragments_recorded_and_fetchable_via_wire():
    """An engine host in ANOTHER process records satellite fragments
    under the proxy's trace_id; the wire `traces` op serves its ring so
    the proxy's /debug/traces can stitch them back in."""
    from spicedb_kubeapi_proxy_tpu.engine import Engine
    from spicedb_kubeapi_proxy_tpu.engine.remote import (
        EngineServer,
        RemoteEngine,
    )

    # adopt a traceparent whose trace is NOT live in this process — the
    # cross-process shape — and record a span under it
    tp = format_traceparent("a1" * 16, "b2" * 8)
    with tracer.adopt(tp, "engine_host.check_bulk", endpoint="x") as sp:
        assert sp.trace_id == "a1" * 16
    frags = [t for t in tracer.recent() if t["external"]]
    assert frags and frags[0]["trace_id"] == "a1" * 16
    # the fragment's root hangs off the proxy's wire-carried span id
    root = frags[0]["spans"][0]
    assert root["parent_id"] == "b2" * 8

    async def go():
        e = Engine()
        srv = EngineServer(e)
        port = await srv.start()
        r = RemoteEngine("127.0.0.1", port)
        got = await asyncio.to_thread(r.fetch_traces, 64)
        assert any(t["trace_id"] == "a1" * 16 and t["external"]
                   for t in got)
        r.close()
        await srv.stop()

    asyncio.run(go())


def test_failover_reaim_spans_two_endpoints_one_trace():
    """Failure path 3: a failover re-aim is ONE logical request whose
    spans cover BOTH engine endpoints under a single trace_id."""
    from spicedb_kubeapi_proxy_tpu.engine import CheckItem, Engine
    from spicedb_kubeapi_proxy_tpu.engine.remote import (
        EngineServer,
        FailoverEngine,
    )

    async def go():
        e = Engine()
        follower = EngineServer(
            e, failover_status=lambda: {"role": "follower", "term": 2,
                                        "revision": 0, "peer_id": 0,
                                        "lag": 0})
        leader = EngineServer(
            e, failover_status=lambda: {"role": "leader", "term": 2,
                                        "revision": 0, "peer_id": 1,
                                        "lag": 0})
        p1, p2 = await follower.start(), await leader.start()
        fe = FailoverEngine([("127.0.0.1", p1), ("127.0.0.1", p2)],
                            retries=0)
        with tracer.start("request") as root:
            out = await asyncio.to_thread(
                fe.check_bulk,
                [CheckItem("namespace", "dev", "view", "user", "alice")])
            assert out == [False]
            trace_id = root.trace_id
        kept = {t["trace_id"]: t for t in tracer.recent()}
        t = kept[trace_id]
        endpoints = {s["attrs"].get("endpoint") for s in t["spans"]
                     if s["name"] == "engine_rpc"}
        # the not_leader rejection on p1 and the re-aimed call on p2 are
        # spans of the SAME trace
        assert f"engine:127.0.0.1:{p1}" in endpoints, (endpoints, p1)
        assert f"engine:127.0.0.1:{p2}" in endpoints, (endpoints, p2)
        fe.close()
        await follower.stop()
        await leader.stop()

    asyncio.run(go())


# -- tracing-off invariants ---------------------------------------------------


def test_tracing_disabled_serves_with_no_spans_and_404_debug(tmp_path):
    from fake_kube import FakeKube
    from spicedb_kubeapi_proxy_tpu.proxy.options import Options

    async def go():
        cfg = Options(
            rule_content=RULES, upstream=FakeKube(), bind_port=0,
            workflow_database_path=str(tmp_path / "dtx.sqlite"),
            trace_sample=0.0, enable_debug_traces=True,
        ).complete()
        alice = _free_client(cfg.server.handle, "alice")
        resp = await alice.get("/api/v1/namespaces")
        assert resp.status == 200
        assert "X-Trace-Id" not in resp.headers
        assert tracer.recent() == []
        resp = await alice.get("/debug/traces")
        assert resp.status == 404  # sampling off -> no ring to serve
        await cfg.workflow.shutdown()

    asyncio.run(go())


def test_debug_traces_flag_gated_and_infra_paths_untraced(tmp_path):
    """/debug/traces is 404 without --enable-debug-traces (the
    /debug/config posture), and health/scrape endpoints never record
    traces — probe cadence must not cycle real requests out of the
    ring."""
    from fake_kube import FakeKube
    from spicedb_kubeapi_proxy_tpu.proxy.options import Options

    async def go():
        cfg = Options(
            rule_content=RULES, upstream=FakeKube(), bind_port=0,
            workflow_database_path=str(tmp_path / "dtx.sqlite"),
            trace_sample=1.0,  # keep everything that IS traced
        ).complete()
        alice = _free_client(cfg.server.handle, "alice")
        assert (await alice.get("/debug/traces")).status == 404
        for _ in range(5):
            assert (await alice.get("/readyz")).status == 200
            assert (await alice.get("/livez")).status == 200
            assert (await alice.get("/metrics")).status == 200
        assert tracer.recent() == [], "infra endpoints must not trace"
        resp = await alice.get("/api/v1/namespaces")
        assert resp.status == 200 and "X-Trace-Id" in resp.headers
        assert len(tracer.recent()) == 1
        await cfg.workflow.shutdown()

    asyncio.run(go())


def test_trace_overhead_disabled_is_negligible():
    """The no-regression guard in unit form: with sample=0 the span hooks
    must cost nanoseconds, not microseconds (the bench-level pin is the
    check-throughput phase staying within noise)."""
    tracer.configure(sample=0.0)
    t0 = time.perf_counter()
    n = 20_000
    for _ in range(n):
        with tracer.span("x"):
            pass
        tracer.begin("y")
    per_call = (time.perf_counter() - t0) / (2 * n)
    # generous bound: even a slow CI box does a no-op contextvar check in
    # well under 20us
    assert per_call < 20e-6, f"{per_call * 1e6:.2f}us per disabled hook"
