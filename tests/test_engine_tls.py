"""TLS on the ``tcp://`` engine protocol.

The reference's remote backend endpoint is TLS with CA verification plus
token by default, plaintext only behind --spicedb-insecure
(/root/reference/pkg/proxy/options.go:325-369). These tests run a real
self-signed CA: request path (JSON + binary mask frames), server-push
watch stream, mirror stream, mutual TLS, and the refuse-plaintext
postures on both the engine-host CLI and the proxy options."""

import asyncio
import datetime
import ipaddress
import socket
import ssl
import threading

import pytest

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import NameOID

from spicedb_kubeapi_proxy_tpu.engine import CheckItem, Engine, WriteOp
from spicedb_kubeapi_proxy_tpu.engine.remote import (
    EngineServer,
    RemoteEngine,
)
from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship
from spicedb_kubeapi_proxy_tpu.utils.tlsconf import (
    TLSConfigError,
    client_ssl_context,
    server_ssl_context,
)


def _key():
    return ec.generate_private_key(ec.SECP256R1())


def _name(cn):
    return x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])


def _cert(subject, issuer, pub, signer, *, ca=False, san=None):
    now = datetime.datetime.now(datetime.timezone.utc)
    b = (x509.CertificateBuilder()
         .subject_name(subject)
         .issuer_name(issuer)
         .public_key(pub)
         .serial_number(x509.random_serial_number())
         .not_valid_before(now - datetime.timedelta(minutes=5))
         .not_valid_after(now + datetime.timedelta(days=1))
         .add_extension(x509.BasicConstraints(ca=ca, path_length=None),
                        critical=True))
    if san:
        b = b.add_extension(x509.SubjectAlternativeName(san), critical=False)
    return b.sign(signer, hashes.SHA256())


@pytest.fixture(scope="module")
def pki(tmp_path_factory):
    """CA + engine-host server cert + a client cert, and a SECOND
    independent CA for negative tests."""
    d = tmp_path_factory.mktemp("engine-pki")

    def write(path, *objs):
        data = b"".join(
            o.private_bytes(serialization.Encoding.PEM,
                            serialization.PrivateFormat.PKCS8,
                            serialization.NoEncryption())
            if hasattr(o, "private_bytes")
            else o.public_bytes(serialization.Encoding.PEM)
            for o in objs)
        p = d / path
        p.write_bytes(data)
        return str(p)

    files = {}
    for prefix in ("ca", "otherca"):
        ca_key = _key()
        ca_name = _name(f"engine-{prefix}")
        ca_cert = _cert(ca_name, ca_name, ca_key.public_key(), ca_key,
                        ca=True)
        files[prefix] = write(f"{prefix}.pem", ca_cert)
        srv_key = _key()
        srv_cert = _cert(
            _name("engine-host"), ca_name, srv_key.public_key(), ca_key,
            san=[x509.DNSName("localhost"),
                 x509.IPAddress(ipaddress.ip_address("127.0.0.1"))])
        files[f"{prefix}_server_cert"] = write(f"{prefix}-server.pem",
                                               srv_cert)
        files[f"{prefix}_server_key"] = write(f"{prefix}-server-key.pem",
                                              srv_key)
        cl_key = _key()
        cl_cert = _cert(_name("proxy-client"), ca_name,
                        cl_key.public_key(), ca_key)
        files[f"{prefix}_client"] = write(f"{prefix}-client.pem",
                                          cl_cert, cl_key)
    return files


def _seed_engine() -> Engine:
    e = Engine()
    e.write_relationships([
        WriteOp("touch", parse_relationship(
            f"namespace:n{i}#creator@user:u{i % 3}"))
        for i in range(10)
    ])
    return e


def run_with_tls_server(engine, fn, pki, client_ca=None, token="tls-tok"):
    """Start a TLS EngineServer and run ``fn(client_kwargs, port)`` in a
    worker thread on a live event loop."""
    server_ssl = server_ssl_context(pki["ca_server_cert"],
                                    pki["ca_server_key"],
                                    client_ca_file=client_ca)

    async def go():
        srv = EngineServer(engine, port=0, token=token,
                           ssl_context=server_ssl)
        port = await srv.start()
        try:
            await asyncio.to_thread(fn, port)
        finally:
            await srv.stop()

    asyncio.run(go())


def test_tls_request_path_json_and_binary_frames(pki):
    """check_bulk (JSON frames) and lookup_resources (binary mask frame +
    id sync) round-trip over TLS with CA verification."""
    e = _seed_engine()

    def fn(port):
        ctx = client_ssl_context(ca_file=pki["ca"])
        c = RemoteEngine("127.0.0.1", port, token="tls-tok",
                         ssl_context=ctx, server_hostname="localhost")
        try:
            got = c.check_bulk([
                CheckItem("namespace", "n1", "view", "user", "u1"),
                CheckItem("namespace", "n1", "view", "user", "u2"),
            ])
            assert got == [True, False]
            ids = c.lookup_resources("namespace", "view", "user", "u0")
            assert sorted(ids) == ["n0", "n3", "n6", "n9"]
            # writes and pooled-socket reuse (the TLS liveness probe must
            # treat an idle TLS socket as alive, not discard it)
            c.write_relationships([WriteOp("touch", parse_relationship(
                "namespace:fresh#creator@user:u1"))])
            assert c.check_bulk([CheckItem(
                "namespace", "fresh", "view", "user", "u1")]) == [True]
        finally:
            c.close()

    run_with_tls_server(e, fn, pki)


def test_tls_pooled_sockets_are_reused(pki):
    """The pool's pre-send liveness probe must keep idle TLS sockets —
    re-handshaking per request would tank the remote hot path."""
    e = _seed_engine()

    def fn(port):
        ctx = client_ssl_context(ca_file=pki["ca"])
        c = RemoteEngine("127.0.0.1", port, token="tls-tok",
                         ssl_context=ctx, server_hostname="localhost")
        try:
            for _ in range(3):
                c.check_bulk([CheckItem("namespace", "n1", "view",
                                        "user", "u1")])
            with c._pool_lock:
                pooled = list(c._pool)
            assert len(pooled) == 1  # sequential calls rode ONE socket
            sock_before = pooled[0]
            c.check_bulk([CheckItem("namespace", "n1", "view",
                                    "user", "u1")])
            with c._pool_lock:
                assert c._pool and c._pool[0] is sock_before
        finally:
            c.close()

    run_with_tls_server(e, fn, pki)


def test_plaintext_client_rejected_by_tls_server(pki):
    e = _seed_engine()

    def fn(port):
        c = RemoteEngine("127.0.0.1", port, token="tls-tok")  # no TLS
        try:
            with pytest.raises(Exception):
                c.check_bulk([CheckItem("namespace", "n1", "view",
                                        "user", "u1")])
        finally:
            c.close()

    run_with_tls_server(e, fn, pki)


def test_wrong_ca_rejected(pki):
    e = _seed_engine()

    def fn(port):
        ctx = client_ssl_context(ca_file=pki["otherca"])
        c = RemoteEngine("127.0.0.1", port, token="tls-tok",
                         ssl_context=ctx, server_hostname="localhost")
        try:
            with pytest.raises(ssl.SSLError):
                c.check_bulk([CheckItem("namespace", "n1", "view",
                                        "user", "u1")])
        finally:
            c.close()

    run_with_tls_server(e, fn, pki)


def test_skip_verify_ca_still_encrypts(pki):
    """The reference's SkipVerifyCA mode: TLS without cert verification
    still completes the handshake and carries traffic."""
    e = _seed_engine()

    def fn(port):
        ctx = client_ssl_context(skip_verify=True)
        c = RemoteEngine("127.0.0.1", port, token="tls-tok",
                         ssl_context=ctx, server_hostname="localhost")
        try:
            assert c.check_bulk([CheckItem(
                "namespace", "n1", "view", "user", "u1")]) == [True]
        finally:
            c.close()

    run_with_tls_server(e, fn, pki)


def test_mutual_tls_requires_client_cert(pki):
    """With a client CA configured, cert-less clients fail the handshake
    and cert-bearing ones proceed (mTLS on top of the token)."""
    e = _seed_engine()

    def fn(port):
        bare = RemoteEngine(
            "127.0.0.1", port, token="tls-tok",
            ssl_context=client_ssl_context(ca_file=pki["ca"]),
            server_hostname="localhost")
        try:
            # TLS 1.3 delivers the missing-client-cert rejection after the
            # client's handshake completes: either an SSLError (alert) or
            # a reset on first read, depending on timing — both OSError
            with pytest.raises(OSError):
                bare.check_bulk([CheckItem("namespace", "n1", "view",
                                           "user", "u1")])
        finally:
            bare.close()
        withcert = RemoteEngine(
            "127.0.0.1", port, token="tls-tok",
            ssl_context=client_ssl_context(
                ca_file=pki["ca"], client_cert_file=pki["ca_client"]),
            server_hostname="localhost")
        try:
            assert withcert.check_bulk([CheckItem(
                "namespace", "n1", "view", "user", "u1")]) == [True]
        finally:
            withcert.close()

    run_with_tls_server(e, fn, pki, client_ca=pki["ca"])


def test_push_watch_stream_over_tls(pki):
    """The server-push watch subscription (dedicated socket) rides TLS:
    subscribe, receive a pushed grant, close."""
    e = _seed_engine()

    def fn(port):
        ctx = client_ssl_context(ca_file=pki["ca"])
        c = RemoteEngine("127.0.0.1", port, token="tls-tok",
                         ssl_context=ctx, server_hostname="localhost")
        try:
            stream = c.watch_push_stream(c.revision)
            try:
                t = threading.Thread(
                    target=lambda: e.write_relationships(
                        [WriteOp("touch", parse_relationship(
                            "namespace:pushed#viewer@user:u9"))]),
                    daemon=True)
                t.start()
                got = []
                while not got:
                    got = stream.next_batch()  # [] = heartbeat
                assert any(
                    ev.relationship.resource_id == "pushed"
                    for ev in got)
                t.join(5)
            finally:
                stream.close()
        finally:
            c.close()

    run_with_tls_server(e, fn, pki)


def test_mirror_stream_over_tls(pki):
    """A follower subscribes to a MirroredEngine leader over TLS and
    replays its writes (multi-host serving path, parallel/multihost.py)."""
    from spicedb_kubeapi_proxy_tpu.parallel.multihost import (
        MirroredEngine,
        follower_loop,
    )

    leader_inner = _seed_engine()
    leader = MirroredEngine(leader_inner, min_subscribers=1,
                            join_timeout=30.0)
    follower_engine = _seed_engine()
    server_ssl = server_ssl_context(pki["ca_server_cert"],
                                    pki["ca_server_key"])

    async def go():
        srv = EngineServer(leader, port=0, token="tls-tok",
                           ssl_context=server_ssl)
        port = await srv.start()
        ctx = client_ssl_context(ca_file=pki["ca"])
        ft = threading.Thread(
            target=follower_loop,
            args=(follower_engine, "127.0.0.1", port),
            kwargs={"token": "tls-tok", "ssl_context": ctx,
                    "server_hostname": "localhost"},
            daemon=True)
        ft.start()
        try:
            # leader write blocks on the join barrier until the follower's
            # TLS subscription lands, then mirrors to it
            await asyncio.to_thread(
                leader.write_relationships,
                [WriteOp("touch", parse_relationship(
                    "namespace:mirrored#creator@user:u5"))])
            deadline = asyncio.get_running_loop().time() + 20
            item = CheckItem("namespace", "mirrored", "view", "user", "u5")
            while True:
                ok = await asyncio.to_thread(
                    follower_engine.check_bulk, [item])
                if ok == [True]:
                    break
                assert asyncio.get_running_loop().time() < deadline, \
                    "follower never replayed the mirrored write"
                await asyncio.sleep(0.05)
        finally:
            await srv.stop()
            ft.join(10)

    asyncio.run(go())


# -- flag-surface postures ---------------------------------------------------


def test_engine_host_cli_serves_tls(pki, tmp_path):
    """The standalone CLI actually wires its TLS context into the server
    (regression: the context was built but not passed — the host served
    plaintext and every TLS client saw a handshake EOF)."""
    import os
    import subprocess
    import sys
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    boot = tmp_path / "boot.yaml"
    boot.write_text("schema: |\n  definition user {}\n")
    script = (
        "import os, sys\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        f"sys.path.insert(0, {repo!r})\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from spicedb_kubeapi_proxy_tpu.engine.remote import main\n"
        "sys.exit(main(sys.argv[1:]))\n")
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    p = subprocess.Popen(
        [sys.executable, "-c", script,
         "--bootstrap", str(boot), "--bind-port", str(port),
         "--token", "cli-tok",
         "--tls-cert-file", pki["ca_server_cert"],
         "--tls-key-file", pki["ca_server_key"]],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env)
    try:
        deadline = time.monotonic() + 60
        while True:
            try:
                probe = socket.create_connection(("127.0.0.1", port),
                                                 timeout=1)
                probe.close()
                break
            except OSError:
                assert p.poll() is None, p.communicate()[0][-2000:]
                assert time.monotonic() < deadline, "host never bound"
                time.sleep(0.2)
        c = RemoteEngine(
            "127.0.0.1", port, token="cli-tok",
            ssl_context=client_ssl_context(ca_file=pki["ca"]),
            server_hostname="localhost")
        try:
            assert isinstance(c.revision, int)
        finally:
            c.close()
    finally:
        p.terminate()
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_engine_host_cli_refuses_plaintext_without_opt_out(tmp_path):
    from spicedb_kubeapi_proxy_tpu.engine.remote import main

    with pytest.raises(SystemExit) as exc:
        main(["--bind-port", "0"])
    assert exc.value.code == 2  # argparse error, not a crash


def test_engine_host_cli_follower_needs_no_serving_certs(tmp_path):
    """A mirror follower never serves TCP — the refuse-plaintext check
    must not demand cert/key from it (review finding). It proceeds past
    flag validation (blocking on the coordinator, which proves argparse
    accepted it) instead of exiting 2."""
    import os
    import subprocess
    import sys
    import time

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = (
        "import os, sys\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        f"sys.path.insert(0, {repo!r})\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from spicedb_kubeapi_proxy_tpu.engine.remote import main\n"
        "sys.exit(main(sys.argv[1:]))\n")
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    p = subprocess.Popen(
        [sys.executable, "-c", script,
         "--distributed", f"127.0.0.1:{port},2,1",
         "--mirror-leader", f"127.0.0.1:{port}",
         "--mirror-skip-verify-ca"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env)
    try:
        time.sleep(3)
        # still alive = past argparse (blocked joining the coordinator);
        # an exit means flag validation rejected the follower
        if p.poll() is not None:
            out = p.communicate()[0]
            assert "refusing to serve plaintext" not in out, out[-1500:]
            assert p.returncode != 2, out[-1500:]
    finally:
        p.kill()
        p.wait(timeout=10)
    # and a malformed spec still fails fast with a clean argparse error
    from spicedb_kubeapi_proxy_tpu.engine.remote import main

    with pytest.raises(SystemExit) as exc:
        main(["--distributed", "not-a-spec", "--mirror-leader", "h:1"])
    assert exc.value.code == 2


def test_engine_host_cli_rejects_half_tls_and_conflicts(pki):
    from spicedb_kubeapi_proxy_tpu.engine.remote import main

    with pytest.raises(SystemExit):
        main(["--tls-cert-file", pki["ca_server_cert"]])  # no key
    with pytest.raises(SystemExit):
        main(["--engine-insecure",
              "--tls-cert-file", pki["ca_server_cert"],
              "--tls-key-file", pki["ca_server_key"]])


def test_proxy_options_tls_validation(pki):
    from spicedb_kubeapi_proxy_tpu.proxy.options import (
        Options,
        OptionsError,
    )

    base = dict(rule_content="x", upstream_url="https://k")
    # engine TLS flags demand a tcp:// endpoint
    with pytest.raises(OptionsError):
        Options(engine_ca_file=pki["ca"], **base).validate()
    with pytest.raises(OptionsError):
        Options(engine_insecure=True, **base).validate()
    # plaintext excludes the TLS options
    with pytest.raises(OptionsError):
        Options(engine_endpoint="tcp://h:1", engine_insecure=True,
                engine_ca_file=pki["ca"], **base).validate()
    # client cert/key go together
    with pytest.raises(OptionsError):
        Options(engine_endpoint="tcp://h:1",
                engine_client_cert_file=pki["ca_client"],
                **base).validate()
    # well-formed TLS config validates
    Options(engine_endpoint="tcp://h:1", engine_ca_file=pki["ca"],
            engine_server_name="localhost", **base).validate()
    Options(engine_endpoint="tcp://h:1", engine_insecure=True,
            **base).validate()


def test_proxy_completes_with_tls_engine_client(pki):
    """Options.complete() against a tcp:// endpoint builds a RemoteEngine
    whose connections are TLS — verified against a live TLS server."""
    from spicedb_kubeapi_proxy_tpu.proxy.options import Options

    e = _seed_engine()

    def fn(port):
        opts = Options(
            engine_endpoint=f"tcp://127.0.0.1:{port}",
            engine_token="tls-tok",
            engine_ca_file=pki["ca"],
            engine_server_name="localhost",
            rule_content="""
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
lock: Pessimistic
match:
- apiVersion: v1
  resource: namespaces
  verbs: ["get"]
check:
- tpl: "namespace:{{name}}#view@user:{{user.name}}"
""",
            upstream_url="https://unused.test",
            workflow_database_path=":memory:")
        cfg = opts.complete()
        try:
            assert cfg.engine.check_bulk([CheckItem(
                "namespace", "n1", "view", "user", "u1")]) == [True]
        finally:
            cfg.engine.close()

    run_with_tls_server(e, fn, pki)


def test_tlsconf_error_surfaces(tmp_path):
    with pytest.raises(TLSConfigError):
        server_ssl_context(str(tmp_path / "no.pem"),
                           str(tmp_path / "no-key.pem"))
    with pytest.raises(TLSConfigError):
        client_ssl_context(ca_file=str(tmp_path / "no-ca.pem"))


def test_ca_file_pins_trust_to_that_bundle(pki):
    """--engine-ca-file must REPLACE the trust store, not extend it: a
    MITM holding any publicly-trusted certificate must fail verification
    when the operator named a private CA (reference CAPath semantics).
    The context built from a ca_file must trust exactly that bundle."""
    pinned = client_ssl_context(ca_file=pki["ca"])
    assert pinned.cert_store_stats()["x509_ca"] == 1
    # the default (no ca_file) context loads the system store — on any
    # realistic image that is far more than our single test CA; at
    # minimum it must differ from the pinned store
    system = client_ssl_context()
    assert system.cert_store_stats() != pinned.cert_store_stats() \
        or system.cert_store_stats()["x509_ca"] <= 1  # bare image: vacuous


def test_insecure_excludes_server_name():
    from spicedb_kubeapi_proxy_tpu.proxy.options import (
        Options, OptionsError)
    with pytest.raises(OptionsError, match="server-name"):
        Options(engine_endpoint="tcp://h:1", engine_insecure=True,
                engine_server_name="engine.corp", rule_content="x",
                upstream_url="http://x").validate()
