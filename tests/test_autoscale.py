"""Elastic scale-out in both directions (ISSUE 20).

Covers the acceptance surface:

- RevisionVector component removal and shrink-token translation
  (tokens at/past the retire watermark translate; tokens below it get
  StoreError re-list semantics; unknown map versions are rejected —
  never misindexed);
- grow -> shrink end-to-end: the retiring tail group drains through
  the existing copy/catch-up/cutover machinery, its copies GC, the
  routing space renumbers, zero data loss;
- the shrink crash matrix: a post-cut SIGKILL resumes the coordinator
  at boot and completes the retire;
- the archive-retirement regression (satellite 1): grow->shrink cycles
  must not pin stale scatter-merge owner filters through dead-index
  archives;
- cross-shard frontier exchange: oracle parity for cross-namespace
  reference schemas WITHOUT replication, boundary-only wire accounting,
  hard round budget failing CLOSED, non-monotone schemas refused;
- the autoscale policy kernel (hysteresis, cooldown, never-shrink-
  while-burning, knob parsing/validation) and the controller
  end-to-end in apply mode driving REAL grow and shrink transitions.
"""

import os
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from spicedb_kubeapi_proxy_tpu.autoscale import (  # noqa: E402
    AutoscaleController,
    AutoscaleError,
    AutoscalePolicy,
    PolicyConfig,
    Signals,
    parse_policy,
)
from spicedb_kubeapi_proxy_tpu.engine import Engine  # noqa: E402
from spicedb_kubeapi_proxy_tpu.engine.engine import CheckItem  # noqa: E402
from spicedb_kubeapi_proxy_tpu.engine.store import (  # noqa: E402
    RelationshipFilter,
    StoreError,
    WriteOp,
)
from spicedb_kubeapi_proxy_tpu.models.tuples import (  # noqa: E402
    Relationship,
)
from spicedb_kubeapi_proxy_tpu.scaleout import (  # noqa: E402
    FrontierConfig,
    FrontierError,
    MapTransition,
    RebalanceCoordinator,
    RevisionVector,
    ShardedEngine,
    ShardMap,
    ShardMapError,
    SplitJournal,
    plan_moves,
    reference_pairs,
    shrink_map,
)
from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics  # noqa: E402

SCHEMA_YAML = """\
schema: |-
  definition user {}

  definition namespace {
    relation viewer: user
    permission view = viewer
  }

  definition pod {
    relation namespace: namespace
    relation viewer: user
    permission view = viewer + namespace->view
  }
relationships: ""
"""

# cross-namespace reference schema: docs grant through team usersets
# that live in OTHER namespaces — the schema class PR 11 forced to be
# cluster-scoped (replicated) and the frontier exchange now serves
# from single-copy placement
FRONTIER_YAML = """\
schema: |-
  definition user {}

  definition team {
    relation member: user | team#member
    permission view = member
  }

  definition doc {
    relation owner: team#member
    relation viewer: user
    permission view = viewer + owner
  }
relationships: ""
"""


def _engine(yaml: str = SCHEMA_YAML) -> Engine:
    return Engine(bootstrap=yaml)


def _map(n: int, version: int = 1, vnodes: int = 64) -> ShardMap:
    return ShardMap(version=version,
                    groups=tuple((("127.0.0.1", 0),) for _ in range(n)),
                    virtual_nodes=vnodes)


def rel(rt, rid, rl, st, sid, srl=None) -> Relationship:
    return Relationship(rt, rid, rl, st, sid, srl)


def _seed_writes(n_ns: int, users: int = 4) -> list:
    out = []
    for i in range(n_ns):
        out.append(WriteOp("create", rel(
            "namespace", f"ns{i}", "viewer", "user", f"u{i % users}")))
        out.append(WriteOp("create", rel(
            "pod", f"ns{i}/p0", "namespace", "namespace", f"ns{i}")))
        out.append(WriteOp("create", rel(
            "pod", f"ns{i}/p0", "viewer", "user", f"u{i % users}")))
    return out


def _ns_on(smap: ShardMap, rtype: str, group: int, tag: str) -> str:
    """A namespace name the map routes to ``group`` for ``rtype``."""
    for i in range(10_000):
        ns = f"{tag}{i}"
        if smap.shard_for(ns, rtype) == group:
            return ns
    raise AssertionError(f"no {tag}* namespace lands on group {group}")


def _wait_gc(p: ShardedEngine, budget: float = 30.0) -> None:
    deadline = time.monotonic() + budget
    while time.monotonic() < deadline:
        if all(t.gc_complete for t in p._archived_transitions):
            return
        time.sleep(0.02)
    raise AssertionError("archived transitions never finished GC")


# -- revision-vector component removal ---------------------------------------


def test_drop_component_units():
    v = RevisionVector((5, 7, 9))
    assert v.drop_component(2) == (5, 7)
    assert v.drop_component(0) == (7, 9)
    assert isinstance(v.drop_component(1), RevisionVector)
    with pytest.raises(ShardMapError, match="drop component"):
        v.drop_component(3)
    with pytest.raises(ShardMapError, match="drop component"):
        v.drop_component(-1)


def test_shrink_token_translation():
    engines = [_engine(), _engine()]
    p = ShardedEngine(_map(2), engines)
    p.write_relationships(_seed_writes(12))
    # mint a resumption token under the 2-group map, quiesced: its
    # retired-group component sits exactly AT the watermark the shrink
    # will record, so translation must accept it
    tok_at = p.revision_vector().encode(map_version=1)
    coord = p.begin_rebalance(shrink_map(p.map))
    assert coord.wait(90) and coord.error is None
    assert p.map.version == 2 and len(p.groups) == 1
    assert len(p.revision_vector()) == 1
    t = p._archived_transitions[-1]
    assert t.retire == 1 and t.retire_cut is not None
    # at/past the cut: translated through the recorded transition
    p.watch_since(tok_at)
    # below the cut: the retiring group delivered events no survivor
    # re-delivers — re-list semantics, loudly
    assert int(t.retire_cut) > 0
    with pytest.raises(StoreError, match="predates the shrink"):
        p.watch_since("v0.0@m1")
    # unknown minting epoch: rejected, never misindexed
    with pytest.raises(ShardMapError, match="no transition"):
        p.watch_since("v0.0@m9")
    # a component count no recorded transition explains
    with pytest.raises(ShardMapError, match="no recorded transition"):
        p.watch_since("v0.0.0.0")
    p.close()


# -- grow -> shrink end-to-end ------------------------------------------------


def test_grow_then_shrink_round_trip():
    n_ns = 16
    engines = [_engine(), _engine()]
    p = ShardedEngine(_map(2), engines)
    p.write_relationships(_seed_writes(n_ns))

    extra = _engine()
    grown = ShardMap(version=2,
                     groups=p.map.groups + ((("127.0.0.1", 0),),),
                     virtual_nodes=64)
    coord = p.begin_rebalance(grown, new_clients={2: extra})
    assert coord.wait(90) and coord.error is None
    engines.append(extra)
    assert p.map.version == 3 - 1 and len(p.groups) == 3
    _wait_gc(p)
    moved = [i for i in range(n_ns)
             if p.map.shard_for(f"ns{i}", "pod") == 2]
    assert moved, "grow moved nothing to the new group"

    coord = p.begin_rebalance(shrink_map(p.map))
    assert coord.wait(90) and coord.error is None
    assert p.map.version == 3 and len(p.groups) == 2
    assert len(p.revision_vector()) == 2
    assert p.rebalance_status() is None
    _wait_gc(p)
    # zero loss: every seeded grant still answers, on the shrunken map
    for i in range(n_ns):
        assert p.check(CheckItem("pod", f"ns{i}/p0", "view", "user",
                                 f"u{i % 4}")), i
        assert not p.check(CheckItem("pod", f"ns{i}/p0", "view",
                                     "user", "intruder")), i
    # the retiree drained: its copies were moved off and GC'd
    f = RelationshipFilter(resource_type="pod")
    assert not extra.store.exists(f)
    # placement matches the committed map exactly (no survivor moved)
    for i in range(n_ns):
        ff = RelationshipFilter(resource_type="pod",
                                resource_id=f"ns{i}/p0")
        holders = [gi for gi, e in enumerate(engines[:2])
                   if e.store.exists(ff)]
        assert holders == [p.map.shard_for(f"ns{i}", "pod")], i
    p.close()


def test_shrink_crash_after_cut_resumes(tmp_path):
    """SIGKILL mid-shrink after >= 1 slice cut: boot resumes the
    coordinator, finishes the drain + GC, commits and renumbers."""
    n_ns = 12
    old = _map(3, 1)
    new = shrink_map(old, version=2)
    engines = [_engine(), _engine(), _engine()]
    journal = SplitJournal(str(tmp_path / "sj.sqlite"))
    p = ShardedEngine(old, engines, journal=journal)
    p.write_relationships(_seed_writes(n_ns))
    t = MapTransition(old, new, plan_moves(old, new, retire=2),
                      retire=2)
    p._install_transition(t)
    coord = RebalanceCoordinator(p, t)
    for i, sl in enumerate(t.slices):
        copy_rev, rows = coord._slice_read(sl.src, sl.ranges)
        coord._slice_load(sl.dst, rows)
        t.set_state(sl, "catchup", copy_rev=copy_rev,
                    replayed=copy_rev)
        while coord._catch_up_once(sl) > 0:
            pass
        if i == 0:
            src_cut = coord._src_revision(sl.src)
            dst_cut = coord._src_revision(sl.dst)
            t.set_state(sl, "cut", src_cut=src_cut, dst_cut=dst_cut)
    coord._persist()
    p.close(close_journal=False)  # the "SIGKILL": record stays

    p2 = ShardedEngine(old, engines, journal=journal)
    assert p2._coordinator is not None  # resumed at boot
    assert p2._coordinator.wait(90)
    assert p2._coordinator.error is None, p2._coordinator.error
    assert p2.map.version == 2 and len(p2.groups) == 2
    _wait_gc(p2)
    for i in range(n_ns):
        assert p2.check(CheckItem("pod", f"ns{i}/p0", "view", "user",
                                  f"u{i % 4}")), i
    assert not engines[2].store.exists(
        RelationshipFilter(resource_type="pod"))
    p2.close()


# -- archive retirement across grow->shrink cycles (satellite 1) --------------


def test_stale_archives_retired_across_grow_shrink_grow():
    """The first grow's archive references group index 2; after the
    shrink renumbers to a 2-group space that archive would pin
    ``_copies_may_linger`` open (per-row owner filtering on every
    scatter) and make the era walk compare dead indices forever.
    Commit must retire it — and routing must stay exact after."""
    n_ns = 12
    engines = [_engine(), _engine()]
    p = ShardedEngine(_map(2), engines)
    p.write_relationships(_seed_writes(n_ns))
    retired0 = metrics.counter("scaleout_archives_retired_total").value

    def grow():
        extra = _engine()
        grown = ShardMap(version=p.map.version + 1,
                         groups=p.map.groups + ((("127.0.0.1", 0),),),
                         virtual_nodes=64)
        coord = p.begin_rebalance(grown, new_clients={2: extra})
        assert coord.wait(90) and coord.error is None
        _wait_gc(p)
        return extra

    grow()
    coord = p.begin_rebalance(shrink_map(p.map))
    assert coord.wait(90) and coord.error is None
    _wait_gc(p)
    # the grow archive referenced index 2 and died with the shrink
    assert metrics.counter(
        "scaleout_archives_retired_total").value > retired0
    n = len(p.groups)
    for past in p._archived_transitions:
        refs = ({sl.src for sl in past.slices}
                | {sl.dst for sl in past.slices})
        refs.discard(past.retire)
        assert all(gi < n for gi in refs), (past.retire, refs)
    grow()  # a fresh cycle must start clean, not inherit dead filters
    assert not p._copies_may_linger()
    for i in range(n_ns):
        assert p.check(CheckItem("pod", f"ns{i}/p0", "view", "user",
                                 f"u{i % 4}")), i
        assert not p.check(CheckItem("pod", f"ns{i}/p0", "view",
                                     "user", "intruder")), i
    p.close()


# -- cross-shard frontier exchange -------------------------------------------


def test_reference_pairs_extraction_and_refusal():
    pairs = reference_pairs(_engine(FRONTIER_YAML).schema)
    assert pairs == (("team", "member"),)
    # no userset references at all: nothing to exchange
    assert reference_pairs(_engine(SCHEMA_YAML).schema) == ()
    bad = """\
schema: |-
  definition user {}

  definition team {
    relation member: user
  }

  definition doc {
    relation owner: team#member
    relation banned: user
    permission view = owner - banned
  }
relationships: ""
"""
    with pytest.raises(FrontierError, match="monotone"):
        reference_pairs(_engine(bad).schema)


def _frontier_fixture(max_rounds: int = 8):
    """2-group planner + unsharded oracle over FRONTIER_YAML, with a
    2-hop cross-shard chain: u0 -> teamB (group 0) -> teamA (group 1)
    -> doc (group 0, owned by teamA#member). No tuple is replicated."""
    smap = _map(2)
    engines = [_engine(FRONTIER_YAML), _engine(FRONTIER_YAML)]
    p = ShardedEngine(smap, engines,
                      frontier=FrontierConfig(max_rounds=max_rounds))
    oracle = _engine(FRONTIER_YAML)
    ns_b = _ns_on(smap, "team", 0, "tb")
    ns_a = _ns_on(smap, "team", 1, "ta")
    ns_d = _ns_on(smap, "doc", 0, "dd")
    team_b, team_a, doc = f"{ns_b}/t", f"{ns_a}/t", f"{ns_d}/d"
    writes = [
        WriteOp("create", rel("team", team_b, "member", "user", "u0")),
        WriteOp("create", rel("team", team_a, "member", "team",
                              team_b, "member")),
        WriteOp("create", rel("doc", doc, "owner", "team", team_a,
                              "member")),
        WriteOp("create", rel("doc", doc, "viewer", "user", "direct")),
    ]
    p.write_relationships(writes)
    oracle.write_relationships(writes)
    return p, engines, oracle, (team_b, team_a, doc)


def test_frontier_cross_shard_oracle_parity():
    p, engines, oracle, (team_b, team_a, doc) = _frontier_fixture()
    scatter0 = metrics.counter("scaleout_frontier_wire_bytes_total",
                               direction="scatter").value
    gather0 = metrics.counter("scaleout_frontier_wire_bytes_total",
                              direction="gather").value
    conv0 = metrics.counter("scaleout_frontier_exchanges_total",
                            outcome="converged").value
    # single-copy placement, proven: each membership tuple exists on
    # exactly one group (this is what PR 11 would have replicated)
    for rt, rid in (("team", team_b), ("team", team_a), ("doc", doc)):
        f = RelationshipFilter(resource_type=rt, resource_id=rid)
        assert sum(1 for e in engines if e.store.exists(f)) == 1
    for sid, rid in (("u0", doc), ("direct", doc), ("intruder", doc)):
        want = oracle.check(CheckItem("doc", rid, "view", "user", sid))
        got = p.check(CheckItem("doc", rid, "view", "user", sid))
        assert got == want, (sid, got, want)
    assert p.check(CheckItem("doc", doc, "view", "user", "u0"))
    # lookup parity: the closure widens the gather the same way
    assert sorted(p.lookup_resources("doc", "view", "user", "u0")) \
        == sorted(oracle.lookup_resources("doc", "view", "user", "u0"))
    assert p.lookup_resources("doc", "view", "user", "intruder") == []
    # boundary-only mass moved, and it was counted in BOTH directions
    scatter = metrics.counter("scaleout_frontier_wire_bytes_total",
                              direction="scatter").value - scatter0
    gather = metrics.counter("scaleout_frontier_wire_bytes_total",
                             direction="gather").value - gather0
    assert 0 < scatter < 4096 and 0 < gather < 4096
    assert metrics.counter("scaleout_frontier_exchanges_total",
                           outcome="converged").value > conv0
    p.close()


def test_frontier_budget_exhaustion_fails_closed():
    # the chain needs two exchange rounds; a 1-round budget must stop
    # short and DENY (under-approximate), never grant, and count the
    # exhaustion
    p, _, oracle, (team_b, team_a, doc) = _frontier_fixture(
        max_rounds=1)
    exh0 = metrics.counter("scaleout_frontier_exchanges_total",
                           outcome="budget-exhausted").value
    assert oracle.check(CheckItem("doc", doc, "view", "user", "u0"))
    assert not p.check(CheckItem("doc", doc, "view", "user", "u0"))
    assert metrics.counter("scaleout_frontier_exchanges_total",
                           outcome="budget-exhausted").value > exh0
    # direct grants on the resource's own group are untouched
    assert p.check(CheckItem("doc", doc, "view", "user", "direct"))
    p.close()


# -- the policy kernel --------------------------------------------------------


def test_policy_hysteresis_and_cooldown():
    pol = AutoscalePolicy(PolicyConfig(
        min_groups=1, max_groups=4, hysteresis_ticks=3,
        cooldown_seconds=100.0))
    hot = Signals(n_groups=2, occupancy=0.9)
    cold = Signals(n_groups=2, occupancy=0.1)
    # two hot ticks then a cold one: the streak restarts — flapping
    # around the threshold proposes nothing
    assert pol.observe(hot, now=0.0) is None
    assert pol.observe(hot, now=1.0) is None
    assert pol.observe(cold, now=2.0) is None
    assert pol.observe(hot, now=3.0) is None
    assert pol.observe(hot, now=4.0) is None
    prop = pol.observe(hot, now=5.0)
    assert prop is not None and prop.action == "grow"
    assert prop.target_groups == 3
    # inside the cooldown even a completed streak fires nothing...
    for ts in (6.0, 7.0, 8.0, 9.0):
        assert pol.observe(hot, now=ts) is None
    # ...and it fires on the first tick past the cooldown (the streak
    # kept accruing — the signal never stopped saying grow)
    assert pol.observe(hot, now=106.0) is not None


def test_policy_guards():
    pol = AutoscalePolicy(PolicyConfig(
        min_groups=1, max_groups=3, hysteresis_ticks=1,
        cooldown_seconds=0.0))
    # never-shrink-while-burning: idle occupancy but the error budget
    # is burning at objective-failing rate
    burning = Signals(n_groups=2, occupancy=0.05, burn_rate=1.5)
    assert pol.observe(burning, now=0.0) is None
    calm = Signals(n_groups=2, occupancy=0.05, burn_rate=0.2)
    prop = pol.observe(calm, now=1.0)
    assert prop is not None and prop.action == "shrink"
    assert prop.target_groups == 1
    # bounds: min_groups floors the shrink, max_groups caps the grow
    assert pol.observe(Signals(n_groups=1, occupancy=0.0),
                       now=2.0) is None
    assert pol.observe(Signals(n_groups=3, occupancy=0.99),
                       now=3.0) is None
    # an in-flight transition (or owed GC) resets the streak
    pol2 = AutoscalePolicy(PolicyConfig(hysteresis_ticks=2,
                                        cooldown_seconds=0.0))
    hot = Signals(n_groups=2, occupancy=0.9)
    assert pol2.observe(hot, now=0.0) is None
    assert pol2.observe(Signals(n_groups=2, occupancy=0.9,
                                rebalance_active=True), now=1.0) is None
    assert pol2.observe(hot, now=2.0) is None  # re-earning
    assert pol2.observe(hot, now=3.0) is not None
    # SLO burn alone triggers a grow
    pol3 = AutoscalePolicy(PolicyConfig(hysteresis_ticks=1,
                                        cooldown_seconds=0.0))
    prop = pol3.observe(Signals(n_groups=2, occupancy=0.1,
                                burn_rate=5.0), now=0.0)
    assert prop is not None and prop.action == "grow"


def test_policy_parsing_and_validation():
    cfg = parse_policy("max_groups=6,grow_occupancy=0.7,"
                       "hysteresis_ticks=2")
    assert cfg.max_groups == 6
    assert cfg.grow_occupancy == 0.7
    assert cfg.hysteresis_ticks == 2
    assert cfg.cooldown_seconds == 300.0  # unnamed knobs keep defaults
    with pytest.raises(AutoscaleError, match="unknown"):
        parse_policy("bogus_knob=1")
    with pytest.raises(AutoscaleError, match="bad autoscale"):
        parse_policy("max_groups=lots")
    with pytest.raises(AutoscaleError, match="min_groups"):
        PolicyConfig(min_groups=5, max_groups=2).validate()
    with pytest.raises(AutoscaleError, match="thrash"):
        PolicyConfig(grow_occupancy=0.5,
                     shrink_occupancy=0.6).validate()
    with pytest.raises(AutoscaleError, match="hysteresis"):
        PolicyConfig(hysteresis_ticks=0).validate()


# -- controller end-to-end (apply mode) --------------------------------------


def test_controller_apply_mode_drives_real_transitions():
    """ISSUE 20 acceptance: the autoscaler in apply mode drives a real
    grow AND a real shrink through the rebalance coordinator, with the
    transition-in-flight guard holding proposals off until each one
    converges (including GC)."""
    n_ns = 10
    engines = [_engine(), _engine()]
    p = ShardedEngine(_map(2), engines)
    p.write_relationships(_seed_writes(n_ns))

    sig = {"occupancy": 0.95}

    def signal_fn():
        return Signals(
            n_groups=len(p.groups),
            occupancy=sig["occupancy"],
            burn_rate=0.0,
            rebalance_active=p.rebalance_status() is not None,
            gc_pending=any(not t.gc_complete
                           for t in p._archived_transitions))

    spares = []

    def grow_source(gi):
        e = _engine()
        spares.append(e)
        return ((("127.0.0.1", 0),), e)

    ctl = AutoscaleController(
        p, AutoscalePolicy(PolicyConfig(
            min_groups=2, max_groups=3, hysteresis_ticks=2,
            cooldown_seconds=0.0)),
        mode="apply", signal_fn=signal_fn,
        grow_group_source=grow_source)
    started0 = metrics.counter("autoscale_transitions_total",
                               action="grow", outcome="started").value

    ticks = 0
    prop = None
    while prop is None and ticks < 10:
        prop = ctl.tick(now=float(ticks))
        ticks += 1
    assert prop is not None and prop.action == "grow"
    assert ticks >= 2  # hysteresis made it earn the streak
    assert metrics.counter("autoscale_transitions_total",
                           action="grow",
                           outcome="started").value > started0
    assert p._coordinator is not None
    assert p._coordinator.wait(90) and p._coordinator.error is None
    assert len(p.groups) == 3 and p.map.version == 2
    _wait_gc(p)
    st = ctl.status()
    assert st["mode"] == "apply" and st["transitions"] == 1
    assert st["last_proposal"]["action"] == "grow"

    # load drains away: the same controller proposes and applies the
    # shrink back down through shrink_map + the coordinator
    sig["occupancy"] = 0.02
    prop = None
    ticks = 100
    while prop is None and ticks < 120:
        prop = ctl.tick(now=float(ticks))
        ticks += 1
    assert prop is not None and prop.action == "shrink"
    assert p._coordinator is not None
    assert p._coordinator.wait(90) and p._coordinator.error is None
    assert len(p.groups) == 2 and p.map.version == 3
    _wait_gc(p)
    assert ctl.status()["transitions"] == 2
    for i in range(n_ns):
        assert p.check(CheckItem("pod", f"ns{i}/p0", "view", "user",
                                 f"u{i % 4}")), i
    p.close()


def test_controller_dry_run_proposes_but_never_acts():
    engines = [_engine(), _engine()]
    p = ShardedEngine(_map(2), engines)
    props0 = metrics.counter("autoscale_proposals_total",
                             action="grow").value
    ctl = AutoscaleController(
        p, AutoscalePolicy(PolicyConfig(hysteresis_ticks=1,
                                        cooldown_seconds=0.0)),
        mode="dry-run",
        signal_fn=lambda: Signals(n_groups=2, occupancy=0.99))
    prop = ctl.tick(now=0.0)
    assert prop is not None and prop.action == "grow"
    assert metrics.counter("autoscale_proposals_total",
                           action="grow").value > props0
    # surfaced, counted — and nothing moved
    assert p.rebalance_status() is None
    assert p.map.version == 1 and len(p.groups) == 2
    assert ctl.status()["last_proposal"]["mode"] == "dry-run"
    assert ctl.status()["transitions"] == 0
    p.close()


def test_controller_apply_grow_without_source_fails_safe():
    engines = [_engine(), _engine()]
    p = ShardedEngine(_map(2), engines)
    failed0 = metrics.counter("autoscale_transitions_total",
                              action="grow", outcome="failed").value
    ctl = AutoscaleController(
        p, AutoscalePolicy(PolicyConfig(hysteresis_ticks=1,
                                        cooldown_seconds=0.0)),
        mode="apply",
        signal_fn=lambda: Signals(n_groups=2, occupancy=0.99))
    assert ctl.tick(now=0.0) is not None  # proposed...
    # ...but acting failed SAFE: counted, fleet untouched
    assert metrics.counter("autoscale_transitions_total",
                           action="grow",
                           outcome="failed").value > failed0
    assert p.rebalance_status() is None and len(p.groups) == 2
    assert ctl.status()["transitions"] == 0
    p.close()
