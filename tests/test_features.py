"""Feature gates (reference pkg/proxy/features.go:10-27): registry,
CLI spec parsing, and the gates actually switching behavior."""

import numpy as np
import pytest

from spicedb_kubeapi_proxy_tpu.engine import CheckItem, Engine, WriteOp
from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship
from spicedb_kubeapi_proxy_tpu.proxy.options import Options, OptionsError
from spicedb_kubeapi_proxy_tpu.proxy.upstream import rewrite_accept
from spicedb_kubeapi_proxy_tpu.utils.features import (
    FeatureGateError,
    features,
)
from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics


@pytest.fixture(autouse=True)
def reset_gates():
    features.reset()
    yield
    features.reset()


def test_spec_parsing():
    assert features.validate_spec(
        "IncrementalGraphUpdates=false, BitKernel=true") == [
        ("IncrementalGraphUpdates", False), ("BitKernel", True)]
    for bad in ("Nope=true", "BitKernel=maybe", "BitKernel"):
        with pytest.raises(FeatureGateError):
            features.validate_spec(bad)
    with pytest.raises(OptionsError):
        Options(rule_content="x", upstream_url="http://u",
                feature_gates="Nope=true").validate()


def test_incremental_gate_forces_full_recompiles():
    e = Engine()
    e.write_relationships([WriteOp("touch", parse_relationship(
        "namespace:a#creator@user:alice"))])
    e.compiled()
    c0 = metrics.counter("engine_graph_compiles_total").value
    features.set("IncrementalGraphUpdates", False)
    e.write_relationships([WriteOp("touch", parse_relationship(
        "namespace:b#creator@user:alice"))])
    assert e.check(CheckItem("namespace", "b", "view", "user", "alice"))
    assert metrics.counter("engine_graph_compiles_total").value == c0 + 1
    # back on: next write goes incremental again
    features.set("IncrementalGraphUpdates", True)
    e.write_relationships([WriteOp("touch", parse_relationship(
        "namespace:c#creator@user:alice"))])
    assert e.check(CheckItem("namespace", "c", "view", "user", "alice"))
    assert metrics.counter("engine_graph_compiles_total").value == c0 + 1


def test_bitkernel_gate(monkeypatch):
    from spicedb_kubeapi_proxy_tpu.ops import bitprop

    monkeypatch.setenv("SDBKP_BITPROP", "interpret")
    assert bitprop.kernel_enabled()
    features.set("BitKernel", False)
    assert not bitprop.kernel_enabled()


def test_protobuf_gate():
    accept = "application/vnd.kubernetes.protobuf,application/json"
    assert rewrite_accept(accept, False) == accept
    features.set("ProtobufNegotiation", False)
    assert rewrite_accept(accept, False) == "application/json"
