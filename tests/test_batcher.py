"""Cross-request lookup batching: fused dispatches must return exactly
what per-request dispatches return, under max-rows flushes, window
flushes, unknown types, and engine errors."""

import threading

import numpy as np
import pytest

from spicedb_kubeapi_proxy_tpu.engine import Engine, WriteOp
from spicedb_kubeapi_proxy_tpu.models import parse_schema
from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship

SCHEMA = parse_schema("""
definition user {}
definition ns {
  relation viewer: user
  permission view = viewer
}
definition pod {
  relation owner: user
  permission view = owner
}
""")


def build(batch_window=None, max_rows=8):
    e = Engine(schema=SCHEMA)
    rng = np.random.default_rng(0)
    rels = {f"ns:n{rng.integers(30)}#viewer@user:u{rng.integers(20)}"
            for _ in range(200)} | {
        f"pod:p{i}#owner@user:u{i % 20}" for i in range(25)}
    e.write_relationships(
        [WriteOp("touch", parse_relationship(r)) for r in sorted(rels)])
    if batch_window is not None:
        e.enable_lookup_batching(window=batch_window, max_rows=max_rows)
    return e


def masks(e, subjects, rtype="ns"):
    futs = [e.lookup_resources_mask_async(rtype, "view", "user", u)
            for u in subjects]
    return [f.result() for f in futs]


def test_batched_matches_unbatched_across_types():
    plain = build()
    batched = build(batch_window=5.0, max_rows=4)  # flushes on max_rows
    subjects = [f"u{i}" for i in range(8)]
    want_ns = masks(plain, subjects, "ns")
    want_pod = masks(plain, subjects[:4], "pod")

    # heterogeneous batch: mixed types fuse into the same dispatches
    futs = [batched.lookup_resources_mask_async("ns", "view", "user", u)
            for u in subjects[:2]]
    futs += [batched.lookup_resources_mask_async("pod", "view", "user", u)
             for u in subjects[:2]]
    got = [f.result() for f in futs]
    np.testing.assert_array_equal(got[0][0], want_ns[0][0])
    np.testing.assert_array_equal(got[1][0], want_ns[1][0])
    np.testing.assert_array_equal(got[2][0], want_pod[0][0])
    np.testing.assert_array_equal(got[3][0], want_pod[1][0])

    # full sweep through the batcher (window flush for the tail)
    batched2 = build(batch_window=0.01, max_rows=4)
    got_all = masks(batched2, subjects, "ns")
    for (gm, _), (wm, _) in zip(got_all, want_ns):
        np.testing.assert_array_equal(gm, wm)


def test_window_flush_single_item():
    e = build(batch_window=0.01)
    mask, interner = e.lookup_resources_mask("ns", "view", "user", "u3")
    names = {interner.string(i) for i in np.flatnonzero(mask)}
    assert names == set(e.lookup_resources("ns", "view", "user", "u3"))


def test_unknown_type_resolves_none():
    e = build(batch_window=0.01)
    fut = e.lookup_resources_mask_async("nosuch", "view", "user", "u1")
    assert fut.result() == (None, None)


def test_error_propagates_to_all_waiters():
    e = build(batch_window=5.0, max_rows=2)

    def boom(*a, **k):
        raise RuntimeError("device on fire")

    e.compiled()  # pre-build the graph
    e._batcher._dispatch = boom
    f1 = e.lookup_resources_mask_async("ns", "view", "user", "u1")
    f2 = e.lookup_resources_mask_async("ns", "view", "user", "u2")
    with pytest.raises(RuntimeError, match="on fire"):
        f1.result()
    with pytest.raises(RuntimeError, match="on fire"):
        f2.result()


def test_explicit_now_bypasses_batcher():
    # a pinned evaluation time cannot share the batch's dispatch clock
    e = build(batch_window=5.0, max_rows=8)
    import time as _t
    mask, interner = e.lookup_resources_mask(
        "ns", "view", "user", "u3", now=_t.time())
    assert interner is not None  # resolved without waiting on the window


def test_concurrent_threads_fuse():
    e = build(batch_window=0.05, max_rows=8)
    plain = build()
    subjects = [f"u{i}" for i in range(8)]
    want = {u: m for u, (m, _) in zip(subjects, masks(plain, subjects))}
    results = {}
    lock = threading.Lock()

    def worker(u):
        m, _ = e.lookup_resources_mask("ns", "view", "user", u)
        with lock:
            results[u] = m

    threads = [threading.Thread(target=worker, args=(u,)) for u in subjects]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics
    for u in subjects:
        np.testing.assert_array_equal(results[u], want[u])
    # the 8 concurrent lookups fused into at most a few dispatches
    assert metrics.counter("engine_lookup_batches_total").value >= 1


def test_close_marks_batcher_dead_and_submits_fall_through():
    # a submit racing disable_lookup_batching (shutdown) must not queue
    # into a dead batcher whose timer will never fire
    e = build(batch_window=60.0, max_rows=100)  # nothing flushes on its own
    b = e._batcher
    b.close()
    fut = b.submit("ns", "view", "user", "u3", None)
    mask, interner = fut.result()  # direct engine path, no window wait
    want, _ = build().lookup_resources_mask("ns", "view", "user", "u3")
    np.testing.assert_array_equal(mask, want)


def test_disable_lookup_batching_closes_and_flushes():
    e = build(batch_window=60.0, max_rows=100)
    b = e._batcher
    pending = e.lookup_resources_mask_async("ns", "view", "user", "u1")
    e.disable_lookup_batching()
    assert b._closed
    # the pending lookup was flushed by close(), not abandoned
    mask, interner = pending.result()
    want, _ = build().lookup_resources_mask("ns", "view", "user", "u1")
    np.testing.assert_array_equal(mask, want)
    # new lookups take the direct path
    m2, _ = e.lookup_resources_mask("ns", "view", "user", "u2")
    assert m2 is not None
