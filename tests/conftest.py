"""Test configuration: force JAX onto a virtual 8-device CPU platform.

Real-TPU runs happen via bench.py / __graft_entry__.py; unit tests exercise
the same jitted code paths on CPU, including multi-device sharding over a
virtual 8-device mesh (SURVEY.md env notes).
"""

import os
import sys

# Must be set before jax is imported anywhere. Force CPU even if the outer
# environment selects the TPU platform — unit tests must not grab the chip.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Opt-in runtime concurrency sanitizer (PROXY_SANITIZE=1): swap the lock
# factories BEFORE any package module imports, so every named lock in
# the codebase is created instrumented and the whole suite doubles as a
# lock-order / loop-blocking race detector (utils/sanitizer.py). The
# session fixture below fails the run on enforced violations.
_SANITIZE = os.environ.get("PROXY_SANITIZE", "") == "1"
if _SANITIZE:
    from spicedb_kubeapi_proxy_tpu.utils import sanitizer as _sanitizer

    _sanitizer.install()

# The axon TPU plugin (sitecustomize on this image) overrides platform
# selection to "axon,cpu" when jax registers, which makes the first backend
# use initialize the TPU tunnel — slow, single-tenant, and hang-prone from
# test processes. Backends initialize lazily, so forcing the config back to
# cpu here (before any jax computation) keeps tests off the chip entirely.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _proxy_sanitize_gate():
    """With PROXY_SANITIZE=1: after the whole session, report advisory
    findings (hold-time, loop contention) and FAIL on enforced ones
    (lock-order cycles, loop-thread blocking calls) — the acceptance
    bar for the sanitizer-enabled tier-1 run in CI's chaos job."""
    yield
    if not _SANITIZE:
        return
    advisory = [v for v in _sanitizer.report()
                if v.kind not in _sanitizer.ENFORCED_KINDS]
    if advisory:
        print(f"\n[sanitizer] {len(advisory)} advisory finding(s):",
              file=sys.stderr)
        for v in advisory[:40]:
            print(f"[sanitizer]   {v.render()}", file=sys.stderr)
    bad = _sanitizer.enforced_violations()
    assert not bad, (
        "concurrency sanitizer violations:\n"
        + "\n".join(v.render() for v in bad))
