"""Test configuration: force JAX onto a virtual 8-device CPU platform.

Real-TPU runs happen via bench.py / __graft_entry__.py; unit tests exercise
the same jitted code paths on CPU, including multi-device sharding over a
virtual 8-device mesh (SURVEY.md env notes).
"""

import os
import sys

# Must be set before jax is imported anywhere. Force CPU even if the outer
# environment selects the TPU platform — unit tests must not grab the chip.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The axon TPU plugin (sitecustomize on this image) overrides platform
# selection to "axon,cpu" when jax registers, which makes the first backend
# use initialize the TPU tunnel — slow, single-tenant, and hang-prone from
# test processes. Backends initialize lazily, so forcing the config back to
# cpu here (before any jax computation) keeps tests off the chip entirely.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
