"""Fixture tests for the invariant lint suite (tools/analysis/): every
pass must FIRE on its bad snippet and stay QUIET on its good one, the
allowlist grammar must hold, and `--strict` must gate. The real-tree
acceptance (`run.py --strict` over the package with the checked-in
allowlist) runs as a test too, so CI cannot drift from `make analyze`.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.analysis import (core, fail_closed, jit_stability,  # noqa: E402
                            lock_discipline, loop_blocking,
                            metrics_contract)
from tools.analysis.run import main as run_main  # noqa: E402

FIX = os.path.join("tests", "fixtures", "analysis")


def _mods(*relpaths):
    return core.load_modules(REPO, [os.path.join(FIX, p)
                                    for p in relpaths])


def _tokens(findings):
    return sorted(f.token for f in findings)


# ---------------------------------------------------------------- passes

def test_loop_blocking_fires_on_bad():
    fs = loop_blocking.run(_mods("loop_blocking_bad.py"))
    toks = _tokens(fs)
    assert "time.sleep" in toks
    assert "queue.get" in toks and "queue.put" in toks
    assert "sqlite.execute" in toks and "sqlite.commit" in toks
    assert "sqlite3.connect" in toks
    assert "block_until_ready" in toks
    assert len(fs) == 7


def test_loop_blocking_quiet_on_good():
    assert loop_blocking.run(_mods("loop_blocking_good.py")) == []


def test_lock_discipline_fires_on_bad():
    fs = lock_discipline.run(_mods("lock_discipline_bad.py"))
    toks = _tokens(fs)
    assert "time.sleep-under-_lock" in toks
    assert "os.fsync-under-host_lock" in toks
    assert "jax.device_put-under-host_lock" in toks
    assert "await-under-_lock" in toks
    assert "unlocked-iter-_tenants" in toks
    assert "unlocked-snapshot-_subs" in toks
    assert len(fs) == 6


def test_lock_discipline_quiet_on_good():
    assert lock_discipline.run(_mods("lock_discipline_good.py")) == []


def test_fail_closed_fires_on_bad_scoped():
    fs = fail_closed.run(_mods("scoped"))
    toks = _tokens(fs)
    assert "swallowed-Exception" in toks
    assert "swallowed-ValueError" in toks
    assert "retry-after-producer" in toks
    assert "builder-unclamped" in toks
    assert len(fs) == 4


def test_fail_closed_quiet_on_good_scoped():
    # re-raise, domain raise, builder route, explicit fallback, and the
    # REASONED noqa suppression all count as disposal
    assert fail_closed.run(_mods("scoped_good")) == []


def test_fail_closed_ignores_out_of_scope_files():
    # the same swallowed handlers outside the decision-path files are
    # not findings (the lock/loop passes own generic hygiene)
    fs = fail_closed.run(_mods("loop_blocking_bad.py"))
    assert fs == []


def test_jit_stability_fires_on_bad():
    fs = jit_stability.run(_mods("jit_stability_bad.py"))
    toks = _tokens(fs)
    assert "py-branch-n" in toks
    assert "py-range-n" in toks
    assert "np-on-traced-x" in toks
    assert "item-in-jit" in toks
    assert "host-sync-under-_lock" in toks
    # the mesh-path shape: jit target resolved through an assignment
    # chain and the shard_map wrapper (jax.jit(shard_map(partial(f))))
    assert "py-range-n_steps" in toks
    # scope-aware resolution: a SECOND function reusing the same local
    # names (fn/smapped) must still have ITS kernel checked
    assert "py-range-m" in toks
    # taint propagation (ISSUE 17): a Python branch on a value DERIVED
    # from a traced arg (occ = mean(v); if occ <= ...) is a finding —
    # the semiring push/pull switch must stay a lax.cond
    assert "py-branch-derived-frac" in toks
    assert "py-branch-crossover" in toks
    assert len(fs) == 10


def test_jit_stability_quiet_on_good():
    assert jit_stability.run(_mods("jit_stability_good.py")) == []


def test_metrics_contract_fires_on_bad():
    root = os.path.join(REPO, FIX, "metrics_bad_root")
    fs = metrics_contract.run(core.load_modules(root, ["code.py"]), root)
    toks = _tokens(fs)
    assert "kind-conflict-app_requests_total" in toks
    assert "label-conflict-app_sheds_total" in toks
    assert "dynamic-name" in toks
    assert "undocumented-app_undocumented_seconds" in toks
    assert "doc-kind-app_mismatched_kind" in toks
    assert "doc-labels-app_mismatched_labels_total" in toks
    assert "stale-doc-app_removed_total" in toks
    assert len(fs) == 7


def test_metrics_contract_quiet_on_good():
    root = os.path.join(REPO, FIX, "metrics_good_root")
    fs = metrics_contract.run(core.load_modules(root, ["code.py"]), root)
    assert fs == []


def test_metrics_contract_missing_section_is_a_finding(tmp_path):
    code = tmp_path / "code.py"
    code.write_text("def f(m):\n    m.counter('x_total').inc()\n")
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "operations.md").write_text("# no table\n")
    fs = metrics_contract.run(
        core.load_modules(str(tmp_path), ["code.py"]), str(tmp_path))
    assert _tokens(fs) == ["missing-reference-section"]


# ------------------------------------------------------------- allowlist

def test_allowlist_fingerprints_are_line_number_free():
    fs = loop_blocking.run(_mods("loop_blocking_bad.py"))
    fp = fs[0].fingerprint
    assert str(fs[0].line) not in fp.split("|")
    assert fp.count("|") == 3


def test_allowlist_match_and_stale(tmp_path):
    fs = loop_blocking.run(_mods("loop_blocking_bad.py"))
    listed, unlisted = fs[0], fs[-1]
    al = tmp_path / "allow.txt"
    al.write_text(
        f"{listed.fingerprint}  # known, justified\n"
        "loop-blocking|gone.py|<module>|time.sleep  # stale entry\n")
    allow = core.Allowlist.load(str(al))
    assert allow.match(listed)
    assert not allow.match(unlisted)
    assert allow.stale() == [
        "loop-blocking|gone.py|<module>|time.sleep"]


def test_allowlist_requires_justification(tmp_path):
    al = tmp_path / "allow.txt"
    al.write_text("loop-blocking|a.py|f|time.sleep\n"      # no comment
                  "loop-blocking|a.py|f|time.sleep  #\n"   # empty reason
                  "not-a-fingerprint  # why\n")
    allow = core.Allowlist.load(str(al))
    assert len(allow.malformed) == 3
    assert allow.entries == {}


# ------------------------------------------------------------------- CLI

def test_run_strict_fails_on_new_findings(capsys):
    rc = run_main(["--root", REPO, "--strict", "--allowlist", "",
                   os.path.join(FIX, "loop_blocking_bad.py")])
    out = capsys.readouterr().out
    assert rc == 1
    assert "loop-blocking:" in out and "time.sleep" in out


def test_run_strict_passes_on_clean_tree(capsys):
    rc = run_main(["--root", REPO, "--strict", "--allowlist", "",
                   "--select", "loop-blocking,lock-discipline",
                   os.path.join(FIX, "loop_blocking_good.py")])
    assert rc == 0
    assert "0 new" in capsys.readouterr().out


def test_run_unknown_pass_is_an_error():
    assert run_main(["--select", "nope", "--root", REPO]) == 2


def test_syntax_error_is_a_finding(tmp_path, capsys):
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n")
    rc = run_main(["--root", str(tmp_path), "--strict",
                   "--allowlist", "", "broken.py"])
    assert rc == 1
    assert "does not parse" in capsys.readouterr().out


# --------------------------------------------------- the real-tree gate

def test_real_tree_strict_gate_passes():
    """`make analyze` must be green: zero unallowlisted findings over
    the package with the checked-in allowlist. Runs the CLI exactly as
    CI does (subprocess, so argv/exit-code handling is covered too)."""
    proc = subprocess.run(
        [sys.executable, os.path.join("tools", "analysis", "run.py"),
         "--strict"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_docs_metric_table_matches_code_both_directions():
    """The metrics-contract acceptance in-process: no undocumented-,
    stale-doc-, doc-kind- or doc-labels- findings on the real tree."""
    mods = core.load_modules(REPO, ["spicedb_kubeapi_proxy_tpu"])
    fs = metrics_contract.run(mods, REPO)
    allow = core.Allowlist.load(
        os.path.join(REPO, "tools", "analysis", "allowlist.txt"))
    fs = [f for f in fs if not allow.match(f)]
    assert fs == [], [f.render() for f in fs]
