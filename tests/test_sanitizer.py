"""Unit tests for the runtime concurrency sanitizer
(utils/sanitizer.py): seeded lock-order cycle detected, clean ordering
clean, loop-thread sleep detection, hold-time ceiling, Condition
integration, and factory scoping. Tests swap in a private _State so a
PROXY_SANITIZE=1 outer session's accumulated graph is never polluted.
"""

import asyncio
import threading
import time

import pytest

from spicedb_kubeapi_proxy_tpu.utils import sanitizer


@pytest.fixture
def fresh_state(monkeypatch):
    """Swap in a private _State so these tests never pollute (or read)
    a PROXY_SANITIZE=1 outer session's accumulated graph. record_all
    stays False by default: with the factories globally installed,
    attributing every non-package frame would instrument pytest's own
    stdlib locks into the private state."""
    st = sanitizer._State()
    monkeypatch.setattr(sanitizer, "_state", st)
    return st


@pytest.fixture
def reinstall_guard():
    """Restore the session's installation state after a test that
    installs/uninstalls — under PROXY_SANITIZE=1 the factories are
    already live and must stay live for the rest of the session."""
    was = sanitizer.installed()
    yield
    if was and not sanitizer.installed():
        sanitizer.install()
    elif not was and sanitizer.installed():
        sanitizer.uninstall()


def _lock(site):
    # _real_lock: never double-wrap under an outer installed sanitizer
    return sanitizer.SanitizedLock(sanitizer._real_lock(), site, False)


def _rlock(site):
    return sanitizer.SanitizedLock(sanitizer._real_rlock(), site, True)


def _kinds(st):
    return sorted(v.kind for v in st.violations)


def test_seeded_lock_order_cycle_detected(fresh_state):
    a, b = _lock("mod.py:1"), _lock("mod.py:2")

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    for fn in (ab, ba):  # sequential: no real deadlock, just the order
        t = threading.Thread(target=fn)
        t.start()
        t.join()
    kinds = _kinds(fresh_state)
    assert kinds.count("lock-order-cycle") == 1
    v = [x for x in fresh_state.violations
         if x.kind == "lock-order-cycle"][0]
    assert "mod.py:1" in v.render() and "mod.py:2" in v.render()


def test_consistent_order_is_clean(fresh_state):
    a, b = _lock("mod.py:1"), _lock("mod.py:2")
    for _ in range(3):
        with a:
            with b:
                pass
    assert fresh_state.violations == []


def test_three_lock_transitive_cycle(fresh_state):
    a, b, c = _lock("m.py:1"), _lock("m.py:2"), _lock("m.py:3")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with c:
        with a:  # closes a->b->c->a transitively
            pass
    assert "lock-order-cycle" in _kinds(fresh_state)


def test_reentrant_rlock_no_self_edge(fresh_state):
    r = _rlock("m.py:9")
    with r:
        with r:  # reentrant: not an order edge, not a cycle
            pass
    assert fresh_state.violations == []


def test_trylock_never_participates_in_cycles(fresh_state):
    a, b = _lock("m.py:1"), _lock("m.py:2")
    with a:
        with b:
            pass
    with b:
        assert a.acquire(blocking=False)  # trylock cannot deadlock
        a.release()
    assert fresh_state.violations == []


def test_hold_time_ceiling_records(fresh_state):
    fresh_state.hold_ms = 10.0
    lk = _lock("m.py:5")
    with lk:
        sanitizer._real_sleep(0.05)
    assert _kinds(fresh_state) == ["hold-time"]
    # advisory, never enforced
    assert sanitizer.enforced_violations() == []


def test_loop_thread_sleep_detected(fresh_state, reinstall_guard):
    fresh_state.record_all = True  # attribute this test file's frames
    sanitizer.install()

    async def bad():
        time.sleep(0.005)

    asyncio.run(bad())
    kinds = _kinds(fresh_state)
    assert "loop-blocking-call" in kinds
    assert any(v.kind == "loop-blocking-call"
               for v in sanitizer.enforced_violations())


def test_worker_thread_sleep_is_fine(fresh_state, reinstall_guard):
    fresh_state.record_all = True
    sanitizer.install()
    t = threading.Thread(target=time.sleep, args=(0.005,))
    t.start()
    t.join()
    assert [v for v in fresh_state.violations
            if v.kind == "loop-blocking-call"] == []


def test_loop_lock_contention_recorded_but_advisory(fresh_state):
    lk = _lock("m.py:7")
    release = threading.Event()
    held = threading.Event()

    def holder():
        with lk:
            held.set()
            release.wait(2)

    t = threading.Thread(target=holder)
    t.start()
    held.wait(2)

    async def contend():
        loop = asyncio.get_running_loop()
        loop.call_later(0.05, release.set)
        # a blocking acquire on the loop thread that actually contends
        with lk:
            pass

    asyncio.run(contend())
    t.join()
    assert "loop-lock-contention" in _kinds(fresh_state)
    assert sanitizer.enforced_violations() == []


def test_condition_wait_does_not_read_as_held(fresh_state):
    fresh_state.hold_ms = 30.0
    inner = sanitizer._real_rlock()
    lk = sanitizer.SanitizedLock(inner, "m.py:11", True)
    cond = threading.Condition(lk)
    woke = []

    def waiter():
        with cond:
            woke.append(cond.wait(1.0))

    t = threading.Thread(target=waiter)
    t.start()
    sanitizer._real_sleep(0.15)  # waiter parked well past hold_ms
    with cond:
        cond.notify_all()
    t.join()
    assert woke == [True]
    # the wait released the lock: no hold-time for the parked window
    holds = [v for v in fresh_state.violations if v.kind == "hold-time"]
    assert holds == [], [v.render() for v in holds]


def test_factory_scopes_to_package_frames(fresh_state, reinstall_guard):
    sanitizer.install()
    # created from a test frame (not package code): raw primitive
    raw = threading.Lock()
    assert not isinstance(raw, sanitizer.SanitizedLock)
    # created from package code: instrumented
    from spicedb_kubeapi_proxy_tpu.utils.metrics import Registry

    reg = Registry()
    assert isinstance(reg._lock, sanitizer.SanitizedLock)


def test_install_uninstall_restores(fresh_state, reinstall_guard):
    sanitizer.uninstall()  # reach the raw state whatever the session is
    sanitizer.install()
    sanitizer.install()  # idempotent
    assert threading.Lock is not sanitizer._real_lock
    sanitizer.uninstall()
    assert threading.Lock is sanitizer._real_lock
    assert threading.RLock is sanitizer._real_rlock
    assert time.sleep is sanitizer._real_sleep


def test_reset_clears_graph_and_violations(fresh_state):
    a, b = _lock("m.py:1"), _lock("m.py:2")
    with a:
        with b:
            pass
    assert fresh_state.edges
    # reset() acts on the swapped-in state via the module surface
    sanitizer.reset()
    assert fresh_state.edges == {} and fresh_state.violations == []
