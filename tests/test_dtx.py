"""Durable dual-write tests: happy paths, rollbacks, and the crash matrix.

Ports the shape of the reference's e2e failpoint suite
(reference e2e/proxy_test.go:650-860): kube write failures, post-success
crashes, SpiceDB write failures with idempotent retries, per-lock-mode
reruns, and the zero-leftover-locks invariant (proxy_test.go:106-111).
"""

import asyncio
import base64
import json

import pytest

from spicedb_kubeapi_proxy_tpu.dtx import (
    ActivityHandler,
    WorkflowEngine,
    WorkflowInput,
    register_workflows,
)
from spicedb_kubeapi_proxy_tpu.dtx.runner import ActivityError
from spicedb_kubeapi_proxy_tpu.dtx.workflow import (
    LOCK_MODE_OPTIMISTIC,
    LOCK_MODE_PESSIMISTIC,
)
from spicedb_kubeapi_proxy_tpu.engine import (
    CheckItem,
    Engine,
    RelationshipFilter,
    WriteOp,
)
from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship
from spicedb_kubeapi_proxy_tpu.utils.failpoints import failpoints

from fake_kube import FakeKube


def ns_create_input(name="team-a", user="alice") -> WorkflowInput:
    body = json.dumps({"metadata": {"name": name}, "kind": "Namespace"})
    return WorkflowInput(
        verb="create",
        path="/api/v1/namespaces",
        uri="/api/v1/namespaces",
        headers={"Content-Type": "application/json"},
        user_name=user,
        object_name=name,
        namespace="",
        api_group="",
        resource="namespaces",
        body_b64=base64.b64encode(body.encode()).decode(),
        preconditions=[{
            "must_exist": False,
            "filter": {"resource_type": "namespace", "resource_id": name,
                       "relation": "cluster"},
        }],
        creates=[
            f"namespace:{name}#creator@user:{user}",
            f"namespace:{name}#cluster@cluster:cluster",
        ],
    )


def ns_delete_input(name="team-a", user="alice") -> WorkflowInput:
    return WorkflowInput(
        verb="delete",
        path=f"/api/v1/namespaces/{name}",
        uri=f"/api/v1/namespaces/{name}",
        headers={},
        user_name=user,
        object_name=name,
        namespace="",
        api_group="",
        resource="namespaces",
        deletes=[
            f"namespace:{name}#creator@user:{user}",
            f"namespace:{name}#cluster@cluster:cluster",
        ],
    )


class World:
    """Engine + fake kube + workflow runner wired together."""

    def __init__(self, db_path=":memory:"):
        self.engine = Engine()
        self.kube = FakeKube()
        self.db_path = db_path
        self.runner = self.new_runner()

    def new_runner(self) -> WorkflowEngine:
        r = WorkflowEngine(db_path=self.db_path)
        register_workflows(r)
        ActivityHandler(self.engine, self.kube).register(r)
        return r

    def no_leftover_locks(self) -> bool:
        return not self.engine.store.exists(
            RelationshipFilter(resource_type="lock"))

    def has_rel(self, rel: str) -> bool:
        r = parse_relationship(rel)
        return self.engine.store.exists(RelationshipFilter(
            r.resource_type, r.resource_id, r.relation,
            r.subject_type, r.subject_id, r.subject_relation))


@pytest.fixture(autouse=True)
def clear_failpoints():
    failpoints.disable_all()
    yield
    failpoints.disable_all()


@pytest.mark.parametrize("mode", [LOCK_MODE_PESSIMISTIC, LOCK_MODE_OPTIMISTIC])
def test_dual_write_happy_path(mode):
    async def run():
        w = World()
        iid = await w.runner.create_instance(mode, ns_create_input().to_dict())
        out = await w.runner.get_result(iid, timeout=10)
        assert out["status"] == 201
        body = json.loads(base64.b64decode(out["body_b64"]))
        assert body["metadata"]["name"] == "team-a"
        assert ("namespaces", "", "team-a") in w.kube.objects
        assert w.has_rel("namespace:team-a#creator@user:alice")
        assert w.engine.check(CheckItem("namespace", "team-a", "view",
                                        "user", "alice"))
        assert w.no_leftover_locks()
    asyncio.run(run())


@pytest.mark.parametrize("mode", [LOCK_MODE_PESSIMISTIC, LOCK_MODE_OPTIMISTIC])
def test_spicedb_precondition_failure_conflict(mode):
    async def run():
        w = World()
        # precondition (cluster rel must not exist) already violated
        w.engine.write_relationships(
            [WriteOp("touch",
                     parse_relationship("namespace:team-a#cluster@cluster:cluster"))])
        iid = await w.runner.create_instance(mode, ns_create_input().to_dict())
        out = await w.runner.get_result(iid, timeout=10)
        assert out["status"] == 409
        assert ("namespaces", "", "team-a") not in w.kube.objects
        assert not w.has_rel("namespace:team-a#creator@user:alice")
        assert w.no_leftover_locks()
    asyncio.run(run())


def test_lock_conflict_returns_409():
    async def run():
        w = World()
        from spicedb_kubeapi_proxy_tpu.dtx.workflow import resource_lock_rel
        lock = resource_lock_rel(ns_create_input(), "other-workflow")
        w.engine.write_relationships([WriteOp("touch", parse_relationship(lock))])
        iid = await w.runner.create_instance(
            LOCK_MODE_PESSIMISTIC, ns_create_input().to_dict())
        out = await w.runner.get_result(iid, timeout=10)
        assert out["status"] == 409
        assert not w.has_rel("namespace:team-a#creator@user:alice")
        # the other workflow's lock is untouched
        assert w.engine.store.exists(RelationshipFilter(resource_type="lock"))
    asyncio.run(run())


def test_kube_rejection_rolls_back():
    async def run():
        w = World()
        # kube rejects with a NON-retryable failure status (422)
        w.kube.fail_next(n=1, status=422, method="POST")
        iid = await w.runner.create_instance(
            LOCK_MODE_PESSIMISTIC, ns_create_input().to_dict())
        out = await w.runner.get_result(iid, timeout=10)
        assert out["status"] == 422
        assert not w.has_rel("namespace:team-a#creator@user:alice")
        assert w.no_leftover_locks()
    asyncio.run(run())


def test_unsupported_verb_rejected_before_any_side_effect():
    """A dual-write on a verb outside create/update/patch/delete is
    rejected up front in BOTH lock modes — before any SpiceDB write, so
    nothing needs rolling back, no retry budget burns, and (critically)
    the optimistic path's existence arbitration cannot fabricate success
    over committed relationship writes (a collection GET answers 200).
    The activity's verb->method map and _is_successful stay as
    defense-in-depth behind it."""
    async def run():
        for mode in (LOCK_MODE_PESSIMISTIC, LOCK_MODE_OPTIMISTIC):
            w = World()
            inp = ns_create_input()
            inp.verb = "deletecollection"
            iid = await w.runner.create_instance(mode, inp.to_dict())
            with pytest.raises(Exception, match="unsupported kube verb"):
                await w.runner.get_result(iid, timeout=10)
            assert not w.has_rel("namespace:team-a#creator@user:alice"), mode
            assert w.no_leftover_locks(), mode
            assert not w.kube.requests, (mode, "kube must never be hit")
    asyncio.run(run())


def test_kube_transient_exception_retried():
    async def run():
        w = World()
        w.kube.fail_next(n=2, exception=ConnectionError("kaboom"),
                         method="POST")
        iid = await w.runner.create_instance(
            LOCK_MODE_PESSIMISTIC, ns_create_input().to_dict())
        out = await w.runner.get_result(iid, timeout=15)
        assert out["status"] == 201
        assert w.has_rel("namespace:team-a#creator@user:alice")
        assert w.no_leftover_locks()
    asyncio.run(run())


def test_delete_with_404_is_success():
    async def run():
        w = World()
        w.engine.write_relationships([
            WriteOp("touch",
                    parse_relationship("namespace:team-a#creator@user:alice")),
            WriteOp("touch",
                    parse_relationship("namespace:team-a#cluster@cluster:cluster")),
        ])
        # object already gone from kube: delete still succeeds (404 ok)
        iid = await w.runner.create_instance(
            LOCK_MODE_PESSIMISTIC, ns_delete_input().to_dict())
        out = await w.runner.get_result(iid, timeout=10)
        assert out["status"] == 404
        assert not w.has_rel("namespace:team-a#creator@user:alice")
        assert w.no_leftover_locks()
    asyncio.run(run())


@pytest.mark.parametrize("mode", [LOCK_MODE_PESSIMISTIC, LOCK_MODE_OPTIMISTIC])
@pytest.mark.parametrize("failpoint", [
    "panicWriteSpiceDB",     # before the spicedb side effect
    "panicSpiceDBReadResp",  # after the spicedb side effect
    "panicKubeWrite",        # before the kube side effect
    "panicKubeReadResp",     # after the kube side effect
])
def test_crash_matrix_resume_exactly_once(tmp_path, mode, failpoint):
    """Crash at every side-effect edge; a restarted worker must complete the
    dual-write exactly once (reference proxy_test.go:650-830)."""
    async def run():
        db = str(tmp_path / f"dtx-{mode}-{failpoint}.sqlite")
        w = World(db_path=db)
        failpoints.enable(failpoint, 1)
        iid = await w.runner.create_instance(mode, ns_create_input().to_dict())
        with pytest.raises(asyncio.TimeoutError):
            await w.runner.get_result(iid, timeout=0.5)
        assert w.runner.pending_count() == 1
        # "restart": a fresh engine over the same event log
        w.runner = w.new_runner()
        resumed = await w.runner.resume_pending()
        assert resumed == [iid]
        out = await w.runner.get_result(iid, timeout=15)
        assert out["status"] in (201, 409)  # 409: kube write landed pre-crash
        assert ("namespaces", "", "team-a") in w.kube.objects
        assert w.has_rel("namespace:team-a#creator@user:alice")
        assert w.has_rel("namespace:team-a#cluster@cluster:cluster")
        assert w.no_leftover_locks()
        # exactly-once: no duplicate objects, exactly one creator rel
        rels = list(w.engine.read_relationships(RelationshipFilter(
            resource_type="namespace", relation="creator")))
        assert len(rels) == 1
    asyncio.run(run())


def test_optimistic_ambiguous_kube_failure_object_exists():
    """Kube activity fails but the write landed: no rollback
    (reference workflow.go:335-348)."""
    async def run():
        w = World()
        # the object already exists in kube (simulating a prior landed write),
        # and the kube activity raises
        w.kube.objects[("namespaces", "", "team-a")] = {
            "kind": "Namespace", "metadata": {"name": "team-a"}}
        w.kube.fail_next(n=10, exception=ConnectionError("down"),
                         method="POST")
        iid = await w.runner.create_instance(
            LOCK_MODE_OPTIMISTIC, ns_create_input().to_dict())
        out = await w.runner.get_result(iid, timeout=10)
        assert out["status"] == 200
        assert w.has_rel("namespace:team-a#creator@user:alice")
    asyncio.run(run())


def test_optimistic_ambiguous_kube_failure_object_absent():
    async def run():
        w = World()
        # POSTs fail; the existence probe (GET) succeeds and reports absent,
        # so the relationship write must be rolled back (workflow.go:341-346)
        w.kube.fail_next(n=20, exception=ConnectionError("down"),
                         method="POST")
        iid = await w.runner.create_instance(
            LOCK_MODE_OPTIMISTIC, ns_create_input().to_dict())
        with pytest.raises(ActivityError):
            await w.runner.get_result(iid, timeout=10)
        assert not w.has_rel("namespace:team-a#creator@user:alice")
    asyncio.run(run())


def seed_pod_delete_by_filter(w: "World") -> "WorkflowInput":
    """Three pod viewer rels + the kube object, and the deleteByFilter
    input that removes them — shared by the happy-path and crash-resume
    variants."""
    w.engine.write_relationships([
        WriteOp("touch", parse_relationship(f"pod:ns/p#viewer@user:u{i}"))
        for i in range(3)
    ])
    w.kube.objects[("pods", "ns", "p")] = {
        "kind": "Pod", "metadata": {"name": "p", "namespace": "ns"}}
    return WorkflowInput(
        verb="delete", path="/api/v1/namespaces/ns/pods/p",
        uri="/api/v1/namespaces/ns/pods/p", headers={},
        user_name="alice", object_name="p", namespace="ns",
        api_group="", resource="pods",
        delete_by_filter=[{"resource_type": "pod", "resource_id": "ns/p"}],
    )


@pytest.mark.parametrize("failpoint", ["panicReadSpiceDB",
                                       "panicSpiceDBReadRelResp"])
def test_crash_during_delete_by_filter_read_resumes(tmp_path, failpoint):
    """Crash inside the ReadRelationships activity (before/after the
    read) while expanding deleteByFilter: the resumed workflow still
    deletes the stable concrete set exactly once (reference
    workflow.go:354-389; failpoints at activity.go:153,155)."""
    async def run():
        db = str(tmp_path / f"dbf-{failpoint}.sqlite")
        w = World(db_path=db)
        inp = seed_pod_delete_by_filter(w)
        failpoints.enable(failpoint, 1)
        iid = await w.runner.create_instance(LOCK_MODE_PESSIMISTIC,
                                             inp.to_dict())
        with pytest.raises(asyncio.TimeoutError):
            await w.runner.get_result(iid, timeout=0.5)
        w.runner = w.new_runner()
        await w.runner.resume_pending()
        out = await w.runner.get_result(iid, timeout=10)
        assert out["status"] == 200
        assert not w.engine.store.exists(
            RelationshipFilter(resource_type="pod"))
        assert w.no_leftover_locks()
    asyncio.run(run())


def test_crash_during_kube_existence_probe_resumes(tmp_path):
    """Optimistic arbitration: the kube write fails ambiguously, then the
    process dies INSIDE the existence probe (failpoint at
    activity.go:233-247). The resumed workflow re-probes, finds the
    object absent, and rolls the relationships back."""
    async def run():
        db = str(tmp_path / "probe.sqlite")
        w = World(db_path=db)
        w.kube.fail_next(n=20, exception=ConnectionError("down"),
                         method="POST")
        failpoints.enable("panicCheckKube", 1)
        iid = await w.runner.create_instance(
            LOCK_MODE_OPTIMISTIC, ns_create_input().to_dict())
        with pytest.raises(asyncio.TimeoutError):
            await w.runner.get_result(iid, timeout=1.0)
        w.runner = w.new_runner()
        await w.runner.resume_pending()
        with pytest.raises(ActivityError):
            await w.runner.get_result(iid, timeout=10)
        assert not w.has_rel("namespace:team-a#creator@user:alice")
        assert ("namespaces", "", "team-a") not in w.kube.objects
    asyncio.run(run())


def test_delete_by_filter_expansion():
    async def run():
        w = World()
        inp = seed_pod_delete_by_filter(w)
        iid = await w.runner.create_instance(LOCK_MODE_PESSIMISTIC,
                                             inp.to_dict())
        out = await w.runner.get_result(iid, timeout=10)
        assert out["status"] == 200
        assert not w.engine.store.exists(
            RelationshipFilter(resource_type="pod"))
        assert w.no_leftover_locks()
    asyncio.run(run())


def test_workflow_determinism_replay_guard(tmp_path):
    """Replaying with different workflow code fails loudly."""
    async def run():
        db = str(tmp_path / "det.sqlite")
        w = World(db_path=db)
        failpoints.enable("panicKubeWrite", 1)
        iid = await w.runner.create_instance(
            LOCK_MODE_PESSIMISTIC, ns_create_input().to_dict())
        with pytest.raises(asyncio.TimeoutError):
            await w.runner.get_result(iid, timeout=0.5)
        # resume with a DIFFERENT (incompatible) workflow registered
        w.runner = w.new_runner()

        def bogus(ctx, input):
            yield ctx.call("read_relationships", filter={})
            return None

        w.runner.register_workflow(LOCK_MODE_PESSIMISTIC, bogus)
        await w.runner.resume_pending()
        with pytest.raises(ActivityError, match="non-deterministic"):
            await w.runner.get_result(iid, timeout=5)
    asyncio.run(run())


@pytest.mark.parametrize("mode", [LOCK_MODE_PESSIMISTIC, LOCK_MODE_OPTIMISTIC])
def test_ownership_stealing_prevented_when_crashing(tmp_path, mode):
    """paul creates chani's namespace but crashes before reading the kube
    response; the resumed workflow completes PAUL's ownership, and chani's
    later create conflicts instead of stealing it (reference
    proxy_test.go:734-747)."""
    async def run():
        db = str(tmp_path / f"steal-{mode}.sqlite")
        w = World(db_path=db)
        failpoints.enable("panicKubeReadResp", 1)
        iid = await w.runner.create_instance(
            mode, ns_create_input(name="chani-ns", user="paul").to_dict())
        with pytest.raises(asyncio.TimeoutError):
            await w.runner.get_result(iid, timeout=0.5)
        # "restart": paul's dual-write resumes and completes
        w.runner = w.new_runner()
        await w.runner.resume_pending()
        out = await w.runner.get_result(iid, timeout=15)
        assert out["status"] in (201, 409)
        assert w.has_rel("namespace:chani-ns#creator@user:paul")

        # chani attempts to create "her" namespace: conflict, not theft
        iid2 = await w.runner.create_instance(
            mode, ns_create_input(name="chani-ns", user="chani").to_dict())
        out2 = await w.runner.get_result(iid2, timeout=15)
        assert out2["status"] == 409
        assert w.has_rel("namespace:chani-ns#creator@user:paul")
        assert not w.has_rel("namespace:chani-ns#creator@user:chani")
        # chani can't view it — paul owns it and hasn't shared
        assert not w.engine.check(CheckItem("namespace", "chani-ns", "view",
                                            "user", "chani"))
        assert w.engine.check(CheckItem("namespace", "chani-ns", "view",
                                        "user", "paul"))
        assert w.no_leftover_locks()
    asyncio.run(run())


@pytest.mark.parametrize("mode", [LOCK_MODE_PESSIMISTIC, LOCK_MODE_OPTIMISTIC])
def test_ownership_stealing_prevented_when_retrying(tmp_path, mode):
    """paul owns the namespace; chani's create crashes before reading the
    kube response. The resumed retry must surface the conflict and roll
    chani's relationships back — not grant her ownership (reference
    proxy_test.go:748-760)."""
    async def run():
        db = str(tmp_path / f"steal2-{mode}.sqlite")
        w = World(db_path=db)
        iid = await w.runner.create_instance(
            mode, ns_create_input(name="chani-ns", user="paul").to_dict())
        out = await w.runner.get_result(iid, timeout=15)
        assert out["status"] == 201

        # the failpoint is armed but chani's create conflicts at the
        # SpiceDB precondition before any kube write — exactly like the
        # reference run of this scenario, where the rule preconditions are
        # the ownership guard (its kube-409-on-create path deliberately
        # KEEPS relationships, for crash-resume of one's own landed write)
        failpoints.enable("panicKubeReadResp", 1)
        iid2 = await w.runner.create_instance(
            mode, ns_create_input(name="chani-ns", user="chani").to_dict())
        out2 = await w.runner.get_result(iid2, timeout=15)
        assert out2["status"] == 409
        assert w.has_rel("namespace:chani-ns#creator@user:paul")
        assert not w.has_rel("namespace:chani-ns#creator@user:chani")
        assert not w.engine.check(CheckItem("namespace", "chani-ns", "view",
                                            "user", "chani"))
        assert w.no_leftover_locks()
    asyncio.run(run())


@pytest.mark.parametrize("mode", [LOCK_MODE_PESSIMISTIC, LOCK_MODE_OPTIMISTIC])
@pytest.mark.parametrize("rep", range(5))
def test_single_writer_per_object(mode, rep):
    """Two users race to create the same namespace: exactly one wins,
    the loser gets 409 (pessimistic lock conflict / optimistic
    already-exists), run 5x per lock mode — the reference runs this under
    MustPassRepeatedly(5) (proxy_test.go:866-904)."""
    async def run():
        w = World()
        i1, i2 = await asyncio.gather(
            w.runner.create_instance(
                mode, ns_create_input(name="race-ns", user="paul").to_dict()),
            w.runner.create_instance(
                mode, ns_create_input(name="race-ns", user="chani").to_dict()),
        )
        o1, o2 = await asyncio.gather(
            w.runner.get_result(i1, timeout=20),
            w.runner.get_result(i2, timeout=20),
        )
        statuses = sorted([o1["status"], o2["status"]])
        assert statuses == [201, 409], statuses
        winner = "paul" if o1["status"] == 201 else "chani"
        loser = "chani" if winner == "paul" else "paul"
        assert w.has_rel(f"namespace:race-ns#creator@user:{winner}")
        assert not w.has_rel(f"namespace:race-ns#creator@user:{loser}")
        assert w.no_leftover_locks()
    asyncio.run(run())
