"""Delta-overlay write path (ISSUE 8): differential oracle parity across
interleaved write/delete/expiry churn with queries between every
mutation, the fallback edge cases that force a counted recompile
(closured-block expiration-attach, overlay overflow), the
compaction-swap-under-concurrent-dispatch race, overlay-full write
back-pressure, the mirror apply path (a replicated frame must never
shed), and decision-cache retirement at fold cadence."""

import threading
import time

import numpy as np
import pytest

import spicedb_kubeapi_proxy_tpu.ops.reachability as R
from spicedb_kubeapi_proxy_tpu.engine import CheckItem, Engine
from spicedb_kubeapi_proxy_tpu.engine.compaction import (
    MAX_RETRY_AFTER,
    MIN_RETRY_AFTER,
    OverlayBackpressure,
    validate_overlay_config,
)
from spicedb_kubeapi_proxy_tpu.engine.decision_cache import (
    DecisionCache,
    check_key,
)
from spicedb_kubeapi_proxy_tpu.engine.store import (
    RelationshipFilter,
    WriteOp,
)
from spicedb_kubeapi_proxy_tpu.models import parse_schema
from spicedb_kubeapi_proxy_tpu.models.tuples import (
    Relationship,
    parse_relationship as rel,
)
from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

SCHEMA = """
use expiration

definition user {}
definition group { relation member: user | group#member with expiration }
definition namespace {
  relation viewer: group#member | user | user with expiration
  permission view = viewer
}
"""


def build(delta_capacity: int = 256, n_users: int = 6, n_groups: int = 5,
          n_ns: int = 6) -> Engine:
    """Engine with every object pre-seeded into the slot layout (the
    overlay absorbs edges between EXISTING objects; a brand-new object
    is a layout fallback by design) and a compiled base."""
    e = Engine(schema=parse_schema(SCHEMA), delta_capacity=delta_capacity)
    ops = []
    for i in range(n_users):
        ops.append(WriteOp("touch", rel(f"group:g{i % n_groups}#member"
                                        f"@user:u{i}")))
    for i in range(n_ns):
        ops.append(WriteOp("touch", rel(f"namespace:ns{i}#viewer"
                                        f"@user:u{i % n_users}")))
        ops.append(WriteOp("touch", rel(
            f"namespace:ns{i}#viewer@group:g{i % n_groups}#member")))
    e.write_relationships(ops)
    e.compiled()
    # warm the device path so churn tests measure steady state
    e.check_bulk([CheckItem("namespace", "ns0", "view", "user", "u0")])
    return e


def fallback_value(reason: str) -> float:
    return metrics.counter("engine_graph_incremental_fallback_total",
                           reason=reason).value


def assert_oracle_parity(e: Engine, n_users=6, n_ns=6, n_groups=5):
    """Exhaustive namespace#view grid + a spot lookup, twice (the second
    round re-reads the same compiled graph)."""
    for _ in range(2):
        o = e.oracle()
        items, want = [], []
        for i in range(n_ns):
            for u in range(n_users):
                items.append(CheckItem("namespace", f"ns{i}", "view",
                                       "user", f"u{u}"))
                want.append(o.check("namespace", f"ns{i}", "view",
                                    "user", f"u{u}"))
        got = e.check_bulk(items)
        if got != want:
            # an expiration boundary may have passed between oracle and
            # engine reads; a real overlay bug reproduces fresh
            o = e.oracle()
            want = [o.check(it.resource_type, it.resource_id,
                            it.permission, it.subject_type, it.subject_id)
                    for it in items]
            got = e.check_bulk(items)
        bad = [(items[i], got[i], want[i])
               for i in range(len(items)) if got[i] != want[i]]
        assert not bad, bad[:5]
        u = f"u{n_users // 2}"
        got_l = set(e.lookup_resources("namespace", "view", "user", u))
        want_l = e.oracle().lookup_resources("namespace", "view",
                                             "user", u)
        assert got_l == want_l, (u, got_l, want_l)


def test_overlay_differential_randomized_churn():
    """Randomized interleaved write/delete/expiry churn with oracle
    parity after EVERY mutation, and ZERO full recompiles: every
    mutation between pre-seeded objects must ride the overlay."""
    e = build()
    rng = np.random.default_rng(7)
    compiles0 = metrics.counter("engine_graph_compiles_total").value
    live: list[Relationship] = []
    exp_at = None
    for step in range(40):
        r = rng.random()
        if r < 0.35 or not live:
            rl = Relationship("namespace", f"ns{rng.integers(6)}",
                              "viewer", "user", f"u{rng.integers(6)}")
            e.write_relationships([WriteOp("touch", rl)])
            live.append(rl)
        elif r < 0.50:
            # expiring grant: dies while the test still queries
            exp_at = time.time() + 1.2
            rl = Relationship("namespace", f"ns{rng.integers(6)}",
                              "viewer", "user", f"u{rng.integers(6)}",
                              expiration=exp_at)
            e.write_relationships([WriteOp("touch", rl)])
            live.append(rl)
        elif r < 0.70:
            rl = live.pop(int(rng.integers(len(live))))
            e.write_relationships([WriteOp("delete", rl)])
        elif r < 0.85:
            # group membership churn (dense-block territory)
            rl = Relationship("group", f"g{rng.integers(5)}", "member",
                              "user", f"u{rng.integers(6)}")
            e.write_relationships([WriteOp("touch", rl)])
            live.append(rl)
        else:
            # re-touch an existing edge (overlay slot update, not a
            # second slot)
            rl = live[int(rng.integers(len(live)))]
            e.write_relationships([WriteOp("touch", rl)])
        assert_oracle_parity(e)
    if exp_at is not None:
        time.sleep(max(0.0, exp_at + 0.05 - time.time()))
        assert_oracle_parity(e)  # expired overlay edges are invisible
    assert metrics.counter("engine_graph_compiles_total").value \
        == compiles0, "steady-state churn must not recompile"
    assert e.compiled().n_delta > 0


def test_overlay_filter_delete_and_idempotent_redelete():
    e = build()
    compiles0 = metrics.counter("engine_graph_compiles_total").value
    e.write_relationships([WriteOp(
        "touch", rel("namespace:ns1#viewer@user:u4"))])
    n = e.delete_relationships(RelationshipFilter(
        resource_type="namespace", resource_id="ns1"))
    assert n >= 1
    assert_oracle_parity(e)
    # idempotent re-delete of an already-dead base pair: no new dead-
    # ledger growth, still parity
    cg1 = e.compiled()
    e.write_relationships([WriteOp(
        "delete", rel("namespace:ns2#viewer@user:u2"))])
    cg2 = e.compiled()
    e.write_relationships([WriteOp(
        "delete", rel("namespace:ns2#viewer@user:u2"))])
    cg3 = e.compiled()
    assert cg3.n_dead == cg2.n_dead >= cg1.n_dead
    assert_oracle_parity(e)
    assert metrics.counter("engine_graph_compiles_total").value \
        == compiles0


def test_closured_block_delete_recloses_and_expiry_attach_falls_back(
        monkeypatch):
    """The two fallback edge cases of the closured dense block: deleting
    a base group->group edge re-closes the block in place (NO recompile,
    parity held — derived multi-hop cells must die with it), while
    attaching an expiration to a closured pair cannot be expressed
    against the block and must take the counted closured-expiry
    fallback recompile."""
    monkeypatch.setattr(R, "DENSE_MIN_EDGES", 1)
    e = Engine(schema=parse_schema(SCHEMA), delta_capacity=256)
    ops = [WriteOp("touch", rel(f"group:g{i}#member@user:u{i}"))
           for i in range(4)]
    # membership chain g0 <- g1 <- g2 (g2's members reach g0)
    ops += [WriteOp("touch", rel("group:g0#member@group:g1#member")),
            WriteOp("touch", rel("group:g1#member@group:g2#member")),
            WriteOp("touch", rel("namespace:ns0#viewer@group:g0#member"))]
    e.write_relationships(ops)
    cg = e.compiled()
    assert any(b.closured for b in cg.blocks), \
        "test precondition: the group self-block must be closured"
    assert e.check_bulk([CheckItem("namespace", "ns0", "view",
                                   "user", "u2")])[0]  # via g2->g1->g0

    compiles0 = metrics.counter("engine_graph_compiles_total").value
    # delete the middle chain edge: the DERIVED g2->g0 reachability must
    # die with it (a naive single-cell clear would leave it alive)
    e.write_relationships([WriteOp(
        "delete", rel("group:g0#member@group:g1#member"))])
    assert not e.check_bulk([CheckItem("namespace", "ns0", "view",
                                       "user", "u2")])[0]
    assert not e.check_bulk([CheckItem("namespace", "ns0", "view",
                                       "user", "u1")])[0]
    assert e.check_bulk([CheckItem("namespace", "ns0", "view",
                                   "user", "u0")])[0]
    assert metrics.counter("engine_graph_compiles_total").value \
        == compiles0, "closured delete must re-close, not recompile"

    # expiration-attach onto a closured pair: counted fallback recompile
    fb0 = fallback_value("closured-expiry")
    e.write_relationships([WriteOp("touch", Relationship(
        "group", "g1", "member", "group", "g2",
        subject_relation="member", expiration=time.time() + 500))])
    assert e.check_bulk([CheckItem("namespace", "ns0", "view",
                                   "user", "u1") ])[0] is False
    assert fallback_value("closured-expiry") == fb0 + 1
    assert metrics.counter("engine_graph_compiles_total").value \
        == compiles0 + 1

    # a NEW dependency direction (plain add into closured-block
    # territory) is the stratification-inversion fallback — counted
    # under its own reason
    si0 = fallback_value("stratification-inversion")
    e.write_relationships([WriteOp(
        "touch", rel("group:g3#member@group:g0#member"))])
    assert e.check_bulk([CheckItem("group", "g3", "member",
                                   "user", "u0")])[0]
    assert fallback_value("stratification-inversion") >= si0


def test_overlay_overflow_counted_fallback_without_compactor():
    """Without a compactor, overflowing the fixed-capacity overlay is a
    COUNTED fallback to one full recompile (which empties the overlay) —
    correctness never depends on capacity."""
    e = build(delta_capacity=64, n_users=12, n_ns=12)
    fb0 = fallback_value("overflow")
    compiles0 = metrics.counter("engine_graph_compiles_total").value
    for i in range(100):  # > capacity DISTINCT pairs (12x12 pair space)
        e.write_relationships([WriteOp("touch", Relationship(
            "namespace", f"ns{i % 12}", "viewer", "user",
            f"u{(i * 5 + i // 12) % 12}"))])
    assert_oracle_parity(e, n_users=12, n_ns=12)
    assert fallback_value("overflow") > fb0
    assert metrics.counter("engine_graph_compiles_total").value \
        > compiles0
    assert e.compiled().revision == e.store.revision


def test_overlay_full_sheds_bounded_retry_after_nothing_applied():
    """With compaction enabled, overlay-full is admission back-pressure:
    the write sheds BEFORE any store mutation with a bounded
    Retry-After, and a later fold restores write headroom."""
    e = build(delta_capacity=64, n_users=12, n_ns=12)
    c = e.enable_compaction(1.0)
    real_compact, c.compact = c.compact, lambda: False  # freeze the fold
    shed = None
    rev_before = None
    for i in range(200):
        try:
            e.write_relationships([WriteOp("touch", Relationship(
                "namespace", f"ns{i % 12}", "viewer", "user",
                f"u{(i * 5 + i // 12) % 12}"))])
        except OverlayBackpressure as ex:
            rev_before = e.store.revision
            shed = ex
            break
    assert shed is not None, "overlay never filled"
    assert MIN_RETRY_AFTER <= shed.retry_after <= MAX_RETRY_AFTER
    assert shed.capacity == 64 and shed.occupancy <= 64
    # a shed write left no trace: revision unchanged, retrying the same
    # write sheds again identically
    with pytest.raises(OverlayBackpressure):
        e.write_relationships([WriteOp("touch", Relationship(
            "namespace", "ns0", "viewer", "user", "u11"))])
    assert e.store.revision == rev_before
    assert metrics.counter("engine_overlay_backpressure_total").value > 0
    assert_oracle_parity(e, n_users=12, n_ns=12)  # reads keep serving
    # one fold restores headroom
    c.compact = real_compact
    assert c.compact() is True
    e.write_relationships([WriteOp("touch", Relationship(
        "namespace", "ns0", "viewer", "user", "u11"))])
    assert e.store.revision == rev_before + 1
    e.close_compaction()
    assert_oracle_parity(e, n_users=12, n_ns=12)


def test_compaction_swap_under_concurrent_dispatch():
    """Folds swapping the compiled base while reader threads dispatch
    continuously: no errors, every read sees a consistent graph, parity
    at the end, and the swap preserves the revision (decision-cache
    keys stay exactly valid)."""
    e = build(delta_capacity=512)
    e.enable_decision_cache()
    c = e.enable_compaction(1.0)  # manual folds only
    stop = threading.Event()
    errors: list = []

    def reader(k: int):
        i = 0
        while not stop.is_set():
            try:
                got = e.check_bulk([CheckItem(
                    "namespace", f"ns{(i + k) % 6}", "view",
                    "user", f"u{i % 6}")])
                assert isinstance(got[0], bool)
                e.lookup_resources_mask("namespace", "view", "user",
                                        f"u{(i + k) % 6}")
                i += 1
            except Exception as ex:  # noqa: BLE001 - the assertion
                errors.append(ex)
                return

    threads = [threading.Thread(target=reader, args=(k,))
               for k in range(3)]
    for t in threads:
        t.start()
    try:
        for i in range(30):
            e.write_relationships([WriteOp("touch", Relationship(
                "namespace", f"ns{i % 6}", "viewer", "user",
                f"u{(i * 5) % 6}"))])
            if i % 5 == 4:
                rev = e.store.revision
                assert c.compact() is True
                assert e.compiled().revision == rev, \
                    "the swap must preserve the revision"
                assert e.compiled().n_delta == 0
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
        e.close_compaction()
    assert not errors, errors[:3]
    assert metrics.counter("engine_compactions_total").value >= 6
    e.disable_decision_cache()
    assert_oracle_parity(e)


def test_mirror_shed_before_publish_and_apply_never_sheds():
    """Replication safety: the leader's overlay back-pressure runs
    BEFORE the frame is published (a post-publish shed would fork the
    lineages), and a follower applying a replicated frame NEVER sheds —
    overflow there falls back to a counted recompile instead."""
    from spicedb_kubeapi_proxy_tpu.engine.remote import _rel_to_dict
    from spicedb_kubeapi_proxy_tpu.parallel.multihost import (
        MirroredEngine,
        apply_mirror_frame,
    )

    def fill(e: Engine) -> None:
        for i in range(200):
            try:
                e.write_relationships([WriteOp("touch", Relationship(
                    "namespace", f"ns{i % 12}", "viewer", "user",
                    f"u{(i * 5 + i // 12) % 12}"))])
            except OverlayBackpressure:
                return
        raise AssertionError("overlay never filled")

    leader = build(delta_capacity=64, n_users=12, n_ns=12)
    lc = leader.enable_compaction(1.0)
    lc.compact = lambda: False  # freeze: stays full
    fill(leader)
    m = MirroredEngine(leader, mirror_queries=False)
    published = []
    m._publish = lambda *a, **kw: published.append(a) or None
    with pytest.raises(OverlayBackpressure):
        m.write_relationships([WriteOp("touch", Relationship(
            "namespace", "ns0", "viewer", "user", "u11"))])
    assert not published, "a shed write must never reach followers"
    leader.close_compaction()

    follower = build(delta_capacity=64, n_users=12, n_ns=12)
    fc = follower.enable_compaction(1.0)
    fc.compact = lambda: False
    fill(follower)
    rev = follower.store.revision
    frame = {"method": "write_relationships", "ops": [
        {"op": "touch", "rel": _rel_to_dict(Relationship(
            "namespace", "ns0", "viewer", "user", "u11"))}]}
    apply_mirror_frame(follower, frame)  # must NOT raise
    assert follower.store.revision == rev + 1
    assert follower.check_bulk([CheckItem("namespace", "ns0", "view",
                                          "user", "u11")])[0]
    follower.close_compaction()


def test_decision_cache_retire_below():
    dc = DecisionCache(max_entries=128)
    now = time.time()
    it = CheckItem("ns", "n0", "view", "user", "u0")
    for rev in (3, 4, 5):
        dc.put(check_key(rev, it), True, now + 60, 0, now)
    assert dc.retire_below(5) == 2
    assert dc.get(check_key(5, it), now) is True
    assert dc.stats()["entries"] == 1
    assert dc.retire_below(5) == 0  # idempotent


def test_validate_overlay_config_bounds():
    validate_overlay_config(64, 0.0)
    validate_overlay_config(4096, 1.0)
    with pytest.raises(ValueError):
        validate_overlay_config(63, 0.5)
    with pytest.raises(ValueError):
        validate_overlay_config(1024, 1.5)
    with pytest.raises(ValueError):
        validate_overlay_config(1024, -0.1)
