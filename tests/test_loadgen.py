"""Open-loop macrobench + live SLO layer tests (ISSUE 7).

Covers the loadgen subsystem (schedule determinism, the open-loop pin,
sweep/knee math), the SLO monitor (objective parsing, burn rates, the
``slo_*`` metric family, ``/debug/slo``), the previously-unexercised
authz surface the macrobench drives (LookupSubjects, wildcard relations
through the proxy filter path, Table filtering at >=1k rows), and the
shed-503 ``X-Trace-Id`` + rate-capped shed audit line regression.
"""

import asyncio
import json
import threading
import time

import pytest

from spicedb_kubeapi_proxy_tpu.admission import AdmissionRejected
from spicedb_kubeapi_proxy_tpu.authz import AuthzDeps, authorize
from spicedb_kubeapi_proxy_tpu.engine import CheckItem, Engine
from spicedb_kubeapi_proxy_tpu.loadgen import (
    OpenLoopDriver,
    ScheduleConfig,
    build_schedule,
    knee_estimate,
    run_sweep,
    trace_shaped_config,
)
from spicedb_kubeapi_proxy_tpu.loadgen.driver import (
    OUTCOME_ERROR,
    OUTCOME_OK,
    OUTCOME_SHED,
    DriverReport,
)
from spicedb_kubeapi_proxy_tpu.loadgen.schedule import (
    OP_CHECK,
    OP_LIST_PREFILTER,
    OP_WATCH_OPEN,
    burst_windows,
)
from spicedb_kubeapi_proxy_tpu.loadgen.sweep import SweepPoint
from spicedb_kubeapi_proxy_tpu.models import parse_schema
from spicedb_kubeapi_proxy_tpu.obs.audit import AuditLog
from spicedb_kubeapi_proxy_tpu.obs.slo import (
    SLOError,
    SLOMonitor,
    default_objectives,
    parse_objectives,
)
from spicedb_kubeapi_proxy_tpu.obs.trace import tracer
from spicedb_kubeapi_proxy_tpu.proxy.requestinfo import parse_request_info
from spicedb_kubeapi_proxy_tpu.proxy.types import ProxyRequest, json_response
from spicedb_kubeapi_proxy_tpu.rules import MapMatcher
from spicedb_kubeapi_proxy_tpu.rules.input import UserInfo
from spicedb_kubeapi_proxy_tpu.utils.metrics import (
    Histogram,
    Registry,
    metrics,
)

SCHEMA = """
definition user {}
definition group {
  relation member: user
}
definition namespace {
  relation viewer: user | user:* | group#member
  permission view = viewer
}
"""

LIST_RULES = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata:
  name: ns-list
match:
  - apiVersion: v1
    resource: namespaces
    verbs: [list]
prefilter:
  - fromObjectIDNameExpr: "{{resourceId}}"
    lookupMatchingResources:
      tpl: "namespace:$#view@user:{{user.name}}"
"""


def _engine(tuples) -> Engine:
    """An engine over SCHEMA holding ``(ns, subject_type, subject_id[,
    subject_relation])`` viewer tuples (or group member tuples via
    ``("group:g", "user", id)``)."""
    import numpy as np

    cols = {k: [] for k in ("resource_type", "resource_id", "relation",
                            "subject_type", "subject_id",
                            "subject_relation")}
    for t in tuples:
        res, st, sid = t[0], t[1], t[2]
        srl = t[3] if len(t) > 3 else ""
        rt, rid = res.split(":", 1)
        cols["resource_type"].append(rt)
        cols["resource_id"].append(rid)
        cols["relation"].append("viewer" if rt == "namespace" else "member")
        cols["subject_type"].append(st)
        cols["subject_id"].append(sid)
        cols["subject_relation"].append(srl)
    e = Engine(schema=parse_schema(SCHEMA))
    e.bulk_load({k: np.asarray(v) for k, v in cols.items()})
    return e


def _request(method, path, user="alice", query=None):
    query = query or {}
    return ProxyRequest(
        method=method, path=path, query=query,
        headers={"Content-Type": "application/json"}, body=b"",
        user=UserInfo(name=user),
        request_info=parse_request_info(method, path, query))


# -- schedule -----------------------------------------------------------------


def test_identical_seed_identical_schedule():
    """The reproducibility pin: same seed => byte-identical arrivals;
    a different seed diverges."""
    cfg = trace_shaped_config(4.0, 200.0, tenants=6, seed=99)
    a, b = build_schedule(cfg), build_schedule(cfg)
    assert a == b
    assert len(a) > 400
    c = build_schedule(trace_shaped_config(4.0, 200.0, tenants=6, seed=98))
    assert a != c


def test_burst_phases_modulate_rate_and_mix():
    cfg = trace_shaped_config(10.0, 100.0, seed=3, burst_multiplier=4.0)
    sched = build_schedule(cfg)
    wins = dict((n, (a, b)) for n, a, b in burst_windows(cfg))
    s0, s1 = wins["watch-storm"]

    def rate(t0, t1):
        return sum(1 for a in sched if t0 <= a.t < t1) / (t1 - t0)

    # the storm window runs ~4x the pre-storm baseline
    assert rate(s0, s1) > 2.5 * rate(0.0, s0)
    # and its mix shifts toward watch-open
    in_storm = [a for a in sched if s0 <= a.t < s1]
    storm_watch = sum(a.op == OP_WATCH_OPEN for a in in_storm) / len(in_storm)
    base = [a for a in sched if a.t < s0]
    base_watch = sum(a.op == OP_WATCH_OPEN for a in base) / max(1, len(base))
    assert storm_watch > 3 * base_watch
    # arrivals are tagged with their phase
    assert all(a.phase == "watch-storm" and a.burst for a in in_storm)


def test_zipf_tenant_skew():
    cfg = ScheduleConfig(duration=5.0, rate=400.0, tenants=8, zipf_s=1.2,
                         seed=1)
    sched = build_schedule(cfg)
    counts = {}
    for a in sched:
        counts[a.tenant] = counts.get(a.tenant, 0) + 1
    # rank-0 tenant dominates the tail tenant by a wide margin
    assert counts["tenant0"] > 4 * counts.get("tenant7", 1)


# -- driver: the open-loop pin ------------------------------------------------


def test_open_loop_never_closes_under_shedding():
    """THE acceptance pin: a server that sheds half its arrivals and
    stalls the rest gets the full scheduled offered load anyway —
    offered stays within 5% of the schedule."""
    shed = [0]
    done = [0]

    def slow_shedding_op(a):
        if a.key % 2:
            shed[0] += 1
            raise AdmissionRejected("check", "queue full", retry_after=1.0)
        time.sleep(0.02)  # far slower than the arrival gap
        done[0] += 1

    cfg = ScheduleConfig(duration=1.5, rate=300.0, tenants=4, seed=5,
                         mix={OP_CHECK: 1.0})
    sched = build_schedule(cfg)
    driver = OpenLoopDriver({OP_CHECK: slow_shedding_op}, max_workers=8,
                            drain_timeout=10.0)
    rep = driver.run(sched, duration=cfg.duration)
    # every scheduled arrival was fired: the loop never closed, so the
    # offered load is the schedule's, within 5%, no matter what the
    # server did (here: half shed, the rest 6x slower than the gap)
    assert rep.fired_n == rep.scheduled_n == len(sched)
    assert abs(rep.offered_rps - len(sched) / cfg.duration) \
        <= 0.05 * len(sched) / cfg.duration
    # generator drift is REPORTED (late_n), never silently absorbed into
    # arrival times; a stalling server must not push the whole schedule
    # late (that would be the loop closing through the dispatcher)
    assert rep.late_n < rep.fired_n / 2, \
        f"{rep.late_n}/{rep.fired_n} arrivals submitted late"
    # sheds are accounted outcomes, not errors
    per = rep.per_class()[OP_CHECK]
    assert per["shed"] == shed[0] > 50
    assert per["error"] == 0


def test_driver_outcome_accounting_and_exec_split():
    def op(a):
        if a.key % 3 == 0:
            raise AdmissionRejected("check", "shed", retry_after=0.5)
        if a.key % 3 == 1:
            raise ValueError("boom")

    cfg = ScheduleConfig(duration=0.4, rate=200.0, seed=2,
                         mix={OP_CHECK: 1.0}, key_space=30)
    rep = OpenLoopDriver({OP_CHECK: op}, max_workers=4).run(
        build_schedule(cfg), duration=cfg.duration)
    outs = {r.outcome for r in rep.records}
    assert outs == {OUTCOME_OK, OUTCOME_SHED, OUTCOME_ERROR}
    assert rep.error_samples and "boom" in rep.error_samples[0]
    for r in rep.records:
        assert r.latency_s >= r.exec_s >= 0.0


# -- sweep / knee -------------------------------------------------------------


def _point(offered, good_frac):
    rep = DriverReport(duration_s=1.0)
    p = SweepPoint(multiplier=offered / 100.0, offered_rps=offered,
                   fired_n=int(offered), completed_n=int(offered),
                   good_n=int(offered * good_frac), shed_n=0, error_n=0,
                   late_n=0, report=rep)
    return p


def test_knee_estimate_interpolates_crossing():
    pts = [_point(100, 0.99), _point(200, 0.95), _point(400, 0.45)]
    knee, saturated = knee_estimate(pts)
    assert saturated
    assert 200 < knee < 400
    # the crossing of 0.85 between (200, .95) and (400, .45) is at 240
    assert knee == pytest.approx(240.0, rel=0.01)


def test_knee_estimate_never_reached_is_lower_bound():
    pts = [_point(100, 0.99), _point(200, 0.97)]
    knee, saturated = knee_estimate(pts)
    assert not saturated
    assert knee == 200.0


def test_run_sweep_curve_and_burst_schema():
    """A tiny two-point sweep over a fast vs saturating op mix yields
    the full result schema: curve, knee, per-class p50/p99/p99.9,
    burst windows with p99.9, SLO attainment."""
    def fast(a):
        pass

    def watch(a):
        time.sleep(0.001)

    def make_config(m):
        return trace_shaped_config(0.8, 120.0 * m, tenants=3, seed=11,
                                   burst_multiplier=3.0)

    slo_s = {OP_CHECK: 0.05, OP_WATCH_OPEN: 0.05, OP_LIST_PREFILTER: 0.05}
    ops = {OP_CHECK: fast, OP_LIST_PREFILTER: fast, OP_WATCH_OPEN: watch}

    # restrict the mix to the ops this harness implements
    def cfg_for(m):
        cfg = make_config(m)
        cfg.mix = {OP_CHECK: 0.6, OP_LIST_PREFILTER: 0.3,
                   OP_WATCH_OPEN: 0.1}
        for b in cfg.bursts:
            if b.mix is not None:
                b.mix.clear()
                b.mix.update(cfg.mix)
        return cfg

    res = run_sweep(cfg_for, ops, (0.5, 1.0), slo_s, max_workers=8,
                    trace_ops=False, drain_timeout=5.0)
    d = res.to_dict()
    assert len(d["curve"]) == 2
    for pt in d["curve"]:
        assert {"multiplier", "offered_rps", "completed_rps",
                "goodput_rps", "shed", "errors", "late",
                "classes"} <= set(pt)
    assert d["knee_rps"] is not None
    # per-class quantiles carry the p99.9 key
    top = d["curve"][-1]["classes"]
    assert top and all("p999_ms" in q for q in top.values())
    # burst windows from the top point, each class with exact p99.9
    assert set(d["bursts"]) == {"watch-storm", "get-wave", "reconcile"}
    for b in d["bursts"].values():
        assert {"n", "shed", "errors", "window_epoch", "window_rel",
                "classes"} <= set(b)
        for st in b["classes"].values():
            assert {"n", "p50_ms", "p99_ms", "p999_ms"} <= set(st)
    assert set(d["slo_attainment"]) == set(ops)
    for v in d["slo_attainment"].values():
        assert v is None or 0.0 <= v <= 1.0


def test_worst_burst_prefers_fully_shed_window():
    from spicedb_kubeapi_proxy_tpu.loadgen.sweep import _worst_burst

    bursts = {
        "mild": {"n": 50, "shed": 0, "errors": 0,
                 "classes": {"check": {"n": 50, "p50_ms": 1.0,
                                       "p99_ms": 5.0, "p999_ms": 9.0}}},
        "starved": {"n": 40, "shed": 40, "errors": 0, "classes": {}},
    }
    # a window the server shed ENTIRELY is the worst case even though
    # it has no completed-op percentiles to rank by
    assert _worst_burst(bursts) == "starved"
    bursts["starved"]["shed"] = 0
    bursts["starved"]["n"] = 0  # no arrivals at all: not starved
    assert _worst_burst(bursts) == "mild"


# -- metrics satellites -------------------------------------------------------


def test_histogram_quantile_empty_window_is_none_not_zero():
    h = Histogram()
    assert h.quantile(0.5) is None
    assert h.quantile(0.999) is None
    h.observe(0.004)
    assert h.quantile(0.5) is not None
    assert h.quantile(0.999) == h.quantile(0.5)  # single sample


def test_hist_snapshot_label_filter():
    r = Registry()
    r.histogram("lg_test_seconds", op="a").observe(0.001)
    r.histogram("lg_test_seconds", op="b").observe(0.001)
    r.histogram("lg_test_seconds", op="b").observe(0.001)
    assert r.hist_snapshot("lg_test_seconds")["n"] == 3
    assert r.hist_snapshot("lg_test_seconds", op="b")["n"] == 2
    assert r.hist_snapshot("lg_test_seconds", op="nope") is None


# -- SLO monitor --------------------------------------------------------------


def test_parse_objectives_good_and_bad():
    objs = parse_objectives("check=25:99.9, lookup=100:99")
    assert [o.name for o in objs] == ["check", "lookup"]
    assert [o.latency_ms for o in objs] == [25.0, 100.0]
    assert [o.target for o in objs] == pytest.approx([0.999, 0.99])
    assert objs[0].histogram == "engine_check_seconds"
    for bad in ("nope=25:99", "check", "check=abc:99", "check=25:0",
                "check=-1:99", ""):
        with pytest.raises(SLOError):
            parse_objectives(bad)


def test_burn_rate_multi_window():
    """1% bad at a 99.9% target burns 10x; the short window recovers
    once traffic goes clean while the long window still remembers."""
    r = Registry()
    clock = [1000.0]
    mon = SLOMonitor(parse_objectives("check=25:99.9"),
                     windows=(10.0, 100.0), tick_seconds=1.0,
                     clock=lambda: clock[0], registry=r)
    h = r.histogram("engine_check_seconds")
    for _ in range(990):
        h.observe(0.001)  # good
    for _ in range(10):
        h.observe(0.5)  # bad (>25ms)
    clock[0] += 5.0
    mon.tick()
    st = mon._window_stats("check")
    for w in (10.0, 100.0):
        assert st[w]["events"] == 1000
        assert st[w]["bad"] == 10
        assert st[w]["attainment"] == pytest.approx(0.99)
        assert st[w]["burn_rate"] == pytest.approx(10.0, rel=1e-6)
    # clean traffic afterwards: the 10s window forgives, 100s remembers
    for _ in range(1000):
        h.observe(0.001)
    clock[0] += 20.0
    mon.tick()
    st = mon._window_stats("check")
    assert st[10.0]["bad"] == 0 and st[10.0]["burn_rate"] == 0.0
    assert st[100.0]["bad"] == 10 and st[100.0]["burn_rate"] > 0.0
    # gauges exported per window
    assert r.gauge("slo_burn_rate", objective="check",
                   window="10s").value == 0.0
    assert r.gauge("slo_burn_rate", objective="check",
                   window="100s").value > 0.0


def test_slo_counts_sheds_as_bad_events():
    """A shed never reaches the latency histogram; the objective's bad
    counters fold it into both events and bad."""
    r = Registry()
    clock = [0.0]
    mon = SLOMonitor(parse_objectives("check=25:99"), windows=(60.0,),
                     tick_seconds=1.0, clock=lambda: clock[0], registry=r)
    h = r.histogram("engine_check_seconds")
    for _ in range(99):
        h.observe(0.001)
    r.counter("admission_shed_total", **{"class": "check"}).inc()
    clock[0] += 1.0
    mon.tick()
    st = mon._window_stats("check")[60.0]
    assert st["events"] == 100 and st["bad"] == 1
    assert st["burn_rate"] == pytest.approx(1.0, rel=1e-6)


def test_slo_metrics_pass_exposition_contract():
    """slo_* gauges registered in the SHARED registry render through the
    same exposition path the contract lint gates."""
    mon = SLOMonitor(default_objectives(), windows=(60.0,),
                     tick_seconds=1.0)
    mon.tick()
    text = metrics.render()
    assert 'slo_burn_rate{objective="check",window="60s"}' in text
    assert 'slo_attainment{objective="check",window="60s"}' in text
    assert 'slo_objective_latency_ms{objective="check"} 25' in text


def test_slo_ring_prunes_by_age_not_count():
    """Frequent external ticks (every /debug/slo read appends a sample)
    must not shrink the span the long window measures: samples are kept
    for the longest window's duration regardless of tick count."""
    r = Registry()
    clock = [0.0]
    mon = SLOMonitor(parse_objectives("check=25:99"), windows=(100.0,),
                     tick_seconds=5.0, clock=lambda: clock[0], registry=r)
    h = r.histogram("engine_check_seconds")
    h.observe(0.5)  # one bad event at t=0
    mon.tick()
    # a read storm: 500 ticks over 50s — far more samples than the
    # old count-based depth (100/5+2) would have kept
    for i in range(500):
        clock[0] = 0.1 * (i + 1) + 1.0
        mon.tick()
    st = mon._window_stats("check")[100.0]
    assert st["bad"] == 1, "the old bad event fell out of a 100s window"
    # and age pruning still bounds the ring: once the clock moves past
    # the window (plus slack), the old samples are dropped
    clock[0] = 300.0
    mon.tick()
    clock[0] = 301.0
    mon.tick()
    assert mon._ring[0][0] >= 301.0 - 100.0 - 2 * 5.0
    assert len(mon._ring) <= 3


def test_slo_monitor_thread_lifecycle():
    mon = SLOMonitor(default_objectives(), windows=(30.0,),
                     tick_seconds=0.01)
    mon.start()
    mon.start()  # idempotent
    time.sleep(0.05)
    mon.stop()
    assert mon._thread is None
    with pytest.raises(SLOError):
        SLOMonitor([], windows=(30.0,))
    with pytest.raises(SLOError):
        SLOMonitor(default_objectives(), windows=())


# -- /debug/slo ---------------------------------------------------------------


def test_debug_slo_endpoint_flag_gated_and_live(tmp_path):
    from fake_kube import FakeKube
    from spicedb_kubeapi_proxy_tpu.proxy.inmemory import InMemoryClient
    from spicedb_kubeapi_proxy_tpu.proxy.options import Options

    async def go():
        # gated off: 404 even though authenticated
        off = Options(
            rule_content=LIST_RULES, upstream=FakeKube(),
            workflow_database_path=str(tmp_path / "dtx1.sqlite"),
        ).complete()
        alice = InMemoryClient(off.server.handle, user="alice")
        assert (await alice.get("/debug/slo")).status == 404
        await off.workflow.shutdown()

        on = Options(
            rule_content=LIST_RULES, upstream=FakeKube(),
            workflow_database_path=str(tmp_path / "dtx2.sqlite"),
            enable_debug_slo=True,
            slo_objectives="check=25:99.9,request=250:99",
            slo_windows="30,300",
        ).complete()
        try:
            alice = InMemoryClient(on.server.handle, user="alice")
            # unauthenticated is rejected before the endpoint
            anon = InMemoryClient(on.server.handle)
            assert (await anon.get("/debug/slo")).status == 401
            # drive one real request so the request objective has events
            assert (await alice.get("/api/v1/namespaces")).status == 200
            resp = await alice.get("/debug/slo")
            assert resp.status == 200
            doc = json.loads(resp.body)
            assert doc["windows_seconds"] == [30.0, 300.0]
            by_name = {o["name"]: o for o in doc["objectives"]}
            assert set(by_name) == {"check", "request"}
            o = by_name["request"]
            assert o["latency_ms"] == 250.0 and o["target"] == 0.99
            w = o["windows"]["30s"]
            assert {"events", "bad", "attainment", "burn_rate"} <= set(w)
            # the endpoint tick sampled the request we just made
            assert w["events"] >= 1
        finally:
            await on.workflow.shutdown()
            if on.slo_monitor is not None:
                on.slo_monitor.stop()

    asyncio.run(go())


def test_slo_options_validation():
    from spicedb_kubeapi_proxy_tpu.proxy.options import (
        Options,
        OptionsError,
    )

    for kw in ({"slo_objectives": "nope=1:99"},
               {"slo_objectives": "check=25:99", "slo_windows": "0,60"},
               {"enable_debug_slo": True, "slo_windows": "garbage"},
               {"slo_objectives": "check=25:99",
                "slo_tick_seconds": 0.0},
               # a window sampled less than once per span is blind
               {"slo_objectives": "check=25:99", "slo_windows": "60,300",
                "slo_tick_seconds": 90.0}):
        with pytest.raises(OptionsError):
            Options(rule_content="x", upstream_url="http://u",
                    **kw).validate()


# -- shed 503: X-Trace-Id + audit agreement -----------------------------------


def test_shed_503_header_and_audit_line_without_server_wrapper(tmp_path):
    """Regression (ISSUE 7 satellite): the early-reject 503 emitted
    before the root span's normal finish path still carries
    ``X-Trace-Id``, and the shed leaves a rate-capped audit line whose
    trace_id agrees with the header."""
    class AlwaysShed:
        async def acquire_async(self, tenant, cls):
            raise AdmissionRejected(cls.name, "queue full",
                                    retry_after=2.0)

    audit_path = str(tmp_path / "audit.jsonl")
    audit = AuditLog(audit_path, allow_rps=10.0)
    e = _engine([("namespace:ns0", "user", "alice")])
    deps = AuthzDeps(matcher=MapMatcher.from_yaml(LIST_RULES), engine=e,
                     upstream=None, admission=AlwaysShed(), audit=audit)

    async def go():
        tracer.configure(sample=1.0)
        # no server wrapper: authorize() runs under a bare root span the
        # way executor-side callers and in-memory transports drive it
        with tracer.start("request", method="GET",
                          path="/api/v1/namespaces") as root:
            resp = await authorize(
                _request("GET", "/api/v1/namespaces"), deps)
        assert resp.status == 503
        assert resp.headers["X-Trace-Id"] == root.trace_id
        assert resp.headers["Retry-After"] == "2"
        return root.trace_id

    trace_id = asyncio.run(go())
    audit.flush()
    audit.close()
    lines = [json.loads(ln) for ln in open(audit_path)]
    sheds = [r for r in lines if r["decision"] == "shed"]
    assert len(sheds) == 1
    s = sheds[0]
    assert s["class"] == "lookup-prefilter"
    assert s["tenant"] == "alice"
    assert s["verb"] == "list" and s["resource"] == "namespaces"
    assert s["retry_after"] == 2.0
    assert s["trace_id"] == trace_id  # audit and trace agree


def test_shed_audit_lines_rate_capped():
    clock = [0.0]
    import io

    a = AuditLog.__new__(AuditLog)
    # construct against stderr to avoid files, then swap the stream
    AuditLog.__init__(a, "stderr", allow_rps=3.0, clock=lambda: clock[0])
    a._fh = io.StringIO()
    before = metrics.counter("audit_sheds_sampled_out_total").value
    for i in range(10):
        a.shed(op_class="check", tenant=f"t{i}", retry_after=1.0,
               trace_id=f"{i:032x}")
    a.flush()
    out = [json.loads(ln) for ln in a._fh.getvalue().splitlines()]
    assert len(out) == 3  # burst = shed_rps with the clock frozen
    assert all(r["decision"] == "shed" for r in out)
    assert metrics.counter(
        "audit_sheds_sampled_out_total").value - before == 7
    a.close()


# -- the macrobench's authz surface -------------------------------------------


def test_lookup_subjects_direct_group_and_wildcard():
    e = _engine([
        ("namespace:ns0", "user", "alice"),
        ("namespace:ns0", "group", "g0", "member"),
        ("group:g0", "user", "bob"),
        ("group:g0", "user", "carol"),
        ("namespace:other", "user", "dave"),
        ("namespace:pub", "user", "*"),
        ("namespace:pub", "user", "eve"),
    ])
    # direct + group-expanded subjects; dave (other ns only) excluded
    assert e.lookup_subjects("namespace", "ns0", "view", "user") == [
        "alice", "bob", "carol"]
    # the wildcard namespace admits every KNOWN subject, reported as
    # concrete ids — never a literal '*' row
    subs = e.lookup_subjects("namespace", "pub", "view", "user")
    assert "*" not in subs
    assert set(subs) == {"alice", "bob", "carol", "dave", "eve"}
    assert e.lookup_subjects("namespace", "nothere", "view", "user") == []


def test_wildcard_relations_through_proxy_filter_path():
    """A ``user:*`` grant flows end-to-end: prefiltered list responses
    include public namespaces for a subject holding no direct tuples."""
    e = _engine([
        ("namespace:mine", "user", "alice"),
        ("namespace:pub", "user", "*"),
    ])
    items = [{"apiVersion": "v1", "kind": "Namespace",
              "metadata": {"name": n}} for n in ("mine", "pub")]

    async def upstream(req):
        return json_response(200, {"kind": "NamespaceList",
                                   "apiVersion": "v1", "items": items})

    deps = AuthzDeps(matcher=MapMatcher.from_yaml(LIST_RULES), engine=e,
                     upstream=upstream)

    async def names(user):
        resp = await authorize(
            _request("GET", "/api/v1/namespaces", user=user), deps)
        assert resp.status == 200
        return sorted(o["metadata"]["name"]
                      for o in json.loads(resp.body)["items"])

    async def go():
        assert await names("alice") == ["mine", "pub"]
        # ghost has NO tuples at all: the wildcard alone grants pub
        assert await names("ghost") == ["pub"]

    asyncio.run(go())


def test_table_response_filtering_at_1k_rows():
    """Table filtering at macrobench scale: >=1k rows filtered by the
    allowed-set in one pass, kept rows byte-preserved."""
    n_rows, allowed_every = 1500, 3
    e = _engine([(f"namespace:ns{i}", "user", "alice")
                 for i in range(0, n_rows, allowed_every)])
    table = {
        "kind": "Table", "apiVersion": "meta.k8s.io/v1",
        "columnDefinitions": [{"name": "Name", "type": "string"}],
        "rows": [{"cells": [f"ns{i}"],
                  "object": {"kind": "PartialObjectMetadata",
                             "metadata": {"name": f"ns{i}"}}}
                 for i in range(n_rows)],
    }

    async def upstream(req):
        return json_response(200, table)

    deps = AuthzDeps(matcher=MapMatcher.from_yaml(LIST_RULES), engine=e,
                     upstream=upstream)

    async def go():
        resp = await authorize(
            _request("GET", "/api/v1/namespaces", user="alice"), deps)
        assert resp.status == 200
        doc = json.loads(resp.body)
        kept = [r["cells"][0] for r in doc["rows"]]
        assert kept == [f"ns{i}" for i in range(0, n_rows, allowed_every)]
        # a no-tuples user keeps nothing
        resp = await authorize(
            _request("GET", "/api/v1/namespaces", user="ghost"), deps)
        assert json.loads(resp.body)["rows"] == []

    asyncio.run(go())


# -- loadgen metrics land in the shared registry ------------------------------


def test_driver_observes_loadgen_metrics():
    before = metrics.counter("loadgen_ops_total", op=OP_CHECK,
                             outcome=OUTCOME_OK).value
    cfg = ScheduleConfig(duration=0.2, rate=100.0, seed=4,
                         mix={OP_CHECK: 1.0})
    rep = OpenLoopDriver({OP_CHECK: lambda a: None}, max_workers=2).run(
        build_schedule(cfg), duration=cfg.duration)
    after = metrics.counter("loadgen_ops_total", op=OP_CHECK,
                            outcome=OUTCOME_OK).value
    assert after - before == rep.fired_n
    snap = metrics.hist_snapshot("loadgen_op_seconds", op=OP_CHECK)
    assert snap is not None and snap["n"] >= rep.fired_n
