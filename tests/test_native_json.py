"""Native JSON list scanner (graphcore.cpp json_list_spans): the
wire-level filter must agree with the Python json path on every input —
differential-fuzzed over documents with escapes, unicode, nested
containers, odd whitespace, and missing/duplicate fields; anything the
scanner cannot prove structurally identical must BAIL (return None) so
the Python path keeps authority."""

from __future__ import annotations

import json
import random
import string

import pytest

from spicedb_kubeapi_proxy_tpu import native
from spicedb_kubeapi_proxy_tpu.authz.filterer import (
    FilterError,
    _filter_list_wire,
    filter_body,
)
from spicedb_kubeapi_proxy_tpu.authz.lookups import AllowedSet
from spicedb_kubeapi_proxy_tpu.rules.input import (
    RequestInfo,
    ResolveInput,
    UserInfo,
)

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")

INPUT = ResolveInput.create(
    RequestInfo(verb="list", api_version="v1", resource="pods",
                path="/api/v1/pods"),
    UserInfo(name="a"))


def py_filter(body: bytes, allowed: AllowedSet, monkeypatch=None):
    """The pure-Python path, with the wire path forced off."""
    import spicedb_kubeapi_proxy_tpu.authz.filterer as f

    orig = f._filter_list_wire
    f._filter_list_wire = lambda *a: None
    try:
        return filter_body(body, allowed, INPUT)
    finally:
        f._filter_list_wire = orig


NAMES = ["plain", "with/slash", 'quo"te', "back\\slash", "uni-\u65e5\u672c", "tab\there", "new\nline", "\u2028sep", "na\x00me"]


def rand_value(rng, depth=0):
    r = rng.random()
    if depth > 2 or r < 0.3:
        return rng.choice([
            1, -2.5, 1e10, True, False, None, "s", 'esc"aped',
            "unié", rng.random()])
    if r < 0.55:
        return [rand_value(rng, depth + 1) for _ in range(rng.randrange(3))]
    return {f"k{i}": rand_value(rng, depth + 1)
            for i in range(rng.randrange(3))}


def rand_doc(rng):
    items = []
    for _ in range(rng.randrange(6)):
        item = {"metadata": {}}
        if rng.random() < 0.9:
            item["metadata"]["name"] = rng.choice(NAMES)
        if rng.random() < 0.6:
            item["metadata"]["namespace"] = rng.choice(NAMES)
        if rng.random() < 0.5:
            item["metadata"]["labels"] = {
                "".join(rng.choices(string.ascii_letters, k=3)):
                rand_value(rng)}
        if rng.random() < 0.5:
            item["spec"] = rand_value(rng)
        if rng.random() < 0.2:
            del item["metadata"]
        items.append(item)
    doc = {"kind": "PodList", "apiVersion": "v1",
           "metadata": {"resourceVersion": "7"},
           "items": items}
    if rng.random() < 0.3:
        doc["extra"] = rand_value(rng)
    sep = rng.choice([(",", ":"), (", ", ": "), (",\n ", " : ")])
    ea = rng.random() < 0.5
    return json.dumps(doc, separators=sep, ensure_ascii=ea).encode(), items


def test_differential_fuzz_against_python_path():
    rng = random.Random(1234)
    for trial in range(300):
        body, items = rand_doc(rng)
        # random allowed set over the names present (+ noise)
        pool = [((i.get("metadata") or {}).get("namespace") or "",
                 (i.get("metadata") or {}).get("name") or "")
                for i in items]
        allowed = AllowedSet(set(
            p for p in pool if rng.random() < 0.6) | {("x", "noise")})
        py_status, py_out = py_filter(body, allowed)
        wire = _filter_list_wire(body, allowed)
        assert wire is not None, f"trial {trial}: scanner bailed on {body!r}"
        w_status, w_out = wire
        assert w_status == py_status == 200
        assert json.loads(w_out) == json.loads(py_out), \
            f"trial {trial}: {body!r}"
        if w_out != body:
            doc = json.loads(body)
            for i, item in enumerate(doc["items"]):
                pair = ((item.get("metadata") or {}).get("namespace") or "",
                        (item.get("metadata") or {}).get("name") or "")
                if allowed.allows(*pair):
                    frag = json.dumps(
                        item, separators=(",", ":")).encode()
                    # spans carry the ORIGINAL bytes; reparse equality
                    # is already asserted above — here just ensure the
                    # kept item's name appears in the output
                    assert json.loads(frag) in json.loads(w_out)["items"]


def test_wire_no_drop_is_byte_identical_and_drop_splices():
    body = (b'{"kind":"PodList", "items":[\n'
            b'  {"metadata":{"name":"a","namespace":"n1"},"x":1.50},\n'
            b'  {"metadata":{"namespace":"n2","name":"b"}}\n]}')
    both = AllowedSet({("n1", "a"), ("n2", "b")})
    assert _filter_list_wire(body, both) == (200, body)
    one = AllowedSet({("n2", "b")})
    status, out = _filter_list_wire(body, one)
    assert status == 200
    # the kept item's original bytes are spliced verbatim
    assert b'{"metadata":{"namespace":"n2","name":"b"}}' in out
    assert json.loads(out)["items"] == [
        {"metadata": {"namespace": "n2", "name": "b"}}]
    # zero kept: the array empties, wrapper intact
    status, out = _filter_list_wire(body, AllowedSet(set()))
    assert json.loads(out) == {"kind": "PodList", "items": []}


def test_escaped_names_decode_exactly():
    name = 'quo"te\\pathé\n'
    body = json.dumps({"kind": "PodList", "items": [
        {"metadata": {"name": name, "namespace": "ns"}}]}).encode()
    allowed = AllowedSet({("ns", name)})
    assert _filter_list_wire(body, allowed) == (200, body)
    assert _filter_list_wire(
        body, AllowedSet({("ns", "other")}))[1] is not None


@pytest.mark.parametrize("body", [
    b'{"items":[1,2]}',                          # non-object items: bail
    b'{"items":[{}],"items":[{}]}',              # duplicate items: bail
    b'{"items":[{}]} trailing',                  # trailing garbage: bail
    b'{"items":[{"metadata":{"name":123}}]}',    # non-string name: bail
    b'{"items":[{"metadata":{"na\\u006de":"x"}}]}',  # escaped key: bail
    b'not json at all',
    b'{"kind":"Pod","metadata":{"name":"x"}}',   # single object
    b'[1,2,3]',                                  # root array
    # malformed tokens inside SKIPPED values must bail, not be spliced
    # into a 200 (review finding)
    b'{"kind":"PodList","items":['
    b'{"metadata":{"name":"x"},"spec":{"a":@@@}}]}',
    b'{"kind":"PodList","items":['
    b'{"metadata":{"name":"x"},"n":1e+e+5}]}',
    b'{"kind":"PodList","items":[{"metadata":{"name":"x"},"n":01}]}',
    b'{"kind":"PodList","items":[{"metadata":{"name":"x"},"n":+1}]}',
    # invalid escape in a judged name: json.loads rejects the body, so
    # the wire path must yield to the Python path's clean error
    b'{"kind":"PodList","items":[{"metadata":{"name":"a\\qb"}}]}',
    # invalid utf-8 inside an escaped record
    b'{"kind":"PodList","items":[{"metadata":'
    b'{"name":"a\\tb","namespace":"\xff\xfe"}}]}',
])
def test_scanner_bails_conservatively(body):
    """Anything structurally surprising returns None (Python keeps
    authority) — and combined filter_body behavior matches pure-Python."""
    allowed = AllowedSet({("", "x")})
    assert _filter_list_wire(body, allowed) is None
    try:
        py = py_filter(body, allowed)
    except FilterError:
        py = "error"
    try:
        combined = filter_body(body, allowed, INPUT)
    except FilterError:
        combined = "error"
    assert combined == py


def test_table_rows_filter_at_the_wire():
    """JSON Tables route through a rows-keyed rescan: metadata reads
    from each row's ``object``; kept rows stay byte-identical and the
    results match the Python Table path."""
    rng = random.Random(77)
    for _ in range(60):
        rows = []
        for _ in range(rng.randrange(5)):
            row = {"cells": [rng.choice(NAMES), rng.randrange(9)]}
            if rng.random() < 0.85:
                row["object"] = {"kind": "PartialObjectMetadata",
                                 "metadata": {}}
                if rng.random() < 0.9:
                    row["object"]["metadata"]["name"] = rng.choice(NAMES)
                if rng.random() < 0.5:
                    row["object"]["metadata"]["namespace"] = \
                        rng.choice(NAMES)
            rows.append(row)
        doc = {"kind": "Table", "apiVersion": "meta.k8s.io/v1",
               "columnDefinitions": [{"name": "Name", "type": "string"}],
               "rows": rows}
        body = json.dumps(doc,
                          ensure_ascii=rng.random() < 0.5).encode()
        pool = [(((r.get("object") or {}).get("metadata") or {})
                 .get("namespace") or "",
                 ((r.get("object") or {}).get("metadata") or {})
                 .get("name") or "")
                for r in rows]
        allowed = AllowedSet(set(
            p for p in pool if rng.random() < 0.6))
        py = py_filter(body, allowed)
        wire = _filter_list_wire(body, allowed)
        assert wire is not None
        assert wire[0] == py[0] == 200
        assert json.loads(wire[1]) == json.loads(py[1])
        if py[1] == body:
            # nothing dropped: the wire path must be byte-identical too
            assert wire[1] == body
    # empty table passes through byte-identically
    empty = b'{"kind":"Table","rows":[],"items":[]}'
    assert _filter_list_wire(empty, AllowedSet(set())) == (200, empty)


def test_lone_surrogate_names_ride_escaped_records():
    """json.loads accepts lone-surrogate \\u escapes; such names cannot
    UTF-8-encode into the bytes record set, so they compare via the
    decoded-str path — kept and dropped both match the Python path."""
    body = (b'{"kind":"PodList","items":'
            b'[{"metadata":{"name":"a\\ud800b"}}]}')
    name = json.loads('"a\\ud800b"')
    allowed = AllowedSet({("", name)})
    assert _filter_list_wire(body, allowed) == (200, body)
    status, out = _filter_list_wire(body, AllowedSet({("", "z")}))
    assert status == 200 and json.loads(out)["items"] == []
    # invalid utf-8 raw bytes, by contrast, bail (json.loads rejects)
    bad = (b'{"kind":"PodList","items":'
           b'[{"metadata":{"name":"\xed\xa0\x80"}}]}')
    assert _filter_list_wire(bad, allowed) is None


def test_proto_list_native_matches_python_walker():
    """The native proto scanner must produce byte-identical output to
    kubeproto.filter_list_raw across fuzzing: extra fields, duplicate
    metadata, non-length-delimited fields sharing the field numbers."""
    from spicedb_kubeapi_proxy_tpu.authz.filterer import filter_body_proto
    from spicedb_kubeapi_proxy_tpu.proxy import kubeproto

    def ld(fno, payload):
        return (kubeproto._encode_varint((fno << 3) | 2)
                + kubeproto._encode_varint(len(payload)) + payload)

    def vint(fno, v):
        return kubeproto._encode_varint(fno << 3) \
            + kubeproto._encode_varint(v)

    rng = random.Random(99)
    for trial in range(150):
        items = []
        metas = []
        for _ in range(rng.randrange(6)):
            name = rng.choice([n for n in NAMES
                               if "\x00" not in n]) \
                if rng.random() < 0.9 else None
            ns = rng.choice(["", "ns1", "uni-日本"]) \
                if rng.random() < 0.7 else None
            meta = b""
            if rng.random() < 0.3:
                meta += vint(2, rng.randrange(99))  # unrelated varint
            if name is not None:
                meta += ld(1, name.encode())
            if ns:
                meta += ld(3, ns.encode())
            item = b""
            if rng.random() < 0.3:
                item += vint(1, 7)  # field 1 with WRONG wire type first
            item += ld(1, meta)
            if rng.random() < 0.4:
                item += ld(1, ld(1, b"duplicate-meta-ignored"))
            if rng.random() < 0.5:
                item += ld(2, b"\x0a\x03xyz")  # spec-ish nested bytes
            items.append(ld(2, item))
            metas.append((ns or "", name or ""))
        raw = ld(1, b"\x0a\x021")  # ListMeta-ish
        raw += b"".join(items)
        if rng.random() < 0.3:
            raw += vint(9, 5)  # trailing unrelated field
        body = kubeproto.encode_unknown("v1", "PodList", raw)
        allowed = AllowedSet(set(
            p for p in metas if rng.random() < 0.6))
        py_raw = kubeproto.filter_list_raw(raw, allowed.allows)
        py_body = kubeproto.replace_unknown_raw(body, py_raw)
        status, native_body = filter_body_proto(body, allowed, INPUT)
        assert status == 200
        assert native_body == py_body or (
            py_raw == raw and native_body == body), trial
        # no-drop must be byte-identical to the ORIGINAL body
        every = AllowedSet(set(metas) | {("", "")})
        status, out = filter_body_proto(body, every, INPUT)
        assert (status, out) == (200, body)

    # control bytes / invalid utf-8 in a proto name: native bails, the
    # Python walker (errors='replace') keeps authority
    bad_raw = ld(2, ld(1, ld(1, b"\x01ctl")))
    bad_body = kubeproto.encode_unknown("v1", "PodList", bad_raw)
    from spicedb_kubeapi_proxy_tpu import native as _native

    assert _native.proto_list_spans(bad_raw) is None
    status, out = filter_body_proto(bad_body, AllowedSet(set()), INPUT)
    py = kubeproto.replace_unknown_raw(
        bad_body, kubeproto.filter_list_raw(
            bad_raw, AllowedSet(set()).allows))
    assert (status, out) == (200, py)
    bad_utf8 = ld(2, ld(1, ld(1, b"\xff\xfe")))
    assert _native.proto_list_spans(bad_utf8) is None


def test_proto_table_native_matches_python_walker():
    """proto_table_spans must agree with kubeproto.filter_table_raw on
    fuzzing over both object encodings (nested magic Unknown and bare
    PartialObjectMetadata), and bail wherever the walker raises."""
    from spicedb_kubeapi_proxy_tpu.authz.filterer import filter_body_proto
    from spicedb_kubeapi_proxy_tpu.proxy import kubeproto
    from test_kubeproto import table, table_row, unknown as t_unknown

    rng = random.Random(4242)
    for trial in range(120):
        rows = []
        metas = []
        for _ in range(rng.randrange(5)):
            name = rng.choice(["a", "b-2", "uni-日本", "x/y"])
            ns = rng.choice(["", "ns1", "ns2"])
            rows.append(table_row(name, ns,
                                  wrap_unknown=rng.random() < 0.5))
            metas.append((ns, name))
        raw = table(rows)
        body = t_unknown("Table", raw, api_version="meta.k8s.io/v1")
        allowed = AllowedSet(set(
            p for p in metas if rng.random() < 0.6))
        py_raw = kubeproto.filter_table_raw(raw, allowed.allows)
        py_body = kubeproto.replace_unknown_raw(body, py_raw)
        status, out = filter_body_proto(body, allowed, INPUT)
        assert status == 200
        assert out == py_body or (py_raw == raw and out == body), trial
        # no-drop: byte-identical to the ORIGINAL body
        status, out = filter_body_proto(
            body, AllowedSet(set(metas)), INPUT)
        assert (status, out) == (200, body)
    # a row without a keyable object: scanner bails; the walker raises
    # ProtoError -> FilterError (clean 401 upstream)
    from spicedb_kubeapi_proxy_tpu import native as _native
    from spicedb_kubeapi_proxy_tpu.authz.filterer import FilterError

    bare = table([b"\x0a\x03abc"])  # row with cells only, no object
    assert _native.proto_table_spans(bare) is None
    with pytest.raises(FilterError):
        filter_body_proto(
            t_unknown("Table", bare, api_version="meta.k8s.io/v1"),
            AllowedSet(set()), INPUT)


def test_proto_scanner_adversarial_wire():
    """Crafted wire data that would loop/overflow a naive scanner must
    BAIL cleanly (review finding: huge length varints cancel the cursor
    advance; >32-bit field numbers alias onto the items field)."""
    from spicedb_kubeapi_proxy_tpu import native as _native
    from spicedb_kubeapi_proxy_tpu.proxy import kubeproto

    def ld(fno, payload):
        return (kubeproto._encode_varint((fno << 3) | 2)
                + kubeproto._encode_varint(len(payload)) + payload)

    # length varint 2^64-11: i += (int64)len would step BACKWARD
    huge = kubeproto._encode_varint(10)[:0]  # build by hand:
    huge = bytes([0x0A]) + bytes([0xF5] + [0xFF] * 8 + [0x01])
    assert _native.proto_list_spans(huge + b"xxxx") is None
    # same huge length on the items field itself
    evil_item = bytes([0x12]) + bytes([0xF5] + [0xFF] * 8 + [0x01])
    assert _native.proto_list_spans(evil_item + b"xxxx") is None
    # a >32-bit field number whose low bits alias to field 2: Python
    # copies it through; the native scanner must NOT key it as an item
    big_fno = ((1 << 32) + 2)
    tag = kubeproto._encode_varint((big_fno << 3) | 2)
    chunk = tag + kubeproto._encode_varint(4) + b"zzzz"
    item = ld(2, ld(1, ld(1, b"keepme")))
    raw = chunk + item
    scan = _native.proto_list_spans(raw)
    assert scan is not None
    item_spans, keys = scan
    assert len(item_spans) == 1  # only the REAL item keyed
    assert keys == b"0\x1fkeepme\x1e"
    # truncated payload lengths at every nesting level bail
    assert _native.proto_list_spans(ld(2, ld(1, b"\x0a\x7fshort"))) is None


def test_kind_and_whitespace_variants():
    body = (b'  {  "apiVersion" : "v1" ,\n "items" : [ '
            b'{ "metadata" : { "name" : "w" } } ] , "kind" : "PodList" }  ')
    allowed = AllowedSet({("", "w")})
    assert _filter_list_wire(body, allowed) == (200, body)
    status, out = _filter_list_wire(body, AllowedSet(set()))
    assert json.loads(out)["items"] == []
