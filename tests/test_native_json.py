"""Native JSON list scanner (graphcore.cpp json_list_spans): the
wire-level filter must agree with the Python json path on every input —
differential-fuzzed over documents with escapes, unicode, nested
containers, odd whitespace, and missing/duplicate fields; anything the
scanner cannot prove structurally identical must BAIL (return None) so
the Python path keeps authority."""

from __future__ import annotations

import json
import random
import string

import pytest

from spicedb_kubeapi_proxy_tpu import native
from spicedb_kubeapi_proxy_tpu.authz.filterer import (
    FilterError,
    _filter_list_wire,
    filter_body,
)
from spicedb_kubeapi_proxy_tpu.authz.lookups import AllowedSet
from spicedb_kubeapi_proxy_tpu.rules.input import (
    RequestInfo,
    ResolveInput,
    UserInfo,
)

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")

INPUT = ResolveInput.create(
    RequestInfo(verb="list", api_version="v1", resource="pods",
                path="/api/v1/pods"),
    UserInfo(name="a"))


def py_filter(body: bytes, allowed: AllowedSet, monkeypatch=None):
    """The pure-Python path, with the wire path forced off."""
    import spicedb_kubeapi_proxy_tpu.authz.filterer as f

    orig = f._filter_list_wire
    f._filter_list_wire = lambda *a: None
    try:
        return filter_body(body, allowed, INPUT)
    finally:
        f._filter_list_wire = orig


NAMES = ["plain", "with/slash", 'quo"te', "back\\slash", "uni-\u65e5\u672c", "tab\there", "new\nline", "\u2028sep", "na\x00me"]


def rand_value(rng, depth=0):
    r = rng.random()
    if depth > 2 or r < 0.3:
        return rng.choice([
            1, -2.5, 1e10, True, False, None, "s", 'esc"aped',
            "unié", rng.random()])
    if r < 0.55:
        return [rand_value(rng, depth + 1) for _ in range(rng.randrange(3))]
    return {f"k{i}": rand_value(rng, depth + 1)
            for i in range(rng.randrange(3))}


def rand_doc(rng):
    items = []
    for _ in range(rng.randrange(6)):
        item = {"metadata": {}}
        if rng.random() < 0.9:
            item["metadata"]["name"] = rng.choice(NAMES)
        if rng.random() < 0.6:
            item["metadata"]["namespace"] = rng.choice(NAMES)
        if rng.random() < 0.5:
            item["metadata"]["labels"] = {
                "".join(rng.choices(string.ascii_letters, k=3)):
                rand_value(rng)}
        if rng.random() < 0.5:
            item["spec"] = rand_value(rng)
        if rng.random() < 0.2:
            del item["metadata"]
        items.append(item)
    doc = {"kind": "PodList", "apiVersion": "v1",
           "metadata": {"resourceVersion": "7"},
           "items": items}
    if rng.random() < 0.3:
        doc["extra"] = rand_value(rng)
    sep = rng.choice([(",", ":"), (", ", ": "), (",\n ", " : ")])
    ea = rng.random() < 0.5
    return json.dumps(doc, separators=sep, ensure_ascii=ea).encode(), items


def test_differential_fuzz_against_python_path():
    rng = random.Random(1234)
    for trial in range(300):
        body, items = rand_doc(rng)
        # random allowed set over the names present (+ noise)
        pool = [((i.get("metadata") or {}).get("namespace") or "",
                 (i.get("metadata") or {}).get("name") or "")
                for i in items]
        allowed = AllowedSet(set(
            p for p in pool if rng.random() < 0.6) | {("x", "noise")})
        py_status, py_out = py_filter(body, allowed)
        wire = _filter_list_wire(body, allowed)
        assert wire is not None, f"trial {trial}: scanner bailed on {body!r}"
        w_status, w_out = wire
        assert w_status == py_status == 200
        assert json.loads(w_out) == json.loads(py_out), \
            f"trial {trial}: {body!r}"
        if w_out != body:
            doc = json.loads(body)
            for i, item in enumerate(doc["items"]):
                pair = ((item.get("metadata") or {}).get("namespace") or "",
                        (item.get("metadata") or {}).get("name") or "")
                if allowed.allows(*pair):
                    frag = json.dumps(
                        item, separators=(",", ":")).encode()
                    # spans carry the ORIGINAL bytes; reparse equality
                    # is already asserted above — here just ensure the
                    # kept item's name appears in the output
                    assert json.loads(frag) in json.loads(w_out)["items"]


def test_wire_no_drop_is_byte_identical_and_drop_splices():
    body = (b'{"kind":"PodList", "items":[\n'
            b'  {"metadata":{"name":"a","namespace":"n1"},"x":1.50},\n'
            b'  {"metadata":{"namespace":"n2","name":"b"}}\n]}')
    both = AllowedSet({("n1", "a"), ("n2", "b")})
    assert _filter_list_wire(body, both) == (200, body)
    one = AllowedSet({("n2", "b")})
    status, out = _filter_list_wire(body, one)
    assert status == 200
    # the kept item's original bytes are spliced verbatim
    assert b'{"metadata":{"namespace":"n2","name":"b"}}' in out
    assert json.loads(out)["items"] == [
        {"metadata": {"namespace": "n2", "name": "b"}}]
    # zero kept: the array empties, wrapper intact
    status, out = _filter_list_wire(body, AllowedSet(set()))
    assert json.loads(out) == {"kind": "PodList", "items": []}


def test_escaped_names_decode_exactly():
    name = 'quo"te\\pathé\n'
    body = json.dumps({"kind": "PodList", "items": [
        {"metadata": {"name": name, "namespace": "ns"}}]}).encode()
    allowed = AllowedSet({("ns", name)})
    assert _filter_list_wire(body, allowed) == (200, body)
    assert _filter_list_wire(
        body, AllowedSet({("ns", "other")}))[1] is not None


@pytest.mark.parametrize("body", [
    b'{"items":[1,2]}',                          # non-object items: bail
    b'{"items":[{}],"items":[{}]}',              # duplicate items: bail
    b'{"items":[{}]} trailing',                  # trailing garbage: bail
    b'{"items":[{"metadata":{"name":123}}]}',    # non-string name: bail
    b'{"items":[{"metadata":{"na\\u006de":"x"}}]}',  # escaped key: bail
    b'not json at all',
    b'{"kind":"Pod","metadata":{"name":"x"}}',   # single object
    b'[1,2,3]',                                  # root array
    # malformed tokens inside SKIPPED values must bail, not be spliced
    # into a 200 (review finding)
    b'{"kind":"PodList","items":['
    b'{"metadata":{"name":"x"},"spec":{"a":@@@}}]}',
    b'{"kind":"PodList","items":['
    b'{"metadata":{"name":"x"},"n":1e+e+5}]}',
    b'{"kind":"PodList","items":[{"metadata":{"name":"x"},"n":01}]}',
    b'{"kind":"PodList","items":[{"metadata":{"name":"x"},"n":+1}]}',
    # invalid escape in a judged name: json.loads rejects the body, so
    # the wire path must yield to the Python path's clean error
    b'{"kind":"PodList","items":[{"metadata":{"name":"a\\qb"}}]}',
    # invalid utf-8 inside an escaped record
    b'{"kind":"PodList","items":[{"metadata":'
    b'{"name":"a\\tb","namespace":"\xff\xfe"}}]}',
])
def test_scanner_bails_conservatively(body):
    """Anything structurally surprising returns None (Python keeps
    authority) — and combined filter_body behavior matches pure-Python."""
    allowed = AllowedSet({("", "x")})
    assert _filter_list_wire(body, allowed) is None
    try:
        py = py_filter(body, allowed)
    except FilterError:
        py = "error"
    try:
        combined = filter_body(body, allowed, INPUT)
    except FilterError:
        combined = "error"
    assert combined == py


def test_table_rows_filter_at_the_wire():
    """JSON Tables route through a rows-keyed rescan: metadata reads
    from each row's ``object``; kept rows stay byte-identical and the
    results match the Python Table path."""
    rng = random.Random(77)
    for _ in range(60):
        rows = []
        for _ in range(rng.randrange(5)):
            row = {"cells": [rng.choice(NAMES), rng.randrange(9)]}
            if rng.random() < 0.85:
                row["object"] = {"kind": "PartialObjectMetadata",
                                 "metadata": {}}
                if rng.random() < 0.9:
                    row["object"]["metadata"]["name"] = rng.choice(NAMES)
                if rng.random() < 0.5:
                    row["object"]["metadata"]["namespace"] = \
                        rng.choice(NAMES)
            rows.append(row)
        doc = {"kind": "Table", "apiVersion": "meta.k8s.io/v1",
               "columnDefinitions": [{"name": "Name", "type": "string"}],
               "rows": rows}
        body = json.dumps(doc,
                          ensure_ascii=rng.random() < 0.5).encode()
        pool = [(((r.get("object") or {}).get("metadata") or {})
                 .get("namespace") or "",
                 ((r.get("object") or {}).get("metadata") or {})
                 .get("name") or "")
                for r in rows]
        allowed = AllowedSet(set(
            p for p in pool if rng.random() < 0.6))
        py = py_filter(body, allowed)
        wire = _filter_list_wire(body, allowed)
        assert wire is not None
        assert wire[0] == py[0] == 200
        assert json.loads(wire[1]) == json.loads(py[1])
        if py[1] == body:
            # nothing dropped: the wire path must be byte-identical too
            assert wire[1] == body
    # empty table passes through byte-identically
    empty = b'{"kind":"Table","rows":[],"items":[]}'
    assert _filter_list_wire(empty, AllowedSet(set())) == (200, empty)


def test_lone_surrogate_names_ride_escaped_records():
    """json.loads accepts lone-surrogate \\u escapes; such names cannot
    UTF-8-encode into the bytes record set, so they compare via the
    decoded-str path — kept and dropped both match the Python path."""
    body = (b'{"kind":"PodList","items":'
            b'[{"metadata":{"name":"a\\ud800b"}}]}')
    name = json.loads('"a\\ud800b"')
    allowed = AllowedSet({("", name)})
    assert _filter_list_wire(body, allowed) == (200, body)
    status, out = _filter_list_wire(body, AllowedSet({("", "z")}))
    assert status == 200 and json.loads(out)["items"] == []
    # invalid utf-8 raw bytes, by contrast, bail (json.loads rejects)
    bad = (b'{"kind":"PodList","items":'
           b'[{"metadata":{"name":"\xed\xa0\x80"}}]}')
    assert _filter_list_wire(bad, allowed) is None


def test_kind_and_whitespace_variants():
    body = (b'  {  "apiVersion" : "v1" ,\n "items" : [ '
            b'{ "metadata" : { "name" : "w" } } ] , "kind" : "PodList" }  ')
    allowed = AllowedSet({("", "w")})
    assert _filter_list_wire(body, allowed) == (200, body)
    status, out = _filter_list_wire(body, AllowedSet(set()))
    assert json.loads(out)["items"] == []
