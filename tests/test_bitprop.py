"""Bit-packed propagation kernel: parity with the matmul path and the
numpy oracle (kernel runs in Pallas interpreter mode on CPU)."""

import numpy as np
import pytest

import jax.numpy as jnp

from spicedb_kubeapi_proxy_tpu.ops import bitprop


def _random_block(rng, n_dst, n_src, n_edges):
    dst = rng.integers(n_dst, size=n_edges).astype(np.int32)
    src = rng.integers(n_src, size=n_edges).astype(np.int32)
    return dst, src


def test_pack_block_host_sets_expected_bits():
    bits = bitprop.pack_block_host(
        np.asarray([0, 0, 2]), np.asarray([0, 33, 127]), n_dst=32, n_src=128)
    assert bits.shape == (32, 128)  # K padded to one lane row
    assert bits[0, 0] == 1  # src 0 -> word 0 bit 0
    assert bits[0, 1] == 2  # src 33 -> word 1 bit 1
    assert bits[2, 3] == np.uint32(1) << 31  # src 127 -> word 3 bit 31


@pytest.mark.parametrize("n_dst,n_src,n_b", [
    (32, 32, 1), (256, 128, 3), (512, 1024, 8), (288, 96, 2),
])
def test_kernel_matches_oracle(monkeypatch, n_dst, n_src, n_b):
    monkeypatch.setenv("SDBKP_BITPROP", "interpret")
    rng = np.random.default_rng(n_dst + n_src)
    dst, src = _random_block(rng, n_dst, n_src, n_edges=4 * n_dst)
    a_bits = bitprop.pack_block_host(dst, src, n_dst, n_src)
    frontier = (rng.random((n_src, n_b)) < 0.1).astype(np.uint8)

    # engine layout: frontier rows are batch lanes [B, n_src]
    vb = bitprop.pack_frontier(jnp.asarray(frontier.T.copy()), n_src)
    got = np.asarray(bitprop.bit_or_matmul(
        jnp.asarray(a_bits), vb, n_b))
    want = bitprop.bit_hop_reference(a_bits, frontier)
    np.testing.assert_array_equal(got, want)
    # cross-check the oracle against the dense matmul formulation
    dense = np.zeros((n_dst, n_src), dtype=np.int32)
    dense[dst, src] = 1
    np.testing.assert_array_equal(
        want, (dense @ frontier.astype(np.int32) > 0).astype(np.uint8))


def test_vmem_budget_bounds_eligibility(monkeypatch):
    """Blocks whose packed rows cannot fit VMEM at any tile fall back to
    the matmul path instead of failing Mosaic compilation at runtime."""
    # small blocks: eligible, and bigger dst picks a bigger tile
    assert bitprop.eligible(256, 4096)
    assert bitprop.pick_tile(256, 4096) == 256

    # 10M-src block: packed K ~ 312k words -> even a 32-row tile is
    # ~2*32*312k*4 + 8*312k*4 ≈ 90MB >> budget
    n_src_huge = 10_000_000 - (10_000_000 % 32)
    assert not bitprop.eligible(512, n_src_huge)
    assert bitprop.pick_tile(512, n_src_huge) is None

    # mid-size: full 256 tile busts the budget but a smaller one fits ->
    # still eligible, with a reduced tile
    monkeypatch.setattr(bitprop, "VMEM_BUDGET", 2 * 1024 * 1024)
    n_src_mid = 32 * 32 * bitprop.LANES  # K = 4096 words = 16KiB rows
    t = bitprop.pick_tile(256, n_src_mid)
    assert t is not None and t < 256
    assert bitprop.eligible(256, n_src_mid)
    # and the kernel actually runs with the reduced tile
    monkeypatch.setenv("SDBKP_BITPROP", "interpret")
    rng = np.random.default_rng(3)
    dst, src = _random_block(rng, 64, n_src_mid, n_edges=200)
    a_bits = bitprop.pack_block_host(dst, src, 64, n_src_mid)
    frontier = np.zeros((n_src_mid, 1), dtype=np.uint8)
    frontier[src[:5], 0] = 1
    vb = bitprop.pack_frontier(jnp.asarray(frontier.T.copy()), n_src_mid)
    got = np.asarray(bitprop.bit_or_matmul(jnp.asarray(a_bits), vb, 1))
    np.testing.assert_array_equal(
        got, bitprop.bit_hop_reference(a_bits, frontier))


def test_engine_query_parity_bit_vs_matmul(monkeypatch):
    """Same engine queries through both block representations."""
    from spicedb_kubeapi_proxy_tpu.engine import CheckItem, Engine, WriteOp
    from spicedb_kubeapi_proxy_tpu.models import parse_schema
    from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship
    from spicedb_kubeapi_proxy_tpu.ops import reachability

    monkeypatch.setattr(reachability, "DENSE_MIN_EDGES", 4)
    schema = parse_schema("""
definition user {}
definition ns {
  relation viewer: user
  permission view = viewer
}
""")
    rng = np.random.default_rng(7)
    rels = [f"ns:n{rng.integers(40)}#viewer@user:u{rng.integers(30)}"
            for _ in range(300)]

    def run(mode):
        monkeypatch.setenv("SDBKP_BITPROP", mode)
        e = Engine(schema=schema)
        e.write_relationships(
            [WriteOp("touch", parse_relationship(r)) for r in set(rels)])
        items = [CheckItem("ns", f"n{i}", "view", "user", f"u{i % 30}")
                 for i in range(40)]
        # B=1 per check_bulk row grouping is engine-internal; both calls
        # use identical inputs either way
        return e.check_bulk(items)

    assert run("interpret") == run("0")
