"""Remote engine endpoint: protocol round-trips, error-kind fidelity,
token auth, and the full proxy running against a tcp:// engine host
(the reference's remote-SpiceDB deployment shape, options.go:325-369)."""

import asyncio
import json

import pytest

from spicedb_kubeapi_proxy_tpu.engine import (
    CheckItem,
    Engine,
    RelationshipFilter,
    WriteOp,
)
from spicedb_kubeapi_proxy_tpu.engine.remote import (
    EngineServer,
    RemoteEngine,
    RemoteEngineError,
)
from spicedb_kubeapi_proxy_tpu.engine.store import (
    Precondition,
    PreconditionFailed,
)
from spicedb_kubeapi_proxy_tpu.engine.engine import SchemaViolation
from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship
from spicedb_kubeapi_proxy_tpu.proxy.inmemory import InMemoryClient
from spicedb_kubeapi_proxy_tpu.proxy.options import Options, OptionsError

from fake_kube import FakeKube


def run_with_server(engine, fn, token=None):
    """Run ``await fn(remote)`` with an EngineServer live on the loop."""
    async def go():
        server = EngineServer(engine, token=token)
        port = await server.start()
        remote = RemoteEngine("127.0.0.1", port, token=token)
        try:
            return await fn(remote)
        finally:
            remote.close()
            await server.stop()
    return asyncio.run(go())


def test_remote_round_trips():
    e = Engine()
    rels = ["namespace:dev#creator@user:alice",
            "pod:dev/api#namespace@namespace:dev"]
    e.write_relationships(
        [WriteOp("touch", parse_relationship(r)) for r in rels])

    async def fn(remote):
        rev0 = await asyncio.to_thread(lambda: remote.revision)
        assert rev0 == e.revision
        # check_bulk
        got = await asyncio.to_thread(remote.check_bulk, [
            CheckItem("namespace", "dev", "view", "user", "alice"),
            CheckItem("namespace", "dev", "view", "user", "bob"),
        ])
        assert got == [True, False]
        # lookup
        assert await asyncio.to_thread(
            remote.lookup_resources, "namespace", "view", "user", "alice"
        ) == ["dev"]
        # writes round-trip incl. revision bump + watch events
        rel = parse_relationship("namespace:dev#viewer@user:bob")
        rev = await asyncio.to_thread(
            remote.write_relationships, [WriteOp("touch", rel)])
        assert rev > rev0
        assert await asyncio.to_thread(remote.check_bulk, [
            CheckItem("namespace", "dev", "view", "user", "bob")]) == [True]
        events = await asyncio.to_thread(remote.watch_since, rev0)
        assert [str(ev.relationship) for ev in events] == [str(rel)]
        # read + store.exists shim
        out = await asyncio.to_thread(
            remote.read_relationships,
            RelationshipFilter(resource_type="namespace"))
        assert str(rel) in {str(r) for r in out}
        assert await asyncio.to_thread(
            remote.store.exists,
            RelationshipFilter(subject_id="bob"))
        # delete
        await asyncio.to_thread(
            remote.delete_relationships,
            RelationshipFilter(subject_id="bob"))
        assert not await asyncio.to_thread(
            remote.store.exists, RelationshipFilter(subject_id="bob"))
    run_with_server(e, fn)


def test_remote_large_chunked_check_bulk():
    """A 40k-item bulk check over tcp:// — the shared-engine-host shape —
    exercising the chunked device pipeline server-side, the big-frame
    path client-side, and exact result ordering across chunk bounds."""
    import numpy as np

    rng = np.random.default_rng(5)
    e = Engine()
    n_ns, n_users = 40, 25
    ops = []
    grants = set()
    for i in range(n_ns):
        u = int(rng.integers(n_users))
        ops.append(f"namespace:n{i}#creator@user:u{u}")
        grants.add((i, u))
    e.write_relationships(
        [WriteOp("touch", parse_relationship(r)) for r in ops])

    items, want = [], []
    for _ in range(40_000):
        i, u = int(rng.integers(n_ns)), int(rng.integers(n_users))
        items.append(CheckItem("namespace", f"n{i}", "view", "user", f"u{u}"))
        want.append((i, u) in grants)

    async def fn(remote):
        got = await asyncio.to_thread(remote.check_bulk, items)
        assert got == want
    run_with_server(e, fn)


def test_remote_mask_wire_round_trip_and_incremental_sync():
    """The list-filter hot path over tcp://: lookups ride a packed
    bitmask + an incrementally-synced id table, not a JSON string list.
    Results must match the in-process engine exactly; the second lookup
    must fetch only the id-table DELTA; a server-side snapshot restore
    (new interner epoch) must invalidate the client cache, not alias ids."""
    import numpy as np

    e = Engine()
    ops = [WriteOp("touch", parse_relationship(
        f"namespace:n{i}#creator@user:alice")) for i in range(50)]
    ops += [WriteOp("touch", parse_relationship(
        "namespace:other#creator@user:bob"))]
    e.write_relationships(ops)

    async def fn(remote):
        calls = []
        orig = RemoteEngine._call_any

        def spy(self, op, **args):
            calls.append((op, dict(args)))
            return orig(self, op, **args)

        remote._call_any = spy.__get__(remote)
        want = sorted(e.lookup_resources("namespace", "view", "user",
                                         "alice"))
        got = await asyncio.to_thread(
            remote.lookup_resources, "namespace", "view", "user", "alice")
        assert sorted(got) == want and len(want) == 50
        assert [op for op, _ in calls] == ["lookup_mask", "object_ids"]
        assert calls[1][1]["from"] == 0
        # mask surface parity with the in-process engine
        mask, interner = await asyncio.to_thread(
            remote.lookup_resources_mask, "namespace", "view", "user",
            "alice")
        m2, it2 = e.lookup_resources_mask("namespace", "view", "user",
                                          "alice")
        assert np.array_equal(mask[:m2.size], m2)
        assert len(calls) == 3, "warm id table: no object_ids refetch"
        # new ids intern past the cached table: only the tail transfers
        e.write_relationships([WriteOp("touch", parse_relationship(
            "namespace:brand-new#creator@user:alice"))])
        before = len(interner)
        got = await asyncio.to_thread(
            remote.lookup_resources, "namespace", "view", "user", "alice")
        assert "brand-new" in got and len(got) == 51
        sync = [a for op, a in calls if op == "object_ids"]
        assert sync[-1]["from"] == before, "must sync only the delta"
        # snapshot restore server-side: same ids, NEW interner epoch
        import tempfile

        with tempfile.NamedTemporaryFile(suffix=".npz") as f:
            e.save_snapshot(f.name)
            e.load_snapshot(f.name)
        got = await asyncio.to_thread(
            remote.lookup_resources, "namespace", "view", "user", "alice")
        assert sorted(got) == sorted(want + ["brand-new"])
        assert [op for op, _ in calls[-2:]] == ["lookup_mask",
                                                "object_ids"]
        assert calls[-1][1]["from"] == 0, "new epoch resyncs from scratch"
        # unknown type -> (None, None) / []
        assert await asyncio.to_thread(
            remote.lookup_resources, "ghost", "view", "user", "alice") == []
    run_with_server(e, fn)


def test_remote_mask_wire_frame_size():
    """At 100k objects the allowed-set frame is ~12.5KB packed bits, not
    a multi-MB JSON id list (VERDICT r3 weak #4)."""
    from spicedb_kubeapi_proxy_tpu.engine.remote import (
        BinaryResult,
        _pack_binary,
    )
    import numpy as np

    mask = np.ones(100_000, dtype=bool)
    frame = _pack_binary(BinaryResult(
        {"found": True, "n": 100_000, "gen": 100_000, "epoch": "e" * 32},
        np.packbits(mask).tobytes()))
    assert len(frame) < 13_000
    json_list = json.dumps([f"pod-{i:06d}" for i in range(100_000)]).encode()
    assert len(json_list) > 1_000_000  # what the old wire would have sent


def test_remote_watch_gate():
    """The watch recompute gate round-trips from the engine host: type
    set and the expiration flag both carried, so remote watchers skip
    unrelated recomputes and only expiry-tick when the WATCHED permission
    can actually expire (the DEFAULT_BOOTSTRAP's expiration lives on the
    workflow idempotency-key relation, which namespace#view cannot reach
    — schema-wide `use expiration` must not make it tick)."""
    e = Engine()  # DEFAULT_BOOTSTRAP: uses expiration (idempotency keys)

    async def fn(remote):
        types, use_exp = await asyncio.to_thread(
            remote.watch_gate, "namespace", "view")
        assert types == frozenset({"namespace"})
        assert use_exp is False
        types, _ = await asyncio.to_thread(remote.watch_gate, "pod", "view")
        assert types == frozenset({"pod"})
        # the idempotency-key relation itself IS expiring
        _, use_exp = await asyncio.to_thread(
            remote.watch_gate, "workflow", "idempotency_key")
        assert use_exp is True
    run_with_server(e, fn)


def test_remote_error_kinds_round_trip():
    e = Engine()

    async def fn(remote):
        # precondition failures keep their type (dual-write lock path
        # branches on it)
        with pytest.raises(PreconditionFailed):
            await asyncio.to_thread(
                remote.write_relationships,
                [WriteOp("touch", parse_relationship(
                    "namespace:x#creator@user:y"))],
                [Precondition(RelationshipFilter(resource_type="namespace",
                                                 resource_id="x"),
                              must_exist=True)])
        with pytest.raises(SchemaViolation):
            await asyncio.to_thread(
                remote.write_relationships,
                [WriteOp("touch", parse_relationship("nope:x#y@user:z"))])
    run_with_server(e, fn)


def test_remote_token_auth():
    e = Engine()

    async def fn_ok(remote):
        return await asyncio.to_thread(remote.check_bulk, [
            CheckItem("namespace", "x", "view", "user", "y")])
    assert run_with_server(e, fn_ok, token="sekrit") == [False]

    async def fn_bad(remote):
        remote.token = "wrong"
        with pytest.raises(RemoteEngineError, match="invalid token"):
            await asyncio.to_thread(remote.check_bulk, [
                CheckItem("namespace", "x", "view", "user", "y")])
    run_with_server(e, fn_bad, token="sekrit")


def test_preauth_frame_cap():
    """An unauthenticated connection may not make the server buffer a huge
    frame: pre-auth frames are capped at MAX_FRAME_PREAUTH and the
    connection is dropped without reading the body. After auth, the same
    size is accepted (and rejected only past the big MAX_FRAME)."""
    import struct

    from spicedb_kubeapi_proxy_tpu.engine import remote as remote_mod

    e = Engine()

    async def fn(remote):
        # handshake once so we know the port; then talk raw
        await asyncio.to_thread(remote.check_bulk, [
            CheckItem("namespace", "x", "view", "user", "y")])
        big = remote_mod.MAX_FRAME_PREAUTH + 1

        # unauthenticated socket announcing an oversized frame: server
        # must drop the connection instead of buffering the body
        reader, writer = await asyncio.open_connection(remote.host,
                                                       remote.port)
        writer.write(struct.pack(">I", big))
        await writer.drain()
        got = await asyncio.wait_for(reader.read(4), timeout=5)
        assert got == b""  # closed without a response frame
        writer.close()

        # authenticated connection: the same size sails through (a padded
        # but valid request well over the pre-auth cap)
        pad = "p" * big
        resp = await asyncio.to_thread(
            remote._call, "revision", _pad=pad)
        assert isinstance(resp, int)

        # a FRESH client whose very first request is oversized must also
        # succeed (the client pings to authenticate before the big frame)
        fresh = RemoteEngine(remote.host, remote.port, token="sekrit")
        try:
            resp = await asyncio.to_thread(fresh._call, "revision", _pad=pad)
            assert isinstance(resp, int)
        finally:
            fresh.close()
    run_with_server(e, fn, token="sekrit")


def _repo_rules() -> str:
    import os
    return open(os.path.join(os.path.dirname(__file__), "..", "deploy",
                             "rules.yaml")).read()


@pytest.mark.parametrize("mesh_spec", [None, "data=2,graph=4"])
def test_proxy_against_remote_engine(tmp_path, mesh_spec):
    """Full proxy (rules, dual-write, list filtering) on a tcp:// engine —
    single-device and with the engine host owning a device mesh (the
    remote CLI's --engine-mesh deployment shape)."""
    RULES = _repo_rules()

    async def go():
        mesh = None
        if mesh_spec:
            from spicedb_kubeapi_proxy_tpu.parallel import make_mesh
            from spicedb_kubeapi_proxy_tpu.parallel.mesh import (
                parse_mesh_spec,
            )

            mesh = make_mesh(**parse_mesh_spec(mesh_spec))
        engine = Engine(mesh=mesh)
        server = EngineServer(engine)
        port = await server.start()
        fake = FakeKube()
        cfg = Options(
            engine_endpoint=f"tcp://127.0.0.1:{port}",
            engine_insecure=True,  # plaintext test server on loopback
            rule_content=RULES,
            upstream=fake,
            workflow_database_path=str(tmp_path / "dtx.sqlite"),
        ).complete()
        await cfg.workflow.resume_pending()
        alice = InMemoryClient(cfg.server.handle, user="alice")
        bob = InMemoryClient(cfg.server.handle, user="bob")
        resp = await alice.post("/api/v1/namespaces", {
            "apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": "remote-ns"}})
        assert resp.status == 201, resp.body
        # the write landed in the REMOTE engine
        assert engine.check(
            CheckItem("namespace", "remote-ns", "view", "user", "alice"))
        resp = await alice.get("/api/v1/namespaces")
        assert [o["metadata"]["name"]
                for o in json.loads(resp.body)["items"]] == ["remote-ns"]
        resp = await bob.get("/api/v1/namespaces")
        assert json.loads(resp.body)["items"] == []
        await cfg.workflow.shutdown()
        cfg.engine.close()
        await server.stop()
    asyncio.run(go())


def test_remote_endpoint_option_validation():
    with pytest.raises(OptionsError, match="bootstrap applies"):
        Options(engine_endpoint="tcp://h:1", rule_content="x",
                upstream_url="http://x",
                bootstrap_content="schema: ''").validate()
    # malformed host:port is a pure configuration error -> validate()
    with pytest.raises(OptionsError, match="invalid engine endpoint"):
        Options(engine_endpoint="tcp://nohost", rule_content="x",
                upstream=object()).validate()



def _watch_fixture():
    """(prefilter, ResolveInput) for a namespaces watch as alice — shared
    by the push-stream and pump-restart tests."""
    from spicedb_kubeapi_proxy_tpu.rules.matcher import (
        MapMatcher,
        RequestMeta,
    )
    from spicedb_kubeapi_proxy_tpu.rules.input import (
        RequestInfo,
        ResolveInput,
        UserInfo,
    )

    rules = MapMatcher.from_yaml("""
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
lock: Pessimistic
match:
- apiVersion: v1
  resource: namespaces
  verbs: ["watch"]
prefilter:
- fromObjectIDNameExpr: "{{resourceId}}"
  lookupMatchingResources:
    tpl: "namespace:$#view@user:{{user.name}}"
""")
    rule = rules.match(RequestMeta(verb="watch", api_group="",
                                   api_version="v1",
                                   resource="namespaces"))[0]
    input = ResolveInput.create(
        RequestInfo(verb="watch", api_version="v1", resource="namespaces",
                    path="/api/v1/namespaces"),
        UserInfo(name="alice"))
    return rule.pre_filters[0], input


def test_remote_watch_push_zero_steady_state_polls():
    """VERDICT r3 directive 4: a watcher on a tcp:// engine rides ONE
    server-push subscription — zero per-interval request traffic — and
    grant/revoke latency is bounded by the push, not a poll interval
    (reference long-lived watch stream, pkg/authz/watch.go:29)."""
    import time

    from spicedb_kubeapi_proxy_tpu.authz.watchhub import WatchHub

    e = Engine()
    e.write_relationships([WriteOp("touch", parse_relationship(
        "namespace:seen#creator@user:alice"))])
    pf, input = _watch_fixture()

    async def fn(remote):
        calls = []
        orig = RemoteEngine._call_any

        def spy(self, op, **args):
            calls.append(op)
            return orig(self, op, **args)

        remote._call_any = spy.__get__(remote)
        # warm the lookup kernels so the latency assertion below times
        # the push, not a first-query XLA compile
        await asyncio.to_thread(
            remote.lookup_resources, "namespace", "view", "user", "alice")
        hub = WatchHub(remote)
        handle = await hub.register(pf, input)
        # settle, then measure steady-state traffic
        await asyncio.sleep(1.0)
        before = list(calls)
        await asyncio.sleep(1.5)
        steady = calls[len(before):]
        assert steady == [], \
            f"steady-state watcher issued requests: {steady}"
        # a grant lands server-side: push (no poll) delivers it
        t0 = time.perf_counter()
        await asyncio.to_thread(
            e.write_relationships,
            [WriteOp("touch", parse_relationship(
                "namespace:pushed#viewer@user:alice"))])
        while True:
            kind, *rest = await asyncio.wait_for(handle.queue.get(),
                                                 timeout=10)
            if kind == "allowed" and ("", "pushed") in rest[0].pairs:
                break
        latency = time.perf_counter() - t0
        # push latency: write + one one-way frame + one device query —
        # far under any 50ms poll tick even on a loaded CI box
        assert latency < 2.0
        # the recompute itself rides the binary mask wire, not polling
        assert "watch_since" not in calls
        await hub.unregister(handle)
    run_with_server(e, fn)


def test_pump_cancel_during_push_connect_closes_stream():
    """A hub torn down while watch_push_stream is still connecting must
    close the stream the worker thread eventually produces — a cancel
    mid-connect previously leaked the dedicated socket until GC
    (advisor finding, watchhub._source_reader)."""
    import threading

    from spicedb_kubeapi_proxy_tpu.authz.watchhub import WatchHub

    pf, input = _watch_fixture()
    connect_entered = threading.Event()
    release_connect = threading.Event()

    class SlowStream:
        def __init__(self):
            self.closed = threading.Event()

        def next_batch(self):
            return []

        def close(self):
            self.closed.set()

    stream = SlowStream()

    class FakeEngine:
        revision = 0

        def watch_push_stream(self, since):
            connect_entered.set()
            assert release_connect.wait(30)
            return stream

    async def go():
        hub = WatchHub(FakeEngine())
        h = await hub.register(pf, input)
        # wait until the source reader's worker thread is inside connect
        deadline = asyncio.get_running_loop().time() + 5
        while not connect_entered.is_set():
            assert asyncio.get_running_loop().time() < deadline
            await asyncio.sleep(0.01)
        # teardown races the connect: the reader task is cancelled while
        # the thread still hasn't produced the stream
        await hub.unregister(h)
        release_connect.set()
        # the late-arriving stream must get closed by SOMEONE
        deadline = asyncio.get_running_loop().time() + 5
        while not stream.closed.is_set():
            assert asyncio.get_running_loop().time() < deadline, \
                "stream leaked after cancel-during-connect"
            await asyncio.sleep(0.01)

    asyncio.run(go())


def test_remote_watch_pump_restarts_after_host_restart():
    """An engine-host restart kills the push stream: current watchers get
    an error (their streams end; clients re-watch), and the hub must
    start a FRESH pump for watchers that arrive afterwards — a dead pump
    must never permanently freeze future watchers' allowed sets."""
    from spicedb_kubeapi_proxy_tpu.authz.watchhub import WatchHub

    pf, input = _watch_fixture()

    async def go():
        e = Engine()
        e.write_relationships([WriteOp("touch", parse_relationship(
            "namespace:seen#creator@user:alice"))])
        srv = EngineServer(e, port=0)
        port = await srv.start()
        remote = RemoteEngine("127.0.0.1", port)
        hub = WatchHub(remote)
        try:
            h1 = await hub.register(pf, input)
            # wait (bounded) for the push stream, then kill the host
            deadline = asyncio.get_running_loop().time() + 5
            while hub._push_stream is None:
                assert asyncio.get_running_loop().time() < deadline, \
                    "push stream never established"
                await asyncio.sleep(0.02)
        finally:
            await srv.stop()
        kind, *rest = await asyncio.wait_for(h1.queue.get(), timeout=10)
        assert kind == "error"
        await hub.unregister(h1)
        # host comes back on the SAME port (a restart, not a new host)
        srv2 = EngineServer(e, port=port)
        await srv2.start()
        try:
            # a client re-watches: the hub must build a fresh pump and
            # deliver recomputes again (the dead pump's teardown has a 1s
            # backoff; registration alone must also work after it)
            h2 = await hub.register(pf, input)
            await hub.refresh(h2)
            await asyncio.to_thread(
                e.write_relationships,
                [WriteOp("touch", parse_relationship(
                    "namespace:fresh#viewer@user:alice"))])
            deadline = asyncio.get_running_loop().time() + 10
            got = None
            while asyncio.get_running_loop().time() < deadline:
                kind, *rest = await asyncio.wait_for(h2.queue.get(),
                                                     timeout=10)
                if kind == "allowed" and ("", "fresh") in rest[0].pairs:
                    got = rest[0]
                    break
                assert kind != "error", "fresh pump must be healthy"
            assert got is not None, "recomputes must flow after restart"
            await hub.unregister(h2)
        finally:
            remote.close()
            await srv2.stop()

    asyncio.run(go())


def test_remote_lookups_fuse_across_connections():
    """An engine host with lookup batching on (--lookup-batch-window):
    concurrent lookup_mask requests from SEPARATE proxy connections fuse
    into shared device dispatches, and per-subject results stay
    correct."""
    from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

    e = Engine()
    users = [f"u{i}" for i in range(6)]
    rels = [f"namespace:ns{i}#creator@user:{u}"
            for i, u in enumerate(users)]
    e.write_relationships(
        [WriteOp("touch", parse_relationship(r)) for r in rels])
    e.lookup_resources_mask("namespace", "view", "user", users[0])  # warm
    e.enable_lookup_batching(window=0.02)

    async def go():
        server = EngineServer(e)
        port = await server.start()
        remotes = [RemoteEngine("127.0.0.1", port) for _ in users]
        try:
            b0 = metrics.counter("engine_lookup_batches_total").value
            l0 = metrics.counter("engine_lookups_total").value

            def one(remote, u):
                ids = remote.lookup_resources(
                    "namespace", "view", "user", u)
                return set(ids)

            for _ in range(5):  # burst can straggle under load: retry
                results = await asyncio.gather(*(
                    asyncio.to_thread(one, r, u)
                    for r, u in zip(remotes, users)))
                fused = metrics.counter(
                    "engine_lookup_batches_total").value - b0
                issued = metrics.counter(
                    "engine_lookups_total").value - l0
                if 0 < fused < issued:
                    break
                b0, l0 = (metrics.counter(
                    "engine_lookup_batches_total").value,
                    metrics.counter("engine_lookups_total").value)
            else:
                raise AssertionError("no cross-connection fusion observed")
            for i, (u, got) in enumerate(zip(users, results)):
                assert got == {f"ns{i}"}, (u, got)
        finally:
            for r in remotes:
                r.close()
            await server.stop()
    asyncio.run(go())
