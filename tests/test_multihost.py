"""Multi-host (multi-process) execution of the sharded engine: the full
query path — bulk load, dense blocks, cross-process collective joins,
incremental writes — over TWO OS processes whose collectives ride Gloo
(the CPU stand-in for DCN). Mirrors SURVEY §2.5's requirement that the
distributed backend scale to multi-host like the reference's gRPC tier.

The worker script lives in this file (__MULTIHOST_WORKER__ guard) and is
re-invoked per process, because jax.distributed can only be initialized
once per process and must happen before the backend comes up.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

import pytest

WORKER = r"""
import os, sys
proc, n, port, repo = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, repo)
import jax
jax.config.update("jax_platforms", "cpu")
from spicedb_kubeapi_proxy_tpu.parallel.multihost import init_distributed
init_distributed(f"127.0.0.1:{port},{n},{proc}")
import numpy as np
from spicedb_kubeapi_proxy_tpu.engine import CheckItem, Engine, WriteOp
from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship
from spicedb_kubeapi_proxy_tpu.parallel import make_mesh

devs = jax.devices()
assert len(devs) == 2 * n, (len(devs), n)
mesh = make_mesh(len(devs), devices=devs)
# identical store on every process (the SPMD contract; serving mirrors
# writes the same way)
rng = np.random.default_rng(7)
rels = [f"namespace:n{i}#creator@user:u{int(rng.integers(50))}"
        for i in range(300)]
rels += [f"pod:n{i%30}/p{i}#namespace@namespace:n{i%30}"
         for i in range(200)]
em = Engine(mesh=mesh)
em.write_relationships([WriteOp("touch", parse_relationship(r))
                        for r in rels])
e1 = Engine()
e1.write_relationships([WriteOp("touch", parse_relationship(r))
                        for r in rels])
items = [CheckItem("namespace", f"n{int(i)}", "view", "user", f"u{int(u)}")
         for i, u in zip(rng.integers(300, size=32),
                         rng.integers(50, size=32))]
assert em.check_bulk(items) == e1.check_bulk(items)
lk = em.lookup_resources("namespace", "view", "user", "u3")
assert sorted(lk) == sorted(
    e1.lookup_resources("namespace", "view", "user", "u3"))
# incremental write over the multi-host mesh, re-queried
for eng in (em, e1):
    eng.write_relationships([WriteOp("touch", parse_relationship(
        "namespace:n1#viewer@user:u49"))])
assert em.check_bulk(
    [CheckItem("namespace", "n1", "view", "user", "u49")]) == [True]
print(f"proc {proc}: MULTIHOST PARITY OK mesh={dict(mesh.shape)}",
      flush=True)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_sharded_engine_parity(tmp_path):
    """2 processes x 2 virtual devices: one global ('data','graph') mesh,
    cross-process collectives over Gloo, engine parity vs single-device
    incl. an incremental write."""
    script = tmp_path / "mh_worker.py"
    script.write_text(WORKER)
    port = _free_port()
    repo_root = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    # the workers pin their own platform/device config; scrub any
    # conftest leakage that would fight it
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), "2", str(port), repo_root],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=repo_root)
        for i in range(2)
    ]
    # one SHARED deadline for both workers (sequential communicate()
    # timeouts would stack), and always drain stdout after a kill so a
    # flake leaves diagnostics instead of zombies + empty output
    import time as _time

    deadline = _time.monotonic() + 240
    timed_out = False
    for p in procs:
        try:
            p.wait(timeout=max(0.1, deadline - _time.monotonic()))
        except subprocess.TimeoutExpired:
            timed_out = True
            p.kill()
    outs = [p.communicate()[0] for p in procs]
    if timed_out:
        pytest.fail("multihost workers timed out; outputs:\n"
                    + "\n---\n".join(o[-2000:] for o in outs))
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-2000:]}"
        assert "MULTIHOST PARITY OK" in out, out[-2000:]


SERVE_WORKER = r"""
import os, sys
role, port_coord, port_tcp, repo = (sys.argv[1], sys.argv[2], sys.argv[3],
                                    sys.argv[4])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, repo)
import jax
jax.config.update("jax_platforms", "cpu")
from spicedb_kubeapi_proxy_tpu.engine.remote import main

pid = "0" if role == "leader" else "1"
argv = ["--distributed", f"127.0.0.1:{port_coord},2,{pid}",
        "--engine-mesh", "auto", "--token", "mh-tok",
        "--engine-insecure"]  # loopback-only test fixture
if role == "leader":
    argv += ["--bind-port", port_tcp]
    print("LEADER STARTING", flush=True)
else:
    argv += ["--mirror-leader", f"127.0.0.1:{port_tcp}",
             "--bind-port", "0"]
    print("FOLLOWER STARTING", flush=True)
sys.exit(main(argv))
"""


def test_multihost_serving_leader_follower():
    """Full multi-host SERVING: the engine-host CLI as leader (process 0,
    serving TCP, MirroredEngine) + follower (process 1, replaying the
    mirror stream); a real client drives writes, bulk checks, and mask
    lookups whose collectives span both processes."""
    import time

    from spicedb_kubeapi_proxy_tpu.engine import CheckItem, Engine, WriteOp
    from spicedb_kubeapi_proxy_tpu.engine.remote import RemoteEngine
    from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo_root, ".pytest-mh-serve-worker.py")
    with open(script, "w") as f:
        f.write(SERVE_WORKER)
    port_coord, port_tcp = _free_port(), _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    procs = []
    client = None
    try:
        for role in ("leader", "follower"):
            procs.append(subprocess.Popen(
                [sys.executable, script, role, str(port_coord),
                 str(port_tcp), repo_root],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env, cwd=repo_root))
        # wait for the leader's TCP port to accept
        deadline = time.monotonic() + 120
        while True:
            try:
                probe = socket.create_connection(
                    ("127.0.0.1", port_tcp), timeout=1)
                probe.close()
                break
            except OSError:
                for p in procs:
                    assert p.poll() is None, \
                        p.communicate()[0][-2000:]
                assert time.monotonic() < deadline, "leader never bound"
                time.sleep(0.25)
        client = RemoteEngine("127.0.0.1", port_tcp, token="mh-tok")
        rels = [f"namespace:n{i}#creator@user:u{i % 7}" for i in range(40)]
        client.write_relationships(
            [WriteOp("touch", parse_relationship(r)) for r in rels])
        # reference truth from a local single-device engine
        ref = Engine()
        ref.write_relationships(
            [WriteOp("touch", parse_relationship(r)) for r in rels])
        items = [CheckItem("namespace", f"n{i}", "view", "user",
                           f"u{i % 5}") for i in range(25)]
        assert client.check_bulk(items) == ref.check_bulk(items)
        assert sorted(client.lookup_resources(
            "namespace", "view", "user", "u3")) == \
            sorted(ref.lookup_resources("namespace", "view", "user", "u3"))
        # a second write + re-query: the incremental path in lockstep
        for eng in (client, ref):
            eng.write_relationships([WriteOp("touch", parse_relationship(
                "namespace:n1#viewer@user:u6"))])
        assert client.check_bulk(
            [CheckItem("namespace", "n1", "view", "user", "u6")]) == [True]
        # a DETERMINISTICALLY-FAILING write (bad precondition) must fail
        # identically on leader and follower — the follower keeps
        # replaying rather than dying and hanging the next collective
        from spicedb_kubeapi_proxy_tpu.engine import RelationshipFilter
        from spicedb_kubeapi_proxy_tpu.engine.store import (
            Precondition,
            PreconditionFailed,
        )

        try:
            client.write_relationships(
                [WriteOp("touch", parse_relationship(
                    "namespace:nope#viewer@user:u0"))],
                [Precondition(RelationshipFilter(
                    resource_type="ghost-type"), must_exist=True)])
            raise AssertionError("precondition should have failed")
        except PreconditionFailed:
            pass
        # the set is still alive and consistent after the failure
        assert client.check_bulk(
            [CheckItem("namespace", "n1", "view", "user", "u6")]) == [True]
    finally:
        if client is not None:
            client.close()
        for p in procs:
            p.terminate()
        deadline = time.monotonic() + 20
        outs = []
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
            outs.append(p.communicate()[0])
        os.unlink(script)
    for role, out in zip(("leader", "follower"), outs):
        assert "STARTING" in out, (role, out[-1500:])
        assert "Traceback" not in out, (role, out[-2500:])


def test_mirror_check_item_codec_round_trip():
    """The compact mirror codec must be injective for ANY client-supplied
    field content (review findings: separator-based encoding let crafted
    ids kill or desync followers) and keep '' distinct from None — the
    engine groups device dispatches by subject key, so a lossy codec
    desyncs SPMD dispatch shapes."""
    from spicedb_kubeapi_proxy_tpu.engine import CheckItem
    from spicedb_kubeapi_proxy_tpu.parallel.multihost import (
        MultiHostError,
        decode_check_items,
        encode_check_items,
        normalize_check_item,
    )

    items = [
        CheckItem("pod", "ns/p1", "view", "user", "alice", None),
        CheckItem("pod", "a\x1fb", "view", "user", "c\x1ed", None),
        CheckItem("group", "g\nx", "member", "group", "inner", "member"),
        CheckItem("ns", "", "view", "user", "u", ""),  # '' != None
        CheckItem("t", "名前", "view", "user", "ünïcode", None),
    ]
    got = decode_check_items(encode_check_items(items))
    assert got == items
    # '' and None subject relations survive distinctly
    assert got[3].subject_relation == "" and got[0].subject_relation is None
    # non-str fields (legal JSON from a token-holding client) normalize to
    # the SAME value the leader executes
    n = normalize_check_item(CheckItem("pod", 123, "view", "user", 7, None))
    assert n.resource_id == "123" and n.subject_id == "7"
    assert decode_check_items(encode_check_items([n])) == [n]
    # malformed payloads fail loudly, not with a silent partial batch
    blob = encode_check_items(items)
    import pytest as _pytest

    with _pytest.raises(MultiHostError):
        decode_check_items(blob[:-3])


def test_multihost_follower_death_blocks_leader_restart_heals():
    """The documented failure model (parallel/multihost.py): SPMD is
    all-or-nothing — with a dead follower the leader's next device
    collective fails or blocks depending on the transport (Gloo errors
    fast; DCN may stall), but NEVER answers, and the leader process
    survives; restarting the process set as a unit heals serving on the
    same endpoint."""
    import time

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo_root, ".pytest-mh-death-worker.py")
    with open(script, "w") as f:
        f.write(SERVE_WORKER)
    port_tcp = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)

    def boot_pair(port_coord):
        procs = []
        for role in ("leader", "follower"):
            procs.append(subprocess.Popen(
                [sys.executable, script, role, str(port_coord),
                 str(port_tcp), repo_root],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env, cwd=repo_root))
        try:
            deadline = time.monotonic() + 120
            while True:
                try:
                    probe = socket.create_connection(
                        ("127.0.0.1", port_tcp), timeout=1)
                    probe.close()
                    return procs
                except OSError:
                    for p in procs:
                        assert p.poll() is None, p.communicate()[0][-2000:]
                    assert time.monotonic() < deadline, "leader never bound"
                    time.sleep(0.25)
        except BaseException:
            # boot failed: reap HERE — a surviving leader would hold
            # port_tcp and poison the restart phase
            reap(procs)
            raise

    def reap(procs):
        for p in procs:
            p.terminate()
        deadline = time.monotonic() + 20
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
            p.communicate()

    try:
        _death_and_restart_phases(boot_pair, reap, port_tcp)
    finally:
        if os.path.exists(script):
            os.unlink(script)


def _death_and_restart_phases(boot_pair, reap, port_tcp):
    import threading

    from spicedb_kubeapi_proxy_tpu.engine import CheckItem, WriteOp
    from spicedb_kubeapi_proxy_tpu.engine.remote import RemoteEngine
    from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship

    procs = boot_pair(_free_port())
    client = None
    try:
        client = RemoteEngine("127.0.0.1", port_tcp, token="mh-tok",
                              timeout=30.0)
        client.write_relationships([WriteOp("touch", parse_relationship(
            "namespace:alive#creator@user:u1"))])
        item = CheckItem("namespace", "alive", "view", "user", "u1")
        assert client.check_bulk([item]) == [True]

        # kill the follower: the leader's NEXT collective must fail or
        # block — never ANSWER — and the leader process must survive
        procs[1].kill()
        procs[1].wait(timeout=10)
        result: dict = {}

        def doomed_check():
            c2 = RemoteEngine("127.0.0.1", port_tcp, token="mh-tok",
                              timeout=60.0)
            try:
                result["got"] = c2.check_bulk([item])
            except Exception as e:  # noqa: BLE001
                result["err"] = e
            finally:
                c2.close()

        t = threading.Thread(target=doomed_check, daemon=True)
        t.start()
        t.join(20.0)
        if t.is_alive():
            pass  # blocked: the DCN-like stall mode
        else:
            # errored: the Gloo fast-fail mode — still no answer
            assert "got" not in result, \
                f"leader ANSWERED with a dead follower: {result}"
            assert "err" in result
        assert procs[0].poll() is None, "leader process died"
    finally:
        if client is not None:
            client.close()
        reap(procs)

    # orchestrator restart: a FRESH process set on the same serving port
    procs = boot_pair(_free_port())
    client = None
    try:
        client = RemoteEngine("127.0.0.1", port_tcp, token="mh-tok",
                              timeout=60.0)
        client.write_relationships([WriteOp("touch", parse_relationship(
            "namespace:healed#creator@user:u2"))])
        assert client.check_bulk([CheckItem(
            "namespace", "healed", "view", "user", "u2")]) == [True]
    finally:
        if client is not None:
            client.close()
        reap(procs)
