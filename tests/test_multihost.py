"""Multi-host (multi-process) execution of the sharded engine: the full
query path — bulk load, dense blocks, cross-process collective joins,
incremental writes — over TWO OS processes whose collectives ride Gloo
(the CPU stand-in for DCN). Mirrors SURVEY §2.5's requirement that the
distributed backend scale to multi-host like the reference's gRPC tier.

The worker script lives in this file (__MULTIHOST_WORKER__ guard) and is
re-invoked per process, because jax.distributed can only be initialized
once per process and must happen before the backend comes up.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

import pytest

WORKER = r"""
import os, sys
proc, n, port, repo = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, repo)
import jax
jax.config.update("jax_platforms", "cpu")
from spicedb_kubeapi_proxy_tpu.parallel.multihost import init_distributed
init_distributed(f"127.0.0.1:{port},{n},{proc}")
import numpy as np
from spicedb_kubeapi_proxy_tpu.engine import CheckItem, Engine, WriteOp
from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship
from spicedb_kubeapi_proxy_tpu.parallel import make_mesh

devs = jax.devices()
assert len(devs) == 2 * n, (len(devs), n)
mesh = make_mesh(len(devs), devices=devs)
# identical store on every process (the SPMD contract; serving mirrors
# writes the same way)
rng = np.random.default_rng(7)
rels = [f"namespace:n{i}#creator@user:u{int(rng.integers(50))}"
        for i in range(300)]
rels += [f"pod:n{i%30}/p{i}#namespace@namespace:n{i%30}"
         for i in range(200)]
em = Engine(mesh=mesh)
em.write_relationships([WriteOp("touch", parse_relationship(r))
                        for r in rels])
e1 = Engine()
e1.write_relationships([WriteOp("touch", parse_relationship(r))
                        for r in rels])
items = [CheckItem("namespace", f"n{int(i)}", "view", "user", f"u{int(u)}")
         for i, u in zip(rng.integers(300, size=32),
                         rng.integers(50, size=32))]
assert em.check_bulk(items) == e1.check_bulk(items)
lk = em.lookup_resources("namespace", "view", "user", "u3")
assert sorted(lk) == sorted(
    e1.lookup_resources("namespace", "view", "user", "u3"))
# incremental write over the multi-host mesh, re-queried
for eng in (em, e1):
    eng.write_relationships([WriteOp("touch", parse_relationship(
        "namespace:n1#viewer@user:u49"))])
assert em.check_bulk(
    [CheckItem("namespace", "n1", "view", "user", "u49")]) == [True]
print(f"proc {proc}: MULTIHOST PARITY OK mesh={dict(mesh.shape)}",
      flush=True)
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_sharded_engine_parity(tmp_path):
    """2 processes x 2 virtual devices: one global ('data','graph') mesh,
    cross-process collectives over Gloo, engine parity vs single-device
    incl. an incremental write."""
    script = tmp_path / "mh_worker.py"
    script.write_text(WORKER)
    port = _free_port()
    repo_root = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    # the workers pin their own platform/device config; scrub any
    # conftest leakage that would fight it
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), "2", str(port), repo_root],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=repo_root)
        for i in range(2)
    ]
    # one SHARED deadline for both workers (sequential communicate()
    # timeouts would stack), and always drain stdout after a kill so a
    # flake leaves diagnostics instead of zombies + empty output
    import time as _time

    deadline = _time.monotonic() + 240
    timed_out = False
    for p in procs:
        try:
            p.wait(timeout=max(0.1, deadline - _time.monotonic()))
        except subprocess.TimeoutExpired:
            timed_out = True
            p.kill()
    outs = [p.communicate()[0] for p in procs]
    if timed_out:
        pytest.fail("multihost workers timed out; outputs:\n"
                    + "\n---\n".join(o[-2000:] for o in outs))
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-2000:]}"
        assert "MULTIHOST PARITY OK" in out, out[-2000:]


SERVE_WORKER = r"""
import os, sys
role, port_coord, port_tcp, repo = (sys.argv[1], sys.argv[2], sys.argv[3],
                                    sys.argv[4])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, repo)
import jax
jax.config.update("jax_platforms", "cpu")
from spicedb_kubeapi_proxy_tpu.engine.remote import main

pid = "0" if role == "leader" else "1"
argv = ["--distributed", f"127.0.0.1:{port_coord},2,{pid}",
        "--engine-mesh", "auto", "--token", "mh-tok",
        "--engine-insecure"]  # loopback-only test fixture
if role == "leader":
    argv += ["--bind-port", port_tcp]
    print("LEADER STARTING", flush=True)
else:
    argv += ["--mirror-leader", f"127.0.0.1:{port_tcp}",
             "--bind-port", "0"]
    print("FOLLOWER STARTING", flush=True)
sys.exit(main(argv))
"""


def test_multihost_serving_leader_follower():
    """Full multi-host SERVING: the engine-host CLI as leader (process 0,
    serving TCP, MirroredEngine) + follower (process 1, replaying the
    mirror stream); a real client drives writes, bulk checks, and mask
    lookups whose collectives span both processes."""
    import time

    from spicedb_kubeapi_proxy_tpu.engine import CheckItem, Engine, WriteOp
    from spicedb_kubeapi_proxy_tpu.engine.remote import RemoteEngine
    from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo_root, ".pytest-mh-serve-worker.py")
    with open(script, "w") as f:
        f.write(SERVE_WORKER)
    port_coord, port_tcp = _free_port(), _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    procs = []
    client = None
    try:
        for role in ("leader", "follower"):
            procs.append(subprocess.Popen(
                [sys.executable, script, role, str(port_coord),
                 str(port_tcp), repo_root],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env, cwd=repo_root))
        # wait for the leader's TCP port to accept
        deadline = time.monotonic() + 120
        while True:
            try:
                probe = socket.create_connection(
                    ("127.0.0.1", port_tcp), timeout=1)
                probe.close()
                break
            except OSError:
                for p in procs:
                    assert p.poll() is None, \
                        p.communicate()[0][-2000:]
                assert time.monotonic() < deadline, "leader never bound"
                time.sleep(0.25)
        client = RemoteEngine("127.0.0.1", port_tcp, token="mh-tok")
        rels = [f"namespace:n{i}#creator@user:u{i % 7}" for i in range(40)]
        client.write_relationships(
            [WriteOp("touch", parse_relationship(r)) for r in rels])
        # reference truth from a local single-device engine
        ref = Engine()
        ref.write_relationships(
            [WriteOp("touch", parse_relationship(r)) for r in rels])
        items = [CheckItem("namespace", f"n{i}", "view", "user",
                           f"u{i % 5}") for i in range(25)]
        assert client.check_bulk(items) == ref.check_bulk(items)
        assert sorted(client.lookup_resources(
            "namespace", "view", "user", "u3")) == \
            sorted(ref.lookup_resources("namespace", "view", "user", "u3"))
        # a second write + re-query: the incremental path in lockstep
        for eng in (client, ref):
            eng.write_relationships([WriteOp("touch", parse_relationship(
                "namespace:n1#viewer@user:u6"))])
        assert client.check_bulk(
            [CheckItem("namespace", "n1", "view", "user", "u6")]) == [True]
        # a DETERMINISTICALLY-FAILING write (bad precondition) must fail
        # identically on leader and follower — the follower keeps
        # replaying rather than dying and hanging the next collective
        from spicedb_kubeapi_proxy_tpu.engine import RelationshipFilter
        from spicedb_kubeapi_proxy_tpu.engine.store import (
            Precondition,
            PreconditionFailed,
        )

        try:
            client.write_relationships(
                [WriteOp("touch", parse_relationship(
                    "namespace:nope#viewer@user:u0"))],
                [Precondition(RelationshipFilter(
                    resource_type="ghost-type"), must_exist=True)])
            raise AssertionError("precondition should have failed")
        except PreconditionFailed:
            pass
        # the set is still alive and consistent after the failure
        assert client.check_bulk(
            [CheckItem("namespace", "n1", "view", "user", "u6")]) == [True]
    finally:
        if client is not None:
            client.close()
        for p in procs:
            p.terminate()
        deadline = time.monotonic() + 20
        outs = []
        for p in procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
            outs.append(p.communicate()[0])
        os.unlink(script)
    for role, out in zip(("leader", "follower"), outs):
        assert "STARTING" in out, (role, out[-1500:])
        assert "Traceback" not in out, (role, out[-2500:])
