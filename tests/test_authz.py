"""Authorization middleware integration tests.

Ports the shape of the reference e2e scenario suite
(reference e2e/proxy_test.go): every verb through the full middleware
against a fake kube upstream, using the reference's own deploy/rules.yaml
rule set and bootstrap schema — per-user isolation on get/list/watch,
dual-write visibility, table filtering, postchecks, CEL `if` rules.
"""

import asyncio
import json

import pytest

from spicedb_kubeapi_proxy_tpu.authz import AuthzDeps, authorize
from spicedb_kubeapi_proxy_tpu.dtx import ActivityHandler, WorkflowEngine, register_workflows
from spicedb_kubeapi_proxy_tpu.engine import CheckItem, Engine, RelationshipFilter
from spicedb_kubeapi_proxy_tpu.proxy.authn import HeaderAuthenticator
from spicedb_kubeapi_proxy_tpu.proxy.requestinfo import parse_request_info
from spicedb_kubeapi_proxy_tpu.proxy.types import ProxyRequest
from spicedb_kubeapi_proxy_tpu.rules import MapMatcher
from spicedb_kubeapi_proxy_tpu.rules.input import UserInfo

from fake_kube import FakeKube

RULES = open("/root/reference/deploy/rules.yaml").read()


class Env:
    def __init__(self, rules_yaml: str = RULES, bootstrap=None):
        # default: DEFAULT_BOOTSTRAP schema; custom bootstrap YAML gets the
        # dual-write infra definitions (lock/workflow/activity) appended by
        # parse_bootstrap
        self.engine = Engine(bootstrap=bootstrap)
        self.kube = FakeKube()
        self.workflow = WorkflowEngine()
        register_workflows(self.workflow)
        ActivityHandler(self.engine, self.kube).register(self.workflow)
        self.deps = AuthzDeps(
            matcher=MapMatcher.from_yaml(rules_yaml),
            engine=self.engine,
            upstream=self.kube,
            workflow=self.workflow,
            watch_poll_interval=0.01,
        )

    async def request(self, method: str, path: str, user: str = "alice",
                      body=None, query=None, groups=(), headers=None):
        query = query or {}
        info = parse_request_info(method, path, query)
        req = ProxyRequest(
            method=method, path=path, query=query,
            headers={"Content-Type": "application/json", **(headers or {})},
            body=json.dumps(body).encode() if body is not None else b"",
            user=UserInfo(name=user, groups=list(groups)),
            request_info=info,
        )
        return await authorize(req, self.deps)

    async def create_ns(self, name: str, user: str = "alice"):
        return await self.request(
            "POST", "/api/v1/namespaces", user=user,
            body={"apiVersion": "v1", "kind": "Namespace",
                  "metadata": {"name": name}})

    async def create_pod(self, ns: str, name: str, user: str = "alice"):
        return await self.request(
            "POST", f"/api/v1/namespaces/{ns}/pods", user=user,
            body={"apiVersion": "v1", "kind": "Pod",
                  "metadata": {"name": name, "namespace": ns}})


def run(coro):
    return asyncio.run(coro)


def test_discovery_always_allowed():
    async def go():
        env = Env()
        resp = await env.request("GET", "/api")
        assert resp.status == 200
    run(go())


def test_unmatched_request_forbidden():
    async def go():
        env = Env()
        resp = await env.request("GET", "/api/v1/configmaps")
        assert resp.status == 403
        assert b"Forbidden" in resp.body
    run(go())


def test_create_then_get_namespace_dual_write():
    async def go():
        env = Env()
        resp = await env.create_ns("team-a")
        assert resp.status == 201
        # relationships written
        assert env.engine.store.exists(RelationshipFilter(
            "namespace", "team-a", "creator", "user", "alice"))
        assert not env.engine.store.exists(RelationshipFilter(
            resource_type="lock"))
        # creator can get it
        r2 = await env.request("GET", "/api/v1/namespaces/team-a")
        assert r2.status == 200
        # another user cannot
        r3 = await env.request("GET", "/api/v1/namespaces/team-a", user="bob")
        assert r3.status == 403
    run(go())


def test_create_conflict_second_user():
    async def go():
        env = Env()
        assert (await env.create_ns("shared")).status == 201
        # second create: precondition (cluster rel exists) -> 409
        resp = await env.create_ns("shared", user="mallory")
        assert resp.status == 409
        assert not env.engine.store.exists(RelationshipFilter(
            "namespace", "shared", "creator", "user", "mallory"))
    run(go())


def test_list_namespaces_prefiltered_per_user():
    async def go():
        env = Env()
        await env.create_ns("alpha", user="alice")
        await env.create_ns("beta", user="bob")
        await env.create_ns("gamma", user="alice")
        resp = await env.request("GET", "/api/v1/namespaces", user="alice")
        assert resp.status == 200
        names = [o["metadata"]["name"] for o in json.loads(resp.body)["items"]]
        assert sorted(names) == ["alpha", "gamma"]
        resp = await env.request("GET", "/api/v1/namespaces", user="bob")
        names = [o["metadata"]["name"] for o in json.loads(resp.body)["items"]]
        assert names == ["beta"]
        resp = await env.request("GET", "/api/v1/namespaces", user="carol")
        assert json.loads(resp.body)["items"] == []
    run(go())


def test_list_pods_prefiltered_split_names():
    async def go():
        env = Env()
        await env.create_ns("ns1", user="alice")
        await env.create_pod("ns1", "p1", user="alice")
        await env.create_pod("ns1", "p2", user="alice")
        await env.create_ns("ns2", user="bob")
        await env.create_pod("ns2", "q1", user="bob")
        resp = await env.request("GET", "/api/v1/pods", user="alice")
        names = [o["metadata"]["name"] for o in json.loads(resp.body)["items"]]
        assert sorted(names) == ["p1", "p2"]
        # namespace-scoped list also filtered
        resp = await env.request("GET", "/api/v1/namespaces/ns2/pods",
                                 user="alice")
        assert json.loads(resp.body)["items"] == []
    run(go())


def test_get_single_pod_not_allowed():
    async def go():
        env = Env()
        await env.create_ns("ns1", user="alice")
        await env.create_pod("ns1", "p1", user="alice")
        assert (await env.request(
            "GET", "/api/v1/namespaces/ns1/pods/p1", user="alice")).status == 200
        assert (await env.request(
            "GET", "/api/v1/namespaces/ns1/pods/p1", user="bob")).status == 403
    run(go())


def test_delete_namespace_removes_relationships():
    async def go():
        env = Env()
        await env.create_ns("doomed", user="alice")
        resp = await env.request("DELETE", "/api/v1/namespaces/doomed",
                                 user="alice")
        assert resp.status == 200
        assert not env.engine.store.exists(RelationshipFilter(
            "namespace", "doomed", "creator"))
        # object gone upstream
        assert ("namespaces", "", "doomed") not in env.kube.objects
    run(go())


def test_table_response_filtering():
    async def go():
        env = Env()
        await env.create_ns("mine", user="alice")
        await env.create_ns("theirs", user="bob")
        # hand-craft a Table response upstream
        table = {
            "kind": "Table", "apiVersion": "meta.k8s.io/v1",
            "columnDefinitions": [{"name": "Name"}],
            "rows": [
                {"cells": ["mine"],
                 "object": {"kind": "PartialObjectMetadata",
                            "metadata": {"name": "mine"}}},
                {"cells": ["theirs"],
                 "object": {"kind": "PartialObjectMetadata",
                            "metadata": {"name": "theirs"}}},
            ],
        }
        import spicedb_kubeapi_proxy_tpu.proxy.types as T

        async def table_upstream(req):
            return T.json_response(200, table)

        env.deps.upstream = table_upstream
        resp = await env.request("GET", "/api/v1/namespaces", user="alice")
        doc = json.loads(resp.body)
        assert [r["cells"][0] for r in doc["rows"]] == ["mine"]
    run(go())


POSTFILTER_RULES = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata:
  name: list-pods-postfiltered
match:
- apiVersion: v1
  resource: pods
  verbs: ["list"]
postfilter:
- checkPermissionTemplate:
    tpl: "pod:{{namespacedName}}#view@user:{{user.name}}"
"""


def test_postfilter_bulk_checks():
    async def go():
        env = Env(rules_yaml=RULES + "\n---\n" + POSTFILTER_RULES)
        # seed engine + kube directly (no create rule interplay needed)
        from spicedb_kubeapi_proxy_tpu.engine import WriteOp
        from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship
        env.engine.write_relationships([
            WriteOp("touch", parse_relationship("pod:ns1/a#viewer@user:alice")),
        ])
        for name in ("a", "b"):
            env.kube.objects[("pods", "ns1", name)] = {
                "kind": "Pod",
                "metadata": {"name": name, "namespace": "ns1"}}
        resp = await env.request("GET", "/api/v1/namespaces/ns1/pods",
                                 user="alice")
        names = [o["metadata"]["name"] for o in json.loads(resp.body)["items"]]
        # prefilter (view) allows 'a'; postfilter also only passes 'a'
        assert names == ["a"]

        # postfilter paths must force a JSON upstream response even when
        # the client negotiates protobuf (the postfilter resolves rule
        # expressions over item JSON; proxy/upstream.py otherwise forwards
        # protobuf ranges now that the prefilter path can filter them)
        seen = {}
        orig = env.deps.upstream

        async def recording_upstream(req):
            seen["accept"] = next((v for k, v in req.headers.items()
                                   if k.lower() == "accept"), None)
            return await orig(req)

        env.deps.upstream = recording_upstream
        resp = await env.request(
            "GET", "/api/v1/namespaces/ns1/pods", user="alice",
            headers={"Accept":
                     "application/vnd.kubernetes.protobuf,application/json"})
        assert seen["accept"] == "application/json"
        assert [o["metadata"]["name"]
                for o in json.loads(resp.body)["items"]] == ["a"]
    run(go())


POSTCHECK_RULES = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata:
  name: get-pod-postcheck
match:
- apiVersion: v1
  resource: pods
  verbs: ["get"]
postcheck:
- tpl: "pod:{{namespacedName}}#edit@user:{{user.name}}"
"""


def test_postchecks_run_after_upstream():
    async def go():
        env = Env(rules_yaml=POSTCHECK_RULES)
        from spicedb_kubeapi_proxy_tpu.engine import WriteOp
        from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship
        env.engine.write_relationships([
            WriteOp("touch", parse_relationship("pod:ns1/a#creator@user:alice")),
        ])
        env.kube.objects[("pods", "ns1", "a")] = {
            "kind": "Pod", "metadata": {"name": "a", "namespace": "ns1"}}
        ok = await env.request("GET", "/api/v1/namespaces/ns1/pods/a",
                               user="alice")
        assert ok.status == 200
        denied = await env.request("GET", "/api/v1/namespaces/ns1/pods/a",
                                   user="bob")
        assert denied.status == 403
    run(go())


CEL_RULES = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata:
  name: masters-only
match:
- apiVersion: v1
  resource: secrets
  verbs: ["get"]
if:
- "'system:masters' in user.groups"
"""


def test_cel_if_conditions_gate_rules():
    async def go():
        env = Env(rules_yaml=CEL_RULES)
        env.kube.objects[("secrets", "ns1", "s")] = {
            "kind": "Secret", "metadata": {"name": "s", "namespace": "ns1"}}
        ok = await env.request("GET", "/api/v1/namespaces/ns1/secrets/s",
                               groups=["system:masters"])
        assert ok.status == 200
        denied = await env.request("GET", "/api/v1/namespaces/ns1/secrets/s",
                                   groups=["dev"])
        assert denied.status == 403
    run(go())


def test_watch_filtered_per_user():
    async def go():
        env = Env()
        await env.create_ns("w1", user="alice")
        resp = await env.request("GET", "/api/v1/namespaces", user="alice",
                                 query={"watch": ["true"]})
        assert resp.status == 200 and resp.stream is not None
        frames = []

        async def consume():
            async for f in resp.stream:
                frames.append(json.loads(f))
                if len(frames) >= 2:
                    return

        task = asyncio.ensure_future(consume())
        await asyncio.sleep(0.05)
        # alice's initial namespace should stream through (ADDED)
        # bob creates one -> must NOT reach alice; alice creates -> must
        await env.create_ns("w2", user="bob")
        await env.create_ns("w3", user="alice")
        await asyncio.wait_for(task, timeout=5)
        names = [f["object"]["metadata"]["name"] for f in frames]
        assert names == ["w1", "w3"]
        env.kube.stop_watches()
    run(go())


def test_watch_allows_object_after_grant():
    async def go():
        env = Env()
        await env.create_ns("gr", user="bob")
        resp = await env.request("GET", "/api/v1/namespaces", user="alice",
                                 query={"watch": ["true"]})
        frames = []

        async def consume():
            async for f in resp.stream:
                frames.append(json.loads(f))
                return

        task = asyncio.ensure_future(consume())
        await asyncio.sleep(0.05)
        assert not frames  # buffered: alice can't see bob's namespace yet
        # grant alice viewer -> the buffered ADDED frame must flush
        from spicedb_kubeapi_proxy_tpu.engine import WriteOp
        from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship
        env.engine.write_relationships([WriteOp("touch", parse_relationship(
            "namespace:gr#viewer@user:alice"))])
        await asyncio.wait_for(task, timeout=5)
        assert frames[0]["object"]["metadata"]["name"] == "gr"
        env.kube.stop_watches()
    run(go())


def test_watch_namespaced_resource_keys_frames_by_prefilter():
    """Pods watch: the prefilter carries a namespace expression
    (split_namespace over 'ns/name' object ids), so frames key on
    (metadata.namespace, metadata.name) — buffer for the wrong user,
    flush on grant, keyed exactly as the grant side maps object ids
    (authz/watch.py _frame_object_key)."""
    async def go():
        env = Env()
        await env.create_ns("wns", user="bob")
        await env.create_pod("wns", "api", user="bob")
        resp = await env.request("GET", "/api/v1/pods", user="alice",
                                 query={"watch": ["true"]})
        assert resp.status == 200 and resp.stream is not None
        frames = []

        async def consume():
            async for f in resp.stream:
                frames.append(json.loads(f))
                return

        task = asyncio.ensure_future(consume())
        await asyncio.sleep(0.05)
        assert not frames  # buffered: alice can't view bob's namespace
        # grant alice view on the pod directly (the default bootstrap has
        # no namespace arrow) -> the buffered ADDED frame for (wns, api)
        # must flush
        from spicedb_kubeapi_proxy_tpu.engine import WriteOp
        from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship
        env.engine.write_relationships([WriteOp("touch", parse_relationship(
            "pod:wns/api#viewer@user:alice"))])
        await asyncio.wait_for(task, timeout=5)
        meta = frames[0]["object"]["metadata"]
        assert (meta["namespace"], meta["name"]) == ("wns", "api")
        env.kube.stop_watches()
    run(go())


def test_watch_drops_frames_after_revocation_mid_stream():
    """Reference proxy_test.go:905-943: once a subject's permission on an
    object is revoked, subsequent watch events for that object are dropped
    from the stream (and other objects keep flowing)."""
    async def go():
        from spicedb_kubeapi_proxy_tpu.engine import WriteOp
        from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship

        env = Env()
        await env.create_ns("mine", user="alice")
        resp = await env.request("GET", "/api/v1/namespaces", user="alice",
                                 query={"watch": ["true"]})
        frames = []

        async def consume():
            async for f in resp.stream:
                frames.append(json.loads(f))

        task = asyncio.ensure_future(consume())
        # alice owns "mine": the ADDED frame flows through
        await asyncio.wait_for(_wait_for(lambda: len(frames) >= 1), timeout=5)
        assert frames[0]["object"]["metadata"]["name"] == "mine"
        # revoke alice's ownership, then emit a MODIFIED event upstream
        env.engine.write_relationships([WriteOp("delete", parse_relationship(
            "namespace:mine#creator@user:alice"))])
        await asyncio.sleep(0.05)  # let the revocation reach the tracker
        env.kube.emit_watch_event("namespaces", "MODIFIED", "mine")
        # and a fresh grant on another namespace must still flow
        await env.create_ns("other", user="bob")
        env.engine.write_relationships([WriteOp("touch", parse_relationship(
            "namespace:other#viewer@user:alice"))])
        await asyncio.wait_for(
            _wait_for(lambda: any(
                f["object"]["metadata"]["name"] == "other" for f in frames)),
            timeout=5)
        names = [f["object"]["metadata"]["name"] for f in frames]
        # the post-revocation MODIFIED frame for "mine" was dropped
        assert names.count("mine") == 1, names
        task.cancel()
        env.kube.stop_watches()
    run(go())


async def _wait_for(pred, interval=0.02):
    while not pred():
        await asyncio.sleep(interval)


def test_proto_watch_filtered_grant_and_revoke_mid_stream():
    """VERDICT r4 directive 5: a protobuf watch passes through the filter
    natively — frames are kube-proto WatchEvents (length-prefixed, byte-
    identical to what the upstream sent), buffered frames flush on grant,
    and post-revocation frames are dropped. No JSON downgrade."""
    async def go():
        from spicedb_kubeapi_proxy_tpu.engine import WriteOp
        from spicedb_kubeapi_proxy_tpu.models.tuples import (
            parse_relationship,
        )
        from spicedb_kubeapi_proxy_tpu.proxy import kubeproto

        env = Env()
        await env.create_ns("pw-mine", user="alice")
        await env.create_ns("pw-hidden", user="bob")
        resp = await env.request(
            "GET", "/api/v1/namespaces", user="alice",
            query={"watch": ["true"]},
            headers={"Accept": kubeproto.CONTENT_TYPE
                     + ",application/json"})
        assert resp.status == 200 and resp.stream is not None
        assert "protobuf" in resp.headers.get("Content-Type", "")
        frames: list = []

        async def consume():
            async for f in resp.stream:
                frames.append(f)

        task = asyncio.ensure_future(consume())
        # alice's own namespace streams through as a proto frame,
        # byte-identical to the upstream encoding (length prefix intact)
        await asyncio.wait_for(_wait_for(lambda: len(frames) >= 1),
                               timeout=5)
        assert int.from_bytes(frames[0][:4], "big") == len(frames[0]) - 4
        assert kubeproto.watch_frame_key(frames[0]) == ("", "pw-mine")
        expected = kubeproto.encode_watch_frame(
            "ADDED", kubeproto.encode_unknown(
                "v1", "Namespace",
                kubeproto.encode_object_meta_only("pw-mine")))
        assert frames[0] == expected  # byte-identical passthrough
        # bob's namespace stayed buffered; granting alice flushes it
        env.engine.write_relationships([WriteOp("touch", parse_relationship(
            "namespace:pw-hidden#viewer@user:alice"))])
        await asyncio.wait_for(
            _wait_for(lambda: any(
                kubeproto.watch_frame_key(f) == ("", "pw-hidden")
                for f in frames)), timeout=5)
        # revoke and emit: the post-revocation frame must be dropped
        env.engine.write_relationships([WriteOp("delete", parse_relationship(
            "namespace:pw-hidden#viewer@user:alice"))])
        await asyncio.sleep(0.05)
        env.kube.emit_watch_event("namespaces", "MODIFIED", "pw-hidden")
        env.kube.emit_watch_event("namespaces", "MODIFIED", "pw-mine")
        await asyncio.wait_for(
            _wait_for(lambda: sum(
                1 for f in frames
                if kubeproto.watch_frame_key(f) == ("", "pw-mine")) >= 2),
            timeout=5)
        keys = [kubeproto.watch_frame_key(f) for f in frames]
        assert keys.count(("", "pw-hidden")) == 1, keys
        task.cancel()
        env.kube.stop_watches()
    run(go())


def test_proto_watch_bookmarks_pass_through():
    """Proto BOOKMARK frames (progress markers, no object) pass through
    to every watcher byte-identically."""
    async def go():
        from spicedb_kubeapi_proxy_tpu.proxy import kubeproto

        env = Env()
        await env.create_ns("pb", user="alice")
        resp = await env.request(
            "GET", "/api/v1/namespaces", user="alice",
            query={"watch": ["true"],
                   "allowWatchBookmarks": ["true"]},
            headers={"Accept": kubeproto.CONTENT_TYPE})
        frames: list = []

        async def consume():
            async for f in resp.stream:
                frames.append(f)

        task = asyncio.ensure_future(consume())
        await asyncio.wait_for(_wait_for(lambda: len(frames) >= 2),
                               timeout=5)
        types = [kubeproto.decode_watch_event(f[4:])[0] for f in frames]
        assert "BOOKMARK" in types
        task.cancel()
        env.kube.stop_watches()
    run(go())


def test_watch_skips_recompute_for_unrelated_writes(monkeypatch):
    """Writes to types that cannot affect the watched permission must not
    cost a device query per watcher: the schema-derived relevant-type set
    gates the recompute. (The expiry tick is pinned long so only the gate
    is under test.)"""
    from spicedb_kubeapi_proxy_tpu.authz import watchhub as watchhub_mod

    monkeypatch.setattr(watchhub_mod, "EXPIRY_RECOMPUTE_INTERVAL", 600.0)

    async def go():
        from spicedb_kubeapi_proxy_tpu.engine import WriteOp
        from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship
        from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

        env = Env()
        await env.create_ns("sk", user="alice")
        resp = await env.request("GET", "/api/v1/namespaces", user="alice",
                                 query={"watch": ["true"]})
        frames = []

        async def consume():
            async for f in resp.stream:
                frames.append(json.loads(f))

        task = asyncio.ensure_future(consume())
        await asyncio.wait_for(_wait_for(lambda: len(frames) >= 1),
                               timeout=10)
        await asyncio.sleep(0.1)  # drain any startup polls
        lookups0 = metrics.counter("engine_lookups_total").value
        # lock/workflow writes (the dual-write machinery's own types)
        # cannot affect namespace#view: no recompute may fire
        for i in range(3):
            env.engine.write_relationships([WriteOp(
                "touch", parse_relationship(
                    f"lock:unrelated-{i}#workflow@workflow:w{i}"))])
            await asyncio.sleep(0.05)
        await asyncio.sleep(0.2)  # several poll ticks
        assert metrics.counter("engine_lookups_total").value == lookups0, \
            "unrelated writes triggered allowed-set recomputes"
        # a RELEVANT write still recomputes and flushes
        await env.create_ns("sk2", user="bob")
        env.engine.write_relationships([WriteOp("touch", parse_relationship(
            "namespace:sk2#viewer@user:alice"))])
        await asyncio.wait_for(_wait_for(lambda: any(
            f["object"]["metadata"]["name"] == "sk2" for f in frames)),
            timeout=10)
        assert metrics.counter("engine_lookups_total").value > lookups0
        task.cancel()
        env.kube.stop_watches()
    run(go())


def test_watch_enforces_expiring_grant_without_events(monkeypatch):
    """An expiring grant revokes at QUERY time with no watch event: the
    periodic recompute tick must drop post-expiry frames even when no
    other write ever lands (review finding: the type gate must not starve
    expiry enforcement — which previously depended on unrelated write
    traffic arriving at all)."""
    import time as _time

    from spicedb_kubeapi_proxy_tpu.authz import watchhub as watchhub_mod
    from spicedb_kubeapi_proxy_tpu.engine import WriteOp
    from spicedb_kubeapi_proxy_tpu.models.tuples import Relationship

    monkeypatch.setattr(watchhub_mod, "EXPIRY_RECOMPUTE_INTERVAL", 0.05)

    async def go():
        env = Env(bootstrap="""
schema: |-
  use expiration

  definition user {}
  definition cluster {}
  definition namespace {
    relation cluster: cluster
    relation creator: user
    relation viewer: user with expiration
    permission admin = creator
    permission view = viewer + creator
  }
relationships: ""
""")
        await env.create_ns("exp", user="bob")
        # pre-warm the expiry-shaped kernels: the first expiring tuple
        # changes the compiled graph shape, and that one-time XLA compile
        # (~1s) must not eat the 0.6s expiry budget this test times
        env.engine.write_relationships([WriteOp("touch", Relationship(
            "namespace", "warm", "viewer", "user", "alice",
            expiration=_time.time() + 300))])
        env.engine.lookup_resources("namespace", "view", "user", "alice")
        env.engine.write_relationships([WriteOp("delete", Relationship(
            "namespace", "warm", "viewer", "user", "alice"))])
        env.engine.write_relationships([WriteOp("touch", Relationship(
            "namespace", "exp", "viewer", "user", "alice",
            expiration=_time.time() + 0.6))])
        resp = await env.request("GET", "/api/v1/namespaces", user="alice",
                                 query={"watch": ["true"]})
        frames = []

        async def consume():
            async for f in resp.stream:
                frames.append(json.loads(f))

        task = asyncio.ensure_future(consume())
        # while the grant is live, the ADDED frame flows
        await asyncio.wait_for(_wait_for(lambda: len(frames) >= 1),
                               timeout=10)
        # wait past expiry with ZERO further writes, then emit an event
        await asyncio.sleep(0.9)
        env.kube.emit_watch_event("namespaces", "MODIFIED", "exp")
        await asyncio.sleep(0.4)
        assert len(frames) == 1, "post-expiry frame must be dropped"
        task.cancel()
        env.kube.stop_watches()
    run(go())


def test_prefilter_strict_vs_lenient_id_mapping():
    """strict=True (the pre-headers run) raises on an unmappable id;
    strict=False (mid-stream recomputes) skips only that id — an aborted
    recompute would freeze the watch's allowed set, which fails OPEN for
    revocations."""
    from spicedb_kubeapi_proxy_tpu.authz.lookups import (
        PreFilterError,
        run_prefilter_sync,
    )
    from spicedb_kubeapi_proxy_tpu.engine import WriteOp
    from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship
    from spicedb_kubeapi_proxy_tpu.rules.expr import ExprError

    env = Env()
    env.engine.write_relationships([
        WriteOp("touch", parse_relationship("namespace:good#creator@user:a")),
        WriteOp("touch", parse_relationship("namespace:bad#creator@user:a")),
    ])
    info = parse_request_info("GET", "/api/v1/namespaces",
                              {"watch": ["true"]})
    from spicedb_kubeapi_proxy_tpu.rules.input import ResolveInput
    inp = ResolveInput.create(info, UserInfo(name="a"), headers={})
    from spicedb_kubeapi_proxy_tpu.rules.compile import compile_rule
    from spicedb_kubeapi_proxy_tpu.rules.proxyrule import parse_rule_configs
    rule = compile_rule(parse_rule_configs("""
match: [{apiVersion: v1, resource: namespaces, verbs: [list, watch]}]
prefilter:
  - fromObjectIDNameExpr: "{{resourceId}}"
    lookupMatchingResources:
      tpl: "namespace:$#view@user:{{user.name}}"
""")[0])
    pf = rule.pre_filters[0]

    class FailsOnBad:
        def evaluate_str(self, data):
            if data["resourceId"] == "bad":
                raise ExprError("unmappable id")
            return data["resourceId"]

    object.__setattr__(pf, "name_expr", FailsOnBad())
    # mapping_kind is DERIVED from the exprs: the duck-typed fake (no
    # refs/source) reclassifies the prefilter as "general" automatically,
    # so the substituted expr actually runs
    assert pf.mapping_kind == "general"
    with pytest.raises(PreFilterError, match="unmappable|mapping"):
        run_prefilter_sync(env.engine, pf, inp)  # strict default
    allowed = run_prefilter_sync(env.engine, pf, inp, strict=False)
    assert allowed.pairs == {("", "good")}  # bad skipped, not fatal


def test_watch_flushes_on_arrow_mediated_grant():
    """A NAMESPACE-level grant makes buffered POD frames flush (pod#view
    includes namespace->view): the event batch recomputes the full
    allowed set, catching permission changes the changed relationship's
    own type never mentions. (The reference's per-object re-check of
    same-type events misses this — our join is strictly stronger.)
    Symmetrically, revoking the namespace grant drops subsequent pod
    frames."""
    async def go():
        from spicedb_kubeapi_proxy_tpu.engine import WriteOp
        from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship

        # the DEFAULT bootstrap has no namespace->view arrow on pods (and
        # the reference's sample create-pods rule writes the namespace
        # tuple keyed by bare name, disconnected from namespacedName
        # checks — our deploy/rules.yaml fixes that): use an arrowed
        # schema and write the consistently-keyed namespace tuple
        env = Env(bootstrap="""
schema: |-
  definition user {}
  definition namespace {
    relation creator: user
    relation viewer: user
    permission admin = creator
    permission view = viewer + creator
  }
  definition pod {
    relation namespace: namespace
    relation creator: user
    relation viewer: user
    permission edit = creator
    permission view = viewer + creator + namespace->view
  }
relationships: ""
""")
        await env.create_ns("wa", user="bob")
        await env.create_pod("wa", "api", user="bob")
        env.engine.write_relationships([WriteOp("touch", parse_relationship(
            "pod:wa/api#namespace@namespace:wa"))])
        resp = await env.request("GET", "/api/v1/pods", user="alice",
                                 query={"watch": ["true"]})
        frames = []

        async def consume():
            async for f in resp.stream:
                frames.append(json.loads(f)["object"]["metadata"]["name"])

        task = asyncio.ensure_future(consume())
        await asyncio.sleep(0.05)
        assert not frames  # buffered: alice can't view bob's namespace
        # grant at the NAMESPACE level — no pod-type relationship changes
        env.engine.write_relationships([WriteOp("touch", parse_relationship(
            "namespace:wa#viewer@user:alice"))])
        await asyncio.wait_for(_wait_for(lambda: frames == ["api"]),
                               timeout=10)
        # revoke the namespace grant; a subsequent pod event is dropped
        env.engine.write_relationships([WriteOp("delete", parse_relationship(
            "namespace:wa#viewer@user:alice"))])
        await asyncio.sleep(0.1)  # let the revocation reach the join
        env.kube.emit_watch_event("pods", "MODIFIED", "api", ns="wa")
        await asyncio.sleep(0.3)
        assert frames == ["api"]  # the MODIFIED frame was dropped
        task.cancel()
        env.kube.stop_watches()
    run(go())


def test_concurrent_watchers_per_user_isolation():
    """Three users watch namespaces concurrently; each stream delivers
    exactly that user's objects as grants land (proxy_test.go:615-649
    exercises per-user watch isolation with parallel clients)."""
    async def go():
        from spicedb_kubeapi_proxy_tpu.engine import WriteOp
        from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship

        env = Env()
        # warm the jitted watch-check kernels before the delivery clock
        # starts: a cold first compile (up to ~3s on a loaded machine)
        # made a 5s all-or-nothing wait flaky
        env.engine.check_bulk([
            CheckItem("namespace", "warm", "view", "user", "alice")])
        frames = {}

        async def consume(user, stream):
            async for f in stream:
                frames[user].append(
                    json.loads(f)["object"]["metadata"]["name"])

        tasks = []
        for user in ("alice", "bob", "carol"):
            resp = await env.request("GET", "/api/v1/namespaces", user=user,
                                     query={"watch": ["true"]})
            assert resp.status == 200
            frames[user] = []
            tasks.append(asyncio.ensure_future(consume(user, resp.stream)))
        # create one namespace per user (interleaved)
        for user, ns in (("alice", "a-ns"), ("bob", "b-ns"),
                         ("carol", "c-ns")):
            r = await env.create_ns(ns, user=user)
            assert r.status == 201
        # and one namespace bob shares with carol
        r = await env.create_ns("shared", user="bob")
        assert r.status == 201
        env.engine.write_relationships([WriteOp("touch", parse_relationship(
            "namespace:shared#viewer@user:carol"))])
        want = {"alice": ["a-ns"], "bob": ["b-ns", "shared"],
                "carol": ["c-ns", "shared"]}
        try:
            await asyncio.wait_for(
                _wait_for(lambda: frames == want), timeout=15)
        finally:
            for t in tasks:
                t.cancel()
            env.kube.stop_watches()
        assert frames == want  # reports per-user stream contents on failure
    run(go())


def test_watch_frames_pass_through_byte_identical():
    """The reference guarantees allowed watch frames are relayed
    byte-identical (frameCapturingReader, pkg/authz/frames.go:13-68) —
    no re-serialization, no key reordering. Compare the delivered bytes
    against exactly what the upstream emitted."""
    async def go():
        env = Env()
        await env.create_ns("bi", user="alice")
        # capture what the upstream actually sends
        sent = []
        orig_notify = env.kube._notify

        def capturing_notify(res, ns, event):
            sent.append((json.dumps(event) + "\n").encode())
            orig_notify(res, ns, event)

        env.kube._notify = capturing_notify
        resp = await env.request("GET", "/api/v1/namespaces", user="alice",
                                 query={"watch": ["true"]})
        got = []

        async def consume():
            async for f in resp.stream:
                got.append(bytes(f))

        task = asyncio.ensure_future(consume())
        await asyncio.sleep(0.05)
        env.kube.emit_watch_event("namespaces", "MODIFIED", "bi")
        await asyncio.wait_for(_wait_for(lambda: len(got) >= 2), timeout=5)
        task.cancel()
        # frame 0 is the initial ADDED (sent before capture); frame 1 must
        # be bit-for-bit the upstream's MODIFIED frame
        assert sent and got[1] == sent[0], (got[1], sent[0])
        env.kube.stop_watches()
    run(go())


UPDATE_PATCH_RULES = RULES + """
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata:
  name: pod-update
match:
  - apiVersion: v1
    resource: pods
    verbs: ["update", "patch"]
check:
  - tpl: "pod:{{namespacedName}}#edit@user:{{user.name}}"
update:
  touches:
    # viewer is NOT written by the create rule, so its existence after an
    # update proves this rule's touches ran (reference touches #creator,
    # which create also writes — that assertion would be vacuous here)
    - tpl: "pod:{{namespacedName}}#viewer@user:{{user.name}}"
"""


def test_update_and_patch_verbs_dual_write():
    """Reference e2e updateTestResource rule (proxy_test.go:1256-1272):
    update/patch gated on #edit, dual-writing a #creator touch. The
    creator may update AND patch; a viewer-only user is denied both."""
    async def go():
        env = Env(rules_yaml=UPDATE_PATCH_RULES)
        await env.create_ns("upd", user="alice")
        await env.create_pod("upd", "api", user="alice")
        from spicedb_kubeapi_proxy_tpu.engine import WriteOp
        from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship
        env.engine.write_relationships([WriteOp("touch", parse_relationship(
            "pod:upd/api#viewer@user:bob"))])  # bob can view, not edit
        # the touched relation must not pre-exist: the assertion below is
        # only meaningful if the PUT's dual-write creates it
        assert not env.engine.store.exists(RelationshipFilter(
            "pod", "upd/api", "viewer", "user", "alice"))
        body = {"apiVersion": "v1", "kind": "Pod",
                "metadata": {"name": "api", "namespace": "upd",
                             "labels": {"v": "2"}}}
        # creator updates: allowed, upstream applied, touch written
        r = await env.request("PUT", "/api/v1/namespaces/upd/pods/api",
                              user="alice", body=body)
        assert r.status == 200, r.body
        assert env.kube.objects[("pods", "upd", "api")]["metadata"][
            "labels"] == {"v": "2"}
        assert env.engine.store.exists(RelationshipFilter(
            "pod", "upd/api", "viewer", "user", "alice"))
        # creator patches: allowed
        body["metadata"]["labels"] = {"v": "3"}
        r = await env.request("PATCH", "/api/v1/namespaces/upd/pods/api",
                              user="alice", body=body)
        assert r.status == 200, r.body
        # viewer-only bob: denied on both verbs with a DISTINCT body, so
        # a fail-open forward would be visible upstream
        bob_body = {"apiVersion": "v1", "kind": "Pod",
                    "metadata": {"name": "api", "namespace": "upd",
                                 "labels": {"v": "bob-was-here"}}}
        rv = env.kube.objects[("pods", "upd", "api")]["metadata"][
            "resourceVersion"]
        for method in ("PUT", "PATCH"):
            r = await env.request(method, "/api/v1/namespaces/upd/pods/api",
                                  user="bob", body=bob_body)
            assert r.status == 403, (method, r.status)
        meta = env.kube.objects[("pods", "upd", "api")]["metadata"]
        assert meta["labels"] == {"v": "3"}
        assert meta["resourceVersion"] == rv  # no upstream write happened
    run(go())


def test_multiple_update_rules_rejected():
    async def go():
        dup = RULES + "\n---\n" + RULES.split("---")[0]  # duplicate create rule
        env = Env(rules_yaml=dup)
        resp = await env.create_ns("x")
        assert resp.status == 500
        assert b"only one" in resp.body
    run(go())


CRD_RULES = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata:
  name: testresource-create
lock: Pessimistic
match:
- apiVersion: example.com/v1alpha1
  resource: testresources
  verbs: ["create"]
update:
  preconditionDoesNotExist:
  # subject-independent: NO creator may exist yet (the '$' wildcard), so
  # a second user's create conflicts instead of adding a second owner
  - tpl: "testresource:{{namespacedName}}#creator@user:$"
  creates:
  - tpl: "testresource:{{namespacedName}}#creator@user:{{user.name}}"
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata:
  name: testresource-read
match:
- apiVersion: example.com/v1alpha1
  resource: testresources
  verbs: ["get", "list", "watch"]
prefilter:
- fromObjectIDNameExpr: "{{split_name(resourceId)}}"
  fromObjectIDNamespaceExpr: "{{split_namespace(resourceId)}}"
  lookupMatchingResources:
    tpl: "testresource:$#view@user:{{user.name}}"
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata:
  name: testresource-write
lock: Pessimistic
match:
- apiVersion: example.com/v1alpha1
  resource: testresources
  verbs: ["update", "patch"]
check:
- tpl: "testresource:{{namespacedName}}#edit@user:{{user.name}}"
update:
  touches:
  - tpl: "testresource:{{namespacedName}}#creator@user:{{user.name}}"
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata:
  name: testresource-delete
lock: Pessimistic
match:
- apiVersion: example.com/v1alpha1
  resource: testresources
  verbs: ["delete"]
check:
- tpl: "testresource:{{namespacedName}}#edit@user:{{user.name}}"
update:
  deletes:
  - tpl: "testresource:{{namespacedName}}#creator@user:{{user.name}}"
"""

CRD_BOOTSTRAP = """
schema: |-
  definition user {}
  definition testresource {
    relation creator: user
    relation viewer: user
    permission edit = creator
    permission view = viewer + creator
  }
"""


def test_crd_custom_group_end_to_end():
    """CRD-shaped resources under a named apiGroup
    (/apis/example.com/v1alpha1/...): create / get / list / watch /
    update / delete with per-user isolation and cross-user write denial —
    the reference installs testresource CRDs into envtest and drives the
    verbs on them (e2e/e2e_test.go:74, proxy_test.go:448-546).
    Unstructured handling means no type registration is needed here."""
    async def go():
        env = Env(rules_yaml=CRD_RULES, bootstrap=CRD_BOOTSTRAP)

        base = "/apis/example.com/v1alpha1/namespaces/ns1/testresources"
        resp = await env.request(
            "POST", base, user="alice",
            body={"apiVersion": "example.com/v1alpha1",
                  "kind": "TestResource",
                  "metadata": {"name": "tr1", "namespace": "ns1"}})
        assert resp.status == 201, resp.body
        resp = await env.request(
            "POST", base, user="bob",
            body={"apiVersion": "example.com/v1alpha1",
                  "kind": "TestResource",
                  "metadata": {"name": "tr2", "namespace": "ns1"}})
        assert resp.status == 201, resp.body

        # list isolation per user
        resp = await env.request("GET", base, user="alice")
        assert [o["metadata"]["name"]
                for o in json.loads(resp.body)["items"]] == ["tr1"]
        resp = await env.request("GET", base, user="bob")
        assert [o["metadata"]["name"]
                for o in json.loads(resp.body)["items"]] == ["tr2"]

        # single-get isolation
        assert (await env.request("GET", f"{base}/tr1",
                                  user="alice")).status == 200
        assert (await env.request("GET", f"{base}/tr1",
                                  user="bob")).status == 404

        # create conflict on the precondition
        resp = await env.request(
            "POST", base, user="bob",
            body={"apiVersion": "example.com/v1alpha1",
                  "kind": "TestResource",
                  "metadata": {"name": "tr1", "namespace": "ns1"}})
        assert resp.status == 409

        # update allowed for the owner, denied cross-user (check on #edit)
        resp = await env.request(
            "PUT", f"{base}/tr1", user="alice",
            body={"apiVersion": "example.com/v1alpha1",
                  "kind": "TestResource",
                  "metadata": {"name": "tr1", "namespace": "ns1",
                               "labels": {"v": "2"}}})
        assert resp.status == 200, resp.body
        resp = await env.request(
            "PUT", f"{base}/tr1", user="bob",
            body={"apiVersion": "example.com/v1alpha1",
                  "kind": "TestResource",
                  "metadata": {"name": "tr1", "namespace": "ns1"}})
        assert resp.status == 403

        # watch: alice's stream carries only her resource
        resp = await env.request("GET", base, user="alice",
                                 query={"watch": ["true"]})
        assert resp.status == 200 and resp.stream is not None
        async for frame in resp.stream:
            ev = json.loads(frame)
            assert ev["object"]["metadata"]["name"] == "tr1"
            break
        env.kube.stop_watches()

        # delete denied cross-user; owner delete removes object + rels
        assert (await env.request("DELETE", f"{base}/tr1",
                                  user="bob")).status == 403
        resp = await env.request("DELETE", f"{base}/tr1", user="alice")
        assert resp.status == 200
        resp = await env.request("GET", base, user="alice")
        assert json.loads(resp.body)["items"] == []
        assert not env.engine.store.exists(
            RelationshipFilter(resource_type="testresource",
                               resource_id="ns1/tr1"))
    run(go())


def test_watch_recomputes_shared_across_watchers():
    """VERDICT r3 directive 2: W watchers on one (rule, subject) must cost
    ONE device query per relevant write batch, not W — the hub groups them
    (reference shared watch service, pkg/authz/watch.go:48-109). Watchers
    with DISTINCT subjects each get their own group."""
    async def go():
        from spicedb_kubeapi_proxy_tpu.engine import WriteOp
        from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship
        from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

        env = Env()
        await env.create_ns("shared-w", user="alice")
        env.engine.check_bulk([  # warm kernels off the delivery clock
            CheckItem("namespace", "warm", "view", "user", "alice")])
        n_watchers = 100
        tasks, streams = [], []
        frames_per = [[] for _ in range(n_watchers)]

        async def consume(i, stream):
            async for f in stream:
                frames_per[i].append(
                    json.loads(f)["object"]["metadata"]["name"])

        for i in range(n_watchers):
            resp = await env.request(
                "GET", "/api/v1/namespaces", user="alice",
                query={"watch": ["true"]})
            assert resp.status == 200
            streams.append(resp.stream)
            tasks.append(asyncio.ensure_future(consume(i, resp.stream)))
        # one more watcher for a DIFFERENT subject: its own group
        resp = await env.request("GET", "/api/v1/namespaces", user="bob",
                                 query={"watch": ["true"]})
        bob_frames = []

        async def consume_bob():
            async for f in resp.stream:
                bob_frames.append(json.loads(f)["object"]["metadata"]["name"])

        tasks.append(asyncio.ensure_future(consume_bob()))
        hub = env.deps.watch_hub
        assert hub is not None
        # registration happens when each stream starts being consumed
        await asyncio.wait_for(_wait_for(lambda: sum(
            len(g.watchers) for g in hub._groups.values()) == 101),
            timeout=10)
        assert len(hub._groups) == 2, \
            "100 same-subject watchers + 1 other must form exactly 2 groups"
        await asyncio.sleep(0.1)  # drain initial traffic
        lookups0 = metrics.counter("engine_lookups_total").value
        # one relevant write batch: a new grant for alice
        await env.create_ns("shared-w2", user="alice")
        env.engine.write_relationships([WriteOp("touch", parse_relationship(
            "namespace:shared-w2#viewer@user:alice"))])
        # every alice watcher must see the new namespace
        await asyncio.wait_for(_wait_for(lambda: all(
            "shared-w2" in fp for fp in frames_per)), timeout=10)
        await asyncio.sleep(0.2)  # let any trailing recomputes land
        recomputes = metrics.counter("engine_lookups_total").value - lookups0
        # O(groups) per batch, NOT O(watchers): the two writes above are
        # at most 2 batches x 2 groups (+1 for trigger coalescing slack)
        assert recomputes <= 5, \
            f"{recomputes} device lookups for 101 watchers on 2 groups"
        assert not any("shared-w2" in f for f in bob_frames)
        for t in tasks:
            t.cancel()
        env.kube.stop_watches()
    run(go())


def test_postfilter_proto_response_clean_401_not_500():
    """A hand-crafted proto Accept on a postfilter route is rewritten to
    JSON upstream; an upstream that returns protobuf ANYWAY must produce
    a clean 401 from the postfilter, never a 500 (VERDICT r3 weak #7)."""
    async def go():
        from spicedb_kubeapi_proxy_tpu.engine import WriteOp
        from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship
        from spicedb_kubeapi_proxy_tpu.proxy import kubeproto
        from spicedb_kubeapi_proxy_tpu.proxy.types import ProxyResponse

        # postfilter-ONLY rule set: the response must reach the
        # postfilter (no prefilter in front) to prove ITS 4xx path
        env = Env(rules_yaml=POSTFILTER_RULES)
        env.engine.write_relationships([
            WriteOp("touch",
                    parse_relationship("pod:ns1/a#viewer@user:alice")),
        ])

        async def stubborn_proto_upstream(req):
            return ProxyResponse(
                status=200,
                headers={"Content-Type": kubeproto.CONTENT_TYPE},
                body=kubeproto.MAGIC + b"\x0a\x00")

        env.deps.upstream = stubborn_proto_upstream
        resp = await env.request(
            "GET", "/api/v1/namespaces/ns1/pods", user="alice",
            headers={"Accept":
                     "application/vnd.kubernetes.protobuf;as=Table"})
        assert resp.status == 401, resp.status
        assert b"Status" in resp.body  # a proper kube Status body
    run(go())


def test_prefilter_proto_table_end_to_end():
    """A protobuf Table response on a prefiltered route is row-filtered
    at the wire level through the full middleware (reference
    responsefilterer.go:349-374)."""
    async def go():
        from spicedb_kubeapi_proxy_tpu.proxy import kubeproto
        from spicedb_kubeapi_proxy_tpu.proxy.types import ProxyResponse

        env = Env()
        await env.create_ns("mine", user="alice")
        await env.create_ns("theirs", user="bob")

        def ld(f, p):
            return kubeproto._ld_field(f, p)

        def row(name):
            pom = ld(1, ld(1, name.encode()))  # PartialObjectMetadata
            wrapped = (kubeproto.MAGIC
                       + ld(1, ld(1, b"meta.k8s.io/v1")
                            + ld(2, b"PartialObjectMetadata"))
                       + ld(2, pom))
            return ld(1, ld(1, b'"cell"')) + ld(3, ld(1, wrapped))

        table_raw = ld(1, ld(2, b"rv1")) + ld(3, row("mine")) \
            + ld(3, row("theirs"))
        body = (kubeproto.MAGIC
                + ld(1, ld(1, b"meta.k8s.io/v1") + ld(2, b"Table"))
                + ld(2, table_raw))

        async def proto_table_upstream(req):
            return ProxyResponse(
                status=200,
                headers={"Content-Type": kubeproto.CONTENT_TYPE},
                body=body)

        env.deps.upstream = proto_table_upstream
        resp = await env.request("GET", "/api/v1/namespaces", user="alice")
        assert resp.status == 200
        _, kind, new_raw = kubeproto.decode_unknown(resp.body)
        assert kind == "Table"
        rows = [p for f, w, _, p in kubeproto.fields(new_raw) if f == 3]
        assert len(rows) == 1
        assert kubeproto.table_row_meta(rows[0]) == ("", "mine")
    run(go())


def test_dual_write_genuine_rv_conflict_from_fake():
    """The fake upstream now enforces optimistic concurrency itself: an
    update carrying a stale resourceVersion draws a GENUINE 409 from the
    fake (not an injected failure), and the dual-write workflow completes
    with the reference's verb-aware semantics (409 counts as applied,
    workflow.go:252-275) — no hung workflow, no leftover locks."""
    async def go():
        env = Env(rules_yaml=UPDATE_PATCH_RULES)
        await env.create_ns("rv-ns", user="alice")
        await env.create_pod("rv-ns", "api", user="alice")
        obj = json.loads((await env.request(
            "GET", "/api/v1/namespaces/rv-ns/pods/api")).body)
        stale_rv = obj["metadata"]["resourceVersion"]
        # an out-of-band write bumps the object's RV
        env.kube.put("pods", "api", ns="rv-ns",
                     obj={"metadata": {"name": "api",
                                       "namespace": "rv-ns",
                                       "labels": {"touched": "yes"}}})
        # now update through the proxy with the STALE rv
        obj["metadata"]["resourceVersion"] = stale_rv
        obj["metadata"]["labels"] = {"mine": "yes"}
        resp = await env.request("PUT", "/api/v1/namespaces/rv-ns/pods/api",
                                 user="alice", body=obj)
        assert resp.status == 409, resp.body
        assert b"Conflict" in resp.body or b"modified" in resp.body
        # workflow finished cleanly: no lock tuples left behind
        # (reference invariant, proxy_test.go:106-111)
        assert not env.engine.store.exists(
            RelationshipFilter(resource_type="lock"))
        # the conflicted write did NOT land upstream
        cur = env.kube.objects[("pods", "rv-ns", "api")]
        assert cur["metadata"].get("labels") == {"touched": "yes"}
        # a fresh-RV update then succeeds
        obj["metadata"]["resourceVersion"] = \
            cur["metadata"]["resourceVersion"]
        resp = await env.request("PUT", "/api/v1/namespaces/rv-ns/pods/api",
                                 user="alice", body=obj)
        assert resp.status == 200
        assert env.kube.objects[("pods", "rv-ns", "api")]["metadata"][
            "labels"] == {"mine": "yes"}
    run(go())


def test_delete_with_finalizer_two_phase():
    """Finalizer semantics in the fake: DELETE on a finalized object only
    marks it terminating (deletionTimestamp, MODIFIED event); the object
    disappears when a controller clears the finalizers — what the
    reference gets from envtest + a real GC controller
    (e2e/e2e_test.go:156-186)."""
    async def go():
        env = Env()
        assert (await env.create_ns("fin-ns")).status == 201
        key = ("namespaces", "", "fin-ns")
        env.kube.objects[key]["metadata"]["finalizers"] = ["test/guard"]
        resp = await env.request("DELETE", "/api/v1/namespaces/fin-ns")
        assert resp.status == 200
        # still present upstream, terminating
        obj = env.kube.objects.get(key)
        assert obj is not None
        assert obj["metadata"]["deletionTimestamp"]
        # the dual-write already removed the relationships (the reference
        # also deletes rels on the DELETE request; kube-side GC finishes
        # later)
        from spicedb_kubeapi_proxy_tpu.engine import RelationshipFilter

        assert not env.engine.store.exists(RelationshipFilter(
            "namespace", "fin-ns", "creator"))
        # a controller clears the finalizer -> object actually deleted
        from spicedb_kubeapi_proxy_tpu.proxy.types import ProxyRequest
        patch = ProxyRequest(
            method="PATCH", path="/api/v1/namespaces/fin-ns",
            headers={"Content-Type": "application/merge-patch+json"},
            body=json.dumps({"metadata": {"finalizers": None}}).encode())
        r = await env.kube(patch)
        assert r.status == 200
        assert key not in env.kube.objects
    run(go())


def test_watch_error_status_frames_pass_through():
    """A terminal ERROR/Status frame (watch expiry, 410 Gone) carries no
    authorizable object; suppressing it would hang the client on a dead
    watch — it must pass through (review finding: the JSON path buffered
    it under the unkeyable ("", "") pair forever)."""
    async def go():
        env = Env()
        await env.create_ns("err-ns", user="alice")
        resp = await env.request("GET", "/api/v1/namespaces", user="alice",
                                 query={"watch": ["true"]})
        frames = []

        async def consume():
            async for f in resp.stream:
                frames.append(json.loads(f))

        task = asyncio.ensure_future(consume())
        await asyncio.wait_for(_wait_for(lambda: len(frames) >= 1),
                               timeout=5)
        env.kube._notify("namespaces", "", {
            "type": "ERROR",
            "object": {"kind": "Status", "apiVersion": "v1",
                       "code": 410, "reason": "Expired"}})
        await asyncio.wait_for(_wait_for(lambda: any(
            f["type"] == "ERROR" for f in frames)), timeout=5)
        task.cancel()
        env.kube.stop_watches()
    run(go())


def test_list_filter_no_drop_is_byte_identical():
    """When every list item / table row is allowed, the response body
    passes through byte-identical — no decode/re-serialize artifacts
    (key order, float formatting, unicode escapes) and no re-serialize
    cost on multi-MB bodies."""
    from spicedb_kubeapi_proxy_tpu.authz.filterer import filter_body
    from spicedb_kubeapi_proxy_tpu.authz.lookups import AllowedSet
    from spicedb_kubeapi_proxy_tpu.rules.input import (
        RequestInfo,
        ResolveInput,
        UserInfo,
    )

    input = ResolveInput.create(
        RequestInfo(verb="list", api_version="v1", resource="pods",
                    path="/api/v1/pods"),
        UserInfo(name="a"))
    # deliberately quirky serialization a re-dump would normalize
    body = (b'{"kind":"PodList",  "items":[\n'
            b'  {"metadata":{"name":"p1","namespace":"ns"}},'
            b'{"metadata":{"namespace":"ns","name":"p2"},"x":1.50}]}')
    allowed = AllowedSet({("ns", "p1"), ("ns", "p2")})
    status, out = filter_body(body, allowed, input)
    assert (status, out) == (200, body)
    # dropping one item still filters (and re-serializes)
    partial = AllowedSet({("ns", "p1")})
    status, out = filter_body(body, partial, input)
    assert status == 200
    names = [o["metadata"]["name"] for o in json.loads(out)["items"]]
    assert names == ["p1"]
    # Table branch: all rows kept -> byte-identical; a drop re-serializes
    table = (b'{"kind":"Table", "rows":[\n'
             b' {"cells":["p1"],"object":{"metadata":'
             b'{"name":"p1","namespace":"ns"}}},'
             b' {"cells":["p2"],"object":{"metadata":'
             b'{"name":"p2","namespace":"ns"}}}]}')
    status, out = filter_body(table, allowed, input)
    assert (status, out) == (200, table)
    status, out = filter_body(table, partial, input)
    assert status == 200
    kept_rows = json.loads(out)["rows"]
    assert [r["object"]["metadata"]["name"] for r in kept_rows] == ["p1"]


def test_prefilter_mapping_fast_paths_match_general_evaluation():
    """run_prefilter_sync short-circuits the two deploy/rules.yaml
    mapping shapes (identity, split_name/split_namespace) into plain
    string ops; they must produce byte-for-byte the same allowed pairs
    as general expression evaluation, including slashless (cluster-
    scoped) and multi-slash ids."""
    from spicedb_kubeapi_proxy_tpu.authz.lookups import run_prefilter_sync
    from spicedb_kubeapi_proxy_tpu.engine import Engine, WriteOp
    from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship
    from spicedb_kubeapi_proxy_tpu.rules.matcher import (
        MapMatcher,
        RequestMeta,
    )
    from spicedb_kubeapi_proxy_tpu.rules.input import (
        RequestInfo,
        ResolveInput,
        UserInfo,
    )

    engine = Engine()
    ids = ["plain", "ns1/pod-a", "ns2/pod/with/slashes"]
    engine.write_relationships([
        WriteOp("touch", parse_relationship(f"pod:{i}#viewer@user:alice"))
        for i in ids
    ])
    input = ResolveInput.create(
        RequestInfo(verb="list", api_version="v1", resource="pods",
                    path="/api/v1/pods"),
        UserInfo(name="alice"))

    def pf_for(mapping_yaml: str):
        rules = MapMatcher.from_yaml(f"""
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
lock: Pessimistic
match:
- apiVersion: v1
  resource: pods
  verbs: ["list"]
prefilter:
{mapping_yaml}
""")
        return rules.match(RequestMeta(
            verb="list", api_group="", api_version="v1",
            resource="pods"))[0].pre_filters[0]

    # identity fast path == a general expr forced off the fast path by
    # an equivalent-but-differently-spelled source; interior whitespace
    # must NOT defeat the compile-time classification
    pf_id = pf_for(
        '- fromObjectIDNameExpr: "{{ resourceId }}"\n'
        '  lookupMatchingResources:\n'
        '    tpl: "pod:$#view@user:{{user.name}}"')
    assert pf_id.mapping_kind == "identity"
    fast = run_prefilter_sync(engine, pf_id, input)
    general = run_prefilter_sync(engine, pf_for(
        '- fromObjectIDNameExpr: "{{string(resourceId)}}"\n'
        '  lookupMatchingResources:\n'
        '    tpl: "pod:$#view@user:{{user.name}}"'), input)
    assert fast.pairs == general.pairs == {("", i) for i in ids}

    # a braceless LITERAL template that merely spells "resourceId" means
    # a CONSTANT name (the {{ }}/literal duality) — it must NOT take the
    # identity fast path (review finding: matching it fails open)
    pf_lit = pf_for(
        '- fromObjectIDNameExpr: "resourceId"\n'
        '  lookupMatchingResources:\n'
        '    tpl: "pod:$#view@user:{{user.name}}"')
    assert pf_lit.mapping_kind == "general"
    literal = run_prefilter_sync(engine, pf_lit, input)
    assert literal.pairs == {("", "resourceId")}

    # split fast path == general split evaluation (name-only spelling
    # avoids the fast path; add the ns expr separately); whitespace
    # variants classify too
    pf_split = pf_for(
        '- fromObjectIDNameExpr: "{{ split_name( resourceId ) }}"\n'
        '  fromObjectIDNamespaceExpr: '
        '"{{ split_namespace( resourceId ) }}"\n'
        '  lookupMatchingResources:\n'
        '    tpl: "pod:$#view@user:{{user.name}}"')
    assert pf_split.mapping_kind == "split"
    fast = run_prefilter_sync(engine, pf_split, input)
    general = run_prefilter_sync(engine, pf_for(
        '- fromObjectIDNameExpr: "{{string(split_name(resourceId))}}"\n'
        '  fromObjectIDNamespaceExpr: '
        '"{{string(split_namespace(resourceId))}}"\n'
        '  lookupMatchingResources:\n'
        '    tpl: "pod:$#view@user:{{user.name}}"'), input)
    assert fast.pairs == general.pairs == {
        ("", "plain"), ("ns1", "pod-a"), ("ns2", "pod/with/slashes")}


def test_gc_cascade_background_semantics():
    """Fake GC fidelity (reference runs a REAL kube GC controller,
    e2e/e2e_test.go:156-186): deleting an owner background-deletes
    dependents whose ownerReferences all dangle; a dependent with a
    second LIVING owner survives; Orphan strips refs instead; a
    finalized dependent terminates rather than vanishing; grandchildren
    cascade recursively."""
    async def go():
        kube = FakeKube()

        def put_with_refs(res, name, ns="", refs=None, finalizers=None):
            obj = {"metadata": {}}
            if refs:
                obj["metadata"]["ownerReferences"] = refs
            if finalizers:
                obj["metadata"]["finalizers"] = finalizers
            return kube.put(res, name, ns, obj)

        ref = lambda kind, name: {"apiVersion": "v1", "kind": kind,  # noqa: E731
                                  "name": name}
        put_with_refs("widgets", "parent")
        put_with_refs("widgets", "keeper")
        put_with_refs("gadgets", "child", refs=[ref("Widget", "parent")])
        put_with_refs("gadgets", "shared", refs=[ref("Widget", "parent"),
                                                 ref("Widget", "keeper")])
        put_with_refs("gizmos", "grandchild",
                      refs=[ref("Gadget", "child")])
        put_with_refs("gadgets", "finalized",
                      refs=[ref("Widget", "parent")],
                      finalizers=["test/guard"])
        from spicedb_kubeapi_proxy_tpu.proxy.types import ProxyRequest

        r = await kube(ProxyRequest(method="DELETE",
                                    path="/api/v1/widgets/parent"))
        assert r.status == 200
        # background: cascade lands after the handler returns
        await asyncio.wait_for(_wait_for(
            lambda: ("gadgets", "", "child") not in kube.objects), 5)
        await asyncio.wait_for(_wait_for(
            lambda: ("gizmos", "", "grandchild") not in kube.objects), 5)
        # the dependent with a living second owner survives
        assert ("gadgets", "", "shared") in kube.objects
        # the finalized dependent is terminating, not gone
        fin = kube.objects[("gadgets", "", "finalized")]
        assert fin["metadata"]["deletionTimestamp"]
        # orphan policy: the deleted owner's refs are stripped from its
        # (sole-owner) dependent, which survives
        put_with_refs("gadgets", "solo", refs=[ref("Widget", "keeper")])
        r = await kube(ProxyRequest(
            method="DELETE", path="/api/v1/widgets/keeper",
            query={"propagationPolicy": ["Orphan"]}))
        assert r.status == 200
        await asyncio.wait_for(_wait_for(
            lambda: "ownerReferences" not in
            kube.objects[("gadgets", "", "solo")]["metadata"]), 5)
        assert ("gadgets", "", "solo") in kube.objects
        # orphan intent survives a finalizer wait (review finding): the
        # owner terminates first, and the GC that runs when its finalizer
        # clears must still ORPHAN, not background-delete
        put_with_refs("widgets", "slowowner", finalizers=["test/guard"])
        put_with_refs("gadgets", "patient",
                      refs=[ref("Widget", "slowowner")])
        r = await kube(ProxyRequest(
            method="DELETE", path="/api/v1/widgets/slowowner",
            query={"propagationPolicy": ["Orphan"]}))
        assert r.status == 200
        assert ("widgets", "", "slowowner") in kube.objects  # terminating
        r = await kube(ProxyRequest(
            method="PATCH", path="/api/v1/widgets/slowowner",
            headers={"Content-Type": "application/merge-patch+json"},
            body=json.dumps({"metadata": {"finalizers": None}}).encode()))
        assert r.status == 200
        await asyncio.wait_for(_wait_for(
            lambda: ("widgets", "", "slowowner") not in kube.objects), 5)
        await asyncio.wait_for(_wait_for(
            lambda: "ownerReferences" not in
            kube.objects[("gadgets", "", "patient")]["metadata"]), 5)
        assert ("gadgets", "", "patient") in kube.objects
    run(go())


def test_unparseable_watch_frame_fails_closed():
    """A frame that is neither JSON nor a well-formed proto frame (e.g.
    truncated by a dying upstream) must never pass through unjudged
    (review finding: it used to be forwarded verbatim)."""
    from spicedb_kubeapi_proxy_tpu.authz.watch import _frame_object_key
    from spicedb_kubeapi_proxy_tpu.proxy import kubeproto
    from spicedb_kubeapi_proxy_tpu.rules.matcher import (
        MapMatcher,
        RequestMeta,
    )

    rules = MapMatcher.from_yaml("""
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
lock: Pessimistic
match:
- apiVersion: v1
  resource: namespaces
  verbs: ["watch"]
prefilter:
- fromObjectIDNameExpr: "{{resourceId}}"
  lookupMatchingResources:
    tpl: "namespace:$#view@user:{{user.name}}"
""")
    pf = rules.match(RequestMeta(verb="watch", api_group="",
                                 api_version="v1",
                                 resource="namespaces"))[0].pre_filters[0]
    with pytest.raises(kubeproto.ProtoError):
        _frame_object_key(b"garbage not json", pf)
    with pytest.raises(kubeproto.ProtoError):
        # a truncated proto frame: length prefix larger than the body
        _frame_object_key(b"\x00\x00\x10\x00partial", pf)
    # bare whitespace keepalives are harmless passthrough
    assert _frame_object_key(b"\n", pf) is None


@pytest.mark.parametrize("mode", ["Pessimistic", "Optimistic"])
def test_dual_write_delete_parent_cascades_children(mode):
    """VERDICT r4 directive 7: dual-write DELETE of a parent whose
    children ride ownerReferences — on success the parent's relationships
    are removed and the fake's GC cascades the children (watch-visible);
    on kube failure the workflow ROLLS BACK the parent's relationships
    and no cascade fires. Both lock modes."""
    rules = RULES.replace("lock: Pessimistic", f"lock: {mode}")

    async def go():
        from spicedb_kubeapi_proxy_tpu.engine import RelationshipFilter

        env = Env(rules_yaml=rules)
        # parent namespace + child pod referencing it
        assert (await env.create_ns("gcp")).status == 201
        ns_uid = env.kube.objects[("namespaces", "", "gcp")]["metadata"]["uid"]
        resp = await env.request(
            "POST", "/api/v1/namespaces/gcp/pods", user="alice",
            body={"apiVersion": "v1", "kind": "Pod",
                  "metadata": {"name": "victim", "namespace": "gcp",
                               "ownerReferences": [{
                                   "apiVersion": "v1", "kind": "Namespace",
                                   "name": "gcp", "uid": ns_uid}]}})
        assert resp.status == 201, resp.body
        assert env.engine.store.exists(RelationshipFilter(
            "pod", "gcp/victim", "creator", "user", "alice"))

        # -- failure leg first: kube rejects the DELETE ------------------
        env.kube.fail_next(n=1, method="DELETE")
        resp = await env.request("DELETE", "/api/v1/namespaces/gcp",
                                 user="alice")
        assert resp.status >= 400
        # the child was never cascaded (the kube delete never landed)
        assert ("pods", "gcp", "victim") in env.kube.objects
        if mode == "Pessimistic":
            # pessimistic rolls back on a rejected status
            # (workflow.go:232-234): the parent's relationships return
            assert env.engine.store.exists(RelationshipFilter(
                "namespace", "gcp", "creator", "user", "alice"))
        else:
            # reference optimistic semantics: a rejected (non-error) kube
            # response is returned WITHOUT rollback (workflow.go:327-351
            # only arbitrates activity errors) — restore the rel so the
            # success leg's authorization still holds
            from spicedb_kubeapi_proxy_tpu.engine import WriteOp
            from spicedb_kubeapi_proxy_tpu.models.tuples import (
                parse_relationship,
            )

            if not env.engine.store.exists(RelationshipFilter(
                    "namespace", "gcp", "creator", "user", "alice")):
                env.engine.write_relationships([WriteOp(
                    "touch", parse_relationship(
                        "namespace:gcp#creator@user:alice"))])
        assert not env.engine.store.exists(
            RelationshipFilter(resource_type="lock"))

        # -- success leg: delete lands, GC cascades the child -----------
        resp = await env.request("DELETE", "/api/v1/namespaces/gcp",
                                 user="alice")
        assert resp.status == 200, resp.body
        assert not env.engine.store.exists(RelationshipFilter(
            "namespace", "gcp", "creator"))
        assert ("namespaces", "", "gcp") not in env.kube.objects
        await asyncio.wait_for(_wait_for(
            lambda: ("pods", "gcp", "victim") not in env.kube.objects), 5)
        # no lock tuples left behind in either mode (reference invariant,
        # proxy_test.go:106-111)
        assert not env.engine.store.exists(
            RelationshipFilter(resource_type="lock"))
    run(go())


def test_watch_bookmarks_pass_through_filter():
    """BOOKMARK events carry no authorizable object; the filtered watch
    must pass them through (clients use them to checkpoint), not swallow
    them as unauthorized frames."""
    async def go():
        env = Env()
        await env.create_ns("bm-ns", user="alice")
        resp = await env.request(
            "GET", "/api/v1/namespaces", user="alice",
            query={"watch": ["true"], "allowWatchBookmarks": ["true"]})
        frames = []

        async def consume():
            async for f in resp.stream:
                frames.append(json.loads(f))

        task = asyncio.ensure_future(consume())
        # initial ADDED + the initial-events-end bookmark
        await asyncio.wait_for(_wait_for(lambda: len(frames) >= 2),
                               timeout=10)
        types = [f["type"] for f in frames]
        assert "BOOKMARK" in types and "ADDED" in types
        # a periodic bookmark also flows
        env.kube.emit_bookmark("namespaces")
        await asyncio.wait_for(
            _wait_for(lambda: types.count("BOOKMARK") < len(
                [f for f in frames if f["type"] == "BOOKMARK"])),
            timeout=10)
        task.cancel()
        env.kube.stop_watches()
    run(go())


def test_strategic_merge_patch_through_dual_write():
    """Strategic-merge-patch fidelity in the fake upstream: lists of
    named objects merge by name (the kube patchMergeKey convention) and
    $patch: delete removes entries — exercised through the proxy's patch
    dual-write path."""
    async def go():
        env = Env(rules_yaml=UPDATE_PATCH_RULES)
        await env.create_ns("smp", user="alice")
        await env.create_pod("smp", "api", user="alice")
        key = ("pods", "smp", "api")
        env.kube.objects[key]["spec"] = {"containers": [
            {"name": "app", "image": "app:v1"},
            {"name": "sidecar", "image": "sc:v1"},
        ]}
        resp = await env.request(
            "PATCH", "/api/v1/namespaces/smp/pods/api", user="alice",
            headers={"Content-Type":
                     "application/strategic-merge-patch+json"},
            body={"spec": {"containers": [
                {"name": "app", "image": "app:v2"},
                {"name": "sidecar", "$patch": "delete"},
                {"name": "logger", "image": "log:v1"},
            ]}})
        assert resp.status == 200, resp.body
        got = {c["name"]: c.get("image")
               for c in env.kube.objects[key]["spec"]["containers"]}
        assert got == {"app": "app:v2", "logger": "log:v1"}
        # plain merge-patch still REPLACES lists wholesale
        resp = await env.request(
            "PATCH", "/api/v1/namespaces/smp/pods/api", user="alice",
            headers={"Content-Type": "application/merge-patch+json"},
            body={"spec": {"containers": [
                {"name": "only", "image": "o:v1"}]}})
        assert resp.status == 200
        assert [c["name"] for c in
                env.kube.objects[key]["spec"]["containers"]] == ["only"]
    run(go())


def test_watch_churn_no_leaked_hub_state():
    """Rapid watcher churn under write load: watchers that come and go
    must leave ZERO hub state behind (groups empty, pump stopped) and
    never wedge registration for later watchers — the register/
    unregister/teardown interleavings are all lock-ordered."""
    async def go():
        from spicedb_kubeapi_proxy_tpu.engine import WriteOp
        from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship

        env = Env()
        await env.create_ns("churn", user="alice")
        env.engine.check_bulk([
            CheckItem("namespace", "warm", "view", "user", "alice")])

        async def one_watcher(i):
            resp = await env.request(
                "GET", "/api/v1/namespaces", user="alice",
                query={"watch": ["true"]})
            assert resp.status == 200
            frames = 0
            async for f in resp.stream:
                frames += 1
                if frames >= 1 + (i % 2 == 0):
                    break  # churn: leave after 1-2 frames
            await resp.stream.aclose()

        async def writer():
            for j in range(10):
                env.engine.write_relationships([WriteOp(
                    "touch", parse_relationship(
                        f"namespace:churn#viewer@user:w{j}"))])
                env.kube.emit_watch_event("namespaces", "MODIFIED",
                                          "churn")
                await asyncio.sleep(0.02)

        for wave in range(3):
            tasks = [asyncio.ensure_future(one_watcher(i))
                     for i in range(12)]
            wtask = asyncio.ensure_future(writer())
            await asyncio.wait_for(
                asyncio.gather(*tasks, wtask), timeout=30)
        hub = env.deps.watch_hub
        await asyncio.wait_for(_wait_for(
            lambda: not hub._groups), timeout=10)
        assert hub._pump_task is None, "pump must stop with no watchers"
        assert hub._push_stream is None
        # and a fresh watcher still works after all the churn
        resp = await env.request("GET", "/api/v1/namespaces",
                                 user="alice", query={"watch": ["true"]})
        frames = []

        async def consume():
            async for f in resp.stream:
                frames.append(f)

        t = asyncio.ensure_future(consume())
        await asyncio.wait_for(_wait_for(lambda: len(frames) >= 1),
                               timeout=10)
        t.cancel()
        env.kube.stop_watches()
    run(go())
