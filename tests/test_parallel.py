"""Sharded-engine tests: the shard_map fixpoint over a virtual 8-device CPU
mesh must agree exactly with the single-device jitted path (which itself is
fuzzed against the recursive oracle in test_engine.py)."""

import numpy as np
import pytest

import jax

from spicedb_kubeapi_proxy_tpu.engine import CheckItem, Engine, WriteOp
from spicedb_kubeapi_proxy_tpu.models import parse_schema
from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship
from spicedb_kubeapi_proxy_tpu.parallel import ShardedGraph, make_mesh

SCHEMA = """
definition user {}
definition group {
  relation member: user | group#member
}
definition org {
  relation admin: user
  relation parent: org
  permission admin_rec = admin + parent->admin_rec
}
definition doc {
  relation org: org
  relation owner: user
  relation reader: user | group#member
  relation banned: user
  permission read = (reader + owner + org->admin_rec) - banned
}
"""


def touch(*rels):
    return [WriteOp("touch", parse_relationship(r)) for r in rels]


def build_engine(seed=7, n_users=8, n_groups=5, n_docs=12, n_orgs=3):
    rng = np.random.default_rng(seed)
    e = Engine(schema=parse_schema(SCHEMA))
    users = [f"u{i}" for i in range(n_users)]
    ops = set()
    for g in range(n_groups):
        for u in rng.choice(n_users, size=3, replace=False):
            ops.add(f"group:g{g}#member@user:u{u}")
        g2 = rng.integers(n_groups)
        if g2 != g:
            ops.add(f"group:g{g}#member@group:g{g2}#member")
    for o in range(n_orgs):
        ops.add(f"org:o{o}#admin@user:u{rng.integers(n_users)}")
        o2 = rng.integers(n_orgs)
        if o2 != o:
            ops.add(f"org:o{o}#parent@org:o{o2}")
    for d in range(n_docs):
        for u in rng.choice(n_users, size=2, replace=False):
            ops.add(f"doc:d{d}#reader@user:u{u}")
        if rng.random() < 0.5:
            ops.add(f"doc:d{d}#owner@user:u{rng.integers(n_users)}")
        if rng.random() < 0.5:
            ops.add(f"doc:d{d}#banned@user:u{rng.integers(n_users)}")
        if rng.random() < 0.6:
            ops.add(f"doc:d{d}#reader@group:g{rng.integers(n_groups)}#member")
        if rng.random() < 0.7:
            ops.add(f"doc:d{d}#org@org:o{rng.integers(n_orgs)}")
    e.write_relationships(touch(*ops))
    return e, users


def grid_for_lookup(cg, objs, subjects, resource_type, permission):
    """seeds [B,2] + q_slots [B,Q] reading every object's permission slot."""
    off = cg.offset_of(resource_type, permission)
    n = cg.type_sizes[resource_type]
    seeds = np.asarray(
        [cg.encode_subject(t, i, None, objs) for (t, i) in subjects],
        dtype=np.int32,
    )
    q = np.tile(off + np.arange(n, dtype=np.int32), (len(subjects), 1))
    return seeds, q, n


@pytest.mark.parametrize("data,graph", [(2, 4), (1, 8), (8, 1), (4, 2)])
def test_sharded_matches_unsharded(data, graph):
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    e, users = build_engine()
    cg = e.compiled()
    objs = e._objects_by_name()
    mesh = make_mesh(8, data=data, graph=graph)
    sg = ShardedGraph(cg, mesh)

    subjects = [("user", u) for u in users] + [("user", "nobody")]
    seeds, q, n = grid_for_lookup(cg, objs, subjects, "doc", "read")
    got = sg.query_grid(seeds, q)

    interner = objs["doc"]
    for b, (_, u) in enumerate(subjects):
        want = set(e.lookup_resources("doc", "read", "user", u))
        got_ids = {
            interner.string(i)
            for i in np.flatnonzero(got[b]).tolist()
            if i >= 2 and i < len(interner)  # skip void/wildcard slots
        }
        assert got_ids == want, f"subject {u}: {got_ids} != {want}"


def test_sharded_check_grid_odd_shapes():
    e, users = build_engine(seed=11)
    cg = e.compiled()
    objs = e._objects_by_name()
    sg = ShardedGraph(cg, make_mesh(8, data=2, graph=4))

    # B=3 (not divisible by data axis), Q=5 (odd) — padding must handle it
    subjects = [("user", "u0"), ("user", "u3"), ("group", "g1")]
    checks = [("doc", f"d{i}", "read") for i in range(5)]
    seeds = np.asarray(
        [cg.encode_subject(t, i, "member" if t == "group" else None, objs)
         for (t, i) in subjects],
        dtype=np.int32,
    )
    q = np.asarray(
        [[cg.encode_target(rt, perm, rid, objs) for (rt, rid, perm) in checks]
         for _ in subjects],
        dtype=np.int32,
    )
    got = sg.query_grid(seeds, q)
    for b, (t, i) in enumerate(subjects):
        srel = "member" if t == "group" else None
        for qi, (rt, rid, perm) in enumerate(checks):
            want = e.check(CheckItem(rt, rid, perm, t, i, srel))
            assert bool(got[b, qi]) == want, (t, i, rt, rid)


def test_sharded_expiration_mask():
    import time

    now = time.time()
    e = Engine(schema=parse_schema(
        """
        definition user {}
        definition doc {
          relation reader: user with expiration
          permission read = reader
        }
        """
    ))
    from spicedb_kubeapi_proxy_tpu.models.tuples import Relationship

    e.write_relationships([
        WriteOp("touch", Relationship("doc", "live", "reader", "user", "u",
                                      expiration=now + 3600)),
        WriteOp("touch", Relationship("doc", "dead", "reader", "user", "u",
                                      expiration=now - 5)),
    ])
    cg = e.compiled()
    objs = e._objects_by_name()
    sg = ShardedGraph(cg, make_mesh(8))
    seeds = np.asarray([cg.encode_subject("user", "u", None, objs)],
                       dtype=np.int32)
    q = np.asarray([[cg.encode_target("doc", "read", "live", objs),
                     cg.encode_target("doc", "read", "dead", objs)]],
                   dtype=np.int32)
    got = sg.query_grid(seeds, q, now=now)
    assert got.tolist() == [[True, False]]


def test_sharded_sees_incremental_updates():
    """A ShardedGraph built from an incrementally-updated CompiledGraph
    folds the delta segment and dead-pair kills into its edge shards."""
    from spicedb_kubeapi_proxy_tpu.engine.store import RelationshipFilter
    from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

    e, users = build_engine(seed=23)
    e.compiled()
    c0 = metrics.counter("engine_graph_compiles_total").value

    # revoke one existing reader tuple and grant a new one — both must be
    # applied incrementally (no full recompile)
    existing = sorted(
        e.read_relationships(RelationshipFilter(
            resource_type="doc", relation="reader", subject_type="user")),
        key=str)[0]
    e.write_relationships([
        WriteOp("delete", existing),
        WriteOp("touch", parse_relationship("doc:d1#reader@user:u7")),
        WriteOp("touch", parse_relationship("group:g0#member@user:u6")),
    ])
    cg = e.compiled()
    assert metrics.counter("engine_graph_compiles_total").value == c0
    assert cg.n_delta >= 2 and len(cg.dead_pairs) >= 1

    objs = e._objects_by_name()
    sg = ShardedGraph(cg, make_mesh(8, data=2, graph=4))
    subjects = [("user", u) for u in users]
    seeds, q, _ = grid_for_lookup(cg, objs, subjects, "doc", "read")
    got = sg.query_grid(seeds, q)
    interner = objs["doc"]
    for b, (_, u) in enumerate(subjects):
        want = set(e.lookup_resources("doc", "read", "user", u))
        got_ids = {
            interner.string(i)
            for i in np.flatnonzero(got[b]).tolist()
            if i >= 2 and i < len(interner)
        }
        assert got_ids == want, f"subject {u}: {got_ids} != {want}"
