"""Sharded-engine tests: the shard_map fixpoint over a virtual 8-device CPU
mesh must agree exactly with the single-device jitted path (which itself is
fuzzed against the recursive oracle in test_engine.py)."""

import numpy as np
import pytest

import jax

from spicedb_kubeapi_proxy_tpu.engine import CheckItem, Engine, WriteOp
from spicedb_kubeapi_proxy_tpu.models import parse_schema
from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship
from spicedb_kubeapi_proxy_tpu.parallel import ShardedGraph, make_mesh

SCHEMA = """
definition user {}
definition group {
  relation member: user | group#member
}
definition org {
  relation admin: user
  relation parent: org
  permission admin_rec = admin + parent->admin_rec
}
definition doc {
  relation org: org
  relation owner: user
  relation reader: user | group#member
  relation banned: user
  permission read = (reader + owner + org->admin_rec) - banned
}
"""


def touch(*rels):
    return [WriteOp("touch", parse_relationship(r)) for r in rels]


def build_engine(seed=7, n_users=8, n_groups=5, n_docs=12, n_orgs=3):
    rng = np.random.default_rng(seed)
    e = Engine(schema=parse_schema(SCHEMA))
    users = [f"u{i}" for i in range(n_users)]
    ops = set()
    for g in range(n_groups):
        for u in rng.choice(n_users, size=3, replace=False):
            ops.add(f"group:g{g}#member@user:u{u}")
        g2 = rng.integers(n_groups)
        if g2 != g:
            ops.add(f"group:g{g}#member@group:g{g2}#member")
    for o in range(n_orgs):
        ops.add(f"org:o{o}#admin@user:u{rng.integers(n_users)}")
        o2 = rng.integers(n_orgs)
        if o2 != o:
            ops.add(f"org:o{o}#parent@org:o{o2}")
    for d in range(n_docs):
        for u in rng.choice(n_users, size=2, replace=False):
            ops.add(f"doc:d{d}#reader@user:u{u}")
        if rng.random() < 0.5:
            ops.add(f"doc:d{d}#owner@user:u{rng.integers(n_users)}")
        if rng.random() < 0.5:
            ops.add(f"doc:d{d}#banned@user:u{rng.integers(n_users)}")
        if rng.random() < 0.6:
            ops.add(f"doc:d{d}#reader@group:g{rng.integers(n_groups)}#member")
        if rng.random() < 0.7:
            ops.add(f"doc:d{d}#org@org:o{rng.integers(n_orgs)}")
    e.write_relationships(touch(*ops))
    return e, users


def grid_for_lookup(cg, objs, subjects, resource_type, permission):
    """seeds [B,2] + q_slots [B,Q] reading every object's permission slot."""
    off = cg.offset_of(resource_type, permission)
    n = cg.type_sizes[resource_type]
    seeds = np.asarray(
        [cg.encode_subject(t, i, None, objs) for (t, i) in subjects],
        dtype=np.int32,
    )
    q = np.tile(off + np.arange(n, dtype=np.int32), (len(subjects), 1))
    return seeds, q, n


@pytest.mark.parametrize("data,graph", [(2, 4), (1, 8), (8, 1), (4, 2)])
def test_sharded_matches_unsharded(data, graph):
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    e, users = build_engine()
    cg = e.compiled()
    objs = e._objects_by_name()
    mesh = make_mesh(8, data=data, graph=graph)
    sg = ShardedGraph(cg, mesh)

    subjects = [("user", u) for u in users] + [("user", "nobody")]
    seeds, q, n = grid_for_lookup(cg, objs, subjects, "doc", "read")
    got = sg.query_grid(seeds, q)

    interner = objs["doc"]
    for b, (_, u) in enumerate(subjects):
        want = set(e.lookup_resources("doc", "read", "user", u))
        got_ids = {
            interner.string(i)
            for i in np.flatnonzero(got[b]).tolist()
            if i >= 2 and i < len(interner)  # skip void/wildcard slots
        }
        assert got_ids == want, f"subject {u}: {got_ids} != {want}"


def test_sharded_contig_grid_promise_matches_flat():
    """The batcher's homogeneous-grid promise on the sharded backend must
    agree with the general flat path (which argsort-re-maps), including a
    malformed promise falling back rather than mis-slicing."""
    e, users = build_engine()
    cg = e.compiled()
    objs = e._objects_by_name()
    sg = ShardedGraph(cg, make_mesh(8, data=2, graph=4))
    off = cg.offset_of("doc", "read")
    n = cg.type_sizes["doc"]
    subs = [("user", users[0]), ("user", users[3]), ("user", "nobody")]
    seeds = np.asarray(
        [cg.encode_subject(t, i, None, objs) for (t, i) in subs],
        dtype=np.int32)
    qs = np.tile(off + np.arange(n, dtype=np.int32), len(subs))
    qb = np.repeat(np.arange(len(subs), dtype=np.int32), n)
    flat = sg.query_async(seeds, qs, qb).result()
    fast = sg.query_async(seeds, qs, qb,
                          q_contig_grid=(off, n, len(subs))).result()
    assert np.array_equal(flat, fast)
    assert flat[:n].any() and not flat[2 * n:].any()
    # wrong row count: promise declined, result still correct
    bad = sg.query_async(seeds, qs, qb,
                         q_contig_grid=(off, n, 2)).result()
    assert np.array_equal(bad, flat)


def test_sharded_check_grid_odd_shapes():
    e, users = build_engine(seed=11)
    cg = e.compiled()
    objs = e._objects_by_name()
    sg = ShardedGraph(cg, make_mesh(8, data=2, graph=4))

    # B=3 (not divisible by data axis), Q=5 (odd) — padding must handle it
    subjects = [("user", "u0"), ("user", "u3"), ("group", "g1")]
    checks = [("doc", f"d{i}", "read") for i in range(5)]
    seeds = np.asarray(
        [cg.encode_subject(t, i, "member" if t == "group" else None, objs)
         for (t, i) in subjects],
        dtype=np.int32,
    )
    q = np.asarray(
        [[cg.encode_target(rt, perm, rid, objs) for (rt, rid, perm) in checks]
         for _ in subjects],
        dtype=np.int32,
    )
    got = sg.query_grid(seeds, q)
    for b, (t, i) in enumerate(subjects):
        srel = "member" if t == "group" else None
        for qi, (rt, rid, perm) in enumerate(checks):
            want = e.check(CheckItem(rt, rid, perm, t, i, srel))
            assert bool(got[b, qi]) == want, (t, i, rt, rid)


def test_sharded_expiration_mask():
    import time

    now = time.time()
    e = Engine(schema=parse_schema(
        """
        definition user {}
        definition doc {
          relation reader: user with expiration
          permission read = reader
        }
        """
    ))
    from spicedb_kubeapi_proxy_tpu.models.tuples import Relationship

    e.write_relationships([
        WriteOp("touch", Relationship("doc", "live", "reader", "user", "u",
                                      expiration=now + 3600)),
        WriteOp("touch", Relationship("doc", "dead", "reader", "user", "u",
                                      expiration=now - 5)),
    ])
    cg = e.compiled()
    objs = e._objects_by_name()
    sg = ShardedGraph(cg, make_mesh(8))
    seeds = np.asarray([cg.encode_subject("user", "u", None, objs)],
                       dtype=np.int32)
    q = np.asarray([[cg.encode_target("doc", "read", "live", objs),
                     cg.encode_target("doc", "read", "dead", objs)]],
                   dtype=np.int32)
    got = sg.query_grid(seeds, q, now=now)
    assert got.tolist() == [[True, False]]


def test_sharded_sees_incremental_updates():
    """A ShardedGraph built from an incrementally-updated CompiledGraph
    folds the delta segment and dead-pair kills into its edge shards."""
    from spicedb_kubeapi_proxy_tpu.engine.store import RelationshipFilter
    from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

    e, users = build_engine(seed=23)
    e.compiled()
    c0 = metrics.counter("engine_graph_compiles_total").value

    # revoke one existing reader tuple and grant a new one — both must be
    # applied incrementally (no full recompile)
    existing = sorted(
        e.read_relationships(RelationshipFilter(
            resource_type="doc", relation="reader", subject_type="user")),
        key=str)[0]
    e.write_relationships([
        WriteOp("delete", existing),
        WriteOp("touch", parse_relationship("doc:d1#reader@user:u7")),
        WriteOp("touch", parse_relationship("group:g0#member@user:u6")),
    ])
    cg = e.compiled()
    assert metrics.counter("engine_graph_compiles_total").value == c0
    assert cg.n_delta >= 2 and len(cg.dead_pairs) >= 1

    objs = e._objects_by_name()
    sg = ShardedGraph(cg, make_mesh(8, data=2, graph=4))
    subjects = [("user", u) for u in users]
    seeds, q, _ = grid_for_lookup(cg, objs, subjects, "doc", "read")
    got = sg.query_grid(seeds, q)
    interner = objs["doc"]
    for b, (_, u) in enumerate(subjects):
        want = set(e.lookup_resources("doc", "read", "user", u))
        got_ids = {
            interner.string(i)
            for i in np.flatnonzero(got[b]).tolist()
            if i >= 2 and i < len(interner)
        }
        assert got_ids == want, f"subject {u}: {got_ids} != {want}"


def test_engine_mesh_routes_queries_through_sharded():
    """Engine(mesh=...) answers checks and lookups through the sharded
    backend — parity with a single-device engine over the same store,
    including dense MXU blocks inside the shard_map body and incremental
    writes after the first compile."""
    from spicedb_kubeapi_proxy_tpu.ops import reachability
    from spicedb_kubeapi_proxy_tpu.engine import CheckItem
    from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

    old_min = reachability.DENSE_MIN_EDGES
    reachability.DENSE_MIN_EDGES = 4  # force dense blocks at test scale
    try:
        mesh = make_mesh(8, data=2, graph=4)
        em, users = build_engine(seed=5)
        em.mesh = mesh  # build_engine has no mesh param; attach before use
        e1, _ = build_engine(seed=5)

        cg = em.compiled()
        assert cg.blocks, "need dense blocks to exercise the MXU path"
        sg = em._backend(cg)
        assert sg is not cg and sg._blocks, \
            "mesh engine must route through ShardedGraph with kept blocks"

        def parity():
            items = [
                CheckItem("doc", f"d{d}", "read", "user", u)
                for d in range(12) for u in users
            ]
            assert em.check_bulk(items) == e1.check_bulk(items)
            for u in users:
                assert sorted(em.lookup_resources("doc", "read", "user", u)) \
                    == sorted(e1.lookup_resources("doc", "read", "user", u))

        parity()
        # incremental writes rebuild the sharded view and stay exact
        c0 = metrics.counter("engine_graph_compiles_total").value
        for eng in (em, e1):
            eng.write_relationships([
                WriteOp("delete", parse_relationship("doc:d0#reader@user:u1"))
                for _ in range(1)] + [
                WriteOp("touch", parse_relationship("doc:d2#banned@user:u0")),
            ])
        parity()
        assert metrics.counter("engine_graph_compiles_total").value == c0
        sg2 = em._sharded
        assert sg2.cg is em.compiled()
        # the incremental sharded view reuses the jitted shard_map and the
        # resident base edge shards — no rebuild per write (src/dst shards
        # are shared; only killed levels' exp and the delta re-upload)
        assert sg2 is not sg and sg2._run is sg._run
        assert all(a[0] is b[0] and a[1] is b[1]
                   for a, b in zip(sg2._level_edges, sg._level_edges))
    finally:
        reachability.DENSE_MIN_EDGES = old_min


def test_proxy_with_engine_mesh(tmp_path):
    """Full proxy (rules, dual-write, list filtering) with the in-process
    engine spread over the virtual 8-device mesh."""
    import asyncio
    import json

    from spicedb_kubeapi_proxy_tpu.proxy.inmemory import InMemoryClient
    from spicedb_kubeapi_proxy_tpu.proxy.options import Options

    import os
    deploy = os.path.join(os.path.dirname(__file__), "..", "deploy")

    async def go():
        from fake_kube import FakeKube

        cfg = Options(
            rule_files=[os.path.join(deploy, "rules.yaml")],
            bootstrap_files=[os.path.join(deploy, "bootstrap.yaml")],
            upstream=FakeKube(),
            workflow_database_path=str(tmp_path / "dtx.sqlite"),
            engine_mesh="data=2,graph=4",
        ).complete()
        assert cfg.engine.mesh is not None
        await cfg.workflow.resume_pending()
        alice = InMemoryClient(cfg.server.handle, user="alice")
        bob = InMemoryClient(cfg.server.handle, user="bob")
        for ns in ("mesh-a", "mesh-b"):
            resp = await alice.post("/api/v1/namespaces", {
                "apiVersion": "v1", "kind": "Namespace",
                "metadata": {"name": ns}})
            assert resp.status == 201, resp.body
        resp = await alice.get("/api/v1/namespaces")
        assert sorted(o["metadata"]["name"]
                      for o in json.loads(resp.body)["items"]) \
            == ["mesh-a", "mesh-b"]
        resp = await bob.get("/api/v1/namespaces")
        assert json.loads(resp.body)["items"] == []
        resp = await alice.delete("/api/v1/namespaces/mesh-b")
        assert resp.status == 200
        resp = await alice.get("/api/v1/namespaces")
        assert [o["metadata"]["name"]
                for o in json.loads(resp.body)["items"]] == ["mesh-a"]
        await cfg.workflow.shutdown()
    asyncio.run(go())


CAVEAT_BOOTSTRAP = """
schema: |-
  use expiration
  caveat ip_allowlist(ip ipaddress, allowed list<ipaddress>) {
    ip in allowed
  }
  caveat win(now timestamp, until timestamp) { now < until }
  definition user {}
  definition doc {
    relation viewer: user | user with ip_allowlist
      | user with win | user with expiration
    permission view = viewer
  }
relationships: |-
  doc:readme#viewer@user:alice
  doc:readme#viewer@user:bob[ip_allowlist:{"allowed":["10.0.0.0/8"]}]
  doc:plan#viewer@user:bob[ip_allowlist:{"allowed":["192.168.0.0/16"]}]
"""


def test_sharded_caveated_matches_single_device_and_oracle():
    """Conditional grants evaluate ON the mesh: the caveat VM runs
    inside the shard_map body against replicated instance tables, so a
    caveated graph routes through ShardedGraph (no fallback) and its
    verdicts — satisfying context, non-matching context, and the
    fail-closed missing-context tri-state — are byte-identical to the
    single-device engine and the recursive oracle."""
    from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

    mesh = make_mesh(8, data=2, graph=4)
    em = Engine(bootstrap=CAVEAT_BOOTSTRAP, mesh=mesh)
    e1 = Engine(bootstrap=CAVEAT_BOOTSTRAP)
    fb0 = metrics.counter("engine_caveat_mesh_fallback_total").value
    items = [CheckItem("doc", d, "view", "user", u)
             for d in ("readme", "plan") for u in ("alice", "bob")]
    for ctx in ({"ip": "10.0.0.5"}, {"ip": "192.168.1.1"},
                {"ip": "8.8.8.8"}, None):
        got = em.check_bulk(items, context=ctx)
        assert got == e1.check_bulk(items, context=ctx), ctx
        o = em.oracle(context=ctx)
        assert got == [o.check(i.resource_type, i.resource_id,
                               i.permission, i.subject_type, i.subject_id)
                       for i in items], ctx
    # the mesh really served these: ShardedGraph built, zero fallbacks
    assert em._sharded is not None
    assert metrics.counter(
        "engine_caveat_mesh_fallback_total").value == fb0
    # contexted lookups agree too
    for ctx in ({"ip": "10.0.0.5"}, None):
        assert sorted(em.lookup_resources("doc", "view", "user", "bob",
                                          context=ctx)) == \
            sorted(e1.lookup_resources("doc", "view", "user", "bob",
                                       context=ctx))
    # missing context fails closed AND counts through the mesh path
    c0 = metrics.counter(
        "engine_caveat_denied_missing_context_total").value
    assert em.check_bulk(
        [CheckItem("doc", "readme", "view", "user", "bob")]) == [False]
    assert metrics.counter(
        "engine_caveat_denied_missing_context_total").value > c0


def test_sharded_caveated_incremental_churn_differential():
    """The ISSUE 15 parity bar: randomized caveated + expiring +
    plain-tuple churn (touches with reused AND new contexts, deletes,
    live/lapsed expirations) applied to a mesh engine and a
    single-device engine in lockstep on the forced 8-device host
    platform — after EVERY batch the verdicts are byte-identical to
    each other and to the recursive oracle, and steady churn stays on
    the incremental path (no per-write recompiles, resident shard
    reuse)."""
    import time as _time

    from spicedb_kubeapi_proxy_tpu.models.tuples import Relationship
    from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

    rng = np.random.default_rng(0xCAFE)
    mesh = make_mesh(8, data=2, graph=4)
    em = Engine(bootstrap=CAVEAT_BOOTSTRAP, mesh=mesh)
    e1 = Engine(bootstrap=CAVEAT_BOOTSTRAP)
    users = [f"u{i}" for i in range(6)]
    docs = [f"d{i}" for i in range(5)]
    ctxs = ['{"allowed":["10.0.0.0/8"]}',
            '{"allowed":["172.16.0.0/12"]}']
    req = {"ip": "10.5.5.5"}
    now_fixed = _time.time()

    def wr(op):
        for eng in (em, e1):
            eng.write_relationships([op])

    # warm both engines (first compile + first sharded build)
    em.check_bulk([CheckItem("doc", "readme", "view", "user", "alice")],
                  context=req, now=now_fixed)
    e1.check_bulk([CheckItem("doc", "readme", "view", "user", "alice")],
                  context=req, now=now_fixed)
    compiles0 = metrics.counter("engine_graph_compiles_total").value
    fb0 = metrics.counter("engine_caveat_mesh_fallback_total").value
    live: list[Relationship] = []
    for step in range(16):
        kind = int(rng.integers(4))
        d = docs[int(rng.integers(len(docs)))]
        u = users[int(rng.integers(len(users)))]
        if kind == 0 and live:  # delete an existing churn tuple
            wr(WriteOp("delete", live.pop(int(rng.integers(len(live))))))
        elif kind == 1:  # caveated touch, contexts mostly reused
            ctx = ctxs[int(rng.integers(len(ctxs)))]
            rel = Relationship("doc", d, "viewer", "user", u, None, None,
                               "ip_allowlist", ctx)
            wr(WriteOp("touch", rel))
            live.append(rel)
        elif kind == 2:  # expiring grant, alive or lapsed at the clock
            exp = now_fixed + (300.0 if rng.random() < 0.5 else -300.0)
            rel = Relationship("doc", d, "viewer", "user", u,
                               expiration=exp)
            wr(WriteOp("touch", rel))
            live.append(rel)
        else:  # plain grant
            rel = Relationship("doc", d, "viewer", "user", u)
            wr(WriteOp("touch", rel))
            live.append(rel)
        items = [CheckItem("doc", dd, "view", "user", uu)
                 for dd in docs for uu in users]
        for ctx in (req, None):
            got = em.check_bulk(items, context=ctx, now=now_fixed)
            want = e1.check_bulk(items, context=ctx, now=now_fixed)
            assert got == want, (step, ctx)
            o = em.oracle(now=now_fixed, context=ctx)
            assert got == [o.check(i.resource_type, i.resource_id,
                                   i.permission, i.subject_type,
                                   i.subject_id) for i in items], \
                (step, ctx)
        assert sorted(em.lookup_resources(
            "doc", "view", "user", u, now=now_fixed, context=req)) == \
            sorted(e1.lookup_resources(
                "doc", "view", "user", u, now=now_fixed, context=req)), \
            step
    # reused contexts ride the overlay: no per-write full recompiles
    # (the churn's distinct contexts at most add instance rows once),
    # and a caveated graph NEVER fell back off the mesh
    assert metrics.counter("engine_graph_compiles_total").value \
        <= compiles0 + 2
    assert metrics.counter(
        "engine_caveat_mesh_fallback_total").value == fb0


def test_sharded_updated_carries_caveat_instance_append():
    """A caveated write with a NEW (caveat, context) pair rides the
    incremental path (spare instance row) and ShardedGraph.updated()
    patches the REPLICATED context tables in place: no recompile, no
    sharded rebuild, and the new conditional grant answers correctly
    under both polarities of request context."""
    from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship
    from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

    mesh = make_mesh(8, data=2, graph=4)
    em = Engine(bootstrap=CAVEAT_BOOTSTRAP, mesh=mesh)
    em.check_bulk([CheckItem("doc", "readme", "view", "user", "bob")],
                  context={"ip": "10.0.0.1"})
    sg0 = em._sharded
    assert sg0 is not None
    compiles0 = metrics.counter("engine_graph_compiles_total").value
    upd0 = metrics.counter("engine_sharded_updates_total").value
    em.write_relationships([WriteOp("touch", parse_relationship(
        'doc:memo#viewer@user:carol'
        '[ip_allowlist:{"allowed":["172.16.0.0/12"]}]'))])
    item = CheckItem("doc", "memo", "view", "user", "carol")
    assert em.check_bulk([item], context={"ip": "172.16.9.9"}) == [True]
    assert em.check_bulk([item], context={"ip": "10.0.0.1"}) == [False]
    assert em.check_bulk([item]) == [False]  # missing ctx: fail closed
    assert metrics.counter("engine_graph_compiles_total").value \
        == compiles0, "instance append must not recompile"
    assert metrics.counter("engine_sharded_updates_total").value > upd0
    sg1 = em._sharded
    assert sg1 is not sg0 and sg1._run is sg0._run, \
        "updated() must reuse the jitted shard_map"
    assert sg1._applied_inst != sg0._applied_inst, \
        "the replicated instance tables must have advanced"
    assert em.oracle(context={"ip": "172.16.9.9"}).check(
        "doc", "memo", "view", "user", "carol")


def test_sharded_kstep_fuses_convergence_checks():
    """K-step fused fixpoint: the mesh runs K propagation steps per
    convergence collective, so a query pays ceil(iters/K)+<=1 checks
    instead of one per hop — counted via conv_checks() and compared
    against the single-device iteration count for the SAME query, with
    identical results. iterations() reports the TRUE converged-at step
    (the per-step change flags survive the fuse as a [K] pmax vector),
    not the K-quantized budget the pre-semiring future reported."""
    e, users = build_engine(seed=3)
    cg = e.compiled()
    objs = e._objects_by_name()
    sg = ShardedGraph(cg, make_mesh(8, data=2, graph=4))
    assert sg.k_steps >= 2
    off = cg.offset_of("doc", "read")
    n = cg.type_sizes["doc"]
    seeds = np.asarray([cg.encode_subject("user", users[0], None, objs)],
                       dtype=np.int32)
    qs = off + np.arange(n, dtype=np.int32)
    qb = np.zeros(n, dtype=np.int32)
    f1 = cg.query_async(seeds, qs, qb)
    want = f1.result()
    iters_single = f1.iterations()
    fm = sg.query_async(seeds, qs, qb)
    got = fm.result()
    assert np.array_equal(got, want)
    checks = fm.conv_checks()
    # the relative pin from ISSUE 15: at most one confirming block past
    # the single-device iteration count, and strictly fewer collectives
    # than one-per-hop whenever the query iterates past one block
    assert 1 <= checks <= -(-iters_single // sg.k_steps) + 1
    # the ISSUE 17 fix: no more "budget consumed, a multiple of K" —
    # the mesh future reports the same converged-at step the
    # single-device future does, and the checks stay fused
    assert fm.iterations() == iters_single
    assert fm.iterations() <= checks * sg.k_steps
    if iters_single > sg.k_steps:
        assert checks < iters_single
    # explicit K override is honored and stays exact
    sg4 = ShardedGraph(cg, make_mesh(8, data=2, graph=4), k_steps=4)
    f4 = sg4.query_async(seeds, qs, qb)
    assert np.array_equal(f4.result(), want)
    assert f4.conv_checks() <= -(-iters_single // 4) + 1
    assert f4.iterations() == iters_single


def test_sharded_refuses_unstratified_caveated_graph():
    """The one genuinely unsupported mesh shape: a caveated graph
    without per-edge caveat rows (hand-built unstratified layout) must
    be refused — serving it would drop the caveat mask (fail open) —
    and Engine._backend counts the fallback."""
    from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

    em = Engine(bootstrap=CAVEAT_BOOTSTRAP)
    cg = em.compiled()
    assert ShardedGraph.unsupported_reason(cg) is None
    import dataclasses

    bare = dataclasses.replace(cg, res_src=None, res_dst=None,
                               res_exp=None, res_cav=None,
                               res_level_bounds=None, _device={})
    assert ShardedGraph.unsupported_reason(bare) is not None
    with pytest.raises(ValueError, match="cannot serve"):
        ShardedGraph(bare, make_mesh(8))
    # the engine routes it to the single-device path, counted
    em2 = Engine(bootstrap=CAVEAT_BOOTSTRAP, mesh=make_mesh(8))
    fb0 = metrics.counter("engine_caveat_mesh_fallback_total").value
    backend = em2._backend(bare)
    assert backend is bare
    assert metrics.counter(
        "engine_caveat_mesh_fallback_total").value == fb0 + 1


def test_mesh_topology_label():
    from spicedb_kubeapi_proxy_tpu.parallel.mesh import mesh_topology

    t = mesh_topology(make_mesh(8, data=2, graph=4))
    assert t == {"devices": 8, "data": 2, "graph": 4, "platform": "cpu"}


def test_mesh_spec_parsing():
    from spicedb_kubeapi_proxy_tpu.proxy.options import (
        Options, OptionsError, _parse_mesh_spec)

    assert _parse_mesh_spec("auto") == {}
    assert _parse_mesh_spec("data=2,graph=4") == {"data": 2, "graph": 4}
    assert _parse_mesh_spec("graph=8") == {"graph": 8}
    for bad in ("nope", "data=x", "data=0", "rows=2"):
        with pytest.raises(OptionsError):
            _parse_mesh_spec(bad)
    with pytest.raises(OptionsError, match="engine-mesh applies"):
        Options(engine_endpoint="tcp://h:1", engine_mesh="auto",
                rule_content="x", upstream_url="http://u").validate()


def test_sharded_update_after_recompile_with_equal_signature():
    """REGRESSION (found in round 4, present since round 3): a write that
    forces a FULL recompile can leave the new graph with a signature
    equal to the old one (bucket padding absorbs small edge-count
    changes) while folding the delta into NEW base arrays.
    ShardedGraph.updated() used to treat signature equality as
    incremental descent and kept the old resident shards — silently
    answering stale DENIALS for the new edge. The guard is base-array
    object identity."""
    import numpy as np

    from spicedb_kubeapi_proxy_tpu.engine import CheckItem, Engine, WriteOp
    from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship
    from spicedb_kubeapi_proxy_tpu.parallel import make_mesh
    from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

    import jax

    mesh = make_mesh(4, devices=jax.devices()[:4])
    rng = np.random.default_rng(7)
    rels = [f"namespace:n{i}#creator@user:u{int(rng.integers(50))}"
            for i in range(300)]
    em = Engine(mesh=mesh)
    em.write_relationships(
        [WriteOp("touch", parse_relationship(r)) for r in rels])
    item = CheckItem("namespace", "n1", "view", "user", "u49")
    assert em.check_bulk([item]) == [False]
    upd0 = metrics.counter("engine_sharded_updates_total").value
    # first-ever viewer edge: incremental_update declines (layout), the
    # engine recompiles, and the recompiled graph's signature happens to
    # equal the old one
    em.write_relationships([WriteOp("touch", parse_relationship(
        "namespace:n1#viewer@user:u49"))])
    got = em.check_bulk([item])
    assert got == [True], \
        "stale sharded shards after an equal-signature recompile"
    assert em.oracle().check("namespace", "n1", "view", "user", "u49")
    assert metrics.counter("engine_sharded_updates_total").value > upd0


def test_sharded_incremental_interleaving_fuzz():
    """Adversarial fuzz over the incremental/recompile boundary the
    stale-shards regression lived on: random touches, deletes, NEW
    relations, NEW objects, and expiring grants interleaved with queries,
    asserting mesh-engine == single-device == oracle after every batch.
    Each step may take the incremental path, the equal-signature
    recompile path, or a layout-changing recompile — the engines must be
    indistinguishable through all of them."""
    import numpy as np

    from spicedb_kubeapi_proxy_tpu.engine import CheckItem, Engine, WriteOp
    from spicedb_kubeapi_proxy_tpu.models.tuples import (
        Relationship,
        parse_relationship,
    )
    from spicedb_kubeapi_proxy_tpu.parallel import make_mesh

    import jax

    rng = np.random.default_rng(0xFADE)
    mesh = make_mesh(4, devices=jax.devices()[:4])
    bootstrap = """
schema: |-
  use expiration

  definition cluster {}
  definition user {}
  definition namespace {
    relation cluster: cluster
    relation creator: user
    relation viewer: user | user with expiration
    permission admin = creator
    permission view = viewer + creator
  }
  definition pod {
    relation namespace: namespace
    relation creator: user
    relation viewer: user
    permission view = viewer + creator + namespace->view
  }
relationships: ""
"""
    em = Engine(bootstrap=bootstrap, mesh=mesh)
    e1 = Engine(bootstrap=bootstrap)
    live: list[str] = []

    def wr(ops):
        for eng in (em, e1):
            eng.write_relationships(ops)

    # seed
    seed = [f"namespace:n{i}#creator@user:u{int(rng.integers(12))}"
            for i in range(40)]
    wr([WriteOp("touch", parse_relationship(r)) for r in seed])
    live += seed

    now_fixed = 1_700_000_000.0
    for step in range(14):
        kind = rng.integers(5)
        if kind == 0 and live:  # delete an existing edge
            r = live.pop(int(rng.integers(len(live))))
            wr([WriteOp("delete", parse_relationship(r))])
        elif kind == 1:  # touch within existing types/objects
            r = (f"namespace:n{int(rng.integers(40))}#viewer"
                 f"@user:u{int(rng.integers(12))}")
            wr([WriteOp("touch", parse_relationship(r))])
            live.append(r)
        elif kind == 2:  # NEW object id (bucket growth possible)
            r = (f"namespace:fresh-{step}#creator"
                 f"@user:new-u{step}")
            wr([WriteOp("touch", parse_relationship(r))])
            live.append(r)
        elif kind == 3:  # first-ever edges of a relation (layout change)
            r = (f"pod:n{int(rng.integers(40))}/p{step}#viewer"
                 f"@user:u{int(rng.integers(12))}")
            wr([WriteOp("touch", parse_relationship(r))])
            live.append(r)
        else:  # expiring grant, alive or lapsed at the query clock
            exp = now_fixed + (300.0 if rng.random() < 0.5 else -300.0)
            wr([WriteOp("touch", Relationship(
                "namespace", f"n{int(rng.integers(40))}", "viewer",
                "user", f"u{int(rng.integers(12))}", expiration=exp))])
        items = [
            CheckItem("namespace", f"n{int(i)}", "view", "user",
                      f"u{int(u)}")
            for i, u in zip(rng.integers(42, size=12),
                            rng.integers(12, size=12))
        ]
        got = em.check_bulk(items, now=now_fixed)
        want = e1.check_bulk(items, now=now_fixed)
        assert got == want, (step, got, want)
        oracle = em.oracle(now=now_fixed)
        for it, g in zip(items, got):
            assert g == oracle.check(it.resource_type, it.resource_id,
                                     it.permission, it.subject_type,
                                     it.subject_id), (step, it)
        u = f"u{int(rng.integers(12))}"
        assert sorted(em.lookup_resources(
            "namespace", "view", "user", u, now=now_fixed)) == \
            sorted(e1.lookup_resources(
                "namespace", "view", "user", u, now=now_fixed)), step


def test_watch_over_engine_mesh(tmp_path):
    """A live watch stream with the engine sharded over the virtual
    8-device mesh: grants flowing through dual-writes must reach the
    watcher via the hub's recompute path (which dispatches sharded grid
    queries), completing the mesh-engine coverage beyond list/get."""
    import asyncio
    import json
    import os

    from fake_kube import FakeKube, serve_upstream
    from test_proxy_server import HttpClient, RULES

    from spicedb_kubeapi_proxy_tpu.proxy.options import Options

    async def go():
        fake = FakeKube()
        upstream_server, upstream_port = await serve_upstream(fake)
        cfg = Options(
            rule_content=RULES,
            upstream_url=f"http://127.0.0.1:{upstream_port}",
            workflow_database_path=str(tmp_path / "dtx.sqlite"),
            bind_port=0,
            engine_mesh="data=2,graph=4",
        ).complete()
        assert cfg.engine.mesh is not None
        await cfg.run()
        alice = HttpClient(cfg.server.port, "alice")
        status, _, _ = await alice.request(
            "POST", "/api/v1/namespaces",
            body={"apiVersion": "v1", "kind": "Namespace",
                  "metadata": {"name": "mw-a"}})
        assert status == 201
        status, headers, (reader, writer) = await alice.request(
            "GET", "/api/v1/namespaces?watch=true", stream=True)
        assert status == 200
        first = await asyncio.wait_for(alice.read_chunk(reader), timeout=15)
        ev = json.loads(first)
        assert (ev["type"], ev["object"]["metadata"]["name"]) \
            == ("ADDED", "mw-a")
        status, _, _ = await alice.request(
            "POST", "/api/v1/namespaces",
            body={"apiVersion": "v1", "kind": "Namespace",
                  "metadata": {"name": "mw-b"}})
        assert status == 201
        nxt = await asyncio.wait_for(alice.read_chunk(reader), timeout=15)
        assert json.loads(nxt)["object"]["metadata"]["name"] == "mw-b"
        writer.close()
        fake.stop_watches()
        await cfg.server.stop()
        await cfg.workflow.shutdown()
        upstream_server.close()
    asyncio.run(go())


def test_semiring_push_pull_differential_churn(monkeypatch):
    """The ISSUE 17 parity bar for the masked-semiring core: forced
    push, forced pull, and auto mode agree byte-identically with each
    other and with the recursive oracle at EVERY churn step, on BOTH
    backends, with real dense blocks and bit-packed duals in play
    (interpret-mode kernels on the CPU host platform) while expiring +
    caveated + plain tuples churn through the incremental overlay."""
    import time as _time

    from spicedb_kubeapi_proxy_tpu.models.tuples import Relationship
    from spicedb_kubeapi_proxy_tpu.ops import reachability, semiring

    # interpret-mode bit kernel + a low dense threshold: the small test
    # graph forms real dense blocks WITH bit duals, so push and pull are
    # genuinely different code paths here, not the same fallback
    monkeypatch.setenv("SDBKP_BITPROP", "interpret")
    monkeypatch.setattr(reachability, "DENSE_MIN_EDGES", 8)

    rng = np.random.default_rng(0x5E31)
    users = [f"u{i}" for i in range(7)]
    docs = [f"d{i}" for i in range(10)]
    engines = {"single": Engine(bootstrap=CAVEAT_BOOTSTRAP),
               "mesh": Engine(bootstrap=CAVEAT_BOOTSTRAP,
                              mesh=make_mesh(8, data=2, graph=4))}
    seed_rels = [f"doc:{d}#viewer@user:{u}"
                 for d in docs for u in users if hash((d, u)) % 2]
    for e in engines.values():
        e.write_relationships(touch(*seed_rels))
    cg = engines["single"].compiled()
    assert cg.blocks, "differential needs at least one dense block"
    assert any(b is not None
               for b in cg._dev()["blocks_bits"]), \
        "differential needs a bit-packed dual (real push path)"

    now_fixed = _time.time()
    req = {"ip": "10.5.5.5"}
    ctxs = ['{"allowed":["10.0.0.0/8"]}', '{"allowed":["172.16.0.0/12"]}']
    items = [CheckItem("doc", d, "view", "user", u)
             for d in docs for u in users]
    live: list[Relationship] = []
    for step in range(6):
        kind = int(rng.integers(4))
        d = docs[int(rng.integers(len(docs)))]
        u = users[int(rng.integers(len(users)))]
        if kind == 0 and live:
            op = WriteOp("delete", live.pop(int(rng.integers(len(live)))))
        elif kind == 1:
            rel = Relationship("doc", d, "viewer", "user", u, None, None,
                               "ip_allowlist",
                               ctxs[int(rng.integers(len(ctxs)))])
            live.append(rel)
            op = WriteOp("touch", rel)
        elif kind == 2:
            exp = now_fixed + (300.0 if rng.random() < 0.5 else -300.0)
            rel = Relationship("doc", d, "viewer", "user", u,
                               expiration=exp)
            live.append(rel)
            op = WriteOp("touch", rel)
        else:
            rel = Relationship("doc", d, "viewer", "user", u)
            live.append(rel)
            op = WriteOp("touch", rel)
        for e in engines.values():
            e.write_relationships([op])
        for ctx in (req, None):
            o = engines["single"].oracle(now=now_fixed, context=ctx)
            want = [o.check(i.resource_type, i.resource_id, i.permission,
                            i.subject_type, i.subject_id) for i in items]
            for mode in ("pull", "push", "auto"):
                with semiring.force_mode(mode):
                    for name, e in engines.items():
                        got = e.check_bulk(items, context=ctx,
                                           now=now_fixed)
                        assert got == want, (step, ctx, mode, name)
