"""Device-side caveat evaluation (ISSUE 9): the vectorized expression VM
vs the pure-Python AST interpreter (randomized differential), tri-state
missing-context semantics, expiry interaction, tuple-context round-trip
properties, decision-cache context digests, incremental caveated writes,
the remote wire's ctx field, and the end-to-end IP-allowlist /
time-window scenarios through the proxy middleware."""

import asyncio
import json
import random
import time

import numpy as np
import pytest

from spicedb_kubeapi_proxy_tpu.authz import AuthzDeps, authorize
from spicedb_kubeapi_proxy_tpu.caveats.ast import (
    Bin,
    CaveatDef,
    CaveatError,
    CaveatParam,
    CaveatType,
    Lit,
    StringInterner,
    Un,
    Var,
    interpret,
    parse_caveat_body,
)
from spicedb_kubeapi_proxy_tpu.caveats.compile import compile_caveat
from spicedb_kubeapi_proxy_tpu.caveats.vm import (
    build_caveat_table,
    eval_caveats,
)
from spicedb_kubeapi_proxy_tpu.engine import CheckItem, Engine, WriteOp
from spicedb_kubeapi_proxy_tpu.engine.engine import SchemaViolation
from spicedb_kubeapi_proxy_tpu.models.bootstrap import parse_bootstrap
from spicedb_kubeapi_proxy_tpu.models.schema import (
    SchemaError,
    parse_schema,
)
from spicedb_kubeapi_proxy_tpu.models.tuples import (
    Relationship,
    TupleError,
    canonical_context,
    parse_relationship,
)
from spicedb_kubeapi_proxy_tpu.proxy.requestinfo import parse_request_info
from spicedb_kubeapi_proxy_tpu.proxy.types import ProxyRequest, json_response
from spicedb_kubeapi_proxy_tpu.rules import MapMatcher
from spicedb_kubeapi_proxy_tpu.rules.input import UserInfo
from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics


# -- grammar / compiler -------------------------------------------------------


def test_parse_precedence_and_fold():
    e = parse_caveat_body("1 + 2 * 3 == 7 && !(false)")
    d = CaveatDef("t", (), e)
    prog = compile_caveat(d, StringInterner())
    # fully constant: folds to one CONST true
    assert len(prog.ops) == 1
    i = StringInterner()
    assert interpret(e, {}, {}, i) is True


def test_compiler_rejects_malformed():
    p_str = CaveatParam("day", CaveatType("string"))
    p_list = CaveatParam("tags", CaveatType("list", "string"))
    for body, params in [
        ("day + 1 == 2", (p_str,)),       # string arithmetic
        ("day < 'x'", (p_str,)),          # ordered string comparison
        ("tags == tags", (p_list,)),      # list outside 'in'
        ("nope == 1", ()),                # unknown parameter
        ("1 + 1", ()),                    # non-boolean body
        ("day in day", (p_str,)),         # 'in' needs a list rhs
    ]:
        with pytest.raises(CaveatError):
            compile_caveat(
                CaveatDef("t", params, parse_caveat_body(body)),
                StringInterner())


def test_schema_parses_typed_caveats_and_validates():
    s = parse_schema("""
    caveat ipal(ip ipaddress, allowed list<ipaddress>) { ip in allowed }
    caveat win(now timestamp, start timestamp, end timestamp) {
      now >= start && now < end
    }
    definition user {}
    definition doc {
      relation viewer: user | user with ipal
      permission view = viewer
    }
    """)
    assert set(s.caveat_defs) == {"ipal", "win"}
    ipal = s.caveat_defs["ipal"]
    assert [str(p.type) for p in ipal.params] == \
        ["ipaddress", "list<ipaddress>"]
    with pytest.raises(SchemaError, match="duplicate caveat"):
        parse_schema("caveat c(a int) { a == 1 }\n"
                     "caveat c(b int) { b == 1 }\ndefinition user {}")
    with pytest.raises(SchemaError):  # malformed body fails the PARSE
        parse_schema("caveat c(day string) { day + 1 == 2 }\n"
                     "definition user {}")
    with pytest.raises(SchemaError, match="parameter type"):
        parse_schema("caveat c(x frobnicator) { true }\ndefinition u {}")


# -- randomized differential: VM vs interpreter -------------------------------

_BASE_TS = 1_700_000_000.0

_PARAMS = (
    CaveatParam("a", CaveatType("int")),
    CaveatParam("b", CaveatType("int")),
    CaveatParam("day", CaveatType("string")),
    CaveatParam("ip", CaveatType("ipaddress")),
    CaveatParam("allowed", CaveatType("list", "ipaddress")),
    CaveatParam("tags", CaveatType("list", "string")),
    CaveatParam("now", CaveatType("timestamp")),
    CaveatParam("start", CaveatType("timestamp")),
)


def _gen_num(rng, depth):
    if depth <= 0 or rng.random() < 0.4:
        if rng.random() < 0.5:
            return Lit(float(rng.randint(-40, 40)), "double")
        return Var(rng.choice(["a", "b"]))
    op = rng.choice(["+", "-", "*"])
    return Bin(op, _gen_num(rng, depth - 1), _gen_num(rng, depth - 1))


def _gen_bool(rng, depth):
    r = rng.random()
    if depth <= 0 or r < 0.25:
        kind = rng.randrange(5)
        if kind == 0:
            return Bin(rng.choice(["==", "!=", "<", "<=", ">", ">="]),
                       _gen_num(rng, 1), _gen_num(rng, 1))
        if kind == 1:
            return Bin("==", Var("day"),
                       Lit(rng.choice(["mon", "tue", "wed"]), "string"))
        if kind == 2:
            return Bin("in", Var("ip"), Var("allowed"))
        if kind == 3:
            return Bin("in", Var("day"), Var("tags"))
        return Bin(rng.choice(["<", ">=", "=="]), Var("now"),
                   Var("start"))
    if r < 0.4:
        return Un("!", _gen_bool(rng, depth - 1))
    return Bin(rng.choice(["&&", "||"]),
               _gen_bool(rng, depth - 1), _gen_bool(rng, depth - 1))


def _rand_ctx(rng, full=False):
    ctx = {}
    p = 1.0 if full else 0.65

    def coin():
        return rng.random() < p

    if coin():
        ctx["a"] = rng.randint(-40, 40)
    if coin():
        ctx["b"] = rng.randint(-40, 40)
    if coin():
        ctx["day"] = rng.choice(["mon", "tue", "wed", "thu"])
    if coin():
        ctx["ip"] = "10.%d.%d.%d" % (rng.randrange(3), rng.randrange(3),
                                     rng.randrange(4))
    if coin():
        ctx["allowed"] = rng.sample(
            ["10.0.0.0/24", "10.1.0.0/16", "10.2.2.2", "10.0.1.3"],
            k=rng.randint(1, 3))
    if coin():
        ctx["tags"] = rng.sample(["mon", "tue", "xyz"],
                                 k=rng.randint(1, 2))
    if coin():
        ctx["now"] = _BASE_TS + rng.randint(-500, 500)
    if coin():
        ctx["start"] = _BASE_TS + rng.randint(-500, 500)
    return ctx


def test_vm_matches_interpreter_randomized():
    """The acceptance differential: for random expressions, random
    tuple contexts, and random request contexts, the vectorized VM's
    per-instance tri-state equals the scalar interpreter's — allow,
    deny, AND missing-context."""
    rng = random.Random(20260803)
    params = {p.name: p.type for p in _PARAMS}
    for trial in range(10):
        expr = _gen_bool(rng, 3)
        defn = CaveatDef("c", _PARAMS, expr)
        tuple_ctxs = [_rand_ctx(rng) for _ in range(6)]
        inst = [("", "")] + [
            ("c", canonical_context(c) or "") for c in tuple_ctxs]
        table = build_caveat_table({"c": defn}, inst,
                                   np.arange(1, len(inst)))
        stat = table.device_static()
        for _ in range(4):
            req_ctx = _rand_ctx(rng)
            req_ctx.setdefault("now", _BASE_TS)  # symmetric injection
            req, _ts = table.encode_request(req_ctx, _BASE_TS)
            ok, missing = eval_caveats(table.metas, stat, req,
                                       table.n_rows)
            ok = np.asarray(ok)
            n_missing = 0
            for i, tctx in enumerate(tuple_ctxs):
                merged = dict(req_ctx)
                merged.update(tctx)  # tuple context wins
                want = interpret(expr, merged, params, table.interner)
                row = int(table.inst_row[1 + i])
                got_allow = bool(ok[row])
                assert got_allow == (want is True), (
                    f"trial {trial}: expr {expr} ctx {merged} "
                    f"want {want} got allow={got_allow}")
                if want is None:
                    n_missing += 1
            assert int(missing) == n_missing


def test_division_by_zero_is_missing_context():
    params = (CaveatParam("a", CaveatType("int")),
              CaveatParam("b", CaveatType("int")))
    defn = CaveatDef("d", params, parse_caveat_body("a / b >= 1"))
    inst = [("", ""), ("d", canonical_context({"a": 4}))]
    table = build_caveat_table({"d": defn}, inst, np.array([1]))
    stat = table.device_static()
    pmap = {p.name: p.type for p in params}
    for b, want in [(2, True), (8, False), (0, None)]:
        req, _ = table.encode_request({"b": b}, 0.0)
        ok, missing = eval_caveats(table.metas, stat, req, table.n_rows)
        assert bool(np.asarray(ok)[1]) == (want is True)
        assert int(missing) == (1 if want is None else 0)
        assert interpret(defn.expr, {"a": 4, "b": b}, pmap,
                         table.interner) is want


def test_unseen_request_strings_never_compare_equal():
    """Two DIFFERENT strings that appear in no tuple context or literal
    must get DISTINCT codes — a shared match-all sentinel would make
    `user == owner` grant for arbitrary non-matching values (fail
    open). Review finding regression."""
    params = (CaveatParam("user", CaveatType("string")),
              CaveatParam("owner", CaveatType("string")))
    defn = CaveatDef("own", params, parse_caveat_body("user == owner"))
    inst = [("", ""), ("own", "")]  # context-free instance: both
    #                                 parameters come from the request
    table = build_caveat_table({"own": defn}, inst, np.array([1]))
    stat = table.device_static()
    pmap = {p.name: p.type for p in params}
    for ctx, want in [({"user": "mallory", "owner": "prod"}, False),
                      ({"user": "same", "owner": "same"}, True)]:
        req, _ = table.encode_request(ctx, 0.0)
        ok, _m = eval_caveats(table.metas, stat, req, table.n_rows)
        assert bool(np.asarray(ok)[1]) is want, ctx
        assert interpret(defn.expr, ctx, pmap, table.interner) is want
    # membership over unseen strings: no cross-matching either
    defn2 = CaveatDef("mem", (CaveatParam("u", CaveatType("string")),
                              CaveatParam("us", CaveatType("list",
                                                           "string"))),
                      parse_caveat_body("u in us"))
    t2 = build_caveat_table({"mem": defn2}, [("", ""), ("mem", "")],
                            np.array([1]))
    s2 = t2.device_static()
    req, _ = t2.encode_request({"u": "eve", "us": ["adam", "bob"]}, 0.0)
    ok, _m = eval_caveats(t2.metas, s2, req, t2.n_rows)
    assert not bool(np.asarray(ok)[1])
    req, _ = t2.encode_request({"u": "bob", "us": ["adam", "bob"]}, 0.0)
    ok, _m = eval_caveats(t2.metas, s2, req, t2.n_rows)
    assert bool(np.asarray(ok)[1])


def test_literal_cidr_list_engine_oracle_parity():
    """A CONSTANT CIDR allowlist in the caveat body (not a parameter)
    must evaluate as IP ranges in both the VM and the oracle
    interpreter. Review finding regression (the oracle used to compare
    interner codes)."""
    e = Engine(bootstrap="""
schema: |-
  caveat vpn_only(ip ipaddress) { ip in ["10.8.0.0/16", "172.16.0.9"] }
  definition user {}
  definition doc {
    relation viewer: user with vpn_only
    permission view = viewer
  }
relationships: |-
  doc:d#viewer@user:u[vpn_only]
""")
    u = CheckItem("doc", "d", "view", "user", "u")
    for ip, want in [("10.8.3.4", True), ("10.9.0.1", False),
                     ("172.16.0.9", True), ("172.16.0.8", False),
                     ("0.0.0.3", False)]:
        ctx = {"ip": ip}
        got = e.check(u, context=ctx)
        assert got is want, (ip, got)
        assert e.oracle(context=ctx).check(
            "doc", "d", "view", "user", "u") is want, ip


def test_request_list_capacity_floor():
    """Request-only list parameters (no tuple-side sizing signal, e.g.
    the middleware's `groups`) must accept realistic lengths instead of
    silently going missing-context at 5 elements."""
    defn = CaveatDef(
        "grp", (CaveatParam("team", CaveatType("string")),
                CaveatParam("groups", CaveatType("list", "string"))),
        parse_caveat_body("team in groups"))
    table = build_caveat_table({"grp": defn}, [("", ""), ("grp",
                               canonical_context({"team": "g7"}))],
                               np.array([1]))
    stat = table.device_static()
    groups = [f"g{i}" for i in range(12)]  # > the old floor of 4
    req, _ = table.encode_request({"groups": groups}, 0.0)
    ok, missing = eval_caveats(table.metas, stat, req, table.n_rows)
    assert bool(np.asarray(ok)[1]) and int(missing) == 0


# -- engine: tri-state, expiry interaction, metrics ---------------------------

IP_BOOT = """
schema: |-
  use expiration
  caveat ip_allowlist(ip ipaddress, allowed list<ipaddress>) {
    ip in allowed
  }
  definition user {}
  definition doc {
    relation viewer: user | user with ip_allowlist and expiration
    permission view = viewer
  }
relationships: |-
  doc:readme#viewer@user:alice
  doc:readme#viewer@user:bob[ip_allowlist:{"allowed":["10.0.0.0/8"]}]
"""


def test_missing_context_fails_closed_and_counts():
    e = Engine(bootstrap=IP_BOOT)
    c0 = metrics.counter(
        "engine_caveat_denied_missing_context_total").value
    bob = CheckItem("doc", "readme", "view", "user", "bob")
    assert not e.check(bob)  # no ip: fail closed
    assert metrics.counter(
        "engine_caveat_denied_missing_context_total").value > c0
    assert e.check(bob, context={"ip": "10.2.3.4"})
    assert not e.check(bob, context={"ip": "11.2.3.4"})
    # context with a malformed value is missing context, not an error
    assert not e.check(bob, context={"ip": "not-an-ip"})


def test_caveat_and_expiry_interaction():
    e = Engine(bootstrap=IP_BOOT)
    soon = time.time() + 0.8
    rel = Relationship("doc", "readme", "viewer", "user", "carol", None,
                       soon, "ip_allowlist",
                       canonical_context({"allowed": ["10.0.0.0/8"]}))
    e.write_relationships([WriteOp("touch", rel)])
    carol = CheckItem("doc", "readme", "view", "user", "carol")
    ctx = {"ip": "10.1.1.1"}
    # live + satisfying context -> allow; live + missing -> deny
    assert e.check(carol, now=soon - 0.5, context=ctx)
    assert not e.check(carol, now=soon - 0.5)
    # expired -> deny even with a satisfying context
    assert not e.check(carol, now=soon + 0.5, context=ctx)
    # oracle agrees on every cell
    for now, c in [(soon - 0.5, ctx), (soon - 0.5, None),
                   (soon + 0.5, ctx)]:
        o = e.oracle(now=now, context=c)
        assert o.check("doc", "readme", "view", "user", "carol") == \
            e.check(carol, now=now, context=c)


def test_prefiltered_lookup_and_lookup_subjects_with_context():
    e = Engine(bootstrap=IP_BOOT)
    assert e.lookup_resources("doc", "view", "user", "bob",
                              context={"ip": "10.0.0.1"}) == ["readme"]
    assert e.lookup_resources("doc", "view", "user", "bob",
                              context={"ip": "172.16.0.1"}) == []
    subs = e.lookup_subjects("doc", "readme", "view", "user",
                             context={"ip": "10.0.0.1"})
    assert subs == ["alice", "bob"]
    subs = e.lookup_subjects("doc", "readme", "view", "user")
    assert subs == ["alice"]  # conditional grant missing context


def test_batched_lookup_counts_missing_context():
    """Context-free lookups FUSE through the batcher (the watch-hub
    recompute path): their fail-closed conditional denials must tick
    the missing-context counter like every other path."""
    e = Engine(bootstrap=IP_BOOT)
    e.enable_lookup_batching(window=0.005)
    try:
        c0 = metrics.counter(
            "engine_caveat_denied_missing_context_total").value
        assert e.lookup_resources("doc", "view", "user", "bob") == []
        assert metrics.counter(
            "engine_caveat_denied_missing_context_total").value > c0
    finally:
        e.disable_lookup_batching()


# -- write validation ---------------------------------------------------------


def test_write_validation_typed_contexts():
    e = Engine(bootstrap=IP_BOOT)
    # well-typed context accepted
    e.write_relationships([WriteOp("touch", parse_relationship(
        'doc:x#viewer@user:d[ip_allowlist:{"allowed":["1.2.3.4"]}]'))])
    # unknown parameter rejected
    with pytest.raises(SchemaViolation, match="no parameter"):
        e.write_relationships([WriteOp("touch", parse_relationship(
            'doc:x#viewer@user:d2[ip_allowlist:{"nope":1}]'))])
    # wrong type rejected
    with pytest.raises(SchemaViolation):
        e.write_relationships([WriteOp("touch", parse_relationship(
            'doc:x#viewer@user:d3[ip_allowlist:{"allowed":"10.0.0.1"}]'
        ))])
    # an entry REQUIRING a caveat never accepts an unconditional tuple
    e3 = Engine(schema=parse_schema("""
      caveat ip_allowlist(ip ipaddress, allowed list<ipaddress>) {
        ip in allowed
      }
      definition user {}
      definition doc {
        relation viewer: user with ip_allowlist
        permission view = viewer
      }
    """))
    with pytest.raises(SchemaViolation, match="does not allow"):
        e3.write_relationships([WriteOp("touch", parse_relationship(
            "doc:x#viewer@user:plain"))])


# -- tuple round-trip properties (satellite) ----------------------------------


def _rand_json_value(rng, depth=2):
    r = rng.random()
    if depth <= 0 or r < 0.45:
        return rng.choice([
            rng.randint(-10_000, 10_000),
            round(rng.uniform(-5, 5), 3),
            rng.random() < 0.5,
            "".join(rng.choice("abc]de[f:#@/.\\\" 日本") for _ in
                    range(rng.randint(0, 6))),
        ])
    if r < 0.75:
        return [_rand_json_value(rng, 0) for _ in range(rng.randint(0, 3))]
    return {f"k{i}": _rand_json_value(rng, depth - 1)
            for i in range(rng.randint(0, 3))}


def test_relationship_context_round_trip_property():
    """parse ∘ format == identity for caveated relationships with
    arbitrary JSON contexts (nested brackets, escapes, unicode) — the
    satellite: JSON-array contexts used to parse leniently but not
    serialize back losslessly."""
    rng = random.Random(7)
    for _ in range(120):
        ctx = {f"p{i}": _rand_json_value(rng)
               for i in range(rng.randint(0, 3))}
        rel = Relationship(
            "doc", "x", "viewer", "user", "u", None,
            1893456000.0 if rng.random() < 0.3 else None,
            "some_caveat", canonical_context(ctx))
        back = parse_relationship(str(rel))
        assert back == rel, (str(rel), back)
        # format ∘ parse ∘ format is idempotent
        assert str(parse_relationship(str(back))) == str(rel)


def test_canonical_context_normalizes():
    a = canonical_context({"b": 1, "a": [2, 3]})
    b = canonical_context('{"a": [2, 3], "b": 1}')
    assert a == b == '{"a":[2,3],"b":1}'
    assert canonical_context(None) is None
    assert canonical_context("") is None
    assert canonical_context({}) is None
    with pytest.raises(TupleError):
        canonical_context("[1, 2]")  # not an object
    with pytest.raises(TupleError):
        canonical_context("{nope")


def test_caveat_survives_snapshot_and_watch_log(tmp_path):
    e = Engine(bootstrap=IP_BOOT)
    path = str(tmp_path / "s.npz")
    e.save_snapshot(path)
    e2 = Engine(bootstrap=IP_BOOT.split("relationships")[0]
                + "relationships: ''")
    e2.load_snapshot(path)
    bob = CheckItem("doc", "readme", "view", "user", "bob")
    assert e2.check(bob, context={"ip": "10.0.0.1"})
    assert not e2.check(bob)
    # watch log round-trips the caveat fields
    rel = parse_relationship(
        'doc:z#viewer@user:w[ip_allowlist:{"allowed":["10.9.9.9"]}]')
    rev0 = e.revision
    e.write_relationships([WriteOp("touch", rel)])
    evs = e.watch_since(rev0)
    assert evs[-1].relationship.caveat == "ip_allowlist"
    assert evs[-1].relationship.caveat_context == \
        '{"allowed":["10.9.9.9"]}'


# -- decision cache: context digest + time bounds -----------------------------


def test_cache_context_digest_no_leakage():
    e = Engine(bootstrap=IP_BOOT)
    e.enable_decision_cache()
    bob = CheckItem("doc", "readme", "view", "user", "bob")
    in_ctx = {"ip": "10.0.0.1"}
    out_ctx = {"ip": "9.9.9.9"}
    # warm both contexts, then assert repeats stay correct (a digest
    # collision would leak one context's verdict into the other)
    for _ in range(3):
        assert e.check(bob, context=in_ctx)
        assert not e.check(bob, context=out_ctx)
        assert not e.check(bob)  # context-free key is its own entry
    hits = metrics.counter("engine_decision_cache_hits_total",
                           kind="check").value
    assert e.check(bob, context=in_ctx)
    assert metrics.counter("engine_decision_cache_hits_total",
                           kind="check").value > hits
    # the event-loop probe honors the digest too
    assert e.try_cached_check([bob], context=in_ctx) == [True]
    assert e.try_cached_check([bob], context=out_ctx) == [False]


def test_time_window_cache_deadline():
    """A time-window caveat revokes/grants without a write: cached
    entries must die at the window boundary, exactly like the store's
    expiration watermark."""
    now = time.time()
    start, end = now + 3600, now + 7200
    boot = f"""
schema: |-
  caveat win(now timestamp, start timestamp, end timestamp) {{
    now >= start && now < end
  }}
  definition user {{}}
  definition doc {{
    relation viewer: user with win | user
    permission view = viewer
  }}
relationships: |-
  doc:d#viewer@user:u[win:{{"end":{end},"start":{start}}}]
"""
    e = Engine(bootstrap=boot)
    u = CheckItem("doc", "d", "view", "user", "u")
    assert not e.check(u)  # before the window (auto-injected now)
    cg = e.compiled()
    assert cg.caveats.any_now
    # next verdict flip after "now" is the window start; after start,
    # the window end
    assert e._cache_deadline(cg, now, None) == pytest.approx(start)
    assert e._cache_deadline(cg, start + 1, None) == pytest.approx(end)
    assert e._cache_deadline(cg, end + 1, None) == float("inf")
    # request-supplied timestamps bound the deadline too
    d = e._cache_deadline(cg, now, {"start": now + 60.0})
    assert d == pytest.approx(now + 60.0)


def test_cache_digest_scoped_to_declared_params():
    """Only declared caveat parameters join the digest: per-request
    middleware fields (name/verb/...) must not fragment the cache when
    the graph's caveats only read `ip`. Review finding regression."""
    e = Engine(bootstrap=IP_BOOT)
    e.enable_decision_cache()
    bob = CheckItem("doc", "readme", "view", "user", "bob")
    base = {"ip": "10.0.0.1", "verb": "get", "name": "a",
            "user": "bob", "groups": []}
    assert e.check(bob, context=base)
    hits0 = metrics.counter("engine_decision_cache_hits_total",
                            kind="check").value
    # same ip, DIFFERENT request-shaped noise: must be a cache HIT
    assert e.check(bob, context={**base, "verb": "list", "name": "b"})
    assert metrics.counter("engine_decision_cache_hits_total",
                           kind="check").value > hits0
    # different ip: still its own entry (correctness)
    assert not e.check(bob, context={**base, "ip": "9.9.9.9"})


def test_bulk_load_validates_caveat_columns():
    e = Engine(bootstrap=IP_BOOT)
    ok_cols = {
        "resource_type": ["doc"], "resource_id": ["bk"],
        "relation": ["viewer"], "subject_type": ["user"],
        "subject_id": ["zed"], "caveat": ["ip_allowlist"],
        "caveat_context": ['{"allowed":["10.0.0.0/8"]}'],
    }
    e.bulk_load(ok_cols)
    assert e.check(CheckItem("doc", "bk", "view", "user", "zed"),
                   context={"ip": "10.1.1.1"})
    # an undeclared name / mistyped context must fail the LOAD, not
    # brick the next compile (review finding regression)
    with pytest.raises(SchemaViolation):
        e.bulk_load({**ok_cols, "resource_id": ["bk2"],
                     "caveat": ["ip_allowlst"]})
    with pytest.raises(SchemaViolation):
        e.bulk_load({**ok_cols, "resource_id": ["bk3"],
                     "caveat_context": ['{"allowed":"not-a-list"}']})
    # engine still serves
    assert e.check(CheckItem("doc", "readme", "view", "user", "alice"))


def test_incremental_append_extends_time_bounds():
    """A time-window tuple added via the INCREMENTAL path must extend
    the verdict-flip watermark — otherwise a cached ALLOW filled before
    the write outlives the new tuple's window (fail open). Review
    finding regression."""
    now = time.time()
    t1 = now + 7200
    boot = f"""
schema: |-
  caveat win(now timestamp, until timestamp) {{ now < until }}
  definition user {{}}
  definition doc {{
    relation viewer: user with win | user
    permission view = viewer
  }}
relationships: |-
  doc:a#viewer@user:u[win:{{"until":{t1}}}]
"""
    e = Engine(bootstrap=boot)
    assert e.check(CheckItem("doc", "a", "view", "user", "u"))
    cg = e.compiled()
    assert e._cache_deadline(cg, now, None) == pytest.approx(t1)
    # incremental write of a NEW instance with an EARLIER window end
    t2 = now + 1800
    e.write_relationships([WriteOp("touch", Relationship(
        "doc", "b", "viewer", "user", "u", None, None, "win",
        canonical_context({"until": t2})))])
    cg2 = e.compiled()
    assert cg2.caveats is cg.caveats  # same shared table (incremental)
    assert e._cache_deadline(cg2, now, None) == pytest.approx(t2)


# -- incremental caveated churn ----------------------------------------------


def test_incremental_caveated_churn_oracle_parity():
    """Randomized touch/delete churn over caveated + plain tuples:
    after EVERY mutation the device verdicts match the oracle under a
    fixed request context, and steady-state churn (reused contexts)
    stays on the incremental path."""
    rng = random.Random(99)
    e = Engine(bootstrap=IP_BOOT)
    ctxs = ['{"allowed":["10.0.0.0/8"]}', '{"allowed":["172.16.0.0/12"]}']
    users = [f"u{i}" for i in range(6)]
    live: dict = {}
    req = {"ip": "10.5.5.5"}
    e.check(CheckItem("doc", "readme", "view", "user", "alice"))  # warm
    compiles0 = metrics.counter("engine_graph_compiles_total").value
    for step in range(25):
        u = rng.choice(users)
        if u in live and rng.random() < 0.35:
            from spicedb_kubeapi_proxy_tpu.engine.store import (
                RelationshipFilter,
            )

            e.delete_relationships(RelationshipFilter(
                resource_type="doc", resource_id="r", relation="viewer",
                subject_id=u))
            live.pop(u)
        else:
            cav = rng.random() < 0.7
            ctx = rng.choice(ctxs) if cav else None
            rel = Relationship("doc", "r", "viewer", "user", u, None,
                               None, "ip_allowlist" if cav else None,
                               ctx)
            e.write_relationships([WriteOp("touch", rel)])
            live[u] = ctx
        got = e.check_bulk(
            [CheckItem("doc", "r", "view", "user", u2) for u2 in users],
            context=req)
        o = e.oracle(context=req)
        want = [o.check("doc", "r", "view", "user", u2) for u2 in users]
        assert got == want, f"step {step}: {got} != {want}"
    # reused contexts ride the overlay: no per-write full recompiles
    # (the two distinct contexts at most add instance rows once)
    assert metrics.counter("engine_graph_compiles_total").value \
        <= compiles0 + 1


def test_first_ever_caveat_falls_back_counted():
    """A caveated write against a graph compiled with NO instances of
    that caveat cannot be expressed on the frozen instance tables: the
    incremental path declines with reason=caveat and the read-path
    recompile serves it correctly."""
    e = Engine(bootstrap="""
schema: |-
  caveat c1(x int) { x > 3 }
  definition user {}
  definition doc {
    relation viewer: user | user with c1
    permission view = viewer
  }
relationships: |-
  doc:a#viewer@user:plain
""")
    assert e.check(CheckItem("doc", "a", "view", "user", "plain"))
    fb0 = metrics.counter("engine_graph_incremental_fallback_total",
                          reason="caveat").value
    e.write_relationships([WriteOp("touch", parse_relationship(
        'doc:a#viewer@user:cond[c1:{"x":5}]'))])
    assert metrics.counter("engine_graph_incremental_fallback_total",
                           reason="caveat").value == fb0 + 1
    assert e.check(CheckItem("doc", "a", "view", "user", "cond"))
    # a second same-context caveated write now reuses the instance row
    fb1 = metrics.counter("engine_graph_incremental_fallback_total",
                          reason="caveat").value
    e.write_relationships([WriteOp("touch", parse_relationship(
        'doc:b#viewer@user:cond2[c1:{"x":9}]'))])
    assert metrics.counter("engine_graph_incremental_fallback_total",
                           reason="caveat").value == fb1
    assert e.check(CheckItem("doc", "b", "view", "user", "cond2"))


# -- remote wire --------------------------------------------------------------


def test_remote_engine_carries_context():
    from spicedb_kubeapi_proxy_tpu.engine.remote import (
        EngineServer,
        RemoteEngine,
    )

    e = Engine(bootstrap=IP_BOOT)

    async def go():
        server = EngineServer(e)
        port = await server.start()
        remote = RemoteEngine("127.0.0.1", port)
        try:
            bob = CheckItem("doc", "readme", "view", "user", "bob")
            got = await asyncio.to_thread(
                remote.check_bulk, [bob], None, {"ip": "10.0.0.1"})
            assert got == [True]
            got = await asyncio.to_thread(remote.check_bulk, [bob])
            assert got == [False]
            ids = await asyncio.to_thread(
                lambda: remote.lookup_resources(
                    "doc", "view", "user", "bob",
                    context={"ip": "10.0.0.1"}))
            assert ids == ["readme"]
            mask, interner = await asyncio.to_thread(
                lambda: remote.lookup_resources_mask(
                    "doc", "view", "user", "bob",
                    context={"ip": "10.0.0.1"}))
            from spicedb_kubeapi_proxy_tpu.engine.engine import mask_to_ids
            assert mask_to_ids(mask, interner) == ["readme"]
            subs = await asyncio.to_thread(
                lambda: remote.lookup_subjects(
                    "doc", "readme", "view", "user",
                    context={"ip": "10.0.0.1"}))
            assert subs == ["alice", "bob"]
        finally:
            remote.close()
            await server.stop()
    asyncio.run(go())


# -- end to end through the proxy middleware ----------------------------------

E2E_RULES = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata:
  name: namespace-get
match:
  - apiVersion: v1
    resource: namespaces
    verbs: [get]
check:
  - tpl: "namespace:{{name}}#view@user:{{user.name}}"
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata:
  name: namespace-list
match:
  - apiVersion: v1
    resource: namespaces
    verbs: [list]
prefilter:
  - fromObjectIDNameExpr: "{{resourceId}}"
    lookupMatchingResources:
      tpl: "namespace:$#view@user:{{user.name}}"
"""

E2E_BOOT = """
schema: |-
  caveat ip_allowlist(ip ipaddress, allowed list<ipaddress>) {
    ip in allowed
  }
  caveat office_hours(now timestamp, start timestamp, end timestamp) {
    now >= start && now < end
  }
  definition user {}
  definition namespace {
    relation viewer: user | user with ip_allowlist | user with office_hours
    permission view = viewer
  }
relationships: |-
  namespace:public#viewer@user:alice
  namespace:internal#viewer@user:alice[ip_allowlist:{"allowed":["10.0.0.0/8","192.168.1.0/24"]}]
"""


def _req(method, path, user="alice", headers=None):
    return ProxyRequest(
        method=method, path=path, query={},
        headers={"Content-Type": "application/json", **(headers or {})},
        body=b"", user=UserInfo(name=user),
        request_info=parse_request_info(method, path, {}))


async def _upstream_ns_list(req):
    return json_response(200, {"kind": "NamespaceList", "items": [
        {"metadata": {"name": "public"}},
        {"metadata": {"name": "internal"}},
    ]})


def test_e2e_ip_allowlist_prefiltered_list():
    """The acceptance scenario: schema declaring an IP-allowlist caveat
    plus caveated tuples serves a correct conditional verdict end to
    end through the proxy's prefiltered list — allow with matching
    context, deny with non-matching, fail-closed deny with missing
    context — with the caveat mask evaluated on-device in the same
    dispatch as the fixpoint."""
    b = parse_bootstrap(E2E_BOOT)
    e = Engine(schema=b.schema)
    e.write_relationships([WriteOp("touch", r) for r in b.relationships])
    deps = AuthzDeps(matcher=MapMatcher.from_yaml(E2E_RULES), engine=e,
                     upstream=_upstream_ns_list)

    async def names(headers):
        resp = await authorize(
            _req("GET", "/api/v1/namespaces", headers=headers), deps)
        assert resp.status == 200
        doc = json.loads(resp.body)
        return sorted(i["metadata"]["name"] for i in doc["items"])

    async def go():
        # matching client IP: the conditional namespace appears
        assert await names({"X-Forwarded-For": "10.20.30.40"}) == \
            ["internal", "public"]
        # LB chain: the LAST hop (appended by the trusted proxy) wins —
        # a client-forged leading entry must NOT spoof the allowlist
        assert await names(
            {"X-Forwarded-For": "8.8.8.8, 192.168.1.7"}) == \
            ["internal", "public"]
        assert await names(
            {"X-Forwarded-For": "10.0.0.1, 8.8.8.8"}) == ["public"]
        # non-matching IP: conditional grant filtered out
        assert await names({"X-Forwarded-For": "8.8.8.8"}) == ["public"]
        # no trusted header at all: missing context fails closed
        assert await names({}) == ["public"]
        # GET of the conditional namespace follows the same verdicts
        ok = await authorize(_req(
            "GET", "/api/v1/namespaces/internal",
            headers={"X-Forwarded-For": "10.1.1.1"}), deps)
        assert ok.status == 200
        denied = await authorize(_req(
            "GET", "/api/v1/namespaces/internal",
            headers={"X-Forwarded-For": "8.8.8.8"}), deps)
        assert denied.status == 403
        denied2 = await authorize(
            _req("GET", "/api/v1/namespaces/internal"), deps)
        assert denied2.status == 403
    asyncio.run(go())


def test_e2e_time_window_grant():
    b = parse_bootstrap(E2E_BOOT)
    e = Engine(schema=b.schema)
    now = time.time()
    inside = canonical_context(
        {"start": now - 3600, "end": now + 3600})
    outside = canonical_context(
        {"start": now + 3600, "end": now + 7200})
    e.write_relationships([WriteOp("touch", Relationship(
        "namespace", "live", "viewer", "user", "alice", None, None,
        "office_hours", inside))])
    e.write_relationships([WriteOp("touch", Relationship(
        "namespace", "later", "viewer", "user", "alice", None, None,
        "office_hours", outside))])
    deps = AuthzDeps(matcher=MapMatcher.from_yaml(E2E_RULES), engine=e,
                     upstream=_upstream_ns_list)

    async def go():
        # the wall clock is auto-injected as `now`: the in-window grant
        # holds, the future-window one does not — with NO context from
        # the caller at all
        ok = await authorize(
            _req("GET", "/api/v1/namespaces/live"), deps)
        assert ok.status == 200
        denied = await authorize(
            _req("GET", "/api/v1/namespaces/later"), deps)
        assert denied.status == 403
    asyncio.run(go())


def test_caveat_context_disabled_fails_closed():
    b = parse_bootstrap(E2E_BOOT)
    e = Engine(schema=b.schema)
    e.write_relationships([WriteOp("touch", r) for r in b.relationships])
    deps = AuthzDeps(matcher=MapMatcher.from_yaml(E2E_RULES), engine=e,
                     upstream=_upstream_ns_list,
                     caveat_context_enabled=False)

    async def go():
        resp = await authorize(_req(
            "GET", "/api/v1/namespaces/internal",
            headers={"X-Forwarded-For": "10.1.1.1"}), deps)
        assert resp.status == 403  # context never forwarded: fail closed
    asyncio.run(go())


# -- IPv6 in the ipaddress type (ISSUE 11 satellite) -------------------------

IP6_BOOT = """\
schema: |-
  caveat office_net(ip ipaddress) {
    ip in ['2001:db8::/64', '10.0.0.0/8', '192.168.1.7']
  }
  caveat same_addr(ip ipaddress, peer ipaddress) {
    ip == peer
  }
  caveat below(ip ipaddress, peer ipaddress) {
    ip < peer
  }
  caveat dyn_list(ip ipaddress, allowed list<ipaddress>) {
    ip in allowed
  }

  definition user {}

  definition doc {
    relation viewer: user with office_net
    relation editor: user with same_addr
    relation ranker: user with below
    relation lister: user with dyn_list
  }
relationships: |-
  doc:d#viewer@user:al[office_net]
  doc:e#editor@user:al[same_addr:{"peer": "2001:db8::42"}]
  doc:r#ranker@user:al[below:{"peer": "2001:db8::100"}]
  doc:l#lister@user:al[dyn_list:{"allowed": ["10.1.0.0/16", "2001:db8::/64"]}]
  doc:l4#lister@user:al[dyn_list:{"allowed": ["10.1.0.0/16"]}]
"""

_IP6_RELS = {"d": "viewer", "e": "editor", "r": "ranker",
             "l": "lister", "l4": "lister"}


def _ip6_engine():
    b = parse_bootstrap(IP6_BOOT)
    e = Engine(schema=b.schema)
    e.write_relationships([WriteOp("touch", r) for r in b.relationships])
    return e


def _ip6_check(e, doc, ip):
    ctx = {"ip": ip} if ip is not None else None
    return e.check_bulk([CheckItem("doc", doc, _IP6_RELS[doc], "user",
                                   "al")], context=ctx)[0]


def test_ipv6_literal_cidr_exact_lexicographic_boundaries():
    """Literal CIDR allowlists lower to exact word-wise lexicographic
    range checks in the mapped 128-bit space: the /64 boundary addresses
    split EXACTLY, v4 members keep working, and a v6 address never
    matches a v4 block (distinct mapped ranges)."""
    e = _ip6_engine()
    # inside the /64: first and last address of the block
    assert _ip6_check(e, "d", "2001:db8::")
    assert _ip6_check(e, "d", "2001:db8::ffff:ffff:ffff:ffff")
    # one past either edge: exact misses (low 64 bits all-ones + 1)
    assert not _ip6_check(e, "d", "2001:db8:0:1::")
    assert not _ip6_check(e, "d", "2001:db7:ffff:ffff:ffff:ffff:ffff:ffff")
    # v4 members of the same list
    assert _ip6_check(e, "d", "10.255.255.255")
    assert _ip6_check(e, "d", "192.168.1.7")
    assert not _ip6_check(e, "d", "192.168.1.8")
    # a v6 address inside the v4 block's MAPPED range only via ::ffff —
    # the mapped form of a member matches (families share one space)
    assert _ip6_check(e, "d", "::ffff:10.0.0.1")
    # garbage -> missing context -> fail closed
    assert not _ip6_check(e, "d", "not-an-ip")
    assert not _ip6_check(e, "d", None)


def test_ipv6_wide_compare_eq_and_ordering():
    e = _ip6_engine()
    # equality across all four words: low-bit differences matter
    assert _ip6_check(e, "e", "2001:db8::42")
    assert not _ip6_check(e, "e", "2001:db8::43")
    assert not _ip6_check(e, "e", "2001:db8:0:0:1::42")
    # lexicographic ordering: below 2001:db8::100 in the HIGH words and
    # in the LOW words; v4 is always below any non-mapped v6
    assert _ip6_check(e, "r", "2001:db8::ff")
    assert not _ip6_check(e, "r", "2001:db8::100")
    assert not _ip6_check(e, "r", "2001:db8::101")
    assert _ip6_check(e, "r", "9.9.9.9")  # mapped v4 < 2001:db8::


def test_ipv6_param_list_v4_gate_and_unencodable_counter():
    before = metrics.counter(
        "engine_caveat_ipv6_unencodable_total").value
    e = _ip6_engine()
    # a PURE-v4 param list keeps working exactly
    assert _ip6_check(e, "l4", "10.1.2.3")
    assert not _ip6_check(e, "l4", "10.2.0.1")
    # the tuple's list held a v6 element: the WHOLE list is
    # unencodable -> UNKNOWN -> fail closed (even for v4 members that a
    # narrowed list would have admitted: a KNOWN narrowed answer would
    # fail OPEN under '!(ip in blocked)' denylists), and counted
    miss0 = metrics.counter(
        "engine_caveat_denied_missing_context_total").value
    assert not _ip6_check(e, "l", "10.1.2.3")
    assert metrics.counter(
        "engine_caveat_denied_missing_context_total").value > miss0
    after = metrics.counter(
        "engine_caveat_ipv6_unencodable_total").value
    assert after > before
    # a v6 request address against a v4-only list is a KNOWN miss (the
    # sentinel lowering): denied WITHOUT a missing-context tick — the
    # true answer, not an unknown. Isolated engine: the combined
    # fixture's v6-bearing instance is legitimately missing on every
    # dispatch and would tick the counter regardless of the doc asked
    b4 = parse_bootstrap("""\
schema: |-
  caveat dyn_list(ip ipaddress, allowed list<ipaddress>) {
    ip in allowed
  }

  definition user {}

  definition doc {
    relation lister: user with dyn_list
  }
relationships: |-
  doc:l4#lister@user:al[dyn_list:{"allowed": ["10.1.0.0/16"]}]
""")
    e4 = Engine(schema=b4.schema)
    e4.write_relationships([WriteOp("touch", r)
                            for r in b4.relationships])
    miss1 = metrics.counter(
        "engine_caveat_denied_missing_context_total").value
    assert not e4.check_bulk([CheckItem("doc", "l4", "lister", "user",
                                        "al")],
                             context={"ip": "2001:db8::1"})[0]
    assert metrics.counter(
        "engine_caveat_denied_missing_context_total").value == miss1
    # writes carrying v6 list elements are ACCEPTED (well-typed; they
    # resolve UNKNOWN at evaluation), never a SchemaViolation
    from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship
    e.write_relationships([WriteOp("touch", parse_relationship(
        'doc:lw#lister@user:al'
        '[dyn_list:{"allowed": ["fe80::/10"]}]'))])


def test_ipv6_vm_matches_interpreter_over_address_corpus():
    """Differential over both families and every caveat shape: the VM's
    verdict equals the tri-state oracle's for literal lists, wide
    compares, and the param-list v4 gate."""
    e = _ip6_engine()
    b = parse_bootstrap(IP6_BOOT)
    defs = b.schema.caveat_defs
    corpus = [
        "2001:db8::", "2001:db8::1", "2001:db8::42", "2001:db8::100",
        "2001:db8::ffff:ffff:ffff:ffff", "2001:db8:0:1::", "fe80::1",
        "::1", "::", "ffff:ffff:ffff:ffff:ffff:ffff:ffff:ffff",
        "10.0.0.0", "10.1.2.3", "10.255.255.255", "11.0.0.0",
        "192.168.1.7", "0.0.0.0", "255.255.255.255",
        "::ffff:10.0.0.1", "::ffff:192.168.1.7",
    ]
    tuple_ctx = {"e": {"peer": "2001:db8::42"},
                 "r": {"peer": "2001:db8::100"},
                 "l": {"allowed": ["10.1.0.0/16", "2001:db8::/64"]}}
    cav_of = {"d": "office_net", "e": "same_addr", "r": "below",
              "l": "dyn_list"}
    for doc, cname in cav_of.items():
        defn = defs[cname]
        params = {p.name: p.type for p in defn.params}
        for ip in corpus:
            got = _ip6_check(e, doc, ip)
            ctx = dict(tuple_ctx.get(doc, {}))
            ctx["ip"] = ip
            want = interpret(defn.expr, ctx, params, StringInterner())
            assert got == (want is True), (doc, ip, want, got)


def test_ipaddress_type_misuse_rejected():
    for body, params in (
            ("ip + 1 > 5", (CaveatParam("ip", CaveatType("ipaddress")),)),
            ("ip > 5", (CaveatParam("ip", CaveatType("ipaddress")),)),
            ("5 in allowed", (CaveatParam(
                "allowed", CaveatType("list", "ipaddress")),)),
            ("ip in [7]", (CaveatParam("ip", CaveatType("ipaddress")),)),
    ):
        defn = CaveatDef("bad", params, parse_caveat_body(body))
        with pytest.raises(CaveatError):
            compile_caveat(defn, StringInterner())


def test_ipv6_unencodable_list_never_fails_open_under_negation():
    """The denylist polarity pin: '!(ip in blocked)' with a v6 element
    in the blocked PARAM list must DENY (the list is UNKNOWN, and
    Kleene NOT(unknown) = unknown = fail closed) — a dropped-element
    narrowing would have answered known-False and GRANTED."""
    boot = """\
schema: |-
  caveat not_blocked(ip ipaddress, blocked list<ipaddress>) {
    !(ip in blocked)
  }

  definition user {}

  definition doc {
    relation viewer: user with not_blocked
    permission view = viewer
  }
relationships: |-
  doc:v6#viewer@user:al[not_blocked:{"blocked": ["2001:db8::/64"]}]
  doc:v4#viewer@user:al[not_blocked:{"blocked": ["10.0.0.0/8"]}]
"""
    b = parse_bootstrap(boot)
    e = Engine(schema=b.schema)
    e.write_relationships([WriteOp("touch", r) for r in b.relationships])

    def chk(doc, ip):
        return e.check_bulk([CheckItem("doc", doc, "viewer", "user",
                                       "al")], context={"ip": ip})[0]

    # v6-bearing denylist: UNKNOWN -> denied for EVERYONE (the blocked
    # v6 client above all — never granted by a narrowed known-False)
    assert not chk("v6", "2001:db8::1")   # explicitly blocked: denied
    assert not chk("v6", "9.9.9.9")       # fail closed, not fail open
    # pure-v4 denylist keeps exact semantics either family
    assert not chk("v4", "10.1.2.3")      # blocked
    assert chk("v4", "11.0.0.1")          # not blocked: granted
    assert chk("v4", "2001:db8::1")       # v6 truly not in a v4 list
    # and the oracle agrees on the unknown polarity
    defn = b.schema.caveat_defs["not_blocked"]
    params = {p.name: p.type for p in defn.params}
    assert interpret(defn.expr,
                     {"ip": "9.9.9.9", "blocked": ["2001:db8::/64"]},
                     params, StringInterner()) is None
