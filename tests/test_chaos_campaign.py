"""Chaos campaign (ISSUE 12): deterministic fault schedules, safety
invariants, layered retry budgets.

Tier-1 (fast, deterministic): failpoint env hardening (non-positive
budgets rejected, ``name:p=`` probabilistic arming off the seeded chaos
RNG), fault-schedule determinism with the seed-0 digest PINNED
bench-contract style, fault actions (error/drop/delay/crash), the
flag-gated chaos wire ops, RetryBudget units + the counter-verified
amplification bound at every layer (transport retries, failover
re-aim, planner scatter re-issue), the parametrized Retry-After audit
across every fail-closed 503 source, invariant-checker units, and the
in-process campaign smoke.

Slow-marked (the CI chaos job): the subprocess campaign regression home
and the composed ShardedWatchStream resumption across a group-leader
SIGKILL (PR 11 tested resumption and failover separately, never
composed).
"""

import asyncio
import os
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from spicedb_kubeapi_proxy_tpu.admission import (  # noqa: E402
    AdmissionRejected,
)
from spicedb_kubeapi_proxy_tpu.authz import (  # noqa: E402
    AuthzDeps,
    authorize,
)
from spicedb_kubeapi_proxy_tpu.chaos import (  # noqa: E402
    ChaosScheduleError,
    EpisodeEvidence,
    FaultSchedule,
    FaultSpec,
    InvariantViolation,
    OpRecord,
    brownout_schedule,
    check_all,
    check_never_fail_open,
    check_no_stale_verdict,
    check_retry_amplification,
    check_zero_acked_write_loss,
    retry_amplification_bound,
)
from spicedb_kubeapi_proxy_tpu.chaos.campaign import (  # noqa: E402
    Campaign,
    CampaignConfig,
    SubprocessTopology,
)
from spicedb_kubeapi_proxy_tpu.chaos.invariants import (  # noqa: E402
    KIND_CHECK,
    KIND_DELETE,
    KIND_WRITE,
    OUTCOME_OK,
    OUTCOME_SHED,
)
from spicedb_kubeapi_proxy_tpu.engine import (  # noqa: E402
    CheckItem,
    Engine,
)
from spicedb_kubeapi_proxy_tpu.engine.compaction import (  # noqa: E402
    OverlayBackpressure,
)
from spicedb_kubeapi_proxy_tpu.engine.remote import (  # noqa: E402
    EngineInternalError,
    EngineServer,
    NotLeaderError,
    RemoteEngine,
)
from spicedb_kubeapi_proxy_tpu.engine.store import (  # noqa: E402
    StoreError,
    WriteOp,
)
from spicedb_kubeapi_proxy_tpu.models.tuples import (  # noqa: E402
    Relationship,
    parse_relationship,
)
from spicedb_kubeapi_proxy_tpu.proxy.requestinfo import (  # noqa: E402
    parse_request_info,
)
from spicedb_kubeapi_proxy_tpu.proxy.types import ProxyRequest  # noqa: E402
from spicedb_kubeapi_proxy_tpu.rules import MapMatcher  # noqa: E402
from spicedb_kubeapi_proxy_tpu.rules.input import UserInfo  # noqa: E402
from spicedb_kubeapi_proxy_tpu.utils.failpoints import (  # noqa: E402
    DECISION_HORIZON,
    FailPointError,
    _Registry,
    decision_sequence,
    failpoints,
)
from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics  # noqa: E402
from spicedb_kubeapi_proxy_tpu.utils.resilience import (  # noqa: E402
    BreakerOpen,
    CircuitBreaker,
    DeadlineExceeded,
    RetryBudget,
    RetryPolicy,
)

pytestmark = pytest.mark.chaos

NO_BACKOFF = RetryPolicy(base=0.0, cap=0.0)

# the bench-contract-style pin: the stock brownout schedule at seed 0
# must derive these exact decision tables forever — a drift here means
# "re-running a seed" no longer reproduces historical fault histories
BROWNOUT_SEED0_DIGEST = \
    "0f050b3ea4cbcfb8c308607124ed4f16f523960e42d3686a0130f30de51042ad"


@pytest.fixture(autouse=True)
def _clean_slate():
    failpoints.disable_all()
    metrics.reset()
    yield
    failpoints.disable_all()
    metrics.reset()


# -- satellite: FAILPOINTS env hardening --------------------------------------


def test_env_rejects_nonpositive_budgets(monkeypatch):
    """`name:-3` used to arm-then-pop silently; now it warns and stays
    un-armed (as does `name:0`), while positive budgets still arm."""
    monkeypatch.setenv("FAILPOINTS", "a.bad:-3,b.bad:0,c.ok:2,d.ok")
    reg = _Registry()
    assert not reg.armed("a.bad")
    assert not reg.armed("b.bad")
    assert reg.armed("c.ok")
    assert reg.armed("d.ok")


def test_env_probabilistic_arming_is_seed_deterministic(monkeypatch):
    """`name:p=0.25` arms off the seeded chaos RNG (CHAOS_SEED): two
    registries with the same seed fire on the same hit indices; a
    different seed gives a different pattern; malformed p is ignored."""
    monkeypatch.setenv("FAILPOINTS", "x.prob:p=0.25,bad:p=2.0,worse:p=x")
    monkeypatch.setenv("CHAOS_SEED", "42")

    def pattern(reg):
        out = []
        for _ in range(64):
            try:
                reg.hit("x.prob")
                out.append(False)
            except FailPointError:
                out.append(True)
        return out

    reg1, reg2 = _Registry(), _Registry()
    assert reg1.armed("x.prob")
    assert not reg1.armed("bad") and not reg1.armed("worse")
    p1, p2 = pattern(reg1), pattern(reg2)
    assert p1 == p2, "same seed must fire on the same hit indices"
    assert 0 < sum(p1) < 64  # actually probabilistic
    assert p1 == decision_sequence(42, "x.prob", 0.25)[:64]
    monkeypatch.setenv("CHAOS_SEED", "43")
    assert pattern(_Registry()) != p1


# -- fault schedules: determinism, digest pin, actions ------------------------


def test_schedule_digest_pinned_and_reproducible():
    assert brownout_schedule(0).digest() == BROWNOUT_SEED0_DIGEST
    assert brownout_schedule(0).digest() == brownout_schedule(0).digest()
    assert brownout_schedule(1).digest() != BROWNOUT_SEED0_DIGEST
    # the wire round trip re-derives byte-identical decision tables
    s = brownout_schedule(3)
    assert FaultSchedule.parse(s.encode()).digest() == s.digest()


def test_schedule_validation():
    with pytest.raises(ChaosScheduleError):
        FaultSpec("s", "explode")
    with pytest.raises(ChaosScheduleError):
        FaultSpec("s", "delay:nope")
    with pytest.raises(ChaosScheduleError):
        FaultSpec("s", "error", p=0.0)
    with pytest.raises(ChaosScheduleError):
        FaultSpec("s", "error", budget=0)
    with pytest.raises(ChaosScheduleError):
        FaultSchedule(0, [FaultSpec("dup"), FaultSpec("dup")])
    with pytest.raises(ChaosScheduleError):
        FaultSchedule.parse({"seed": 0})


def test_fault_actions_error_drop_delay_crash(monkeypatch):
    # error at a hit site raises; budget disarms deterministically
    FaultSchedule(0, [FaultSpec("t.err", "error", budget=2)]).arm()
    for _ in range(2):
        with pytest.raises(FailPointError):
            failpoints.hit("t.err")
    failpoints.hit("t.err")  # budget spent: a no-op again

    # drop at a branch site returns True (the frame falls on the floor)
    FaultSchedule(0, [FaultSpec("t.drop", "drop", budget=1)]).arm()
    assert failpoints.branch("t.drop") is True
    assert failpoints.branch("t.drop") is False

    # delay sleeps and lets the op proceed (no raise)
    FaultSchedule(0, [FaultSpec("t.delay", "delay:40", budget=1)]).arm()
    t0 = time.monotonic()
    failpoints.hit("t.delay")
    assert time.monotonic() - t0 >= 0.03

    # crash SIGKILLs the process — assert the call, not the death
    calls = []
    monkeypatch.setattr(os, "kill", lambda pid, sig: calls.append(
        (pid, sig)))
    FaultSchedule(0, [FaultSpec("t.crash", "crash", budget=1)]).arm()
    failpoints.hit("t.crash")
    import signal as _signal

    assert calls == [(os.getpid(), _signal.SIGKILL)]


def test_history_digest_deterministic_for_same_hit_sequence():
    sched = FaultSchedule(5, [FaultSpec("h.x", "error", p=0.5,
                                        budget=DECISION_HORIZON)])

    def run():
        failpoints.disable_all()
        sched.arm()
        for _ in range(50):
            try:
                failpoints.hit("h.x")
            except FailPointError:
                pass
        return failpoints.history_digest()

    assert run() == run()


# -- the flag-gated chaos wire ops --------------------------------------------


def test_chaos_ops_flag_gated_and_deterministic_over_the_wire():
    async def go():
        e = Engine()
        e.write_relationships([WriteOp("touch", parse_relationship(
            "namespace:dev#creator@user:alice"))])

        # gate OFF: chaos ops refused, nothing armed
        srv_off = EngineServer(e)
        port = await srv_off.start()
        remote = RemoteEngine("127.0.0.1", port, retries=0,
                              retry_policy=NO_BACKOFF)
        sched = FaultSchedule(0, [FaultSpec("engine.dispatch", "error",
                                            budget=2)])
        with pytest.raises(StoreError, match="chaos ops are disabled"):
            await asyncio.to_thread(remote.chaos_arm, sched.encode())
        remote.close()
        await srv_off.stop()

        # gate ON: arming returns the schedule digest, the armed site
        # fires exactly budget times, status reports the history
        srv = EngineServer(e, allow_chaos=True)
        port = await srv.start()
        remote = RemoteEngine("127.0.0.1", port, retries=0,
                              retry_policy=NO_BACKOFF)
        got = await asyncio.to_thread(remote.chaos_arm, sched.encode())
        assert got["digest"] == sched.digest()
        assert got["armed"] == ["engine.dispatch"]
        from spicedb_kubeapi_proxy_tpu.engine.remote import (
            RemoteEngineError,
        )

        for _ in range(2):
            with pytest.raises(RemoteEngineError):
                await asyncio.to_thread(lambda: remote.revision)
        # budget spent: the host answers again
        assert await asyncio.to_thread(lambda: remote.revision) \
            == e.revision
        st = await asyncio.to_thread(remote.chaos_status)
        fired = {s["name"]: s["fired"] for s in st["sites"]}
        # the error rule disarmed after its budget; history remembers
        assert len(st["history"]) == 2
        assert all(site == "engine.dispatch"
                   for site, _, _ in st["history"])
        assert fired.get("engine.dispatch", 0) in (0, 2)
        await asyncio.to_thread(remote.chaos_reset)
        remote.close()
        await srv.stop()

    asyncio.run(go())


def test_chaos_delay_schedule_browns_out_dispatch_without_error():
    """A wire-armed delay schedule slows the op (worker-side sleep) but
    answers correctly — the brownout shape, distinct from failure."""
    async def go():
        e = Engine()
        srv = EngineServer(e, allow_chaos=True)
        port = await srv.start()
        remote = RemoteEngine("127.0.0.1", port, retries=0,
                              retry_policy=NO_BACKOFF)
        await asyncio.to_thread(remote.chaos_arm, FaultSchedule(
            0, [FaultSpec("engine.dispatch", "delay:80",
                          budget=1)]).encode())
        t0 = time.monotonic()
        assert await asyncio.to_thread(lambda: remote.revision) \
            == e.revision
        assert time.monotonic() - t0 >= 0.06
        remote.close()
        await srv.stop()

    asyncio.run(go())


# -- RetryBudget: units + the layered amplification bound ---------------------


def test_retry_budget_units():
    b = RetryBudget("dep-x", ratio=0.5, burst=2.0)
    assert b.tokens == 2.0
    assert b.allow() and b.allow()  # burst spends down
    assert not b.allow()  # dry: refused and counted
    assert metrics.counter("resilience_retry_budget_exhausted_total",
                           dependency="dep-x").value == 1.0
    b.on_attempt()  # +0.5
    assert not b.allow()  # still < 1 token
    b.on_attempt()  # 1.0
    assert b.allow()
    for _ in range(100):
        b.on_attempt()
    assert b.tokens == 2.0  # capped at burst
    with pytest.raises(ValueError):
        RetryBudget(ratio=-1)
    with pytest.raises(ValueError):
        RetryBudget(burst=0)


def test_transport_retries_counter_verified_within_budget_bound():
    """Hammer a dead endpoint through a budgeted client: TOTAL retries
    observed stay within burst + ratio × attempts even though each call
    carries retries=5."""
    async def go():
        e = Engine()
        srv = EngineServer(e)
        port = await srv.start()
        await srv.stop()  # connections now refused
        budget = RetryBudget("engine-stack", ratio=0.1, burst=3.0)
        remote = RemoteEngine(
            "127.0.0.1", port, retries=5, retry_policy=NO_BACKOFF,
            breaker=CircuitBreaker(f"engine:127.0.0.1:{port}",
                                   failure_threshold=10**6),
            retry_budget=budget)
        attempts = 40
        for _ in range(attempts):
            with pytest.raises(OSError):
                await asyncio.to_thread(lambda: remote.revision)
        retries = metrics.counter(
            "proxy_dependency_retries_total",
            dependency=f"engine:127.0.0.1:{port}").value
        bound = retry_amplification_bound(0.1, 3.0, attempts)
        assert retries <= bound, (retries, bound)
        # without the budget, the same hammering would have retried
        # 5 × attempts = 200 times
        assert retries < attempts * 5 / 2
        assert metrics.counter(
            "resilience_retry_budget_exhausted_total",
            dependency="engine-stack").value > 0
        remote.close()

    asyncio.run(go())


def test_failover_reaim_draws_from_shared_budget():
    """The failover layer's re-issue is a retry too: with the shared
    budget dry, a dead primary surfaces the budget refusal immediately
    instead of parking in an election-window resolve loop."""
    from spicedb_kubeapi_proxy_tpu.engine.remote import FailoverEngine
    from spicedb_kubeapi_proxy_tpu.utils.resilience import (
        DependencyUnavailable,
    )

    budget = RetryBudget("engine-stack", ratio=0.0, burst=1.0)
    assert budget.allow()  # drain the burst
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    fe = FailoverEngine(
        [("127.0.0.1", port)], probe_timeout=0.5, resolve_deadline=5.0,
        connect_timeout=0.5, timeout=0.5, retries=0,
        retry_policy=NO_BACKOFF, retry_budget=budget)
    t0 = time.monotonic()
    with pytest.raises(DependencyUnavailable, match="retry budget"):
        fe.check_bulk([CheckItem("namespace", "dev", "view", "user",
                                 "alice")])
    # no resolve-loop wait: the refusal is immediate (well under the
    # 5s resolve deadline)
    assert time.monotonic() - t0 < 2.0
    assert metrics.counter("resilience_retry_budget_exhausted_total",
                           dependency="engine-stack").value >= 1
    fe.close()


class _FlakyOnce:
    """Engine-surface wrapper whose read ops die once (transport) then
    recover — the planner's scatter-leg re-issue shape."""

    def __init__(self, inner):
        self._inner = inner
        self.deaths = 0

    def __getattr__(self, name):
        val = getattr(self._inner, name)
        if name in ("lookup_resources", "check_bulk"):
            def hooked(*a, _fn=val, **kw):
                if self.deaths == 0:
                    self.deaths += 1
                    raise ConnectionResetError("flaky leg")
                return _fn(*a, **kw)

            return hooked
        return val

    @property
    def revision(self):
        return self._inner.revision

    @property
    def store(self):
        return self._inner.store


SHARD_SCHEMA = """\
schema: |-
  definition user {}

  definition namespace {
    relation viewer: user
    permission view = viewer
  }

  definition pod {
    relation namespace: namespace
    relation viewer: user
    permission view = viewer + namespace->view
  }
relationships: ""
"""


def _shard_planner(flaky_budget):
    from spicedb_kubeapi_proxy_tpu.scaleout import ShardedEngine, ShardMap

    engines = [Engine(bootstrap=SHARD_SCHEMA) for _ in range(2)]
    flaky = _FlakyOnce(engines[1])
    smap = ShardMap(version=1, groups=(
        (("127.0.0.1", 1),), (("127.0.0.1", 2),)))
    planner = ShardedEngine(smap, [engines[0], flaky],
                            retry_budget=flaky_budget)
    # one pod on EACH group's slice, whatever the hash layout
    ns = {g: next(f"ns{i}" for i in range(64)
                  if smap.shard_of("pod", f"ns{i}/p") == g)
          for g in range(2)}
    planner.write_relationships([
        WriteOp("create", Relationship("pod", f"{ns[0]}/p0", "viewer",
                                       "user", "al", None)),
        WriteOp("create", Relationship("pod", f"{ns[1]}/p0", "viewer",
                                       "user", "al", None)),
    ])
    flaky.deaths = 0  # the seeding write is not under test
    return planner, flaky, ns


def test_planner_scatter_leg_reissue_is_budget_gated():
    # WITH budget: the dead leg re-issues once and the gather is exact
    planner, flaky, ns = _shard_planner(RetryBudget("engine-stack",
                                                    ratio=0.0, burst=4.0))
    ids = planner.lookup_resources("pod", "view", "user", "al")
    assert sorted(ids) == sorted([f"{ns[0]}/p0", f"{ns[1]}/p0"])
    assert flaky.deaths == 1
    assert sum(metrics.counter("scaleout_scatter_retries_total",
                               group=str(g)).value
               for g in range(2)) == 1.0
    planner.close()

    # WITHOUT budget (or a dry one): the leg's death propagates —
    # fail closed, never a half union
    metrics.reset()
    dry = RetryBudget("engine-stack", ratio=0.0, burst=1.0)
    assert dry.allow()
    planner2, _, _ = _shard_planner(dry)
    with pytest.raises(ConnectionResetError):
        planner2.lookup_resources("pod", "view", "user", "al")
    planner2.close()


# -- satellite: every fail-closed 503 carries a bounded Retry-After -----------


CHECK_RULES = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata:
  name: ns-list
match:
  - apiVersion: v1
    resource: namespaces
    verbs: [list]
check:
  - tpl: "namespace:ns0#view@user:{{user.name}}"
"""


class _RaisingEngine:
    """The sliver of the engine surface the check path touches, raising
    a configured fail-closed family on dispatch."""

    def __init__(self, exc):
        self.exc = exc

    def check_bulk(self, items, now=None, context=None):
        raise self.exc


def _request(method="GET", path="/api/v1/namespaces"):
    return ProxyRequest(
        method=method, path=path, query={},
        headers={"Content-Type": "application/json"}, body=b"",
        user=UserInfo(name="alice"),
        request_info=parse_request_info(method, path, {}))


@pytest.mark.parametrize("name,exc,want", [
    ("breaker-open",
     BreakerOpen("engine:h:1", "circuit open", retry_after=7.0), 7),
    ("admission-shed",
     AdmissionRejected("check", "queue full", retry_after=2.0,
                       dependency="proxy-admission"), 2),
    ("shard-partial-shed",
     AdmissionRejected("lookup-prefilter", "1/2 shards shed",
                       retry_after=3.0, dependency="shard-admission"), 3),
    ("not-leader", NotLeaderError(), 1),
    ("overlay-backpressure", OverlayBackpressure(0.4, 4096, 4096), 1),
    ("deadline", DeadlineExceeded("engine:h:1", "deadline spent"), 1),
    # an engine host ANSWERING kind="internal" (e.g. a chaos-armed
    # server-side fault) is a dependency failure too: 503, never a raw
    # 500 panic without Retry-After (found by the campaign's verify
    # drive, fixed in this PR). Scoped to the internal kind — the
    # RemoteEngineError BASE (auth/proto/frame misconfigurations) must
    # stay a loud 500, not an endlessly-retried 503 (tested below).
    ("engine-internal",
     EngineInternalError("failpoint 'engine.dispatch' triggered"), 1),
    # stragglers the cap exists for: a source forgetting to bound its
    # hint (or emitting garbage) still yields a BOUNDED header
    ("unbounded-hint",
     BreakerOpen("engine:h:1", "open", retry_after=1e9), 60),
    ("zero-hint",
     BreakerOpen("engine:h:1", "open", retry_after=0.0), 1),
])
def test_every_fail_closed_503_carries_bounded_retry_after(name, exc,
                                                           want):
    """ONE parametrized audit across the fail-closed families: every
    DependencyUnavailable source maps to a 503 whose Retry-After is
    present, >= 1, <= 60, and equal to the (rounded, clamped) hint."""
    deps = AuthzDeps(matcher=MapMatcher.from_yaml(CHECK_RULES),
                     engine=_RaisingEngine(exc), upstream=None)
    resp = asyncio.run(authorize(_request(), deps))
    assert resp.status == 503, (name, resp.status, resp.body)
    ra = resp.headers.get("Retry-After")
    assert ra is not None, f"{name}: Retry-After missing"
    assert 1 <= int(ra) <= 60, (name, ra)
    assert int(ra) == want, (name, ra)


def test_permanent_remote_errors_stay_loud_not_retryable_503():
    """A wrong token / protocol error surfaces as the RemoteEngineError
    BASE — a permanent misconfiguration. It must NOT be converted into
    the retryable 503 family (a polite client would hot-loop a request
    that can never succeed while the breaker stays closed)."""
    from spicedb_kubeapi_proxy_tpu.engine.remote import RemoteEngineError

    deps = AuthzDeps(matcher=MapMatcher.from_yaml(CHECK_RULES),
                     engine=_RaisingEngine(
                         RemoteEngineError("invalid token")),
                     upstream=None)
    with pytest.raises(RemoteEngineError):
        asyncio.run(authorize(_request(), deps))


# -- invariant checker units --------------------------------------------------


def test_invariant_never_fail_open_catches_seeded_violation():
    records = [
        OpRecord(KIND_CHECK, OUTCOME_OK, seq=1, key="a", verdict=False,
                 expected=False),
        OpRecord(KIND_CHECK, OUTCOME_OK, seq=2, key="b", verdict=True,
                 expected=True),
        OpRecord(KIND_CHECK, OUTCOME_OK, seq=3, key="evil", verdict=True,
                 expected=False),  # the fail-open
        OpRecord(KIND_CHECK, OUTCOME_SHED, seq=4, retry_after=None),
    ]
    got = check_never_fail_open(records)
    assert len(got) == 2
    assert "evil" in got[0].detail
    assert "Retry-After" in got[1].detail
    assert check_never_fail_open(records[:2]) == []


def test_invariant_acked_write_loss():
    records = [OpRecord(KIND_WRITE, OUTCOME_OK, seq=1, rel="r1"),
               OpRecord(KIND_WRITE, OUTCOME_OK, seq=2, rel="r2"),
               # an errored write carries NO obligation
               OpRecord(KIND_WRITE, "error", seq=3, rel="r3")]
    assert check_zero_acked_write_loss(
        records, {"r1": True, "r2": True}) == []
    got = check_zero_acked_write_loss(records, {"r1": True, "r2": False})
    assert len(got) == 1 and "r2" in got[0].detail
    # a missing read-back is a campaign bug, surfaced loudly
    assert len(check_zero_acked_write_loss(records, {"r1": True})) == 1


def test_invariant_no_stale_verdict():
    def probe(seq, v):
        return OpRecord(KIND_CHECK, OUTCOME_OK, seq=seq, key="k",
                        verdict=v, expected=None)

    base = [probe(1, True),
            OpRecord(KIND_DELETE, OUTCOME_OK, seq=2, key="k"),
            probe(3, True),  # pre-deny allow: replication lag, tolerated
            probe(4, False)]
    assert check_no_stale_verdict(base) == []
    stale = base + [probe(5, True)]  # allow AFTER a post-revocation deny
    got = check_no_stale_verdict(stale)
    assert len(got) == 1 and "stale" in got[0].invariant


def test_invariant_retry_amplification_and_check_all():
    assert check_retry_amplification(10.0, 0.1, 20.0, 100) == []
    got = check_retry_amplification(500.0, 0.1, 20.0, 100)
    assert len(got) == 1
    ev = EpisodeEvidence(
        name="unit",
        records=[OpRecord(KIND_CHECK, OUTCOME_OK, seq=1, key="x",
                          verdict=True, expected=False)],
        readback={}, pending_splits=2,
        retries_observed=999.0, budget_ratio=0.1, budget_burst=5.0,
        attempts=10)
    names = {v.invariant for v in check_all(ev)}
    assert names == {"never-fail-open", "split-journal-completion",
                     "retry-amplification"}
    assert isinstance(str(InvariantViolation("x", "y")), str)


# -- the in-process campaign smoke (tier-1) -----------------------------------


def test_inproc_campaign_one_seed_zero_violations(tmp_path):
    """The campaign machinery end-to-end without subprocesses: seeded
    load + a wire-shaped brownout schedule against 2 in-process shard
    groups, every invariant green, and the per-seed fault digest equal
    to the schedule's own (the reproducibility contract)."""
    cfg = CampaignConfig(seeds=(0,), episodes="short", inproc=True,
                         workdir=str(tmp_path))
    result = Campaign(cfg).run()
    assert result["ok"], result["violations"]
    assert result["violations"] == []
    assert result["seeds"]["0"]["fault_digest"] == BROWNOUT_SEED0_DIGEST
    episodes = {e["episode"] for e in result["episodes"]}
    assert episodes == {"seed0/baseline", "seed0/brownout",
                        "seed0/elastic", "seed0/migration"}
    # records actually flowed (checks, writes, lookups all exercised)
    assert all(e["records"] > 20 for e in result["episodes"])
    # the elastic episode completed its full grow -> shrink -> grow
    # cycle (each phase converged, group count home where it started +1
    # from the final grow)
    elastic = next(e for e in result["episodes"]
                   if e["episode"] == "seed0/elastic")
    phases = [(t["phase"], t["converged"]) for t in elastic["transitions"]]
    assert phases == [("grow", True), ("shrink", True),
                      ("regrow", True)]
    assert elastic["transitions"][-1]["groups"] == 3


# -- slow compositions (the CI chaos job) -------------------------------------


@pytest.mark.slow
def test_subprocess_campaign_one_seed(tmp_path):
    """The campaign's pytest regression home: one full seed against the
    real 2-group × 2-peer subprocess topology — brownout wire-armed
    through chaos_arm, SIGKILL/restart of a group leader, zero
    violations."""
    cfg = CampaignConfig(seeds=(0,), episodes="short",
                         workdir=str(tmp_path))
    result = Campaign(cfg).run()
    assert result["ok"], result["violations"]
    names = [e["episode"] for e in result["episodes"]]
    assert names == ["seed0/baseline", "seed0/brownout", "seed0/crash",
                     "seed0/elastic", "seed0/migration"]
    crash = result["episodes"][2]
    assert crash["killed"], "the crash episode never killed a leader"
    elastic = result["episodes"][3]
    assert elastic["killed"], \
        "the elastic episode never killed the retiring group's leader"
    assert [(t["phase"], t["converged"])
            for t in elastic["transitions"]] == \
        [("grow", True), ("shrink", True), ("regrow", True)]
    brown = result["episodes"][1]
    assert brown["retries_at_faulted_group"] is not None


@pytest.mark.slow
def test_sharded_watch_resumes_across_group_leader_sigkill(tmp_path):
    """ISSUE 12 satellite: ShardedWatchStream resumption COMPOSED with
    failover. Vector-stamped events resume with no gap and no duplicate
    after the observed group's leader is SIGKILLed and its failover
    peer takes over (PR 11 tested resumption and failover separately)."""
    topo = SubprocessTopology(workdir=str(tmp_path))
    try:
        topo.wait_ready()
        planner = topo.make_planner()
        smap = topo.map

        def ns_of(group):
            return next(f"ns{i}" for i in range(64)
                        if smap.shard_of("pod", f"ns{i}/p") == group)

        ns = {g: ns_of(g) for g in range(2)}

        def write(name, group):
            """Acked write of one unique watchable tuple; retries until
            acked (fail-closed windows are expected mid-election)."""
            deadline = time.monotonic() + 30.0
            while True:
                try:
                    planner.write_relationships([WriteOp(
                        "touch",
                        Relationship("pod", f"{ns[group]}/{name}",
                                     "viewer", "user", name, None))])
                    return
                except Exception:
                    if time.monotonic() >= deadline:
                        raise
                    time.sleep(0.3)

        start_vec = planner.revision_vector(refresh=True)
        stream = planner.watch_push_stream(start_vec)
        acked_a = []
        for i in range(4):
            g = i % 2
            write(f"wa{i}", g)
            acked_a.append(f"wa{i}")

        def drain(s, want, budget=30.0):
            """Collect event subject-ids until ``want`` are all seen or
            an error surfaces; returns (names seen in order, error)."""
            seen = []
            deadline = time.monotonic() + budget
            while time.monotonic() < deadline:
                try:
                    for ev in s.next_batch():
                        seen.append(ev.relationship.subject_id)
                except Exception as e:  # noqa: BLE001 - the kill signal
                    return seen, e
                if want <= set(seen):
                    return seen, None
            return seen, None

        seen_a, err = drain(stream, set(acked_a))
        assert err is None and set(acked_a) <= set(seen_a), \
            (seen_a, err)
        resume_vec = stream.revision  # the consumer's resumption token

        # SIGKILL the watched group's leader; the stream surfaces the
        # death (or goes quiet) — the consumer closes and resumes
        g, p = topo.kill_group_leader(0)
        seen_gap, _err = drain(stream, {"__nothing__"}, budget=4.0)
        stream.close()
        for name in seen_gap:
            resume_vec = stream.revision
        topo.wait_group_leader(0)
        topo.restart(g, p)

        acked_b = []
        for i in range(4):
            write(f"wb{i}", i % 2)
            acked_b.append(f"wb{i}")

        stream2 = planner.watch_push_stream(resume_vec)
        try:
            seen_b, err = drain(stream2, set(acked_b), budget=45.0)
        finally:
            stream2.close()
        assert err is None, err

        # NO GAP: every post-kill acked write's event arrived
        assert set(acked_b) <= set(seen_b), (acked_b, seen_b)
        # NO DUPLICATE: nothing observed before the kill reappears, and
        # nothing is delivered twice within either stream
        all_seen = seen_a + seen_gap + seen_b
        dups = {n for n in all_seen if all_seen.count(n) > 1}
        assert not dups, f"duplicated events across resumption: {dups}"
        # events carry monotone VECTOR stamps on the resumed stream too
        assert isinstance(stream2.revision, type(resume_vec))
        assert stream2.revision.dominates(resume_vec)
    finally:
        topo.close()


@pytest.mark.slow
def test_watch_resumes_across_leader_sigkill_in_dual_write_window(
        tmp_path):
    """ISSUE 14 satellite: the composed resumption scenario fired
    DURING a live rebalance's dual-write window. A SIGKILL of the
    moving slice's SOURCE group leader mid-window must lose no acked
    write (the mirrors ride the split journal), keep the merged watch
    stream gap- and duplicate-free across failover AND the eventual
    cutover, and leave the transition completed (chaos-invariant
    checked)."""
    from spicedb_kubeapi_proxy_tpu.chaos.invariants import (
        check_rebalance_converged,
    )
    from spicedb_kubeapi_proxy_tpu.scaleout import (
        MapTransition,
        RebalanceCoordinator,
        ShardMap,
        plan_moves,
    )
    from spicedb_kubeapi_proxy_tpu.scaleout.rebalance import DUAL

    topo = SubprocessTopology(workdir=str(tmp_path))
    try:
        topo.wait_ready()
        planner = topo.make_planner()
        smap = topo.map
        new_map = ShardMap(version=2, groups=smap.groups,
                           virtual_nodes=96)
        t = MapTransition(smap, new_map, plan_moves(smap, new_map))
        # the slice whose SOURCE is group 0 (the leader we will kill)
        sl = next(s for s in t.slices if s.src == 0)
        ns_dual = next(f"ns{i}" for i in range(128)
                       if t.slice_for_key(f"ns{i}", "pod") is sl)
        ns_calm = next(f"ns{i}" for i in range(128)
                       if t.slice_for_key(f"ns{i}", "pod") is None
                       and smap.shard_of("pod", f"ns{i}/p") == 1)

        acked = []

        def write(name, ns):
            """One acked watchable tuple; every RETRY mints a fresh
            subject so an ambiguous first attempt that actually landed
            cannot double-count an acked name (only acked names are
            asserted on)."""
            deadline = time.monotonic() + 45.0
            attempt = 0
            while True:
                sub = f"{name}a{attempt}"
                try:
                    planner.write_relationships([WriteOp(
                        "touch",
                        Relationship("pod", f"{ns}/p0", "viewer",
                                     "user", sub, None))])
                except Exception:  # noqa: BLE001 - fail-closed window
                    if time.monotonic() >= deadline:
                        raise
                    attempt += 1
                    time.sleep(0.3)
                else:
                    acked.append(sub)
                    return sub

        # phase A: pre-window traffic observed on a live stream
        start_vec = planner.revision_vector(refresh=True)
        stream = planner.watch_push_stream(start_vec)
        a_names = [write(f"wa{i}", ns_dual if i % 2 == 0 else ns_calm)
                   for i in range(4)]

        def drain(s, want, budget=30.0):
            seen = []
            deadline = time.monotonic() + budget
            while time.monotonic() < deadline:
                try:
                    for ev in s.next_batch():
                        seen.append(ev.relationship.subject_id)
                except Exception as e:  # noqa: BLE001 - kill signal
                    return seen, e
                if want <= set(seen):
                    return seen, None
            return seen, None

        seen_a, err = drain(stream, set(a_names))
        assert err is None and set(a_names) <= set(seen_a), (seen_a,
                                                            err)

        # phase B: open the dual-write window on the slice (white-box
        # phase driving — the window must be OPEN when the kill lands)
        planner._install_transition(t)
        coord = RebalanceCoordinator(planner, t)
        copy_rev, rows = coord._slice_read(sl.src, sl.ranges)
        coord._slice_load(sl.dst, rows)
        t.set_state(sl, "catchup", copy_rev=int(copy_rev),
                    replayed=int(copy_rev))
        while coord._catch_up_once(sl) > 0:
            pass
        t.set_state(sl, DUAL)
        coord._persist()

        # SIGKILL the source group's leader MID-WINDOW
        g, p = topo.kill_group_leader(0)
        seen_gap, _err = drain(stream, {"__nothing__"}, budget=4.0)
        resume_vec = stream.revision
        stream.close()
        # bring the killed peer back so the promoted survivor can meet
        # its --min-sync-replicas floor again (writes fail CLOSED until
        # then — the write() retry loop rides that window out)
        topo.restart(g, p)
        topo.wait_group_leader(0)

        # acked writes THROUGH the window: the slice's dual writes ride
        # the split journal; failover re-aims the source legs
        b_names = [write(f"wb{i}", ns_dual if i % 2 == 0 else ns_calm)
                   for i in range(4)]

        # phase C: drive the interrupted transition to COMPLETION
        # (re-copy is idempotent; the persisted state resumes forward)
        planner.recover_splits()
        coord.run_to_completion()
        assert planner.map.version == 2
        assert check_rebalance_converged(
            planner.journal.load_transition()) == []
        assert planner.journal.pending_count() == 0

        # post-cutover traffic, then resume the stream across the
        # whole history: failover + dual-write window + cutover
        c_names = [write(f"wc{i}", ns_dual if i % 2 == 0 else ns_calm)
                   for i in range(4)]
        stream2 = planner.watch_push_stream(resume_vec)
        try:
            seen_bc, err = drain(stream2, set(b_names + c_names),
                                 budget=60.0)
        finally:
            stream2.close()
        assert err is None, err

        # NO GAP: every acked write's event arrived exactly once; the
        # mover's copy/catch-up/GC echoes never surface
        want = set(b_names + c_names)
        missing = want - set(seen_bc)
        assert not missing, f"gap across window+cutover: {missing}"
        all_seen = [s for s in seen_a + seen_gap + seen_bc
                    if s in set(a_names) | want]
        dups = {n for n in all_seen if all_seen.count(n) > 1}
        assert not dups, f"duplicates across resumption: {dups}"

        # zero acked writes lost, never fail-open — read back at V+1
        for sub in acked:
            got = planner.lookup_resources("pod", "view", "user", sub)
            assert got, f"acked write {sub} lost across the window"
        assert not planner.check(CheckItem(
            "pod", f"{ns_dual}/p0", "view", "user", "intruder"))
    finally:
        topo.close()
