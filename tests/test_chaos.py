"""Failpoint-driven transport chaos for the resilience layer.

Deterministic by construction: failures come from armed failpoints
(`upstream.connect`, `upstream.read`, `engine.connect`, `engine.read`)
or from real connection-refused sockets on loopback, backoff schedules
are injected as all-zero (no sleeps), and breaker clocks are fake.

Covers the ISSUE acceptance pins: upstream dies before the status line
(GET retried once, POST never), upstream dies mid-watch-stream (partial
proto frame dropped, partial JSON line surfaced), engine refused then
recovered (breaker opens -> half-opens -> closes), an open engine
breaker failing an authorized list CLOSED with a 503 + Retry-After and
an unready /readyz naming the dependency, and single-attempt writes.
"""

import asyncio
import json
import os

import pytest

from spicedb_kubeapi_proxy_tpu.engine import Engine, WriteOp
from spicedb_kubeapi_proxy_tpu.engine.remote import (
    EngineServer,
    RemoteEngine,
)
from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship
from spicedb_kubeapi_proxy_tpu.proxy.inmemory import InMemoryClient
from spicedb_kubeapi_proxy_tpu.proxy.options import Options
from spicedb_kubeapi_proxy_tpu.proxy.types import ProxyRequest
from spicedb_kubeapi_proxy_tpu.proxy.upstream import HttpUpstream
from spicedb_kubeapi_proxy_tpu.utils.failpoints import (
    FailPointError,
    failpoints,
)
from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics
from spicedb_kubeapi_proxy_tpu.utils.resilience import (
    STATE_CLOSED,
    STATE_OPEN,
    BreakerOpen,
    CircuitBreaker,
    RetryPolicy,
)

from fake_kube import FakeKube, serve_upstream

pytestmark = pytest.mark.chaos

NO_BACKOFF = RetryPolicy(base=0.0, cap=0.0)

RULES = open(os.path.join(os.path.dirname(__file__), "..", "deploy",
                          "rules.yaml")).read()


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture(autouse=True)
def _clean_slate():
    failpoints.disable_all()
    metrics.reset()
    yield
    failpoints.disable_all()
    metrics.reset()


def _upstream(port, **kw):
    kw.setdefault("retries", 1)
    kw.setdefault("retry_policy", NO_BACKOFF)
    kw.setdefault("breaker",
                  CircuitBreaker("upstream", failure_threshold=100))
    return HttpUpstream(f"http://127.0.0.1:{port}", **kw)


# -- upstream: death before the status line ----------------------------------


def test_upstream_get_retried_once_on_pre_response_death():
    async def go():
        server, port = await serve_upstream(FakeKube())
        up = _upstream(port)
        for fp in ("upstream.connect", "upstream.read"):
            metrics.reset()
            # one pre-response death: the GET retries once and succeeds
            failpoints.enable(fp, 1)
            resp = await up(ProxyRequest(method="GET",
                                         path="/api/v1/namespaces"))
            assert resp.status == 200, fp
            retries = metrics.counter("proxy_dependency_retries_total",
                                      dependency="upstream")
            assert retries.value == 1.0, fp
        # deaths exceeding the retry budget surface the transport error
        failpoints.enable("upstream.connect", 2)
        with pytest.raises(FailPointError):
            await up(ProxyRequest(method="GET", path="/api/v1/namespaces"))
        server.close()
    asyncio.run(go())


def test_upstream_post_never_retried():
    async def go():
        server, port = await serve_upstream(FakeKube())
        up = _upstream(port, retries=3)
        # pre-connect death: even this NEVER retries a POST
        failpoints.enable("upstream.connect", 2)
        with pytest.raises(FailPointError):
            await up(ProxyRequest(method="POST", path="/api/v1/namespaces",
                                  body=b"{}"))
        assert failpoints.armed("upstream.connect"), \
            "exactly one attempt: one of the two armed hits must remain"
        failpoints.disable_all()
        # post-send death (request bytes are on the wire): same — the
        # upstream may already be applying the write
        failpoints.enable("upstream.read", 2)
        with pytest.raises(FailPointError):
            await up(ProxyRequest(method="POST", path="/api/v1/namespaces",
                                  body=b"{}"))
        assert failpoints.armed("upstream.read")
        retries = metrics.counter("proxy_dependency_retries_total",
                                  dependency="upstream")
        assert retries.value == 0.0
        server.close()
    asyncio.run(go())


# -- upstream: death mid-watch-stream ----------------------------------------


async def _canned_http_server(payload: bytes):
    """Serve exactly ``payload`` after consuming a request head, then
    close — an upstream that dies mid-response."""
    async def conn(reader, writer):
        try:
            await reader.readuntil(b"\r\n\r\n")
            writer.write(payload)
            await writer.drain()
        finally:
            writer.close()

    server = await asyncio.start_server(conn, "127.0.0.1", 0)
    return server, server.sockets[0].getsockname()[1]


def _watch_req():
    return ProxyRequest(method="GET", path="/api/v1/namespaces",
                        query={"watch": ["true"]},
                        headers={"Accept": "application/json"})


def test_upstream_dies_mid_proto_watch_drops_partial_frame():
    async def go():
        whole = (3).to_bytes(4, "big") + b"abc"
        torso = (100).to_bytes(4, "big") + b"only-ten"  # 92 bytes missing
        head = (b"HTTP/1.1 200 OK\r\n"
                b"Content-Type: application/vnd.kubernetes.protobuf;"
                b"stream=watch\r\n\r\n")
        server, port = await _canned_http_server(head + whole + torso)
        up = _upstream(port)
        resp = await up(_watch_req())
        assert resp.status == 200 and resp.stream is not None
        frames = [f async for f in resp.stream]
        # the complete frame arrives intact (length prefix preserved for
        # byte-identical passthrough); the dead connection's torso is
        # DROPPED, never surfaced as a truncated frame
        assert frames == [whole]
        server.close()
    asyncio.run(go())


def test_upstream_dies_mid_json_watch_surfaces_partial_line():
    async def go():
        head = (b"HTTP/1.1 200 OK\r\n"
                b"Content-Type: application/json\r\n\r\n")
        body = b'{"type":"ADDED"}\n{"type":"MODI'  # cut mid-event
        server, port = await _canned_http_server(head + body)
        up = _upstream(port)
        resp = await up(_watch_req())
        frames = [f async for f in resp.stream]
        # JSON framing is newline-delimited: the partial tail is still
        # surfaced (the downstream join refuses to judge it), unlike the
        # self-describing proto torso above
        assert frames == [b'{"type":"ADDED"}\n', b'{"type":"MODI']
        server.close()
    asyncio.run(go())


# -- upstream: garbled chunk-size line ----------------------------------------


def test_garbled_chunk_size_is_a_connection_error():
    async def go():
        head = (b"HTTP/1.1 200 OK\r\n"
                b"Transfer-Encoding: chunked\r\n\r\n")
        server, port = await _canned_http_server(head + b"zz-not-hex\r\n")
        up = _upstream(port, retries=0)
        with pytest.raises(ConnectionResetError, match="chunk-size"):
            await up(ProxyRequest(method="GET", path="/api/v1/namespaces"))
        # a NEGATIVE size parses as an int but is just as garbled — it
        # must not leak as readexactly's bare ValueError either
        server_neg, port_neg = await _canned_http_server(head + b"-5\r\n")
        up_neg = _upstream(port_neg, retries=0)
        with pytest.raises(ConnectionResetError, match="chunk-size"):
            await up_neg(ProxyRequest(method="GET",
                                      path="/api/v1/namespaces"))
        server_neg.close()
        # streaming path classifies it the same way: the watch ends
        # instead of ValueError escaping through the frame iterator
        server2, port2 = await _canned_http_server(head + b"zz-not-hex\r\n")
        up2 = _upstream(port2, retries=0)
        resp = await up2(_watch_req())
        with pytest.raises(ConnectionResetError, match="chunk-size"):
            async for _ in resp.stream:
                pass
        server.close()
        server2.close()
    asyncio.run(go())


# -- engine: refused then recovers (breaker full cycle) -----------------------


def test_engine_refused_then_recovers_breaker_cycle():
    async def go():
        e = Engine()
        srv = EngineServer(e)
        port = await srv.start()
        await srv.stop()  # connections now refused
        clock = FakeClock()
        breaker = CircuitBreaker(f"engine:127.0.0.1:{port}",
                                 failure_threshold=2, reset_timeout=5.0,
                                 clock=clock)
        remote = RemoteEngine("127.0.0.1", port, retries=0,
                              retry_policy=NO_BACKOFF, breaker=breaker)
        for _ in range(2):
            with pytest.raises(OSError):
                await asyncio.to_thread(lambda: remote.revision)
        assert breaker.state == STATE_OPEN
        # fail-fast: no socket is touched while open
        with pytest.raises(BreakerOpen):
            await asyncio.to_thread(lambda: remote.revision)
        # the engine host comes back on the same port; after the reset
        # window the half-open probe succeeds and the circuit closes
        srv2 = EngineServer(e, port=port)
        await srv2.start()
        clock.advance(5.0)
        assert await asyncio.to_thread(lambda: remote.revision) \
            == e.revision
        assert breaker.state == STATE_CLOSED
        state = metrics.gauge("proxy_dependency_breaker_state",
                              dependency=f"engine:127.0.0.1:{port}")
        assert state.value == STATE_CLOSED
        remote.close()
        await srv2.stop()
    asyncio.run(go())


def test_engine_stall_is_bounded_by_one_total_deadline():
    """A host that ACCEPTS but never answers must stall a read for at
    most ~the read timeout TOTAL — retries share one deadline instead of
    multiplying the worst case by attempts, and the exhausted budget
    surfaces as the 503-mapped DeadlineExceeded."""
    import time as _time

    from spicedb_kubeapi_proxy_tpu.utils.resilience import DeadlineExceeded

    async def go():
        async def black_hole(reader, writer):
            await reader.read()  # consume forever, never respond

        server = await asyncio.start_server(black_hole, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        remote = RemoteEngine("127.0.0.1", port, timeout=0.3, retries=5,
                              retry_policy=NO_BACKOFF,
                              breaker=CircuitBreaker(
                                  f"engine:127.0.0.1:{port}",
                                  failure_threshold=100))
        t0 = _time.monotonic()
        with pytest.raises(DeadlineExceeded):
            await asyncio.to_thread(lambda: remote.revision)
        elapsed = _time.monotonic() - t0
        # 6 attempts at 0.3s each would be ~1.8s; the shared deadline
        # caps the whole call near one read-timeout
        assert elapsed < 1.0, elapsed
        remote.close()
        server.close()
    asyncio.run(go())


def test_engine_read_ops_retry_and_count_metrics():
    async def go():
        e = Engine()
        e.write_relationships([WriteOp("touch", parse_relationship(
            "namespace:dev#creator@user:alice"))])
        srv = EngineServer(e)
        port = await srv.start()
        remote = RemoteEngine("127.0.0.1", port, retries=2,
                              retry_policy=NO_BACKOFF,
                              breaker=CircuitBreaker(
                                  f"engine:127.0.0.1:{port}",
                                  failure_threshold=100))
        # a read op absorbs transport deaths within its retry budget
        failpoints.enable("engine.read", 2)
        ids = await asyncio.to_thread(
            remote.lookup_resources, "namespace", "view", "user", "alice")
        assert ids == ["dev"]
        retries = metrics.counter(
            "proxy_dependency_retries_total",
            dependency=f"engine:127.0.0.1:{port}")
        assert retries.value == 2.0
        assert "proxy_dependency_retries_total" in metrics.render()
        remote.close()
        await srv.stop()
    asyncio.run(go())


def test_engine_writes_never_retried_single_attempt():
    async def go():
        e = Engine()
        srv = EngineServer(e)
        port = await srv.start()
        remote = RemoteEngine("127.0.0.1", port, retries=3,
                              retry_policy=NO_BACKOFF,
                              breaker=CircuitBreaker(
                                  f"engine:127.0.0.1:{port}",
                                  failure_threshold=100))
        rel = parse_relationship("namespace:dev#creator@user:alice")
        # post-send failpoint: the request reached the engine host, the
        # response never came — a replay could double-apply the write
        failpoints.enable("engine.read", 2)
        with pytest.raises(FailPointError):
            await asyncio.to_thread(
                remote.write_relationships, [WriteOp("touch", rel)])
        assert failpoints.armed("engine.read"), \
            "exactly one attempt: one of the two armed hits must remain"
        failpoints.disable("engine.read")
        retries = metrics.counter(
            "proxy_dependency_retries_total",
            dependency=f"engine:127.0.0.1:{port}")
        assert retries.value == 0.0
        # the single attempt DID land server-side even though the client
        # never saw a response — exactly why replays are unsafe. The
        # server dispatches the buffered frame asynchronously after the
        # client hangs up, so wait (bounded) for it to apply.
        from spicedb_kubeapi_proxy_tpu.engine import CheckItem

        item = CheckItem("namespace", "dev", "view", "user", "alice")
        for _ in range(200):
            if e.revision >= 1:
                break
            await asyncio.sleep(0.01)
        assert e.check(item)
        remote.close()
        await srv.stop()
    asyncio.run(go())


def test_open_upstream_breaker_fails_dual_write_fast_with_503(tmp_path):
    """A dual-write against a hard-open upstream breaker gets the same
    fail-closed 503 + Retry-After as reads — BEFORE the workflow is
    durably enqueued (a BreakerOpen inside an activity would otherwise
    burn the workflow retry budget and surface as a 502)."""
    async def go():
        fake = FakeKube()
        upstream_server, upstream_port = await serve_upstream(fake)
        cfg = Options(
            rule_content=RULES,
            upstream_url=f"http://127.0.0.1:{upstream_port}",
            workflow_database_path=str(tmp_path / "dtx.sqlite"),
        ).complete()
        await cfg.workflow.resume_pending()
        cfg.deps.upstream.breaker.force_open()
        alice = InMemoryClient(cfg.server.handle, user="alice")
        resp = await alice.post("/api/v1/namespaces", {
            "apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": "blocked"}})
        assert resp.status == 503, resp.body
        assert int(resp.headers["Retry-After"]) >= 1
        assert json.loads(resp.body)["reason"] == "ServiceUnavailable"
        # nothing reached the upstream and nothing landed in the graph
        assert not any(r.method == "POST" for r in fake.requests)
        await cfg.workflow.shutdown()
        upstream_server.close()
    asyncio.run(go())


# -- mirror-stream chaos: partitions and heartbeat loss -----------------------


def test_mirror_partition_failpoint_drops_frame_and_follower_detects_gap():
    """`mirror.partition` drops mirror frames on the floor (a one-sided
    network partition). The follower must DETECT the gap and fail shut
    (MultiHostError) rather than silently diverge."""
    import threading
    import time as _time

    from spicedb_kubeapi_proxy_tpu.engine import WriteOp
    from spicedb_kubeapi_proxy_tpu.parallel.multihost import (
        MirroredEngine,
        MultiHostError,
        follower_loop,
    )

    async def go():
        inner = Engine()
        leader = MirroredEngine(inner, term=1, mirror_queries=False)
        srv = EngineServer(leader)
        srv.mirror_heartbeat = 0.2
        port = await srv.start()
        follower = Engine()
        result: dict = {}

        def run_follower():
            try:
                follower_loop(follower, "127.0.0.1", port,
                              from_revision=0, current_term=1,
                              heartbeat_timeout=10.0, fail_on_loss=True)
            except Exception as e:  # noqa: BLE001
                result["err"] = e

        t = threading.Thread(target=run_follower, daemon=True)
        t.start()
        # deterministic ordering: the follower must be SUBSCRIBED before
        # the first write, so that write arrives as a live frame (the
        # gap check baselines on live frames, not the catch-up cut)
        deadline = _time.monotonic() + 10
        while not leader._subs and _time.monotonic() < deadline:
            await asyncio.sleep(0.02)
        assert leader._subs, "follower never subscribed"

        def write(i):
            leader.write_relationships([WriteOp("touch", parse_relationship(
                f"namespace:n{i}#creator@user:u1"))])

        await asyncio.to_thread(write, 1)  # seq 1: sets the baseline
        for _ in range(100):
            if follower.revision >= 1:
                break
            await asyncio.sleep(0.05)
        assert follower.revision == 1
        failpoints.enable("mirror.partition", 1)
        await asyncio.to_thread(write, 2)  # seq 2: dropped by the void
        await asyncio.to_thread(write, 3)  # seq 3: exposes the gap
        deadline = _time.monotonic() + 10
        while "err" not in result and _time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        assert isinstance(result.get("err"), MultiHostError), result
        assert "gap" in str(result["err"])
        # the partitioned frame NEVER applied: no silent divergence
        assert follower.revision == 1
        t.join(5)
        await srv.stop()

    asyncio.run(go())


def test_mirror_heartbeat_failpoint_surfaces_leader_loss():
    """`mirror.heartbeat` suppresses liveness heartbeats on an idle
    stream: the follower must classify the silence as a dead leader
    (LeaderLost + `mirror_heartbeat_misses_total`) — the trigger the
    election path runs on."""
    from spicedb_kubeapi_proxy_tpu.parallel.multihost import (
        LeaderLost,
        MirroredEngine,
        follower_loop,
    )

    async def go():
        leader = MirroredEngine(Engine(), term=1, mirror_queries=False)
        srv = EngineServer(leader)
        srv.mirror_heartbeat = 0.1
        port = await srv.start()
        failpoints.enable("mirror.heartbeat", 1000)
        follower = Engine()
        with pytest.raises(LeaderLost):
            await asyncio.to_thread(
                follower_loop, follower, "127.0.0.1", port,
                from_revision=0, current_term=1,
                heartbeat_timeout=0.6, fail_on_loss=True)
        misses = metrics.counter("mirror_heartbeat_misses_total")
        assert misses.value >= 1
        await srv.stop()

    asyncio.run(go())


# -- the acceptance pin: fail-closed 503 through the whole proxy --------------


def test_open_engine_breaker_fails_list_closed_with_503_and_readyz(tmp_path):
    async def go():
        e = Engine()
        srv = EngineServer(e)
        port = await srv.start()
        dep = f"engine:127.0.0.1:{port}"
        cfg = Options(
            engine_endpoint=f"tcp://127.0.0.1:{port}",
            engine_insecure=True,
            rule_content=RULES,
            upstream=FakeKube(),
            workflow_database_path=str(tmp_path / "dtx.sqlite"),
            engine_retries=0,
            breaker_failure_threshold=1,
            breaker_reset_seconds=60.0,
        ).complete()
        await cfg.workflow.resume_pending()
        alice = InMemoryClient(cfg.server.handle, user="alice")

        # healthy baseline: authorized list succeeds, /readyz is 200
        resp = await alice.get("/api/v1/namespaces")
        assert resp.status == 200
        assert (await alice.get("/readyz")).status == 200

        # the engine host wedges: one transport death trips the breaker
        failpoints.enable("engine.read", 1)
        resp = await alice.get("/api/v1/namespaces")
        assert resp.status >= 500
        assert cfg.engine.breaker.state == STATE_OPEN

        # fail-CLOSED and bounded: 503 with Retry-After, never a hang,
        # never a fail-open 200 list
        resp = await alice.get("/api/v1/namespaces")
        assert resp.status == 503, resp.body
        status = json.loads(resp.body)
        assert status["kind"] == "Status"
        assert status["reason"] == "ServiceUnavailable"
        assert dep in status["message"]
        assert int(resp.headers["Retry-After"]) >= 1
        assert int(resp.headers["Retry-After"]) <= 60

        # /readyz turns unready NAMING the engine dependency
        resp = await alice.get("/readyz")
        assert resp.status == 503
        assert f"[-]{dep}" in resp.body.decode()
        assert "circuit open" in resp.body.decode()
        # liveness is about the process, not its dependencies
        assert (await alice.get("/livez")).status == 200

        # breaker state + failure counters are visible on /metrics
        body = (await alice.get("/metrics")).body.decode()
        assert (f'proxy_dependency_breaker_state{{dependency="{dep}"}} '
                f'{float(STATE_OPEN)}') in body
        assert 'proxy_dependency_unavailable_total' in body

        await cfg.workflow.shutdown()
        cfg.engine.close()
        await srv.stop()
    asyncio.run(go())
