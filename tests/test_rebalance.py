"""Online shard rebalancing: the live tuple mover (ISSUE 14).

Covers the acceptance surface:

- ring-diff planning (moving slice set; a pure version bump moves
  nothing; every key whose owner changed falls in exactly one slice);
- the versioned RevisionVector satellite (encode/parse carry the
  shard-map version; cross-version tokens are rejected, translated
  only through a recorded transition — never misindexed);
- end-to-end live moves, in-process and over loopback TCP engine
  groups: zero acked writes lost, never fail-open, watch streams gap-
  and duplicate-free across cutover, goodput on non-moving slices
  held during the move;
- the dual-write window mirroring through the split journal (entries
  tagged with both versions; a mid-window planner crash replays to
  completion);
- the crash matrix: no slice cut -> clean abort (copies dropped,
  routing never left V); >= 1 slice cut -> resume to completion;
  committed-but-uncleared -> finish at boot (chaos-invariant checked);
- mover traffic admission-classed `rebalance` and shed-aware;
- /readyz's `rebalance:` line and --rebalance-to options validation.
"""

import os
import sys
import threading
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from spicedb_kubeapi_proxy_tpu.admission import (  # noqa: E402
    REBALANCE,
    AdmissionRejected,
    classify_op,
)
from spicedb_kubeapi_proxy_tpu.chaos.invariants import (  # noqa: E402
    check_rebalance_converged,
)
from spicedb_kubeapi_proxy_tpu.engine import Engine  # noqa: E402
from spicedb_kubeapi_proxy_tpu.engine.engine import CheckItem  # noqa: E402
from spicedb_kubeapi_proxy_tpu.engine.store import (  # noqa: E402
    RelationshipFilter,
    WriteOp,
)
from spicedb_kubeapi_proxy_tpu.models.tuples import (  # noqa: E402
    Relationship,
)
from spicedb_kubeapi_proxy_tpu.scaleout import (  # noqa: E402
    MapTransition,
    RebalanceCoordinator,
    RevisionVector,
    ShardedEngine,
    ShardMap,
    ShardMapError,
    SplitJournal,
    hash_key,
    plan_moves,
)
from spicedb_kubeapi_proxy_tpu.scaleout.rebalance import (  # noqa: E402
    CUT,
    DUAL,
    abort_transition,
)
from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics  # noqa: E402

SCHEMA_YAML = """\
schema: |-
  use expiration

  definition user {}

  definition group {
    relation member: user
  }

  definition namespace {
    relation creator: user
    relation viewer: user | group#member
    permission admin = creator
    permission view = viewer + creator
  }

  definition pod {
    relation namespace: namespace
    relation creator: user
    relation viewer: user
    permission edit = creator
    permission view = viewer + creator + namespace->view
  }
relationships: ""
"""


def _engine() -> Engine:
    return Engine(bootstrap=SCHEMA_YAML)


def _map(n: int, version: int = 1, vnodes: int = 64) -> ShardMap:
    return ShardMap(version=version,
                    groups=tuple((("127.0.0.1", 0),) for _ in range(n)),
                    virtual_nodes=vnodes)


def rel(rt, rid, rl, st, sid, srl=None) -> Relationship:
    return Relationship(rt, rid, rl, st, sid, srl)


def _seed_writes(n_ns: int, users: int = 4) -> list:
    out = []
    for i in range(n_ns):
        out.append(WriteOp("create", rel(
            "namespace", f"ns{i}", "viewer", "user", f"u{i % users}")))
        out.append(WriteOp("create", rel(
            "pod", f"ns{i}/p0", "namespace", "namespace", f"ns{i}")))
        out.append(WriteOp("create", rel(
            "pod", f"ns{i}/p0", "viewer", "user", f"u{i % users}")))
    return out


def _moving_split(t: MapTransition, n_ns: int):
    """(moving, staying) namespace name lists under transition ``t``."""
    moving, staying = [], []
    for i in range(n_ns):
        (moving if t.slice_for_key(f"ns{i}", "pod") is not None
         else staying).append(f"ns{i}")
    return moving, staying


# -- planning ----------------------------------------------------------------


def test_plan_moves_version_bump_moves_nothing():
    assert plan_moves(_map(2, 1), _map(2, 2)) == []


def test_plan_moves_covers_exactly_the_changed_keys():
    old, new = _map(2, 1, vnodes=64), _map(2, 2, vnodes=96)
    moves = plan_moves(old, new)
    assert moves, "a vnode change must move slices"
    t = MapTransition(old, new, moves)
    for i in range(400):
        ns = f"ns{i}"
        sl = t.slice_for_key(ns, "pod")
        src = old.shard_for(ns, "pod")
        dst = new.shard_for(ns, "pod")
        if src == dst:
            assert sl is None, (ns, "unchanged key inside a slice")
        else:
            assert sl is not None, (ns, "changed key outside all slices")
            assert (sl.src, sl.dst) == (src, dst)
    # grow: adding a group produces slices INTO the new group only
    grown = _map(3, 2)
    for sl in plan_moves(_map(2, 1), grown):
        assert sl.dst == 2 and sl.src in (0, 1)


# -- revision-vector map-version satellite -----------------------------------


def test_revision_vector_encode_parse_carry_map_version():
    v = RevisionVector((3, 5))
    assert v.encode() == "v3.5"
    tagged = v.encode(map_version=2)
    assert tagged == "v3.5@m2"
    assert RevisionVector.parse(tagged) == (3, 5)
    assert RevisionVector.parse(tagged, map_version=2) == (3, 5)
    assert RevisionVector.parse_versioned(tagged) == ((3, 5), 2)
    assert RevisionVector.parse_versioned("v3.5") == ((3, 5), None)
    # a vector minted under ANOTHER map version is rejected, not bound
    # to whatever groups now sit at those indices
    with pytest.raises(ShardMapError, match="minted under"):
        RevisionVector.parse(tagged, map_version=3)
    with pytest.raises(ShardMapError):
        RevisionVector.parse("v3.5@mX")
    assert RevisionVector((1, 2)).extend(4) == (1, 2, 0, 0)


def test_planner_rejects_wrong_size_or_unknown_version_tokens():
    engines = [_engine(), _engine(), _engine()]
    p = ShardedEngine(_map(3), engines)
    # a 2-component vector against a 3-group planner used to misindex;
    # now it is rejected (no recorded transition explains the growth)
    with pytest.raises(ShardMapError):
        p.watch_since(RevisionVector((1, 2)))
    with pytest.raises(ShardMapError, match="no transition"):
        p.watch_since("v1.2.3@m99")
    assert p.watch_since("v0.0.0@m1") == []  # current version: fine
    p.close()


# -- live move, in process ---------------------------------------------------


def test_inproc_rebalance_end_to_end(tmp_path):
    n_ns = 24
    old, new = _map(2, 1), _map(2, 2, vnodes=96)
    engines = [_engine(), _engine()]
    journal = SplitJournal(str(tmp_path / "sj.sqlite"))
    p = ShardedEngine(old, engines, journal=journal)
    p.write_relationships(_seed_writes(n_ns))
    users = [f"u{i}" for i in range(4)]
    before = {u: sorted(p.lookup_resources("pod", "view", "user", u))
              for u in users}

    coord = p.begin_rebalance(new)
    assert coord.wait(90), "mover never finished"
    assert coord.error is None, coord.error
    assert p.map.version == 2

    # zero acked writes lost; lookups byte-identical
    after = {u: sorted(p.lookup_resources("pod", "view", "user", u))
             for u in users}
    assert before == after
    for i in range(n_ns):
        assert p.check(CheckItem("pod", f"ns{i}/p0", "view", "user",
                                 f"u{i % 4}"))
        # never fail-open for a never-granted subject
        assert not p.check(CheckItem("pod", f"ns{i}/p0", "view",
                                     "user", "intruder"))
    # GC: each namespaced tuple lives on exactly its NEW owner
    for i in range(n_ns):
        f = RelationshipFilter(resource_type="pod",
                               resource_id=f"ns{i}/p0")
        holders = [gi for gi, e in enumerate(engines)
                   if e.store.exists(f)]
        assert holders == [new.shard_for(f"ns{i}", "pod")], (i, holders)
    # the durable completion marker (phase "done") persists so a
    # stale-flag restart cannot re-run the move against the GC'd
    # source; the converged invariant treats it as completed
    assert journal.load_transition()["phase"] == "done"
    assert journal.pending_count() == 0
    assert check_rebalance_converged(journal.load_transition()) == []
    p.close()


def test_rebalance_grow_one_to_two_groups_translates_tokens():
    old = _map(1, 1)
    new = _map(2, 2)
    engines = [_engine()]
    extra = _engine()
    p = ShardedEngine(old, engines)
    n_ns = 16
    p.write_relationships(_seed_writes(n_ns))
    # a V-minted resumption token (1 component, tagged)
    token = p.revision_vector().encode(map_version=1)

    coord = p.begin_rebalance(new, new_clients={1: extra})
    assert coord.wait(90) and coord.error is None, coord.error
    assert p.map.version == 2 and len(p.groups) == 2

    # the new group holds its slices AND the replicated globals
    moved = [f"ns{i}" for i in range(n_ns)
             if new.shard_for(f"ns{i}", "pod") == 1]
    assert moved, "fixture must move something to the new group"
    for ns in moved:
        assert extra.store.exists(RelationshipFilter(
            resource_type="pod", resource_id=f"{ns}/p0"))
        assert extra.store.exists(RelationshipFilter(
            resource_type="namespace", resource_id=ns))
    # the 1-component V token translates (new component from zero) and
    # replays NO mover echoes: every tuple it replays was already
    # acked before the token was minted -> zero events expected
    replay = p.watch_since(token)
    assert replay == [], [
        (e.relationship.resource_id, e.operation) for e in replay]
    # lookups still exact across the grown placement
    for u in (f"u{i}" for i in range(4)):
        got = sorted(p.lookup_resources("pod", "view", "user", u))
        want = sorted(f"ns{i}/p0" for i in range(n_ns)
                      if f"u{i % 4}" == u)
        assert got == want
    p.close()


def test_watch_stream_gap_and_duplicate_free_across_cutover():
    """The tentpole's watch-continuity core: a stream opened before the
    move sees every acked write exactly once — none of the mover's
    copy/catch-up/dual/GC echoes, no gap at the flip."""
    n_ns = 16
    old, new = _map(2, 1), _map(2, 2, vnodes=96)
    engines = [_engine(), _engine()]
    p = ShardedEngine(old, engines)
    p.write_relationships(_seed_writes(n_ns))
    t = MapTransition(old, new, plan_moves(old, new))
    moving, staying = _moving_split(t, n_ns)
    assert moving and staying

    stream = p.watch_push_stream(p.revision_vector())
    acked = []
    stop = threading.Event()

    def writer():
        i = 0
        while not stop.is_set():
            ns = (moving + staying)[i % n_ns]
            name = f"w{i}"
            p.write_relationships([WriteOp("touch", rel(
                "pod", f"{ns}/p0", "viewer", "user", name))])
            acked.append(name)
            i += 1
            time.sleep(0.005)

    wt = threading.Thread(target=writer, daemon=True)
    wt.start()
    try:
        coord = p.begin_rebalance(new, pace_seconds=0.002,
                                  batch_rows=16)
        assert coord.wait(120) and coord.error is None, coord.error
    finally:
        stop.set()
        wt.join(10)
    # drain the stream until every acked write's event arrived
    want = set(acked)
    seen = []
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        for e in stream.next_batch():
            if e.relationship.subject_id.startswith("w"):
                seen.append(e.relationship.subject_id)
        if want <= set(seen):
            break
    stream.close()
    missing = want - set(seen)
    assert not missing, f"gap across cutover: {sorted(missing)[:5]}"
    dups = {n for n in seen if seen.count(n) > 1}
    assert not dups, f"duplicates across cutover: {sorted(dups)[:5]}"
    p.close()


# -- dual-write window -------------------------------------------------------


def test_dual_write_window_mirrors_and_tags_journal(tmp_path):
    n_ns = 12
    old, new = _map(2, 1), _map(2, 2, vnodes=96)
    engines = [_engine(), _engine()]
    journal = SplitJournal(str(tmp_path / "sj.sqlite"))
    p = ShardedEngine(old, engines, journal=journal)
    p.write_relationships(_seed_writes(n_ns))
    t = MapTransition(old, new, plan_moves(old, new))
    moving, _ = _moving_split(t, n_ns)
    ns = moving[0]
    sl = t.slice_for_key(ns, "pod")
    # open the window by hand: copy, then DUAL (the coordinator's own
    # sequencing is covered by the end-to-end tests)
    p._install_transition(t)
    coord = RebalanceCoordinator(p, t)
    copy_rev, rows = coord._slice_read(sl.src, sl.ranges)
    coord._slice_load(sl.dst, rows)
    t.set_state(sl, "catchup", copy_rev=copy_rev, replayed=copy_rev)
    while coord._catch_up_once(sl) > 0:
        pass
    t.set_state(sl, DUAL)

    before = metrics.counter(
        "scaleout_rebalance_dual_writes_total").value
    p.write_relationships([WriteOp("touch", rel(
        "pod", f"{ns}/p0", "viewer", "user", "mirrored"))])
    assert metrics.counter(
        "scaleout_rebalance_dual_writes_total").value > before
    # the write landed on BOTH owners
    f = RelationshipFilter(resource_type="pod", resource_id=f"{ns}/p0",
                           subject_id="mirrored")
    assert engines[sl.src].store.exists(f)
    assert engines[sl.dst].store.exists(f)
    assert journal.pending_count() == 0
    # reads still route at V (src)
    s_before = metrics.counter("scaleout_ops_total", group=str(sl.src),
                               op="check_bulk", mode="single").value
    assert p.check(CheckItem("pod", f"{ns}/p0", "view", "user",
                             "mirrored"))
    assert metrics.counter("scaleout_ops_total", group=str(sl.src),
                           op="check_bulk", mode="single"
                           ).value == s_before + 1
    p.close(close_journal=False)

    # a mid-window planner crash: the mirrored split stays replayable
    # (tagged with BOTH versions -> NOT re-routed by recovery)
    engines2 = [_engine(), _engine()]
    p2 = ShardedEngine(old, engines2, journal=journal, recover=False)
    p2._install_transition(MapTransition.from_doc(t.to_doc(), old))
    sl2 = p2._active_transition.slices[sl.sid]
    p2._active_transition.set_state(sl2, DUAL)

    class _Dying:
        def __init__(self, inner):
            self._inner = inner

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def write_relationships(self, ops, preconditions=()):
            self._inner.write_relationships(ops, preconditions)
            raise ConnectionResetError("crash after first owner")

    p2.groups[max(sl.src, sl.dst)] = _Dying(
        p2.groups[max(sl.src, sl.dst)])
    with pytest.raises(ConnectionResetError):
        p2.write_relationships([WriteOp("touch", rel(
            "pod", f"{ns}/p0", "viewer", "user", "window-crash"))])
    ent = journal.pending()[0]
    assert ent["map_version"] == 1 and ent["map_version_to"] == 2
    p2.close(close_journal=False)
    # "restart" mid-window with NO slice cut: the pending dual-write
    # split replays FIRST (the entry names both versions, so the
    # recorded owners route as-is), then the transition aborts cleanly
    # — source keeps every acked write, destination copies are dropped
    p3 = ShardedEngine(old, engines2, journal=journal)
    assert journal.pending_count() == 0
    assert journal.load_transition() is None
    wc = RelationshipFilter(resource_type="pod",
                            resource_id=f"{ns}/p0",
                            subject_id="window-crash")
    assert engines2[sl.src].store.exists(wc)
    assert not engines2[sl.dst].store.exists(wc), \
        "aborted transition left a stale destination copy"
    # and the planner (routing at V) serves it
    assert p3.exists(wc)
    p3.close()


# -- crash matrix ------------------------------------------------------------


def _persisted_transition(tmp_path, n_ns=12, cut_first=False):
    """Build engines + journal holding a mid-flight transition record;
    returns (old, new, engines, journal, transition)."""
    old, new = _map(2, 1), _map(2, 2, vnodes=96)
    engines = [_engine(), _engine()]
    journal = SplitJournal(str(tmp_path / "sj.sqlite"))
    p = ShardedEngine(old, engines, journal=journal)
    p.write_relationships(_seed_writes(n_ns))
    t = MapTransition(old, new, plan_moves(old, new))
    p._install_transition(t)
    coord = RebalanceCoordinator(p, t)
    for i, sl in enumerate(t.slices):
        copy_rev, rows = coord._slice_read(sl.src, sl.ranges)
        coord._slice_load(sl.dst, rows)
        t.set_state(sl, "catchup", copy_rev=copy_rev,
                    replayed=copy_rev)
        while coord._catch_up_once(sl) > 0:
            pass
        if cut_first and i == 0:
            src_cut = coord._src_revision(sl.src)
            dst_cut = coord._src_revision(sl.dst)
            t.set_state(sl, CUT, src_cut=src_cut, dst_cut=dst_cut)
    coord._persist()
    p.close(close_journal=False)  # the "SIGKILL": record stays
    return old, new, engines, journal, t


def test_crash_before_any_cut_aborts_cleanly(tmp_path):
    old, new, engines, journal, t = _persisted_transition(tmp_path)
    assert journal.load_transition() is not None
    # invariant checker: a still-persisted record is a violation...
    assert check_rebalance_converged(journal.load_transition())
    p2 = ShardedEngine(old, engines, journal=journal)
    # ...and recovery resolves it: clean abort — record cleared,
    # routing still at V, the destination copies dropped
    assert journal.load_transition() is None
    assert check_rebalance_converged(journal.load_transition()) == []
    assert p2.map.version == 1
    for i in range(12):
        ns = f"ns{i}"
        f = RelationshipFilter(resource_type="pod",
                               resource_id=f"{ns}/p0")
        holders = [gi for gi, e in enumerate(engines)
                   if e.store.exists(f)]
        assert holders == [old.shard_for(ns, "pod")], (ns, holders)
        assert p2.check(CheckItem("pod", f"{ns}/p0", "view", "user",
                                  f"u{i % 4}"))
    p2.close()


def test_crash_after_first_cut_resumes_to_completion(tmp_path):
    old, new, engines, journal, t = _persisted_transition(
        tmp_path, cut_first=True)
    p2 = ShardedEngine(old, engines, journal=journal)
    # past the point of no return: a coordinator auto-resumed at boot
    assert p2._coordinator is not None
    assert p2._coordinator.wait(90)
    assert p2._coordinator.error is None, p2._coordinator.error
    assert p2.map.version == 2
    assert journal.load_transition()["phase"] == "done"
    assert check_rebalance_converged(journal.load_transition()) == []
    for i in range(12):
        ns = f"ns{i}"
        assert p2.check(CheckItem("pod", f"{ns}/p0", "view", "user",
                                  f"u{i % 4}"))
        f = RelationshipFilter(resource_type="pod",
                               resource_id=f"{ns}/p0")
        holders = [gi for gi, e in enumerate(engines)
                   if e.store.exists(f)]
        assert holders == [new.shard_for(ns, "pod")], (ns, holders)
    p2.close()


def test_committed_but_uncleared_record_finishes_at_boot(tmp_path):
    old, new = _map(2, 1), _map(2, 2, vnodes=96)
    engines = [_engine(), _engine()]
    journal = SplitJournal(str(tmp_path / "sj.sqlite"))
    p = ShardedEngine(old, engines, journal=journal)
    p.write_relationships(_seed_writes(8))
    coord = p.begin_rebalance(new)
    assert coord.wait(90) and coord.error is None
    # re-persist the committed record as if the crash hit before clear
    t = p._archived_transitions[0]
    journal.save_transition(t.to_doc("committed"))
    p.close(close_journal=False)
    p2 = ShardedEngine(old, engines, journal=journal)
    assert p2.map.version == 2
    # the recovered GC runs OFF the boot path; the record flips to the
    # "done" marker when it lands
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        doc = journal.load_transition()
        if doc is not None and doc.get("phase") == "done":
            break
        time.sleep(0.05)
    assert journal.load_transition()["phase"] == "done"
    p2.close()


def test_abort_requires_no_cut_slice():
    old, new = _map(2, 1), _map(2, 2, vnodes=96)
    t = MapTransition(old, new, plan_moves(old, new))
    t.set_state(t.slices[0], CUT, src_cut=1, dst_cut=1)
    p, _ = ShardedEngine(old, [_engine(), _engine()]), None
    from spicedb_kubeapi_proxy_tpu.scaleout import RebalanceError

    with pytest.raises(RebalanceError, match="point of no return"):
        abort_transition(p, t)
    p.close()


# -- admission classing (mover traffic is sheddable) -------------------------


def test_slice_ops_are_rebalance_classed_and_mover_backs_off():
    for op in ("slice_read", "slice_load", "slice_apply",
               "slice_drop"):
        assert classify_op(op) is REBALANCE
    # lowest shed priority: migration yields to every serving class
    from spicedb_kubeapi_proxy_tpu.admission import CLASSES

    assert all(REBALANCE.priority < c.priority
               for n, c in CLASSES.items() if n != "rebalance")
    # a shedding host backs the mover off by Retry-After, then it
    # proceeds — a shed never fails the transition
    old, new = _map(2, 1), _map(2, 2, vnodes=96)
    t = MapTransition(old, new, plan_moves(old, new))
    p = ShardedEngine(old, [_engine(), _engine()])
    coord = RebalanceCoordinator(p, t)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise AdmissionRejected("rebalance", "host full",
                                    retry_after=0.01,
                                    dependency="engine-admission")
        return "ok"

    before = metrics.counter(
        "scaleout_rebalance_shed_backoff_total").value
    assert coord._call_shed_aware(flaky) == "ok"
    assert metrics.counter(
        "scaleout_rebalance_shed_backoff_total").value == before + 2
    p.close()


# -- /readyz + options -------------------------------------------------------


def test_sharding_status_and_readyz_report_rebalance(tmp_path):
    import asyncio

    from fake_kube import FakeKube
    from spicedb_kubeapi_proxy_tpu.engine.remote import EngineServer
    from spicedb_kubeapi_proxy_tpu.proxy.inmemory import InMemoryClient
    from spicedb_kubeapi_proxy_tpu.proxy.options import Options

    RULES = open(os.path.join(os.path.dirname(__file__), "..",
                              "deploy", "rules.yaml")).read()

    async def go():
        srvs = [EngineServer(_engine()), EngineServer(_engine())]
        ports = [await s.start() for s in srvs]
        smap = ('{"version": 1, "groups": [["127.0.0.1:%d"], '
                '["127.0.0.1:%d"]]}' % (ports[0], ports[1]))
        cfg = Options(
            shard_map=smap,
            shard_journal_path=str(tmp_path / "sj.sqlite"),
            engine_insecure=True,
            rule_content=RULES,
            upstream=FakeKube(),
            workflow_database_path=str(tmp_path / "dtx.sqlite"),
        ).complete()
        await cfg.workflow.resume_pending()
        # install a mid-flight transition white-box (deterministic:
        # no racing mover) and read /readyz
        old = cfg.engine.map
        new = ShardMap(version=2, groups=old.groups, virtual_nodes=96)
        t = MapTransition(old, new, plan_moves(old, new))
        cfg.engine._install_transition(t)
        st = cfg.engine.sharding_status()
        assert st["rebalance"] == {
            "to_version": 2, "moving": len(t.slices),
            "copied": 0, "cut": 0, "lag": 0}
        alice = InMemoryClient(cfg.server.handle, user="alice")
        resp = await alice.get("/readyz")
        assert resp.status == 200, resp.body
        body = resp.body.decode()
        assert "[+]rebalance: to_version=2 moving=" in body
        assert "cut=0 lag=0" in body
        cfg.engine._active_transition = None
        cfg.engine.journal.clear_transition()
        await cfg.workflow.shutdown()
        cfg.engine.close()
        for s in srvs:
            await s.stop()

    asyncio.run(go())


def test_options_validation_rebalance_to():
    from spicedb_kubeapi_proxy_tpu.proxy.options import (
        Options,
        OptionsError,
    )

    good = '{"version": 1, "groups": [["127.0.0.1:1"], ["127.0.0.1:2"]]}'
    with pytest.raises(OptionsError, match="requires --shard-map"):
        Options(rebalance_to=good, rule_content="x",
                upstream=object()).validate()
    with pytest.raises(OptionsError, match="must exceed"):
        Options(shard_map=good, rebalance_to=good, rule_content="x",
                upstream=object()).validate()
    good3 = ('{"version": 1, "groups": [["127.0.0.1:1"], '
             '["127.0.0.1:2"], ["127.0.0.1:3"]]}')
    with pytest.raises(OptionsError, match="at most ONE group"):
        Options(shard_map=good3,
                rebalance_to='{"version": 2, '
                             '"groups": [["127.0.0.1:1"]]}',
                rule_content="x", upstream=object()).validate()
    with pytest.raises(OptionsError, match="LAST group"):
        Options(shard_map=good,
                rebalance_to='{"version": 2, '
                             '"groups": [["127.0.0.1:2"]]}',
                rule_content="x", upstream=object()).validate()
    # a valid transition map validates; so does a tail-group shrink
    Options(shard_map=good,
            rebalance_to='{"version": 2, "groups": [["127.0.0.1:1"], '
                         '["127.0.0.1:2"]], "virtual_nodes": 96}',
            rule_content="x", upstream=object()).validate()
    Options(shard_map=good,
            rebalance_to='{"version": 2, "groups": [["127.0.0.1:1"]]}',
            rule_content="x", upstream=object()).validate()


# -- the live-move acceptance run (loopback TCP groups) ----------------------


def test_live_move_acceptance_over_tcp(tmp_path):
    """ISSUE 14 acceptance: under sustained load, a live move between
    two loopback engine groups loses zero acked writes, never answers
    fail-open, keeps an open watch stream gap- and duplicate-free
    across cutover, and holds goodput on NON-moving slices >= 0.9x the
    no-migration baseline (measured around the long-lived dual-write
    window, the protocol's steady overhead state)."""
    import asyncio

    from spicedb_kubeapi_proxy_tpu.engine.remote import (
        EngineServer,
        RemoteEngine,
    )

    # a GROW move (3 -> 4 groups): the copy/catch-up import load lands
    # on the added group, which serves no pre-existing slice — so the
    # goodput measurement isolates the protocol's cost to non-moving
    # slices (reads routed at V, dual-writes on moving slices only)
    # instead of conflating it with two hosts sharing every slice.
    n_ns = 48
    old, new = _map(3, 1), _map(4, 2)
    loop = asyncio.new_event_loop()
    lt = threading.Thread(target=loop.run_forever, daemon=True)
    lt.start()

    def run(coro, timeout=60.0):
        return asyncio.run_coroutine_threadsafe(coro, loop).result(
            timeout)

    servers, clients = [], []
    p = None
    try:
        for _ in range(4):
            srv = EngineServer(_engine())
            port = run(srv.start())
            servers.append(srv)
            clients.append(RemoteEngine("127.0.0.1", port))
        journal = SplitJournal(str(tmp_path / "sj.sqlite"))
        p = ShardedEngine(old, clients[:3], journal=journal)
        p.write_relationships(_seed_writes(n_ns))
        t = MapTransition(old, new, plan_moves(old, new))
        moving, staying = _moving_split(t, n_ns)
        assert moving and staying
        # warm the mover's power-of-two write/delete kernel shapes on
        # every host (in production they compile once, on the fleet's
        # first-ever move, and stay cached — the measurement below is
        # about the steady-state protocol, not one-time XLA compiles)
        for gi, c in enumerate(clients):
            for size in (16, 8, 4, 2, 1):
                warm = [rel("pod", f"{staying[0]}/warm{gi}", "viewer",
                            "user", f"warm{gi}-{size}-{k}")
                        for k in range(size)]
                c.write_relationships(
                    [WriteOp("touch", r) for r in warm])
                c.write_relationships(
                    [WriteOp("touch", r) for r in warm])
                c.write_relationships(
                    [WriteOp("delete", r) for r in warm])

        stream = p.watch_push_stream(p.revision_vector())
        acked: list = []
        acked_lock = threading.Lock()
        fail_open = []
        goodput = {"n": 0}
        stop = threading.Event()

        # a small, stable probe set: the goodput comparison measures
        # the MOVER's interference, so the probes themselves should be
        # cache-steady in both windows
        probes = staying[:8]

        def load_worker(wi):
            """Closed-loop checks on NON-moving slices (the goodput
            probe) + never-granted intruder probes."""
            j = wi
            while not stop.is_set():
                ns = probes[j % len(probes)]
                p.check(CheckItem("pod", f"{ns}/p0", "view",
                                  "user", f"u{j % 4}"))
                if p.check(CheckItem("pod", f"{ns}/p0", "view",
                                     "user", "intruder")):
                    fail_open.append(ns)
                goodput["n"] += 2
                j += 4

        def write_worker():
            """Sustained writes to MOVING slices (unique subjects: the
            watch stream's dedupe oracle). The rate is set to a level
            the two CPU loopback engines absorb with headroom — the
            goodput comparison measures the MOVER's overhead, not two
            saturated hosts fighting a doubled write load."""
            i = 0
            while not stop.is_set():
                ns = moving[i % len(moving)]
                name = f"mv{i}"
                try:
                    p.write_relationships([WriteOp("touch", rel(
                        "pod", f"{ns}/p0", "viewer", "user", name))])
                except Exception:  # noqa: BLE001 - unacked: no claim
                    pass
                else:
                    with acked_lock:
                        acked.append((ns, name))
                i += 1
                time.sleep(0.1)

        workers = [threading.Thread(target=load_worker, args=(wi,),
                                    daemon=True) for wi in range(4)]
        writer = threading.Thread(target=write_worker, daemon=True)
        for w in workers:
            w.start()
        writer.start()

        import statistics

        def goodput_window(sec=0.6):
            goodput["n"] = 0
            t0 = time.monotonic()
            time.sleep(sec)
            return goodput["n"] / (time.monotonic() - t0)

        time.sleep(1.0)  # warmup (jit shapes, caches)

        # live move, paced so migration bandwidth is a bounded small
        # fraction of host capacity. The goodput comparison INTERLEAVES
        # paused and running mover windows (coordinator pause/resume —
        # the operator quiesce lever): adjacent-in-time windows share
        # identical process warmth and background noise, so the ratio
        # isolates exactly the mover's interference — which is the
        # claim under test — instead of drift between two far-apart
        # measurement periods on a noisy CI box.
        coord = p.begin_rebalance(new, new_clients={3: clients[3]},
                                  pace_seconds=0.25, batch_rows=8,
                                  poll_seconds=0.3)
        time.sleep(0.5)  # let the move reach steady state
        paused_w, running_w = [], []
        for _ in range(3):
            if coord._done.is_set():
                break
            coord.pause()
            time.sleep(0.1)  # in-flight mover op drains
            paused_w.append(goodput_window())
            coord.resume()
            time.sleep(0.1)
            if coord._done.is_set():
                break
            running_w.append(goodput_window())
        coord.resume()
        assert len(paused_w) >= 2 and len(running_w) >= 2, \
            "move finished before goodput could be sampled"
        baseline = statistics.median(paused_w)
        during = statistics.median(running_w)

        assert coord.wait(120), "mover never finished"
        assert coord.error is None, coord.error
        stop.set()
        writer.join(10)
        for w in workers:
            w.join(10)

        assert not fail_open, f"fail-open on {fail_open[:3]}"
        assert p.map.version == 2 and len(p.groups) == 4

        # zero acked writes lost (read back through the NEW placement)
        with acked_lock:
            acked_now = list(acked)
        for ns, name in acked_now:
            assert p.exists(RelationshipFilter(
                resource_type="pod", resource_id=f"{ns}/p0",
                relation="viewer", subject_id=name)), (ns, name)

        # watch stream: every acked moving-slice write exactly once
        want = {name for _, name in acked_now}
        seen: list = []
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            for e in stream.next_batch():
                sid = e.relationship.subject_id
                if sid.startswith("mv"):
                    seen.append(sid)
            if want <= set(seen):
                break
        stream.close()
        missing = want - set(seen)
        assert not missing, f"gap: {sorted(missing)[:5]}"
        dups = {n for n in seen if seen.count(n) > 1}
        assert not dups, f"duplicates: {sorted(dups)[:5]}"

        # goodput on non-moving slices held through the live move
        ratio = during / max(baseline, 1e-9)
        sys.stderr.write(
            f"\nlive-move goodput: baseline {baseline:.0f} op/s, "
            f"during move {during:.0f} op/s, ratio {ratio:.2f}\n")
        assert ratio >= 0.9, (baseline, during)
    finally:
        if p is not None:
            p.close()
        for srv in servers:
            try:
                run(srv.stop(), timeout=15.0)
            except Exception:  # noqa: BLE001 - teardown best effort
                pass
        loop.call_soon_threadsafe(loop.stop)
        lt.join(10)


def test_resume_replays_deletes_from_the_crash_window(tmp_path):
    """Review regression: resuming an interrupted slice move must
    replay from the PERSISTED watermark, not the fresh copy revision —
    a tuple copied to the destination and then deleted on the source
    during the crash window would otherwise survive on the new owner
    (a revoked grant answering allow after cutover: fail-open)."""
    old, new, engines, journal, t = _persisted_transition(
        tmp_path, cut_first=True)
    sl = next(s for s in t.slices if s.state != CUT)
    idx = next(i for i in range(12)
               if t.slice_for_key(f"ns{i}", "pod") is sl)
    ns = f"ns{idx}"
    # a grant whose ONLY path is the moved pod tuple ("vic" has no
    # namespace-level access): present on BOTH stores — as if the copy
    # carried it — then granted+revoked on the source strictly after
    # the persisted replay watermark (the "crash window")
    victim = rel("pod", f"{ns}/p0", "viewer", "user", "vic")
    engines[sl.dst].write_relationships([WriteOp("touch", victim)])
    engines[sl.src].write_relationships([WriteOp("touch", victim)])
    engines[sl.src].write_relationships([WriteOp("delete", victim)])
    vic_f = RelationshipFilter(resource_type="pod",
                               resource_id=f"{ns}/p0",
                               subject_id="vic")
    assert engines[sl.dst].store.exists(vic_f)

    p2 = ShardedEngine(old, engines, journal=journal)
    coord = p2._coordinator
    assert coord is not None and coord.wait(90)
    assert coord.error is None, coord.error
    assert p2.map.version == 2
    # the revocation reached the new owner: never a stale allow
    assert not engines[sl.dst].store.exists(vic_f)
    assert not p2.check(CheckItem("pod", f"{ns}/p0", "view", "user",
                                  "vic"))
    p2.close()


def test_stale_flags_restart_boots_the_completed_map(tmp_path):
    """Review regression: after a completed move, a restart whose CLI
    flags still name the OLD map must serve the committed new map from
    the durable "done" marker — re-running the move would route the
    moved slices to the GC'd (empty) source groups."""
    old, new = _map(2, 1), _map(2, 2, vnodes=96)
    engines = [_engine(), _engine()]
    journal = SplitJournal(str(tmp_path / "sj.sqlite"))
    p = ShardedEngine(old, engines, journal=journal)
    p.write_relationships(_seed_writes(12))
    coord = p.begin_rebalance(new)
    assert coord.wait(90) and coord.error is None, coord.error
    p.close(close_journal=False)

    # restart with the STALE map (the operator has not rolled the
    # flag): the done marker makes V+1 authoritative
    p2 = ShardedEngine(old, engines, journal=journal)
    assert p2.map.version == 2
    for i in range(12):
        assert p2.check(CheckItem("pod", f"ns{i}/p0", "view", "user",
                                  f"u{i % 4}"))
    assert journal.load_transition()["phase"] == "done"  # marker kept
    p2.close(close_journal=False)

    # the flag catches up: booting WITH the new map clears the marker
    p3 = ShardedEngine(new, engines, journal=journal)
    assert p3.map.version == 2
    assert journal.load_transition() is None
    assert p3.check(CheckItem("pod", "ns0/p0", "view", "user", "u0"))
    p3.close()
