"""Scale-out: hash-partitioned engine shards + scatter-gather planner.

Covers the ISSUE 11 acceptance surface:

- shard-map determinism, versioning, global-vs-namespaced routing;
- revision-vector ordering/merge/encode and the merge edge cases
  (gathers across shards at DIFFERENT revisions, old-vector cache
  entries never serving after any component advances);
- single-shard checks routing direct (per-shard op counters prove no
  scatter), scatter-gather parity with an unsharded oracle engine;
- cross-shard split writes journaled durably and replayed to a
  consistent state after a mid-split crash;
- partial-shed scatter failing CLOSED with Retry-After = max over
  shards, and the per-shard admission cost multiplier;
- /readyz's ``sharding:`` info line;
- the end-to-end 2-group deployment over REAL TCP engine hosts with a
  SIGKILL'd group leader failing over without disturbing the other
  group.
"""

import os
import subprocess
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from spicedb_kubeapi_proxy_tpu.admission import (  # noqa: E402
    AdmissionRejected,
    CHECK,
    LOOKUP_PREFILTER,
    WATCH_RECOMPUTE,
    WRITE_DTX,
)
from spicedb_kubeapi_proxy_tpu.engine import Engine  # noqa: E402
from spicedb_kubeapi_proxy_tpu.engine.engine import (  # noqa: E402
    CheckItem,
    mask_to_ids,
)
from spicedb_kubeapi_proxy_tpu.engine.store import (  # noqa: E402
    RelationshipFilter,
    WriteOp,
)
from spicedb_kubeapi_proxy_tpu.models.tuples import (  # noqa: E402
    Relationship,
)
from spicedb_kubeapi_proxy_tpu.scaleout import (  # noqa: E402
    RevisionVector,
    ShardedEngine,
    ShardMap,
    ShardMapError,
    ShardVectorCache,
    SplitJournal,
    load_shard_map,
    parse_shard_map,
    split_resource,
)
from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics  # noqa: E402

SCHEMA_YAML = """\
schema: |-
  use expiration

  definition user {}

  definition group {
    relation member: user
  }

  definition namespace {
    relation creator: user
    relation viewer: user | group#member
    permission admin = creator
    permission view = viewer + creator
  }

  definition pod {
    relation namespace: namespace
    relation creator: user
    relation viewer: user
    permission edit = creator
    permission view = viewer + creator + namespace->view
  }
relationships: ""
"""


def _engine() -> Engine:
    return Engine(bootstrap=SCHEMA_YAML)


def _map(n: int, version: int = 1) -> ShardMap:
    return ShardMap(version=version,
                    groups=tuple((("127.0.0.1", 0),) for _ in range(n)))


def _planner(n: int, journal=None, cache=None):
    engines = [_engine() for _ in range(n)]
    return ShardedEngine(_map(n), engines, journal=journal,
                         cache=cache), engines


def _ops_count(mode: str, op: str = "check_bulk") -> float:
    tot = 0.0
    for gi in range(8):
        tot += metrics.counter("scaleout_ops_total", group=str(gi),
                               op=op, mode=mode).value
    return tot


def rel(rt, rid, rl, st, sid, srl=None) -> Relationship:
    return Relationship(rt, rid, rl, st, sid, srl)


# -- shard map ---------------------------------------------------------------


def test_shard_map_deterministic_versioned_and_spread():
    doc = ('{"version": 3, "groups": [["127.0.0.1:7001", '
           '"127.0.0.1:7002"], ["127.0.0.1:7011"], ["127.0.0.1:7021"]]}')
    m1, m2 = parse_shard_map(doc), parse_shard_map(doc)
    assert m1.version == 3 and m1.n_groups == 3
    assert m1.groups[0] == (("127.0.0.1", 7001), ("127.0.0.1", 7002))
    # deterministic: two instances agree on every key
    owners = {}
    for i in range(300):
        key = (f"ns{i}", "pod")
        owners[key] = m1.shard_for(*key)
        assert m2.shard_for(*key) == owners[key]
    # consistent hashing actually spreads the keyspace
    per_group = [0] * 3
    for g in owners.values():
        per_group[g] += 1
    assert all(c > 0 for c in per_group), per_group
    # (namespace, TYPE) is part of the key: same ns, different types may
    # land on different groups (the documented colocation caveat)
    assert len({m1.shard_for("nsx", t)
                for t in ("pod", "deployment", "secret", "configmap",
                          "service", "job")}) > 1


def test_shard_map_validation_errors():
    with pytest.raises(ShardMapError):
        parse_shard_map("not json")
    with pytest.raises(ShardMapError):
        parse_shard_map('{"version": 0, "groups": [["h:1"]]}')
    with pytest.raises(ShardMapError):
        parse_shard_map('{"version": 1, "groups": []}')
    with pytest.raises(ShardMapError):
        parse_shard_map('{"version": 1, "groups": [["nonsense"]]}')
    with pytest.raises(ShardMapError):
        load_shard_map("/definitely/not/a/file.json")


def test_global_vs_namespaced_split():
    assert split_resource("ns1/p0") == ("ns1", True)
    assert split_resource("ns1") == ("", False)
    m = _map(4)
    assert m.shard_of("pod", "ns1/p0") is not None
    assert m.shard_of("namespace", "ns1") is None  # global: replicated
    # anchored reads of a global object still pick ONE stable group
    a = m.anchor_shard("namespace", "ns1")
    assert a == m.anchor_shard("namespace", "ns1")
    assert 0 <= a < 4


# -- revision vectors --------------------------------------------------------


def test_revision_vector_ordering_merge_encode():
    v0 = RevisionVector.zero(3)
    v1 = v0.bump(1, 5)
    v2 = v1.bump(0, 2)
    assert v1 == (0, 5, 0) and v2 == (2, 5, 0)
    assert v2.dominates(v1) and not v1.dominates(v2)
    assert v1.join(RevisionVector((3, 1, 0))) == (3, 5, 0)
    # bump never regresses a component
    assert v2.bump(1, 3) == (2, 5, 0)
    # encode/parse round-trip; parse accepts sequences
    assert RevisionVector.parse(v2.encode()) == v2
    assert RevisionVector.parse([1, 2, 3]) == (1, 2, 3)
    with pytest.raises(ShardMapError):
        RevisionVector.parse("x1.2")
    # tuple lexicographic order agrees with causality along one stream
    assert v2 > v1 > v0


# -- planner routing ---------------------------------------------------------


def test_single_shard_check_routes_direct_no_scatter():
    p, engines = _planner(2)
    p.write_relationships([
        WriteOp("create", rel("pod", "nsa/p0", "viewer", "user", "al")),
        WriteOp("create", rel("pod", "nsb/p0", "viewer", "user", "bo")),
    ])
    s_before = _ops_count("scatter")
    d_before = _ops_count("single")
    assert p.check(CheckItem("pod", "nsa/p0", "view", "user", "al"))
    assert not p.check(CheckItem("pod", "nsa/p0", "view", "user", "bo"))
    assert p.check(CheckItem("pod", "nsb/p0", "view", "user", "bo"))
    assert _ops_count("scatter") == s_before  # NO scatter for checks
    assert _ops_count("single") >= d_before + 3
    # a bulk mixing both shards' pods scatters only to the owners and
    # reassembles in item order
    out = p.check_bulk([
        CheckItem("pod", "nsa/p0", "view", "user", "al"),
        CheckItem("pod", "nsb/p0", "view", "user", "al"),
        CheckItem("pod", "nsb/p0", "view", "user", "bo"),
    ])
    assert out == [True, False, True]
    p.close()


def test_scatter_gather_parity_with_unsharded_oracle():
    import random

    rng = random.Random(11)
    n_ns, n_users = 12, 6
    writes = []
    # namespaces (global), group grants, pods across namespaces
    for i in range(n_ns):
        writes.append(WriteOp("create", rel(
            "namespace", f"ns{i}", "viewer", "user",
            f"u{rng.randrange(n_users)}")))
    writes.append(WriteOp("create", rel(
        "group", "admins", "member", "user", "u0")))
    writes.append(WriteOp("create", rel(
        "namespace", "ns0", "viewer", "group", "admins", "member")))
    for i in range(n_ns):
        for pj in range(3):
            writes.append(WriteOp("create", rel(
                "pod", f"ns{i}/p{pj}", "namespace", "namespace",
                f"ns{i}")))
            if rng.random() < 0.5:
                writes.append(WriteOp("create", rel(
                    "pod", f"ns{i}/p{pj}", "viewer", "user",
                    f"u{rng.randrange(n_users)}")))

    oracle = _engine()
    oracle.write_relationships(list(writes))
    for n_shards in (2, 3):
        p, engines = _planner(n_shards)
        p.write_relationships(list(writes))
        for u in [f"u{i}" for i in range(n_users)]:
            want = sorted(oracle.lookup_resources(
                "pod", "view", "user", u))
            got = sorted(p.lookup_resources("pod", "view", "user", u))
            assert got == want, (n_shards, u)
            # the gathered mask materializes the SAME ids byte-for-byte
            mask, interner = p.lookup_resources_mask(
                "pod", "view", "user", u)
            assert mask_to_ids(mask, interner) == sorted(want)
            # global-type lookups dedupe the replicated answers
            assert sorted(p.lookup_resources(
                "namespace", "view", "user", u)) == sorted(
                    oracle.lookup_resources("namespace", "view",
                                            "user", u))
        # LookupSubjects parity on a namespaced and a global anchor
        assert p.lookup_subjects("pod", "ns0/p0", "view", "user") == \
            oracle.lookup_subjects("pod", "ns0/p0", "view", "user")
        assert p.lookup_subjects("namespace", "ns0", "view", "user") \
            == oracle.lookup_subjects("namespace", "ns0", "view",
                                      "user")
        # check parity over a sample
        items = [CheckItem("pod", f"ns{i}/p0", "view", "user",
                           f"u{i % n_users}") for i in range(n_ns)]
        assert p.check_bulk(items) == oracle.check_bulk(items)
        p.close()


def test_read_and_exists_route_and_dedupe():
    p, engines = _planner(2)
    p.write_relationships([
        WriteOp("create", rel("namespace", "ns1", "creator", "user",
                              "al")),
        WriteOp("create", rel("pod", "ns1/p0", "viewer", "user", "al")),
        WriteOp("create", rel("pod", "ns2/p0", "viewer", "user", "bo")),
    ])
    # replicated global rows come back ONCE
    got = p.read_relationships(RelationshipFilter(
        resource_type="namespace", resource_id="ns1"))
    assert len(got) == 1
    # unanchored read unions disjoint namespaced slices
    got = p.read_relationships(RelationshipFilter(resource_type="pod"))
    assert {r.resource_id for r in got} == {"ns1/p0", "ns2/p0"}
    assert p.exists(RelationshipFilter(resource_type="pod",
                                       resource_id="ns2/p0"))
    assert not p.exists(RelationshipFilter(resource_type="pod",
                                           resource_id="ns3/p9"))
    # global delete converges on every replica and counts ONE copy
    n = p.delete_relationships(RelationshipFilter(
        resource_type="namespace", resource_id="ns1"))
    assert n == 1
    for e in engines:
        assert not e.store.exists(RelationshipFilter(
            resource_type="namespace", resource_id="ns1"))
    p.close()


# -- revision-vector merge edge cases (satellite) ----------------------------


def test_gather_at_mixed_revisions_is_not_torn():
    """Shards at DIFFERENT revisions gather into a mask consistent with
    each shard's own revision: advancing ONE shard changes only that
    shard's slice of the union, and the vector shows exactly which
    component moved."""
    p, engines = _planner(2)
    sa = p.map.shard_of("pod", "nsa/p0")
    sb = p.map.shard_of("pod", "nsb/p0")
    assert sa != sb, "fixture namespaces must land on distinct shards"
    p.write_relationships([WriteOp(
        "create", rel("pod", "nsa/p0", "viewer", "user", "al"))])
    v1 = p.revision_vector()
    assert sorted(p.lookup_resources("pod", "view", "user", "al")) == \
        ["nsa/p0"]
    # advance ONLY shard sb
    p.write_relationships([WriteOp(
        "create", rel("pod", "nsb/p1", "viewer", "user", "al"))])
    v2 = p.revision_vector()
    assert v2[sb] > v1[sb] and v2[sa] == v1[sa]
    # the gather now reflects sb's new revision AND sa's old one —
    # each shard answers at its own revision, no torn cross-shard view
    assert sorted(p.lookup_resources("pod", "view", "user", "al")) == \
        ["nsa/p0", "nsb/p1"]
    p.close()


def test_vector_cache_never_serves_after_component_advance():
    cache = ShardVectorCache()
    p, engines = _planner(2, cache=cache)
    p.write_relationships([WriteOp(
        "create", rel("pod", "nsa/p0", "viewer", "user", "al"))])
    items = [CheckItem("pod", "nsa/p0", "view", "user", "al")]
    assert p.try_cached_check(items) is None  # cold
    assert p.check_bulk(items) == [True]
    got = p.try_cached_check(items)
    assert got == [True]  # hot at the current vector
    # advance ONE component (a write to the OTHER shard): the old-vector
    # entry must never serve again
    p.write_relationships([WriteOp(
        "create", rel("pod", "nsb/p0", "viewer", "user", "bo"))])
    assert p.try_cached_check(items) is None
    # context fragments the key
    assert p.check_bulk(items) == [True]
    assert p.try_cached_check(items) == [True]
    assert p.try_cached_check(items, context={"ip": "1.2.3.4"}) is None
    p.close()


def test_vector_cache_unit_semantics():
    c = ShardVectorCache(max_entries=2)
    v1 = RevisionVector((1, 1))
    v2 = RevisionVector((2, 1))
    c.put("k", v1, [True])
    assert c.get("k", v1) == [True]
    assert c.get("k", v2) is None  # exact-vector match only
    c.retire_below(v2)  # v1 dominated by v2 -> gone
    assert c.get("k", v1) is None
    c.put("a", v1, [1])
    c.put("b", v1, [2])
    c.put("c", v1, [3])  # LRU bound
    assert c.get("a", v1) is None and c.get("c", v1) == [3]


# -- cross-shard split writes (dtx journal) ----------------------------------


class _FlakyWrites:
    """Delegating engine wrapper whose write_relationships dies (after
    optionally applying) — the mid-split crash injector."""

    def __init__(self, inner, fail_times: int = 1,
                 apply_before_dying: bool = False):
        self._inner = inner
        self.fail_times = fail_times
        self.apply_before_dying = apply_before_dying

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def write_relationships(self, ops, preconditions=()):
        if self.fail_times > 0:
            self.fail_times -= 1
            if self.apply_before_dying:
                self._inner.write_relationships(ops, preconditions)
            raise ConnectionResetError("injected mid-split crash")
        return self._inner.write_relationships(ops, preconditions)


def test_cross_shard_split_write_journals_and_replays(tmp_path):
    engines = [_engine(), _engine()]
    journal = SplitJournal(str(tmp_path / "sj.sqlite"))
    smap = _map(2)
    # shard 1 (the SECOND applied) dies mid-split
    flaky = ShardedEngine(
        smap, [engines[0], _FlakyWrites(engines[1])], journal=journal)
    ops = [
        WriteOp("create", rel("namespace", "ns1", "creator", "user",
                              "al")),  # global -> replicates (split!)
        WriteOp("create", rel("pod", "nsa/p0", "viewer", "user", "al")),
        WriteOp("create", rel("pod", "nsb/p0", "viewer", "user", "al")),
    ]
    with pytest.raises(ConnectionResetError):
        flaky.write_relationships(ops)
    # the crash left a PENDING journal entry with partial progress
    assert journal.pending_count() == 1
    ent = journal.pending()[0]
    assert 0 in ent["applied"] and 1 not in ent["applied"]
    # shard 0 applied, shard 1 did not: visibly half-applied ONLY
    # through the journal (reads would miss shard 1's slice)
    assert engines[0].store.exists(RelationshipFilter(
        resource_type="namespace", resource_id="ns1"))
    assert not engines[1].store.exists(RelationshipFilter(
        resource_type="namespace", resource_id="ns1"))
    # "restart": a NEW planner over the same journal replays the split
    # to completion (creates degraded to touches — idempotent)
    p2 = ShardedEngine(smap, engines, journal=journal)
    assert journal.pending_count() == 0
    for e in engines:
        assert e.store.exists(RelationshipFilter(
            resource_type="namespace", resource_id="ns1"))
    assert p2.check(CheckItem("pod", "nsa/p0", "view", "user", "al"))
    assert p2.check(CheckItem("pod", "nsb/p0", "view", "user", "al"))
    p2.close()


def test_replay_idempotent_when_shard_applied_before_crash(tmp_path):
    """The other torn shape: the shard APPLIED the sub-write but the
    crash landed before mark_applied — replay re-touches (never a
    duplicate-create error) and converges."""
    engines = [_engine(), _engine()]
    journal = SplitJournal(str(tmp_path / "sj.sqlite"))
    smap = _map(2)
    flaky = ShardedEngine(
        smap,
        [engines[0], _FlakyWrites(engines[1], apply_before_dying=True)],
        journal=journal)
    with pytest.raises(ConnectionResetError):
        flaky.write_relationships([
            WriteOp("create", rel("pod", "nsa/p0", "viewer", "user",
                                  "al")),
            WriteOp("create", rel("pod", "nsb/p0", "viewer", "user",
                                  "al")),
        ])
    assert journal.pending_count() == 1
    p2 = ShardedEngine(smap, engines, journal=journal)
    assert journal.pending_count() == 0
    assert p2.check(CheckItem("pod", "nsb/p0", "view", "user", "al"))
    # exactly one tuple, not two
    assert len(p2.read_relationships(RelationshipFilter(
        resource_type="pod", resource_id="nsb/p0"))) == 1
    p2.close()


class _RejectingWrites:
    """Delegating wrapper whose write_relationships REJECTS (the engine
    answered — provably nothing applied)."""

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def write_relationships(self, ops, preconditions=()):
        from spicedb_kubeapi_proxy_tpu.engine.store import (
            PreconditionFailed,
        )

        raise PreconditionFailed("injected engine-answered rejection")


def test_first_shard_rejection_closes_the_journal_entry(tmp_path):
    """A split whose FIRST shard REJECTS (the engine answered:
    precondition/schema) applied nothing anywhere: the journal entry is
    finished, not replayed — the caller saw the error and recovery must
    not resurrect the write."""
    from spicedb_kubeapi_proxy_tpu.engine.store import (
        PreconditionFailed,
    )

    engines = [_engine(), _engine()]
    journal = SplitJournal(str(tmp_path / "sj.sqlite"))
    flaky = ShardedEngine(
        _map(2), [_RejectingWrites(engines[0]), engines[1]],
        journal=journal)
    with pytest.raises(PreconditionFailed):
        flaky.write_relationships([
            WriteOp("create", rel("pod", "nsa/p0", "viewer", "user",
                                  "al")),
            WriteOp("create", rel("pod", "nsb/p0", "viewer", "user",
                                  "al")),
        ])
    assert journal.pending_count() == 0
    p2 = ShardedEngine(_map(2), engines, journal=journal)
    assert not p2.check(CheckItem("pod", "nsb/p0", "view", "user",
                                  "al"))
    p2.close()


def test_first_shard_transport_death_stays_pending(tmp_path):
    """A TRANSPORT failure on the first shard is ambiguous — the write
    may have applied even though the caller saw an error. The entry
    stays pending and recovery touch-replays everything: at-LEAST-once
    under ambiguity, never silently half-applied."""
    engines = [_engine(), _engine()]
    journal = SplitJournal(str(tmp_path / "sj.sqlite"))
    flaky = ShardedEngine(
        _map(2),
        [_FlakyWrites(engines[0], apply_before_dying=True),
         engines[1]],
        journal=journal)
    with pytest.raises(ConnectionResetError):
        flaky.write_relationships([
            WriteOp("create", rel("pod", "nsa/p0", "viewer", "user",
                                  "al")),
            WriteOp("create", rel("pod", "nsb/p0", "viewer", "user",
                                  "al")),
        ])
    assert journal.pending_count() == 1
    p2 = ShardedEngine(_map(2), engines, journal=journal)
    assert journal.pending_count() == 0
    # BOTH shards converged (shard 0's leg had applied pre-crash; the
    # touch replay was idempotent)
    assert p2.check(CheckItem("pod", "nsa/p0", "view", "user", "al"))
    assert p2.check(CheckItem("pod", "nsb/p0", "view", "user", "al"))
    assert len(p2.read_relationships(RelationshipFilter(
        resource_type="pod", resource_id="nsa/p0"))) == 1
    p2.close()


def test_later_shard_precondition_cannot_reject():
    """Every precondition decision point sits at or before the FIRST
    shard's apply (anchored-global pcs bind on the first split shard;
    later-shard owners are probed up front) — so a pending journal
    entry is always safe to replay with preconditions stripped."""
    from spicedb_kubeapi_proxy_tpu.engine.store import (
        Precondition,
        PreconditionFailed,
    )

    p, engines = _planner(2)
    # a namespaced pc whose owner is NOT the first split shard: probed
    # up front, so a failing one aborts BEFORE anything applies
    ns0 = next(f"q{i}" for i in range(64)
               if p.map.shard_for(f"q{i}", "pod") == 0)
    ns1 = next(f"q{i}" for i in range(64)
               if p.map.shard_for(f"q{i}", "pod") == 1)
    pc = Precondition(RelationshipFilter(
        resource_type="pod", resource_id=f"{ns1}/p9",
        relation="viewer"), must_exist=True)
    with pytest.raises(PreconditionFailed):
        p.write_relationships([
            WriteOp("create", rel("pod", f"{ns0}/p1", "viewer", "user",
                                  "al")),
            WriteOp("create", rel("pod", f"{ns1}/p1", "viewer", "user",
                                  "al")),
        ], [pc])
    # nothing applied on either shard, nothing pending
    assert not p.exists(RelationshipFilter(resource_type="pod",
                                           resource_id=f"{ns0}/p1"))
    assert not p.exists(RelationshipFilter(resource_type="pod",
                                           resource_id=f"{ns1}/p1"))
    p.close()


# -- per-shard admission (satellite of the tentpole) -------------------------


class _SheddingEngine:
    def __init__(self, inner, retry_after: float):
        self._inner = inner
        self.retry_after = retry_after

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def lookup_resources(self, *a, **kw):
        raise AdmissionRejected("lookup-prefilter", "host full",
                                retry_after=self.retry_after,
                                dependency="engine-admission")


def test_partial_shed_scatter_fails_closed_max_retry_after():
    engines = [_engine(), _engine(), _engine()]
    p = ShardedEngine(_map(3), [
        engines[0],
        _SheddingEngine(engines[1], 2.0),
        _SheddingEngine(engines[2], 7.0),
    ])
    before = metrics.counter("scaleout_partial_shed_total").value
    with pytest.raises(AdmissionRejected) as ei:
        p.lookup_resources("pod", "view", "user", "al")
    # fails CLOSED (never a partial union), Retry-After = max over the
    # shedding shards, its own dependency label
    assert ei.value.retry_after == 7.0
    assert ei.value.dependency == "shard-admission"
    assert metrics.counter(
        "scaleout_partial_shed_total").value == before + 1
    p.close()


def test_admission_fanout_and_scaled_cost():
    p, _ = _planner(4)
    # scatter classes charge once per touched shard
    assert p.admission_fanout(LOOKUP_PREFILTER) == 4
    assert p.admission_fanout(WATCH_RECOMPUTE) == 4
    # anchored classes stay 1x
    assert p.admission_fanout(CHECK) == 1
    assert p.admission_fanout(WRITE_DTX) == 1
    scaled = LOOKUP_PREFILTER.scaled(4)
    assert scaled.weight == LOOKUP_PREFILTER.weight * 4
    assert scaled.name == LOOKUP_PREFILTER.name  # same shed/metric label
    assert scaled.priority == LOOKUP_PREFILTER.priority
    assert CHECK.scaled(1) is CHECK
    p.close()


def test_middleware_charges_scatter_per_shard():
    """End-to-end through the authz middleware: a list-prefilter against
    a 3-group planner acquires 3x the lookup weight from the proxy-side
    admission controller."""
    import asyncio

    from spicedb_kubeapi_proxy_tpu.authz.middleware import (
        AuthzDeps,
        authorize,
    )
    from spicedb_kubeapi_proxy_tpu.proxy.requestinfo import (
        parse_request_info,
    )
    from spicedb_kubeapi_proxy_tpu.proxy.types import ProxyRequest
    from spicedb_kubeapi_proxy_tpu.rules.input import UserInfo
    from spicedb_kubeapi_proxy_tpu.rules.matcher import MapMatcher

    RULES = open(os.path.join(os.path.dirname(__file__), "..",
                              "deploy", "rules.yaml")).read()

    class _RecordingAdmission:
        def __init__(self):
            self.classes = []

        async def acquire_async(self, tenant, cls):
            self.classes.append(cls)

            class _T:
                def release(self, observe=True):
                    pass

            return _T()

    async def fake_upstream(req):
        from spicedb_kubeapi_proxy_tpu.proxy.types import ProxyResponse

        return ProxyResponse(status=200, headers={
            "Content-Type": "application/json"},
            body=b'{"kind":"NamespaceList","items":[]}')

    p, _ = _planner(3)
    adm = _RecordingAdmission()
    deps = AuthzDeps(matcher=MapMatcher.from_yaml(RULES), engine=p,
                     upstream=fake_upstream, admission=adm)
    req = ProxyRequest(
        method="GET", path="/api/v1/namespaces", query={}, headers={},
        body=b"",
        request_info=parse_request_info("GET", "/api/v1/namespaces",
                                        {}),
        user=UserInfo(name="alice"))
    resp = asyncio.run(authorize(req, deps))
    assert resp.status == 200
    assert len(adm.classes) == 1
    cls = adm.classes[0]
    assert cls.name == "lookup-prefilter"
    assert cls.weight == LOOKUP_PREFILTER.weight * 3  # 3 shards
    p.close()


# -- watch streams -----------------------------------------------------------


def test_sharded_watch_stream_vector_resumption():
    p, engines = _planner(2)
    stream = p.watch_push_stream(p.map.zero_vector())
    try:
        p.write_relationships([
            WriteOp("create", rel("pod", "nsa/p0", "viewer", "user",
                                  "al")),
            WriteOp("create", rel("pod", "nsb/p0", "viewer", "user",
                                  "bo")),
        ])
        seen = []
        deadline = time.monotonic() + 10
        while len(seen) < 2 and time.monotonic() < deadline:
            seen.extend(stream.next_batch())
        assert len(seen) >= 2
        # revisions are VECTORS, monotone along the merged stream
        vecs = [e.revision for e in seen]
        assert all(isinstance(v, RevisionVector) for v in vecs)
        for a, b in zip(vecs, vecs[1:]):
            assert b.dominates(a)
        # resuming from the final vector replays nothing
        assert p.watch_since(vecs[-1]) == []
        # resuming from zero replays both shards' events, stamped
        # monotonically
        replay = p.watch_since(p.map.zero_vector())
        assert {e.relationship.resource_id for e in replay} == {
            "nsa/p0", "nsb/p0"}
    finally:
        stream.close()
        p.close()


# -- /readyz sharding line (satellite) ---------------------------------------


def test_readyz_reports_sharding_line(tmp_path):
    import asyncio

    from fake_kube import FakeKube
    from spicedb_kubeapi_proxy_tpu.engine.remote import EngineServer
    from spicedb_kubeapi_proxy_tpu.proxy.inmemory import InMemoryClient
    from spicedb_kubeapi_proxy_tpu.proxy.options import Options

    RULES = open(os.path.join(os.path.dirname(__file__), "..",
                              "deploy", "rules.yaml")).read()

    async def go():
        srvs = [EngineServer(_engine()), EngineServer(_engine())]
        ports = [await s.start() for s in srvs]
        smap = ('{"version": 2, "groups": [["127.0.0.1:%d"], '
                '["127.0.0.1:%d"]]}' % (ports[0], ports[1]))
        cfg = Options(
            shard_map=smap,
            shard_journal_path=str(tmp_path / "sj.sqlite"),
            engine_insecure=True,
            rule_content=RULES,
            upstream=FakeKube(),
            workflow_database_path=str(tmp_path / "dtx.sqlite"),
        ).complete()
        assert isinstance(cfg.engine, ShardedEngine)
        await cfg.workflow.resume_pending()
        alice = InMemoryClient(cfg.server.handle, user="alice")
        resp = await alice.get("/readyz")
        assert resp.status == 200, resp.body
        body = resp.body.decode()
        assert "[+]sharding: groups=2 map_version=2" in body
        assert "g0=leader" in body and "g1=leader" in body
        assert "pending_splits=0" in body
        # and requests actually flow through the planner
        resp = await alice.get("/api/v1/namespaces")
        assert resp.status == 200
        await cfg.workflow.shutdown()
        cfg.engine.close()
        for s in srvs:
            await s.stop()

    asyncio.run(go())


def test_options_validation():
    from spicedb_kubeapi_proxy_tpu.proxy.options import (
        Options,
        OptionsError,
    )

    good = '{"version": 1, "groups": [["127.0.0.1:1"]]}'
    with pytest.raises(OptionsError, match="mutually exclusive"):
        Options(shard_map=good, engine_endpoint="tcp://h:1",
                rule_content="x", upstream=object()).validate()
    with pytest.raises(OptionsError, match="bootstrap"):
        Options(shard_map=good, bootstrap_content="x",
                rule_content="x", upstream=object()).validate()
    with pytest.raises(OptionsError):
        Options(shard_map='{"version": 0, "groups": [["h:1"]]}',
                rule_content="x", upstream=object()).validate()


# -- the end-to-end acceptance: 2 groups over real TCP -----------------------


_HOST_WORKER = r"""
import os, sys
mode = sys.argv[1]
bootstrap = sys.argv[2]
repo = sys.argv[-1]
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, repo)
import jax
jax.config.update("jax_platforms", "cpu")
from spicedb_kubeapi_proxy_tpu.engine.remote import main

print("HOST STARTING", flush=True)
if mode == "peer":
    peer_id, port0, port1, data_dir = sys.argv[3:7]
    sys.exit(main([
        "--bootstrap", bootstrap,
        "--peers", "127.0.0.1:%s,127.0.0.1:%s" % (port0, port1),
        "--peer-id", peer_id,
        "--bind-port", port0 if peer_id == "0" else port1,
        "--token", "sh-tok", "--engine-insecure",
        "--data-dir", data_dir, "--wal-fsync", "always",
        "--mirror-heartbeat-seconds", "0.3",
        "--failover-boot-grace", "30",
    ]))
else:
    port = sys.argv[3]
    sys.exit(main([
        "--bootstrap", bootstrap,
        "--bind-port", port,
        "--token", "sh-tok", "--engine-insecure",
    ]))
"""


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_e2e_two_tcp_groups_failover_and_split_replay(tmp_path):
    """The ISSUE 11 acceptance run, over REAL TCP engine hosts:

    - group 0 = a 2-peer failover set, group 1 = a single host;
    - single-shard checks answer with NO scatter (op counters);
    - scatter-gathered prefilter ids match an unsharded oracle
      byte-for-byte over the same tuples;
    - a cross-shard write interrupted mid-split replays to a consistent
      state on "restart" (a fresh planner over the same journal);
    - SIGKILL of group 0's leader fails over WITHOUT disturbing group 1
      (its checks keep answering throughout the election window).
    """
    from spicedb_kubeapi_proxy_tpu.engine.remote import (
        FailoverEngine,
        RemoteEngine,
    )
    from spicedb_kubeapi_proxy_tpu.utils.resilience import (
        DependencyUnavailable,
    )

    repo_root = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    script = str(tmp_path / "host_worker.py")
    with open(script, "w") as f:
        f.write(_HOST_WORKER)
    bootstrap = str(tmp_path / "bootstrap.yaml")
    with open(bootstrap, "w") as f:
        f.write(SCHEMA_YAML)
    g0p0, g0p1, g1p = _free_port(), _free_port(), _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)

    def boot_peer(peer_id):
        return subprocess.Popen(
            [sys.executable, script, "peer", bootstrap, str(peer_id),
             str(g0p0), str(g0p1), str(tmp_path / f"data{peer_id}"),
             repo_root],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=repo_root)

    def boot_single():
        return subprocess.Popen(
            [sys.executable, script, "single", bootstrap, str(g1p),
             repo_root],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=repo_root)

    procs = {"p0": boot_peer(0), "p1": boot_peer(1),
             "g1": boot_single()}
    smap = ShardMap(version=1, groups=(
        (("127.0.0.1", g0p0), ("127.0.0.1", g0p1)),
        (("127.0.0.1", g1p),)))
    journal = SplitJournal(str(tmp_path / "journal.sqlite"))
    planner = None
    client_kw = dict(connect_timeout=2.0, timeout=20.0, retries=0)

    def make_groups():
        return [
            FailoverEngine([("127.0.0.1", g0p0), ("127.0.0.1", g0p1)],
                           token="sh-tok", probe_timeout=2.0,
                           resolve_deadline=3.0, **client_kw),
            RemoteEngine("127.0.0.1", g1p, token="sh-tok",
                         **client_kw),
        ]

    def wait_ready(budget=120.0):
        deadline = time.monotonic() + budget
        while time.monotonic() < deadline:
            for p in procs.values():
                assert p.poll() is None, p.communicate()[0][-3000:]
            ok = 0
            for port in (g0p0, g0p1, g1p):
                probe = RemoteEngine("127.0.0.1", port, token="sh-tok",
                                     timeout=2.0, connect_timeout=2.0,
                                     retries=0)
                try:
                    st = probe.failover_state()
                    if st["role"] == "leader":
                        ok += 1
                except Exception:
                    pass
                finally:
                    probe.close()
            if ok >= 2:  # group 0's leader + the single host
                return
            time.sleep(0.3)
        raise AssertionError("engine hosts never became ready")

    try:
        wait_ready()
        planner = ShardedEngine(smap, make_groups(), journal=journal)
        # find namespaces owned by each group under THIS map
        ns_g0 = next(f"ns{i}" for i in range(64)
                     if smap.shard_of("pod", f"ns{i}/p") == 0)
        ns_g1 = next(f"ns{i}" for i in range(64)
                     if smap.shard_of("pod", f"ns{i}/p") == 1)

        # seed: a cross-shard write (global namespaces + both groups'
        # pods) through the journaled split path
        writes = [
            WriteOp("create", rel("namespace", ns_g0, "viewer", "user",
                                  "al")),
            WriteOp("create", rel("namespace", ns_g1, "viewer", "user",
                                  "bo")),
            WriteOp("create", rel("pod", f"{ns_g0}/p0", "namespace",
                                  "namespace", ns_g0)),
            WriteOp("create", rel("pod", f"{ns_g1}/p0", "namespace",
                                  "namespace", ns_g1)),
            WriteOp("create", rel("pod", f"{ns_g0}/p0", "viewer",
                                  "user", "solo")),
        ]
        planner.write_relationships(list(writes))
        assert journal.pending_count() == 0

        # (a) single-shard checks: NO scatter, counter-verified
        s_before = _ops_count("scatter")
        assert planner.check(CheckItem("pod", f"{ns_g0}/p0", "view",
                                       "user", "al"))
        assert planner.check(CheckItem("pod", f"{ns_g1}/p0", "view",
                                       "user", "bo"))
        assert not planner.check(CheckItem("pod", f"{ns_g1}/p0",
                                           "view", "user", "al"))
        assert _ops_count("scatter") == s_before

        # (b) scatter-gather parity vs an unsharded oracle
        oracle = _engine()
        oracle.write_relationships(list(writes))
        for u in ("al", "bo", "solo"):
            assert sorted(planner.lookup_resources(
                "pod", "view", "user", u)) == sorted(
                    oracle.lookup_resources("pod", "view", "user", u))

        # (c) cross-shard write interrupted mid-split replays on
        # restart: group 1's leg dies after group 0 applied
        flaky_groups = make_groups()
        flaky_groups[1] = _FlakyWrites(flaky_groups[1])
        flaky = ShardedEngine(smap, flaky_groups, journal=journal,
                              recover=False)
        with pytest.raises(ConnectionResetError):
            flaky.write_relationships([
                WriteOp("create", rel("pod", f"{ns_g0}/p1", "viewer",
                                      "user", "cr")),
                WriteOp("create", rel("pod", f"{ns_g1}/p1", "viewer",
                                      "user", "cr")),
            ])
        assert journal.pending_count() == 1
        flaky.close(close_journal=False)  # the journal outlives the
        #                                   "crashed" planner
        planner2 = ShardedEngine(smap, make_groups(), journal=journal,
                                 recover=True)  # "the restart"
        assert journal.pending_count() == 0
        assert planner2.check(CheckItem("pod", f"{ns_g0}/p1", "view",
                                        "user", "cr"))
        assert planner2.check(CheckItem("pod", f"{ns_g1}/p1", "view",
                                        "user", "cr"))

        # (d) SIGKILL group 0's leader: group 1 undisturbed throughout
        leader_port = None
        for port, proc_key in ((g0p0, "p0"), (g0p1, "p1")):
            probe = RemoteEngine("127.0.0.1", port, token="sh-tok",
                                 timeout=2.0, connect_timeout=2.0,
                                 retries=0)
            try:
                if probe.failover_state()["role"] == "leader":
                    leader_port, victim = port, proc_key
            except Exception:
                pass
            finally:
                probe.close()
        assert leader_port is not None
        procs[victim].kill()
        procs[victim].wait(timeout=10)
        t_kill = time.monotonic()
        g0_recovered = False
        g1_failures = 0
        while time.monotonic() - t_kill < 45:
            # group 1's slice keeps answering DURING the election
            try:
                assert planner2.check(CheckItem(
                    "pod", f"{ns_g1}/p0", "view", "user", "bo"))
            except (DependencyUnavailable, OSError):
                g1_failures += 1
            try:
                if planner2.check(CheckItem(
                        "pod", f"{ns_g0}/p0", "view", "user", "al")):
                    g0_recovered = True
                    break
            except (DependencyUnavailable, OSError):
                pass  # fail-closed window: expected
            time.sleep(0.3)
        assert g1_failures == 0, \
            f"group 1 disturbed by group 0's failover ({g1_failures})"
        assert g0_recovered, "group 0 never failed over"
        planner2.close()
    finally:
        if planner is not None:
            planner.close()
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        outs = []
        for p in procs.values():
            try:
                p.wait(timeout=20)
            except subprocess.TimeoutExpired:
                p.kill()
            outs.append(p.communicate()[0])
    for out in outs:
        assert "STARTING" in out, out[-1500:]


# -- review-hardening regressions --------------------------------------------


def test_cache_key_tolerates_list_valued_context():
    """The middleware's request context always carries a LIST (groups):
    the cache key must stay hashable — probe and fill, no TypeError."""
    cache = ShardVectorCache()
    p, _ = _planner(2, cache=cache)
    p.write_relationships([WriteOp(
        "create", rel("pod", "nsa/p0", "viewer", "user", "al"))])
    items = [CheckItem("pod", "nsa/p0", "view", "user", "al")]
    ctx = {"user": "al", "groups": ["system:authenticated", "dev"],
           "verb": "get", "ip": "10.0.0.1"}
    assert p.try_cached_check(items, context=ctx) is None
    assert p.check_bulk(items, context=ctx) == [True]
    assert p.try_cached_check(items, context=ctx) == [True]
    # different groups list = different key
    ctx2 = dict(ctx, groups=["other"])
    assert p.try_cached_check(items, context=ctx2) is None
    p.close()


def test_cache_entries_are_ttl_bounded():
    """The planner cannot see engine-side verdict-flip watermarks: a
    vector-keyed entry must stop serving after the TTL even when no
    write advances the vector (time-window grants)."""
    now = [0.0]
    c = ShardVectorCache(ttl=5.0, clock=lambda: now[0])
    v = RevisionVector((1, 1))
    c.put("k", v, [True])
    assert c.get("k", v) == [True]
    now[0] = 4.9
    assert c.get("k", v) == [True]
    now[0] = 5.1
    assert c.get("k", v) is None  # expired, never served stale


def test_scatter_delete_precondition_decides_before_any_leg():
    """A failed precondition on the decision shard aborts the WHOLE
    scatter delete with every other shard untouched."""
    from spicedb_kubeapi_proxy_tpu.engine.store import (
        Precondition,
        PreconditionFailed,
    )

    p, engines = _planner(3)
    p.write_relationships([
        WriteOp("create", rel("pod", "nsa/p0", "viewer", "user", "al")),
        WriteOp("create", rel("pod", "nsb/p0", "viewer", "user", "al")),
        WriteOp("create", rel("pod", "nsc/p0", "viewer", "user", "al")),
    ])
    pc = Precondition(RelationshipFilter(
        resource_type="namespace", resource_id="ghost"),
        must_exist=True)
    with pytest.raises(PreconditionFailed):
        p.delete_relationships(
            RelationshipFilter(resource_type="pod"), [pc])
    # NOTHING was deleted anywhere
    assert len(p.read_relationships(RelationshipFilter(
        resource_type="pod"))) == 3
    p.close()


def test_unanchored_precondition_probed_not_bound_per_shard():
    """An unanchored must_exist precondition over a namespaced type
    holds when ANY shard has matching rows — it must not fail the
    split on the shards that hold nothing."""
    p, engines = _planner(2)
    p.write_relationships([WriteOp(
        "create", rel("pod", "nsa/p0", "viewer", "user", "al"))])
    from spicedb_kubeapi_proxy_tpu.engine.store import Precondition

    pc = Precondition(RelationshipFilter(resource_type="pod"),
                      must_exist=True)
    # cross-shard split (global + both shards) with the unanchored pc
    p.write_relationships([
        WriteOp("create", rel("namespace", "ns1", "creator", "user",
                              "al")),
        WriteOp("create", rel("pod", "nsb/p0", "viewer", "user", "al")),
    ], [pc])
    assert p.check(CheckItem("pod", "nsb/p0", "view", "user", "al"))
    p.close()


def test_recovery_reroutes_entries_from_a_different_map(tmp_path):
    """A pending split journaled under a LARGER map must not crash boot
    on a smaller one: the unapplied ops re-route through the CURRENT
    map's owners."""
    journal = SplitJournal(str(tmp_path / "sj.sqlite"))
    # simulate a 4-group deployment's crash: shard indices 0..3, 0 done
    plan = {
        0: [{"op": "create",
             "rel": {"resource_type": "namespace", "resource_id": "n1",
                     "relation": "creator", "subject_type": "user",
                     "subject_id": "al", "subject_relation": None,
                     "expiration": None, "caveat": None,
                     "caveat_context": None}}],
        3: [{"op": "create",
             "rel": {"resource_type": "pod", "resource_id": "nsz/p0",
                     "relation": "viewer", "subject_type": "user",
                     "subject_id": "al", "subject_relation": None,
                     "expiration": None, "caveat": None,
                     "caveat_context": None}}],
    }
    sid = journal.begin(plan, [], map_version=9)
    journal.mark_applied(sid, 0)
    # boot a 2-group planner over the same journal: no IndexError, the
    # unapplied shard-3 ops land on their CURRENT owner
    p = ShardedEngine(_map(2, version=10),
                      [_engine(), _engine()], journal=journal)
    assert journal.pending_count() == 0
    assert p.exists(RelationshipFilter(resource_type="pod",
                                       resource_id="nsz/p0"))
    p.close()


def test_namespaced_lookup_subjects_routes_direct():
    p, _ = _planner(3)
    p.write_relationships([
        WriteOp("create", rel("pod", "nsa/p0", "viewer", "user", "al")),
        WriteOp("create", rel("namespace", "ns1", "viewer", "user",
                              "gl")),
    ])
    s_before = _ops_count("scatter", op="lookup_subjects")
    d_before = _ops_count("single", op="lookup_subjects")
    assert p.lookup_subjects("pod", "nsa/p0", "view", "user") == ["al"]
    assert _ops_count("scatter", op="lookup_subjects") == s_before
    assert _ops_count("single", op="lookup_subjects") == d_before + 1
    # global anchors still scatter (each shard's subject universe
    # covers its own slice) and union exactly
    assert p.lookup_subjects("namespace", "ns1", "view", "user") == \
        ["gl"]
    assert _ops_count("scatter", op="lookup_subjects") > s_before
    p.close()


class _DeadlineWrites:
    """Delegating wrapper raising an AMBIGUOUS failure (an exhausted
    deadline — DependencyUnavailable, not provably-undispatched)."""

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def write_relationships(self, ops, preconditions=()):
        from spicedb_kubeapi_proxy_tpu.utils.resilience import (
            DependencyUnavailable,
        )

        raise DependencyUnavailable("engine:x", "deadline exhausted")


def test_first_shard_deadline_is_ambiguous_stays_pending(tmp_path):
    """An exhausted deadline on the first shard may have dispatched
    (FailoverEngine's own rule): the journal entry must stay pending —
    closing it would leave a silently half-applied split if the write
    actually landed."""
    from spicedb_kubeapi_proxy_tpu.utils.resilience import (
        DependencyUnavailable,
    )

    engines = [_engine(), _engine()]
    journal = SplitJournal(str(tmp_path / "sj.sqlite"))
    flaky = ShardedEngine(
        _map(2), [_DeadlineWrites(engines[0]), engines[1]],
        journal=journal)
    with pytest.raises(DependencyUnavailable):
        flaky.write_relationships([
            WriteOp("create", rel("pod", "nsa/p0", "viewer", "user",
                                  "al")),
            WriteOp("create", rel("pod", "nsb/p0", "viewer", "user",
                                  "al")),
        ])
    assert journal.pending_count() == 1
    p2 = ShardedEngine(_map(2), engines, journal=journal)
    assert journal.pending_count() == 0
    assert p2.check(CheckItem("pod", "nsa/p0", "view", "user", "al"))
    assert p2.check(CheckItem("pod", "nsb/p0", "view", "user", "al"))
    p2.close()


def test_single_shard_write_routes_cross_shard_preconditions():
    """A single-shard write carrying a precondition owned by ANOTHER
    shard must evaluate it against the owner (via the routed probe),
    not against a store that doesn't hold the slice — where must_exist
    would always fail and must_not_exist would always pass (fail
    open)."""
    from spicedb_kubeapi_proxy_tpu.engine.store import (
        Precondition,
        PreconditionFailed,
    )

    p, engines = _planner(2)
    ns0 = next(f"q{i}" for i in range(64)
               if p.map.shard_for(f"q{i}", "pod") == 0)
    ns1 = next(f"q{i}" for i in range(64)
               if p.map.shard_for(f"q{i}", "pod") == 1)
    p.write_relationships([WriteOp(
        "create", rel("pod", f"{ns1}/guard", "viewer", "user", "g"))])
    guard = RelationshipFilter(resource_type="pod",
                               resource_id=f"{ns1}/guard",
                               relation="viewer")
    # must_exist on the OTHER shard's tuple: holds -> write succeeds
    p.write_relationships(
        [WriteOp("create", rel("pod", f"{ns0}/p0", "viewer", "user",
                               "al"))],
        [Precondition(guard, must_exist=True)])
    assert p.check(CheckItem("pod", f"{ns0}/p0", "view", "user", "al"))
    # must_NOT_exist on that same tuple: fails CLOSED, nothing written
    with pytest.raises(PreconditionFailed):
        p.write_relationships(
            [WriteOp("create", rel("pod", f"{ns0}/p1", "viewer",
                                   "user", "al"))],
            [Precondition(guard, must_exist=False)])
    assert not p.exists(RelationshipFilter(resource_type="pod",
                                           resource_id=f"{ns0}/p1"))
    p.close()


def test_boot_survives_unreachable_shard_with_pending_splits(tmp_path):
    """Deferred recovery: a pending split plus one unreachable group
    must NOT prevent planner construction (a one-slice outage must not
    become a full-proxy outage). The entries stay visibly pending and
    replay on the next healthy recover pass — including the lazy one
    before the next split write."""
    engines = [_engine(), _engine()]
    journal = SplitJournal(str(tmp_path / "sj.sqlite"))
    flaky = ShardedEngine(
        _map(2), [engines[0], _FlakyWrites(engines[1])],
        journal=journal)
    with pytest.raises(ConnectionResetError):
        flaky.write_relationships([
            WriteOp("create", rel("pod", "nsa/p0", "viewer", "user",
                                  "al")),
            WriteOp("create", rel("pod", "nsb/p0", "viewer", "user",
                                  "al")),
        ])
    assert journal.pending_count() == 1
    # "restart" with shard 1 STILL down: boots anyway, entry pending
    down = ShardedEngine(
        _map(2), [engines[0], _FlakyWrites(engines[1], fail_times=99)],
        journal=journal)
    assert journal.pending_count() == 1
    assert down.sharding_status()["pending_splits"] == 1
    # the healthy restart replays to completion
    p2 = ShardedEngine(_map(2), engines, journal=journal)
    assert journal.pending_count() == 0
    assert p2.check(CheckItem("pod", "nsb/p0", "view", "user", "al"))
    p2.close()


def test_split_write_retries_deferred_recovery_first(tmp_path):
    """The lazy recovery hook: a planner that booted with deferred
    pending entries replays them before journaling its next split."""
    engines = [_engine(), _engine()]
    journal = SplitJournal(str(tmp_path / "sj.sqlite"))
    flaky = ShardedEngine(
        _map(2), [engines[0], _FlakyWrites(engines[1])],
        journal=journal)
    with pytest.raises(ConnectionResetError):
        flaky.write_relationships([
            WriteOp("create", rel("pod", "nsa/p0", "viewer", "user",
                                  "al")),
            WriteOp("create", rel("pod", "nsb/p0", "viewer", "user",
                                  "al")),
        ])
    assert journal.pending_count() == 1
    # shard 1 recovered, but this planner booted while it was down
    # (recover=False models the deferred state): its next split write
    # replays the backlog first
    p2 = ShardedEngine(_map(2), engines, journal=journal,
                       recover=False)
    assert journal.pending_count() == 1
    p2.write_relationships([
        WriteOp("create", rel("namespace", "nsx", "creator", "user",
                              "al")),
    ])
    assert journal.pending_count() == 0
    assert p2.check(CheckItem("pod", "nsb/p0", "view", "user", "al"))
    p2.close()
