"""Discovery cache: TTL hits, disk persistence across restarts,
stale-on-error (reference disk-cached discovery, server.go:228-243)."""

import asyncio
import json

from spicedb_kubeapi_proxy_tpu.proxy.requestinfo import parse_request_info
from spicedb_kubeapi_proxy_tpu.proxy.types import (
    ProxyRequest,
    json_response,
)
from spicedb_kubeapi_proxy_tpu.utils.discovery import DiscoveryCache


def _req(path="/api", accept=""):
    headers = {"Accept": accept} if accept else {}
    return ProxyRequest(method="GET", path=path, query={}, headers=headers,
                        body=b"", request_info=parse_request_info(
                            "GET", path, {}))


class CountingUpstream:
    def __init__(self, fail=False):
        self.calls = 0
        self.fail = fail

    async def __call__(self, req):
        self.calls += 1
        if self.fail:
            raise ConnectionError("upstream down")
        return json_response(200, {"kind": "APIVersions",
                                   "versions": ["v1"], "n": self.calls})


def test_cache_hits_within_ttl(tmp_path):
    async def go():
        up = CountingUpstream()
        c = DiscoveryCache(ttl=60)
        r1 = await c.serve(_req(), up)
        r2 = await c.serve(_req(), up)
        assert up.calls == 1
        assert r1.body == r2.body
        # distinct paths and Accept values cache separately
        await c.serve(_req("/apis"), up)
        await c.serve(_req(accept="application/json;g=apidiscovery.k8s.io"),
                      up)
        assert up.calls == 3
    asyncio.run(go())


def test_disk_persistence_across_restart(tmp_path):
    async def go():
        up = CountingUpstream()
        c1 = DiscoveryCache(ttl=60, cache_dir=str(tmp_path))
        await c1.serve(_req(), up)
        assert up.calls == 1
        # a "restarted" proxy (fresh cache object) serves from disk
        c2 = DiscoveryCache(ttl=60, cache_dir=str(tmp_path))
        r = await c2.serve(_req(), up)
        assert up.calls == 1
        assert json.loads(r.body)["n"] == 1
    asyncio.run(go())


def test_stale_served_on_upstream_failure():
    async def go():
        up = CountingUpstream()
        c = DiscoveryCache(ttl=0.01)
        r1 = await c.serve(_req(), up)
        await asyncio.sleep(0.05)  # expire
        up.fail = True
        r2 = await c.serve(_req(), up)  # upstream raises -> stale served
        assert r2.body == r1.body
    asyncio.run(go())


def test_authorize_uses_discovery_cache():
    from spicedb_kubeapi_proxy_tpu.authz import AuthzDeps, authorize
    from spicedb_kubeapi_proxy_tpu.engine import Engine
    from spicedb_kubeapi_proxy_tpu.rules.matcher import MapMatcher
    from spicedb_kubeapi_proxy_tpu.rules.input import UserInfo

    RULES = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata:
  name: r
match:
- apiVersion: v1
  resource: namespaces
  verbs: ["get"]
check:
- tpl: "namespace:{{name}}#view@user:{{user.name}}"
"""

    async def go():
        up = CountingUpstream()
        deps = AuthzDeps(matcher=MapMatcher.from_yaml(RULES),
                         engine=Engine(), upstream=up,
                         discovery_cache=DiscoveryCache(ttl=60))
        req = _req("/apis")
        req.user = UserInfo(name="alice", groups=[], extra={})
        await authorize(req, deps)
        await authorize(req, deps)
        assert up.calls == 1
    asyncio.run(go())


def test_cache_bounded_and_identity_encoding(tmp_path):
    async def go():
        seen_enc = []

        async def up(req):
            seen_enc.append(next((v for k, v in req.headers.items()
                                  if k.lower() == "accept-encoding"), None))
            return json_response(200, {"ok": True})

        c = DiscoveryCache(ttl=60, cache_dir=str(tmp_path), max_entries=3)
        # Accept-Encoding is stripped before the upstream call so cached
        # bodies are never compressed
        req = _req()
        req.headers["Accept-Encoding"] = "gzip"
        await c.serve(req, up)
        assert seen_enc == [None]
        # client-controlled key cardinality cannot grow the cache
        # unboundedly: memory and disk stay at max_entries
        for i in range(10):
            await c.serve(_req(accept=f"application/json;x={i}"), up)
        assert len(c._mem) <= 3
        import os
        assert len(os.listdir(tmp_path)) <= 3
    asyncio.run(go())
