"""Native graph-builder core: parity with the numpy fallbacks.

The C++ library (native/graphcore.cpp) is an optional accelerator for
snapshot refresh; behavior must be bit-identical to the numpy paths it
replaces, so every test here checks the native result against the pure
numpy computation on the same inputs.
"""

import numpy as np
import pytest

from spicedb_kubeapi_proxy_tpu import native
from spicedb_kubeapi_proxy_tpu.engine.interning import Interner

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable"
)


def test_unique_inverse_matches_numpy():
    rng = np.random.default_rng(0)
    col = np.char.add("obj", rng.integers(500, size=20_000).astype(str))
    barr = col.astype("S")
    uniq_rows, inv = native.unique_inverse(barr)
    # same partition as np.unique, modulo unique ordering
    np_uniq, np_inv = np.unique(barr, return_inverse=True)
    assert len(uniq_rows) == len(np_uniq)
    # rows mapped to the same native id must hold the same string and
    # vice versa: the inverse arrays are equal up to relabeling
    remap = {}
    for a, b in zip(inv.tolist(), np_inv.reshape(-1).tolist()):
        assert remap.setdefault(a, b) == b
    # uniq_rows are first occurrences
    first = {}
    for i, s in enumerate(barr.tolist()):
        first.setdefault(s, i)
    assert sorted(uniq_rows.tolist()) == sorted(first.values())


def test_unique_inverse_padding_is_not_significant():
    # 'a' vs 'a\0' would collide in a sloppy fixed-width compare only if
    # they were genuinely different strings; with numpy 'S' layout both
    # pad to the same bytes, which matches numpy semantics
    col = np.asarray(["a", "ab", "a", "abc", "ab"], dtype="S3")
    uniq_rows, inv = native.unique_inverse(col)
    assert len(uniq_rows) == 3
    assert inv[0] == inv[2] and inv[1] == inv[4] and inv[3] not in (
        inv[0], inv[1])


def test_sort_perm_matches_stable_argsort():
    rng = np.random.default_rng(1)
    for n in (1, 7, 1000, 100_000):
        keys = rng.integers(0, 1 << 40, size=n, dtype=np.int64)
        # inject duplicates to exercise stability
        keys[n // 2:] = keys[: n - n // 2]
        got = native.sort_perm(keys)
        want = np.argsort(keys, kind="stable")
        np.testing.assert_array_equal(got, want)


def test_sort_perm_rejects_negative_keys():
    assert native.sort_perm(np.asarray([3, -1, 2], dtype=np.int64)) is None


def test_intern_many_bytes_columns_intern_str_keys():
    # 'S' columns must produce str table entries on every path (native,
    # np.unique fallback, small dict loop) so query-time str lookups hit
    rng = np.random.default_rng(5)
    col = np.char.add("x", rng.integers(50, size=3_000).astype(str))
    big, small = Interner(("", "*")), Interner(("", "*"))
    ids_big = big.intern_many(col.astype("S"))
    ids_small = small.intern_many(col[:100].astype("S").tolist())
    assert all(isinstance(s, str) for s in big.strings())
    assert all(isinstance(s, str) for s in small.strings())
    assert big.string(int(ids_big[0])) == str(col[0])
    assert small.string(int(ids_small[0])) == str(col[0])
    assert big.lookup(str(col[1])) == int(ids_big[1])


def test_intern_many_native_vs_python_paths():
    rng = np.random.default_rng(2)
    ids = np.char.add("ns/p", rng.integers(800, size=5_000).astype(str))
    a, b = Interner(("", "*")), Interner(("", "*"))
    got_native = a.intern_many(ids)  # U-array: native path
    got_python = b.intern_many(ids.tolist())  # list: dict loop path
    # same strings must map to the same table contents
    assert [a.string(i) for i in got_native[:100].tolist()] == \
        [b.string(i) for i in got_python[:100].tolist()]
    assert sorted(a.strings()) == sorted(b.strings())
    # interners stay usable incrementally after a bulk pass
    assert a.lookup(str(ids[0])) == int(got_native[0])
    assert a.intern("brand-new") == len(a) - 1
