"""Masked boolean-semiring SpMM primitive (ops/semiring.py): push, pull,
the auto lax.cond, the Pallas dense kernel (interpreter mode on CPU), and
the numpy oracle must agree byte-identically ON EVERY HOP — not just at
the fixpoint — plus the mode-policy plumbing (force_mode, crossover
mapping, per-mode hop_bytes accounting)."""

import numpy as np
import pytest

import jax.numpy as jnp

from spicedb_kubeapi_proxy_tpu.engine import Engine, WriteOp
from spicedb_kubeapi_proxy_tpu.models import parse_schema
from spicedb_kubeapi_proxy_tpu.models.tuples import parse_relationship
from spicedb_kubeapi_proxy_tpu.ops import bitprop, reachability, semiring

SCHEMA = """
definition user {}
definition group {
  relation member: user
}
definition doc {
  relation viewer: user | group#member
  permission view = viewer
}
"""


def _block_engine(monkeypatch, n_docs=12, n_users=7):
    """A small engine whose graph really forms dense blocks WITH
    bit-packed duals on the CPU host (interpret-mode kernel + lowered
    dense threshold) — push and pull are distinct code paths here."""
    monkeypatch.setenv("SDBKP_BITPROP", "interpret")
    monkeypatch.setattr(reachability, "DENSE_MIN_EDGES", 8)
    e = Engine(schema=parse_schema(SCHEMA))
    rels = [f"doc:d{i}#viewer@user:u{(i * 3 + j) % n_users}"
            for i in range(n_docs) for j in range(3)]
    rels += [f"group:g{i}#member@user:u{i % n_users}" for i in range(4)]
    rels += [f"doc:d{i}#viewer@group:g{i % 4}#member" for i in range(6)]
    e.write_relationships(
        [WriteOp("touch", parse_relationship(r)) for r in rels])
    cg = e.compiled()
    d = cg._dev()
    assert cg.blocks and any(b is not None for b in d["blocks_bits"])
    return e, cg, d


def _np_hop(Vf, src, dst, act, metas, blocks):
    """Numpy oracle for one masked-semiring hop (residual + blocks)."""
    B, Mp = Vf.shape
    prop = np.zeros((B, Mp), dtype=np.uint8)
    contrib = Vf[:, src] & act[None, :]
    np.maximum.at(prop.T, dst, contrib.T)
    for bm, A in zip(metas, blocks):
        f = Vf[:, bm.src_off:bm.src_off + bm.n_src].astype(np.int32)
        hit = (f @ np.asarray(A).astype(np.int32).T > 0).astype(np.uint8)
        win = prop[:, bm.dst_off:bm.dst_off + bm.n_dst]
        prop[:, bm.dst_off:bm.dst_off + bm.n_dst] = win | hit
    return prop


def test_propagate_modes_agree_every_hop(monkeypatch):
    """Push, pull, both auto branches, and the numpy oracle produce the
    SAME propagation byte-for-byte at every hop of the closure, and the
    auto lax.cond reports the branch it took."""
    e, cg, d = _block_engine(monkeypatch)
    meta = cg.run_meta()
    Mp = (cg.M // reachability.LANE + 1) * reachability.LANE
    src = np.asarray(d["src"])
    dst = np.asarray(d["dst"])
    act = np.asarray(
        semiring.edge_activation(d["exp"], np.float32(0.0), d["cav"], None))
    dsrc, ddst = d["dsrc"], d["ddst"]
    dact = semiring.edge_activation(d["dexp"], np.float32(0.0),
                                    d["dcav"], None)

    objs = e._objects_by_name()
    B = 3
    V = np.zeros((B, Mp), dtype=np.uint8)
    for b, u in enumerate(("u0", "u1", "u2")):
        # subject slot + wildcard slot, exactly like _seed_base: the
        # user -> group#member -> doc#viewer chain needs multiple hops
        for s in cg.encode_subject("user", u, None, objs):
            if 0 <= s < cg.M:
                V[b, s] = 1

    def one_hop(Vf, mode, crossover):
        prop, is_push = semiring.propagate(
            meta.blocks, d["blocks"], d["blocks_bits"],
            jnp.asarray(src), jnp.asarray(dst), jnp.asarray(act),
            dsrc, ddst, dact, jnp.asarray(Vf),
            semiring.frontier_occupancy(jnp.asarray(Vf)),
            jnp.float32(crossover), level=None, mode=mode)
        return np.asarray(prop), int(is_push)

    for hop in range(6):
        want = _np_hop(V, src, dst, act, meta.blocks, d["blocks"])
        got_push, p1 = one_hop(V, "push", 1.0)
        got_pull, p2 = one_hop(V, "pull", 1.0)
        got_auto_hi, p3 = one_hop(V, "auto", 1.0)   # occ <= 1 -> push
        got_auto_lo, p4 = one_hop(V, "auto", -1.0)  # occ > -1 -> pull
        assert (p1, p2, p3, p4) == (1, 0, 1, 0), hop
        for name, got in (("push", got_push), ("pull", got_pull),
                          ("auto/push", got_auto_hi),
                          ("auto/pull", got_auto_lo)):
            np.testing.assert_array_equal(got, want, err_msg=f"{name}@{hop}")
        V2 = V | want
        if np.array_equal(V2, V):
            break
        V = V2
    else:
        pytest.fail("closure did not settle within the hop budget")
    assert hop >= 1, "graph must need multiple hops to exercise per-hop parity"


def test_edge_activation_fuses_expiry_and_caveat():
    exp = jnp.asarray([1.0, -1.0, 5.0, 5.0], dtype=jnp.float32)
    cav = jnp.asarray([0, 0, 1, 2], dtype=jnp.int32)
    cav_ok = jnp.asarray([1, 0, 1], dtype=jnp.uint8)
    act = np.asarray(semiring.edge_activation(exp, np.float32(0.0),
                                              cav, cav_ok))
    # row0: live + row ok; row1: expired; row2: live + caveat denied;
    # row3: live + caveat ok
    np.testing.assert_array_equal(act, [1, 0, 0, 1])
    # no caveat table: pure expiry mask
    np.testing.assert_array_equal(
        np.asarray(semiring.edge_activation(exp, np.float32(0.0), cav,
                                            None)),
        [1, 0, 1, 1])


def test_crossover_from_occupancy_mapping():
    assert semiring.crossover_from_occupancy(None) == 1.0
    assert semiring.crossover_from_occupancy(0.0) == 1.0
    assert semiring.crossover_from_occupancy(0.3) == pytest.approx(0.7)
    # floor keeps seed-only first hops on push under a dense steady state
    assert semiring.crossover_from_occupancy(1.0) == 0.05


def test_force_mode_and_env(monkeypatch):
    assert semiring.resolved_mode() == "auto"
    monkeypatch.setenv("SDBKP_SEMIRING_MODE", "pull")
    assert semiring.resolved_mode() == "pull"
    monkeypatch.setenv("SDBKP_SEMIRING_MODE", "bogus")
    assert semiring.resolved_mode() == "auto"
    with semiring.force_mode("push"):
        assert semiring.resolved_mode() == "push"
        with semiring.force_mode("pull"):
            assert semiring.resolved_mode() == "pull"
        assert semiring.resolved_mode() == "push"
    assert semiring.resolved_mode() == "auto"
    with pytest.raises(ValueError):
        with semiring.force_mode("sideways"):
            pass


@pytest.mark.parametrize("n_dst,n_src,n_b", [
    (128, 128, 1), (256, 128, 5), (128, 256, 32), (384, 128, 33),
])
def test_dense_pallas_kernel_matches_reference(monkeypatch, n_dst, n_src,
                                               n_b):
    """The MXU-tile dense kernel (interpreter mode on CPU) must match
    the numpy oracle and the dot_general fallback it replaces."""
    monkeypatch.setenv("SDBKP_SEMIRING", "interpret")
    assert bitprop.dense_kernel_enabled()
    assert bitprop.dense_eligible(n_dst, n_src, n_b)
    rng = np.random.default_rng(n_dst + n_src + n_b)
    A = (rng.random((n_dst, n_src)) < 0.05).astype(np.int8)
    frontier = (rng.random((n_b, n_src)) < 0.1).astype(np.uint8)
    got = np.asarray(bitprop.dense_or_matmul(jnp.asarray(A),
                                             jnp.asarray(frontier)))
    want = bitprop.dense_hop_reference(A, frontier)
    np.testing.assert_array_equal(got, want)
    # empty frontier: the @pl.when skip must still zero the output
    zero = np.zeros_like(frontier)
    np.testing.assert_array_equal(
        np.asarray(bitprop.dense_or_matmul(jnp.asarray(A),
                                           jnp.asarray(zero))),
        np.zeros((n_b, n_dst), dtype=np.uint8))


def test_dense_eligibility_matrix():
    """Pallas eligibility: MXU-tile-aligned axes and a VMEM-bounded
    batch only; everything else stays on the dot_general fallback."""
    assert bitprop.dense_eligible(128, 128, 1)
    assert bitprop.dense_eligible(256, 384, 64)
    assert not bitprop.dense_eligible(96, 128, 1)   # dst not tile-aligned
    assert not bitprop.dense_eligible(128, 100, 1)  # src not tile-aligned
    assert not bitprop.dense_eligible(
        128, 128, bitprop.DENSE_B_MAX + 1)          # batch cap
    # the gate composes with the feature switch
    from spicedb_kubeapi_proxy_tpu.utils.features import features
    features.set("SemiringDenseKernel", False)
    try:
        assert not bitprop.dense_kernel_enabled()
    finally:
        features.reset()


def test_hop_bytes_reports_per_mode_traffic(monkeypatch):
    """hop_bytes() breaks the core dense-block bytes out PER SEMIRING
    MODE: push streams the bit-packed duals (8x smaller where they
    exist), pull the full int8 A, pallas adds the MXU kernel's frontier
    re-stream on eligible blocks."""
    _, cg, d = _block_engine(monkeypatch)
    hb = cg.hop_bytes(batch=1)
    modes = hb["modes"]
    assert set(modes) == {"push", "pull", "pallas"}
    core = [bm for bm in cg.run_meta().blocks if bm.level == 0]
    if core:
        assert modes["pull"] == sum(bm.n_dst * bm.n_src for bm in core)
        assert 0 < modes["push"] < modes["pull"]
        assert modes["pallas"] >= modes["pull"]
    # the pre-semiring keys survive for the roofline reports
    for k in ("residual", "blocks", "programs", "tail_once", "total"):
        assert k in hb
