"""Durable persistence: WAL framing/rotation, torn-tail truncation,
snapshot checkpoints + corrupted-snapshot fallback, SIGKILL crash
recovery, revision monotonicity across restarts, and follower catch-up
over the mirror protocol (`mirror_subscribe` with `from_revision`)."""

import asyncio
import os
import signal
import struct
import subprocess
import sys
import time

import numpy as np
import pytest

from spicedb_kubeapi_proxy_tpu.engine import (
    CheckItem,
    Engine,
    RelationshipFilter,
    WriteOp,
)
from spicedb_kubeapi_proxy_tpu.engine.store import Store, StoreError
from spicedb_kubeapi_proxy_tpu.models import parse_schema
from spicedb_kubeapi_proxy_tpu.models.tuples import (
    Relationship,
    parse_relationship,
)
from spicedb_kubeapi_proxy_tpu.persistence import (
    Persistence,
    WalError,
    WriteAheadLog,
    decode_bulk_cols,
    encode_bulk_cols,
    list_snapshots,
    parse_fsync_policy,
    recover,
)
from spicedb_kubeapi_proxy_tpu.persistence import wal as walmod

SCHEMA = parse_schema("""
use expiration

definition user {}
definition group { relation member: user }
definition ns {
  relation viewer: user | group#member | user with expiration
  relation banned: user
  permission view = viewer - banned
}
""")


def rel(i, u="u0", exp=None):
    return Relationship("ns", f"n{i}", "viewer", "user", u, None, exp)


def all_reads(store):
    return sorted(str(r) for r in store.read(RelationshipFilter()))


# -- WAL ---------------------------------------------------------------------


def test_fsync_policy_parse():
    assert parse_fsync_policy("always") == ("always", 0.0)
    assert parse_fsync_policy("off") == ("off", 0.0)
    mode, iv = parse_fsync_policy("interval:250")
    assert mode == "interval" and iv == pytest.approx(0.25)
    for bad in ("", "sometimes", "interval:", "interval:-5", "interval:x"):
        with pytest.raises(WalError):
            parse_fsync_policy(bad)


def test_wal_round_trip_with_blobs(tmp_path):
    d = str(tmp_path / "wal")
    w = WriteAheadLog(d, fsync="off")
    w.append({"kind": "write", "rev": 1, "effects": [{"op": "touch"}]})
    w.append({"kind": "bulk_load", "rev": 2}, b"\x00\x01binary\xffblob")
    w.append({"kind": "write", "rev": 3, "effects": []})
    w.close()
    got = list(walmod.replay(d))
    assert [m["rev"] for m, _ in got] == [1, 2, 3]
    assert got[1][1] == b"\x00\x01binary\xffblob"
    assert got[0][1] is None
    # from_revision filters strictly-greater
    assert [m["rev"] for m, _ in walmod.replay(d, from_revision=2)] == [3]


def test_wal_rotation_and_prune(tmp_path):
    d = str(tmp_path / "wal")
    w = WriteAheadLog(d, fsync="off", segment_bytes=256)
    for i in range(1, 41):
        w.append({"kind": "write", "rev": i,
                  "effects": [{"pad": "x" * 64}]})
    segs = walmod.list_segments(d)
    assert len(segs) > 2, "expected rotation at 256-byte segments"
    # every record survives across segment boundaries, in order
    assert [m["rev"] for m, _ in walmod.replay(d)] == list(range(1, 41))
    # prune everything provably <= rev 20; the active segment stays
    w.prune_upto(20)
    kept = walmod.list_segments(d)
    assert kept and kept[0][0] <= 21
    assert [m["rev"] for m, _ in walmod.replay(d, from_revision=20)] \
        == list(range(21, 41))
    w.close()


def test_wal_torn_tail_truncates_cleanly(tmp_path):
    d = str(tmp_path / "wal")
    w = WriteAheadLog(d, fsync="off")
    for i in range(1, 6):
        w.append({"kind": "write", "rev": i, "effects": []})
    w.close()
    path = walmod.list_segments(d)[-1][1]
    # kill-style torn tail: a partial frame (valid length header, short
    # payload) at the end of the newest segment
    with open(path, "ab") as f:
        f.write(struct.pack(">II", 1000, 0) + b"short")
    size_torn = os.path.getsize(path)
    got = [m["rev"] for m, _ in walmod.replay(d)]
    assert got == [1, 2, 3, 4, 5]
    assert os.path.getsize(path) < size_torn, "torn tail not truncated"
    # a second replay sees a clean log (no re-truncation needed)
    assert [m["rev"] for m, _ in walmod.replay(d)] == [1, 2, 3, 4, 5]
    # appends after recovery land in a FRESH segment and replay fine
    w2 = WriteAheadLog(d, fsync="off")
    w2.append({"kind": "write", "rev": 6, "effects": []})
    w2.close()
    assert [m["rev"] for m, _ in walmod.replay(d)] == [1, 2, 3, 4, 5, 6]


def test_wal_torn_first_frame_removes_segment(tmp_path):
    """A tear that takes a segment's FIRST frame removes the file
    entirely — a kept-but-empty segment would collide with the re-append
    of the revision it is named after."""
    d = str(tmp_path / "wal")
    w = WriteAheadLog(d, fsync="off", segment_bytes=64)
    w.append({"kind": "write", "rev": 1,
              "effects": [{"pad": "x" * 64}]})
    w.append({"kind": "write", "rev": 2, "effects": []})  # second segment
    w.close()
    segs = walmod.list_segments(d)
    assert len(segs) == 2
    # chop the second segment back to magic + partial header
    with open(segs[-1][1], "r+b") as f:
        f.truncate(len(walmod.MAGIC) + 3)
    assert [m["rev"] for m, _ in walmod.replay(d)] == [1]
    assert not os.path.exists(segs[-1][1])
    # revision 2 re-appends into a segment of the SAME name, cleanly
    w2 = WriteAheadLog(d, fsync="off")
    w2.append({"kind": "write", "rev": 2, "effects": []})
    w2.close()
    assert [m["rev"] for m, _ in walmod.replay(d)] == [1, 2]


def test_wal_rejects_oversized_frame(tmp_path, monkeypatch):
    """append() refuses frames replay would classify as torn garbage —
    an oversized record must fail loudly at write time, not be silently
    truncated away at the next recovery."""
    monkeypatch.setattr(walmod, "MAX_WAL_FRAME", 64)
    w = WriteAheadLog(str(tmp_path / "wal"), fsync="off")
    with pytest.raises(WalError, match="frame bound"):
        w.append({"kind": "bulk_load", "rev": 1}, b"x" * 128)
    w.close()


def test_recovery_fails_closed_on_mid_history_corruption(tmp_path):
    """Corruption in a SEALED (non-final) segment must refuse to boot:
    serving would strand every later acknowledged write as permanently
    unreplayable while reporting healthy."""
    from spicedb_kubeapi_proxy_tpu.persistence import RecoveryError

    d = str(tmp_path / "data")
    s = Store()
    p = Persistence.open(s, d, wal_fsync="off", segment_bytes=64,
                         auto_checkpoint=False)
    for i in range(6):
        s.write([WriteOp("touch", rel(i))])
    p.wal.sync()
    p.close(final_checkpoint=False)
    segs = walmod.list_segments(os.path.join(d, "wal"))
    assert len(segs) >= 3
    with open(segs[1][1], "r+b") as f:  # a sealed, non-final segment
        f.seek(len(walmod.MAGIC) + 2)
        b = f.read(1)
        f.seek(len(walmod.MAGIC) + 2)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(RecoveryError, match="mid-history"):
        recover(Store(), d)


def test_wal_corrupt_crc_detected(tmp_path):
    d = str(tmp_path / "wal")
    w = WriteAheadLog(d, fsync="off")
    w.append({"kind": "write", "rev": 1, "effects": []})
    w.append({"kind": "write", "rev": 2, "effects": []})
    w.close()
    path = walmod.list_segments(d)[-1][1]
    # flip one payload byte of the LAST frame: CRC catches it, replay
    # treats it as torn tail
    data = bytearray(open(path, "rb").read())
    data[-3] ^= 0xFF
    open(path, "wb").write(bytes(data))
    assert [m["rev"] for m, _ in walmod.replay(d)] == [1]


# -- store journal + recovery ------------------------------------------------


def test_recover_write_delete_bulk_and_expiry(tmp_path):
    d = str(tmp_path / "data")
    s = Store()
    p = Persistence.open(s, d, wal_fsync="off", auto_checkpoint=False)
    now = time.time()
    for i in range(10):
        s.write([WriteOp("touch", rel(i, f"u{i % 3}"))])
    s.write([WriteOp("touch", rel(99, "exp-user", now + 3600))])
    s.write([WriteOp("touch", rel(98, "dead-user", now - 10))])  # expired
    s.delete_by_filter(RelationshipFilter(resource_id="n3"))
    s.bulk_load({"resource_type": ["pod"] * 3,
                 "resource_id": ["a", "b", "c"],
                 "relation": ["viewer"] * 3,
                 "subject_type": ["user"] * 3,
                 "subject_id": ["x", "y", "z"]})
    s.write([WriteOp("delete", rel(1, "u1"))])
    p.wal.sync()
    want_rev, want_reads, want_len = s.revision, all_reads(s), len(s)
    # crash: no close, no checkpoint
    s2 = Store()
    res = recover(s2, d)
    assert res.snapshot_path is None
    assert res.replayed_records == res.revision == want_rev
    assert s2.revision == want_rev
    assert len(s2) == want_len
    assert all_reads(s2) == want_reads
    # next write continues STRICTLY past the recovered revision
    r = s2.write([WriteOp("touch", rel(500))])
    assert r == want_rev + 1
    p.close(final_checkpoint=False)


def test_snapshot_checkpoint_then_tail_replay(tmp_path):
    d = str(tmp_path / "data")
    s = Store()
    p = Persistence.open(s, d, wal_fsync="off", auto_checkpoint=False)
    for i in range(5):
        s.write([WriteOp("touch", rel(i))])
    cp_rev = p.checkpoint_now()
    assert cp_rev == 5
    assert [r for r, _ in list_snapshots(os.path.join(d, "snapshots"))] \
        == [5]
    for i in range(5, 8):
        s.write([WriteOp("touch", rel(i))])
    p.wal.sync()
    want = all_reads(s)
    s2 = Store()
    res = recover(s2, d)
    assert res.snapshot_revision == 5
    assert res.replayed_records == 3  # only the tail past the snapshot
    assert s2.revision == 8 and all_reads(s2) == want
    p.close(final_checkpoint=False)


def test_corrupt_snapshot_falls_back_to_previous(tmp_path):
    d = str(tmp_path / "data")
    s = Store()
    p = Persistence.open(s, d, wal_fsync="off", auto_checkpoint=False)
    for i in range(4):
        s.write([WriteOp("touch", rel(i))])
    p.checkpoint_now()
    for i in range(4, 9):
        s.write([WriteOp("touch", rel(i))])
    p.checkpoint_now()
    s.write([WriteOp("touch", rel(100, "tail-user"))])
    p.wal.sync()
    want_rev, want = s.revision, all_reads(s)
    snaps = list_snapshots(os.path.join(d, "snapshots"))
    assert len(snaps) == 2
    # mangle the NEWEST snapshot in place
    newest = snaps[-1][1]
    with open(newest, "r+b") as f:
        f.seek(0)
        f.write(b"\xde\xad\xbe\xef" * 8)
    s2 = Store()
    res = recover(s2, d)
    assert res.corrupt_snapshots == [newest]
    assert res.snapshot_revision == snaps[0][0]
    # the longer WAL tail (retained back to the OLDEST snapshot) rebuilt
    # the full state anyway
    assert s2.revision == want_rev and all_reads(s2) == want
    p.close(final_checkpoint=False)


def test_checkpointer_auto_triggers_and_prunes(tmp_path):
    d = str(tmp_path / "data")
    s = Store()
    p = Persistence.open(s, d, wal_fsync="off", segment_bytes=512,
                         checkpoint_wal_records=10, checkpoint_keep=1)
    for i in range(25):
        s.write([WriteOp("touch", rel(i))])
    deadline = time.monotonic() + 10
    snap_dir = os.path.join(d, "snapshots")
    while time.monotonic() < deadline and not list_snapshots(snap_dir):
        time.sleep(0.05)
    snaps = list_snapshots(snap_dir)
    assert snaps, "threshold checkpoint never ran"
    p.close()  # final checkpoint at rev 25
    snaps = list_snapshots(snap_dir)
    assert snaps[-1][0] == 25
    # keep=1: WAL segments behind the kept snapshot are pruned
    s2 = Store()
    res = recover(s2, d)
    assert s2.revision == 25 and res.replayed_records == 0
    assert len(s2) == 25


def test_final_checkpoint_makes_next_boot_replay_free(tmp_path):
    d = str(tmp_path / "data")
    s = Store()
    p = Persistence.open(s, d, wal_fsync="off", auto_checkpoint=False)
    for i in range(6):
        s.write([WriteOp("touch", rel(i))])
    p.close()  # graceful shutdown: final checkpoint
    s2 = Store()
    res = recover(s2, d)
    assert res.replayed_records == 0 and res.snapshot_revision == 6
    assert s2.revision == 6 and len(s2) == 6


# -- engine-level differential restart ---------------------------------------


def engine_checks(e, now=None):
    return [e.check(CheckItem("ns", n, "view", "user", u), now=now)
            for n, u in (("dev", "alice"), ("dev", "bob"),
                         ("prod", "carol"), ("tmp", "dave"),
                         ("dev", "nobody"))]


def test_differential_engine_restart(tmp_path):
    """A write/delete/expire workload replayed after 'restart' produces
    byte-identical check/lookup results and a revision >= pre-crash."""
    d = str(tmp_path / "data")
    e = Engine(schema=SCHEMA)
    e.enable_persistence(d, wal_fsync="off", auto_checkpoint=False)
    now = time.time()
    e.write_relationships(
        [WriteOp("touch", parse_relationship(r)) for r in (
            "group:eng#member@user:alice",
            "ns:dev#viewer@group:eng#member",
            "ns:dev#viewer@user:bob",
            "ns:dev#banned@user:bob",
            "ns:tmp#viewer@user:dave",
        )])
    # expiring grant (future) and an already-dead one (past)
    e.write_relationships([WriteOp("touch", Relationship(
        "ns", "prod", "viewer", "user", "carol", None, now + 3600))])
    e.write_relationships([WriteOp("touch", Relationship(
        "ns", "prod", "viewer", "user", "eve", None, now - 5))])
    e.delete_relationships(RelationshipFilter(resource_id="tmp"))
    e.persistence.wal.sync()
    pre_rev = e.revision
    pin = time.time()  # one clock for both sides of the comparison
    want_checks = engine_checks(e, now=pin)
    want_lookup = sorted(e.lookup_resources("ns", "view", "user", "alice"))
    want_reads = all_reads(e.store)

    e2 = Engine(schema=SCHEMA)
    p2 = e2.enable_persistence(d, wal_fsync="off", auto_checkpoint=False)
    assert p2.recovery.replayed_records == pre_rev
    assert e2.revision == pre_rev
    assert all_reads(e2.store) == want_reads
    assert engine_checks(e2, now=pin) == want_checks
    assert sorted(e2.lookup_resources("ns", "view", "user", "alice")) \
        == want_lookup
    # revisions stay strictly monotonic across the restart: a new write
    # can never mint a revision a pre-crash decision cache already keyed
    assert e2.write_relationships(
        [WriteOp("touch", parse_relationship("ns:new#viewer@user:zed"))]
    ) == pre_rev + 1
    e2.close_persistence(final_checkpoint=False)


def test_load_snapshot_refused_with_persistence(tmp_path):
    e = Engine(schema=SCHEMA)
    path = str(tmp_path / "snap.npz")
    e.save_snapshot(path)
    e.enable_persistence(str(tmp_path / "data"), wal_fsync="off",
                         auto_checkpoint=False)
    with pytest.raises(StoreError):
        e.load_snapshot(path)
    e.close_persistence(final_checkpoint=False)


# -- SIGKILL crash test ------------------------------------------------------

_CHILD = r"""
import sys, time
sys.path.insert(0, {repo!r})
from spicedb_kubeapi_proxy_tpu.engine.store import Store, WriteOp
from spicedb_kubeapi_proxy_tpu.models.tuples import Relationship
from spicedb_kubeapi_proxy_tpu.persistence import Persistence

s = Store()
p = Persistence.open(s, {data_dir!r}, wal_fsync="always",
                     auto_checkpoint=False)
i = 0
while True:
    rev = s.write([WriteOp("touch", Relationship(
        "ns", "n%d" % i, "viewer", "user", "u%d" % (i % 7)))])
    # the ack: only printed AFTER the journaled write returned
    print("ACK %d %d" % (rev, i), flush=True)
    i += 1
"""


def test_sigkill_mid_write_load_recovers_every_acked_write(tmp_path):
    """Hard process death: SIGKILL a writer mid-load; recovery must
    contain EVERY acknowledged write with strictly monotonic revisions
    resuming above the highest acked one."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    d = str(tmp_path / "data")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-c", _CHILD.format(repo=repo, data_dir=d)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env)
    acked = []
    try:
        deadline = time.monotonic() + 60
        while len(acked) < 25 and time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            if line.startswith("ACK "):
                _, rev, i = line.split()
                acked.append((int(rev), int(i)))
        assert len(acked) >= 25, (acked, proc.stderr.read())
    finally:
        proc.kill()  # SIGKILL, mid-write by construction
        proc.wait(timeout=30)

    s = Store()
    res = recover(s, d)
    max_rev = max(r for r, _ in acked)
    # every acked write is present...
    for _, i in acked:
        assert s.exists(RelationshipFilter(
            resource_type="ns", resource_id=f"n{i}")), f"lost acked n{i}"
    # ...revisions were strictly monotonic in the log and resume above
    revs = [r for r, _ in acked]
    assert revs == sorted(set(revs))
    assert s.revision >= max_rev
    assert res.revision == s.revision
    new_rev = s.write([WriteOp("touch", rel(10_000))])
    assert new_rev > max_rev


# -- columnar codec + mirror bulk_load ---------------------------------------


def test_bulk_cols_codec_round_trip():
    cols = {
        "resource_type": ["pod", "pod", "ns"],
        "resource_id": np.asarray(["a", "b", "c"]),
        "relation": ["viewer"] * 3,
        "subject_type": ["user"] * 3,
        # trust boundary: bytes and non-str elements normalize
        "subject_id": [b"x", "y", 7],
        "expiration": [None, 123.5, float("nan")],
    }
    out = decode_bulk_cols(encode_bulk_cols(cols))
    assert [str(x) for x in out["resource_id"]] == ["a", "b", "c"]
    assert [str(x) for x in out["subject_id"]] == ["x", "y", "7"]
    exp = out["expiration"]
    assert np.isnan(exp[0]) and exp[1] == 123.5 and np.isnan(exp[2])


def _frame_from_wire(wire):
    (n,) = struct.unpack(">I", wire[:4])
    body = wire[4:4 + n]
    if body[:1] == b"\x00":
        import json

        (m,) = struct.unpack(">I", body[1:5])
        return json.loads(body[5:5 + m]), body[5 + m:]
    import json

    return json.loads(body), None


def test_mirror_bulk_load_rides_binary_frame():
    """Satellite: MirroredEngine.bulk_load publishes the columnar payload
    on the binary-frame path (one npz encode), not one JSON string per
    cell — and the follower replay reproduces the store exactly."""
    from spicedb_kubeapi_proxy_tpu.parallel.multihost import (
        MirroredEngine,
        apply_mirror_frame,
    )

    leader = MirroredEngine(Engine(schema=SCHEMA))
    q = leader.subscribe()
    n = 500
    leader.bulk_load({
        "resource_type": ["ns"] * n,
        "resource_id": np.asarray([f"n{i}" for i in range(n)]),
        "relation": ["viewer"] * n,
        "subject_type": ["user"] * n,
        "subject_id": [f"u{i % 13}" for i in range(n)],
    })
    msg, blob = _frame_from_wire(q.get_nowait())
    assert blob is not None, "bulk_load frame should carry a binary blob"
    assert "cols" not in msg["frame"], "per-cell JSON lists are retired"
    follower = Engine(schema=SCHEMA)
    apply_mirror_frame(follower, msg["frame"], blob)
    assert len(follower.store) == len(leader.engine.store)
    assert all_reads(follower.store) == all_reads(leader.engine.store)


# -- follower catch-up -------------------------------------------------------


def test_subscribe_with_catchup_atomic_cut():
    from spicedb_kubeapi_proxy_tpu.parallel.multihost import (
        MirroredEngine,
        apply_catchup,
    )

    leader = MirroredEngine(Engine(schema=SCHEMA))
    for i in range(6):
        leader.write_relationships([WriteOp("touch", rel(i))])
    leader.delete_relationships(RelationshipFilter(resource_id="n2"))
    follower = Engine(schema=SCHEMA)
    q, meta, payload = leader.subscribe_with_catchup(follower.revision)
    assert payload is None and meta["effects"]
    apply_catchup(follower, meta, payload)
    assert follower.revision == leader.engine.revision
    assert all_reads(follower.store) == all_reads(leader.engine.store)
    # live frames continue exactly where the catch-up landed
    from spicedb_kubeapi_proxy_tpu.parallel.multihost import (
        apply_mirror_frame,
    )

    leader.write_relationships([WriteOp("touch", rel(50, "late"))])
    msg, blob = _frame_from_wire(q.get_nowait())
    apply_mirror_frame(follower, msg["frame"], blob)
    assert all_reads(follower.store) == all_reads(leader.engine.store)
    assert follower.revision == leader.engine.revision


def test_subscribe_with_catchup_full_state_after_bulk_load():
    from spicedb_kubeapi_proxy_tpu.parallel.multihost import (
        MirroredEngine,
        apply_catchup,
    )

    leader = MirroredEngine(Engine(schema=SCHEMA))
    leader.bulk_load({
        "resource_type": ["ns"] * 4,
        "resource_id": ["a", "b", "c", "d"],
        "relation": ["viewer"] * 4,
        "subject_type": ["user"] * 4,
        "subject_id": ["u1"] * 4,
    })
    leader.write_relationships([WriteOp("touch", rel(9, "u9"))])
    follower = Engine(schema=SCHEMA)
    # the bulk load predates the follower's revision horizon -> a state
    # transfer, not an effects replay
    q, meta, payload = leader.subscribe_with_catchup(0)
    assert payload is not None and meta.get("state")
    apply_catchup(follower, meta, payload)
    assert follower.revision == leader.engine.revision
    assert all_reads(follower.store) == all_reads(leader.engine.store)


def test_catchup_subscribe_satisfies_join_barrier():
    """A leader parked in _publish waiting for its join barrier must be
    released by a catch-up subscription (the queue registers BEFORE the
    consistent cut takes the mirror lock) — and the seq-skip protocol
    keeps the frames queued during the cut from double-applying."""
    import threading

    from spicedb_kubeapi_proxy_tpu.parallel.multihost import (
        MirroredEngine,
        apply_catchup,
        apply_mirror_frame,
    )

    leader = MirroredEngine(Engine(schema=SCHEMA), min_subscribers=1,
                            join_timeout=30.0)
    done = threading.Event()

    def first_write():
        leader.write_relationships([WriteOp("touch", rel(0))])
        done.set()

    t = threading.Thread(target=first_write, daemon=True)
    t.start()
    time.sleep(0.2)  # the writer is parked on the join barrier
    assert not done.is_set()
    follower = Engine(schema=SCHEMA)
    q, meta, payload = leader.subscribe_with_catchup(follower.revision)
    assert done.wait(10), "catch-up subscribe did not satisfy the barrier"
    t.join(10)
    apply_catchup(follower, meta, payload)
    # frames sequenced at or before the cut are covered by the catch-up;
    # anything after it replays live (exactly the follower_loop skip)
    skip_upto = meta["seq"]
    leader.write_relationships([WriteOp("touch", rel(1))])
    while not q.empty():
        msg, blob = _frame_from_wire(q.get_nowait())
        payload_frame = msg["frame"]
        if payload_frame["seq"] <= skip_upto:
            continue
        apply_mirror_frame(follower, payload_frame, blob)
    assert follower.revision == leader.engine.revision
    assert all_reads(follower.store) == all_reads(leader.engine.store)


def test_catchup_diverged_follower_gets_full_state():
    """A follower AHEAD of the leader (lost leader disk / rolled-back
    fsync window) must be forced onto the leader's lineage by a full
    state transfer, not told 'already current'."""
    from spicedb_kubeapi_proxy_tpu.parallel.multihost import (
        MirroredEngine,
        apply_catchup,
    )

    leader = MirroredEngine(Engine(schema=SCHEMA))
    leader.write_relationships([WriteOp("touch", rel(0, "leader-only"))])
    follower = Engine(schema=SCHEMA)
    for i in range(5):  # divergent history the leader never saw
        follower.write_relationships([WriteOp("touch", rel(i, "ghost"))])
    assert follower.revision > leader.engine.revision
    q, meta, payload = leader.subscribe_with_catchup(follower.revision)
    assert payload is not None and meta.get("state")
    apply_catchup(follower, meta, payload)
    assert follower.revision == leader.engine.revision
    assert all_reads(follower.store) == all_reads(leader.engine.store)


def test_follower_catchup_over_tcp_converges_without_bulk_load(tmp_path):
    """Acceptance: a restarting follower resubscribes with from_revision
    (its recovered revision) and converges to the leader over the real
    mirror protocol — no manual bulk_load."""
    from spicedb_kubeapi_proxy_tpu.engine.remote import EngineServer
    from spicedb_kubeapi_proxy_tpu.parallel.multihost import (
        MirroredEngine,
        follower_loop,
    )

    leader = MirroredEngine(Engine(schema=SCHEMA))
    for i in range(8):
        leader.write_relationships([WriteOp("touch", rel(i, f"u{i % 2}"))])
    leader.delete_relationships(RelationshipFilter(resource_id="n5"))

    # the "restarting" follower: recovered some prefix of history from
    # its own data dir (simulated by replaying the first writes locally)
    follower = Engine(schema=SCHEMA)
    follower.enable_persistence(str(tmp_path / "fdata"), wal_fsync="off",
                                auto_checkpoint=False)
    for i in range(3):
        follower.write_relationships(
            [WriteOp("touch", rel(i, f"u{i % 2}"))])
    assert all_reads(follower.store) != all_reads(leader.engine.store)

    async def go():
        server = EngineServer(leader, token="t")
        port = await server.start()
        loop_task = asyncio.create_task(asyncio.to_thread(
            follower_loop, follower, "127.0.0.1", port, "t",
            None, None, follower.revision))
        try:
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if follower.revision == leader.engine.revision and \
                        all_reads(follower.store) \
                        == all_reads(leader.engine.store):
                    break
                await asyncio.sleep(0.05)
            assert all_reads(follower.store) \
                == all_reads(leader.engine.store)
            # live traffic keeps flowing after catch-up
            leader.write_relationships(
                [WriteOp("touch", rel(77, "after"))])
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and \
                    follower.revision != leader.engine.revision:
                await asyncio.sleep(0.05)
            assert all_reads(follower.store) \
                == all_reads(leader.engine.store)
        finally:
            await server.stop()
            await loop_task  # leader gone -> follower_loop returns
    try:
        asyncio.run(go())
    finally:
        follower.close_persistence(final_checkpoint=False)


def test_follower_persistence_survives_catchup_restart(tmp_path):
    """A follower that caught up via a full state transfer journals it
    (load_state record): its NEXT restart recovers the transferred
    baseline from its own data dir."""
    from spicedb_kubeapi_proxy_tpu.parallel.multihost import (
        MirroredEngine,
        apply_catchup,
    )

    leader = MirroredEngine(Engine(schema=SCHEMA))
    leader.bulk_load({
        "resource_type": ["ns"] * 3, "resource_id": ["a", "b", "c"],
        "relation": ["viewer"] * 3, "subject_type": ["user"] * 3,
        "subject_id": ["u1", "u2", "u3"],
    })
    d = str(tmp_path / "fdata")
    follower = Engine(schema=SCHEMA)
    p = follower.enable_persistence(d, wal_fsync="off",
                                    auto_checkpoint=False)
    q, meta, payload = leader.subscribe_with_catchup(0)
    apply_catchup(follower, meta, payload)
    p.wal.sync()
    follower.close_persistence(final_checkpoint=False)

    reborn = Engine(schema=SCHEMA)
    p2 = reborn.enable_persistence(d, wal_fsync="off",
                                   auto_checkpoint=False)
    assert reborn.revision == leader.engine.revision
    assert all_reads(reborn.store) == all_reads(leader.engine.store)
    reborn.close_persistence(final_checkpoint=False)
    assert p2.recovery.replayed_records >= 1


# -- options / CLI wiring ----------------------------------------------------


def test_options_data_dir_validation(tmp_path):
    from spicedb_kubeapi_proxy_tpu.proxy.options import (
        Options,
        OptionsError,
    )

    base = dict(rule_content="x", upstream_url="http://x")
    with pytest.raises(OptionsError, match="data-dir"):
        Options(engine_endpoint="tcp://h:1", data_dir=str(tmp_path),
                **base).validate()
    with pytest.raises(OptionsError, match="mutually exclusive"):
        Options(data_dir=str(tmp_path), snapshot_path="s.npz",
                **base).validate()
    with pytest.raises(OptionsError, match="fsync"):
        Options(data_dir=str(tmp_path), wal_fsync="sometimes",
                **base).validate()
    with pytest.raises(OptionsError, match="checkpoint"):
        Options(data_dir=str(tmp_path), checkpoint_wal_records=0,
                **base).validate()
    Options(data_dir=str(tmp_path), **base).validate()


def test_options_data_dir_wires_engine_and_workflow_db(tmp_path):
    """Satellite: --data-dir makes the store durable AND lands the dtx
    workflow sqlite inside it; without a data dir the historical default
    path is kept."""
    from spicedb_kubeapi_proxy_tpu.proxy.options import (
        DEFAULT_WORKFLOW_DB,
        Options,
    )

    rules = open(os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "deploy", "rules.yaml")).read()
    d = str(tmp_path / "data")
    cfg = Options(rule_content=rules, upstream=object(),
                  data_dir=d, wal_fsync="off", bind_port=0).complete()
    try:
        assert cfg.workflow.db_path == os.path.join(d, "dtx.sqlite")
        assert os.path.exists(cfg.workflow.db_path)
        assert cfg.engine.persistence is not None
        rev0 = cfg.engine.revision
        cfg.engine.write_relationships([WriteOp(
            "touch",
            parse_relationship("namespace:persist#creator@user:alice"))])
        cfg.engine.persistence.wal.sync()
    finally:
        cfg.engine.close_persistence(final_checkpoint=False)

    # a second boot on the same data dir recovers the write
    cfg2 = Options(rule_content=rules, upstream=object(),
                   data_dir=d, wal_fsync="off", bind_port=0).complete()
    try:
        assert cfg2.engine.revision == rev0 + 1
        assert cfg2.engine.store.exists(RelationshipFilter(
            resource_type="namespace", resource_id="persist"))
    finally:
        cfg2.engine.close_persistence(final_checkpoint=False)

    # explicit path and no-data-dir defaults are untouched
    explicit = Options(rule_content="x", upstream_url="http://x",
                       workflow_database_path="/tmp/elsewhere.sqlite")
    assert explicit.workflow_database_path == "/tmp/elsewhere.sqlite"
    assert Options(rule_content="x", upstream_url="http://x"
                   ).workflow_database_path is None
    assert DEFAULT_WORKFLOW_DB  # the unset/no-data-dir fallback


def test_engine_host_cli_data_dir_flags():
    """The engine-host CLI rejects --data-dir + --snapshot-path and bad
    fsync specs at argparse time (no engine built, no sockets)."""
    from spicedb_kubeapi_proxy_tpu.engine import remote as remote_mod

    with pytest.raises(SystemExit):
        remote_mod.main(["--engine-insecure", "--data-dir", "/tmp/x",
                         "--snapshot-path", "/tmp/y.npz"])
    with pytest.raises(SystemExit):
        remote_mod.main(["--engine-insecure", "--data-dir", "/tmp/x",
                         "--wal-fsync", "never"])
