"""Rules engine tests: expression language, templates, tupleSets, matcher,
config validation — modeled on the reference's pkg/rules and
pkg/config/proxyrule test suites."""

import json

import pytest

from spicedb_kubeapi_proxy_tpu.rules import (
    ExprError,
    MapMatcher,
    RequestInfo,
    RequestMeta,
    ResolveInput,
    RuleValidationError,
    UserInfo,
    compile_expr,
    compile_template,
    parse_rule_configs,
)
from spicedb_kubeapi_proxy_tpu.rules.compile import compile_rule
from spicedb_kubeapi_proxy_tpu.rules.proxyrule import RuleConfig


def make_input(verb="get", resource="pods", name="nginx", namespace="default",
               user="alice", groups=(), body=None, api_version="v1",
               api_group=""):
    return ResolveInput.create(
        RequestInfo(verb=verb, api_group=api_group, api_version=api_version,
                    resource=resource, name=name, namespace=namespace,
                    path=f"/api/v1/namespaces/{namespace}/{resource}/{name}"),
        UserInfo(name=user, groups=list(groups)),
        body=body,
        headers={"X-Request-Id": "42"},
    )


# ---------------------------------------------------------------------------
# Expression language
# ---------------------------------------------------------------------------


def ev(src, data=None):
    return compile_expr(src).evaluate(data or {})


def test_expr_basics():
    assert ev("1 + 2") == 3
    assert ev("'a' + 'b'") == "ab"
    assert ev('"x" == "x"')
    assert ev("user.name", {"user": {"name": "alice"}}) == "alice"
    assert ev("'system:masters' in user.groups",
              {"user": {"groups": ["system:masters"]}})
    assert ev("a.b.c", {}) is None  # missing chains to null
    assert ev("a.b.c | 'dflt'", {}) == "dflt"
    assert ev("x ? 'y' : 'n'", {"x": True}) == "y"
    assert ev("if x == 2 { 'two' } else { 'other' }", {"x": 2}) == "two"
    assert ev("!(1 == 2)")
    assert ev("[1,2,3].length()") == 3
    assert ev("'a/b'.split('/')") == ["a", "b"]
    assert ev("'AbC'.lowercase()") == "abc"
    assert ev("'ns1/pod1'.startsWith('ns1')")
    assert ev("7.string()") == "7"
    assert ev("has(a.b)", {"a": {"b": 1}})
    assert not ev("has(a.b)", {})


def test_expr_errors():
    with pytest.raises(ExprError):
        ev("1 + 'a'")
    with pytest.raises(ExprError):
        ev("nosuchfn(1)")
    with pytest.raises(ExprError):
        compile_expr("1 +")
    with pytest.raises(ExprError):
        ev("x.map_each(this)", {"x": "notalist"})
    # non-boolean condition
    with pytest.raises(ExprError):
        compile_expr("'str'").evaluate_bool({})


def test_expr_split_functions():
    # the custom Bloblang env functions (reference env.go)
    assert ev("split_name('ns1/pod1')") == "pod1"
    assert ev("split_namespace('ns1/pod1')") == "ns1"
    assert ev("split_name('cluster-scoped')") == "cluster-scoped"
    assert ev("split_namespace('cluster-scoped')") == ""


def test_expr_lambda_capture_let():
    data = {"namespacedName": "default/dep1",
            "object": {"spec": {"template": {"spec": {"containers": [
                {"name": "server"}, {"name": "sidecar"}]}}}}}
    # the reference's flagship tupleSet expression shape (tupleset_test.go:26)
    out = ev('this.namespacedName.(nsName -> this.object.spec.template.spec'
             '.containers.map_each("deployment:" + nsName '
             '+ "#has-container@container:" + this.name))', data)
    assert out == [
        "deployment:default/dep1#has-container@container:server",
        "deployment:default/dep1#has-container@container:sidecar",
    ]
    # filter variant (tupleset_test.go:64)
    out = ev('this.namespacedName.(nsName -> this.object.spec.template.spec'
             '.containers.filter(this.name != "sidecar")'
             '.map_each("deployment:" + nsName + "#c@container:" + this.name))',
             data)
    assert out == ["deployment:default/dep1#c@container:server"]
    # missing list fallback (tupleset_test.go:116)
    out = ev('(this.object.spec.nope | []).map_each(this.name)', data)
    assert out == []
    # let + $var
    out = ev('let ns = this.namespacedName\n$ns + "!"', data)
    assert out == "default/dep1!"
    # bare var reference
    out = ev('let ns = this.namespacedName\nns + "!"', data)
    assert out == "default/dep1!"


def test_expr_if_else_method_style():
    # service ports shape (tupleset_test.go:81)
    data = {"ports": [{"name": "http", "port": 80}, {"port": 9090}]}
    out = ev('ports.map_each(if this.name != null { this.name } '
             'else { this.port.string() })', data)
    assert out == ["http", "9090"]


def test_template_literal_duality():
    # full-wrap => expression; otherwise literal (reference rules.go:1005-1026)
    assert compile_template("{{user.name}}").evaluate(
        {"user": {"name": "bob"}}) == "bob"
    assert compile_template("literal").evaluate({}) == "literal"
    assert compile_template("$").evaluate({}) == "$"
    assert compile_template("{{}}").evaluate({}) == ""
    assert compile_template("{{split_namespace(resourceId)}}").evaluate(
        {"resourceId": "ns9/p"}) == "ns9"


# ---------------------------------------------------------------------------
# ResolveInput
# ---------------------------------------------------------------------------


def test_resolve_input_namespace_normalization():
    # namespaces resource: namespace field cleared (reference rules.go:331-333)
    i = ResolveInput.create(
        RequestInfo(verb="get", resource="namespaces", name="ns1",
                    namespace="ns1"),
        UserInfo(name="u"))
    assert i.name == "ns1" and i.namespace == "" and i.namespaced_name == "ns1"

    # object metadata preferred over request (create with body)
    body = json.dumps({"metadata": {"name": "frombody", "namespace": "nsb"},
                       "kind": "Pod"}).encode()
    i2 = ResolveInput.create(
        RequestInfo(verb="create", resource="pods", namespace="nsr"),
        UserInfo(name="u"), body=body)
    assert i2.name == "frombody"
    assert i2.namespace == "nsb"
    assert i2.namespaced_name == "nsb/frombody"
    assert i2.object["metadata"]["name"] == "frombody"

    d = i2.template_data()
    assert d["metadata"]["name"] == "frombody"
    assert d["resourceId"] == "nsb/frombody"
    c = i2.condition_data()
    assert c["resourceNamespace"] == "nsb"


# ---------------------------------------------------------------------------
# Rule config parsing + compilation (the reference deploy/rules.yaml)
# ---------------------------------------------------------------------------

REFERENCE_RULES = open("/root/reference/deploy/rules.yaml").read()


def test_parse_reference_deploy_rules():
    cfgs = parse_rule_configs(REFERENCE_RULES)
    assert len(cfgs) == 8
    byname = {c.name: c for c in cfgs}
    cn = byname["create-namespaces"]
    assert cn.spec.locking == "Pessimistic"
    assert cn.spec.update.creates and cn.spec.update.precondition_does_not_exist
    lw = byname["list-watch-pods"]
    assert lw.spec.pre_filters[0].from_object_id_namespace_expr
    # all of them compile
    for c in cfgs:
        compile_rule(c)


def test_rule_end_to_end_resolution():
    cfgs = {c.name: compile_rule(c) for c in parse_rule_configs(REFERENCE_RULES)}
    # get-pods check template resolution
    i = make_input(verb="get", resource="pods", name="nginx",
                   namespace="default", user="alice")
    rels = cfgs["get-pods"].checks[0].generate(i)
    assert str(rels[0]) == "pod:default/nginx#view@user:alice"
    # create-namespaces update resolution
    i2 = ResolveInput.create(
        RequestInfo(verb="create", resource="namespaces", name="",
                    namespace=""),
        UserInfo(name="admin"),
        body=json.dumps({"metadata": {"name": "newns"}}).encode())
    upd = cfgs["create-namespaces"].update
    assert [str(r) for r in upd.creates[0].generate(i2)] == \
        ["namespace:newns#creator@user:admin"]
    assert [str(r) for r in upd.preconditions_do_not_exist[0].generate(i2)] == \
        ["namespace:newns#cluster@cluster:cluster"]
    # prefilter: lookup rel has $ resource id
    pf = cfgs["list-watch-pods"].pre_filters[0]
    i3 = make_input(verb="list", resource="pods", name="", namespace="")
    rel = pf.rel.generate(i3)[0]
    assert rel.resource_id == "$"
    assert rel.subject_id == "alice"
    # name/namespace mapping expressions
    assert pf.name_expr.evaluate({"resourceId": "ns1/p1"}) == "p1"
    assert pf.namespace_expr.evaluate({"resourceId": "ns1/p1"}) == "ns1"


def test_tupleset_rule():
    cfg = parse_rule_configs("""
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata:
  name: deployment-containers
match:
- apiVersion: apps/v1
  resource: deployments
  verbs: ["create"]
update:
  creates:
  - tupleSet: >-
      this.namespacedName.(nsName -> this.object.spec.template.spec.containers.map_each("deployment:" + nsName + "#has-container@container:" + this.name))
""")[0]
    r = compile_rule(cfg)
    body = json.dumps({
        "metadata": {"name": "dep1", "namespace": "default"},
        "spec": {"template": {"spec": {"containers": [
            {"name": "server"}, {"name": "cfg"}]}}},
    }).encode()
    i = ResolveInput.create(
        RequestInfo(verb="create", resource="deployments", namespace="default",
                    api_group="apps", api_version="v1"),
        UserInfo(name="u"), body=body)
    rels = r.update.creates[0].generate(i)
    assert [str(x) for x in rels] == [
        "deployment:default/dep1#has-container@container:server",
        "deployment:default/dep1#has-container@container:cfg",
    ]


def test_if_conditions():
    cfg = parse_rule_configs("""
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata:
  name: cond
match:
- apiVersion: v1
  resource: pods
  verbs: ["get"]
if:
- "request.verb == 'get'"
- "'system:masters' in user.groups"
- "resourceNamespace == 'default'"
check:
- tpl: "pod:{{namespacedName}}#view@user:{{user.name}}"
""")[0]
    r = compile_rule(cfg)
    assert r.conditions_pass(make_input(groups=["system:masters"]))
    assert not r.conditions_pass(make_input(groups=["other"]))
    assert not r.conditions_pass(make_input(groups=["system:masters"],
                                            namespace="kube-system"))


def test_matcher():
    m = MapMatcher.from_yaml(REFERENCE_RULES)
    got = m.match(RequestMeta("get", "", "v1", "pods"))
    assert [r.name for r in got] == ["get-pods"]
    assert m.match(RequestMeta("deletecollection", "", "v1", "pods")) == []
    assert m.match(RequestMeta("get", "apps", "v1", "deployments")) == []
    got = m.match(RequestMeta("watch", "", "v1", "namespaces"))
    assert [r.name for r in got] == ["list-watch-namespaces"]


def test_structured_relationship_template():
    cfg = parse_rule_configs("""
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata:
  name: structured
match:
- apiVersion: v1
  resource: pods
  verbs: ["get"]
check:
- resource:
    type: pod
    id: "{{namespacedName}}"
    relation: view
  subject:
    type: group
    id: eng
    relation: member
""")[0]
    r = compile_rule(cfg)
    rel = r.checks[0].generate(make_input())[0]
    assert str(rel) == "pod:default/nginx#view@group:eng#member"
    assert rel.subject_relation == "member"


@pytest.mark.parametrize("yaml_text,msg", [
    ("kind: ProxyRule\napiVersion: authzed.com/v1alpha1\nmetadata: {name: x}\n",
     "match is required"),
    ("""
kind: ProxyRule
apiVersion: authzed.com/v1alpha1
metadata: {name: x}
match:
- apiVersion: v1
  resource: pods
  verbs: ["frobnicate"]
""", "invalid verb"),
    ("""
kind: ProxyRule
apiVersion: authzed.com/v1alpha1
metadata: {name: x}
match:
- apiVersion: v1
  resource: pods
  verbs: ["list"]
postcheck:
- tpl: "a:b#c@d:e"
""", "postcheck is incompatible"),
    ("""
kind: ProxyRule
apiVersion: authzed.com/v1alpha1
metadata: {name: x}
match:
- apiVersion: v1
  resource: pods
  verbs: ["get"]
check:
- tpl: "a:b#c@d:e"
  tupleSet: "['x']"
""", "mutually exclusive"),
    ("""
kind: NotARule
metadata: {name: x}
match:
- apiVersion: v1
  resource: pods
  verbs: ["get"]
""", "unsupported kind"),
])
def test_rule_validation_errors(yaml_text, msg):
    with pytest.raises(RuleValidationError, match=msg):
        parse_rule_configs(yaml_text)


def test_review_regressions_expr():
    # .or() absorbs missing/null receivers
    assert ev('object.metadata.labels["team"].or("unowned")',
              {"object": {"metadata": {"labels": {}}}}) == "unowned"
    assert ev('x.or("d")', {"x": "real"}) == "real"
    # runtime type errors are recoverable ExprErrors, caught by `|`
    assert ev("int(request.name) | 0", {"request": {"name": "abc"}}) == 0
    with pytest.raises(ExprError):
        ev("request.name.length()", {"request": {"name": 5}})


def test_namespace_subresources_requestinfo():
    from spicedb_kubeapi_proxy_tpu.proxy.requestinfo import parse_request_info
    i = parse_request_info("PUT", "/api/v1/namespaces/default/finalize")
    assert (i.resource, i.name, i.subresource, i.namespace) == \
        ("namespaces", "default", "finalize", "")
    i2 = parse_request_info("GET", "/api/v1/namespaces/default/pods")
    assert (i2.resource, i2.namespace, i2.verb) == ("pods", "default", "list")
