"""In-memory fake kube-apiserver implementing the Upstream interface.

Plays the role envtest's real apiserver plays in the reference e2e suite
(reference e2e/util_test.go:65-102): CRUD + list + watch over JSON
resources, with injectable failures for the crash matrix. Content shape
follows kube conventions (kind lists, Status errors, resourceVersion).
"""

from __future__ import annotations

import asyncio
import json
from typing import Optional

from spicedb_kubeapi_proxy_tpu.proxy.types import (
    ProxyRequest,
    ProxyResponse,
    json_response,
    kube_status,
)
from spicedb_kubeapi_proxy_tpu.proxy.requestinfo import parse_request_info


def _kind_for(resource: str) -> str:
    singular = resource[:-1] if resource.endswith("s") else resource
    return "".join(p.capitalize() for p in singular.split("-"))


async def serve_upstream(fake):
    """Expose an upstream callable (usually a FakeKube) over real HTTP on
    loopback; returns (asyncio server, port)."""
    from spicedb_kubeapi_proxy_tpu.proxy.server import (
        _read_request,
        _write_response,
    )

    async def conn(reader, writer):
        try:
            while True:
                req = await _read_request(reader)
                if req is None:
                    return
                resp = await fake(req)
                await _write_response(writer, resp)
                if resp.stream is not None:
                    return
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            writer.close()

    server = await asyncio.start_server(conn, "127.0.0.1", 0)
    return server, server.sockets[0].getsockname()[1]


class FakeKube:
    def __init__(self):
        # (resource, namespace, name) -> object dict
        self.objects: dict[tuple, dict] = {}
        self.rv = 0
        self._fail_next: list = []  # (matcher, status | Exception)
        self.requests: list[ProxyRequest] = []
        self._watchers: list[tuple[str, str, asyncio.Queue]] = []

    # -- failure injection ---------------------------------------------------

    def fail_next(self, n: int = 1, status: int = 500,
                  exception: Optional[Exception] = None,
                  method: Optional[str] = None):
        for _ in range(n):
            self._fail_next.append((method, status, exception))

    # -- upstream interface --------------------------------------------------

    async def __call__(self, req: ProxyRequest) -> ProxyResponse:
        self.requests.append(req)
        if self._fail_next:
            method, status, exc = self._fail_next[0]
            if method is None or method == req.method:
                self._fail_next.pop(0)
                if exc is not None:
                    raise exc
                return kube_status(status, "injected failure")
        info = req.request_info or parse_request_info(
            req.method, req.path, req.query)
        if not info.is_resource_request:
            if info.path.startswith(("/api", "/apis", "/openapi", "/version")):
                return json_response(200, {"kind": "APIVersions",
                                           "versions": ["v1"]})
            return kube_status(404, "not found")
        res, ns, name = info.resource, info.namespace, info.name
        if info.verb == "get":
            obj = self.objects.get((res, ns, name))
            if obj is None:
                return kube_status(404, f'{res} "{name}" not found', "NotFound")
            return json_response(200, obj)
        if info.verb == "list" or info.verb == "watch":
            if info.verb == "watch":
                return self._start_watch(res, ns)
            items = [o for (r, n_, _), o in sorted(self.objects.items())
                     if r == res and (not ns or n_ == ns)]
            return json_response(200, {
                "kind": _kind_for(res) + "List",
                "apiVersion": "v1",
                "metadata": {"resourceVersion": str(self.rv)},
                "items": items,
            })
        if info.verb == "create":
            try:
                obj = json.loads(req.body)
            except ValueError:
                return kube_status(400, "invalid body")
            name = (obj.get("metadata") or {}).get("name", "")
            if not name:
                return kube_status(400, "name required")
            key = (res, ns, name)
            if key in self.objects:
                return kube_status(409, f'{res} "{name}" already exists',
                                   "AlreadyExists")
            self.rv += 1
            obj.setdefault("metadata", {})
            obj["metadata"]["resourceVersion"] = str(self.rv)
            if ns:
                obj["metadata"]["namespace"] = ns
            obj.setdefault("kind", _kind_for(res))
            self.objects[key] = obj
            self._notify(res, ns, {"type": "ADDED", "object": obj})
            return json_response(201, obj)
        if info.verb == "update":
            key = (res, ns, name)
            if key not in self.objects:
                return kube_status(404, f'{res} "{name}" not found', "NotFound")
            obj = json.loads(req.body)
            self.rv += 1
            obj.setdefault("metadata", {})["resourceVersion"] = str(self.rv)
            self.objects[key] = obj
            self._notify(res, ns, {"type": "MODIFIED", "object": obj})
            return json_response(200, obj)
        if info.verb == "patch":
            key = (res, ns, name)
            if key not in self.objects:
                return kube_status(404, f'{res} "{name}" not found', "NotFound")
            try:
                patch = json.loads(req.body)
            except ValueError:
                return kube_status(400, "invalid patch body", "BadRequest")
            if not isinstance(patch, dict):
                return kube_status(
                    415, "only merge-patch objects supported", "BadRequest")
            obj = json.loads(json.dumps(self.objects[key]))

            def merge(dst, src):
                # JSON Merge Patch (RFC 7386): null deletes the key
                for k, v in src.items():
                    if v is None:
                        dst.pop(k, None)
                    elif isinstance(v, dict) and isinstance(dst.get(k), dict):
                        merge(dst[k], v)
                    else:
                        dst[k] = v

            merge(obj, patch)
            self.rv += 1
            obj.setdefault("metadata", {})["resourceVersion"] = str(self.rv)
            self.objects[key] = obj
            self._notify(res, ns, {"type": "MODIFIED", "object": obj})
            return json_response(200, obj)
        if info.verb == "delete":
            key = (res, ns, name)
            obj = self.objects.pop(key, None)
            if obj is None:
                return kube_status(404, f'{res} "{name}" not found', "NotFound")
            self.rv += 1
            self._notify(res, ns, {"type": "DELETED", "object": obj})
            return json_response(200, {"kind": "Status", "status": "Success",
                                       "code": 200})
        return kube_status(405, f"verb {info.verb} not supported")

    # -- watch ---------------------------------------------------------------

    def _notify(self, res: str, ns: str, event: dict) -> None:
        for r, n_, q in self._watchers:
            if r == res and (not n_ or n_ == ns):
                q.put_nowait(event)

    def _start_watch(self, res: str, ns: str) -> ProxyResponse:
        q: asyncio.Queue = asyncio.Queue()
        # emit existing objects as initial ADDED events (kube semantics with
        # resourceVersion=0 watches)
        for (r, n_, _), o in sorted(self.objects.items()):
            if r == res and (not ns or n_ == ns):
                q.put_nowait({"type": "ADDED", "object": o})
        self._watchers.append((res, ns, q))

        async def frames():
            while True:
                ev = await q.get()
                if ev is None:
                    return
                yield (json.dumps(ev) + "\n").encode()

        return ProxyResponse(
            status=200,
            headers={"Content-Type": "application/json",
                     "Transfer-Encoding": "chunked"},
            stream=frames(),
        )

    def emit_watch_event(self, res: str, event_type: str, name: str,
                         ns: str = "") -> None:
        """Emit a synthetic watch event for an (existing or ad-hoc) object
        — lets tests inject upstream events without a write round trip."""
        obj = self.objects.get((res, ns, name))
        if obj is None:
            obj = {"kind": _kind_for(res), "metadata": {"name": name}}
            if ns:
                obj["metadata"]["namespace"] = ns
        obj = json.loads(json.dumps(obj))  # private copy
        self.rv += 1
        obj.setdefault("metadata", {})["resourceVersion"] = str(self.rv)
        self._notify(res, ns, {"type": event_type, "object": obj})

    def stop_watches(self):
        for _, _, q in self._watchers:
            q.put_nowait(None)
        self._watchers.clear()
