"""Test fake kube-apiserver: the package's in-memory upstream
(`proxy/inmemkube.py`) plus failure injection for the crash matrix and
request recording — the role envtest's real apiserver plays in the
reference e2e suite (reference e2e/util_test.go:65-102).
"""

from __future__ import annotations

import asyncio
from typing import Optional

from spicedb_kubeapi_proxy_tpu.proxy.inmemkube import InMemoryKube
from spicedb_kubeapi_proxy_tpu.proxy.types import (
    ProxyRequest,
    ProxyResponse,
    kube_status,
)


async def serve_upstream(fake):
    """Expose an upstream callable (usually a FakeKube) over real HTTP on
    loopback; returns (asyncio server, port)."""
    from spicedb_kubeapi_proxy_tpu.proxy.server import (
        _read_request,
        _write_response,
    )

    async def conn(reader, writer):
        try:
            while True:
                req = await _read_request(reader)
                if req is None:
                    return
                resp = await fake(req)
                await _write_response(writer, resp)
                if resp.stream is not None:
                    return
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            writer.close()

    server = await asyncio.start_server(conn, "127.0.0.1", 0)
    return server, server.sockets[0].getsockname()[1]


class FakeKube(InMemoryKube):
    def __init__(self):
        super().__init__()
        self._fail_next: list = []  # (method | None, status, Exception | None)
        self.requests: list[ProxyRequest] = []

    # -- failure injection ---------------------------------------------------

    def fail_next(self, n: int = 1, status: int = 500,
                  exception: Optional[Exception] = None,
                  method: Optional[str] = None):
        for _ in range(n):
            self._fail_next.append((method, status, exception))

    async def __call__(self, req: ProxyRequest) -> ProxyResponse:
        self.requests.append(req)
        if self._fail_next:
            method, status, exc = self._fail_next[0]
            if method is None or method == req.method:
                self._fail_next.pop(0)
                if exc is not None:
                    raise exc
                return kube_status(status, "injected failure")
        return await super().__call__(req)
