"""bench.py contract tests: one JSON line on stdout, whatever happens.

Rounds 1 and 2 forfeited their perf evidence because bench.py crashed
(r01) or was SIGTERMed with no JSON flushed (r02). These tests drive the
three init failure modes end-to-end as subprocesses:

- hung TPU plugin (probe times out)          -> degraded CPU run, JSON out
- SIGTERM mid-run (driver timeout kill)      -> partial JSON flushed
- deadline expiry (watchdog thread)          -> partial JSON flushed

``BENCH_PROBE_CMD`` substitutes the TPU probe so a hung plugin is a
``sleep`` and a lying probe is an ``echo``.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

BENCH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench.py")


def _env(probe_cmd):
    env = dict(os.environ)
    env["BENCH_PROBE_CMD"] = probe_cmd
    return env


def _parse_only_line(stdout: str) -> dict:
    lines = [ln for ln in stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line: {lines!r}"
    return json.loads(lines[0])


def test_hung_plugin_falls_back_to_cpu_and_emits_json():
    p = subprocess.run(
        [sys.executable, BENCH, "--tiny", "--probe-timeout", "1",
         "--retry-delay", "0", "--retries", "2"],
        env=_env("sleep 300"), capture_output=True, text=True, timeout=300)
    out = _parse_only_line(p.stdout)
    assert p.returncode == 0
    assert out["degraded"] is True
    assert "hung plugin" in out["backend_error"]
    assert out["value"] is not None and out["value"] > 0
    assert "[DEGRADED: cpu]" in out["metric"]
    # per-stage breakdown (ISSUE 6/7): stages with NO samples in the
    # window are omitted entirely; recorded stages have int counts >= 1
    # and finite-or-null percentiles including p99.9 — never Infinity
    # (json.loads above already rejects bare Infinity-producing bugs at
    # the parse level only for NaN-strict parsers, so check explicitly)
    stages = out["stages"]
    assert set(stages) <= {"admission_wait", "device", "upstream"}
    for st in stages.values():
        assert isinstance(st["n"], int) and st["n"] >= 1
        for k in ("p50_ms", "p99_ms", "p999_ms"):
            v = st[k]
            assert v is None or (isinstance(v, (int, float))
                                 and v == v and abs(v) != float("inf"))
    # the tiny run exercises the engine: the device stage must have
    # samples and real percentiles
    assert stages["device"]["n"] > 0
    assert stages["device"]["p50_ms"] is not None
    _assert_caveat_schema(out["caveats"])
    _assert_mesh_schema(out["mesh"])
    _assert_semiring_schema(out["semiring"])
    _assert_tiered_schema(out["tiered"])
    _assert_shard_schema(out["shard"])
    _assert_rebalance_schema(out["rebalance"])
    _assert_autoscale_schema(out["autoscale"])
    _assert_migration_schema(out["migration"])
    _assert_macro_schema(out["macro"])
    # ISSUE 19: the tiny run also carries the same-seed macro sweep
    # re-run under a live rewriting migration, folded into the baseline
    _assert_macro_migration_schema(out["macro"]["migration"])


def _assert_mesh_schema(mesh: dict) -> None:
    """The ISSUE 15 mesh contract: the device-count axis is MEASURED
    (monotone, labeled with its (data, graph) topology), the caveated
    mix ran ON the mesh (`engine_caveat_mesh_fallback_total` delta
    == 0), steady churn stayed recompile-free on the resident shards,
    p50s are finite, and the K-step fuse's convergence-collective
    reduction is recorded relative to the one-per-hop baseline (the
    single-device iteration count): checks <= ceil(iters/K) + 1."""
    assert mesh["devices_available"] >= 1
    assert mesh["n_pods"] >= 1 and mesh["n_rels"] >= 1
    assert 0.0 < mesh["caveated_share"] < 1.0
    assert mesh["caveat_mesh_fallbacks"] == 0
    counts = mesh["device_counts"]
    assert counts and counts == sorted(set(counts))
    iters = mesh["fixpoint_iters_single"]
    assert isinstance(iters, int) and iters >= 1
    assert set(mesh["points"]) == {str(c) for c in counts}
    for c in counts:
        pt = mesh["points"][str(c)]
        assert pt["devices"] == c
        assert pt["data"] * pt["graph"] == c  # topology label
        assert isinstance(pt["platform"], str) and pt["platform"]
        v = pt["list_p50_ms"]
        assert isinstance(v, (int, float)) and v == v and v > 0 \
            and abs(v) != float("inf"), (c, v)
        k = pt["k_steps"]
        assert isinstance(k, int) and k >= 2
        checks = pt["conv_checks"]
        # per-point baseline, measured at the SAME revision as the mesh
        # query (churn between points can add hops to the cyclic core)
        base = pt["conv_checks_before"]
        assert isinstance(base, int) and base >= 1
        assert 1 <= checks <= -(-base // k) + 1, (c, checks, base, k)
        assert pt["churn_recompiles"] == 0
        assert pt["churn_sharded_updates"] >= 1


def _assert_semiring_schema(sem: dict) -> None:
    """The ISSUE 17 semiring contract: all three forced modes of the one
    SpMM primitive are measured at the SAME revision (the force-mode knob
    is the baseline, not a second checkout), the per-iteration push-vs-
    pull choices are recorded per mode, the dense-phase speedups are
    relative to the forced-pull baseline, the Pallas-vs-lax point is
    present, and a CPU host carries the degraded provenance instead of a
    fabricated MXU number."""
    assert sem["n_pods"] >= 1 and sem["n_rels"] >= 1
    assert 0.0 < sem["caveated_share"] < 1.0
    assert sem["bulk_checks"] >= 1
    # the crossover the auto lax.cond actually compared against (the
    # engine's occupancy EWMA feeds it; bounds pinned by the heuristic)
    assert 0.05 <= sem["crossover"] <= 1.0
    assert set(sem["modes"]) == {"pull", "push", "auto"}
    for mode, pt in sem["modes"].items():
        for k in ("check_p50_ms", "list_p50_ms"):
            v = pt[k]
            assert isinstance(v, (int, float)) and v == v and v > 0 \
                and abs(v) != float("inf"), (mode, k, v)
        iters = pt["iterations"]
        assert isinstance(iters, int) and iters >= 1
        assert 0 <= pt["push_steps"] <= iters
        assert pt["pull_steps"] == iters - pt["push_steps"]
    # a forced-pull fixpoint must never report push steps
    assert sem["modes"]["pull"]["push_steps"] == 0
    for k in ("dense_speedup_push_vs_pull", "dense_speedup_auto_vs_pull",
              "pallas_list_p50_ms", "lax_list_p50_ms", "pallas_over_lax"):
        v = sem[k]
        assert isinstance(v, (int, float)) and v == v and v > 0 \
            and abs(v) != float("inf"), (k, v)
    assert isinstance(sem["pallas_engaged"], bool)
    assert sem["provenance"] in ("tpu", "[DEGRADED: cpu]")
    # no silent MXU claims off-TPU: the kernel cannot have engaged on a
    # degraded (CPU) run, where both sides of the delta are the lax path
    if sem["provenance"] == "[DEGRADED: cpu]":
        assert sem["pallas_engaged"] is False


def _assert_tiered_schema(t: dict) -> None:
    """The ISSUE 18 tiered-storage contract: the SAME graph is measured
    all-resident and under a ~50% device budget (relative ratio — holds
    on any backend speed), the cold start answers with oracle parity,
    steady streaming never re-traces, and the beyond-budget point
    actually paid miss stalls (an empty stall count means the phase
    silently measured a resident graph). tools/tiered_gate.py enforces
    the 1.3x ratio on CI smoke runs; the contract pins the shape."""
    assert t["n_pods"] >= 1 and t["n_rels"] >= 1
    assert t["graph_bytes"] >= 1
    assert 1 <= t["budget_bytes"] < 2 * t["graph_bytes"]
    for k in ("resident_check_p50_ms", "tiered_check_p50_ms",
              "tiered_over_resident", "cold_start_ms"):
        v = t[k]
        assert isinstance(v, (int, float)) and v == v and v > 0 \
            and abs(v) != float("inf"), (k, v)
    assert t["parity_ok"] is True
    assert t["zero_recompiles"] is True
    assert t["miss_stalls"] >= 1
    assert t["hot_blocks"] + t["cold_blocks"] >= 1
    assert t["hot_bytes"] + t["cold_bytes"] == t["graph_bytes"]
    bb = t["beyond_budget"]
    assert bb["budget_bytes"] >= 1
    assert bb["budget_bytes"] < t["budget_bytes"]
    assert bb["n_rels"] >= 1
    assert bb["parity_ok"] is True
    assert bb["miss_stalls"] >= 1
    assert bb["cold_start_ms"] > 0
    assert t["provenance"] in ("tpu", "[DEGRADED: cpu]")


def _assert_shard_schema(sh: dict) -> None:
    """The ISSUE 11 scale-out contract: the 1 vs 2 vs 4 group scaling
    curve is RECORDED (check p50, scatter-lookup p50, goodput per group
    count), and single-shard checks provably never scattered (per-shard
    op counters). Full (non-quick) runs additionally record a 10x
    scale point (~20k namespaces / ~500k relationships) under the same
    schema — pinned here whenever present (the tiny contract run
    doesn't pay its bulk loads)."""
    assert sh["n_ns"] >= 1 and sh["n_rels"] >= 1
    assert sh["single_shard_no_scatter"] is True
    assert set(sh["groups"]) == {"1", "2", "4"}
    for k, g in sh["groups"].items():
        for key in ("check_p50_ms", "scatter_lookup_p50_ms",
                    "goodput_ops_s"):
            v = g[key]
            assert isinstance(v, (int, float)) and v == v and v > 0 \
                and abs(v) != float("inf"), (k, key, v)
        assert g["single_shard_no_scatter"] is True
    if "scale10x" in sh:
        ten = sh["scale10x"]
        assert ten["n_ns"] >= 10 * sh["n_ns"]
        assert ten["n_rels"] >= 100_000
        _assert_shard_schema({k: v for k, v in ten.items()
                              if k != "scale10x"})


def _assert_rebalance_schema(rb: dict) -> None:
    """The ISSUE 14 live-move contract: a 3->4 group grow move is
    MEASURED under load — rows/slices/duration, paused-vs-running
    goodput windows (the mover-interference ratio), zero acked-write
    loss, and zero fail-open probes."""
    assert rb["n_ns"] >= 1 and rb["slices"] >= 1
    assert rb["rows_moved"] >= 1
    assert rb["move_seconds"] > 0
    assert rb["zero_acked_write_loss"] is True
    assert rb["fail_open_probes"] == 0
    for key in ("goodput_paused_ops_s", "goodput_moving_ops_s",
                "goodput_ratio_moving_over_paused"):
        v = rb[key]
        assert v is None or (isinstance(v, (int, float)) and v == v
                             and v > 0 and abs(v) != float("inf")), \
            (key, v)


def _assert_autoscale_schema(au: dict) -> None:
    """The ISSUE 20 elastic scale-out contract: a cross-namespace
    reference schema answered correctly WITHOUT replication (oracle
    parity with exactly one fleet-wide copy of every reference tuple),
    the exchange's boundary mass counter-measured (bounded rounds,
    finite wire bytes), and an SLO-driven shrink PROPOSED by the
    policy and APPLIED by the controller under load with zero acked
    loss and zero fail-open probes."""
    assert au["n_teams"] >= 1 and au["n_docs"] >= 1
    fr = au["frontier"]
    assert fr["parity_checks"] >= 1
    assert fr["parity_ok"] is True
    assert fr["lookup_parity_ok"] is True
    assert fr["reference_single_copy"] is True
    assert fr["exchanges"] >= 1
    assert 1 <= fr["rounds_max"] <= 8
    assert fr["boundary_tuples"] >= 1
    for key in ("scatter_bytes", "gather_bytes"):
        v = fr[key]
        assert isinstance(v, int) and v > 0, (key, v)
    sh = au["shrink"]
    assert sh["proposal_action"] == "shrink"
    assert sh["ticks_to_fire"] >= 2  # hysteresis held, not a one-tick
    assert sh["groups_after"] == 2
    assert sh["move_seconds"] > 0
    assert sh["zero_acked_write_loss"] is True
    assert sh["fail_open_probes"] == 0
    for key in ("goodput_paused_ops_s", "goodput_moving_ops_s",
                "goodput_ratio_moving_over_paused"):
        v = sh[key]
        assert v is None or (isinstance(v, (int, float)) and v == v
                             and v > 0 and abs(v) != float("inf")), \
            (key, v)


def _assert_caveat_schema(cav: dict) -> None:
    """The ISSUE 9 caveat-mix contract: caveated share, cold check p50
    with/without request context vs the uncaveated baseline, warm
    (decision-cached) p50s, the caveated/uncaveated ratio, and the
    fail-closed missing-context denial count."""
    assert cav["n_tuples"] >= 1
    assert 0.0 < cav["caveated_share"] < 1.0
    for k in ("check_p50_uncaveated_ms", "check_p50_caveated_ctx_ms",
              "check_p50_caveated_noctx_ms", "warm_p50_caveated_ctx_ms",
              "warm_p50_uncaveated_ms"):
        v = cav[k]
        assert isinstance(v, (int, float)) and v == v and v >= 0 \
            and abs(v) != float("inf")
    assert cav["caveated_over_uncaveated"] > 0
    # fail-closed accounting: a whole caveated batch without context
    # MUST register missing-context denials (the old behavior silently
    # excluded the tuples instead)
    assert cav["missing_context_denials"] >= 1


def _assert_migration_schema(mig: dict) -> None:
    """The ISSUE 19 live-migration contract: an additive and a
    rewriting migration each complete under a sustained check/write mix
    with finite time-to-cut / freeze / during-window p50 numbers, the
    additive one backfills nothing, the rewriting one backfills the
    affected closure, and the provenance label is honest."""
    assert mig["provenance"] in ("tpu", "[DEGRADED: cpu]")
    assert mig["n_rels"] >= 1
    p50_before = mig["p50_before_ms"]
    assert isinstance(p50_before, (int, float)) and p50_before > 0 \
        and p50_before == p50_before
    ratio = mig["during_over_before_p50"]
    assert isinstance(ratio, (int, float)) and ratio > 0 \
        and abs(ratio) != float("inf")
    for cls in ("additive", "rewriting"):
        row = mig[cls]
        assert row["classification"] == cls
        assert row["phase"] == "done"
        assert row["during_samples"] >= 1
        for k in ("time_to_cut_ms", "freeze_ms", "p50_during_ms"):
            v = row[k]
            assert isinstance(v, (int, float)) and v >= 0 \
                and v == v and abs(v) != float("inf")
    assert mig["additive"]["backfilled"] == 0
    assert mig["rewriting"]["backfilled"] >= 1


def _assert_macro_migration_schema(m: dict) -> None:
    """The macro.migration fold: same-seed sweep under a held-open
    rewriting migration, knee (or top-multiplier goodput) ratio against
    the baseline, and the migration itself finished DONE with a real
    backfill and a sub-second freeze."""
    assert isinstance(m["knee_ratio"], (int, float)) and m["knee_ratio"] > 0
    assert m["basis"] == "knee" or m["basis"].startswith("goodput@x")
    assert m["classification"] == "rewriting"
    assert m["phase"] == "done"
    assert m["backfilled"] >= 1
    assert len(m["curve"]) >= 4
    for k in ("time_to_cut_ms", "freeze_ms"):
        v = m[k]
        assert isinstance(v, (int, float)) and v >= 0 \
            and abs(v) != float("inf")


def _assert_macro_schema(macro: dict) -> None:
    """The ISSUE 7 macro-phase contract: goodput-vs-offered-load curve
    with >= 4 points, a knee estimate, burst p99.9 per op class, per-
    stage tail attribution for the worst burst window, SLO attainment,
    and the reproducibility pin (seed + schedule digest)."""
    curve = macro["curve"]
    assert len(curve) >= 4
    for pt in curve:
        assert {"multiplier", "offered_rps", "completed_rps",
                "goodput_rps", "shed", "errors", "late",
                "classes"} <= set(pt)
        assert pt["offered_rps"] > 0
        for q in pt["classes"].values():
            for k, v in q.items():
                assert k in ("p50_ms", "p99_ms", "p999_ms")
                assert isinstance(v, (int, float)) and v == v \
                    and abs(v) != float("inf")
    # offered load is monotone in the multiplier (open loop: the server
    # cannot flatten it)
    offered = [pt["offered_rps"] for pt in curve]
    assert offered == sorted(offered)
    assert isinstance(macro["knee_saturated"], bool)
    assert macro["knee_rps"] is None or macro["knee_rps"] > 0
    # burst windows with exact per-class tails including p99.9
    assert set(macro["bursts"]) == {"watch-storm", "get-wave",
                                    "reconcile"}
    assert any(b["classes"] for b in macro["bursts"].values())
    for b in macro["bursts"].values():
        for st in b["classes"].values():
            assert st["n"] >= 1
            assert st["p999_ms"] >= st["p99_ms"] >= st["p50_ms"] >= 0
    # tail attribution names the worst burst and splits its stage time
    ta = macro["tail_attribution"]
    assert ta["burst"] in macro["bursts"]
    if ta["traces"] > 0:
        assert ta["stages_us"]
        if any(ta["stages_us"].values()):
            assert sum(ta["stage_share"].values()) == pytest.approx(
                1.0, abs=0.05)
    assert macro["slo_attainment"]
    for v in macro["slo_attainment"].values():
        assert v is None or 0.0 <= v <= 1.0
    assert macro["slo_monitor"]
    # ISSUE 8: every macro result carries the overlay on/off comparison
    # (the same trace re-swept with IncrementalGraphUpdates off) and its
    # per-multiplier goodput ratio, plus the scale annotation
    off = macro["overlay_off"]
    assert off["curve"]
    for pt in off["curve"]:
        assert pt["offered_rps"] > 0
    assert off["goodput_ratio_on_over_off"]
    for v in off["goodput_ratio_on_over_off"].values():
        assert isinstance(v, (int, float)) and v > 0
    assert macro["scale"]["n_ns"] >= 1
    # reproducibility pin: the recorded seed + the digest of the top
    # point's REBUILT schedule (identical seed => identical schedule)
    assert isinstance(macro["seed"], int)
    assert isinstance(macro["schedule_digest"], str)
    assert len(macro["schedule_digest"]) == 16
    int(macro["schedule_digest"], 16)
    assert macro["watch_streams_opened"] >= 0
    assert macro["capacity_rps"] > 0 and macro["base_rate_rps"] > 0


def test_macro_only_headline_is_knee():
    """`bench.py --tiny --macro-only` (the make bench-macro smoke): only
    the sweep runs, the headline metric is the knee estimate, and the
    macro schema holds."""
    p = subprocess.run(
        [sys.executable, BENCH, "--tiny", "--macro-only",
         "--probe-timeout", "10", "--retries", "1"],
        env=_env("echo cpu"), capture_output=True, text=True, timeout=280)
    out = _parse_only_line(p.stdout)
    assert p.returncode == 0, p.stderr[-2000:]
    assert "macrobench goodput knee" in out["metric"]
    assert out["unit"] == "op/s"
    _assert_macro_schema(out["macro"])
    # macro-only really skipped the closed-loop phases
    assert "checks_per_s_per_chip" not in out


def test_sigterm_flushes_partial_json():
    p = subprocess.Popen(
        [sys.executable, BENCH, "--tiny", "--probe-timeout", "120"],
        env=_env("sleep 300"), stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    # wait for the probe-start line: bench logs it AFTER installing the
    # signal handlers and BEFORE the (hung) probe, so killing now is
    # deterministic regardless of machine load. The read runs in a helper
    # thread so a bench that wedges before logging (or exits instantly)
    # cannot block or busy-spin this test past its deadline.
    import threading

    probed = threading.Event()

    def watch_stderr():
        for line in p.stderr:
            if "probing TPU" in line:
                probed.set()
                return

    t = threading.Thread(target=watch_stderr, daemon=True)
    t.start()
    if not probed.wait(timeout=60):
        p.kill()
        raise AssertionError("bench never reached the TPU probe")
    p.send_signal(signal.SIGTERM)
    stdout, _ = p.communicate(timeout=60)
    out = _parse_only_line(stdout)
    assert out["error"] == f"killed by signal {signal.SIGTERM}"
    assert out["degraded"] is True
    assert p.returncode == 128 + signal.SIGTERM


def test_deadline_watchdog_emits_partial_json():
    # the probe lies (echo tpu) and the parent then "hangs": simulated by a
    # probe that passes but a deadline short enough to fire during measure
    p = subprocess.run(
        [sys.executable, BENCH, "--tiny", "--probe-timeout", "1",
         "--retry-delay", "0", "--retries", "1", "--deadline", "1"],
        env=_env("sleep 300"), capture_output=True, text=True, timeout=120)
    out = _parse_only_line(p.stdout)
    assert p.returncode == 2
    assert "deadline" in out["error"]


@pytest.mark.slow
def test_healthy_cpu_quick_run_full_contract():
    # a probe that reports CPU -> degraded but complete measurement
    p = subprocess.run(
        [sys.executable, BENCH, "--tiny", "--probe-timeout", "30",
         "--retries", "1"],
        env=_env("echo cpu"), capture_output=True, text=True, timeout=600)
    out = _parse_only_line(p.stdout)
    assert p.returncode == 0
    assert out["vs_baseline"] is not None
    assert out["checks_per_s_per_chip"] > 0
