"""bench.py contract tests: one JSON line on stdout, whatever happens.

Rounds 1 and 2 forfeited their perf evidence because bench.py crashed
(r01) or was SIGTERMed with no JSON flushed (r02). These tests drive the
three init failure modes end-to-end as subprocesses:

- hung TPU plugin (probe times out)          -> degraded CPU run, JSON out
- SIGTERM mid-run (driver timeout kill)      -> partial JSON flushed
- deadline expiry (watchdog thread)          -> partial JSON flushed

``BENCH_PROBE_CMD`` substitutes the TPU probe so a hung plugin is a
``sleep`` and a lying probe is an ``echo``.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

BENCH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench.py")


def _env(probe_cmd):
    env = dict(os.environ)
    env["BENCH_PROBE_CMD"] = probe_cmd
    return env


def _parse_only_line(stdout: str) -> dict:
    lines = [ln for ln in stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"expected exactly one stdout line: {lines!r}"
    return json.loads(lines[0])


def test_hung_plugin_falls_back_to_cpu_and_emits_json():
    p = subprocess.run(
        [sys.executable, BENCH, "--tiny", "--probe-timeout", "1",
         "--retry-delay", "0", "--retries", "2"],
        env=_env("sleep 300"), capture_output=True, text=True, timeout=300)
    out = _parse_only_line(p.stdout)
    assert p.returncode == 0
    assert out["degraded"] is True
    assert "hung plugin" in out["backend_error"]
    assert out["value"] is not None and out["value"] > 0
    assert "[DEGRADED: cpu]" in out["metric"]
    # per-stage breakdown (ISSUE 6): every stage key serializes, counts
    # are ints, percentiles are finite numbers or null — never Infinity
    # (json.loads above already rejects bare Infinity-producing bugs at
    # the parse level only for NaN-strict parsers, so check explicitly)
    stages = out["stages"]
    assert set(stages) == {"admission_wait", "device", "upstream"}
    for st in stages.values():
        assert isinstance(st["n"], int)
        for k in ("p50_ms", "p99_ms"):
            v = st[k]
            assert v is None or (isinstance(v, (int, float))
                                 and v == v and abs(v) != float("inf"))
    # the tiny run exercises the engine: the device stage must have
    # samples and real percentiles
    assert stages["device"]["n"] > 0
    assert stages["device"]["p50_ms"] is not None


def test_sigterm_flushes_partial_json():
    p = subprocess.Popen(
        [sys.executable, BENCH, "--tiny", "--probe-timeout", "120"],
        env=_env("sleep 300"), stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    # wait for the probe-start line: bench logs it AFTER installing the
    # signal handlers and BEFORE the (hung) probe, so killing now is
    # deterministic regardless of machine load. The read runs in a helper
    # thread so a bench that wedges before logging (or exits instantly)
    # cannot block or busy-spin this test past its deadline.
    import threading

    probed = threading.Event()

    def watch_stderr():
        for line in p.stderr:
            if "probing TPU" in line:
                probed.set()
                return

    t = threading.Thread(target=watch_stderr, daemon=True)
    t.start()
    if not probed.wait(timeout=60):
        p.kill()
        raise AssertionError("bench never reached the TPU probe")
    p.send_signal(signal.SIGTERM)
    stdout, _ = p.communicate(timeout=60)
    out = _parse_only_line(stdout)
    assert out["error"] == f"killed by signal {signal.SIGTERM}"
    assert out["degraded"] is True
    assert p.returncode == 128 + signal.SIGTERM


def test_deadline_watchdog_emits_partial_json():
    # the probe lies (echo tpu) and the parent then "hangs": simulated by a
    # probe that passes but a deadline short enough to fire during measure
    p = subprocess.run(
        [sys.executable, BENCH, "--tiny", "--probe-timeout", "1",
         "--retry-delay", "0", "--retries", "1", "--deadline", "1"],
        env=_env("sleep 300"), capture_output=True, text=True, timeout=120)
    out = _parse_only_line(p.stdout)
    assert p.returncode == 2
    assert "deadline" in out["error"]


@pytest.mark.slow
def test_healthy_cpu_quick_run_full_contract():
    # a probe that reports CPU -> degraded but complete measurement
    p = subprocess.run(
        [sys.executable, BENCH, "--tiny", "--probe-timeout", "30",
         "--retries", "1"],
        env=_env("echo cpu"), capture_output=True, text=True, timeout=600)
    out = _parse_only_line(p.stdout)
    assert p.returncode == 0
    assert out["vs_baseline"] is not None
    assert out["checks_per_s_per_chip"] > 0
