"""Unit coverage for the dependency-resilience layer
(utils/resilience.py) and its metrics surface: deadlines, decorrelated
retry backoff, the circuit-breaker state machine (driven by an injected
clock — no sleeps), the Gauge metric type, and the FAILPOINTS env-parse
hardening."""

import math
import random

import pytest

from spicedb_kubeapi_proxy_tpu.utils.metrics import Registry, metrics
from spicedb_kubeapi_proxy_tpu.utils.resilience import (
    STATE_CLOSED,
    STATE_HALF_OPEN,
    STATE_OPEN,
    BreakerOpen,
    CircuitBreaker,
    Deadline,
    DeadlineExceeded,
    DependencyUnavailable,
    RetryPolicy,
)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture(autouse=True)
def _clean_metrics():
    metrics.reset()
    yield
    metrics.reset()


# -- Deadline ----------------------------------------------------------------


def test_deadline_budget_derives_per_attempt_timeouts():
    clock = FakeClock()
    d = Deadline.after(10.0, clock=clock)
    # attempt caps clamp to the remaining total
    assert d.budget(5.0) == 5.0
    clock.advance(7.0)
    assert d.budget(5.0) == pytest.approx(3.0)
    assert d.remaining() == pytest.approx(3.0)
    assert not d.expired
    clock.advance(4.0)
    assert d.expired
    assert d.remaining() == 0.0
    with pytest.raises(DeadlineExceeded) as ei:
        d.check("upstream")
    assert ei.value.dependency == "upstream"


def test_deadline_zero_or_none_means_unlimited():
    for total in (None, 0, 0.0):
        d = Deadline.after(total)
        assert d.remaining() is math.inf
        assert not d.expired
        assert d.budget(5.0) == 5.0
        assert d.budget() is None  # usable as wait_for/settimeout "no limit"
        d.check("upstream")  # never raises


def test_deadline_exceeded_maps_to_dependency_unavailable():
    assert issubclass(DeadlineExceeded, DependencyUnavailable)
    assert issubclass(BreakerOpen, DependencyUnavailable)


# -- RetryPolicy -------------------------------------------------------------


def test_retry_policy_decorrelated_jitter_bounds():
    p = RetryPolicy(base=0.1, cap=2.0, rng=random.Random(7))
    delays = p.delays()
    prev = p.base
    for _ in range(50):
        d = next(delays)
        assert 0.1 <= d <= 2.0
        assert d <= max(0.1, prev * 3) + 1e-9
        prev = max(d, p.base)


def test_retry_policy_zero_base_is_sleepless():
    p = RetryPolicy(base=0.0, cap=0.0)
    delays = p.delays()
    assert [next(delays) for _ in range(10)] == [0.0] * 10


def test_breaker_check_open_rejects_during_inflight_probe():
    """check_open must also fail fast while the half-open probe is in
    flight — a probe can hang up to a full read timeout against a
    stalled host, and dual-writes must not durably enqueue behind it."""
    clock = FakeClock()
    b = CircuitBreaker("upstream", failure_threshold=1, reset_timeout=5.0,
                       clock=clock)
    b.allow()
    b.record_failure()
    clock.advance(5.0)
    b.check_open()  # probe-eligible: passes
    b.allow()  # probe admitted
    with pytest.raises(BreakerOpen, match="probe in flight"):
        b.check_open()
    b.record_success()
    b.check_open()  # closed again


# -- CircuitBreaker ----------------------------------------------------------


def test_breaker_full_state_machine():
    clock = FakeClock()
    b = CircuitBreaker("engine:h:1", failure_threshold=3,
                      reset_timeout=5.0, clock=clock)
    gauge = metrics.gauge("proxy_dependency_breaker_state",
                          dependency="engine:h:1")
    assert b.state == STATE_CLOSED and gauge.value == STATE_CLOSED
    assert b.open_reason() is None

    # below threshold: stays closed; a success resets the streak
    for _ in range(2):
        b.allow()
        b.record_failure()
    b.allow()
    b.record_success()
    for _ in range(2):
        b.allow()
        b.record_failure()
    assert b.state == STATE_CLOSED

    # threshold consecutive failures -> OPEN, fail-fast with Retry-After
    b.allow()
    b.record_failure()
    assert b.state == STATE_OPEN and gauge.value == STATE_OPEN
    assert "circuit open" in b.open_reason()
    clock.advance(2.0)
    with pytest.raises(BreakerOpen) as ei:
        b.allow()
    assert ei.value.dependency == "engine:h:1"
    assert ei.value.retry_after == pytest.approx(3.0)

    # reset window elapses -> HALF_OPEN admits exactly one probe
    clock.advance(3.5)
    b.allow()
    assert b.state == STATE_HALF_OPEN and gauge.value == STATE_HALF_OPEN
    with pytest.raises(BreakerOpen):
        b.allow()  # second concurrent probe rejected
    # probe failure re-opens with a fresh window
    b.record_failure()
    assert b.state == STATE_OPEN
    with pytest.raises(BreakerOpen):
        b.allow()

    # next probe succeeds -> CLOSED again
    clock.advance(5.0)
    b.allow()
    b.record_success()
    assert b.state == STATE_CLOSED and gauge.value == STATE_CLOSED
    assert b.open_reason() is None
    rejections = metrics.counter(
        "proxy_dependency_breaker_rejections_total",
        dependency="engine:h:1")
    assert rejections.value == 3.0


def test_breaker_release_frees_a_wedged_probe_slot():
    """A half-open probe that ends in a NON-transport outcome (handler
    cancelled, protocol error) must release its slot — otherwise the
    breaker rejects everything forever with no path to recovery."""
    clock = FakeClock()
    b = CircuitBreaker("upstream", failure_threshold=1, reset_timeout=5.0,
                       clock=clock)
    b.allow()
    b.record_failure()  # open
    clock.advance(5.0)
    b.allow()  # half-open probe admitted
    assert b.state == STATE_HALF_OPEN
    b.release()  # probe ended without a transport verdict
    # state and failure streak unchanged, but the next attempt may probe
    assert b.state == STATE_HALF_OPEN
    b.allow()
    b.record_success()
    assert b.state == STATE_CLOSED


def test_breaker_check_open_fails_fast_without_consuming_probe():
    clock = FakeClock()
    b = CircuitBreaker("upstream", failure_threshold=1, reset_timeout=5.0,
                       clock=clock)
    b.check_open()  # closed: no-op
    b.allow()
    b.record_failure()
    with pytest.raises(BreakerOpen) as ei:
        b.check_open()
    assert ei.value.retry_after == pytest.approx(5.0)
    # probe-eligible: check_open defers to a real attempt, and it never
    # consumed the probe slot meanwhile
    clock.advance(5.0)
    b.check_open()
    b.allow()
    b.record_success()
    assert b.state == STATE_CLOSED


def test_breaker_probe_eligible_reports_ready():
    """An open breaker past its reset window reports READY on /readyz:
    unreadiness pulls the replica from rotation, and without traffic
    allow() — the only open->half-open path — would never run, leaving
    the replica unready forever after the dependency recovers."""
    clock = FakeClock()
    b = CircuitBreaker("engine:h:1", failure_threshold=1, reset_timeout=5.0,
                       clock=clock)
    b.allow()
    b.record_failure()
    assert "circuit open" in b.open_reason()
    clock.advance(5.0)
    assert b.open_reason() is None  # probe-eligible -> back in rotation
    b.allow()  # traffic returns and probes
    assert "probing" in b.open_reason()
    b.record_success()
    assert b.open_reason() is None


def test_breaker_force_open_and_reason_naming():
    clock = FakeClock()
    b = CircuitBreaker("upstream", failure_threshold=5, reset_timeout=10.0,
                       clock=clock)
    b.force_open()
    assert b.state == STATE_OPEN
    assert "next probe in 10.0s" in b.open_reason()
    with pytest.raises(BreakerOpen):
        b.allow()


# -- Gauge metric type -------------------------------------------------------


def test_gauge_set_inc_dec_and_render_format():
    r = Registry()
    g = r.gauge("proxy_dependency_breaker_state", dependency="upstream")
    g.set(2)
    g.inc()
    g.dec(0.5)
    assert g.value == 2.5
    r.counter("proxy_requests_total").inc()
    r.histogram("proxy_request_seconds").observe(0.1)
    text = r.render()
    assert ('proxy_dependency_breaker_state{dependency="upstream"} 2.5'
            in text.splitlines())
    # gauges render alongside counters and histogram _count/_sum
    assert "proxy_requests_total 1.0" in text
    assert "proxy_request_seconds_count 1" in text
    # same (name, labels) key returns the same gauge; reset clears it
    assert r.gauge("proxy_dependency_breaker_state",
                   dependency="upstream") is g
    r.reset()
    assert "breaker_state" not in r.render()


# -- FAILPOINTS env hardening ------------------------------------------------


def test_failpoints_malformed_env_entry_is_skipped_not_fatal(monkeypatch):
    from spicedb_kubeapi_proxy_tpu.utils.failpoints import _Registry

    monkeypatch.setenv("FAILPOINTS",
                       "broken:abc, ,good:2,bare,also:bad:3")
    reg = _Registry()  # must not raise despite the malformed entries
    assert not reg.armed("broken")
    assert reg.armed("good")
    assert reg.armed("bare")
    assert not reg.armed("also")
    # budgets parsed from the well-formed entries still count down
    for _ in range(2):
        with pytest.raises(Exception):
            reg.hit("good")
    assert not reg.armed("good")
