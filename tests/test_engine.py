"""Engine core tests: store semantics, oracle, and TPU-path equivalence.

The key gate: the jitted slot-space fixpoint (ops/reachability.py) must
agree with the recursive oracle evaluator on every (object, permission,
subject) combination, across schema features and randomized graphs.
"""

import time

import numpy as np
import pytest

from spicedb_kubeapi_proxy_tpu.engine import (
    CheckItem,
    Engine,
    Precondition,
    PreconditionFailed,
    RelationshipFilter,
    Store,
    WriteOp,
)
from spicedb_kubeapi_proxy_tpu.engine.store import AlreadyExists
from spicedb_kubeapi_proxy_tpu.engine.engine import SchemaViolation
from spicedb_kubeapi_proxy_tpu.models import parse_schema
from spicedb_kubeapi_proxy_tpu.models.tuples import Relationship, parse_relationship


def rel(s: str) -> Relationship:
    return parse_relationship(s)


def touch(*rels: str) -> list[WriteOp]:
    return [WriteOp("touch", rel(r)) for r in rels]


# ---------------------------------------------------------------------------
# Store semantics
# ---------------------------------------------------------------------------


def test_store_create_touch_delete():
    s = Store()
    s.write([WriteOp("create", rel("ns:a#viewer@user:alice"))])
    with pytest.raises(AlreadyExists):
        s.write([WriteOp("create", rel("ns:a#viewer@user:alice"))])
    # touch is an upsert
    s.write([WriteOp("touch", rel("ns:a#viewer@user:alice"))])
    assert len(s) == 1
    # delete is idempotent
    s.write([WriteOp("delete", rel("ns:a#viewer@user:alice"))])
    s.write([WriteOp("delete", rel("ns:a#viewer@user:alice"))])
    assert len(s) == 0


def test_store_preconditions():
    s = Store()
    s.write(touch("ns:a#viewer@user:alice"))
    # must-not-exist fails when it exists
    with pytest.raises(PreconditionFailed):
        s.write(
            touch("ns:b#viewer@user:bob"),
            [Precondition(RelationshipFilter("ns", "a", "viewer"), must_exist=False)],
        )
    # must-exist passes
    s.write(
        touch("ns:b#viewer@user:bob"),
        [Precondition(RelationshipFilter("ns", "a", "viewer"), must_exist=True)],
    )
    assert len(s) == 2
    # filter with subject fields
    assert s.exists(RelationshipFilter(subject_type="user", subject_id="bob"))
    assert not s.exists(RelationshipFilter(subject_type="user", subject_id="carol"))


def test_store_read_and_delete_by_filter():
    s = Store()
    s.write(touch(
        "pod:ns1/a#viewer@user:alice",
        "pod:ns1/b#viewer@user:alice",
        "pod:ns2/c#viewer@user:bob",
        "ns:ns1#viewer@user:alice",
    ))
    got = {str(r) for r in s.read(RelationshipFilter(resource_type="pod"))}
    assert got == {
        "pod:ns1/a#viewer@user:alice",
        "pod:ns1/b#viewer@user:alice",
        "pod:ns2/c#viewer@user:bob",
    }
    n = s.delete_by_filter(
        RelationshipFilter(resource_type="pod", subject_type="user",
                           subject_id="alice"))
    assert n == 2
    assert len(s) == 2


def test_store_expiration():
    s = Store()
    now = time.time()
    s.write([
        WriteOp("touch", Relationship("ns", "a", "viewer", "user", "x",
                                      expiration=now - 10)),
        WriteOp("touch", Relationship("ns", "b", "viewer", "user", "x",
                                      expiration=now + 1000)),
    ])
    live = {r.resource_id for r in s.read(RelationshipFilter(resource_type="ns"))}
    assert live == {"b"}
    # an expired tuple does not block CREATE
    s.write([WriteOp("create", Relationship("ns", "a", "viewer", "user", "x"))])


def test_store_watch_log():
    s = Store()
    r0 = s.revision
    s.write(touch("ns:a#viewer@user:alice"))
    s.write([WriteOp("delete", rel("ns:a#viewer@user:alice"))])
    recs = s.watch_since(r0)
    assert [(r.op, str(r.rel)) for r in recs] == [
        (2, "ns:a#viewer@user:alice"),
        (3, "ns:a#viewer@user:alice"),
    ]


def test_store_bulk_load_and_snapshot():
    s = Store()
    n = 1000
    s.bulk_load({
        "resource_type": ["pod"] * n,
        "resource_id": [f"p{i}" for i in range(n)],
        "relation": ["viewer"] * n,
        "subject_type": ["user"] * n,
        "subject_id": [f"u{i % 7}" for i in range(n)],
    })
    assert len(s) == n
    snap = s.snapshot()
    assert len(snap.cols) == n
    # single-row ops still work after bulk load (lazy index build)
    s.write([WriteOp("delete", rel("pod:p0#viewer@user:u0"))])
    assert len(s) == n - 1


def test_store_bulk_load_prebuilds_index_in_background():
    """bulk_load kicks off the big-chunk index build on a background
    thread, so the first write joins it instead of paying the full
    hash+sort inline (bench first_write_after_bulk target <1s @ 10M)."""
    from spicedb_kubeapi_proxy_tpu.engine.store import INDEX_SMALL_CHUNK

    s = Store()
    n = INDEX_SMALL_CHUNK + 100  # above the sorted-index threshold
    s.bulk_load({
        "resource_type": ["pod"] * n,
        "resource_id": [f"p{i}" for i in range(n)],
        "relation": ["viewer"] * n,
        "subject_type": ["user"] * n,
        "subject_id": [f"u{i % 7}" for i in range(n)],
    })
    t = s._prebuild_thread
    assert t is not None
    t.join(timeout=30)
    assert not t.is_alive()
    # the prebuilt index is stashed, keyed by chunk identity
    assert id(s._chunks[0]) in s._index._prebuilt
    # first write consumes it (no rebuild) and behaves correctly
    s.write([WriteOp("delete", rel("pod:p3#viewer@user:u3"))])
    assert s._index._prebuilt == {}
    assert s._prebuild_thread is None
    assert len(s) == n - 1
    # touch of an existing key replaces, not duplicates (index finds rows)
    before = len(s)
    s.write([WriteOp("touch", rel("pod:p4#viewer@user:u4"))])
    assert len(s) == before


def test_store_back_to_back_bulk_loads_leak_no_prebuilt_entries():
    """A second bulk_load joins the first load's prebuild thread before
    spawning its own, so no abandoned thread can publish a stale sorted
    index after sync() has passed its chunk."""
    from spicedb_kubeapi_proxy_tpu.engine.store import INDEX_SMALL_CHUNK

    s = Store()
    n = INDEX_SMALL_CHUNK + 10
    for batch in range(2):
        s.bulk_load({
            "resource_type": ["pod"] * n,
            "resource_id": [f"b{batch}/p{i}" for i in range(n)],
            "relation": ["viewer"] * n,
            "subject_type": ["user"] * n,
            "subject_id": [f"u{i % 5}" for i in range(n)],
        })
    s.write([WriteOp("delete", rel("pod:b0/p0#viewer@user:u0"))])
    assert s._index._prebuilt == {}
    assert len(s._index._sorted) == 2  # both big chunks indexed exactly once
    assert len(s) == 2 * n - 1


# ---------------------------------------------------------------------------
# Engine write validation
# ---------------------------------------------------------------------------

SCHEMA = """
use expiration

definition user {}
definition group {
  relation member: user | group#member
}
definition namespace {
  relation creator: user
  relation viewer: user | group#member | user:*
  permission edit = creator
  permission view = viewer + creator
}
definition pod {
  relation namespace: namespace
  relation creator: user
  relation viewer: user with expiration
  permission edit = creator
  permission view = viewer + creator + namespace->view
}
"""


def make_engine(*rels_: str) -> Engine:
    e = Engine(schema=parse_schema(SCHEMA))
    if rels_:
        e.write_relationships(touch(*rels_))
    return e


def test_engine_write_validation():
    e = make_engine()
    with pytest.raises(SchemaViolation, match="no relation"):
        e.write_relationships(touch("namespace:a#nope@user:x"))
    with pytest.raises(SchemaViolation, match="not writable"):
        e.write_relationships(touch("namespace:a#view@user:x"))
    with pytest.raises(SchemaViolation, match="not allowed"):
        e.write_relationships(touch("namespace:a#creator@group:g#member"))
    with pytest.raises(SchemaViolation, match="unknown resource type"):
        e.write_relationships(touch("zebra:a#creator@user:x"))
    with pytest.raises(SchemaViolation, match="not allowed"):
        e.write_relationships(touch("namespace:a#creator@user:*"))
    with pytest.raises(SchemaViolation, match="expiring"):
        e.write_relationships([WriteOp("touch", Relationship(
            "namespace", "a", "creator", "user", "x",
            expiration=time.time() + 60))])
    # allowed cases
    e.write_relationships(touch(
        "namespace:a#viewer@user:*",
        "namespace:a#viewer@group:g#member",
        "pod:a/p#namespace@namespace:a",
    ))


# ---------------------------------------------------------------------------
# Oracle sanity (hand-computed expectations)
# ---------------------------------------------------------------------------


def test_oracle_basics():
    e = make_engine(
        "namespace:ns1#creator@user:alice",
        "namespace:ns1#viewer@user:bob",
        "pod:ns1/p1#namespace@namespace:ns1",
        "pod:ns1/p1#creator@user:carol",
    )
    o = e.oracle()
    assert o.check("namespace", "ns1", "view", "user", "alice")  # creator
    assert o.check("namespace", "ns1", "view", "user", "bob")  # viewer
    assert not o.check("namespace", "ns1", "edit", "user", "bob")
    # arrow: pod view via namespace->view
    assert o.check("pod", "ns1/p1", "view", "user", "alice")
    assert o.check("pod", "ns1/p1", "view", "user", "bob")
    assert o.check("pod", "ns1/p1", "view", "user", "carol")
    assert not o.check("pod", "ns1/p1", "edit", "user", "bob")
    assert o.lookup_resources("pod", "view", "user", "bob") == {"ns1/p1"}


def test_oracle_nested_groups_and_wildcard():
    e = make_engine(
        "group:eng#member@user:dev1",
        "group:all#member@group:eng#member",
        "namespace:ns#viewer@group:all#member",
        "namespace:open#viewer@user:*",
    )
    o = e.oracle()
    assert o.check("namespace", "ns", "view", "user", "dev1")
    assert not o.check("namespace", "ns", "view", "user", "outsider")
    assert o.check("namespace", "open", "view", "user", "anyone")


def test_oracle_cycle_terminates():
    e = make_engine(
        "group:a#member@group:b#member",
        "group:b#member@group:a#member",
        "group:b#member@user:u",
        "namespace:ns#viewer@group:a#member",
    )
    o = e.oracle()
    assert o.check("namespace", "ns", "view", "user", "u")
    assert not o.check("namespace", "ns", "view", "user", "v")


# ---------------------------------------------------------------------------
# TPU path vs oracle equivalence
# ---------------------------------------------------------------------------


def assert_engine_matches_oracle(e: Engine, subjects=None):
    """Exhaustively compare engine.check_bulk and lookup_resources against
    the oracle for every (type, object, permission) x subject."""
    o = e.oracle()
    snap = e.store.snapshot()
    if subjects is None:
        uid = snap.types.lookup("user")
        subjects = [
            ("user", snap.objects[uid].string(i))
            for i in range(2, len(snap.objects[uid]))
        ] if uid is not None and uid in snap.objects else []
        subjects.append(("user", "zz-unknown"))
    items, expect = [], []
    for tname, d in e.schema.definitions.items():
        tid = snap.types.lookup(tname)
        if tid is None or tid not in snap.objects:
            continue
        ids = [snap.objects[tid].string(i)
               for i in range(2, len(snap.objects[tid]))]
        for perm in list(d.permissions) + list(d.relations):
            for oid in ids:
                for st, sid in subjects:
                    items.append(CheckItem(tname, oid, perm, st, sid))
                    expect.append(o.check(tname, oid, perm, st, sid))
    got = e.check_bulk(items)
    bad = [
        (items[i], expect[i], got[i])
        for i in range(len(items)) if expect[i] != got[i]
    ]
    assert not bad, f"{len(bad)}/{len(items)} mismatches; first 5: {bad[:5]}"

    # lookup_resources equivalence on permissions
    for tname, d in e.schema.definitions.items():
        for perm in d.permissions:
            for st, sid in subjects:
                got_ids = set(e.lookup_resources(tname, perm, st, sid))
                want = o.lookup_resources(tname, perm, st, sid)
                assert got_ids == want, (tname, perm, st, sid, got_ids, want)


def test_tpu_matches_oracle_reference_style():
    e = make_engine(
        "namespace:ns1#creator@user:alice",
        "namespace:ns1#viewer@user:bob",
        "namespace:ns2#creator@user:bob",
        "pod:ns1/p1#namespace@namespace:ns1",
        "pod:ns1/p2#namespace@namespace:ns1",
        "pod:ns2/q#namespace@namespace:ns2",
        "pod:ns2/q#viewer@user:alice",
        "pod:ns1/p1#creator@user:carol",
        "group:eng#member@user:dev1",
        "group:all#member@group:eng#member",
        "namespace:ns2#viewer@group:all#member",
        "namespace:open#viewer@user:*",
    )
    assert_engine_matches_oracle(e)


INTERSECT_SCHEMA = """
definition user {}
definition group {
  relation member: user | group#member
}
definition doc {
  relation owner: user
  relation reader: user | group#member
  relation banned: user
  relation org: org
  permission read = (reader + owner) - banned
  permission audit = reader & owner
  permission admin = org->admin
  permission super = org->admin & owner
}
definition org {
  relation admin: user
  relation parent: org
  permission all_admin = admin + parent->all_admin
}
"""


def test_tpu_matches_oracle_intersect_exclude_arrows():
    e = Engine(schema=parse_schema(INTERSECT_SCHEMA))
    e.write_relationships(touch(
        "doc:d1#owner@user:o",
        "doc:d1#reader@user:r",
        "doc:d1#reader@user:o",
        "doc:d1#banned@user:o",
        "doc:d2#reader@group:g#member",
        "doc:d2#banned@user:m1",
        "group:g#member@user:m1",
        "group:g#member@user:m2",
        "doc:d3#org@org:acme",
        "org:acme#admin@user:boss",
        "org:acme#parent@org:parent",
        "org:parent#admin@user:grandboss",
        "doc:d3#owner@user:boss",
    ))
    o = e.oracle()
    # sanity: exclusion beats union
    assert not o.check("doc", "d1", "read", "user", "o")
    assert o.check("doc", "d1", "read", "user", "r")
    assert o.check("doc", "d2", "read", "user", "m2")
    assert not o.check("doc", "d2", "read", "user", "m1")
    # multi-hop arrow recursion
    assert o.check("org", "acme", "all_admin", "user", "grandboss")
    assert o.check("doc", "d3", "admin", "user", "boss")
    assert o.check("doc", "d3", "super", "user", "boss")
    assert not o.check("doc", "d3", "super", "user", "grandboss")
    assert_engine_matches_oracle(e)


def test_tpu_deep_chain_10_hops():
    # BASELINE config 4 shape: 10-hop org->team->user chains
    chain = ["group:g%d#member@group:g%d#member" % (i, i + 1) for i in range(10)]
    e = make_engine(
        *chain,
        "group:g10#member@user:deep",
        "namespace:ns#viewer@group:g0#member",
    )
    o = e.oracle()
    assert o.check("namespace", "ns", "view", "user", "deep")
    assert_engine_matches_oracle(e)


def test_tpu_expiration_mask():
    now = time.time()
    e = make_engine()
    e.write_relationships([
        WriteOp("touch", Relationship("pod", "a/p", "viewer", "user", "u1",
                                      expiration=now + 3600)),
        WriteOp("touch", Relationship("pod", "a/q", "viewer", "user", "u1",
                                      expiration=now - 5)),
    ])
    assert e.check(CheckItem("pod", "a/p", "view", "user", "u1"))
    assert not e.check(CheckItem("pod", "a/q", "view", "user", "u1"))
    # lookup sees only the unexpired one
    assert e.lookup_resources("pod", "view", "user", "u1") == ["a/p"]


def test_tpu_matches_oracle_fuzz():
    rng = np.random.default_rng(42)
    for trial in range(6):
        e = Engine(schema=parse_schema(INTERSECT_SCHEMA))
        users = [f"u{i}" for i in range(6)]
        groups = [f"g{i}" for i in range(4)]
        docs = [f"d{i}" for i in range(8)]
        orgs = [f"o{i}" for i in range(4)]
        ops = []
        for g in groups:
            for u in rng.choice(users, size=2, replace=False):
                ops.append(f"group:{g}#member@user:{u}")
            if rng.random() < 0.5:
                g2 = rng.choice(groups)
                if g2 != g:
                    ops.append(f"group:{g}#member@group:{g2}#member")
        for d in docs:
            for u in rng.choice(users, size=2, replace=False):
                ops.append(f"doc:{d}#reader@user:{u}")
            if rng.random() < 0.6:
                ops.append(f"doc:{d}#owner@user:{rng.choice(users)}")
            if rng.random() < 0.4:
                ops.append(f"doc:{d}#banned@user:{rng.choice(users)}")
            if rng.random() < 0.6:
                ops.append(f"doc:{d}#reader@group:{rng.choice(groups)}#member")
            if rng.random() < 0.6:
                ops.append(f"doc:{d}#org@org:{rng.choice(orgs)}")
        for o_ in orgs:
            ops.append(f"org:{o_}#admin@user:{rng.choice(users)}")
            o2 = rng.choice(orgs)
            if o2 != o_:
                ops.append(f"org:{o_}#parent@org:{o2}")
        e.write_relationships(touch(*set(ops)))
        assert_engine_matches_oracle(
            e, subjects=[("user", u) for u in users] + [("user", "nobody")]
        )


def test_closured_block_fuzz_matches_oracle(monkeypatch):
    """Randomized recursive-group graphs with the dense threshold forced
    low, fuzzing the closure machinery: random group→group edges (chains,
    diamonds, cycles), wildcard grants, and — in half the trials —
    expiring group→group edges, which must disqualify the self-pair from
    closure (expiring edges ride the residual path) without losing oracle
    parity either way."""
    import spicedb_kubeapi_proxy_tpu.ops.reachability as R

    monkeypatch.setattr(R, "DENSE_MIN_EDGES", 1)
    rng = np.random.default_rng(1234)
    schema = parse_schema("""
use expiration

definition user {}
definition group { relation member: user | group#member with expiration }
definition namespace {
  relation viewer: group#member | user:*
  permission view = viewer
}
""")
    saw_closured = saw_unclosured = False
    for trial in range(8):
        e = Engine(schema=schema)
        users = [f"u{i}" for i in range(5)]
        groups = [f"g{i}" for i in range(7)]
        ops = []
        for g in groups:
            for u in rng.choice(users, size=2, replace=False):
                ops.append(WriteOp("touch", rel(f"group:{g}#member@user:{u}")))
        n_gg = int(rng.integers(3, 9))
        expiring_trial = trial % 2 == 0
        seen_gg = set()
        for _ in range(n_gg):
            a, b = rng.choice(groups, size=2, replace=False)
            if (a, b) in seen_gg:
                continue
            seen_gg.add((a, b))
            exp_ = (time.time() + 1000
                    if expiring_trial and rng.random() < 0.4 else None)
            ops.append(WriteOp("touch", Relationship(
                "group", a, "member", "group", b,
                subject_relation="member", expiration=exp_)))
        for i in range(4):
            g = rng.choice(groups)
            ops.append(WriteOp("touch", rel(
                f"namespace:ns{i}#viewer@group:{g}#member")))
        if rng.random() < 0.3:
            ops.append(WriteOp("touch", rel("namespace:ns0#viewer@user:*")))
        e.write_relationships(ops)
        cg = e.compiled()
        has_closured = any(b.closured for b in cg.blocks)
        if expiring_trial and any(
                op.rel.expiration is not None for op in ops):
            assert not has_closured, \
                "expiring self-edges must disqualify closure"
            saw_unclosured = True
        saw_closured = saw_closured or has_closured
        subjects = ([("user", u) for u in users]
                    + [("group", g) for g in groups[:2]]
                    + [("user", "nobody")])
        assert_engine_matches_oracle(e, subjects=subjects)
        # random delete batch (the re-close path: cycle edges, leaf
        # edges, already-deleted idempotence), then parity again
        del_ops = []
        for op in rng.choice(len(ops), size=min(4, len(ops)),
                             replace=False).tolist():
            if ops[op].rel.expiration is None:
                del_ops.append(WriteOp("delete", ops[op].rel))
        if del_ops:
            e.write_relationships(del_ops)
            e.write_relationships(del_ops)  # idempotent re-delete
            assert_engine_matches_oracle(e, subjects=subjects)
    assert saw_closured and saw_unclosured, "fuzz must cover both paths"


def test_dense_block_path_matches_oracle(monkeypatch):
    """Force the dense MXU block path (normally >=1024 edges per block) on
    the fuzz graphs and assert oracle parity — covers block splitting,
    local-coordinate construction, and the matmul hop (review finding:
    blocks path untested at default thresholds)."""
    import spicedb_kubeapi_proxy_tpu.ops.reachability as R

    monkeypatch.setattr(R, "DENSE_MIN_EDGES", 1)
    rng = np.random.default_rng(7)
    e = Engine(schema=parse_schema(INTERSECT_SCHEMA))
    users = [f"u{i}" for i in range(6)]
    ops = set()
    for g in range(4):
        for u in rng.choice(users, size=2, replace=False):
            ops.add(f"group:g{g}#member@user:{u}")
    for d in range(10):
        for u in rng.choice(users, size=2, replace=False):
            ops.add(f"doc:d{d}#reader@user:{u}")
        ops.add(f"doc:d{d}#owner@user:{rng.choice(users)}")
        if rng.random() < 0.5:
            ops.add(f"doc:d{d}#banned@user:{rng.choice(users)}")
        ops.add(f"doc:d{d}#reader@group:g{rng.integers(4)}#member")
        ops.add(f"doc:d{d}#org@org:o{rng.integers(3)}")
    for o_ in range(3):
        ops.add(f"org:o{o_}#admin@user:{rng.choice(users)}")
    e.write_relationships(touch(*ops))
    cg = e.compiled()
    assert cg.blocks, "expected dense blocks with DENSE_MIN_EDGES=1"
    assert len(cg.res_idx) < cg.n_edges, "some edges must leave the residual"
    assert_engine_matches_oracle(
        e, subjects=[("user", u) for u in users] + [("user", "nobody")]
    )


def test_closure_pairs_helper():
    """Reflexive-transitive closure of a COO self-block: chains complete,
    cycles converge, the diagonal is always present, and oversized
    closures bail to None."""
    import spicedb_kubeapi_proxy_tpu.ops.reachability as R

    # chain 0 -> 1 -> 2 (edge src->dst flows src's value to dst)
    dl, sl = R._closure_pairs(np.array([1, 2], dtype=np.int32),
                              np.array([0, 1], dtype=np.int32), 4)
    pairs = set(zip(sl.tolist(), dl.tolist()))
    assert {(0, 1), (1, 2), (0, 2)} <= pairs  # incl. the composed hop
    assert {(i, i) for i in range(4)} <= pairs  # diagonal
    # 2-cycle converges, reaching each other and themselves
    dl, sl = R._closure_pairs(np.array([1, 0], dtype=np.int32),
                              np.array([0, 1], dtype=np.int32), 2)
    assert set(zip(sl.tolist(), dl.tolist())) == {
        (0, 1), (1, 0), (0, 0), (1, 1)}
    # cap: a complete bipartite-ish blowup past the limit returns None
    old = R.CLOSURE_MAX_PAIRS
    try:
        R.CLOSURE_MAX_PAIRS = 4
        big_d = np.arange(1, 9, dtype=np.int32)
        big_s = np.zeros(8, dtype=np.int32)
        assert R._closure_pairs(big_d, big_s, 16) is None
    finally:
        R.CLOSURE_MAX_PAIRS = old


NESTED_GROUP_SCHEMA = """
definition user {}
definition group { relation member: user | group#member }
definition namespace {
  relation viewer: group#member
  permission view = viewer
}
"""


def _nested_group_engine(depth: int = 6, fan: int = 3) -> Engine:
    """A strictly layered group tree: users in leaf groups, each layer a
    member of the next, namespaces viewing the root groups."""
    e = Engine(schema=parse_schema(NESTED_GROUP_SCHEMA))
    ops = []
    for g in range(fan):
        ops.append(f"group:l0-{g}#member@user:u{g}")
        for d in range(1, depth):
            ops.append(f"group:l{d}-{g}#member@group:l{d - 1}-{g}#member")
        ops.append(f"namespace:ns{g}#viewer@group:l{depth - 1}-{g}#member")
    e.write_relationships(touch(*ops))
    return e


def test_closured_self_block_peels_nested_groups(monkeypatch):
    """With the group#member self-pair densified, its block holds the
    closure and the range PEELS: deep nested-group membership resolves
    without core iterations (BASELINE config 3's shape)."""
    import spicedb_kubeapi_proxy_tpu.ops.reachability as R

    monkeypatch.setattr(R, "DENSE_MIN_EDGES", 1)
    e = _nested_group_engine()
    cg = e.compiled()
    closured = [b for b in cg.blocks if b.closured]
    assert closured, "group#member self-pair must be closured"
    assert all(b.level % 2 == 0 or b.level == 0 for b in closured)
    assert_engine_matches_oracle(e)
    # u0 sees ns0 through 6 membership hops in ONE core iteration (the
    # convergence probe): closure + peel, no per-hop iteration
    fut = e.check_bulk_async(
        [CheckItem("namespace", "ns0", "view", "user", "u0"),
         CheckItem("namespace", "ns1", "view", "user", "u0")])
    assert fut.result() == [True, False]
    assert fut.iterations() <= 1


def test_closured_block_recursive_group_cycle(monkeypatch):
    """Instance CYCLES inside the closured self-pair (mutually recursive
    groups) stay correct — closure covers them without iteration."""
    import spicedb_kubeapi_proxy_tpu.ops.reachability as R

    monkeypatch.setattr(R, "DENSE_MIN_EDGES", 1)
    e = Engine(schema=parse_schema(NESTED_GROUP_SCHEMA))
    e.write_relationships(touch(
        "group:a#member@user:alice",
        "group:a#member@group:b#member",
        "group:b#member@group:a#member",  # a <-> b cycle
        "group:c#member@group:b#member",
        "namespace:ns#viewer@group:c#member",
    ))
    cg = e.compiled()
    assert any(b.closured for b in cg.blocks)
    assert_engine_matches_oracle(e)
    assert e.check_bulk([
        CheckItem("namespace", "ns", "view", "user", "alice")]) == [True]


def test_closured_block_write_paths(monkeypatch):
    """Writes against a closured self-pair stay fully consistent: adds
    and deletes of member edges are visible on the next read (closure
    cells are derived, so deletes force the re-closing recompile)."""
    import spicedb_kubeapi_proxy_tpu.ops.reachability as R

    monkeypatch.setattr(R, "DENSE_MIN_EDGES", 1)
    e = _nested_group_engine(depth=4)
    assert any(b.closured for b in e.compiled().blocks)
    chk = lambda u, ns: e.check_bulk(  # noqa: E731
        [CheckItem("namespace", ns, "view", "user", u)])[0]
    assert chk("u1", "ns1")
    # delete a mid-chain membership edge: the chain must break
    e.write_relationships([WriteOp("delete", rel(
        "group:l2-1#member@group:l1-1#member"))])
    assert not chk("u1", "ns1")
    # re-add it: the chain must re-form
    e.write_relationships(touch("group:l2-1#member@group:l1-1#member"))
    assert chk("u1", "ns1")
    assert_engine_matches_oracle(e)


def test_closured_core_block_expiring_touch_recompiles(monkeypatch):
    """Review regression: a TOUCH attaching an expiration to an edge of a
    CORE-level closured block (self-pair kept in the core by a
    cross-range cycle, so _level_order_ok passes) must force a recompile
    — otherwise multi-hop closure cells derived through the edge outlive
    its expiration (permanent over-allow)."""
    import spicedb_kubeapi_proxy_tpu.ops.reachability as R

    monkeypatch.setattr(R, "DENSE_MIN_EDGES", 1)
    e = Engine(schema=parse_schema("""
use expiration

definition user {}
definition team { relation member: group#member }
definition group { relation member: user | group#member with expiration | team#member }
definition namespace {
  relation viewer: group#member
  permission view = viewer
}
"""))
    now = time.time()
    e.write_relationships(touch(
        "group:a#member@user:alice",
        "group:b#member@group:a#member",
        "group:c#member@group:b#member",
        # cross-range cycle keeps group#member in the iterated core
        "team:t#member@group:c#member",
        "group:d#member@team:t#member",
        "namespace:ns#viewer@group:c#member",
    ))
    cg = e.compiled()
    core_closured = [b for b in cg.blocks if b.closured and b.level == 0]
    assert core_closured, "self-pair must be closured inside the core"
    item = CheckItem("namespace", "ns", "view", "user", "alice")
    assert e.check_bulk([item], now=now) == [True]
    # touch the mid-chain edge with an expiration 50s out
    e.write_relationships([WriteOp("touch", Relationship(
        "group", "b", "member", "group", "a",
        subject_relation="member", expiration=now + 50))])
    assert e.check_bulk([item], now=now + 10) == [True]  # still valid
    assert e.check_bulk([item], now=now + 100) == [False]  # expired


def test_closured_block_delete_recloses_incrementally(monkeypatch):
    """A membership delete inside a closured block re-closes that block
    from its base edges in O(block) — no full graph recompile — and a
    surviving alternative path keeps derived reachability alive (the
    dead-cell approach would have under-allowed it)."""
    from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

    import spicedb_kubeapi_proxy_tpu.ops.reachability as R

    monkeypatch.setattr(R, "DENSE_MIN_EDGES", 1)
    e = Engine(schema=parse_schema(NESTED_GROUP_SCHEMA))
    # two paths a->b: direct, and via c (a -> c -> b)
    e.write_relationships(touch(
        "group:a#member@user:alice",
        "group:b#member@group:a#member",   # direct
        "group:c#member@group:a#member",
        "group:b#member@group:c#member",   # alternative
        "group:z#member@user:zed",
        "namespace:ns#viewer@group:b#member",
    ))
    assert any(b.closured for b in e.compiled().blocks)
    item = CheckItem("namespace", "ns", "view", "user", "alice")
    assert e.check_bulk([item]) == [True]
    compiles = metrics.counter("engine_graph_compiles_total").value
    inc = metrics.counter("engine_graph_incremental_updates_total").value
    # delete the direct edge: reachability survives via c
    e.write_relationships([WriteOp("delete", rel(
        "group:b#member@group:a#member"))])
    assert e.check_bulk([item]) == [True]
    # delete the alternative too: now revoked
    e.write_relationships([WriteOp("delete", rel(
        "group:b#member@group:c#member"))])
    assert e.check_bulk([item]) == [False]
    assert metrics.counter("engine_graph_compiles_total").value == compiles, \
        "closured deletes must not trigger a full recompile"
    assert metrics.counter(
        "engine_graph_incremental_updates_total").value >= inc + 2
    assert_engine_matches_oracle(e)


def test_closured_block_sharded_parity(monkeypatch):
    """The closured block rides the sharded path too (kept on the MXU
    when the graph axis divides its src range, folded to closure edges
    when it does not) — parity against the single-chip engine."""
    import jax

    import spicedb_kubeapi_proxy_tpu.ops.reachability as R
    from spicedb_kubeapi_proxy_tpu.parallel import make_mesh

    monkeypatch.setattr(R, "DENSE_MIN_EDGES", 1)
    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs 4 virtual devices")
    mesh = make_mesh(4, devices=devs[:4])
    e1 = _nested_group_engine()
    em = Engine(schema=parse_schema(NESTED_GROUP_SCHEMA), mesh=mesh)
    ops = [str(r) for r in e1.read_relationships(RelationshipFilter())]
    em.write_relationships(touch(*ops))
    assert any(b.closured for b in em.compiled().blocks)
    items = [CheckItem("namespace", f"ns{g}", "view", "user", f"u{u}")
             for g in range(3) for u in range(3)]
    assert em.check_bulk(items) == e1.check_bulk(items)
    for g in range(3):
        assert em.lookup_resources("namespace", "view", "user", f"u{g}") \
            == e1.lookup_resources("namespace", "view", "user", f"u{g}")
    # a closured-block delete must stay consistent on the sharded path
    # (re-closed matrices re-uploaded without a full sharded rebuild)
    run_before = em._sharded._run
    for eng in (em, e1):
        eng.write_relationships([WriteOp("delete", rel(
            "group:l3-1#member@group:l2-1#member"))])
    assert em.check_bulk(items) == e1.check_bulk(items)
    assert em._sharded._run is run_before, \
        "re-closed delete must reuse the jitted shard_map (fast path)"
    assert em.lookup_resources("namespace", "view", "user", "u1") \
        == e1.lookup_resources("namespace", "view", "user", "u1")


def test_check_bulk_mixed_subjects_and_unknowns():
    e = make_engine(
        "namespace:ns1#creator@user:alice",
        "namespace:ns1#viewer@user:bob",
    )
    got = e.check_bulk([
        CheckItem("namespace", "ns1", "view", "user", "alice"),
        CheckItem("namespace", "ns1", "view", "user", "bob"),
        CheckItem("namespace", "ns1", "edit", "user", "bob"),
        CheckItem("namespace", "nsX", "view", "user", "alice"),  # unknown obj
        CheckItem("wat", "x", "view", "user", "alice"),  # unknown type
        CheckItem("namespace", "ns1", "view", "robot", "r2"),  # unknown subj type
    ])
    assert got == [True, True, False, False, False, False]


def test_check_bulk_fast_encode_matches_reference_encode():
    """The inlined-cache batch encoder must agree exactly with per-item
    encode_target/encode_subject across every edge case: unknown types /
    permissions / object ids / subject ids, userset subjects, wildcard
    grants, duplicated subjects, and '' vs None subject relations."""
    e = make_engine(
        "namespace:ns1#creator@user:alice",
        "namespace:ns1#viewer@group:eng#member",
        "group:eng#member@user:carol",
        "namespace:open#viewer@user:*",
        "pod:ns1/api#namespace@namespace:ns1",
        "pod:ns1/api#viewer@user:dave",
    )
    base = [
        CheckItem("namespace", "ns1", "view", "user", "alice"),
        CheckItem("namespace", "ns1", "view", "user", "carol"),  # via group
        CheckItem("namespace", "ns1", "view", "group", "eng", "member"),
        CheckItem("namespace", "open", "view", "user", "anyone"),  # wildcard
        CheckItem("namespace", "ns1", "edit", "user", "carol"),
        CheckItem("pod", "ns1/api", "view", "user", "alice"),  # via arrow
        CheckItem("pod", "ns1/api", "view", "user", "dave"),
        CheckItem("namespace", "nsX", "view", "user", "alice"),  # unknown obj
        CheckItem("wat", "x", "view", "user", "alice"),  # unknown type
        CheckItem("namespace", "ns1", "wat", "user", "alice"),  # unknown perm
        CheckItem("namespace", "ns1", "view", "robot", "r2"),  # unknown stype
        CheckItem("namespace", "ns1", "view", "user", "nobody"),  # unknown sid
        CheckItem("namespace", "ns1", "view", "group", "eng", ""),  # ''==None
        CheckItem("namespace", "ns1", "view", "group", "eng"),
    ]
    items = base * 64  # 896 items: subject/offset caches get real reuse
    want_one = e.check_bulk(base)
    got = e.check_bulk(items)
    assert got == want_one * 64
    # the encoded arrays themselves must match per-item reference encoding
    cg = e.compiled()
    objs = e._objects_by_name()
    seeds, q_slots, q_batch = e._encode_checks(cg, objs, items)
    for i, it in enumerate(items):
        assert q_slots[i] == cg.encode_target(
            it.resource_type, it.permission, it.resource_id, objs), i
        assert tuple(seeds[q_batch[i]].tolist()) == cg.encode_subject(
            it.subject_type, it.subject_id, it.subject_relation, objs), i


def test_check_bulk_fast_encode_randomized_parity():
    """Randomized parity fuzz (advisor r3): _encode_checks hand-inlines
    encode_target/encode_subject/Interner.lookup semantics for speed; a
    future change to the canonical encoders must not silently diverge from
    this hot path. 500 random items over known/unknown types, permissions,
    relations, object ids, subject relations, and wildcards."""
    import random

    rng = random.Random(0xC0FFEE)
    e = make_engine(
        "namespace:ns1#creator@user:alice",
        "namespace:ns2#viewer@group:eng#member",
        "group:eng#member@user:carol",
        "namespace:open#viewer@user:*",
        "pod:ns1/api#namespace@namespace:ns1",
    )
    types = ["namespace", "pod", "group", "user", "ghost-type"]
    perms = ["view", "edit", "member", "wat", "creator", "viewer"]
    ids = ["ns1", "ns2", "open", "eng", "alice", "carol", "ns1/api",
           "missing", "*", ""]
    srels = [None, "", "member", "ghost-rel"]
    items = [
        CheckItem(rng.choice(types), rng.choice(ids), rng.choice(perms),
                  rng.choice(types), rng.choice(ids), rng.choice(srels))
        for _ in range(500)
    ]
    cg = e.compiled()
    # post-compile interned ids: a write between compiled() and
    # _objects_by_name() interns ids past the compiled type size — both
    # encoders must agree on the size-overflow (treat-as-void) rule
    e.store.write([WriteOp("touch", parse_relationship(
        "namespace:late-ns#creator@user:late-user"))], [])
    objs = e._objects_by_name()
    items += [
        CheckItem("namespace", "late-ns", "view", "user", "alice"),
        CheckItem("namespace", "ns1", "view", "user", "late-user"),
    ]
    seeds, q_slots, q_batch = e._encode_checks(cg, objs, items)
    for i, it in enumerate(items):
        assert q_slots[i] == cg.encode_target(
            it.resource_type, it.permission, it.resource_id, objs), (i, it)
        assert tuple(seeds[q_batch[i]].tolist()) == cg.encode_subject(
            it.subject_type, it.subject_id, it.subject_relation, objs), \
            (i, it)


def test_check_bulk_chunked_pipeline_preserves_order(monkeypatch):
    """Bulk checks split into pipelined dispatch chunks must return the
    same per-item results in the same order, including a remainder chunk
    and subjects spanning chunk boundaries."""
    e = make_engine(
        "namespace:ns1#creator@user:alice",
        "namespace:ns2#viewer@user:bob",
    )
    items = [
        CheckItem("namespace", f"ns{1 + (i % 3)}", "view", "user",
                  ["alice", "bob", "zed"][i % 3])
        for i in range(25)
    ]
    want = e.check_bulk(items)  # single dispatch
    monkeypatch.setattr(Engine, "CHECK_PIPELINE_CHUNK", 7)  # 4 chunks, rem 4
    assert e.check_bulk(items) == want
    assert want.count(True) > 0 and want.count(False) > 0


# ---------------------------------------------------------------------------
# Review-finding regressions (engine core)
# ---------------------------------------------------------------------------


def test_write_atomicity_on_midbatch_conflict():
    s = Store()
    s.write(touch("ns:a#viewer@user:x", "ns:b#viewer@user:x"))
    rev = s.revision
    with pytest.raises(AlreadyExists):
        s.write([
            WriteOp("touch", rel("ns:a#viewer@user:x")),
            WriteOp("create", rel("ns:b#viewer@user:x")),
        ])
    # nothing applied, revision unchanged, no bogus watch events
    assert len(s) == 2
    assert s.exists(RelationshipFilter("ns", "a", "viewer"))
    assert s.revision == rev
    assert s.watch_since(rev) == []


def test_duplicate_update_in_one_write_rejected():
    from spicedb_kubeapi_proxy_tpu.engine import StoreError
    s = Store()
    with pytest.raises(StoreError, match="duplicate"):
        s.write(touch("ns:a#viewer@user:x", "ns:a#viewer@user:x"))


def test_userset_subject_does_not_match_wildcard():
    schema = parse_schema("""
    definition user {}
    definition group {
      relation member: user
    }
    definition ns {
      relation viewer: group#member | group:*
      permission view = viewer
    }
    """)
    e = Engine(schema=schema)
    e.write_relationships(touch("ns:x#viewer@group:*", "group:g#member@user:u"))
    o = e.oracle()
    assert not o.check("ns", "x", "view", "group", "g", "member")
    assert not e.check(CheckItem("ns", "x", "view", "group", "g", "member"))
    # but a concrete group subject does match the wildcard
    assert o.check("ns", "x", "view", "group", "anything")
    assert e.check(CheckItem("ns", "x", "view", "group", "anything"))


def test_contiguous_query_window_matches_gather():
    """The list-filter shape (one type's full permission range) takes a
    dynamic_slice fast path instead of the general fancy-index gather
    (ops/reachability.py query_async q_contiguous). Both extractions must
    agree bit-for-bit, auto-detection must engage on a contiguous window,
    and windows whose padded tail would clamp past the state tensor must
    fall back to the gather rather than shift."""
    rels = ["namespace:ns%d#viewer@user:alice" % i for i in range(0, 40, 3)]
    rels += ["namespace:ns%d#creator@user:alice" % i for i in range(1, 40, 7)]
    # >1024 pods: the pod window crosses the auto-detect gate (small
    # windows decline to the gather to bound per-length recompiles)
    rels += ["pod:p%d#namespace@namespace:ns%d" % (i, i % 40)
             for i in range(1100)]
    e = make_engine(*rels)
    cg = e.compiled()
    objs = e._objects_by_name()
    seeds = np.asarray([cg.encode_subject("user", "alice", None, objs)],
                       dtype=np.int32)

    for tname, perm in [("pod", "view"), ("namespace", "view")]:
        off = cg.offset_of(tname, perm)
        n = cg.type_sizes[tname]
        qs = off + np.arange(n, dtype=np.int32)
        qb = np.zeros(n, dtype=np.int32)
        general = cg.query_async(seeds, qs, qb, q_contiguous=False).result()
        fast = cg.query_async(seeds, qs, qb, q_contiguous=True).result()
        auto = cg.query_async(seeds, qs, qb).result()
        assert np.array_equal(general, fast), (tname, perm)
        assert np.array_equal(general, auto), (tname, perm)
        assert general.any(), "fixture should grant something"
        assert not general.all(), "fixture should deny something"

    # non-contiguous queries must not be misdetected
    off = cg.offset_of("namespace", "view")
    qs = off + np.asarray([0, 2, 5], dtype=np.int32)
    qb = np.zeros(3, dtype=np.int32)
    got = cg.query_async(seeds, qs, qb).result()
    want = cg.query_async(seeds, qs, qb, q_contiguous=False).result()
    assert np.array_equal(got, want)

    # a window ending at the very top of the slot space must still match
    # (exact slice lengths: no clamp is possible, but keep the guard honest)
    lo = max(0, cg.M - 5)
    qs = lo + np.arange(5, dtype=np.int32)
    qb = np.zeros(5, dtype=np.int32)
    tail_fast = cg.query_async(seeds, qs, qb, q_contiguous=True).result()
    tail_gen = cg.query_async(seeds, qs, qb, q_contiguous=False).result()
    assert np.array_equal(tail_fast, tail_gen)

    # the fused-batch grid form (batcher shape): R rows x one shared window
    subjects = ["alice", "bob", "carol"]
    rels2 = ["namespace:ns0#viewer@user:bob", "namespace:ns7#viewer@user:bob"]
    e.write_relationships(touch(*rels2))
    cg = e.compiled()
    objs = e._objects_by_name()
    off = cg.offset_of("namespace", "view")
    n = cg.type_sizes["namespace"]
    seeds3 = np.asarray(
        [cg.encode_subject("user", s, None, objs) for s in subjects],
        dtype=np.int32)
    qs = np.tile(off + np.arange(n, dtype=np.int32), 3)
    qb = np.repeat(np.arange(3, dtype=np.int32), n)
    grid = cg.query_async(seeds3, qs, qb,
                          q_contig_grid=(off, n, 3)).result()
    gen = cg.query_async(seeds3, qs, qb).result()
    assert np.array_equal(grid, gen)
    assert grid[:n].any() and grid[n:2 * n].any(), "alice+bob see something"
    assert not grid[2 * n:].any(), "carol has no grants"

    # malformed grid promises (wrong total, zero rows) must fall back, not
    # mis-slice
    bad = cg.query_async(seeds3, qs, qb,
                         q_contig_grid=(off, n, 2)).result()
    assert np.array_equal(bad, gen)


def test_nonconvergence_raises_not_denies():
    from spicedb_kubeapi_proxy_tpu.ops.reachability import ConvergenceError
    chain = ["group:g%d#member@group:g%d#member" % (i, i + 1) for i in range(40)]
    e = make_engine(*chain, "group:g40#member@user:deep",
                    "namespace:ns#viewer@group:g0#member")
    cg = e.compiled()
    objs = e._objects_by_name()
    seeds = np.asarray([cg.encode_subject("user", "deep", None, objs)],
                       dtype=np.int32)
    q = np.asarray([cg.encode_target("namespace", "view", "ns", objs)],
                   dtype=np.int32)
    with pytest.raises(ConvergenceError):
        cg.query(seeds, q, np.zeros(1, dtype=np.int32), max_iters=8)
    # with budget it converges and grants
    assert cg.query(seeds, q, np.zeros(1, dtype=np.int32), max_iters=128)[0]


def test_wildcard_expiration_validation():
    schema = parse_schema("""
    definition user {}
    definition ns {
      relation viewer: user:*
      permission view = viewer
    }
    """)
    e = Engine(schema=schema)
    with pytest.raises(SchemaViolation, match="expiring"):
        e.write_relationships([WriteOp("touch", Relationship(
            "ns", "a", "viewer", "user", "*", expiration=time.time() + 60))])


def test_noop_deletes_do_not_bump_revision():
    s = Store()
    s.write(touch("ns:a#viewer@user:x"))
    rev = s.revision
    s.delete_by_filter(RelationshipFilter(resource_type="pod"))
    assert s.revision == rev
    s.write([WriteOp("delete", rel("ns:zz#viewer@user:x"))])
    assert s.revision == rev


def test_delete_by_filter_preconditions_atomic():
    s = Store()
    s.write(touch("lock:l1#workflow@workflow:w1"))
    with pytest.raises(PreconditionFailed):
        s.delete_by_filter(
            RelationshipFilter(resource_type="lock"),
            [Precondition(RelationshipFilter("lock", "l1", "workflow",
                                             subject_id="other"),
                          must_exist=True)],
        )
    assert len(s) == 1


def test_jit_cache_shared_across_revisions():
    """Steady-state writes (same bucket layout) must not recompile
    (review finding: jit-per-CompiledGraph)."""
    e = make_engine("namespace:ns1#creator@user:alice")
    e.check(CheckItem("namespace", "ns1", "view", "user", "alice"))
    cg1 = e.compiled()
    # touch/delete an existing tuple: same interners, same buckets
    e.write_relationships(touch("namespace:ns1#viewer@user:bob"))
    e.check(CheckItem("namespace", "ns1", "view", "user", "bob"))
    cg2 = e.compiled()
    assert cg1 is not cg2
    assert cg1.signature() == cg2.signature()
    from spicedb_kubeapi_proxy_tpu.ops import semiring
    mk = ("run", semiring.resolved_mode())
    assert cg1._device[mk] is cg2._device[mk]


def test_reflexive_userset_identity_both_paths():
    e = make_engine("group:eng#member@user:u",
                    "namespace:ns#viewer@group:eng#member")
    o = e.oracle()
    assert o.check("group", "eng", "member", "group", "eng", "member")
    assert e.check(CheckItem("group", "eng", "member", "group", "eng", "member"))


def test_wildcard_resource_id_rejected():
    e = make_engine()
    with pytest.raises(SchemaViolation, match="wildcard"):
        e.write_relationships(touch("namespace:*#viewer@user:x"))


def test_store_read_does_not_hold_lock():
    s = Store()
    s.write(touch("ns:a#viewer@user:x", "ns:b#viewer@user:x"))
    rels = s.read(RelationshipFilter(resource_type="ns"))
    # read returns a list; a concurrent write must not deadlock
    s.write(touch("ns:c#viewer@user:x"))
    assert len(rels) == 2


def test_watch_trim_and_bisect():
    from spicedb_kubeapi_proxy_tpu.engine import StoreError
    s = Store()
    s.watch_retention = 10
    for i in range(20):
        s.write(touch(f"ns:n{i}#viewer@user:x"))
    recs = s.watch_since(s.revision - 1)
    assert len(recs) == 1
    with pytest.raises(StoreError, match="trimmed"):
        s.watch_since(0)


def test_schema_mixed_operators_require_parens():
    from spicedb_kubeapi_proxy_tpu.models import SchemaError
    with pytest.raises(SchemaError, match="parentheses"):
        parse_schema("""
        definition user {}
        definition d {
          relation a: user
          relation b: user
          relation c: user
          permission p = a + b & c
        }
        """)
    # same-operator chains still fine
    parse_schema("""
    definition user {}
    definition d {
      relation a: user
      relation b: user
      relation c: user
      permission p = a - b - c
      permission q = a + b + c
    }
    """)


# ---------------------------------------------------------------------------
# Incremental graph updates (engine write path without full recompiles)
# ---------------------------------------------------------------------------


def _compiles():
    from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

    return metrics.counter("engine_graph_compiles_total").value


def _incrementals():
    from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

    return metrics.counter("engine_graph_incremental_updates_total").value


def test_incremental_write_avoids_recompile():
    """Small writes after the first compile ride the delta segment: no
    full recompile, answers stay oracle-exact."""
    e = make_engine(
        "namespace:ns1#creator@user:alice",
        "pod:ns1/p1#namespace@namespace:ns1",
        "group:eng#member@user:dev",
        "namespace:ns2#viewer@group:eng#member",
    )
    e.compiled()
    c0, i0 = _compiles(), _incrementals()

    # create: new viewer tuple visible in a fully-consistent read
    e.write_relationships(touch("namespace:ns1#viewer@user:bob"))
    assert e.check(CheckItem("namespace", "ns1", "view", "user", "bob"))
    assert _compiles() == c0 and _incrementals() == i0 + 1
    assert_engine_matches_oracle(e)

    # delete: revoked immediately (base-edge invalidation)
    e.write_relationships(
        [WriteOp("delete", rel("namespace:ns1#creator@user:alice"))])
    assert not e.check(CheckItem("namespace", "ns1", "view", "user", "alice"))
    # arrow edge through the deleted namespace tuple is also gone
    assert not e.check(CheckItem("pod", "ns1/p1", "view", "user", "alice"))
    assert _compiles() == c0
    assert_engine_matches_oracle(e)

    # new subject AND new resource interned within their buckets
    e.write_relationships(touch("namespace:ns-new#viewer@user:carol"))
    assert e.check(CheckItem("namespace", "ns-new", "view", "user", "carol"))
    assert not e.check(CheckItem("namespace", "ns-new", "view", "user", "bob"))
    assert _compiles() == c0
    assert_engine_matches_oracle(e)

    # userset + arrow edges created incrementally
    e.write_relationships(touch(
        "group:eng#member@user:newdev",
        "pod:ns2/px#namespace@namespace:ns2",
    ))
    assert e.check(CheckItem("pod", "ns2/px", "view", "user", "newdev"))
    assert _compiles() == c0
    assert_engine_matches_oracle(e)


def test_incremental_expiration_retouch():
    """TOUCH refreshing a tuple's expiration invalidates the old edge and
    re-adds it with the new clock mask."""
    e = make_engine("pod:a/p#viewer@user:u")
    e.compiled()
    c0 = _compiles()
    now = time.time()
    # retouch with an already-expired timestamp: view revoked
    e.write_relationships([WriteOp("touch", Relationship(
        "pod", "a/p", "viewer", "user", "u", None, now - 10))])
    assert not e.check(CheckItem("pod", "a/p", "view", "user", "u"))
    # retouch back to never-expiring: restored
    e.write_relationships(touch("pod:a/p#viewer@user:u"))
    assert e.check(CheckItem("pod", "a/p", "view", "user", "u"))
    # future expiration honored at query time
    e.write_relationships([WriteOp("touch", Relationship(
        "pod", "a/p", "viewer", "user", "u", None, now + 3600))])
    assert e.check(CheckItem("pod", "a/p", "view", "user", "u"))
    assert not e.check(
        CheckItem("pod", "a/p", "view", "user", "u"), now=now + 7200)
    assert _compiles() == c0


def test_incremental_bucket_overflow_falls_back():
    """Interning objects past the padded bucket forces a full recompile —
    and the answers stay right."""
    e = make_engine("namespace:ns#viewer@user:u0")
    e.compiled()
    c0 = _compiles()
    # LANE-padded bucket is 128; blow past it
    e.write_relationships(touch(*[
        f"namespace:ns#viewer@user:u{i}" for i in range(1, 200)]))
    assert e.check(CheckItem("namespace", "ns", "view", "user", "u150"))
    assert _compiles() > c0
    assert_engine_matches_oracle(e, subjects=[("user", f"u{i}")
                                              for i in (0, 5, 150, 199)])


def test_incremental_after_bulk_load_falls_back():
    """bulk_load bypasses the watch log, so the next read recompiles
    rather than applying an impossible delta."""
    e = make_engine("namespace:a#viewer@user:u")
    e.compiled()
    c0 = _compiles()
    e.bulk_load({
        "resource_type": ["namespace"] * 2,
        "resource_id": ["b", "c"],
        "relation": ["viewer"] * 2,
        "subject_type": ["user"] * 2,
        "subject_id": ["u", "v"],
    })
    assert e.check(CheckItem("namespace", "b", "view", "user", "u"))
    assert e.check(CheckItem("namespace", "c", "view", "user", "v"))
    assert _compiles() > c0
    # and incremental service resumes afterwards
    c1 = _compiles()
    e.write_relationships(touch("namespace:d#viewer@user:w"))
    assert e.check(CheckItem("namespace", "d", "view", "user", "w"))
    assert _compiles() == c1


def test_incremental_dense_block_clear(monkeypatch):
    """Deleting an edge that lives in a dense MXU block clears the block
    cell (not just the residual)."""
    from spicedb_kubeapi_proxy_tpu.ops import reachability

    monkeypatch.setattr(reachability, "DENSE_MIN_EDGES", 4)
    e = make_engine(*[
        f"namespace:n{i}#viewer@user:u{i % 7}" for i in range(40)])
    cg = e.compiled()
    assert cg.blocks, "test needs a dense block to exist"
    c0 = _compiles()
    e.write_relationships(
        [WriteOp("delete", rel("namespace:n3#viewer@user:u3"))])
    assert not e.check(CheckItem("namespace", "n3", "view", "user", "u3"))
    # re-touch restores it through the delta segment
    e.write_relationships(touch("namespace:n3#viewer@user:u3"))
    assert e.check(CheckItem("namespace", "n3", "view", "user", "u3"))
    assert _compiles() == c0
    assert_engine_matches_oracle(e, subjects=[("user", f"u{i}")
                                              for i in range(7)])


def test_incremental_fuzz_against_oracle():
    """Randomized interleaving of creates/touches/deletes applied
    incrementally stays oracle-exact at every step."""
    rng = np.random.default_rng(42)
    e = Engine(schema=parse_schema(INTERSECT_SCHEMA))
    # seed one tuple per relation so every relation id is interned before
    # the first compile — a relation's FIRST-ever tuple is a (one-time)
    # full-recompile event by design, which would muddy the no-recompile
    # assertion below
    e.write_relationships(touch(
        "doc:d0#owner@user:u0",
        "group:g0#member@user:u1",
        "group:g1#member@user:u0",
        "doc:d0#reader@group:g0#member",
        "doc:d0#org@org:o0",
        "org:o0#admin@user:u2",
        "org:o1#parent@org:o0",
        "doc:d9#banned@user:u5",
    ))
    e.compiled()
    c0 = _compiles()
    live = set()
    users = [f"u{i}" for i in range(6)]
    for step in range(12):
        n_ops = int(rng.integers(1, 4))
        ops = []
        seen = set()
        for _ in range(n_ops):
            kind = rng.choice(["reader", "banned", "owner", "member", "org"])
            if kind == "member":
                s = f"group:g{rng.integers(2)}#member@user:{rng.choice(users)}"
            elif kind == "org":
                s = f"doc:d{rng.integers(3)}#org@org:o{rng.integers(2)}"
            else:
                s = f"doc:d{rng.integers(3)}#{kind}@user:{rng.choice(users)}"
            if s in seen:
                continue
            seen.add(s)
            if s in live and rng.random() < 0.5:
                ops.append(WriteOp("delete", rel(s)))
                live.discard(s)
            else:
                ops.append(WriteOp("touch", rel(s)))
                live.add(s)
        if ops:
            e.write_relationships(ops)
        assert_engine_matches_oracle(
            e, subjects=[("user", u) for u in users])
    assert _compiles() == c0, "fuzz writes must all apply incrementally"


# ---------------------------------------------------------------------------
# Vectorized store index
# ---------------------------------------------------------------------------


def test_store_index_big_chunk_paths(monkeypatch):
    """Chunks at/above the threshold use the sorted-hash index; lookups,
    overwrites, and deletes through it behave identically to the dict."""
    from spicedb_kubeapi_proxy_tpu.engine import store as store_mod

    monkeypatch.setattr(store_mod, "INDEX_SMALL_CHUNK", 8)
    s = Store()
    n = 500
    s.bulk_load({
        "resource_type": ["ns"] * n,
        "resource_id": [f"o{i}" for i in range(n)],
        "relation": ["viewer"] * n,
        "subject_type": ["user"] * n,
        "subject_id": [f"u{i % 37}" for i in range(n)],
    })
    # touch-refresh an existing tuple found via the sorted index
    s.write([WriteOp("touch", Relationship("ns", "o42", "viewer", "user",
                                           "u5", None, time.time() + 99))])
    assert len(s) == n  # replaced, not duplicated
    # create of an existing live tuple errors (found via sorted index)
    with pytest.raises(AlreadyExists):
        s.write([WriteOp("create", Relationship("ns", "o7", "viewer",
                                                "user", "u7"))])
    # delete via the index; subsequent create succeeds
    s.write([WriteOp("delete", Relationship("ns", "o7", "viewer", "user",
                                            "u7"))])
    assert len(s) == n - 1
    s.write([WriteOp("create", Relationship("ns", "o7", "viewer", "user",
                                            "u7"))])
    assert len(s) == n


def test_store_index_hash_collision_verified(monkeypatch):
    """Colliding hashes must not alias rows: lookups verify the actual
    key columns."""
    from spicedb_kubeapi_proxy_tpu.engine import store as store_mod

    monkeypatch.setattr(store_mod, "INDEX_SMALL_CHUNK", 2)
    # force EVERY hash equal: all rows land in one searchsorted run
    monkeypatch.setattr(
        store_mod, "_hash_key_cols",
        lambda *cols: np.zeros(np.broadcast(*cols).size or 1,
                               dtype=np.uint64).reshape(
            np.asarray(cols[0]).shape if np.asarray(cols[0]).shape else ()))
    monkeypatch.setattr(store_mod.native, "index_build",
                        lambda *a: None)  # force the python hash path
    s = Store()
    s.bulk_load({
        "resource_type": ["ns"] * 4,
        "resource_id": ["a", "b", "c", "d"],
        "relation": ["viewer"] * 4,
        "subject_type": ["user"] * 4,
        "subject_id": ["u1", "u2", "u3", "u4"],
    })
    with pytest.raises(AlreadyExists):
        s.write([WriteOp("create", Relationship("ns", "c", "viewer",
                                                "user", "u3"))])
    s.write([WriteOp("delete", Relationship("ns", "b", "viewer", "user",
                                            "u2"))])
    live = {r.resource_id for r in s.read(
        RelationshipFilter(resource_type="ns"))}
    assert live == {"a", "c", "d"}


# ---------------------------------------------------------------------------
# Stratified fixpoint (acyclic levels applied once; only cycles iterate)
# ---------------------------------------------------------------------------


def test_stratification_splits_kube_shaped_graph():
    """In the kube-shaped schema only the recursive group-membership
    ranges iterate; pod/namespace ranges are acyclic tail levels applied
    once — the dominant per-pod blocks stay out of the fixpoint loop."""
    e = make_engine(
        "group:a#member@group:b#member",   # recursion -> core
        "group:b#member@group:a#member",
        "group:b#member@user:u",
        "namespace:ns#viewer@group:a#member",
        "pod:ns/p#namespace@namespace:ns",
    )
    cg = e.compiled()
    assert cg.n_levels > 0
    offs = cg.range_offs
    lvl = {  # (type, rel) -> level
        k: int(cg.range_levels[int(np.searchsorted(offs, v, "right")) - 1])
        for k, v in cg.slot_offset.items()
    }
    assert lvl[("group", "member")] == 0  # recursive: iterated core
    # the value-dependency chain ns#view -> pod arrow term -> pod#view is
    # strictly layered tail (pod#namespace itself is a value sink: its
    # TUPLES define arrow edges, its slots feed nothing)
    assert 0 < lvl[("namespace", "view")] < lvl[("pod", "view")]
    assert lvl[("pod", "namespace")] > 0
    # and answers stay oracle-exact (core + levels compose correctly)
    assert_engine_matches_oracle(e)


def test_stratified_deep_acyclic_chain_converges_in_one_core_iter():
    """A 10-hop ACYCLIC chain needs zero core iterations of work — every
    hop is a one-shot level — so the iteration counter stays at the
    convergence-check minimum instead of growing with depth."""
    # (a recursive schema like org->parent->can_admin would stay core;
    # this chain uses 10 DISTINCT types so every hop is acyclic)
    schema = ["definition user {}"]
    for i in range(10):
        sub = "user" if i == 0 else f"t{i - 1}"
        schema.append(f"""
definition t{i} {{
  relation up: {sub}
  permission view = {'up' if i == 0 else 'up->view'}
}}""")
    e = Engine(schema=parse_schema("\n".join(schema)))
    ops = ["t0:x0#up@user:alice"]
    ops += [f"t{i}:x{i}#up@t{i - 1}:x{i - 1}" for i in range(1, 10)]
    e.write_relationships(touch(*ops))
    fut = e.check_bulk_async(
        [CheckItem("t9", "x9", "view", "user", "alice")])
    assert fut.result() == [True]
    # acyclic: the core loop only runs its convergence check
    assert fut.iterations() <= 2
    cg = e.compiled()
    assert cg.n_levels >= 10


def test_incremental_level_violation_forces_recompile():
    """A delta edge inverting the frozen stratification (a first-ever
    dependency direction) must fall back to a full recompile — applying
    it at the wrong phase would read a stale source."""
    e = Engine(schema=parse_schema("""
definition user {}
definition a {
  relation m: user | b#p
  permission p = m
}
definition b {
  relation m: user | a#p
  permission p = m
}
"""))
    # only a->b edges exist: acyclic, b depends on a
    e.write_relationships(touch("a:x#m@user:u", "b:y#m@a:x#p"))
    e.compiled()
    c0 = _compiles()
    # new edge b->a inverts the order (creates a cross-type cycle)
    e.write_relationships(touch("a:z#m@b:y#p"))
    assert e.check(CheckItem("a", "z", "p", "user", "u"))
    assert _compiles() == c0 + 1  # re-stratified via full recompile
    assert_engine_matches_oracle(e, subjects=[("user", "u")])
