"""Decision cache + singleflight: zero repeat dispatches, exact
invalidation (writes, deletes, expiration boundaries), differential
agreement with the oracle, and the authz fast-path probe.

The acceptance gates (ISSUE 2): a repeated identical lookup at an
unchanged revision performs ZERO new device dispatches (read off
``engine_lookups_total`` / batch counters), N concurrent identical
misses dispatch exactly once, and a cache-enabled engine agrees with
``OracleEvaluator`` across writes, deletes, and expiration boundaries.
"""

import threading
import time

import numpy as np
import pytest

from spicedb_kubeapi_proxy_tpu.engine import (
    CheckItem,
    Engine,
    RelationshipFilter,
    WriteOp,
)
from spicedb_kubeapi_proxy_tpu.engine.decision_cache import (
    MISS,
    DecisionCache,
)
from spicedb_kubeapi_proxy_tpu.engine.store import Store
from spicedb_kubeapi_proxy_tpu.models import parse_schema
from spicedb_kubeapi_proxy_tpu.models.tuples import (
    Relationship,
    parse_relationship,
)
from spicedb_kubeapi_proxy_tpu.utils.metrics import metrics

SCHEMA = parse_schema("""
use expiration
definition user {}
definition group {
  relation member: user
}
definition ns {
  relation viewer: user | group#member | user with expiration
  permission view = viewer
}
""")


def build(cache=True, rels=None):
    e = Engine(schema=SCHEMA)
    e.write_relationships([
        WriteOp("touch", parse_relationship(r)) for r in (rels or (
            "ns:n0#viewer@user:u0",
            "ns:n1#viewer@user:u0",
            "ns:n1#viewer@user:u1",
            "ns:n2#viewer@group:g0#member",
            "group:g0#member@user:u2",
        ))
    ])
    if cache:
        e.enable_decision_cache()
    return e


def lookups_total():
    return metrics.counter("engine_lookups_total").value


def checks_total():
    return metrics.counter("engine_checks_total").value


# ---------------------------------------------------------------------------
# Zero repeat dispatches + copy-on-read
# ---------------------------------------------------------------------------


def test_repeat_lookup_zero_dispatches():
    e = build()
    m1, it1 = e.lookup_resources_mask("ns", "view", "user", "u0")
    before = lookups_total()
    m2, it2 = e.lookup_resources_mask("ns", "view", "user", "u0")
    assert lookups_total() == before  # served host-side, no dispatch
    np.testing.assert_array_equal(m1, m2)
    assert it2 is it1
    # lookup_resources shares the SAME mask entry
    ids = e.lookup_resources("ns", "view", "user", "u0")
    assert lookups_total() == before
    assert set(ids) == {"n0", "n1"}


def test_repeat_lookup_zero_dispatches_with_batcher():
    e = build()
    e.enable_lookup_batching(window=0.005)
    e.lookup_resources_mask("ns", "view", "user", "u1")
    before = lookups_total()
    batches = metrics.counter("engine_lookup_batches_total").value
    e.lookup_resources_mask("ns", "view", "user", "u1")
    assert lookups_total() == before
    assert metrics.counter("engine_lookup_batches_total").value == batches


def test_copy_on_read_protects_cached_mask():
    e = build()
    m1, _ = e.lookup_resources_mask("ns", "view", "user", "u0")
    assert m1.any()
    m1[:] = False  # caller mutates its copy
    m2, _ = e.lookup_resources_mask("ns", "view", "user", "u0")
    assert m2.any(), "cached array was mutated through a caller's copy"


def test_repeat_check_zero_dispatches_and_negative_caching():
    e = build()
    items = [CheckItem("ns", "n0", "view", "user", "u0"),
             CheckItem("ns", "n0", "view", "user", "u1")]
    assert e.check_bulk(items) == [True, False]
    before = checks_total()
    assert e.check_bulk(items) == [True, False]  # both polarities cached
    assert checks_total() == before


def test_check_miss_residue_dispatches_in_order():
    e = build()
    e.check_bulk([CheckItem("ns", "n0", "view", "user", "u0")])
    before = checks_total()
    # one hit + one miss: only the residue dispatches, order preserved
    got = e.check_bulk([CheckItem("ns", "n1", "view", "user", "u1"),
                        CheckItem("ns", "n0", "view", "user", "u0"),
                        CheckItem("ns", "n2", "view", "user", "u2")])
    assert got == [True, True, True]
    assert checks_total() - before == 2


def test_explicit_now_bypasses_cache():
    e = build()
    now = time.time()
    e.lookup_resources_mask("ns", "view", "user", "u0", now=now)
    before = lookups_total()
    e.lookup_resources_mask("ns", "view", "user", "u0", now=now)
    assert lookups_total() - before == 1  # pinned-clock queries never cache


def test_trivial_lookup_counts_and_caches():
    e = build()
    before = lookups_total()
    assert e.lookup_resources_mask("nosuch", "view", "user", "u0") == \
        (None, None)
    # the direct path counts trivial lookups like the batched path does
    assert lookups_total() - before == 1
    assert e.lookup_resources_mask("nosuch", "view", "user", "u0") == \
        (None, None)
    assert lookups_total() - before == 1  # repeat is a cache hit


# ---------------------------------------------------------------------------
# Invalidation: writes, deletes, expiration boundaries
# ---------------------------------------------------------------------------


def test_write_and_delete_invalidate():
    e = build()
    assert e.check_bulk([CheckItem("ns", "n9", "view", "user", "u9")]) == \
        [False]
    e.write_relationships(
        [WriteOp("touch", parse_relationship("ns:n9#viewer@user:u9"))])
    assert e.check_bulk([CheckItem("ns", "n9", "view", "user", "u9")]) == \
        [True]
    mask, interner = e.lookup_resources_mask("ns", "view", "user", "u9")
    assert mask[interner.lookup("n9")]
    e.delete_relationships(
        RelationshipFilter(resource_type="ns", resource_id="n9"))
    assert e.check_bulk([CheckItem("ns", "n9", "view", "user", "u9")]) == \
        [False]
    mask, _ = e.lookup_resources_mask("ns", "view", "user", "u9")
    assert not mask.any()


def test_expiration_boundary_kills_entries():
    e = build()
    e.check_bulk([CheckItem("ns", "n0", "view", "user", "u0")])  # warm jit
    now = time.time()
    e.write_relationships([WriteOp("touch", Relationship(
        "ns", "nexp", "viewer", "user", "uexp", expiration=now + 1.2))])
    item = CheckItem("ns", "nexp", "view", "user", "uexp")
    assert e.check_bulk([item]) == [True]
    before = checks_total()
    assert e.check_bulk([item]) == [True]
    assert checks_total() == before  # cached while the watermark holds
    time.sleep(max(0.0, now + 1.25 - time.time()))
    # the boundary passed with NO write: the entry must die at the
    # watermark and the fresh dispatch must see the expired tuple
    assert e.check_bulk([item]) == [False]
    mask, _ = e.lookup_resources_mask("ns", "view", "user", "uexp")
    assert not mask.any()


def test_differential_vs_oracle_across_mutations():
    """A cache-enabled engine must agree with OracleEvaluator after every
    mutation step — writes, deletes, and a tuple-expiration boundary."""
    e = build()
    e.check_bulk([CheckItem("ns", "n0", "view", "user", "u0")])  # warm jit
    base = time.time()
    exp_at = base + 2.5
    steps = [
        lambda: e.write_relationships(
            [WriteOp("touch", parse_relationship("ns:n3#viewer@user:u1"))]),
        lambda: e.write_relationships([WriteOp("touch", Relationship(
            "ns", "n4", "viewer", "user", "u0", expiration=exp_at))]),
        lambda: e.delete_relationships(
            RelationshipFilter(resource_type="ns", resource_id="n1")),
        lambda: e.write_relationships(
            [WriteOp("touch",
                     parse_relationship("group:g0#member@user:u1"))]),
        lambda: e.write_relationships(
            [WriteOp("delete",
                     parse_relationship("ns:n0#viewer@user:u0"))]),
        lambda: time.sleep(max(0.0, exp_at + 0.05 - time.time())),  # expiry
    ]
    users = [f"u{i}" for i in range(4)]
    nss = [f"n{i}" for i in range(5)]

    def compare_once():
        oracle = e.oracle()  # snapshot + clock at comparison time
        bad = []
        for u in users:
            got = set(e.lookup_resources("ns", "view", "user", u))
            want = oracle.lookup_resources("ns", "view", "user", u)
            if got != want:
                bad.append((u, got, want))
        items = [CheckItem("ns", n, "view", "user", u)
                 for n in nss for u in users]
        got = e.check_bulk(items)
        want = [oracle.check("ns", n, "view", "user", u)
                for n in nss for u in users]
        if got != want:
            bad.append(("checks", got, want))
        return bad

    def assert_agreement():
        # double-query: the second round is served from the cache and
        # must still agree (catches stale entries surviving a mutation)
        for _ in range(2):
            bad = compare_once()
            if bad:
                # the wall clock may cross an expiration boundary BETWEEN
                # oracle construction and the engine query — a real cache
                # bug reproduces against a fresh oracle, a clock race
                # does not
                bad = compare_once()
            assert not bad, bad

    assert_agreement()
    for step in steps:
        step()
        assert_agreement()


def test_cache_disabled_engine_agrees():
    plain, cached = build(cache=False), build()
    for u in ("u0", "u1", "u2", "u9"):
        a = set(plain.lookup_resources("ns", "view", "user", u))
        b = set(cached.lookup_resources("ns", "view", "user", u))
        assert a == b


# ---------------------------------------------------------------------------
# Singleflight
# ---------------------------------------------------------------------------


def test_singleflight_one_dispatch_for_concurrent_identical_lookups():
    e = build()
    e.lookup_resources_mask("ns", "view", "user", "uwarm")  # warm jit
    gate = threading.Event()
    orig = e._lookup_submit
    calls = []

    def gated(*a, **k):
        calls.append(a)
        gate.wait(5.0)
        return orig(*a, **k)

    e._lookup_submit = gated
    before = lookups_total()
    piggy0 = metrics.counter(
        "engine_decision_cache_piggybacks_total").value
    n = 8
    results = [None] * n

    def run(i):
        results[i] = e.lookup_resources_mask("ns", "view", "user", "u0")

    threads = [threading.Thread(target=run, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    time.sleep(0.2)  # let every thread reach the flight
    gate.set()
    for t in threads:
        t.join()
    e._lookup_submit = orig
    assert len(calls) == 1  # ONE leader dispatched
    assert lookups_total() - before == 1  # metrics delta agrees
    assert metrics.counter(
        "engine_decision_cache_piggybacks_total").value - piggy0 == n - 1
    ref = results[0][0].copy()
    for mask, _ in results:
        np.testing.assert_array_equal(mask, ref)
    # every caller got its OWN copy: mutating one leaves the rest intact
    results[0][0][:] = False
    np.testing.assert_array_equal(results[1][0], ref)


def test_singleflight_error_propagates_and_is_not_cached():
    e = build()
    e.lookup_resources_mask("ns", "view", "user", "uwarm")

    def boom(*a, **k):
        raise RuntimeError("device on fire")

    orig = e._lookup_submit
    e._lookup_submit = boom
    with pytest.raises(RuntimeError):
        e.lookup_resources_mask("ns", "view", "user", "u0")
    e._lookup_submit = orig
    # the error was not cached: the next call dispatches and succeeds
    mask, _ = e.lookup_resources_mask("ns", "view", "user", "u0")
    assert mask.any()


# ---------------------------------------------------------------------------
# try_cached_check (the middleware fast path)
# ---------------------------------------------------------------------------


def test_try_cached_check_probe():
    e = build()
    items = [CheckItem("ns", "n0", "view", "user", "u0"),
             CheckItem("ns", "n1", "view", "user", "u1")]
    assert e.try_cached_check(items) is None  # cold: no full answer
    e.check_bulk(items)
    assert e.try_cached_check(items) == [True, True]
    assert e.try_cached_check([]) == []
    # partial coverage -> None (a partial answer would dispatch anyway)
    assert e.try_cached_check(
        items + [CheckItem("ns", "n2", "view", "user", "u0")]) is None
    # a write moves the revision: the probe must miss, not serve stale
    e.write_relationships(
        [WriteOp("touch", parse_relationship("ns:n7#viewer@user:u7"))])
    assert e.try_cached_check(items) is None
    e2 = build(cache=False)
    assert e2.try_cached_check(items) is None


def test_cached_verdict_helper():
    from spicedb_kubeapi_proxy_tpu.authz.check import cached_verdict

    class _Probe:
        def __init__(self, answer):
            self.answer = answer

        def try_cached_check(self, items):
            return self.answer

    class _Rule:
        checks = ()
        post_checks = ()

    items, verdict = cached_verdict(_Probe([True, True]), [_Rule()], None)
    assert items == [] and verdict is True  # no checks -> allowed


# ---------------------------------------------------------------------------
# Store watermark + cache internals
# ---------------------------------------------------------------------------


def test_store_next_expiry_watermark():
    s = Store()
    now = time.time()
    assert s.next_expiry(now) == float("inf")
    s.write([WriteOp("touch", Relationship("ns", "a", "viewer", "user", "x",
                                           expiration=now + 50)),
             WriteOp("touch", Relationship("ns", "b", "viewer", "user", "x",
                                           expiration=now + 10)),
             WriteOp("touch", Relationship("ns", "c", "viewer", "user", "x"))])
    assert s.next_expiry(now) == pytest.approx(now + 10)
    # strictly-after semantics: AT the boundary the next one is reported
    assert s.next_expiry(now + 10) == pytest.approx(now + 50)
    assert s.next_expiry(now + 50) == float("inf")
    # deleting the nearest boundary moves the watermark
    s.write([WriteOp("delete", Relationship("ns", "b", "viewer", "user", "x",
                                            expiration=now + 10))])
    assert s.next_expiry(now) == pytest.approx(now + 50)


def test_lru_eviction_and_byte_budget():
    c = DecisionCache(max_entries=4, max_mask_bytes=1 << 30, shards=1)
    t = time.time()
    for i in range(8):
        c.put(("check", 1, i), True, float("inf"), 0, t)
    assert c.stats()["entries"] == 4
    assert c.get(("check", 1, 0), t) is MISS  # cold end evicted
    assert c.get(("check", 1, 7), t) is True
    # byte budget evicts mask-bearing entries independently of count
    cb = DecisionCache(max_entries=1000, max_mask_bytes=100, shards=1)
    cb.put(("lookup", 1, "a"), ("m", None), float("inf"), 60, t)
    cb.put(("lookup", 1, "b"), ("m", None), float("inf"), 60, t)
    assert cb.stats()["mask_bytes"] <= 100
    assert cb.get(("lookup", 1, "a"), t) is MISS
    assert cb.get(("lookup", 1, "b"), t) is not MISS


def test_born_dead_entries_are_not_stored():
    c = DecisionCache(shards=1)
    t = time.time()
    c.put(("check", 1, "k"), True, t - 1.0, 0, t)  # deadline already past
    assert c.stats()["entries"] == 0
    assert c.get(("check", 1, "k"), t) is MISS


def test_disable_clears_gauges():
    e = build()
    e.lookup_resources_mask("ns", "view", "user", "u0")
    g = metrics.gauge("engine_decision_cache_entries")
    before = g.value
    assert before >= 1
    e.disable_decision_cache()
    assert g.value <= before - 1
    # cache off: dispatches again (no phantom hits)
    before_l = lookups_total()
    e.lookup_resources_mask("ns", "view", "user", "u0")
    assert lookups_total() - before_l == 1
